"""Supervisor policy logic under an injectable clock: detection
(stale heartbeat, frozen tick, grace window), jittered exponential
backoff keyed on failure fingerprints, the healthy-uptime budget
refund, recovery-time measurement and the supervisor.json mirror —
all in milliseconds of real time (no child processes, no sleeps)."""

import json
import os
import random

import pytest

from kme_tpu.bridge.supervise import STATE_FILE, Supervisor


class FakeChild:
    """A scripted child: exits `rc` once the fake clock passes
    spawn + exit_after (None = runs forever until killed). Standby
    fakes additionally "write" a heartbeat file on every poll
    (hb_path), the way the real replica's follow loop does."""

    _next_pid = iter(range(40_000, 50_000))

    def __init__(self, clock, exit_after=None, rc=1):
        self._clock = clock
        self.exit_after = exit_after
        self.rc = rc
        self.returncode = None
        self.spawned_at = None
        self.env = None
        self.pid = next(FakeChild._next_pid)
        self.hb_path = None

    def poll(self):
        if (self.returncode is None and self.exit_after is not None
                and self._clock() - self.spawned_at >= self.exit_after):
            self.returncode = self.rc
        if self.returncode is None and self.hb_path is not None:
            open(self.hb_path, "a").close()
        return self.returncode

    def send_signal(self, sig):
        self.returncode = -9

    def terminate(self):
        if self.returncode is None:
            self.returncode = -15

    def kill(self):
        self.returncode = -9

    def wait(self, timeout=None):
        return self.returncode


class Harness:
    """Fake clock + scripted children wired into a Supervisor."""

    def __init__(self, tmp_path, n_children=8, **kw):
        self.now = 0.0
        self.sleeps = []
        self.spawned = []
        self._pending = [FakeChild(self.clock) for _ in range(n_children)]
        # heartbeat model: age() -> seconds (inf = no file yet);
        # tick() -> loop tick value. Tests swap these mid-run.
        self.age = lambda: 0.1
        self.tick = lambda: int(self.now * 10)    # always advancing
        sup = Supervisor([], str(tmp_path),
                         popen=self._popen, clock=self.clock,
                         sleep=self._sleep,
                         mtime=lambda p: self._mtime(),
                         rng=random.Random(0), poll=0.5, **kw)
        sup._hb_tick = self.tick_wrap
        self.sup = sup

    def clock(self):
        return self.now

    def _sleep(self, s):
        self.sleeps.append(s)
        self.now += s
        if self.now > 100000:
            raise AssertionError("supervisor loop ran away")

    def _mtime(self):
        age = self.age()
        if age == float("inf"):
            raise OSError("no heartbeat file")
        return self.now - age

    def tick_wrap(self):
        return self.tick()

    def _popen(self, cmd, env):
        child = self._pending[len(self.spawned)]
        child.spawned_at = self.now
        child.env = env
        self.spawned.append(child)
        return child

    @property
    def backoffs(self):
        """Sleeps that are not the 0.5s poll cadence."""
        return [s for s in self.sleeps if s != 0.5]


def test_clean_exit_no_restart(tmp_path):
    h = Harness(tmp_path)
    h._pending[0].exit_after, h._pending[0].rc = 2.0, 0
    assert h.sup.run() == 0
    assert len(h.spawned) == 1
    assert h.sup.restarts_total == 0
    assert h.spawned[0].env["KME_RESTART_ORDINAL"] == "0"
    assert "KME_FAILED_AT" not in h.spawned[0].env


def test_crash_loop_exhausts_budget_with_growing_backoff(tmp_path):
    h = Harness(tmp_path, max_restarts=3, healthy_decay=10_000,
                backoff_base=1.0, backoff_cap=100.0)
    for c in h._pending:
        c.exit_after, c.rc = 0.0, 1        # dies instantly, forever
    assert h.sup.run() == 1
    assert len(h.spawned) == 4             # initial + 3 restarts
    assert h.sup.restarts_total == 4
    assert h.sup.fingerprints == {"exit:1": 4}
    # three backoff sleeps (the 4th failure exhausts the budget before
    # any backoff), doubling with jitter in [0.5, 1.5)x
    b = h.backoffs
    assert len(b) == 3
    assert 0.5 <= b[0] < 1.5
    assert 1.0 <= b[1] < 3.0
    assert 2.0 <= b[2] < 6.0
    # restart ordinals stamped into each incarnation's environment
    assert [c.env["KME_RESTART_ORDINAL"] for c in h.spawned] == \
        ["0", "1", "2", "3"]
    assert all("KME_FAILED_AT" in c.env for c in h.spawned[1:])


def test_novel_fingerprint_resets_backoff_streak(tmp_path):
    h = Harness(tmp_path, max_restarts=10, healthy_decay=10_000,
                backoff_base=1.0, backoff_cap=100.0)
    for i, c in enumerate(h._pending):
        c.exit_after = 0.0
        c.rc = 1 if i < 2 else 2           # fingerprint changes
        if i >= 3:
            c.rc = 0                       # then exit cleanly
    assert h.sup.run() == 0
    assert h.sup.fingerprints == {"exit:1": 2, "exit:2": 1}
    b = h.backoffs
    assert len(b) == 3
    assert 1.0 <= b[1] < 3.0               # streak 2 of exit:1
    assert 0.5 <= b[2] < 1.5               # exit:2 resets to streak 1


def test_stale_heartbeat_detected(tmp_path):
    h = Harness(tmp_path, stale_after=5.0, grace=1.0)
    h._pending[1].exit_after, h._pending[1].rc = 1.0, 0
    # the FIRST incarnation's heartbeat freezes at t=3; the restarted
    # child beats normally
    h.age = lambda: (0.1 if len(h.spawned) >= 2 or h.now < 3.0
                     else h.now - 3.0)
    assert h.sup.run() == 0
    assert h.sup.fingerprints == {"stale": 1}
    assert h.sup.restarts_total == 1
    assert h.spawned[0].returncode == -9   # SIGKILLed after detection


def test_frozen_tick_is_a_stall_even_with_fresh_heartbeat(tmp_path):
    h = Harness(tmp_path, stall_after=3.0, stale_after=10_000)
    h._pending[1].exit_after, h._pending[1].rc = 1.0, 0
    h.age = lambda: 0.1                        # beater thread alive
    h.tick = lambda: min(int(h.now), 2)        # advances, then freezes
    assert h.sup.run() == 0
    assert h.sup.fingerprints == {"stall": 1}


def test_no_heartbeat_within_grace_fails(tmp_path):
    h = Harness(tmp_path, grace=4.0)
    h._pending[1].exit_after, h._pending[1].rc = 1.0, 0
    first = {"done": False}

    def age():
        # first incarnation never writes a heartbeat; the restarted
        # one is healthy immediately
        return float("inf") if len(h.spawned) < 2 else 0.1

    h.age = age
    assert h.sup.run() == 0
    assert h.sup.fingerprints == {"stale": 1}
    # detection happened only after the grace window
    assert h.sup.recoveries == [] or h.sup.recoveries[0]["detected_at"] >= 4.0


def test_healthy_uptime_refunds_budget(tmp_path):
    h = Harness(tmp_path, max_restarts=2, healthy_decay=5.0)
    h._pending[0].exit_after, h._pending[0].rc = 0.0, 1
    h._pending[1].exit_after, h._pending[1].rc = 12.0, 0  # long healthy run
    assert h.sup.run() == 0
    assert h.sup.restarts_total == 1       # lifetime count unchanged
    assert h.sup.budget_used == 0          # refunded by healthy uptime


def test_recovery_time_measured_and_state_mirrored(tmp_path):
    h = Harness(tmp_path, grace=30.0)
    h._pending[0].exit_after, h._pending[0].rc = 2.0, 1
    h._pending[1].exit_after, h._pending[1].rc = 10.0, 0

    def age():
        if len(h.spawned) < 2:
            return 0.1
        # restarted child's first heartbeat lands 1.5s after spawn
        born = h.spawned[1].spawned_at
        return float("inf") if h.now < born + 1.5 else 0.1

    h.age = age
    assert h.sup.run() == 0
    assert len(h.sup.recoveries) == 1
    rec = h.sup.recoveries[0]
    assert rec["fingerprint"] == "exit:1"
    assert 1.0 <= rec["recovered_in"] <= 4.0
    # the child was told when the failure was detected
    assert float(h.spawned[1].env["KME_FAILED_AT"]) == rec["detected_at"]
    # supervisor.json mirrors the final state
    with open(os.path.join(str(tmp_path), STATE_FILE)) as f:
        state = json.load(f)
    assert state["restarts_total"] == 1
    assert state["fingerprints"] == {"exit:1": 1}
    assert state["recoveries"][0]["recovered_in"] == rec["recovered_in"]


def test_closing_heartbeat_suppresses_the_stall_detector(tmp_path):
    """Same frozen-tick script as the stall test above, but the child's
    final heartbeat carries closing=true (deliberate idle-exit): the
    stall detector must stand down and let the clean exit land."""
    h = Harness(tmp_path, stall_after=3.0, stale_after=10_000)
    h._pending[0].exit_after, h._pending[0].rc = 10.0, 0
    h.age = lambda: 0.1
    h.tick = lambda: min(int(h.now), 2)        # advances, then freezes
    h.sup._hb_closing = lambda: True
    assert h.sup.run() == 0
    assert h.sup.fingerprints == {}
    assert h.sup.restarts_total == 0


# ---------------------------------------------------------------------------
# hot-standby failover


class StandbyHarness(Harness):
    """Harness plus a second scripted-child lane for kme-standby
    spawns (the supervisor's popen is dispatched on the subcommand)."""

    def __init__(self, tmp_path, n_standby=4, standby_beats=True, **kw):
        self.standby_spawned = []
        self._standby_pending = []
        super().__init__(tmp_path, standby=True, **kw)
        for _ in range(n_standby):
            c = FakeChild(self.clock)
            if standby_beats:
                c.hb_path = self.sup.standby_hb
            self._standby_pending.append(c)

    def _popen(self, cmd, env):
        if "standby" in cmd:
            child = self._standby_pending[len(self.standby_spawned)]
            child.spawned_at = self.now
            child.env = env
            self.standby_spawned.append(child)
            return child
        return super()._popen(cmd, env)


def test_failure_promotes_a_ready_standby_without_backoff(tmp_path):
    h = StandbyHarness(tmp_path)
    h._pending[0].exit_after, h._pending[0].rc = 2.0, 1
    adoptee = h._standby_pending[0]
    adoptee.exit_after, adoptee.rc = 8.0, 0    # serves, then exits clean
    assert h.sup.run() == 0
    # the standby was adopted, not a cold serve restart
    assert len(h.spawned) == 1
    assert len(h.standby_spawned) == 2         # adoptee + replacement
    assert h.backoffs == []                    # adoption is not paced
    assert h.sup.restarts_total == 1
    rec = h.sup.recoveries[0]
    assert rec["promoted"] is True
    assert rec["failover_seconds"] == rec["recovered_in"]
    # the promote order is addressed to the adoptee and SPARED by the
    # replacement-standby launch (the adoptee may not have read it yet)
    with open(h.sup.promote_file) as f:
        order = json.load(f)
    assert order["pid"] == adoptee.pid
    assert order["fingerprint"] == "exit:1"
    # clean exit stops the replacement replica
    assert h.standby_spawned[1].returncode == -15


def test_unready_standby_falls_back_to_cold_restart(tmp_path):
    h = StandbyHarness(tmp_path, standby_beats=False)  # never heartbeats
    h._pending[0].exit_after, h._pending[0].rc = 1.0, 1
    h._pending[1].exit_after, h._pending[1].rc = 1.0, 0
    assert h.sup.run() == 0
    assert len(h.spawned) == 2                 # ordinary restart path
    assert len(h.backoffs) == 1
    assert not os.path.exists(h.sup.promote_file)
    assert "promoted" not in h.sup.recoveries[0]


def test_stale_promote_file_is_cleared_at_standby_launch(tmp_path):
    h = StandbyHarness(tmp_path)
    with open(h.sup.promote_file, "w") as f:   # yesterday's order
        json.dump({"failed_at": 1.0, "pid": 12345}, f)
    h._pending[0].exit_after, h._pending[0].rc = 1.0, 0
    assert h.sup.run() == 0
    assert not os.path.exists(h.sup.promote_file)


def test_dead_standby_is_relaunched(tmp_path):
    h = StandbyHarness(tmp_path)
    h._standby_pending[0].exit_after = 1.0     # replica dies early
    h._pending[0].exit_after, h._pending[0].rc = 4.0, 0
    assert h.sup.run() == 0
    assert len(h.standby_spawned) == 2
    assert h.sup.standby_restarts == 1


def test_reserved_serve_args_rejected(tmp_path):
    for bad in ("--checkpoint-dir", "--checkpoint-dir=/x",
                "--health-file", "--health", "--check"):
        with pytest.raises(ValueError, match="managed by the supervisor"):
            Supervisor([bad, "v"], str(tmp_path))
    # non-prefix flags pass through
    Supervisor(["--engine", "oracle", "--batch", "64"], str(tmp_path))
