"""Native C++ scheduler vs the Python semantics authority.

The native scheduler must produce IDENTICAL plans — every column, every
barrier, every segment boundary — on representative workloads, raise the
same capacity/envelope errors, and round-trip its id-space state through
the checkpoint surface."""

import numpy as np
import pytest

import kme_tpu.opcodes as op
from kme_tpu.runtime.sequencer import CapacityError, EnvelopeError, Scheduler
from kme_tpu.wire import OrderMsg
from kme_tpu.workload import (cancel_heavy_stream, harness_stream,
                              zipf_symbol_stream)

native = pytest.importorskip("kme_tpu.native.sched")
if not native.native_available():
    import os
    import shutil

    if os.environ.get("KME_NATIVE") == "0":
        # deliberate disable (the fallback tier-1 leg), not a build
        # failure — these tests compare native vs Python, so there is
        # nothing to test
        pytest.skip("native explicitly disabled (KME_NATIVE=0)",
                    allow_module_level=True)
    if shutil.which("g++"):
        pytest.fail("g++ is available but the native library failed to "
                    "build — a real regression, not a missing toolchain "
                    "(rerun with the kme_tpu.native build stderr)")
    pytest.skip("native library unavailable (no toolchain)",
                allow_module_level=True)


def assert_same_plan(msgs, lanes, accounts, width):
    py = Scheduler(lanes, accounts, width)
    cc = native.NativeScheduler(lanes, accounts, width)
    sp = py.plan(msgs)
    sc = cc.plan(msgs)
    for k in sp.cols:
        assert np.array_equal(sp.cols[k], sc.cols[k]), f"col {k} differs"
    assert sp.barriers == sc.barriers
    assert sp.host_rejects == sc.host_rejects
    assert list(sp.segment_steps) == list(sc.segment_steps)
    assert sp.program == sc.program
    assert py.aid_idx == cc.aid_idx
    assert py.sid_lane == cc.sid_lane
    assert py.oid_sid == cc.oid_sid
    assert py._rr_lane == cc._rr_lane
    return py, cc


@pytest.mark.parametrize("width", [0, 1, 8])
def test_plans_identical_harness(width):
    msgs = harness_stream(1500, seed=3, num_symbols=4, num_accounts=8,
                          payout_opcode_bug=False, validate=True)
    assert_same_plan(msgs, 8, 16, width)


def test_plans_identical_zipf_with_barriers():
    msgs = zipf_symbol_stream(2000, num_symbols=16, num_accounts=32, seed=9,
                              zipf_a=1.1, payout_per_mille=5)
    assert_same_plan(msgs, 16, 64, 8)


def test_plans_identical_cancel_heavy_multi_batch():
    msgs = cancel_heavy_stream(1500, num_symbols=8, num_accounts=16, seed=4)
    py = Scheduler(8, 32, 8)
    cc = native.NativeScheduler(8, 32, 8)
    for lo in range(0, len(msgs), 400):  # id maps persist across plans
        sp = py.plan(msgs[lo:lo + 400])
        sc = cc.plan(msgs[lo:lo + 400])
        for k in sp.cols:
            assert np.array_equal(sp.cols[k], sc.cols[k]), f"col {k}@{lo}"
        assert sp.program == sc.program
    assert py.oid_sid == cc.oid_sid


def test_native_errors_match():
    cc = native.NativeScheduler(2, 2, 0)
    with pytest.raises(CapacityError, match="symbol capacity"):
        cc.plan([OrderMsg(action=op.ADD_SYMBOL, sid=s) for s in range(3)])
    cc2 = native.NativeScheduler(8, 1, 0)
    with pytest.raises(CapacityError, match="account capacity"):
        cc2.plan([OrderMsg(action=op.CREATE_BALANCE, aid=a)
                  for a in range(2)])
    cc3 = native.NativeScheduler(8, 8, 0)
    with pytest.raises(EnvelopeError):
        cc3.plan([OrderMsg(action=op.BUY, oid=1, aid=1, sid=0,
                           price=2**31, size=1)])


def test_plans_identical_extreme_ids():
    """Java-long id wrapping at the scheduler boundary: out-of-int64
    aids/sids/oids and INT64_MIN payout targets plan identically."""
    big = 2**63
    msgs = [
        OrderMsg(action=op.CREATE_BALANCE, aid=big),       # wraps to -2^63
        OrderMsg(action=op.CREATE_BALANCE, aid=-big),      # same account
        OrderMsg(action=op.TRANSFER, aid=big, size=1000),
        OrderMsg(action=op.ADD_SYMBOL, sid=2**63 - 1),
        OrderMsg(action=op.BUY, oid=2**64 + 7, aid=big, sid=2**63 - 1,
                 price=50, size=2),
        OrderMsg(action=op.CANCEL, oid=7, aid=big),        # wrapped route
        OrderMsg(action=op.PAYOUT, sid=-big, size=97),     # abs(INT64_MIN)
        OrderMsg(action=2**70, aid=1),                     # unknown opcode
    ]
    assert_same_plan(msgs, 4, 4, 2)


def test_native_state_roundtrip():
    """The checkpoint surface: export the id maps, import into a fresh
    native scheduler, and plans continue identically."""
    msgs = harness_stream(800, seed=7, num_symbols=4, num_accounts=8,
                          payout_opcode_bug=False, validate=True)
    cc = native.NativeScheduler(8, 16, 8)
    cc.plan(msgs[:500])
    state = (cc.aid_idx, cc.sid_lane, cc.oid_sid, cc._rr_lane)

    cc2 = native.NativeScheduler(8, 16, 8)
    cc2.aid_idx, cc2.sid_lane, cc2.oid_sid, cc2._rr_lane = state
    py = Scheduler(8, 16, 8)
    py.plan(msgs[:500])
    sp = py.plan(msgs[500:])
    sc = cc2.plan(msgs[500:])
    for k in sp.cols:
        assert np.array_equal(sp.cols[k], sc.cols[k]), f"col {k} differs"
