"""On-device metrics: counters accumulated in the scan carry + gauges.

The counters must agree exactly with the oracle-checked wire stream
(they are derived from the same per-message outcomes) and be identical
at any shard count (psum-merged)."""

from kme_tpu.engine.lanes import LaneConfig
from kme_tpu.runtime.session import LaneSession
from kme_tpu.workload import zipf_symbol_stream

CFG = LaneConfig(lanes=8, slots=32, accounts=32, max_fills=16, steps=16)


def _stream():
    return zipf_symbol_stream(800, num_symbols=8, num_accounts=24, seed=3,
                              zipf_a=1.0, payout_per_mille=4)


def test_metrics_agree_with_wire_stream():
    msgs = _stream()
    ses = LaneSession(CFG)
    lines = [ln for lines in ses.process_wire(msgs) for ln in lines]
    met = ses.metrics()

    fills = sum(1 for ln in lines if ln.startswith('OUT {"action":5'))
    assert met["fills"] * 2 == fills + sum(
        1 for ln in lines if ln.startswith('OUT {"action":6'))
    # every trade emits maker+taker events: fills counter == maker events
    assert met["trades_ok"] + met["rej_capacity"] + met["rej_risk"] == sum(
        1 for m in msgs if m.action in (2, 3))
    # every payout in this stream executes (zipf_symbol_stream re-ADDs
    # the symbol right after each payout, so the book always exists at
    # settle time — the counter counts EXECUTED settles)
    assert met["barriers"] == sum(1 for m in msgs if m.action in (1, 200))
    assert met["barriers"] > 0
    assert met["open_orders"] >= 0 and met["books"] <= CFG.lanes
    assert met["accounts"] == 24

    # cumulative across batches: a second batch only adds
    met2_before = met["msgs"]
    ses.process_wire(_stream()[:100])
    assert ses.metrics()["msgs"] > met2_before


def test_metrics_shard_invariant():
    msgs = _stream()
    base = None
    for shards in (1, 2, 8):
        ses = LaneSession(CFG, shards=shards)
        ses.process_wire([m.copy() for m in msgs])
        met = ses.metrics()
        if base is None:
            base = met
        else:
            assert met == base, f"metrics diverged at shards={shards}"
