"""Workload generator: determinism, preamble shape, distribution sanity,
and an oracle smoke-run over the harness distribution (the reference's own
"test" is exactly this: fire random events, assert no crash —
exchange_test.js:33-36, SURVEY.md §4)."""

import collections

from kme_tpu import opcodes as op
from kme_tpu.oracle import OracleEngine
from kme_tpu.workload import WorkloadGen, cancel_heavy_stream, harness_stream, \
    payout_storm_stream, zipf_hot_stream, zipf_symbol_stream


def test_deterministic_under_seed():
    a = harness_stream(500, seed=7)
    b = harness_stream(500, seed=7)
    assert a == b
    c = harness_stream(500, seed=8)
    assert a != c


def test_preamble_shape_matches_reference():
    # exchange_test.js:23-32 with defaults: 10 accounts (create+transfer
    # pairs), then the float loop bound `i < 3/2+1` -> 3 symbols
    pre = WorkloadGen().preamble()
    assert len(pre) == 23
    assert [m.action for m in pre[:4]] == [100, 101, 100, 101]
    assert [m.sid for m in pre[20:]] == [0, 1, 2]
    # numSymbols=4 also creates only symbols 0..2 (the reference quirk)
    pre4 = WorkloadGen(num_symbols=4).preamble()
    assert [m.sid for m in pre4 if m.action == op.ADD_SYMBOL] == [0, 1, 2]


def test_event_mix_roughly_matches_per_mille():
    gen = WorkloadGen(seed=3)
    counts = collections.Counter(gen.gen_event().action for _ in range(50_000))
    assert 0.30 < counts[op.BUY] / 50_000 < 0.37
    assert 0.30 < counts[op.SELL] / 50_000 < 0.37
    # cancels include the opcode-bugged payouts (both action=4)
    assert 0.30 < counts[op.CANCEL] / 50_000 < 0.37
    assert counts[op.PAYOUT] == 0  # Q5: payout opcode bug


def test_payout_opcode_fix_flag():
    gen = WorkloadGen(seed=3, payout_opcode_bug=False)
    actions = [gen.gen_event().action for _ in range(50_000)]
    assert op.PAYOUT in actions


def test_validate_mode_bounds_domain():
    for m in harness_stream(5_000, seed=1, validate=True):
        if m.action in (op.BUY, op.SELL):
            assert 0 <= m.price <= 125 and m.size >= 1


def test_oracle_survives_harness_distribution_java():
    e = OracleEngine("java")
    n = 0
    for m in harness_stream(5_000, seed=11):
        recs = e.process(m)
        assert recs[0].key == "IN" and recs[-1].key == "OUT"
        n += len(recs)
    assert n >= 10_000


def test_oracle_survives_harness_distribution_fixed():
    e = OracleEngine("fixed")
    for m in harness_stream(5_000, seed=11, payout_opcode_bug=False,
                            validate=True):
        e.process(m)
    # fixed-mode solvency: no balance ever ends negative
    assert all(b >= 0 for b in e.balances.values())


def test_scale_streams_shape():
    z = zipf_symbol_stream(2_000, num_symbols=64, num_accounts=128, seed=5)
    assert sum(1 for m in z if m.action == op.ADD_SYMBOL) == 64
    ch = cancel_heavy_stream(2_000, num_symbols=8, num_accounts=32, seed=5)
    cancels = sum(1 for m in ch if m.action == op.CANCEL)
    # every cancel consumes one prior submit: steady state caps near 50%
    assert cancels > 0.45 * 2_000


def test_zipf_hot_deterministic_and_skewed():
    a = zipf_hot_stream(3_000, num_symbols=8, num_accounts=32, seed=9)
    b = zipf_hot_stream(3_000, num_symbols=8, num_accounts=32, seed=9)
    assert a == b
    assert a != zipf_hot_stream(3_000, num_symbols=8, num_accounts=32,
                                seed=10)
    # symbol 0 dominates (hot_frac=0.7 of events), but the cold set is
    # ZIPF, not uniform: the second-ranked book must be distinctly warm
    # (that co-location is what defeats static `lane % shards` placement)
    sub = collections.Counter(
        m.sid for m in a if m.action in (op.BUY, op.SELL))
    total = sum(sub.values())
    assert sub[0] / total > 0.6
    assert sub[1] > 1.5 * sub[4]
    # valid domain end to end (the mesh parity tests feed this raw)
    for m in a:
        if m.action in (op.BUY, op.SELL):
            assert 0 <= m.price <= 125 and m.size >= 1


def test_payout_storm_deterministic_with_bursts():
    a = payout_storm_stream(2_000, num_symbols=8, num_accounts=32,
                            seed=4, storms=3)
    assert a == payout_storm_stream(2_000, num_symbols=8,
                                    num_accounts=32, seed=4, storms=3)
    payouts = [i for i, m in enumerate(a) if m.action == op.PAYOUT]
    # every storm settles EVERY symbol (real PAYOUT opcode, Q5 fixed)
    assert len(payouts) == 3 * 8
    # bursts are contiguous: each storm's 8 payouts interleave only
    # with their re-ADDs (payout positions step by 2 within a burst)
    for s in range(3):
        burst = payouts[s * 8:(s + 1) * 8]
        assert burst[-1] - burst[0] == 2 * 7
    # each payout is immediately followed by the symbol's re-ADD
    for i in payouts:
        assert a[i + 1].action == op.ADD_SYMBOL
        assert a[i + 1].sid == abs(a[i].sid)


def test_storm_profiles_deterministic_under_seed():
    # same seed -> identical stream, for every named profile; a seed
    # bump must move the stream (the chaos scenarios and the CI shed
    # gate both depend on this)
    from kme_tpu.workload import STORM_PROFILES, storm_stream

    for name in STORM_PROFILES:
        a = storm_stream(name, 800, num_symbols=8, num_accounts=16,
                         seed=3)
        b = storm_stream(name, 800, num_symbols=8, num_accounts=16,
                         seed=3)
        assert a == b, name
        assert a != storm_stream(name, 800, num_symbols=8,
                                 num_accounts=16, seed=4), name


def test_storm_windows_cover_stream_and_scale():
    from kme_tpu.workload import (STORM_PROFILES, storm_stream,
                                  storm_windows)

    for name in STORM_PROFILES:
        msgs = storm_stream(name, 800, num_symbols=8, num_accounts=16,
                            seed=0)
        wins = storm_windows(name, 800, num_symbols=8, num_accounts=16)
        assert wins, name
        for lo, hi, mult in wins:
            assert 0 <= lo < hi <= len(msgs), (name, lo, hi, len(msgs))
            assert mult > 1, name


def test_storm_profile_character():
    from kme_tpu import opcodes as op
    from kme_tpu.workload import storm_stream, storm_windows

    # payout-storm-wide: one contiguous burst settling EVERY symbol
    a = storm_stream("payout-storm-wide", 600, num_symbols=16,
                     num_accounts=16, seed=1)
    payouts = [i for i, m in enumerate(a) if m.action == op.PAYOUT]
    assert len(payouts) == 16
    assert payouts[-1] - payouts[0] == 2 * 15        # contiguous burst
    (lo, hi, mult), = storm_windows("payout-storm-wide", 600,
                                    num_symbols=16, num_accounts=16)
    assert lo <= payouts[0] and payouts[-1] < hi

    # cancel-storm: cancels dominate, mostly for bogus oids
    c = storm_stream("cancel-storm", 2_000, num_symbols=8,
                     num_accounts=16, seed=1)
    cancels = [m for m in c if m.action == op.CANCEL]
    assert len(cancels) > 0.6 * 2_000

    # hot-book: one symbol carries nearly all the order flow
    h = storm_stream("hot-book", 2_000, num_symbols=8,
                     num_accounts=16, seed=1)
    sub = collections.Counter(m.sid for m in h
                              if m.action in (op.BUY, op.SELL))
    assert sub[0] / sum(sub.values()) > 0.9

    # liquidation-cascade: multiple full-universe settlement waves
    lq = storm_stream("liquidation-cascade", 1_000, num_symbols=8,
                      num_accounts=16, seed=1)
    assert sum(1 for m in lq if m.action == op.PAYOUT) == 2 * 8


def test_storm_profiles_survive_oracle():
    # oracle-survival at small scale: every profile's full stream must
    # process without crash, and fixed-mode solvency must hold
    from kme_tpu.workload import STORM_PROFILES, storm_stream

    for name in STORM_PROFILES:
        e = OracleEngine("fixed")
        for m in storm_stream(name, 600, num_symbols=8,
                              num_accounts=16, seed=2):
            e.process(m)
        assert all(b >= 0 for b in e.balances.values()), name


def test_adversarial_streams_survive_oracle():
    e = OracleEngine("fixed")
    for m in zipf_hot_stream(1_500, num_symbols=8, num_accounts=24,
                             seed=2):
        e.process(m)
    e2 = OracleEngine("fixed")
    for m in payout_storm_stream(1_500, num_symbols=8,
                                 num_accounts=24, seed=2):
        e2.process(m)
    assert all(b >= 0 for b in e2.balances.values())
