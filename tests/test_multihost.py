"""Multi-host execution proof: 2 OS processes x 4 virtual CPU devices
form one 8-way jax.distributed mesh running the sharded session SPMD,
and the wire output is bit-identical to a single-process run — the
evidence behind parallel/mesh.py's DCN paragraph (SURVEY.md §2.3
cross-node backend; reference analog: multiple Kafka Streams instances
joining one consumer group, KProcessor.java:59-60)."""

import hashlib
import os
import socket
import subprocess
import sys

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
@pytest.mark.parametrize("engine", ["lanes", "seq"])
def test_two_process_mesh_bit_exact(engine):
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    outs = [os.path.join(_HERE, f"_mh_out_{i}.txt") for i in range(2)]
    procs = []
    # the axon site initializes jax at interpreter startup, so the
    # platform MUST be pinned in the subprocess environment (in-script
    # os.environ assignment is too late)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    # the axon sitecustomize registers (and claims) the TPU backend at
    # interpreter startup whenever PALLAS_AXON_POOL_IPS is set,
    # overriding JAX_PLATFORMS — strip it so the workers are pure-CPU
    env.pop("PALLAS_AXON_POOL_IPS", None)
    for i in range(2):
        if os.path.exists(outs[i]):
            os.unlink(outs[i])
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(_HERE, "distributed_worker.py"),
             coord, "2", str(i), outs[i], engine],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True))
    results = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed worker timed out")
        results.append((p.returncode, out, err))
    for rc, out, err in results:
        assert rc == 0, f"worker failed rc={rc}\n{err[-3000:]}"

    # single-process golden (8 virtual devices in THIS process — the
    # conftest already forces that topology), from the SAME
    # session/stream definition the workers use
    from tests.distributed_worker import build_session_and_stream

    ses, msgs = build_session_and_stream(engine)
    golden = ses.process_wire(msgs)
    blob = "\n".join(l for ls in golden for l in ls).encode()
    want = f"{hashlib.sha256(blob).hexdigest()} {len(blob)}"

    for i in range(2):
        with open(outs[i]) as f:
            got = f.read().strip()
        assert got == want, f"worker {i} stream differs from golden"
        os.unlink(outs[i])
