"""Cluster-wide distributed tracing (telemetry/dtrace.py, kme-trace
--cluster, kme-agg). Pins the contracts the observability plane stands
on:

- trace identity is REPLAY-DERIVED: pure mixes of durable identity
  (offset/aid/oid), never a clock or RNG — re-running the same input
  re-mints byte-identical ids (and the vectorized batch minter matches
  the scalar bit for bit);
- the stitcher joins per-group span journals to the deterministic
  front split offline: every admitted order gets exactly one complete
  waterfall, cross-shard transfer legs linked parent/child, replay
  segments deduplicated by the durable (group, local_off, kind) key;
- tracing is ADDITIVE: MatchOut bytes are identical with span
  journaling on or off, and the span ETYPE round-trips identically
  through the JSONL and binary journal framings;
- the SLO plane merges latency histograms at the raw bucket level —
  cluster quantiles are exact, not quantile-of-quantiles — and its
  p99 exemplars resolve back to stitched waterfalls.
"""

import json
import os
import random

import pytest

from kme_tpu import opcodes as op
from kme_tpu.bridge import front
from kme_tpu.bridge.broker import InProcessBroker
from kme_tpu.bridge.provision import group_topics, provision
from kme_tpu.bridge.service import TOPIC_IN, MatchService
from kme_tpu.telemetry import dtrace
from kme_tpu.telemetry.journal import SPAN_KINDS, Journal, read_events
from kme_tpu.wire import dumps_order
from kme_tpu.workload import cross_account_stream, harness_stream


# -- identity ----------------------------------------------------------


def test_trace_ids_are_pure_and_distinct():
    a = dtrace.trace_id(7, 42, 123456)
    assert a == dtrace.trace_id(7, 42, 123456)      # pure
    assert a != dtrace.trace_id(8, 42, 123456)      # offset matters
    assert a != dtrace.local_tid(7, 42)             # distinct salt
    assert a != dtrace.client_trace_id(7, 42, 123456)
    assert a != dtrace.child_tid(a, 1)
    assert dtrace.child_tid(a, 1) != dtrace.child_tid(a, 2)
    for tid in (a, dtrace.local_tid(0, 0), dtrace.child_tid(a, 1),
                dtrace.client_trace_id(0, 0, 0)):
        assert 0 < tid < (1 << 63)      # journal <q packable, nonzero


def test_vectorized_client_ids_match_scalar():
    rng = random.Random(5)
    seq0 = rng.randrange(0, 1 << 40)
    aids = [rng.randrange(0, 1 << 31) for _ in range(64)]
    oids = [rng.randrange(0, 1 << 62) for _ in range(64)]
    assert dtrace.client_trace_ids(seq0, aids, oids) == [
        dtrace.client_trace_id(seq0 + j, aids[j], oids[j])
        for j in range(64)]


# -- route map ---------------------------------------------------------


def _grouped_lines(events=240, ngroups=2, seed=4, cross_frac=1.0):
    msgs = cross_account_stream(events, 32 * ngroups, 8 * ngroups,
                                ngroups, seed=seed,
                                cross_frac=cross_frac)
    return [dumps_order(m) for m in msgs]


def test_route_map_matches_split_and_classifies_legs():
    lines = _grouped_lines()
    entries, router = dtrace.route_map(lines, 2)
    per, ref_router = front.split_lines(lines, 2)
    assert router.counters == ref_router.counters
    # primary rows and legs back-reference the exact split positions
    li = [0, 0]
    for ent, line in zip(entries, lines):
        assert ent is not None
        rows = sorted([(ent["g"], ent["li"])]
                      + [(lg["g"], lg["li"]) for lg in ent["legs"]])
        for g, idx in rows:
            li[g] = max(li[g], idx + 1)
        assert per[ent["g"]][ent["li"]] == line
    assert li == [len(per[0]), len(per[1])]
    # cross-shard BUY/SELL legs come in route_line's emission order:
    # home debit first (xfer_reserve), symbol credit second
    crossed = [e for e in entries
               if e["act"] in (op.BUY, op.SELL) and e["legs"]]
    assert crossed, "cross_frac=1.0 produced no cross-shard orders"
    for ent in crossed:
        assert [lg["kind"] for lg in ent["legs"]] == [
            "xfer_reserve", "xfer_settle"]
        assert {lg["tid"] for lg in ent["legs"]} == {
            dtrace.child_tid(ent["tid"], 1),
            dtrace.child_tid(ent["tid"], 2)}
    # CREATE_BALANCE broadcasts are "route" legs on the other groups
    creates = [e for e in entries
               if e["act"] == op.CREATE_BALANCE and e["legs"]]
    assert creates
    for ent in creates:
        assert all(lg["kind"] == "route" for lg in ent["legs"])


# -- in-process cluster run + stitching --------------------------------


def _run_group(k, ngroups, glines, tmp_path, trace=True, batch=64):
    """Serve one group's substream in-process; returns the journal
    path and the group's MatchOut values."""
    gdir = tmp_path / f"group{k}" / "state"
    os.makedirs(gdir, exist_ok=True)
    jp = str(gdir / "journal.bin")
    br = InProcessBroker()
    topics = group_topics(k) if ngroups > 1 else None
    provision(br, topics=topics)
    topic_in = topics[0] if topics else TOPIC_IN
    for ln in glines:
        br.produce(topic_in, None, ln)
    svc = MatchService(br, engine="oracle", compat="fixed",
                       batch=batch, journal=jp, trace_spans=trace,
                       group=(k, ngroups) if ngroups > 1 else None)
    seen = 0
    while seen < len(glines):
        seen += svc.step(timeout=0.1)
    svc.close()
    out_topic = topics[1] if topics else "MatchOut"
    out = [r.value for r in br.fetch(out_topic, 0, 1 << 20)]
    snap = svc.telemetry.snapshot()
    return jp, out, snap


def _stitch_run(lines, ngroups, tmp_path):
    per, _router = front.split_lines(lines, ngroups)
    group_events, snaps = {}, []
    for k in range(ngroups):
        jp, _out, snap = _run_group(k, ngroups, per[k], tmp_path)
        group_events[k] = [ev for ev in read_events(jp)
                           if ev.get("e") in ("span", "lat")]
        snaps.append((f"g{k}", snap))
    return dtrace.stitch(lines, group_events, ngroups), snaps


@pytest.mark.parametrize("ngroups", [2, 4])
def test_stitch_links_every_admitted_order(ngroups, tmp_path):
    lines = _grouped_lines(events=200, ngroups=ngroups, seed=7)
    doc, _snaps = _stitch_run(lines, ngroups, tmp_path)
    assert doc["admitted"] == len(lines)
    assert doc["stitched"] == doc["admitted"]       # 100% >= 99.9%
    by_off = {o["off"]: o for o in doc["orders"]}
    assert len(by_off) == len(doc["orders"])        # no forks
    entries, _ = dtrace.route_map(lines, ngroups)
    for ent in entries:
        o = by_off[ent["off"]]
        assert o["complete"], o
        kinds = [sp["kind"] for sp in o["spans"]]
        for stage in ("front_accept", "route", "ingress", "plan",
                      "device", "produce", "merge"):
            assert stage in kinds, (o["off"], kinds)
        # every injected leg resolved on ITS group, linked to parent
        legs = [sp for sp in o["spans"]
                if sp["kind"] in ("xfer_reserve", "xfer_settle")]
        want = [lg for lg in ent["legs"]
                if lg["kind"] != "route"]
        assert len(legs) == len(want)
        for sp, lg in zip(legs, want):
            assert sp["g"] == lg["g"]
            assert sp["tid"] == lg["tid"]
            assert sp["ptid"] == ent["tid"]
        # waterfall extent covers every span (legs run on the other
        # group's clock and must not fall outside the window)
        for sp in o["spans"]:
            assert o["t0"] <= sp["t0"] <= sp["t1"] <= o["t1"]


def test_crash_replay_restitches_identical_ids(tmp_path):
    """Two independent runs over the same substreams (the crash-replay
    model: same input prefix, fresh wall clocks) stitch to the same
    trace ids, spans and linkage — only timestamps differ."""
    lines = _grouped_lines(events=120, ngroups=2, seed=11)

    def skeleton(doc):
        return [(o["off"], o["tid"], o["complete"],
                 [(sp["kind"], sp["g"], sp["tid"], sp["ptid"])
                  for sp in o["spans"]])
                for o in doc["orders"]]

    doc1, _ = _stitch_run(lines, 2, tmp_path / "run1")
    doc2, _ = _stitch_run(lines, 2, tmp_path / "run2")
    assert skeleton(doc1) == skeleton(doc2)


def test_replay_overlap_dedups_first_wins():
    evs = [{"e": "span", "kind": "ingress", "off": 0, "oid": 1,
            "tid": 9, "ptid": 0, "t0": 100, "t1": 110},
           {"e": "span", "kind": "ingress", "off": 0, "oid": 1,
            "tid": 9, "ptid": 0, "t0": 900, "t1": 910}]
    spans = dtrace.collect_group_spans(evs, 0)
    assert spans[(0, "ingress")]["t0"] == 100      # first occurrence


def test_matchout_bytes_identical_tracing_on_off(tmp_path):
    lines = [dumps_order(m) for m in harness_stream(
        200, seed=3, num_accounts=6, num_symbols=2,
        payout_opcode_bug=False, validate=True)]
    _jp1, out_on, _ = _run_group(0, 1, lines, tmp_path / "on",
                                 trace=True)
    _jp2, out_off, _ = _run_group(0, 1, lines, tmp_path / "off",
                                  trace=False)
    assert out_on == out_off


def test_span_events_roundtrip_json_and_binary(tmp_path):
    spans = [{"kind": k, "g": 1, "off": 10 + i, "oid": 5 + i,
              "aid": 3, "tid": dtrace.local_tid(1, 10 + i),
              "ptid": 0, "t0": 1000 + i, "t1": 1010 + i, "li": -1}
             for i, k in enumerate(SPAN_KINDS)]
    docs = {}
    for ext in ("jsonl", "bin"):
        p = str(tmp_path / f"j.{ext}")
        j = Journal(p, resume=False)
        j.record_spans(spans, batch=2)
        j.close()
        docs[ext] = [ev for ev in read_events(p)
                     if ev.get("e") == "span"]
    assert len(docs["jsonl"]) == len(SPAN_KINDS)
    for a, b in zip(docs["jsonl"], docs["bin"]):
        for key in ("kind", "off", "oid", "tid", "ptid", "t0", "t1"):
            assert a.get(key) == b.get(key), key


# -- lat fallback, waterfall + chrome rendering ------------------------


def test_lat_fallback_synthesizes_contiguous_stages():
    ev = {"e": "lat", "off": 4, "oid": 9, "ts": 5000, "e2e_us": 40,
          "in_us": 10, "plan_us": 5, "dev_us": 20, "prod_us": 5}
    spans = dtrace.collect_group_spans([ev], 2)
    t = 5000 - 40
    for kind, dur in (("ingress", 10), ("plan", 5), ("device", 20),
                      ("produce", 5)):
        sp = spans[(4, kind)]
        assert (sp["t0"], sp["t1"]) == (t, t + dur)
        assert sp["tid"] == dtrace.local_tid(2, 4)
        t += dur


def test_waterfall_and_chrome_outputs(tmp_path):
    lines = _grouped_lines(events=80, ngroups=2, seed=13)
    doc, _ = _stitch_run(lines, 2, tmp_path)
    order = doc["orders"][0]
    text = dtrace.waterfall_text(order)
    assert f"oid={order['oid']}" in text
    assert f"tid=0x{order['tid']:016x}" in text
    for sp in order["spans"]:
        assert sp["kind"] in text
    chrome = dtrace.chrome_trace_doc(doc)
    evs = chrome["traceEvents"]
    assert {e["ph"] for e in evs} >= {"M", "X"}
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == sum(len(o["spans"]) for o in doc["orders"])
    # cross-group hops draw flow arrows
    assert any(e["ph"] == "s" for e in evs)
    assert any(e["ph"] == "f" and e.get("bp") == "e" for e in evs)
    json.dumps(chrome)      # serializable as written


def test_find_order_by_aid_oid_and_tid(tmp_path):
    lines = _grouped_lines(events=60, ngroups=2, seed=17)
    doc, _ = _stitch_run(lines, 2, tmp_path)
    from collections import Counter

    keys = Counter((o["aid"], o["oid"]) for o in doc["orders"])
    o = next(o for o in doc["orders"]
             if keys[(o["aid"], o["oid"])] == 1)
    assert dtrace.find_order(doc, f"{o['aid']}:{o['oid']}") is o
    assert dtrace.find_order(doc, str(o["tid"])) is o
    assert dtrace.find_order(doc, hex(o["tid"])) is o
    assert dtrace.find_order(doc, "999999:1") is None


# -- front trace + state-root stitching --------------------------------


def test_write_front_trace_spans_are_real_at_stitch(tmp_path):
    lines = _grouped_lines(events=60, ngroups=2, seed=19)
    tp = str(tmp_path / "front.trace")
    wrote = front.write_front_trace(tp, lines, 2)
    assert wrote == 2 * len(lines)      # front_accept + route each
    per, _ = front.split_lines(lines, 2)
    group_events = {}
    for k in range(2):
        jp, _out, _snap = _run_group(k, 2, per[k], tmp_path)
        group_events[k] = [ev for ev in read_events(jp)
                           if ev.get("e") in ("span", "lat")]
    doc = dtrace.stitch(lines, group_events, 2,
                        front_events=list(read_events(tp)))
    for o in doc["orders"]:
        for sp in o["spans"]:
            if sp["kind"] in ("front_accept", "route"):
                assert not sp.get("synthetic"), sp


def test_stitch_state_root_layout(tmp_path):
    lines = _grouped_lines(events=60, ngroups=2, seed=23)
    per, _ = front.split_lines(lines, 2)
    for k in range(2):
        _run_group(k, 2, per[k], tmp_path)
    with open(tmp_path / "front.in", "w") as f:
        f.write("\n".join(lines) + "\n")
    doc = dtrace.stitch_state_root(str(tmp_path))
    assert doc["admitted"] == doc["stitched"] == len(lines)
    assert dtrace.discover_groups(str(tmp_path)) == [
        (0, str(tmp_path / "group0")), (1, str(tmp_path / "group1"))]
    with pytest.raises(FileNotFoundError):
        dtrace.stitch_state_root(str(tmp_path / "group0"))


# -- exemplars + the SLO plane -----------------------------------------


def test_exemplars_resolve_to_waterfalls(tmp_path):
    lines = _grouped_lines(events=120, ngroups=2, seed=29)
    doc, snaps = _stitch_run(lines, 2, tmp_path)
    agg = dtrace.aggregate(snaps, slo_ms=60_000.0)
    assert agg["exemplars"], "service kept no slowest-order exemplars"
    # worst first, and each resolves to a stitched waterfall
    e2es = [e["e2e_us"] for e in agg["exemplars"]]
    assert e2es == sorted(e2es, reverse=True)
    for ex in agg["exemplars"][:4]:
        o = dtrace.find_order(doc, f"{ex['aid']}:{ex['oid']}")
        assert o is not None and o["complete"]
        # the exemplar's group-local join key resolves on its own,
        # to the exact order (kme-trace --order 0x<tid>)
        o2 = dtrace.find_order(doc, f"0x{ex['tid']:x}")
        assert o2 is not None and ex["tid"] in o2["ltids"]
    # SLO plane: merged e2e count covers every record the groups
    # served exactly once (input lines + front-injected XFER legs)
    per, _ = front.split_lines(lines, 2)
    assert agg["e2e"]["count"] == sum(len(p) for p in per)
    assert agg["slo"]["burn_rate"] is not None
    text = dtrace.render_agg(agg)
    assert "slowest orders" in text


def test_merged_quantiles_are_exact():
    """Summing buckets then computing quantiles == one histogram that
    saw every observation (never quantile-of-quantiles)."""
    from kme_tpu.telemetry.registry import LatencyHistogram

    def snap_of(h):
        count, total, counts = h.state()
        return {"count": count, "sum_s": round(total, 6),
                "p50_ms": round(h._quantile_from(
                    counts, count, 0.5) * 1e3, 3),
                "p90_ms": round(h._quantile_from(
                    counts, count, 0.9) * 1e3, 3),
                "p99_ms": round(h._quantile_from(
                    counts, count, 0.99) * 1e3, 3),
                "p999_ms": round(h._quantile_from(
                    counts, count, 0.999) * 1e3, 3),
                "buckets": counts}

    rng = random.Random(31)
    h1, h2, href = (LatencyHistogram("lat_e2e") for _ in range(3))
    for i in range(400):
        v = rng.uniform(1e-6, 0.5)
        (h1 if i % 2 else h2).observe(v)
        href.observe(v)
    snaps = [("a", {"latencies": {"lat_e2e": snap_of(h1)}}),
             ("b", {"latencies": {"lat_e2e": snap_of(h2)}})]
    merged = dtrace.merge_latencies(snaps)["lat_e2e"]
    want = snap_of(href)
    assert merged["buckets"] == want["buckets"]
    for q in ("p50_ms", "p90_ms", "p99_ms", "p999_ms"):
        assert merged[q] == want[q], q


def test_aggregate_renders_degraded_rows():
    snaps = [("g0", {"latencies": {}, "gauges": {}, "counters": {}}),
             ("g1", None)]
    agg = dtrace.aggregate(snaps)
    rows = {r["source"]: r for r in agg["per_group"]}
    assert rows["g0"]["up"] and not rows["g1"]["up"]
    assert "DEGRADED (unreachable)" in dtrace.render_agg(agg)


# -- endpoint discovery (kme-top --cluster) ----------------------------


def test_discover_endpoints_and_cluster_render(tmp_path):
    from kme_tpu.telemetry import top

    for k in range(2):
        os.makedirs(tmp_path / f"group{k}" / "state")
    hb = {"pid": 1, "time": 0, "offset": 7,
          "metrics": {"counters": {"service_records": 7},
                      "gauges": {}, "latencies": {}}}
    with open(tmp_path / "group0" / "state" / "serve.health",
              "w") as f:
        json.dump(hb, f)
    eps = top.discover_endpoints(str(tmp_path))
    assert [g["k"] for g in eps["groups"]] == [0, 1]
    cur = top.collect_cluster(eps["groups"])
    text = "\n".join(top.render_cluster(cur))
    assert "g0" in text
    # group1 never wrote a heartbeat: a degraded row, not a crash
    assert "DEGRADED" in text
    assert "1/2 groups up" in text


def test_aggregate_feed_rows_carry_fanout_health():
    """kme-agg (ISSUE 13): a scraped kme-feed heartbeat contributes a
    per-source row with subscriber count, conflation rate and feed
    lag; sources without feed gauges are untouched."""
    feed_snap = {
        "counters": {"feed_delivered_total": 900,
                     "feed_conflated_frames_total": 100},
        "gauges": {"feed_subscribers": 7},
        "latencies": {"feed_lag": {
            "count": 900, "sum_s": 0.5, "p50_ms": 0.4, "p90_ms": 1.0,
            "p99_ms": 2.5, "p999_ms": 4.0}}}
    plain = {"counters": {}, "gauges": {}, "latencies": {}}
    agg = dtrace.aggregate([("feed", feed_snap), ("g0", plain)])
    rows = {r["source"]: r for r in agg["per_group"]}
    assert rows["feed"]["feed_subs"] == 7
    assert rows["feed"]["feed_conflation"] == pytest.approx(0.1)
    assert rows["feed"]["feed_lag_p99_ms"] == 2.5
    assert "feed_subs" not in rows["g0"]
    text = dtrace.render_agg(agg)
    assert "feed_subs=7" in text and "feed_conflation=0.1" in text
