"""Hot-standby replica: the _FollowBroker tail, pid-addressed promote
orders, the one-batch holdback bound, and the follow -> promote
state machine end to end (exactly-once across the failover)."""

import json
import os
import threading
import time

import pytest

from kme_tpu.bridge import lease
from kme_tpu.bridge.broker import BrokerError, InProcessBroker
from kme_tpu.bridge.consume import DedupRing
from kme_tpu.bridge.provision import provision
from kme_tpu.bridge.replica import _FollowBroker, Replica
from kme_tpu.bridge.service import TOPIC_IN, TOPIC_OUT, MatchService
from kme_tpu.wire import dumps_order
from kme_tpu.workload import harness_stream


# ---------------------------------------------------------------------------
# _FollowBroker: a bounded tail over the leader's durable MatchIn log


def _log(tmp_path, lines):
    path = tmp_path / f"{TOPIC_IN}.log"
    with open(path, "ab") as f:
        for ln in lines:
            f.write(ln)
    return path


def test_follow_broker_tails_and_respects_limit(tmp_path):
    fb = _FollowBroker(str(tmp_path))
    assert fb.fetch(TOPIC_IN, 0, 10) == []      # log not created yet
    _log(tmp_path, [b'["k", "a"]\n', b'["k", "b"]\n', b'["k", "c"]\n'])
    assert fb.fetch(TOPIC_IN, 0, 10) == []      # limit still 0
    fb.limit = 2
    assert [r.value for r in fb.fetch(TOPIC_IN, 0, 10)] == ["a", "b"]
    assert fb.end_offset(TOPIC_IN) == 3         # end_offset is unbounded
    fb.limit = 10
    _log(tmp_path, [b'["k", "d", 2, 7]\n'])     # stamped row tails too
    recs = fb.fetch(TOPIC_IN, 0, 10)
    assert [r.value for r in recs] == ["a", "b", "c", "d"]
    assert (recs[3].epoch, recs[3].out_seq) == (2, 7)
    assert recs[0].epoch is None


def test_follow_broker_leaves_torn_tail_unconsumed(tmp_path):
    fb = _FollowBroker(str(tmp_path))
    fb.limit = 10
    _log(tmp_path, [b'["k", "a"]\n', b'["k", "b'])      # torn mid-append
    assert [r.value for r in fb.fetch(TOPIC_IN, 0, 10)] == ["a"]
    _log(tmp_path, [b'"]\n'])                           # append completes
    assert [r.value for r in fb.fetch(TOPIC_IN, 0, 10)] == ["a", "b"]


def test_follow_broker_resets_when_file_shrinks(tmp_path):
    fb = _FollowBroker(str(tmp_path))
    fb.limit = 10
    path = _log(tmp_path, [b'["k", "a"]\n', b'["k", "b"]\n'])
    assert len(fb.fetch(TOPIC_IN, 0, 10)) == 2
    with open(path, "wb") as f:                 # fresh run reused the dir
        f.write(b'["k", "z"]\n')
    fb.fetch(TOPIC_IN, 0, 10)                   # notices the truncation
    assert [r.value for r in fb.fetch(TOPIC_IN, 0, 10)] == ["z"]


def test_follow_broker_rejects_unknown_topic_and_counts_discards(tmp_path):
    fb = _FollowBroker(str(tmp_path))
    with pytest.raises(BrokerError):
        fb.fetch(TOPIC_OUT, 0, 10)
    assert fb.produce(TOPIC_OUT, "OUT", "x") == -1
    assert fb.produce(TOPIC_OUT, "OUT", "y") == -1
    assert fb.discarded == 2


# ---------------------------------------------------------------------------
# the promote order is pid-addressed


def _mk_replica(tmp_path, **kw):
    ck = str(tmp_path / "ck")
    os.makedirs(ck, exist_ok=True)
    kw.setdefault("engine", "oracle")
    kw.setdefault("batch", 16)
    kw.setdefault("slots", 64)
    kw.setdefault("max_fills", 32)
    kw.setdefault("poll", 0.02)
    kw.setdefault("health_every", 0.05)
    return Replica(ck, listen="127.0.0.1:0", **kw)


def test_read_promote_ignores_orders_for_other_pids(tmp_path):
    rep = _mk_replica(tmp_path)
    assert rep._read_promote() is None          # no file
    with open(rep.promote_file, "w") as f:
        json.dump({"failed_at": 1.0, "pid": os.getpid() + 1}, f)
    assert rep._read_promote() is None          # someone else's order
    assert os.path.exists(rep.promote_file)     # ...and left intact
    with open(rep.promote_file, "w") as f:
        json.dump({"failed_at": 1.0, "pid": os.getpid()}, f)
    assert rep._read_promote()["failed_at"] == 1.0
    with open(rep.promote_file, "w") as f:
        json.dump({"failed_at": 2.0}, f)        # pid-less: manual/test
    assert rep._read_promote()["failed_at"] == 2.0


def test_leader_offset_requires_leader_role(tmp_path):
    rep = _mk_replica(tmp_path)
    assert rep._leader_offset() == 0
    with open(rep.serve_health, "w") as f:
        json.dump({"role": "standby", "offset": 99}, f)
    assert rep._leader_offset() == 0            # never follow a follower
    with open(rep.serve_health, "w") as f:
        json.dump({"role": "leader", "offset": 80}, f)
    assert rep._leader_offset() == 80


# ---------------------------------------------------------------------------
# follow -> promote, end to end (threads, no subprocesses)


@pytest.mark.slow
def test_failover_is_exactly_once_end_to_end(tmp_path):
    """A leader checkpoints at 48, keeps producing durably through 80,
    then dies. The standby (snapshot 48, holdback-bounded tail) is
    promoted: it must re-produce the checkpoint..durable overlap, have
    every duplicate stamp suppressed, and finish the stream byte-exact
    with a clean single-leader run."""
    ck = str(tmp_path / "ck")
    os.makedirs(ck)
    log_dir = os.path.join(ck, "broker-log")
    batch = 16
    msgs = [dumps_order(m) for m in harness_stream(
        112, seed=5, num_accounts=4, num_symbols=2,
        payout_opcode_bug=False, validate=True)]
    n = len(msgs)                               # preamble included

    # -- the doomed leader: checkpoint at 48, durable output through 80
    b = InProcessBroker(persist_dir=log_dir)
    provision(b)
    for m in msgs:
        b.produce(TOPIC_IN, None, m)
    leader = MatchService(b, engine="oracle", compat="fixed", batch=batch,
                          slots=64, max_fills=32, checkpoint_dir=ck,
                          exactly_once=True)
    assert leader.epoch == 1
    leader.run(max_messages=48)
    leader.checkpoint()
    leader.run(max_messages=32)                 # durable but un-snapshotted
    with open(os.path.join(ck, "serve.health"), "w") as f:
        json.dump({"pid": 1, "time": time.time(), "role": "leader",
                   "offset": leader.offset, "tick": 9}, f)
    assert leader.offset == 80
    del leader                                  # SIGKILL: no teardown

    # -- the standby follows, bounded one batch behind
    rep = Replica(ck, listen="127.0.0.1:0", engine="oracle", batch=batch,
                  slots=64, max_fills=32, poll=0.02, health_every=0.05,
                  idle_exit=0.5,
                  health_file=os.path.join(ck, "standby.health"))
    assert rep.svc.offset == 48                 # restored the snapshot
    rc = [None]
    t = threading.Thread(target=lambda: rc.__setitem__(0, rep.run()),
                         daemon=True)
    t.start()
    deadline = time.monotonic() + 10.0
    while rep.svc.offset < 80 - batch and time.monotonic() < deadline:
        time.sleep(0.02)
    assert rep.svc.offset == 80 - batch         # the holdback bound
    time.sleep(0.1)
    assert rep.svc.offset == 80 - batch         # ...and it HOLDS
    assert rep.follow.discarded > 0             # output counted, not kept

    # -- promotion (pid-less order: test-driven)
    failed_at = time.time()
    with open(rep.promote_file, "w") as f:
        json.dump({"failed_at": failed_at}, f)
    t.join(timeout=30.0)
    assert not t.is_alive() and rc[0] == 0
    assert not os.path.exists(rep.promote_file)
    assert lease.current_epoch(ck) == 2
    gauges = rep.svc.telemetry.snapshot()["gauges"]
    assert gauges["leader_epoch"] == 2
    assert gauges["failover_seconds"] >= 0.0
    assert gauges["dup_suppressed_total"] > 0   # the overlap replayed

    # -- the durable MatchOut stream: deduped == byte-exact reference
    rows = [json.loads(ln) for ln in
            open(os.path.join(log_dir, f"{TOPIC_OUT}.log"))]
    ring = DedupRing()
    assert not any(ring.is_dup(r[2], r[3]) for r in rows)
    b3 = InProcessBroker()
    provision(b3)
    for m in msgs:
        b3.produce(TOPIC_IN, None, m)
    ref = MatchService(b3, engine="oracle", compat="fixed", batch=batch,
                       slots=64, max_fills=32)
    ref.run(max_messages=n)
    want = [r.value for r in b3.fetch(TOPIC_OUT, 0, 10 ** 6)]
    assert [r[1] for r in rows] == want
