"""Order-lifecycle flight recorder (kme_tpu/telemetry/journal.py):
framing round-trips, oracle-replay agreement, rotation, torn-tail
resume, at-least-once rewind, pipeline-window math and lifecycle
reconstruction."""

import json
import os

from kme_tpu.oracle import OracleEngine
from kme_tpu.telemetry.journal import (MAGIC, REC_SIZE, Journal,
                                       account_history, batch_events,
                                       canonical_lines, iter_events,
                                       lifecycle_summary,
                                       measured_overlap_s,
                                       oracle_events, order_lifecycle,
                                       read_events)
from kme_tpu.wire import REJ_MALFORMED, dumps_order, parse_order
from kme_tpu.workload import harness_stream


def _wire_groups(n=300, seed=11):
    """Input lines + the oracle's per-message wire line groups — the
    same shape the sessions hand the journal."""
    msgs = harness_stream(n, seed=seed, num_accounts=6, num_symbols=2,
                          payout_opcode_bug=False, validate=True)
    lines = [dumps_order(m) for m in msgs]
    eng = OracleEngine("fixed")
    groups = [[r.wire() for r in eng.process(parse_order(ln))]
              for ln in lines]
    return lines, groups


def _fill_journal(path, groups, chunk=100, **kw):
    j = Journal(path, clock=lambda: 1_000_000, **kw)
    for lo in range(0, len(groups), chunk):
        part = groups[lo:lo + chunk]
        j.record_batch(part, offsets=list(range(lo, lo + len(part))))
    j.close()
    return j


# ---------------------------------------------------------------------------
# derivation + framing


def test_journal_matches_independent_oracle_replay(tmp_path):
    lines, groups = _wire_groups()
    for name in ("j.jsonl", "j.bin"):
        path = str(tmp_path / name)
        _fill_journal(path, groups)
        got = canonical_lines(read_events(path))
        want = canonical_lines(oracle_events(lines))
        assert got == want and len(got) > len(lines)


def test_binary_and_jsonl_decode_identically(tmp_path):
    _, groups = _wire_groups()
    jp, bp = str(tmp_path / "j.jsonl"), str(tmp_path / "j.bin")
    _fill_journal(jp, groups)
    _fill_journal(bp, groups)
    assert open(bp, "rb").read(len(MAGIC)) == MAGIC
    ev_j, ev_b = read_events(jp), read_events(bp)
    assert ev_j == ev_b                 # full dicts, stamps included
    body = os.path.getsize(bp) - len(MAGIC)
    assert body == len(ev_b) * REC_SIZE


def test_event_order_and_stamps(tmp_path):
    _, groups = _wire_groups()
    path = str(tmp_path / "j.jsonl")
    _fill_journal(path, groups, chunk=50)
    evs = read_events(path)
    seqs = [e["seq"] for e in evs]
    assert seqs == list(range(len(evs)))  # dense + monotonic
    assert all(e["ts"] == 1_000_000 and e["sh"] == 0 for e in evs)
    batches = [e["b"] for e in evs]
    assert batches == sorted(batches)
    # per accepted trade: accept precedes its fills precedes any rest
    by_slot = {}
    for e in evs:
        if e["b"] == 0:
            by_slot.setdefault(e["i"], []).append(e["e"])
    for kinds in by_slot.values():
        assert kinds[0] == "submit"
        if "fill" in kinds:
            assert kinds.index("accept") < kinds.index("fill")
        if "rest" in kinds:
            assert kinds.index("rest") == len(kinds) - 1


def test_drop_and_reject_events():
    lines, _ = _wire_groups(80)
    lines.insert(3, "{not json")
    lines.insert(7, '{"action":2,"oid":1,"aid":1,"sid":0,'
                    '"price":99999999999,"size":1,"next":null,'
                    '"prev":null}')   # price outside int32 -> drop
    evs = oracle_events(lines)
    drops = [e for e in evs if e["e"] == "drop"]
    assert [d["off"] for d in drops] == [3, 7]
    assert all(d["rej"] == REJ_MALFORMED for d in drops)
    rejs = [e for e in evs if e["e"] == "reject"]
    assert rejs and all(e["rej"] > 0 for e in rejs)


def test_window_records_roundtrip(tmp_path):
    for name in ("w.jsonl", "w.bin"):
        path = str(tmp_path / name)
        j = Journal(path, clock=lambda: 5)
        j.record_window("submit", 1.0, 2.5, batch=0)
        j.record_window("collect", 2.5, 3.0, batch=0)
        j.close()
        evs = read_events(path)
        assert [e["e"] for e in evs] == ["win", "win"]
        assert evs[0]["kind"] == "submit"
        assert (evs[0]["t0"], evs[0]["t1"]) == (1_000_000, 2_500_000)
        assert evs[1]["kind"] == "collect"
        # windows are provenance-only: canonical comparison drops them
        assert canonical_lines(evs) == []


# ---------------------------------------------------------------------------
# durability behaviors


def test_rotation_shifts_and_reads_in_order(tmp_path):
    _, groups = _wire_groups(200)
    path = str(tmp_path / "r.jsonl")
    _fill_journal(path, groups, chunk=20, rotate_bytes=4096)
    assert os.path.exists(path + ".1")   # rotated at least once
    evs = read_events(path)
    seqs = [e["seq"] for e in evs]
    assert seqs == list(range(len(evs)))
    live_only = read_events(path, include_rotated=False)
    assert len(live_only) < len(evs)
    assert canonical_lines(evs) == canonical_lines(
        oracle_events([ln for ln in _wire_groups(200)[0]]))


def test_resume_continues_seq_after_torn_tail(tmp_path):
    _, groups = _wire_groups(120)
    for name, torn in (("t.jsonl", b'{"e":"subm'),
                       ("t.bin", b"\x01\x02\x03garbage")):
        path = str(tmp_path / name)
        _fill_journal(path, groups[:60])
        n0 = len(read_events(path))
        top = read_events(path)[-1]["seq"]
        with open(path, "ab") as f:
            f.write(torn)               # crash mid-record
        assert len(read_events(path)) == n0   # reader ignores the tear
        j = Journal(path, clock=lambda: 7)    # resume truncates it
        assert j.next_seq == top + 1
        j.record_batch(groups[60:70],
                       offsets=list(range(60, 70)))
        j.close()
        evs = read_events(path)
        seqs = [e["seq"] for e in evs]
        assert seqs == list(range(len(evs)))  # still dense


def test_rewind_to_offset_dedups_replay(tmp_path):
    _, groups = _wire_groups(100)
    for name in ("rw.jsonl", "rw.bin"):
        path = str(tmp_path / name)
        _fill_journal(path, groups, chunk=25)
        j = Journal(path, clock=lambda: 9)
        j.record_window("submit", 0.0, 1.0)   # off == -1: must survive
        j.rewind_to_offset(50)
        # replay the tail, as the service does after a snapshot resume
        j.record_batch(groups[50:75], offsets=list(range(50, 75)))
        j.record_batch(groups[75:100], offsets=list(range(75, 100)))
        j.close()
        evs = read_events(path)
        offs = [e["off"] for e in evs if e["e"] == "submit"]
        assert offs == list(range(100))       # exactly once each
        assert any(e["e"] == "win" for e in evs)
        seqs = [e["seq"] for e in evs]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)


def test_async_writer_preserves_order(tmp_path):
    _, groups = _wire_groups(150)
    path = str(tmp_path / "a.jsonl")
    j = Journal(path, async_write=True, clock=lambda: 1)
    seen = []
    j.observers.append(lambda evs, lines: seen.extend(evs))
    for lo in range(0, len(groups), 30):
        j.record_batch(groups[lo:lo + 30],
                       offsets=list(range(lo, lo + 30)))
    j.flush()
    j.close()
    evs = read_events(path)
    assert [e["seq"] for e in evs] == list(range(len(evs)))
    assert seen == evs                   # observers see committed form
    assert canonical_lines(evs) == canonical_lines(
        batch_events(groups, offsets=list(range(len(groups)))))


def test_fsync_batch_mode_writes_through(tmp_path):
    _, groups = _wire_groups(40)
    path = str(tmp_path / "f.jsonl")
    j = Journal(path, fsync="batch", clock=lambda: 1)
    j.record_batch(groups, offsets=list(range(len(groups))))
    # no close(): batch fsync means the bytes are already durable
    assert len(read_events(path)) > len(groups)
    j.close()


# ---------------------------------------------------------------------------
# pipeline-window math (the bench's measured_overlap_s)


def test_measured_overlap_full_and_none():
    # double-buffered: collect(0) runs entirely while batch 1 is
    # submitted-but-not-collected -> the whole window counts
    over = measured_overlap_s([
        ("submit", 0, 0.0, 1.0), ("submit", 1, 1.0, 2.0),
        ("collect", 0, 3.0, 4.0), ("collect", 1, 5.0, 6.0)])
    assert abs(over - 1.0) < 1e-9
    # strictly serial: nothing in flight during any collect
    assert measured_overlap_s([
        ("submit", 0, 0.0, 1.0), ("collect", 0, 1.0, 2.0),
        ("submit", 1, 2.0, 3.0), ("collect", 1, 3.0, 4.0)]) == 0.0
    # partial cover is clipped to the intersection: batch 1 is in
    # flight over [2.0, 2.5], which collect(0)'s [1.5, 3.0] overlaps
    # for 0.5s; nothing is in flight during collect(1)
    over = measured_overlap_s([
        ("submit", 0, 0.0, 1.0), ("submit", 1, 1.0, 2.0),
        ("collect", 0, 1.5, 3.0), ("collect", 1, 2.5, 4.0)])
    assert abs(over - 0.5) < 1e-9


# ---------------------------------------------------------------------------
# lifecycle reconstruction (what kme-trace prints)


def test_order_lifecycle_and_summary(tmp_path):
    lines, groups = _wire_groups(400, seed=5)
    evs = batch_events(groups, offsets=list(range(len(groups))))
    fills = [e for e in evs if e["e"] == "fill"]
    assert fills
    taker = fills[0]["oid"]
    life = order_lifecycle(evs, taker)
    assert [e["e"] for e in life][:2] == ["submit", "accept"]
    assert any(e["e"] == "fill" for e in life)
    summ = lifecycle_summary(life, taker)
    assert summ["oid"] == taker and summ["filled"] > 0
    assert summ["state"] in ("filled", "accepted", "resting")
    # maker-side: the resting order's lifecycle includes the same fill
    maker = fills[0]["moid"]
    mlife = order_lifecycle(evs, maker)
    assert any(e["e"] == "fill" and e.get("moid") == maker
               for e in mlife)
    # account view covers both sides of its fills
    hist = account_history(evs, fills[0]["maid"])
    assert any(e["e"] == "fill" for e in hist)


def test_iter_events_plain_jsonl_without_stamps(tmp_path):
    # a journal written by other tooling (no seq stamps) still parses
    path = str(tmp_path / "x.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"e": "submit", "oid": 1}) + "\n")
        f.write('{"e":"accept","oid":1}')   # torn final line: ignored
    assert list(iter_events(path)) == [{"e": "submit", "oid": 1}]


# ---------------------------------------------------------------------------
# retention: rotate_keep bounded by the snapshot retention guard


def _segments(path):
    n = 1
    while os.path.exists(f"{path}.{n}"):
        n += 1
    return n - 1


def test_rotate_keep_prunes_old_segments(tmp_path):
    _, groups = _wire_groups()
    free = str(tmp_path / "free.jsonl")
    _fill_journal(free, groups, chunk=20, rotate_bytes=2048)
    assert _segments(free) >= 3                # enough history to prune

    kept = str(tmp_path / "kept.jsonl")
    _fill_journal(kept, groups, chunk=20, rotate_bytes=2048,
                  rotate_keep=2)
    assert _segments(kept) == 2
    # the live file plus the kept segments still replay contiguously
    # from SOME offset — the newest events are never the ones pruned
    offs = [ev["off"] for ev in read_events(kept) if "off" in ev]
    assert offs == sorted(offs)
    assert max(offs) == max(ev["off"] for ev in read_events(free)
                            if "off" in ev)


def test_retention_guard_blocks_pruning_of_replayable_segments(tmp_path):
    """The journal/snapshot retention coupling: a rotated segment may
    only be dropped once every event in it is older than the OLDEST
    retained snapshot — a standby restoring that snapshot must still
    be able to replay to the tip."""
    _, groups = _wire_groups()

    # guard pinned at offset 0 (oldest snapshot never pruned): every
    # segment is still replayable, rotate_keep must be overridden
    p = str(tmp_path / "pinned.jsonl")
    _fill_journal(p, groups, chunk=20, rotate_bytes=2048,
                  rotate_keep=1, retention_guard=lambda: 0)
    assert _segments(p) > 1

    # guard beyond the tip: nothing is needed, rotate_keep rules
    t = str(tmp_path / "tip.jsonl")
    _fill_journal(t, groups, chunk=20, rotate_bytes=2048,
                  rotate_keep=1, retention_guard=lambda: 10 ** 9)
    assert _segments(t) == 1

    # fail-safe: a guard that errors, or reports no snapshot at all,
    # keeps everything
    e = str(tmp_path / "err.jsonl")
    _fill_journal(e, groups, chunk=20, rotate_bytes=2048,
                  rotate_keep=1,
                  retention_guard=lambda: (_ for _ in ()).throw(OSError()))
    assert _segments(e) > 1
    n = str(tmp_path / "none.jsonl")
    _fill_journal(n, groups, chunk=20, rotate_bytes=2048,
                  rotate_keep=1, retention_guard=lambda: None)
    assert _segments(n) > 1


def test_retention_guard_wires_to_snapshot_offsets(tmp_path):
    """With the REAL guard (checkpoint.oldest_retained_offset): an old
    snapshot on disk holds every segment; once only a late snapshot
    remains, history behind it becomes prunable."""
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.runtime import checkpoint as ck

    _, groups = _wire_groups()
    ckd = str(tmp_path / "ck")
    guard = lambda: ck.oldest_retained_offset(ckd)

    ora = OracleEngine("fixed")
    ck.save_oracle(ckd, ora, 0)                # snapshot at the start
    held = str(tmp_path / "held.jsonl")
    _fill_journal(held, groups, chunk=20, rotate_bytes=2048,
                  rotate_keep=1, retention_guard=guard)
    assert _segments(held) > 1                 # replay from 0 intact

    ck.save_oracle(ckd, ora, 10 ** 6, keep=1)  # prunes the 0 snapshot
    late = str(tmp_path / "late.jsonl")
    _fill_journal(late, groups, chunk=20, rotate_bytes=2048,
                  rotate_keep=1, retention_guard=guard)
    assert _segments(late) == 1
