"""Sharded engine: determinism and exactness over the symbol mesh.

SURVEY.md §5 race-detection analog: same input log => bit-identical
output for ANY shard count. Runs on the 8-device virtual CPU mesh.
"""

import pytest

from kme_tpu.engine.lanes import LaneConfig
from kme_tpu.oracle import OracleEngine
from kme_tpu.runtime.session import LaneSession
from kme_tpu.workload import zipf_symbol_stream


@pytest.mark.slow
def test_sharded_determinism_and_oracle_parity(cpu_devices):
    msgs = zipf_symbol_stream(1500, num_symbols=16, num_accounts=32, seed=4)
    cfg = LaneConfig(lanes=16, slots=128, accounts=64, max_fills=32, steps=32)

    ora = OracleEngine("fixed")
    want = [[r.wire() for r in ora.process(m.copy())] for m in msgs]

    streams = {}
    states = {}
    for shards in (1, 2, 8):
        ses = LaneSession(cfg, shards=shards)
        got = ses.process(msgs)
        streams[shards] = [[r.wire() for r in recs] for recs in got]
        states[shards] = ses.export_state()

    for shards in (1, 2, 8):
        assert streams[shards] == want, f"oracle parity broken at shards={shards}"
    assert states[2] == states[1] and states[8] == states[1]


def test_sharded_barrier_ops(cpu_devices):
    """Payout/remove across shards: the owning shard wipes; balances are
    psum-merged identically everywhere."""
    import kme_tpu.opcodes as op
    from kme_tpu.wire import OrderMsg

    cfg = LaneConfig(lanes=4, slots=16, accounts=16, max_fills=8, steps=8)
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=100000),
            OrderMsg(action=op.CREATE_BALANCE, aid=2),
            OrderMsg(action=op.TRANSFER, aid=2, size=100000)]
    for s in range(4):
        msgs.append(OrderMsg(action=op.ADD_SYMBOL, sid=s))
    for s in range(4):
        msgs.append(OrderMsg(action=op.BUY, oid=10 + s, aid=1, sid=s,
                             price=50, size=3))
        msgs.append(OrderMsg(action=op.SELL, oid=20 + s, aid=2, sid=s,
                             price=45, size=2))
    msgs += [OrderMsg(action=op.PAYOUT, sid=2, size=97),
             OrderMsg(action=op.REMOVE_SYMBOL, sid=3),
             OrderMsg(action=op.PAYOUT, sid=-1, size=97)]

    ora = OracleEngine("fixed")
    want = [[r.wire() for r in ora.process(m.copy())] for m in msgs]
    ses = LaneSession(cfg, shards=4)
    got = [[r.wire() for r in recs] for recs in ses.process(msgs)]
    assert got == want
    assert ses.export_state()["balances"] == dict(ora.balances)
