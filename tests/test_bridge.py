"""End-to-end transport bridge tests.

The capability bar (SURVEY.md §7 step 5): the harness roles — provision,
load generator, engine, consumer — run against the MatchIn/MatchOut
topics and the consumer sees the exact `<key> <value>` line stream the
reference's consumer.js:19 prints. Byte parity is judged against the
scalar oracle replica on the same input stream.
"""

import subprocess
import sys
import time

import kme_tpu.opcodes as op
from kme_tpu.bridge.broker import InProcessBroker
from kme_tpu.bridge.consume import consume_lines
from kme_tpu.bridge.provision import provision
from kme_tpu.bridge.service import TOPIC_IN, TOPIC_OUT, MatchService
from kme_tpu.oracle import OracleEngine
from kme_tpu.wire import OrderMsg, dumps_order
from kme_tpu.workload import harness_stream


def _oracle_lines(msgs, compat, **kw):
    ora = OracleEngine(compat, **kw)
    out = []
    for m in msgs:
        out.extend(r.wire() for r in ora.process(m.copy()))
    return out


def _pump(broker, msgs):
    for m in msgs:
        broker.produce(TOPIC_IN, None, dumps_order(m))


def test_bridge_e2e_oracle_java_quirk_exact():
    """Stock harness stream through the oracle-backed service: the
    MatchOut line stream is byte-identical to the reference replica in
    java-compat mode (quirks included)."""
    broker = InProcessBroker()
    assert provision(broker) == {TOPIC_IN: True, TOPIC_OUT: True}
    msgs = harness_stream(400, seed=11)
    _pump(broker, msgs)
    svc = MatchService(broker, engine="oracle", compat="java", batch=64)
    assert svc.run(max_messages=len(msgs)) == len(msgs)
    got = list(consume_lines(broker, follow=False))
    assert got == _oracle_lines(msgs, "java")


def test_bridge_e2e_lanes_engine_fixed():
    """Validated workload through the device lanes engine service; byte
    parity vs the enveloped fixed-mode oracle."""
    broker = InProcessBroker()
    provision(broker)
    msgs = harness_stream(400, seed=5, num_symbols=4, num_accounts=8,
                          payout_opcode_bug=False, validate=True)
    _pump(broker, msgs)
    svc = MatchService(broker, engine="lanes", compat="fixed", batch=128,
                       symbols=8, accounts=16, slots=64, max_fills=32)
    assert svc.run(max_messages=len(msgs)) == len(msgs)
    got = list(consume_lines(broker, follow=False))
    assert got == _oracle_lines(msgs, "fixed", book_slots=64, max_fills=32)


def test_bridge_e2e_native_engine_quirk_exact():
    """Stock harness through the native C++ engine service: byte-
    identical MatchOut stream (the fast java-compat serving path)."""
    import pytest

    nat = pytest.importorskip("kme_tpu.native.oracle")
    if not nat.native_available():
        pytest.skip("native library unavailable")
    broker = InProcessBroker()
    provision(broker)
    msgs = harness_stream(600, seed=21)
    _pump(broker, msgs)
    svc = MatchService(broker, engine="native", compat="java", batch=128)
    assert svc.run(max_messages=len(msgs)) == len(msgs)
    got = list(consume_lines(broker, follow=False))
    assert got == _oracle_lines(msgs, "java")


def test_bridge_native_engine_death_forwards_prefix():
    """A reference-death message mid-batch: every record of the earlier
    messages reaches MatchOut BEFORE the service dies (the reference
    forwards per record; its thread dies on the poisoned one)."""
    import pytest

    nat = pytest.importorskip("kme_tpu.native.oracle")
    if not nat.native_available():
        pytest.skip("native library unavailable")
    from kme_tpu.oracle.engine import ReferenceHang

    broker = InProcessBroker()
    provision(broker)
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=100000),
            OrderMsg(action=op.ADD_SYMBOL, sid=1),
            OrderMsg(action=op.BUY, oid=5, aid=1, sid=1, price=50, size=3),
            OrderMsg(action=op.REMOVE_SYMBOL, sid=1)]  # Q4 hang
    _pump(broker, msgs)
    svc = MatchService(broker, engine="native", compat="java", batch=64)
    with pytest.raises(ReferenceHang):
        svc.run(max_messages=len(msgs))
    got = list(consume_lines(broker, follow=False))
    assert got == _oracle_lines(msgs[:4], "java")


def test_bridge_envelope_overflow_record_policy():
    """A wire-parseable record with price/size outside int32 is outside
    the Jackson envelope (Java int fields — the reference's deserializer
    dies on it): same drop/strict policy as non-JSON, for EVERY engine,
    and the stream continues past it."""
    for engine, compat in (("oracle", "java"), ("native", "java"),
                           ("lanes", "fixed")):
        if engine == "native":
            import pytest

            nat = pytest.importorskip("kme_tpu.native.oracle")
            if not nat.native_available():
                continue
        broker = InProcessBroker()
        provision(broker)
        good1 = '{"action":100,"aid":1}'
        poison = '{"action":2,"oid":1,"aid":1,"sid":1,"price":4294967296,"size":1}'
        good2 = '{"action":101,"aid":1,"size":5}'
        for v in (good1, poison, good2):
            broker.produce(TOPIC_IN, None, v)
        svc = MatchService(broker, engine=engine, compat=compat, batch=16,
                           symbols=4, accounts=8)
        assert svc.run(max_messages=3) == 3
        got = list(consume_lines(broker, follow=False))
        from kme_tpu.wire import parse_order

        want = _oracle_lines([parse_order(good1), parse_order(good2)],
                             compat)
        assert got == want, f"engine={engine}"


def test_bridge_malformed_record_policy():
    """Bad JSON is dropped (non-strict) or raises (strict — the
    reference serde kills the stream thread, KProcessor.java:513-517)."""
    import pytest

    broker = InProcessBroker()
    provision(broker)
    broker.produce(TOPIC_IN, None, '{"action":100,"aid":1}')
    broker.produce(TOPIC_IN, None, "not json at all")
    broker.produce(TOPIC_IN, None, '{"action":101,"aid":1,"size":5}')
    svc = MatchService(broker, engine="oracle", compat="java")
    assert svc.run(max_messages=3) == 3
    got = list(consume_lines(broker, follow=False))
    want = _oracle_lines([
        __import__("kme_tpu.wire", fromlist=["parse_order"]).parse_order(
            '{"action":100,"aid":1}'),
        __import__("kme_tpu.wire", fromlist=["parse_order"]).parse_order(
            '{"action":101,"aid":1,"size":5}'),
    ], "java")
    assert got == want

    broker2 = InProcessBroker()
    provision(broker2)
    broker2.produce(TOPIC_IN, None, "not json")
    strict = MatchService(broker2, engine="oracle", compat="java",
                          strict=True)
    with pytest.raises(ValueError):
        strict.step(timeout=0.0)


def test_bridge_tcp_process_boundary(tmp_path):
    """The real four-process topology over TCP: kme-serve hosts the
    broker+engine; provision, loadgen and consume run as separate OS
    processes (the reference README run order). Consumer output is byte-
    identical to the oracle replica."""
    env = None
    serve = subprocess.Popen(
        [sys.executable, "-m", "kme_tpu.cli", "serve",
         "--listen", "127.0.0.1:0", "--engine", "oracle",
         "--compat", "java", "--auto-provision", "--idle-exit", "30"],
        stderr=subprocess.PIPE, text=True, env=env)
    try:
        line = serve.stderr.readline()
        assert "listening on" in line, line
        addr = line.rsplit(" ", 1)[-1].strip()

        prov = subprocess.run(
            [sys.executable, "-m", "kme_tpu.cli", "provision",
             "--broker", addr],
            capture_output=True, text=True, timeout=60)
        assert prov.returncode == 0, prov.stderr
        assert "MatchIn: exists" in prov.stdout  # auto-provisioned already

        load = subprocess.run(
            [sys.executable, "-m", "kme_tpu.cli", "loadgen",
             "--events", "120", "--seed", "3", "--broker", addr],
            capture_output=True, text=True, timeout=60)
        assert load.returncode == 0, load.stderr

        msgs = harness_stream(120, seed=3)
        want = _oracle_lines(msgs, "java")

        deadline = time.monotonic() + 60
        got = []
        while time.monotonic() < deadline and len(got) < len(want):
            cons = subprocess.run(
                [sys.executable, "-m", "kme_tpu.cli", "consume",
                 "--broker", addr, "--no-follow"],
                capture_output=True, text=True, timeout=60)
            assert cons.returncode == 0, cons.stderr
            got = cons.stdout.splitlines()
            if len(got) < len(want):
                time.sleep(0.3)
        assert got == want
    finally:
        serve.terminate()
        serve.wait(timeout=10)
