"""The native host path (plan/recon in C++) and the double-buffered
service pipeline.

What must hold (ISSUE r06 acceptance):

- kme_plan_batch packs the exact (cols, host_rejects, stacked, cnts, K)
  the Python route+pack produces — plane for plane;
- a pipelined MatchService (--pipeline N) emits a byte-identical
  MatchOut stream to serial serving, with every durability contract
  intact (checkpoints land at the same offsets, crash-resume replays
  the same tail);
- the serve loop publishes the host-path attribution gauges
  (plan_s / recon_s / host_path_s, pipeline_depth when pipelined);
- the in-process pipelined bench hides the collect wall under device
  execution (measured_overlap_frac >= 0.8 on a reduced workload).
"""

import numpy as np
import pytest

from kme_tpu.bridge.broker import InProcessBroker
from kme_tpu.bridge.consume import consume_lines
from kme_tpu.bridge.provision import provision
from kme_tpu.bridge.service import TOPIC_IN, MatchService
from kme_tpu.engine import seq as SQ
from kme_tpu.native import load_library
from kme_tpu.wire import WireBatch, dumps_order
from kme_tpu.workload import harness_stream

needs_native = pytest.mark.skipif(
    load_library() is None,
    reason="native host runtime unavailable (KME_NATIVE=0 or no "
           "toolchain); pipelined serving gates on it")


def _pump(broker, msgs):
    for m in msgs:
        broker.produce(TOPIC_IN, None, dumps_order(m))


_SEQ_KW = dict(engine="seq", compat="fixed", batch=128, symbols=8,
               accounts=128, slots=128, max_fills=32)


@needs_native
def test_plan_batch_parity_native_vs_python():
    """kme_plan_batch (one native call: envelope + route + pack) vs the
    numpy fallback pack over the same router: identical columnar rows,
    reject set, stacked scan planes, chunk counts."""
    from kme_tpu.runtime.seqsession import SeqSession

    cfg = SQ.SeqConfig(lanes=8, slots=128, accounts=128, max_fills=32,
                       batch=128, pos_cap=1 << 11, fill_cap=1 << 12,
                       probe_max=16)
    ses_a, ses_b = SeqSession(cfg), SeqSession(cfg)
    msgs = harness_stream(300, seed=9, num_symbols=4, num_accounts=8,
                          payout_opcode_bug=False, validate=True)
    for lo in range(0, 256, 128):
        wb = WireBatch.from_msgs(msgs[lo:lo + 128])
        cols_a, rej_a, stk_a, cnts_a, K_a = ses_a._plan(wb)
        # a plain list skips the isinstance(WireBatch) fast path, so
        # ses_b routes + packs in Python over the same messages
        cols_b, rej_b, stk_b, cnts_b, K_b = ses_b._plan(list(wb.msgs()))
        assert (K_a, cnts_a, rej_a) == (K_b, cnts_b, rej_b)
        assert set(cols_a) == set(cols_b)
        for f in cols_a:
            assert np.array_equal(cols_a[f], cols_b[f]), f"cols[{f!r}]"
        assert set(stk_a) == set(stk_b)
        for f in stk_a:
            assert np.array_equal(np.asarray(stk_a[f]),
                                  np.asarray(stk_b[f])), f"stacked[{f!r}]"


@needs_native
def test_pipelined_service_byte_parity_and_gauges():
    """Serial (--pipeline 0) vs double-buffered (--pipeline 2) serving
    over the same stream: byte-identical MatchOut, and the pipelined
    loop publishes the host-path attribution gauges."""
    msgs = harness_stream(600, seed=3)
    outs = []
    for pipeline in (0, 2):
        broker = InProcessBroker()
        provision(broker)
        _pump(broker, msgs)
        svc = MatchService(broker, pipeline=pipeline, **_SEQ_KW)
        assert svc.run(max_messages=len(msgs)) == len(msgs)
        if pipeline:
            g = svc.telemetry.snapshot()["gauges"]
            for name in ("plan_s", "recon_s", "host_path_s",
                         "pipeline_depth"):
                assert name in g, name
            # host_path_s is round(plan+recon, 6) while the addends are
            # rounded separately — the two roundings can disagree by up
            # to 1.5e-6, so the tolerance must sit above that
            assert g["host_path_s"] == pytest.approx(
                g["plan_s"] + g["recon_s"], abs=2e-6)
            assert g["pipeline_depth"] == 0  # drained at run() exit
        svc.close()
        outs.append(list(consume_lines(broker, follow=False)))
    assert outs[0] == outs[1]
    assert len(outs[0]) > 0


@needs_native
def test_pipelined_checkpoint_crash_resume(tmp_path):
    """Crash-resume with batches in flight: checkpoints must land at
    the same offsets as serial serving (offsets only advance at collect,
    and the cadence pre-drains the pipe), so a crash past the last
    snapshot replays the identical at-least-once tail."""
    msgs = harness_stream(600, seed=3)  # 623 messages
    outs = []
    for pipeline in (0, 2):
        broker = InProcessBroker()
        provision(broker)
        _pump(broker, msgs)
        ck = str(tmp_path / f"ck{pipeline}")
        kw = dict(checkpoint_dir=ck, checkpoint_every=300,
                  pipeline=pipeline, **_SEQ_KW)
        svc = MatchService(broker, **kw)
        # batches of 128: snapshot fires at offset 384; crash at 512
        assert svc.run(max_messages=512) == 512
        assert svc._last_ckpt_offset == 384
        assert svc.offset == 512
        del svc  # crash: 128 records past the snapshot
        svc2 = MatchService(broker, **kw)
        assert svc2.offset == 384  # resumed from the snapshot
        rest = len(msgs) - 384
        assert svc2.run(max_messages=rest) == rest
        svc2.close()
        outs.append(list(consume_lines(broker, follow=False)))
    # serial crash-resume is the established-correct reference
    # (test_checkpoint.py); pipelined must replay the exact same tail
    assert outs[0] == outs[1]


def test_host_gauges_published_on_serial_path():
    """plan_s/recon_s/host_path_s come from the session's phase timer,
    so the serial seq path (and the KME_NATIVE=0 fallback) publishes
    them too — the attribution surface does not gate on the pipeline."""
    msgs = harness_stream(300, seed=5)
    broker = InProcessBroker()
    provision(broker)
    _pump(broker, msgs)
    svc = MatchService(broker, **_SEQ_KW)
    assert svc.run(max_messages=len(msgs)) == len(msgs)
    g = svc.telemetry.snapshot()["gauges"]
    svc.close()
    for name in ("plan_s", "recon_s", "host_path_s"):
        assert name in g and g[name] >= 0.0, name
    assert "pipeline_depth" not in g  # serial run: no pipeline surface


@needs_native
@pytest.mark.slow
def test_bench_pipeline_overlap_floor():
    """Reduced in-process pipelined bench: the collect wall hides
    under device execution (overlap fraction >= 0.8) and the pipelined
    output stream stays byte-identical to serial (asserted inside
    bench_pipeline)."""
    from kme_tpu.benchmarks import bench_pipeline

    rec = bench_pipeline(events=4096, symbols=8, accounts=128, seed=0,
                         batch=512, depth=2)
    d = rec["detail"]
    assert d["parity"] == "pipelined byte stream == serial byte stream"
    assert d["measured_overlap_frac"] >= 0.8
    assert d["local_s"] > 0.0
    for k in ("parse_s", "plan_s", "dispatch_s", "fetch_s", "recon_s"):
        assert k in d and d[k] >= 0.0
    # the stream front-loads account seeding, so >= events/batch chunks
    assert len(d["per_batch"]) >= 4096 // 512
