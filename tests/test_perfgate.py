"""Perf-regression gate: metric extraction from recorded (truncated)
benchmark artifacts, direction-aware comparison, and the kme-bench
--gate exit-code contract CI depends on."""

import json
import os

import pytest

from kme_tpu import perfgate
from kme_tpu.benchmarks import main as bench_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "BENCH_r05.json")

# a driver-format artifact whose tail starts MID-OBJECT, the way the
# recorded BENCH_r0N.json files are truncated; the java sub-dict
# repeats metric names and must NOT shadow the root values
_TAIL = (
    '_ms": 1.23, "local_orders_per_sec": 100000.0, '
    '"engine_side_p50_ms": 2.0, "engine_side_p99_ms": 4.0, '
    '"device_ms_per_batch": 5.0, "backend": "cpu", '
    '"pipeline_speedup": 1.4, '
    '"java": {"local_orders_per_sec": 5000.0, "engine_side_p99_ms": 99.0}'
)


def _artifact(tmp_path, name="base.json", tail=_TAIL):
    p = str(tmp_path / name)
    with open(p, "w") as f:
        json.dump({"n": 5, "cmd": ["kme-bench"], "rc": 0,
                   "tail": tail, "parsed": None}, f)
    return p


def test_extract_metrics_truncated_first_wins():
    m = perfgate.extract_metrics(_TAIL)
    assert m["local_orders_per_sec"] == 100000.0     # root, not java's
    assert m["engine_side_p99_ms"] == 4.0
    assert perfgate.extract_backend(_TAIL) == "cpu"
    # scientific notation and negatives parse
    m2 = perfgate.extract_metrics('"p99_ms": 1.5e-2, "x": -3')
    assert m2["p99_ms"] == pytest.approx(0.015) and m2["x"] == -3


def test_load_artifact_shapes(tmp_path):
    art = perfgate.load_artifact(_artifact(tmp_path))
    assert art["source"] == "driver-tail"
    assert art["metrics"]["device_ms_per_batch"] == 5.0
    # plain detail JSON and raw text both load
    pj = str(tmp_path / "detail.json")
    with open(pj, "w") as f:
        json.dump({"p99_ms": 3.0, "backend": "tpu"}, f)
    art2 = perfgate.load_artifact(pj)
    assert art2["source"] == "json" and art2["backend"] == "tpu"
    pt = str(tmp_path / "raw.txt")
    with open(pt, "w") as f:
        f.write('garbage then "p50_ms": 7 more garbage')
    assert perfgate.load_artifact(pt)["metrics"]["p50_ms"] == 7.0


def test_compare_direction_aware():
    base = {"metrics": {"local_orders_per_sec": 100.0, "p99_ms": 10.0},
            "backend": "cpu"}
    # throughput UP and latency DOWN are both improvements
    good = {"metrics": {"local_orders_per_sec": 150.0, "p99_ms": 5.0},
            "backend": "cpu"}
    rep = perfgate.compare(base, good, tolerance=0.25)
    assert rep["ok"] and rep["regressions"] == []
    # throughput falling 2x regresses; latency rising 2x regresses
    bad = {"metrics": {"local_orders_per_sec": 50.0, "p99_ms": 20.0},
           "backend": "cpu"}
    rep = perfgate.compare(base, bad, tolerance=0.25)
    assert not rep["ok"]
    assert set(rep["regressions"]) == {"local_orders_per_sec", "p99_ms"}
    # inside tolerance is clean
    meh = {"metrics": {"local_orders_per_sec": 90.0, "p99_ms": 11.0},
           "backend": "cpu"}
    assert perfgate.compare(base, meh, tolerance=0.25)["ok"]


def test_compare_backend_mismatch_is_advisory():
    base = {"metrics": {"p99_ms": 10.0}, "backend": "tpu"}
    bad = {"metrics": {"p99_ms": 100.0}, "backend": "cpu"}
    rep = perfgate.compare(base, bad)
    assert rep["backend_mismatch"] and rep["advisory"]
    assert rep["regressions"] == ["p99_ms"]   # reported...
    assert rep["ok"]                          # ...but not enforced
    assert "ADVISORY" in perfgate.format_report(rep)


def test_compare_advisory_metrics_never_regress():
    base = {"metrics": {"pipeline_speedup": 2.0, "p99_ms": 1.0},
            "backend": "cpu"}
    cur = {"metrics": {"pipeline_speedup": 0.5, "p99_ms": 1.0},
           "backend": "cpu"}
    rep = perfgate.compare(base, cur)
    assert rep["ok"] and rep["regressions"] == []
    row = [r for r in rep["metrics"] if r["name"] == "pipeline_speedup"]
    assert row and row[0]["status"] == "advisory"


def test_checked_in_baseline_is_usable():
    """BENCH_r05.json (the artifact CI gates against) must keep
    yielding gated metrics through the truncated-tail loader."""
    art = perfgate.load_artifact(BASELINE)
    assert art["source"] == "driver-tail"
    gated = set(art["metrics"]) & set(perfgate.GATED_METRICS)
    assert gated, "no gated metrics extracted from BENCH_r05.json"
    assert art["backend"] == "tpu"


def test_gate_cli_exit_codes(tmp_path, capsys):
    base = _artifact(tmp_path, "base.json")
    # self-compare: clean, exit 0
    rc = bench_main(["--baseline", base, "--gate",
                     "--gate-current", base])
    assert rc == 0
    assert "gate clean" in capsys.readouterr().err
    # doctored 2x slowdown: exit 1 with the regression named
    slow = _artifact(tmp_path, "slow.json", tail=_TAIL
                     .replace('"local_orders_per_sec": 100000.0',
                              '"local_orders_per_sec": 50000.0')
                     .replace('"engine_side_p99_ms": 4.0',
                              '"engine_side_p99_ms": 8.0'))
    report = str(tmp_path / "report.json")
    rc = bench_main(["--baseline", base, "--gate", "--gate-current",
                     slow, "--tolerance", "0.25",
                     "--gate-report", report])
    assert rc == 1
    err = capsys.readouterr().err
    assert "REGRESSION" in err and "engine_side_p99_ms" in err
    rep = json.loads(open(report).read())
    assert "local_orders_per_sec" in rep["regressions"]
    # backend mismatch (cpu current vs tpu-flagged baseline): advisory 0
    tpu_base = _artifact(tmp_path, "tpu.json",
                         tail=_TAIL.replace('"backend": "cpu"',
                                            '"backend": "tpu"'))
    rc = bench_main(["--baseline", tpu_base, "--gate",
                     "--gate-current", slow])
    assert rc == 0
    assert "ADVISORY" in capsys.readouterr().err


def test_gate_cli_unusable_baseline_exits_2(tmp_path, capsys):
    empty = str(tmp_path / "empty.json")
    with open(empty, "w") as f:
        f.write("no numbers here")
    base = _artifact(tmp_path)
    # metric-less BASELINE → 2
    rc = bench_main(["--baseline", empty, "--gate",
                     "--gate-current", base])
    assert rc == 2
    # metric-less CURRENT → 2 as well
    rc = bench_main(["--baseline", base, "--gate",
                     "--gate-current", empty])
    assert rc == 2
    capsys.readouterr()


def test_gate_requires_baseline():
    with pytest.raises(SystemExit):
        bench_main(["--gate"])


def test_publish_pipeline_gauges():
    from kme_tpu.benchmarks import publish_pipeline_gauges
    from kme_tpu.telemetry import Registry

    reg = Registry()
    publish_pipeline_gauges(reg, {
        "pipeline_speedup": 0.8, "device_ms_per_batch": 3.5,
        "measured_overlap_frac": 0.4, "pipeline_warning": "slow"})
    g = reg.snapshot()["gauges"]
    assert g["pipeline_speedup"] == 0.8
    assert g["device_ms_per_batch"] == 3.5
    assert g["measured_overlap_frac"] == 0.4
    assert g["pipeline_warning"] == 1
    publish_pipeline_gauges(reg, {"pipeline_speedup": 1.6})
    assert reg.snapshot()["gauges"]["pipeline_warning"] == 0
