"""Continuous invariant auditor (kme_tpu/telemetry/audit.py): the
shadow ledger stays clean on real streams, trips on injected
corruption, cross-checks the live engine at checkpoint cadence, and
its repro dumps reproduce offline."""

import json

import pytest

from kme_tpu.bridge.broker import InProcessBroker
from kme_tpu.bridge.provision import provision
from kme_tpu.bridge.service import TOPIC_IN, MatchService
from kme_tpu.telemetry import Registry
from kme_tpu.telemetry.audit import (InvariantAuditor, load_repro,
                                     replay_repro)
from kme_tpu.telemetry.journal import batch_events, oracle_events
from kme_tpu.oracle import OracleEngine
from kme_tpu.wire import dumps_order, parse_order
from kme_tpu.workload import harness_stream


def _event_batches(n=600, seed=21, chunk=60, book_slots=None,
                   max_fills=None):
    """Message-aligned event batches from an oracle replay — what the
    journal's observer fan-out delivers per committed batch."""
    msgs = harness_stream(n, seed=seed, num_accounts=8, num_symbols=3,
                          payout_opcode_bug=False, validate=True)
    lines = [dumps_order(m) for m in msgs]
    evs = oracle_events(lines, book_slots=book_slots,
                        max_fills=max_fills)
    # chunk message-aligned (by input offset): the auditor finalizes
    # its pending taker at batch end, so a message must not straddle
    # two observe() calls — exactly the guarantee record_batch gives
    out = []
    for lo in range(0, len(lines), chunk):
        out.append([dict(ev, b=lo // chunk) for ev in evs
                    if lo <= ev.get("off", -1) < lo + chunk])
    return lines, out


def test_clean_stream_no_violations():
    reg = Registry()
    aud = InvariantAuditor(registry=reg)
    _, batches = _event_batches()
    for evs in batches:
        aud.observe(evs)
    assert aud.violations == []
    assert reg.counter("audit_violations").value == 0
    assert reg.counter("audit_batches").value == len(batches)
    # the shadow actually accumulated state (not vacuously clean)
    assert aud.balances and aud.batches == len(batches)


def test_payout_stream_stays_clean():
    # settlement wipes books + mints external credit; the escrow
    # invariant must survive it (payouts count as inflow)
    from kme_tpu.workload import zipf_symbol_stream

    msgs = zipf_symbol_stream(900, num_symbols=4, num_accounts=8,
                              seed=4, payout_per_mille=30)
    evs = oracle_events([dumps_order(m) for m in msgs])
    assert any(e["e"] in ("payout", "remove_symbol") for e in evs)
    aud = InvariantAuditor()
    aud.observe(evs)
    assert aud.violations == []


def test_tampered_fill_qty_detected(tmp_path):
    reg = Registry()
    hits = []
    aud = InvariantAuditor(registry=reg, repro_dir=str(tmp_path),
                           on_violation=lambda v, d: hits.append((v, d)))
    _, batches = _event_batches()
    # bump the first fill's quantity in the first batch that has one
    done = False
    for evs in batches:
        if not done:
            for ev in evs:
                if ev["e"] == "fill":
                    ev["qty"] += 1
                    done = True
                    break
        aud.observe(evs)
    assert done and aud.violations
    kinds = {v["kind"] for v in aud.violations}
    assert kinds & {"fill_overfill", "rest_mismatch",
                    "unfilled_residual", "state_mismatch",
                    "position_sum", "escrow_negative",
                    "fill_no_taker"}
    assert reg.counter("audit_violations").value == len(aud.violations)
    assert hits and hits[0][1] is not None       # repro dump written


def test_tampered_balance_conjuring_detected():
    # a transfer event whose qty was inflated after the fact breaks
    # the escrow bound: balances exceed external inflow
    _, batches = _event_batches(300)
    aud = InvariantAuditor()
    tampered = False
    for evs in batches:
        for ev in evs:
            if not tampered and ev["e"] == "fill":
                ev["px"] += 1            # maker paid a different price
                tampered = True
        aud.observe(evs)
    assert tampered
    assert aud.violations


def test_repro_dump_replays_offline(tmp_path):
    aud = InvariantAuditor(repro_dir=str(tmp_path))
    _, batches = _event_batches(500)
    done = False
    for evs in batches:
        if not done:
            for ev in evs:
                if ev["e"] == "fill":
                    ev["qty"] += 2
                    done = True
                    break
        aud.observe(evs)
    assert aud.dumps, "violation must write a repro dump"
    doc = load_repro(aud.dumps[0])
    assert doc["violations"] and doc["events"] and "pre_state" in doc
    # the dump is self-contained: a fresh auditor seeded from its
    # pre-batch state re-finds the violation
    found = replay_repro(aud.dumps[0])
    assert found
    assert ({v["kind"] for v in doc["violations"]}
            <= {v["kind"] for v in found} | {v["kind"] for v in found})


def test_check_engine_against_seq_session():
    from kme_tpu.engine import seq as SQ
    from kme_tpu.runtime.seqsession import SeqSession

    msgs = harness_stream(300, seed=9, num_accounts=8, num_symbols=3,
                          payout_opcode_bug=False, validate=True)
    ses = SeqSession(SQ.SeqConfig(lanes=8, slots=128, accounts=128,
                                  max_fills=16))
    aud = InvariantAuditor()
    for lo in range(0, len(msgs), 100):
        part = [m.copy() for m in msgs[lo:lo + 100]]
        records = ses.process_wire(part)
        evs = batch_events(records, reasons=ses.last_reasons,
                           offsets=list(range(lo, lo + len(part))))
        aud.observe(evs)
    assert aud.violations == []
    # deep cross-check vs the engine's exported stores + histograms
    assert aud.check_engine(ses.export_state(), ses.histograms()) == []
    # corrupt one shadow balance: check_engine must notice
    aid = next(iter(aud.balances))
    aud.balances[aid] += 1
    found = aud.check_engine(ses.export_state())
    assert found and found[0]["kind"] == "state_mismatch"


def test_service_audit_end_to_end_tamper(tmp_path, monkeypatch):
    """The ISSUE's acceptance path: a serving MatchService with --audit
    detects an injected conservation violation (KME_AUDIT_TAMPER test
    hook), increments audit_violations, marks the heartbeat degraded,
    and writes a repro dump that reproduces offline."""
    monkeypatch.setenv("KME_AUDIT_TAMPER", "fill_qty")
    msgs = harness_stream(400, seed=13, num_accounts=8, num_symbols=3,
                          payout_opcode_bug=False, validate=True)
    broker = InProcessBroker()
    provision(broker)
    for m in msgs:
        broker.produce(TOPIC_IN, None, dumps_order(m))
    jp = str(tmp_path / "journal.jsonl")
    rd = str(tmp_path / "repro")
    svc = MatchService(broker, engine="oracle", compat="fixed",
                       batch=80, journal=jp, audit=True,
                       audit_repro_dir=rd)
    assert svc.run(max_messages=len(msgs)) == len(msgs)
    svc.close()
    assert svc.auditor is not None and svc.auditor.violations
    assert svc.degraded is not None
    assert svc.telemetry.counter("audit_violations").value > 0
    hb = tmp_path / "hb.json"
    svc._write_heartbeat(str(hb), seen=len(msgs), tick=1)
    doc = json.loads(hb.read_text())
    assert doc["degraded"] == svc.degraded
    assert doc["metrics"]["counters"]["audit_violations"] > 0
    assert svc.auditor.dumps
    assert replay_repro(svc.auditor.dumps[0])


def test_service_audit_clean_run_and_annotations(tmp_path):
    """No tamper: a full service run over the harness stream audits
    clean, and --annotate-rejects adds ADDITIVE REJ records without
    touching the reference IN/OUT byte stream."""
    from kme_tpu.bridge.consume import consume_lines

    msgs = harness_stream(400, seed=2, num_accounts=8, num_symbols=3,
                          payout_opcode_bug=False, validate=True)
    per_msg = []
    ora = OracleEngine("fixed")
    for m in msgs:
        per_msg.append([r.wire() for r in ora.process(m.copy())])
    broker = InProcessBroker()
    provision(broker)
    for m in msgs:
        broker.produce(TOPIC_IN, None, dumps_order(m))
    jp = str(tmp_path / "journal.bin")
    svc = MatchService(broker, engine="oracle", compat="fixed",
                       batch=100, journal=jp, audit=True,
                       annotate_rejects=True)
    assert svc.run(max_messages=len(msgs)) == len(msgs)
    svc.close()
    assert svc.auditor.violations == []
    assert svc.degraded is None
    got = list(consume_lines(broker, follow=False))
    rej = [ln for ln in got if ln.startswith("REJ ")]
    rest = [ln for ln in got if not ln.startswith("REJ ")]
    assert rest == [ln for lines in per_msg for ln in lines]
    n_rejects = sum(1 for lines in per_msg
                    if '"action":7,' in lines[-1])
    assert len(rej) == n_rejects > 0
    for ln in rej:
        rec = json.loads(ln.partition(" ")[2])
        assert set(rec) == {"oid", "aid", "reason", "rej"}
        assert rec["rej"].startswith("rej_")


def test_audit_requires_journal():
    broker = InProcessBroker()
    provision(broker)
    with pytest.raises(ValueError, match="journal"):
        MatchService(broker, engine="oracle", compat="fixed",
                     audit=True)
