"""Bench-path regression gate.

Both previous round-ending failures (r1: TPU compile of the lanes chunk,
r2: capacity poison + a benchmarks.py/session.py API drift) would have
been caught by running the REAL bench code path once at small scale.
This test does exactly that: bench_lane_engine end-to-end (plan, pack,
dispatch, fetch, reconstruct, in-bench oracle parity) on the CPU
backend, plus the capacity-envelope policy that replaced the sticky
overflow errors.
"""

import pytest

from kme_tpu.benchmarks import bench_lane_engine
from kme_tpu.engine.lanes import LaneConfig
from kme_tpu.oracle import OracleEngine
from kme_tpu.runtime.session import LaneSession
from kme_tpu.workload import zipf_symbol_stream


def test_bench_lane_engine_smoke(cpu_devices):
    """The exact function bench.py times, small shapes, real code path."""
    rec = bench_lane_engine(events=1200, symbols=16, accounts=64, seed=3,
                            zipf_a=1.2, steps=16, slots=32, max_fills=16,
                            shards=1, parity_prefix=400)
    assert rec["metric"] == "orders_per_sec_e2e"
    assert rec["value"] > 0
    d = rec["detail"]
    assert d["out_records"] >= d["events"] * 2  # IN + OUT per message
    assert d["total_s"] > 0
    # phase timings must cover the whole pipeline
    assert set(("plan_s", "dispatch_s", "fetch_s", "recon_s")) <= set(d)


def test_bench_cancel_workload_and_latency_suite_smoke(cpu_devices):
    """The other two bench entry points at small scale: the cancel-heavy
    lanes workload and the streaming-latency suite."""
    from kme_tpu.benchmarks import bench_latency

    rec = bench_lane_engine(events=600, symbols=8, accounts=32, seed=5,
                            steps=8, slots=32, max_fills=16,
                            parity_prefix=200, workload="cancel")
    assert rec["detail"]["workload"] == "cancel"
    assert rec["value"] > 0

    rec = bench_latency(events=600, symbols=8, accounts=32, seed=5,
                        slots=32, max_fills=16, batch=256)
    assert rec["metric"] == "p99_batch_latency_ms"
    assert rec["value"] > 0
    d = rec["detail"]
    assert d["batches"] == (600 + 2 * 32 + 8 + 255) // 256
    assert d["p50_ms"] <= d["p99_ms"] <= d["max_ms"]


def test_bench_native_suite_smoke():
    """The native quirk-exact bench entry point at small scale."""
    import pytest

    nat = pytest.importorskip("kme_tpu.native.oracle")
    if not nat.native_available():
        pytest.skip("native library unavailable")
    from kme_tpu.benchmarks import bench_native_engine

    rec = bench_native_engine(events=3000, batch=1000)
    assert rec["metric"] == "orders_per_sec_native_quirk_exact"
    assert rec["value"] > 0
    assert rec["detail"]["out_lines"] > 0
    with pytest.raises(ValueError, match="must exceed"):
        bench_native_engine(events=100, batch=1000)


def test_capacity_envelope_book_full_rejects_per_message(cpu_devices):
    """H2 policy: overflowing a book side rejects THAT message only —
    the batch continues and stays oracle-exact (no sticky poison)."""
    import kme_tpu.opcodes as op
    from kme_tpu.wire import OrderMsg

    slots = 4
    cfg = LaneConfig(lanes=2, slots=slots, accounts=8, max_fills=8, steps=8)
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=10_000_000),
            OrderMsg(action=op.CREATE_BALANCE, aid=2),
            OrderMsg(action=op.TRANSFER, aid=2, size=10_000_000),
            OrderMsg(action=op.ADD_SYMBOL, sid=0)]
    # 6 non-crossing buys on one side: slots 5 and 6 must reject
    for i in range(slots + 2):
        msgs.append(OrderMsg(action=op.BUY, oid=100 + i, aid=1, sid=0,
                             price=10 + i, size=5))
    # the book still works afterwards: a crossing sell fills the best buy
    msgs.append(OrderMsg(action=op.SELL, oid=200, aid=2, sid=0,
                         price=10, size=5))

    ora = OracleEngine("fixed", book_slots=slots, max_fills=8)
    want = [[r.wire() for r in ora.process(m.copy())] for m in msgs]
    ses = LaneSession(cfg)
    got = [[r.wire() for r in recs] for recs in ses.process(msgs)]
    assert got == want
    # the overflowing buys were rejected, and only those
    flat = [ln for recs in got for ln in recs]
    rejects = [ln for ln in flat if ln.startswith('OUT {"action":7')]
    assert len(rejects) == 2
    # the final sell produced fills (stream survived the overflow)
    assert any(ln.startswith('OUT {"action":5') for ln in flat)


def test_capacity_envelope_max_fills_rejects_per_message(cpu_devices):
    """H3 policy: a taker that would sweep more than max_fills makers is
    rejected as a unit; makers stay untouched."""
    import kme_tpu.opcodes as op
    from kme_tpu.wire import OrderMsg

    E = 2
    cfg = LaneConfig(lanes=2, slots=16, accounts=8, max_fills=E, steps=8)
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=10_000_000),
            OrderMsg(action=op.CREATE_BALANCE, aid=2),
            OrderMsg(action=op.TRANSFER, aid=2, size=10_000_000),
            OrderMsg(action=op.ADD_SYMBOL, sid=0)]
    for i in range(E + 1):  # 3 resting sells at one level
        msgs.append(OrderMsg(action=op.SELL, oid=100 + i, aid=1, sid=0,
                             price=50, size=1))
    # sweeping all 3 exceeds max_fills=2 -> reject
    msgs.append(OrderMsg(action=op.BUY, oid=200, aid=2, sid=0,
                         price=50, size=3))
    # sweeping 2 is inside the envelope -> fills
    msgs.append(OrderMsg(action=op.BUY, oid=201, aid=2, sid=0,
                         price=50, size=2))

    ora = OracleEngine("fixed", book_slots=16, max_fills=E)
    want = [[r.wire() for r in ora.process(m.copy())] for m in msgs]
    ses = LaneSession(cfg)
    got = [[r.wire() for r in recs] for recs in ses.process(msgs)]
    assert got == want
    flat = [ln for recs in got for ln in recs]
    assert sum(1 for ln in flat if ln.startswith('OUT {"action":7')) == 1
    assert sum(1 for ln in flat if ln.startswith('OUT {"action":6')) == 2


def test_capacity_envelope_zipf_stream_parity(cpu_devices):
    """A skewed stream that actually overflows small books stays
    byte-exact vs the enveloped oracle (the BENCH_r02 failure class)."""
    slots = 8
    msgs = zipf_symbol_stream(800, num_symbols=4, num_accounts=16, seed=7,
                              zipf_a=1.5)
    cfg = LaneConfig(lanes=4, slots=slots, accounts=32, max_fills=16,
                     steps=16)
    ora = OracleEngine("fixed", book_slots=slots, max_fills=16)
    want = [[r.wire() for r in ora.process(m.copy())] for m in msgs]
    ses = LaneSession(cfg)
    got = [[r.wire() for r in recs] for recs in ses.process(msgs)]
    assert got == want
    flat = [ln for recs in got for ln in recs]
    # the point of the scenario: overflow actually happened
    assert any(ln.startswith('OUT {"action":7') for ln in flat)


@pytest.mark.slow
def test_bench_seq_engine_smoke(cpu_devices, monkeypatch):
    """The r5 seq bench path at small scale: bytes-in parse, device-path
    measurement, FULL-stream parity vs the judge, local_orders_per_sec,
    and the java sub-run fields."""
    monkeypatch.setenv("KME_BENCH_DEV_REPS", "1")
    from kme_tpu.benchmarks import bench_seq_engine

    rec = bench_seq_engine(events=1200, symbols=16, accounts=128, seed=3,
                           zipf_a=1.2, slots=128, max_fills=16, batch=512,
                           with_java=False)
    d = rec["detail"]
    assert rec["metric"] == "orders_per_sec_e2e"
    assert d["parity_checked_msgs"] == d["events"]
    assert d["device_path_s"] > 0
    assert d["local_orders_per_sec"] > 0
    assert set(("parse_s", "plan_s", "dispatch_s", "fetch_s",
                "recon_s")) <= set(d)


@pytest.mark.slow
def test_bench_seq_java_smoke(cpu_devices, monkeypatch):
    """Java-mode seq bench: full-stream parity vs the java judge on the
    stock harness shape (VMEM-resident deep books at 8 lanes)."""
    monkeypatch.setenv("KME_BENCH_DEV_REPS", "1")
    from kme_tpu.benchmarks import bench_seq_engine

    rec = bench_seq_engine(events=600, seed=1, batch=512, compat="java",
                           with_java=False)
    d = rec["detail"]
    assert rec["metric"] == "orders_per_sec_java_exact_tpu"
    assert d["parity_checked_msgs"] == d["events"]
    assert d["cap_rejects"] == 0
