"""End-to-end latency attribution: LatencyHistogram semantics, per-stage
quantiles on the live /metrics + heartbeat surfaces, per-order "lat"
journal stamps queryable through kme-trace, the broker-admission stamp
(ats) plumbing across the in-process and TCP transports, and the SLO
error-budget evaluator feeding the degraded heartbeat channel."""

import json
import threading
import urllib.request

import pytest

from kme_tpu.bridge.broker import InProcessBroker
from kme_tpu.bridge.provision import provision
from kme_tpu.bridge.service import TOPIC_IN, TOPIC_OUT, MatchService
from kme_tpu.telemetry import (LAT_BOUNDS, LatencyHistogram, Registry,
                               start_metrics_server)
from kme_tpu.telemetry.slo import SLO
from kme_tpu.wire import dumps_order
from kme_tpu.workload import harness_stream


# ---------------------------------------------------------------------------
# LatencyHistogram semantics


def test_latency_histogram_quantiles_bracket_observations():
    h = LatencyHistogram("lat")
    for _ in range(99):
        h.observe(0.001)            # 1 ms
    h.observe(0.5)                  # one 500 ms straggler
    assert h.count == 100
    assert h.sum == pytest.approx(99 * 0.001 + 0.5)
    # log buckets: quantiles are estimates, but must land in the right
    # bucket's range (p50 near 1 ms, p999 near 500 ms)
    assert 0.0005 <= h.quantile(0.5) <= 0.003
    assert 0.25 <= h.quantile(0.999) <= 1.1
    qs = h.quantiles()
    assert set(qs) == {0.5, 0.9, 0.99, 0.999}
    assert qs[0.5] <= qs[0.9] <= qs[0.99] <= qs[0.999]


def test_latency_histogram_weighted_observe_and_count_over():
    h = LatencyHistogram("lat")
    h.observe(0.010, n=50)          # a 10 ms batch of 50 orders
    h.observe(0.100, n=10)
    assert h.count == 60
    # count_over is bucket-conservative: everything in buckets wholly
    # above the threshold
    assert h.count_over(0.050) == 10
    assert h.count_over(10.0) == 0
    # empty histogram: quantiles are 0, not NaN
    assert LatencyHistogram("x").quantile(0.99) == 0.0


def test_latency_histogram_overflow_bucket():
    h = LatencyHistogram("lat")
    h.observe(10 * LAT_BOUNDS[-1])   # beyond the last boundary
    assert h.count == 1
    assert h.quantile(0.5) >= LAT_BOUNDS[-1]


def test_latency_prometheus_summary_and_snapshot():
    reg = Registry()
    h = reg.latency("lat_e2e", help="end to end")
    h.observe(0.002, 10)
    text = reg.prometheus_text()
    assert "# TYPE lat_e2e summary" in text
    assert 'lat_e2e{quantile="0.99"}' in text
    assert "lat_e2e_count 10" in text
    snap = reg.snapshot()
    assert snap["latencies"]["lat_e2e"]["count"] == 10
    assert snap["latencies"]["lat_e2e"]["p50_ms"] > 0
    # same name re-registration returns the same instance; kind clash
    # is loud
    assert reg.latency("lat_e2e") is h
    with pytest.raises(TypeError):
        reg.histogram("lat_e2e")


# ---------------------------------------------------------------------------
# broker-admission stamps (ats)


def test_inprocess_broker_stamps_and_observer():
    br = InProcessBroker()
    br.create_topic("t", 1)
    br.produce("t", "k", "v")
    seen = []
    br.deliver_observer = lambda topic, recs, now_us: seen.append(
        (topic, [r.offset for r in recs], now_us))
    recs = br.fetch("t", 0, 10)
    assert recs[0].ats is not None          # admission stamp, wall µs
    assert seen and seen[0][0] == "t" and seen[0][1] == [0]
    assert seen[0][2] >= recs[0].ats


def test_tcp_round_trip_carries_ats():
    from kme_tpu.bridge.tcp import TcpBroker, serve_broker

    srv, br = serve_broker("127.0.0.1", 0, InProcessBroker())
    host, port = srv.server_address[:2]
    client = TcpBroker(host, port)
    try:
        client.create_topic("t", 1)
        client.produce("t", "k", "v")
        client.produce("t", "k2", "v2", epoch=1, out_seq=0)
        recs = client.fetch("t", 0, 10)
        assert [r.value for r in recs] == ["v", "v2"]
        assert all(r.ats is not None for r in recs)
        assert recs[1].epoch == 1 and recs[1].out_seq == 0
    finally:
        client.close()
        srv.shutdown()


def test_broker_reload_leaves_ats_none(tmp_path):
    d = str(tmp_path / "log")
    br = InProcessBroker(persist_dir=d)
    br.create_topic("t", 1)
    br.produce("t", "k", "v")
    br2 = InProcessBroker(persist_dir=d)   # reload: rows have no ats
    assert br2.fetch("t", 0, 10)[0].ats is None


def test_consume_lines_observes_receipt_latency():
    from kme_tpu.bridge.consume import consume_lines

    br = InProcessBroker()
    provision(br)
    br.produce(TOPIC_OUT, "OUT", '{"x":1}')
    h = LatencyHistogram("receipt")
    lines = list(consume_lines(br, follow=False, latency=h))
    assert lines == ['OUT {"x":1}']
    assert h.count == 1
    assert h.sum >= 0


# ---------------------------------------------------------------------------
# the serving pipeline end to end


def _serve_stream(n=300, **kw):
    br = InProcessBroker()
    provision(br)
    msgs = harness_stream(n, seed=3, num_accounts=6, num_symbols=2,
                          payout_opcode_bug=False, validate=True)
    for m in msgs:
        br.produce(TOPIC_IN, None, dumps_order(m))
    svc = MatchService(br, engine="oracle", compat="fixed", batch=64,
                       **kw)
    seen = 0
    while seen < len(msgs):
        seen += svc.step(timeout=0.1)
    return br, svc, msgs


def test_service_stage_quantiles_live(tmp_path):
    jp = str(tmp_path / "j.bin")
    br, svc, msgs = _serve_stream(journal=jp)
    svc.close()
    snap = svc.telemetry.snapshot()
    lats = snap["latencies"]
    # per-order stages observed for every consumed record
    for stage in ("ingress", "device", "produce", "e2e"):
        assert lats[f"lat_{stage}"]["count"] == len(msgs), stage
        assert lats[f"lat_{stage}"]["p99_ms"] > 0, stage
    # causality: e2e includes ingress wait, so its p50 dominates
    assert lats["lat_e2e"]["p50_ms"] >= lats["lat_ingress"]["p50_ms"]
    # journal writer gauges
    assert snap["gauges"]["journal_last_offset"] == len(msgs) - 1
    assert snap["gauges"]["journal_lag_bytes"] == 0
    assert snap["gauges"]["device_ms_per_batch"] >= 0

    # consume stage: a consumer fetch of MatchOut routes through the
    # broker's deliver observer (serve hosts the broker)
    out = br.fetch(TOPIC_OUT, 0, 100000)
    assert out
    assert svc.telemetry.latency("lat_consume").count == len(out)

    # the same quantiles ride the Prometheus surface
    text = svc.telemetry.prometheus_text()
    assert "# TYPE lat_e2e summary" in text
    assert 'lat_e2e{quantile="0.999"}' in text


def test_service_metrics_http_exposes_latency_stages(tmp_path):
    br, svc, msgs = _serve_stream()
    srv = start_metrics_server(svc.telemetry, 0, host="127.0.0.1")
    host, port = srv.server_address[:2]
    try:
        doc = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/metrics.json").read().decode())
        assert doc["latencies"]["lat_e2e"]["count"] == len(msgs)
        text = urllib.request.urlopen(
            f"http://{host}:{port}/metrics").read().decode()
        assert 'lat_ingress{quantile="0.5"}' in text
    finally:
        srv.shutdown()


def test_heartbeat_carries_latency_and_journal_gauges(tmp_path):
    hb = str(tmp_path / "hb.json")
    jp = str(tmp_path / "j.bin")
    br, svc, msgs = _serve_stream(journal=jp)
    svc._write_heartbeat(hb, len(msgs))
    svc.close()
    doc = json.loads(open(hb).read())
    assert doc["metrics"]["latencies"]["lat_e2e"]["count"] == len(msgs)
    assert doc["metrics"]["gauges"]["journal_last_offset"] == \
        len(msgs) - 1
    assert "journal_lag_bytes" in doc["metrics"]["gauges"]
    assert doc["degraded"] is None


def test_journal_lat_events_and_kme_trace_order(tmp_path, capsys):
    from kme_tpu.cli import trace_main
    from kme_tpu.telemetry.journal import read_events

    jp = str(tmp_path / "j.bin")
    br, svc, msgs = _serve_stream(journal=jp)
    svc.close()
    evs = read_events(jp)
    lats = [e for e in evs if e["e"] == "lat"]
    assert len(lats) == len(msgs)           # one stamp per order
    by_off = {e["off"]: e for e in lats}
    assert set(by_off) == set(range(len(msgs)))
    for e in lats:
        assert e["e2e_us"] >= e["in_us"] >= 0
        assert e["dev_us"] >= 0 and e["prod_us"] >= 0
    # binary framing survived the round trip with stable field mapping
    oid = lats[0]["oid"]
    rc = trace_main([jp, "--order", str(oid), "--json"])
    assert rc == 0
    out = capsys.readouterr().out
    picked = [json.loads(ln) for ln in out.splitlines()]
    assert any(e["e"] == "lat" and "e2e_us" in e for e in picked)
    # the pretty renderer shows the stage stamps too
    rc = trace_main([jp, "--order", str(oid)])
    assert rc == 0
    assert "e2e_us=" in capsys.readouterr().out


def test_journal_lat_events_do_not_break_verify(tmp_path):
    from kme_tpu.cli import trace_main

    jp = str(tmp_path / "j.jsonl")
    inp = str(tmp_path / "input.jsonl")
    br, svc, msgs = _serve_stream(journal=jp)
    svc.close()
    with open(inp, "w") as f:
        for m in msgs:
            f.write(dumps_order(m) + "\n")
    # lat records are dropped from the canonical form, so the oracle
    # replay still byte-agrees
    assert trace_main([jp, "--verify", inp]) == 0


# ---------------------------------------------------------------------------
# SLO evaluation


def test_slo_clean_then_degraded_then_recovers():
    reg = Registry()
    h = reg.latency("lat_e2e")
    clock = [0.0]
    s = SLO(reg, stage="e2e", p99_ms=50, budget=0.001, min_ops=10,
            window_s=1.0, clock=lambda: clock[0])
    assert s.evaluate() is None             # arms the window
    h.observe(0.001, 100)                   # all fast
    clock[0] = 2.0
    assert s.evaluate() is None
    assert reg.gauge("slo_ok").value == 1
    h.observe(0.5, 100)                     # all slow
    clock[0] = 4.0
    reason = s.evaluate()
    assert reason is not None and "burn" in reason
    assert reg.gauge("slo_ok").value == 0
    assert reg.gauge("slo_burn_rate").value > 1
    h.observe(0.001, 1000)                  # healthy again
    clock[0] = 6.0
    assert s.evaluate() is None
    assert reg.gauge("slo_ok").value == 1


def test_slo_quiet_service_is_not_degraded():
    reg = Registry()
    reg.latency("lat_e2e")
    clock = [0.0]
    s = SLO(reg, stage="e2e", p99_ms=1, min_ops=10, window_s=1.0,
            clock=lambda: clock[0])
    s.evaluate()
    clock[0] = 10.0
    assert s.evaluate() is None             # no traffic, no breach


def test_slo_throughput_floor():
    reg = Registry()
    reg.latency("lat_e2e")
    reg.counter("service_records").set(0)
    clock = [0.0]
    s = SLO(reg, stage="e2e", p99_ms=1e9, min_ops=1,
            min_records_per_s=100.0, window_s=1.0,
            clock=lambda: clock[0])
    s.evaluate()
    reg.counter("service_records").set(10)  # 10 records over 10 s
    clock[0] = 10.0
    reason = s.evaluate()
    assert reason is not None and "throughput" in reason


def test_slo_unknown_stage_is_loud():
    with pytest.raises(ValueError):
        SLO(Registry(), stage="warp")


def test_service_slo_marks_heartbeat_degraded(tmp_path):
    hb = str(tmp_path / "hb.json")
    # impossible SLO: every order is a bad event
    br, svc, msgs = _serve_stream(
        slo={"stage": "e2e", "p99_ms": 0.0001, "min_ops": 1,
             "window_s": 0.0})
    # the publish path is rate-limited to 1/s; force one evaluation
    svc._slo_reason = svc.slo.evaluate() or svc.slo.evaluate()
    assert svc._slo_reason is not None
    svc._write_heartbeat(hb, len(msgs))
    doc = json.loads(open(hb).read())
    assert doc["degraded"] and "slo" in doc["degraded"]
    # the auditor verdict, when present, wins over the SLO reason
    svc.degraded = "conservation"
    svc._write_heartbeat(hb, len(msgs))
    assert json.loads(open(hb).read())["degraded"] == "conservation"


# ---------------------------------------------------------------------------
# concurrent scrape while latency histograms update (satellite: atomic
# snapshots under writer load)


def test_concurrent_scrape_while_latency_histograms_update():
    reg = Registry()
    h = reg.latency("lat_e2e")
    reg.counter("service_records")
    srv = start_metrics_server(reg, 0, host="127.0.0.1")
    host, port = srv.server_address[:2]
    stop = threading.Event()
    errs, bodies = [], []

    def scrape():
        while not stop.is_set():
            try:
                bodies.append(urllib.request.urlopen(
                    f"http://{host}:{port}/metrics",
                    timeout=5).read().decode())
                doc = json.loads(urllib.request.urlopen(
                    f"http://{host}:{port}/metrics.json",
                    timeout=5).read().decode())
                lat = doc["latencies"].get("lat_e2e")
                if lat and lat["count"]:
                    # atomic view: a torn read would break monotonicity
                    assert lat["p50_ms"] <= lat["p99_ms"] * 1.0001
            except Exception as e:  # noqa: BLE001 - collected + asserted
                errs.append(e)
                return

    threads = [threading.Thread(target=scrape) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for i in range(20000):
            h.observe(0.0001 * (1 + (i % 64)), n=3)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        srv.shutdown()
    assert errs == []
    assert bodies
    for text in bodies:
        if "lat_e2e_count" in text:
            # every exposition carries the full summary family
            assert 'lat_e2e{quantile="0.5"}' in text
