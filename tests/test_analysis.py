"""kme-lint: per-rule fixtures (one violating + one clean per rule ID),
baseline semantics, the lock rules on synthetic modules, the runtime
lockcheck recorder, the ctypes-boundary validators, and a self-run
asserting `kme-lint --gate` is clean on this repo against the
checked-in baseline."""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from kme_tpu.analysis import (Finding, load_baseline, repo_root,
                              save_baseline, split_new)
from kme_tpu.analysis import lockcheck, lockgraph, rules

# ---------------------------------------------------------------------------
# rule fixtures: (rule id, path the scope tables key on, violating
# source, clean source). Each violating snippet must fire EXACTLY its
# rule; each clean one must produce no findings at all.

FIXTURES = [
    ("KME-H001", "kme_tpu/bridge/service.py", """
class MatchService:
    def _step_pipelined(self):
        out = self.dev_out.block_until_ready()
""", """
class MatchService:
    def _collect_one(self):
        out = self.dev_out.block_until_ready()
"""),
    ("KME-H001", "kme_tpu/runtime/seqsession.py", """
import numpy as np
class SeqSession:
    def submit(self, batch):
        host = np.asarray(self.dev_buf)
""", """
import numpy as np
class SeqSession:
    def collect(self):
        host = np.asarray(self.dev_buf)
"""),
    ("KME-H002", "kme_tpu/runtime/seqsession.py", """
class SeqSession:
    def _plan(self, msgs):
        self.journal_f.flush()
""", """
class SeqSession:
    def _fetch_outputs(self):
        self.journal_f.flush()
"""),
    ("KME-D001", "kme_tpu/bridge/broker.py", """
import time
class Broker:
    def _load_topic(self, name):
        stamp = time.time()
""", """
import time
class Broker:
    def _segment_stats(self):
        stamp = time.time()
"""),
    ("KME-C001", "kme_tpu/bridge/broker.py", """
import time
class Broker:
    def fetch(self, name, offset):
        t0 = time.monotonic()
""", """
import time
class Broker:
    def _segment_stats(self):
        t0 = time.monotonic()
"""),
    ("KME-D002", "kme_tpu/telemetry/journal.py", """
import random
def iter_events(path):
    jitter = random.random()
""", """
import random
def write_events(path):
    jitter = random.random()
"""),
    ("KME-E001", "kme_tpu/telemetry/events.py", """
import uuid
def make_event(source, seq, kind, ts_us):
    return {"src": source, "seq": seq, "kind": kind,
            "id": uuid.uuid4().hex}
""", """
import uuid
def write_merged(events, path):
    tmp = path + uuid.uuid4().hex
"""),
    ("KME-E001", "kme_tpu/telemetry/events.py", """
import time
class EventLog:
    def emit(self, kind):
        fallback = time.time
""", """
import time
class EventLog:
    def flush(self):
        self._last_flush = time.time()
"""),
    ("KME-T001", "kme_tpu/engine/newkernel.py", """
import jax.numpy as jnp
def step(state, price):
    if jnp.sum(price) > 0:
        return state
""", """
import jax.numpy as jnp
def step(state, price):
    return jnp.where(jnp.sum(price) > 0, state, state + 1)
"""),
    ("KME-T002", "kme_tpu/ops/newop.py", """
import jax.numpy as jnp
def pad(n):
    return jnp.zeros((n,))
""", """
import jax.numpy as jnp
def pad(n):
    return jnp.zeros((n,), dtype=jnp.int32)
"""),
    ("KME-T003", "kme_tpu/engine/newkernel.py", """
import numpy as np
def widen(x):
    return x.astype(int)
""", """
import numpy as np
def widen(x):
    return x.astype(np.int32)
"""),
]


@pytest.mark.parametrize(
    "rule,relpath,bad,good",
    FIXTURES, ids=[f"{r}-{i}" for i, (r, *_              # noqa: E501
                                      ) in enumerate(FIXTURES)])
def test_rule_fires_on_violation_only(rule, relpath, bad, good):
    got = {f.rule for f in rules.analyze_file(relpath, bad)}
    assert got == {rule}, f"want exactly {{{rule}}}, got {got}"
    clean = rules.analyze_file(relpath, good)
    assert clean == [], [f.render() for f in clean]


def test_syntax_error_is_a_finding_not_a_crash():
    got = rules.analyze_file("kme_tpu/engine/broken.py", "def f(:\n")
    assert [f.rule for f in got] == ["KME-E000"]


def test_t002_positional_dtype_and_preserving_asarray_are_clean():
    src = """
import numpy as np
import jax.numpy as jnp
def f(existing):
    a = np.asarray(existing)          # dtype-preserving: clean
    b = jnp.asarray(1, jnp.int32)     # positional dtype: clean
    c = np.zeros(4, np.int32)         # positional dtype: clean
    d = jnp.asarray([1, 2])           # fresh literals, no dtype: BAD
    return a, b, c, d
"""
    got = rules.analyze_file("kme_tpu/ops/x.py", src)
    assert [(f.rule, "jnp.asarray" in f.message) for f in got] \
        == [("KME-T002", True)]


# ---------------------------------------------------------------------------
# lock rules on synthetic threaded modules


def _write_module(tmp_path, rel, src):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(src)
    return rel


def test_l001_lock_order_cycle(tmp_path):
    rel = _write_module(tmp_path, "m/cyc.py", """
import threading

class A:
    def __init__(self):
        self.l1 = threading.Lock()
        self.l2 = threading.Lock()
    def fwd(self):
        with self.l1:
            with self.l2:
                pass
    def rev(self):
        with self.l2:
            with self.l1:
                pass
""")
    got = lockgraph.analyze_modules(str(tmp_path), (rel,))
    assert [f.rule for f in got] == ["KME-L001"]
    assert "l1" in got[0].message and "l2" in got[0].message


def test_l001_clean_when_orders_agree(tmp_path):
    rel = _write_module(tmp_path, "m/ok.py", """
import threading

class A:
    def __init__(self):
        self.l1 = threading.Lock()
        self.l2 = threading.Lock()
    def fwd(self):
        with self.l1:
            with self.l2:
                pass
    def also_fwd(self):
        with self.l1:
            with self.l2:
                pass
""")
    assert lockgraph.analyze_modules(str(tmp_path), (rel,)) == []


def test_l001_cycle_through_held_call(tmp_path):
    rel = _write_module(tmp_path, "m/call.py", """
import threading

class A:
    def __init__(self):
        self.l1 = threading.Lock()
        self.l2 = threading.Lock()
    def fwd(self):
        with self.l1:
            self._inner()
    def _inner(self):
        with self.l2:
            pass
    def rev(self):
        with self.l2:
            with self.l1:
                pass
""")
    got = lockgraph.analyze_modules(str(tmp_path), (rel,))
    assert [f.rule for f in got] == ["KME-L001"]


def test_l002_unlocked_cross_thread_store(tmp_path):
    rel = _write_module(tmp_path, "m/race.py", """
import threading

class W:
    def __init__(self):
        self.lock = threading.Lock()
        self.n = 0
        threading.Thread(target=self._work).start()
    def _work(self):
        self.n += 1
    def bump(self):
        self.n += 2
""")
    got = lockgraph.analyze_modules(str(tmp_path), (rel,))
    assert [f.rule for f in got] == ["KME-L002"]
    assert "self.n" in got[0].message


def test_l002_clean_under_common_lock_and_ctor_only(tmp_path):
    rel = _write_module(tmp_path, "m/ok2.py", """
import threading

class W:
    def __init__(self):
        self.lock = threading.Lock()
        self.n = 0
        self._restore()               # ctor-only helper: exempt
        threading.Thread(target=self._work).start()
    def _restore(self):
        self.n = -1
    def _work(self):
        with self.lock:
            self.n += 1
    def bump(self):
        with self.lock:
            self.n += 2
    def bump_via_helper(self):
        with self.lock:
            self._locked_bump()
    def _locked_bump(self):
        self.n += 3                    # guaranteed-caller-held: clean
""")
    got = lockgraph.analyze_modules(str(tmp_path), (rel,))
    assert got == [], [f.render() for f in got]


def test_l002_condition_aliases_its_wrapped_lock(tmp_path):
    rel = _write_module(tmp_path, "m/cond.py", """
import threading

class W:
    def __init__(self):
        self.lock = threading.Lock()
        self.data = threading.Condition(self.lock)
        self.n = 0
        threading.Thread(target=self._work).start()
    def _work(self):
        with self.data:
            self.n += 1
    def bump(self):
        with self.lock:
            self.n += 2
""")
    got = lockgraph.analyze_modules(str(tmp_path), (rel,))
    assert got == [], [f.render() for f in got]


# ---------------------------------------------------------------------------
# baseline semantics


def _mk(rule="KME-T002", path="kme_tpu/x.py", line=10, scope="f",
        snippet="a = jnp.zeros((4,))"):
    return Finding(rule=rule, path=path, line=line, col=0, scope=scope,
                   message="m", snippet=snippet)


def test_fingerprint_is_line_shift_stable():
    assert _mk(line=10).fingerprint == _mk(line=99).fingerprint
    assert _mk().fingerprint != _mk(rule="KME-T003").fingerprint
    assert _mk().fingerprint != _mk(snippet="b = 1").fingerprint


def test_baseline_roundtrip_and_gate_budget(tmp_path):
    base = str(tmp_path / "LINT_BASELINE.json")
    save_baseline(base, [_mk(), _mk(line=30)])   # same fp, count 2
    table = load_baseline(base)
    assert len(table) == 1
    (ent,) = table.values()
    assert ent["count"] == 2
    # two occurrences grandfathered, the third is new
    new, known = split_new([_mk(), _mk(line=30), _mk(line=50)], table)
    assert (len(new), len(known)) == (1, 2)
    # notes survive a rewrite
    table[_mk().fingerprint]["note"] = "accepted"
    with open(base, "w") as f:
        json.dump({"version": 1, "findings": table}, f)
    save_baseline(base, [_mk()])
    assert load_baseline(base)[_mk().fingerprint]["note"] == "accepted"


# ---------------------------------------------------------------------------
# runtime lockcheck


@pytest.fixture
def tracked_locks():
    lockcheck.install()
    lockcheck.reset()
    yield
    lockcheck.reset()
    lockcheck.uninstall()


def test_lockcheck_detects_inversion(tracked_locks):
    a, b = threading.Lock(), threading.Lock()

    def fwd():
        with a:
            with b:
                pass

    def rev():
        with b:
            with a:
                pass

    for fn in (fwd, rev):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    assert len(lockcheck.inversions()) == 1
    with pytest.raises(AssertionError):
        lockcheck.assert_clean()


def test_lockcheck_consistent_order_is_clean(tracked_locks):
    a, b = threading.Lock(), threading.Lock()
    for _ in range(3):
        with a:
            with b:
                pass
    assert lockcheck.inversions() == []
    lockcheck.assert_clean()


def test_lockcheck_condition_and_rlock(tracked_locks):
    lk = threading.Lock()
    cond = threading.Condition(lk)
    done = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            done.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    # wait() must have released the tracked lock or this deadlocks
    import time
    time.sleep(0.1)
    with cond:
        cond.notify()
    t.join(timeout=5)
    assert done == [1]
    r = threading.RLock()
    with r:
        with r:          # reentry must not self-edge
            pass
    assert lockcheck.inversions() == []


def test_lockcheck_condition_over_default_rlock(tracked_locks):
    # Condition() wraps an RLock proxy: without a real _is_owned the
    # stdlib fallback (acquire(False)/release) reenters the owned
    # proxy, concludes not-owned, and wait() raises spuriously
    import time
    cond = threading.Condition()
    done = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            done.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    with cond:
        cond.notify()
    t.join(timeout=5)
    assert done == [1]


# ---------------------------------------------------------------------------
# ctypes boundary validators


def test_check_buffer_rejections():
    from kme_tpu.native import BoundaryError, check_buffer

    ok = np.zeros(8, np.int64)
    assert check_buffer("x", ok, np.int64, 8) is ok
    with pytest.raises(BoundaryError, match="dtype"):
        check_buffer("x", np.zeros(8, np.int32), np.int64, 8)
    with pytest.raises(BoundaryError, match="overread"):
        check_buffer("x", np.zeros(4, np.int64), np.int64, 8)
    with pytest.raises(BoundaryError, match="1-D"):
        check_buffer("x", np.zeros((2, 4), np.int64), np.int64)
    with pytest.raises(BoundaryError, match="contiguous"):
        check_buffer("x", np.zeros(16, np.int64)[::2], np.int64, 8)
    with pytest.raises(BoundaryError, match="ndarray"):
        check_buffer("x", [1, 2, 3], np.int64)


# ---------------------------------------------------------------------------
# self-run: the repo itself must gate clean against the baseline


def test_repo_gates_clean_against_baseline():
    root = repo_root()
    assert os.path.exists(os.path.join(root, "LINT_BASELINE.json"))
    proc = subprocess.run(
        [sys.executable, "-m", "kme_tpu.analysis.cli", "--gate",
         "--no-ruff"],
        capture_output=True, text=True, cwd=root, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_gate_fails_on_new_violation(tmp_path):
    root = repo_root()
    bad = tmp_path / "kme_tpu" / "engine" / "planted.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("import jax.numpy as jnp\n"
                   "def f(n):\n"
                   "    return jnp.zeros((n,))\n")
    # path-scoped run, gated against the real baseline: the planted
    # violation is not grandfathered, so the gate must trip
    proc = subprocess.run(
        [sys.executable, "-m", "kme_tpu.analysis.cli", "--gate",
         "--no-ruff", str(bad)],
        capture_output=True, text=True, cwd=root, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "KME-T002" in proc.stdout
