"""Time-travel state inspection, divergence bisection and live
watchpoints (telemetry/xray.py, kme-xray, ISSUE 17). Pins the
contracts the x-ray plane stands on:

- offset-addressed materialization is EXACT: nearest retained snapshot
  + deterministic replay of the durable MatchIn log reproduces the
  engine state at any retained offset, and targets below the replay
  window fail with an error naming the oldest materializable offset;
- divergence bisection is LOGARITHMIC and exact: the first journal
  batch whose recorded effects diverge from a fresh oracle replay is
  pinned in <= ceil(log2(window_batches)) + 1 replays (count
  asserted), and the minimized repro replays to the same field diff
  offline with no broker and no engine;
- watchpoints are DETERMINISTIC and FREE: identical seeded runs fire
  identical (offset, predicate, value) hit sets, MatchOut bytes are
  identical with watchpoints armed or not, and every capture's
  kme-xray one-liner re-fires offline;
- a cluster cut is CONSISTENT: at any whole-line watermark of the
  merged input, per-group cash + open margin + pending transfer
  reserve byte-agrees with a single-leader replay of the same prefix.
"""

import json
import math
import os

import pytest

from kme_tpu.bridge.broker import InProcessBroker
from kme_tpu.bridge.provision import group_topics, provision
from kme_tpu.bridge.service import TOPIC_IN, MatchService
from kme_tpu.telemetry import xray
from kme_tpu.telemetry.journal import read_events
from kme_tpu.wire import dumps_order
from kme_tpu.workload import cross_account_stream, harness_stream


def _stream(n=600, seed=7):
    return harness_stream(n, seed=seed, num_accounts=6, num_symbols=2,
                          payout_opcode_bug=False, validate=True)


def _serve(tmp_path, msgs, name, **kw):
    """One in-process serve over a persisted broker log; returns
    (svc, log_dir, matchout_values)."""
    log_dir = str(tmp_path / name / "broker-log")
    br = InProcessBroker(persist_dir=log_dir)
    provision(br)
    for m in msgs:
        br.produce(TOPIC_IN, None, dumps_order(m))
    svc = MatchService(br, engine="oracle", compat="fixed", **kw)
    svc.run(max_messages=len(msgs))
    svc.close()
    out, off = [], 0
    while True:
        recs = br.fetch("MatchOut", off, 4096)
        if not recs:
            break
        out.extend(r.value for r in recs)
        off = recs[-1].offset + 1
    return svc, log_dir, out


# -- predicate grammar -------------------------------------------------


def test_watch_grammar():
    p = xray.parse_watch("balance[3]<0")
    assert (p.kind, p.a, p.op, p.rhs) == ("balance", 3, "<", 0)
    p = xray.parse_watch(" position[2,1] >= 10 ")
    assert (p.kind, p.a, p.b, p.op, p.rhs) == ("position", 2, 1, ">=", 10)
    p = xray.parse_watch("depth[1]!=-3")
    assert (p.op, p.rhs) == ("!=", -3)
    assert xray.parse_watch("spread[2]==0").kind == "spread"


@pytest.mark.parametrize("bad", [
    "balance[3]", "balance<0", "balance[a]<0", "position[1]<0",
    "depth[1,2]<0", "balance[1]<-1e9", "volume[1]>0", "",
    "balance[1]<0; import os"])
def test_watch_grammar_rejects(bad):
    with pytest.raises(xray.XrayError):
        xray.parse_watch(bad)


# -- materialization + replay window -----------------------------------


def test_materialize_matches_live_state(tmp_path):
    msgs = _stream()
    ck = str(tmp_path / "ckpt")
    svc, log_dir, _out = _serve(tmp_path, msgs, "m", batch=64,
                                checkpoint_dir=ck, checkpoint_every=256)
    want = xray.engine_canon(svc._oracle)
    # anchored on a snapshot
    eng, anchor, replayed = xray.materialize(log_dir, len(msgs),
                                             ckpt_dir=ck)
    assert anchor > 0 and replayed == len(msgs) - anchor
    assert xray.engine_canon(eng) == want
    # cold replay from offset 0 agrees byte for byte
    eng2, anchor2, replayed2 = xray.materialize(log_dir, len(msgs),
                                                allow_cold=True)
    assert anchor2 == 0 and replayed2 <= len(msgs)
    assert xray.engine_canon(eng2) == want


def test_replay_window_floor(tmp_path):
    """checkpoint-keep pruning moves the materialization floor: at or
    above oldest_retained_offset succeeds, below fails with an error
    naming the floor (the journal's rotate_keep guard releases history
    below the oldest snapshot, so nothing there can be cross-checked).
    """
    from kme_tpu.runtime.checkpoint import oldest_retained_offset

    msgs = _stream()
    ck = str(tmp_path / "ckpt")
    _svc, log_dir, _out = _serve(tmp_path, msgs, "w", batch=64,
                                 checkpoint_dir=ck,
                                 checkpoint_every=128,
                                 checkpoint_keep=2)
    floor = oldest_retained_offset(ck)
    assert floor and floor > 0, "keep=2 should have pruned early snaps"
    assert xray.oldest_materializable(ck) == floor
    # at/above the floor: materializes fine
    eng, anchor, _n = xray.materialize(log_dir, floor, ckpt_dir=ck)
    assert anchor <= floor
    # below: a clear error naming the oldest materializable offset
    with pytest.raises(xray.XrayError) as ei:
        xray.materialize(log_dir, floor - 1, ckpt_dir=ck)
    msg = str(ei.value)
    assert str(floor) in msg and "oldest materializable" in msg
    assert "--checkpoint-keep" in msg and "rotate_keep" in msg
    # the escape hatch: the broker log is never pruned, so a cold
    # replay can still reach below the window on request
    eng3, anchor3, _n3 = xray.materialize(log_dir, floor - 1,
                                          ckpt_dir=ck, allow_cold=True)
    assert anchor3 <= floor - 1


def test_point_queries_and_trace_resolution(tmp_path):
    msgs = _stream()
    svc, log_dir, _out = _serve(tmp_path, msgs, "q", batch=64)
    end = len(msgs)
    eng, _a, _n = xray.materialize(log_dir, end, allow_cold=True)
    # balance agrees with the live engine at the same watermark
    for aid in (1, 2, 3):
        assert eng.balances.get(aid) == svc._oracle.balances.get(aid)
    # book summary derives the same depth/spread the grammar measures
    bs = xray.book_summary(eng, 1)
    assert bs["depth"] == xray.measure_engine(
        xray.parse_watch("depth[1]>=0"), eng)
    assert bs["spread"] == xray.measure_engine(
        xray.parse_watch("spread[1]==0"), eng)
    # trace-id resolution round-trips offset -> tid -> offset
    from kme_tpu.telemetry.dtrace import local_tid

    off = end // 2
    tid = local_tid(0, off)
    assert xray.resolve_trace(tid, log_dir) == off


# -- watchpoints -------------------------------------------------------


def test_watch_deterministic_hits_and_matchout_parity(tmp_path):
    msgs = _stream()
    watch = ["balance[1]<0", "depth[1]>=4", "spread[1]==0",
             "position[2,1]>0"]
    runs = []
    for tag in ("a", "b"):
        svc, _ld, out = _serve(
            tmp_path, msgs, tag, batch=64, watch=watch,
            capture_dir=str(tmp_path / tag / "cap"))
        runs.append((list(svc.watch.hits), out,
                     list(svc.watch.capture_paths)))
    _svc, _ld, out_off = _serve(tmp_path, msgs, "off", batch=64)
    (hits_a, out_a, caps_a), (hits_b, out_b, _caps_b) = runs
    assert hits_a, "the seeded stream should trip at least one pred"
    assert hits_a == hits_b, "hit sets must be identical across runs"
    assert out_a == out_b == out_off, \
        "watchpoints must never touch MatchOut bytes"
    # captures carry the offset, the value and an offline repro line
    assert caps_a
    doc = json.loads(open(caps_a[0]).read())
    assert doc["trigger"] == "watchpoint"
    assert any(h[0] == doc["offset"] and h[1] == doc["predicate"]
               for h in hits_a)
    assert doc["repro"].startswith("kme-xray eval ")


def test_watch_offline_refire(tmp_path):
    """Every live hit re-fires offline: materialize at the captured
    offset + 1 and evaluate the same predicate to the same value."""
    msgs = _stream()
    svc, log_dir, _out = _serve(
        tmp_path, msgs, "r", batch=64,
        watch=["depth[1]>=4"], capture_dir=str(tmp_path / "r" / "cap"))
    assert svc.watch.hits
    for off, expr, val in svc.watch.hits:
        eng, _a, _n = xray.materialize(log_dir, off + 1,
                                       allow_cold=True)
        fired, got = xray.eval_engine(xray.parse_watch(expr), eng)
        assert fired and got == val


def test_watch_shadow_agrees_with_engine(tmp_path):
    """The event-fed shadow path (what non-oracle engines use at the
    barrier) fires the same hit set as the engine-backed path — both
    read the same state machine at the same barriers."""
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.wire import parse_order

    msgs = _stream()
    exprs = ["depth[1]>=4", "balance[1]<0", "spread[1]==0"]
    shadow = xray.WatchEngine(exprs)
    direct = xray.WatchEngine(exprs)
    eng = OracleEngine("fixed")
    groups, offs = [], []
    for off, m in enumerate(msgs):
        recs = eng.process(parse_order(dumps_order(m)))
        groups.append([f"{r.key} {dumps_order(r.value)}"
                       for r in recs])
        offs.append(off)
        if len(groups) == 64:     # the 64-message barrier cadence
            shadow.observe_lines(groups, offsets=offs)
            direct.observe_engine(eng, offs[-1])
            groups, offs = [], []
    if groups:
        shadow.observe_lines(groups, offsets=offs)
        direct.observe_engine(eng, offs[-1])
    assert shadow.hits, "the seeded stream should trip a predicate"
    assert shadow.hits == direct.hits
    # and the shadow's final measurements agree with the engine's
    for expr in exprs:
        pred = xray.parse_watch(expr)
        assert xray.measure(pred, shadow._shadow) == \
            xray.measure_engine(pred, eng)


# -- divergence bisection ----------------------------------------------


def test_bisect_pins_exact_batch(tmp_path, monkeypatch):
    """The CI drill: a journal-side fill-size tamper from batch K on.
    Bisection pins batch K exactly, within the replay bound, and the
    minimized repro replays to the same diff offline."""
    msgs = harness_stream(2000, seed=3, num_accounts=8, num_symbols=3,
                          payout_opcode_bug=False, validate=True)
    ck = str(tmp_path / "ckpt")
    jp = str(tmp_path / "journal.bin")
    monkeypatch.setenv("KME_AUDIT_TAMPER", "journal_fill_qty@17")
    svc, log_dir, _out = _serve(tmp_path, msgs, "b", batch=64,
                                checkpoint_dir=ck,
                                checkpoint_every=512, journal=jp)
    monkeypatch.delenv("KME_AUDIT_TAMPER")
    assert svc._tampered_batch == 17

    res = xray.bisect(jp, log_dir, ckpt_dir=ck,
                      repro_dir=str(tmp_path))
    assert res["divergent"]
    assert res["batch"] == 17, res
    bound = math.ceil(math.log2(res["window_batches"])) + 1
    assert res["replays"] <= bound, \
        f"{res['replays']} replays > log2 bound {bound}"
    assert res["diff"], "divergence must carry a field-level diff"
    assert res["first_divergent_offset"] >= 0

    # the repro dump replays offline to the SAME diff — no broker, no
    # engine, just the dump
    rep = xray.replay_bisect_repro(res["repro"])
    assert rep["match"] and rep["batch"] == 17
    # and names the ready-to-run bisect command (audit.py dump format)
    doc = json.loads(open(res["repro"]).read())
    assert "kme-xray --bisect" in doc["xray"]
    assert doc["violations"][0]["kind"] == "bisect_divergence"


def test_bisect_clean_journal_no_divergence(tmp_path):
    msgs = _stream()
    jp = str(tmp_path / "journal.bin")
    _svc, log_dir, _out = _serve(tmp_path, msgs, "c", batch=64,
                                 journal=jp)
    res = xray.bisect(jp, log_dir)
    assert not res["divergent"]
    assert res["replays"] == 1   # the single hi-probe


def test_audit_repro_names_xray_command(tmp_path, monkeypatch):
    """Satellite 3: auditor repro dumps carry an `xray` key with the
    ready-to-run bisect command for the journal that tripped."""
    msgs = _stream()
    jp = str(tmp_path / "journal.bin")
    rd = str(tmp_path / "repro")
    monkeypatch.setenv("KME_AUDIT_TAMPER", "journal_fill_qty@5")
    svc, log_dir, _out = _serve(tmp_path, msgs, "a", batch=64,
                                journal=jp, audit=True,
                                audit_repro_dir=rd)
    monkeypatch.delenv("KME_AUDIT_TAMPER")
    assert svc.auditor.violations, "journal tamper must trip the audit"
    dumps = sorted(os.listdir(rd))
    assert dumps
    doc = json.loads(open(os.path.join(rd, dumps[0])).read())
    assert "xray" in doc and "--bisect" in doc["xray"]
    assert jp in doc["xray"]
    # the named command's journal/log refs point at real paths
    assert os.path.exists(jp)


# -- cluster cut -------------------------------------------------------


def _grouped_cluster(tmp_path, ngroups=4, events=360, seed=11):
    """A chaos-layout state root: front.in + per-group persisted
    brokers, checkpoints and serves."""
    from kme_tpu.bridge import front

    lines = [dumps_order(m) for m in cross_account_stream(
        events, 32 * ngroups, 8 * ngroups, ngroups, seed=seed,
        cross_frac=1.0)]
    root = tmp_path / "root"
    root.mkdir()
    (root / "front.in").write_text("".join(ln + "\n" for ln in lines))
    per, _router = front.split_lines(lines, ngroups, transfers=True,
                                     prefund=8)
    for k in range(ngroups):
        gdir = root / f"group{k}" / "state"
        gdir.mkdir(parents=True)
        t_in, _t_out, _t_x = group_topics(k)
        br = InProcessBroker(persist_dir=str(gdir / "broker-log"))
        provision(br, topics=group_topics(k))
        for ln in per[k]:
            br.produce(t_in, None, ln)
        svc = MatchService(br, engine="oracle", compat="fixed",
                           batch=64, group=(k, ngroups),
                           checkpoint_dir=str(gdir),
                           checkpoint_every=128)
        svc.run(max_messages=len(per[k]))
        svc.close()
    return str(root), lines


def test_cluster_cut_conserves_cash(tmp_path):
    root, lines = _grouped_cluster(tmp_path)
    # full watermark and an arbitrary mid-stream whole-line cut
    for at in (None, len(lines) * 3 // 5):
        rep = xray.cluster_cut(root, at=at)
        assert rep["conserved"], rep["delta"]
        assert rep["transfer_shortfalls"] == 0
        assert rep["cluster"]["cash"] == rep["single_leader"]["cash"]
        assert (rep["cluster"]["open_margin"]
                == rep["single_leader"]["open_margin"])
        assert len(rep["groups"]) == 4
        if at is not None:
            assert rep["watermark"] == at


# -- capture reader (kme-prof --captures) ------------------------------


def test_capture_reader_shared_format(tmp_path):
    from kme_tpu.telemetry.profiler import format_capture, list_captures

    msgs = _stream()
    cap = str(tmp_path / "cap")
    svc, _ld, _out = _serve(tmp_path, msgs, "cr", batch=64,
                            watch=["depth[1]>=4"], capture_dir=cap)
    assert svc.watch.capture_paths
    paths = list_captures(cap)
    assert paths == sorted(svc.watch.capture_paths)
    text = format_capture(paths[0])
    assert "watchpoint" in text and "depth[1]>=4" in text
    assert "kme-xray eval" in text
    # missing dir degrades to empty, not an exception
    assert list_captures(str(tmp_path / "nope")) == []


# -- kme-agg staleness -------------------------------------------------


def test_aggregate_marks_stale_sources():
    from kme_tpu.telemetry.dtrace import aggregate, render_agg

    snap = {"counters": {}, "gauges": {}, "latencies": {}}
    doc = aggregate([("fresh.hb", snap), ("stuck.hb", snap)],
                    stale={"stuck.hb": {"age_s": 9.5, "intervals": 9.5,
                                        "sample_seq": 42}})
    rows = {r["source"]: r for r in doc["per_group"]}
    assert rows["stuck.hb"]["stale"] is True
    assert "stale" not in rows["fresh.hb"]
    text = render_agg(doc)
    assert text.count("STALE") == 1
    assert "sample_seq frozen at 42" in text
