"""The fault-injection registry (kme_tpu/faults.py) and the hardening
it exists to attack: spec parsing, seed determinism, cross-process fire
accounting, file damage helpers, the broker's bounded-ingress shed and
the service's produce retry-with-backoff."""

import os
import random

import pytest

from kme_tpu import faults
from kme_tpu.bridge.broker import (BrokerError, BrokerOverload,
                                   InProcessBroker)
from kme_tpu.bridge.provision import provision
from kme_tpu.bridge.service import TOPIC_IN, TOPIC_OUT, MatchService
from kme_tpu.faults import FaultPlan, FaultSpecError
from kme_tpu.wire import dumps_order
from kme_tpu.workload import harness_stream


@pytest.fixture(autouse=True)
def _clean_registry():
    """The module-level plan is process state: never leak it."""
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# spec grammar


def test_spec_parses_points_and_fields():
    plan = FaultPlan("seed=7;broker.fetch:n=2;ckpt.torn:frac=0.25:after=1;"
                     "serve.kill:at=500;tcp.partial:p=0.5:n=0")
    assert plan.seed == 7
    assert [r.point for r in plan.rules] == [
        "broker.fetch", "ckpt.torn", "serve.kill", "tcp.partial"]
    assert plan.rules[0].n == 2
    assert plan.rules[1].frac == 0.25 and plan.rules[1].after == 1
    assert plan.rules[2].at == 500
    assert plan.rules[3].p == 0.5 and plan.rules[3].n == 0


def test_spec_rejects_unknown_point_and_bad_fields():
    with pytest.raises(FaultSpecError, match="unknown fault point"):
        FaultPlan("broker.explode")
    with pytest.raises(FaultSpecError, match="unknown fault field"):
        FaultPlan("broker.fetch:whatever=1")
    with pytest.raises(FaultSpecError, match="key=value"):
        FaultPlan("broker.fetch:n")


def test_default_rule_fires_exactly_once():
    plan = FaultPlan("broker.fetch")
    assert plan.fire("broker.fetch") is not None
    assert all(plan.fire("broker.fetch") is None for _ in range(10))
    assert plan.fired_total() == 1


def test_n_zero_is_unlimited_and_after_skips():
    plan = FaultPlan("broker.fetch:n=0:after=2")
    got = [plan.fire("broker.fetch") is not None for _ in range(6)]
    assert got == [False, False, True, True, True, True]


def test_at_gates_on_offset():
    plan = FaultPlan("serve.kill:at=100")
    assert plan.fire("serve.kill", offset=50) is None
    assert plan.fire("serve.kill", offset=None) is None
    assert plan.fire("serve.kill", offset=100) is not None
    assert plan.fire("serve.kill", offset=200) is None  # n=1 spent


def test_probability_is_seed_deterministic():
    def draws(seed):
        plan = FaultPlan(f"seed={seed};broker.fetch:p=0.5:n=0")
        return [plan.fire("broker.fetch") is not None for _ in range(40)]

    a, b = draws(3), draws(3)
    assert a == b                     # same seed, same decisions
    assert any(a) and not all(a)      # actually probabilistic
    assert draws(4) != a              # a different seed diverges


def test_state_dir_makes_n_global_across_plans(tmp_path):
    """A restarted child re-parses the same spec; the state dir must
    keep an n=1 rule from refiring in the new incarnation."""
    sd = str(tmp_path)
    p1 = FaultPlan("broker.fetch:n=2", state_dir=sd)
    assert p1.fire("broker.fetch") is not None
    # "restart": a fresh plan (fresh in-process counters), same state dir
    p2 = FaultPlan("broker.fetch:n=2", state_dir=sd)
    assert p2.fire("broker.fetch") is not None   # fire 2 of 2
    p3 = FaultPlan("broker.fetch:n=2", state_dir=sd)
    assert p3.fire("broker.fetch") is None       # budget spent globally


def test_damage_file_torn_and_bitflip(tmp_path):
    blob = bytes(range(256)) * 4
    torn = tmp_path / "torn.bin"
    torn.write_bytes(blob)
    faults.configure("ckpt.torn:frac=0.25")
    assert faults.damage_file("ckpt.torn", str(torn))
    assert len(torn.read_bytes()) == len(blob) // 4
    assert torn.read_bytes() == blob[:len(blob) // 4]

    flip = tmp_path / "flip.bin"
    flip.write_bytes(blob)
    faults.configure("ckpt.bitflip")
    assert faults.damage_file("ckpt.bitflip", str(flip))
    damaged = flip.read_bytes()
    assert len(damaged) == len(blob)
    diff = [i for i in range(len(blob)) if damaged[i] != blob[i]]
    assert len(diff) == 1             # exactly one byte, one bit
    assert bin(damaged[diff[0]] ^ blob[diff[0]]).count("1") == 1


def test_module_level_should_inactive_without_spec():
    assert not faults.active()
    assert not faults.should("broker.fetch")
    assert faults.fired_total() == 0


# ---------------------------------------------------------------------------
# injection points in the broker + the service's retry/backoff


def test_broker_injection_points_raise():
    faults.configure("broker.produce:n=1;broker.fetch:n=1")
    b = InProcessBroker()
    provision(b)
    with pytest.raises(BrokerError, match="injected fault"):
        b.produce(TOPIC_IN, None, "x")
    assert b.produce(TOPIC_IN, None, "x") == 0    # n=1 spent
    with pytest.raises(BrokerError, match="injected fault"):
        b.fetch(TOPIC_IN, 0)
    assert [r.value for r in b.fetch(TOPIC_IN, 0)] == ["x"]


def test_bounded_ingress_sheds_with_rej_overload():
    """max_lag arms per-topic once a consumer commits a watermark:
    produces past the bound shed with a wire-level rej_overload instead
    of growing the backlog; commits re-open the window."""
    b = InProcessBroker(max_lag=2)
    provision(b)
    # no watermark committed yet: the bound is not armed
    for i in range(4):
        b.produce(TOPIC_IN, None, f"m{i}")
    b.commit(TOPIC_IN, 0)        # consumer at 0, backlog 4 >= 2: full
    with pytest.raises(BrokerOverload) as ei:
        b.produce(TOPIC_IN, None, "m4")
    assert ei.value.code == "rej_overload"
    assert b.overload_rejects == 1
    b.commit(TOPIC_IN, 3)        # backlog 1 < 2: open again
    assert b.produce(TOPIC_IN, None, "m4") == 4
    # MatchOut has no watermark: never shed
    for i in range(10):
        b.produce(TOPIC_OUT, "OUT", f"o{i}")
    with pytest.raises(BrokerError):
        b.commit("NoSuchTopic", 0)


def test_service_produce_retry_rides_through_transient_faults():
    """Two injected produce failures mid-batch must not kill the serve
    loop: the retry path backs off, re-produces, and the output stream
    completes byte-exactly; retries surface in telemetry."""
    msgs = harness_stream(40, seed=5, num_accounts=4, num_symbols=2,
                          payout_opcode_bug=False, validate=True)
    from kme_tpu.oracle import OracleEngine

    ora = OracleEngine("fixed", book_slots=64, max_fills=32)
    want = [rec.wire() for m in msgs for rec in ora.process(m.copy())]

    b = InProcessBroker()
    provision(b)
    for m in msgs:
        b.produce(TOPIC_IN, None, dumps_order(m))
    # configure AFTER seeding so the input produces are not attacked;
    # skip the first 3 MatchOut produces, then fail twice
    faults.configure("broker.produce:n=2:after=3")
    svc = MatchService(b, engine="oracle", compat="fixed", batch=16,
                       slots=64, max_fills=32)
    assert svc.run(max_messages=len(msgs)) == len(msgs)
    got = [f"{r.key} {r.value}" for r in b.fetch(TOPIC_OUT, 0, 10 ** 6)]
    assert got == want
    snap = svc.telemetry.snapshot()
    assert snap["counters"]["broker_retries"] == 2
    assert snap["gauges"]["faults_injected"] == 2


def test_checkpoint_post_write_faults_then_fallback(tmp_path):
    """ckpt.torn / ckpt.bitflip attack the snapshot that was just made
    durable; the load path must fall back to the previous one."""
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.runtime import checkpoint as ck

    ora = OracleEngine("fixed", book_slots=64, max_fills=32)
    msgs = harness_stream(60, seed=11, num_accounts=4, num_symbols=2,
                          payout_opcode_bug=False, validate=True)
    for m in msgs[:20]:
        ora.process(m)
    ck.save_oracle(str(tmp_path), ora, 20)
    faults.configure("ckpt.torn:n=1")          # tear the NEXT save
    for m in msgs[20:40]:
        ora.process(m)
    ck.save_oracle(str(tmp_path), ora, 40)
    loaded, offset = ck.load_oracle(str(tmp_path))
    assert offset == 20 and loaded is not None  # fell back past the tear


def test_exactly_once_fault_points_parse_and_fire():
    """The robustness-drill points behind the exactly-once machinery:
    lease.steal (split-brain: a rival takes the next epoch before our
    checkpoint) and standby.lag (the follower stalls mid-tail)."""
    plan = FaultPlan("seed=1;lease.steal:n=1;standby.lag:at=64")
    assert plan.fire("lease.steal") is not None
    assert plan.fire("lease.steal") is None        # n=1 spent
    assert plan.fire("standby.lag", offset=32) is None
    assert plan.fire("standby.lag", offset=64) is not None
    assert plan.fire("standby.lag", offset=128) is None

    faults.configure("lease.steal:n=1")            # module registry too
    assert faults.should("lease.steal")
    assert not faults.should("lease.steal")
