"""Continuous profiling & telemetry history (ISSUE 16): the on-disk
TSDB (roundtrip, rotation + sha256 prune, torn-tail recovery, restart
dedup via sample_seq), the sampling stage profiler, trigger captures
with kme-trace-resolvable exemplars, the per-backend transfer artifact,
and stage-level regression attribution (kme-prof --diff / kme-perfgate
--attribute naming a planted slowdown).
"""

import json
import os
import threading
import time

import pytest

from kme_tpu import perfgate
from kme_tpu.bridge.broker import InProcessBroker
from kme_tpu.bridge.provision import provision
from kme_tpu.bridge.service import TOPIC_IN, MatchService
from kme_tpu.telemetry.profiler import (StageProfiler, TriggerCapture,
                                        read_transfer_artifact,
                                        write_transfer_artifact)
from kme_tpu.telemetry.tsdb import (MAGIC, REC_SIZE, TSDB, iter_samples,
                                    query, read_samples, verify_store,
                                    window_summary)
from kme_tpu.wire import dumps_order
from kme_tpu.workload import harness_stream


def snap(**gauges):
    return {"gauges": gauges}


# ---------------------------------------------------------------------------
# TSDB: append / read roundtrip


def test_tsdb_roundtrip_snapshot_and_values(tmp_path):
    store = str(tmp_path)
    db = TSDB(store, source="serve")
    assert db.append_snapshot(
        {"counters": {"service_records": 10},
         "gauges": {"pipeline_depth": 2},
         "latencies": {"lat_e2e": {"count": 4, "sum_s": 0.1,
                                   "p50_ms": 1.0, "p99_ms": 3.0}}},
        sample_seq=0, ts_us=1_000)
    assert db.append_snapshot(
        {"counters": {"service_records": 25},
         "gauges": {"pipeline_depth": 3},
         "latencies": {"lat_e2e": {"count": 9, "sum_s": 0.3,
                                   "p50_ms": 1.5, "p99_ms": 7.0}}},
        sample_seq=1, ts_us=2_000)
    db.close()

    series = query(store)
    assert series["service_records"] == [(1_000, 10.0), (2_000, 25.0)]
    assert series["pipeline_depth"] == [(1_000, 2.0), (2_000, 3.0)]
    assert series["lat_e2e.p99_ms"] == [(1_000, 3.0), (2_000, 7.0)]
    # per-source reader agrees and names the writer
    rows = list(read_samples(store, source="serve"))
    assert all(r[0] == "serve" for r in rows)
    assert {r[3] for r in rows} >= {"service_records", "lat_e2e.count",
                                    "lat_e2e.p50_ms"}

    # window summary: monotonic names collapse to last-first deltas,
    # plain gauges to the mean
    summ = window_summary(store)
    assert summ["service_records"] == 15.0        # 25 - 10
    assert summ["lat_e2e.count"] == 5.0           # 9 - 4
    assert summ["pipeline_depth"] == 2.5          # mean(2, 3)
    assert summ["lat_e2e.p99_ms"] == 5.0          # mean(3, 7)


def test_tsdb_values_writer_and_dedup(tmp_path):
    db = TSDB(str(tmp_path), source="loadgen")
    assert db.append_values({"loadgen_produced_total": 100,
                             "skipped_bool": True}, db.next_seq())
    # same seq again: the crash-replay dedup drops the whole snapshot
    assert not db.append_values({"loadgen_produced_total": 999}, 0)
    assert db.dup_skipped == 1
    db.close()
    series = query(str(tmp_path))
    assert series["loadgen_produced_total"] == [
        (series["loadgen_produced_total"][0][0], 100.0)]
    assert "skipped_bool" not in series   # bools are not metrics


def test_tsdb_sources_are_isolated_files(tmp_path):
    store = str(tmp_path)
    a = TSDB(store, source="serve")
    b = TSDB(store, source="feed")
    a.append_values({"x": 1}, 0)
    b.append_values({"x": 2}, 0)
    a.close(), b.close()
    assert query(store, source="serve")["x"] == [
        (query(store, source="serve")["x"][0][0], 1.0)]
    assert query(store, source="feed")["x"][0][1] == 2.0
    with pytest.raises(ValueError):
        TSDB(store, source="../evil")


# ---------------------------------------------------------------------------
# rotation, sha256 sidecars, retention prune


def test_tsdb_rotation_prune_and_digests(tmp_path):
    store = str(tmp_path)
    db = TSDB(store, source="serve", rotate_bytes=REC_SIZE * 8, retain=2)
    for i in range(40):
        db.append_values({"service_records": float(i)}, i)
    db.close()

    segs = [p for p in os.listdir(store) if ".kmet." in p
            and not p.endswith(".sha256")]
    assert segs, "rotation never happened"
    # retention: at most `retain` rotated segments survive
    assert len(segs) <= 2
    # every finalized segment carries a verifying sha256 sidecar
    rep = verify_store(store)
    assert rep["segments"] == len(segs)
    assert rep["verified"] == rep["segments"]
    assert rep["mismatched"] == []
    # readers see one continuous, deduplicated series across segments
    pts = query(store, names=["service_records"])["service_records"]
    seqs = [s for _src, _ts, s, _n, _v in read_samples(store)]
    assert len(pts) == len(set(seqs))  # no replays survived rotation

    # corrupt a finalized segment: the audit names it
    seg = os.path.join(store, sorted(segs)[0])
    with open(seg, "r+b") as f:
        f.seek(len(MAGIC) + 4)
        f.write(b"\xff")
    rep = verify_store(store)
    assert rep["mismatched"] == [seg]


def test_tsdb_rotated_cursor_survives_fresh_live_segment(tmp_path):
    """Rotation right before a crash: the fresh live segment is empty,
    so the dedup cursor must be adopted from the newest rotated file."""
    store = str(tmp_path)
    db = TSDB(store, source="serve", rotate_bytes=REC_SIZE * 4)
    for i in range(20):
        db.append_values({"v": float(i)}, i)
    db.close()
    db2 = TSDB(store, source="serve", rotate_bytes=REC_SIZE * 4)
    assert db2.next_seq() == 20
    assert not db2.append_values({"v": 0.0}, 19)  # replay: dropped
    db2.close()


# ---------------------------------------------------------------------------
# torn-tail recovery


def test_tsdb_torn_tail_truncates_to_last_whole_record(tmp_path):
    store = str(tmp_path)
    db = TSDB(store, source="serve")
    db.append_values({"a": 1.0, "b": 2.0}, 0)
    db.append_values({"a": 3.0, "b": 4.0}, 1)
    db.close()
    path = os.path.join(store, "serve.kmet")
    whole = os.path.getsize(path)
    # crash mid-record: append half a record of garbage
    with open(path, "ab") as f:
        f.write(b"\x00" * (REC_SIZE // 2))

    db2 = TSDB(store, source="serve")
    assert db2._torn_bytes == REC_SIZE // 2
    assert os.path.getsize(path) == whole     # tail truncated away
    assert db2.last_seq == 1                  # committed records survive
    db2.append_values({"a": 5.0}, db2.next_seq())
    db2.close()
    series = query(store)
    assert [v for _ts, v in series["a"]] == [1.0, 3.0, 5.0]


def test_tsdb_header_stub_restarts_segment(tmp_path):
    store = str(tmp_path)
    path = os.path.join(store, "serve.kmet")
    os.makedirs(store, exist_ok=True)
    with open(path, "wb") as f:
        f.write(MAGIC[:3])                    # crash inside the header
    db = TSDB(store, source="serve")
    db.append_values({"a": 1.0}, 0)
    db.close()
    assert query(store)["a"][0][1] == 1.0


def test_tsdb_bad_magic_refuses(tmp_path):
    path = os.path.join(str(tmp_path), "serve.kmet")
    with open(path, "wb") as f:
        f.write(b"NOTATSDB" + b"\x00" * 64)
    with pytest.raises(ValueError, match="bad magic"):
        TSDB(str(tmp_path), source="serve")
    with pytest.raises(ValueError, match="not a TSDB segment"):
        list(iter_samples(path))


# ---------------------------------------------------------------------------
# restart dedup: sample_seq rides the checkpoint, TSDB drops replays


def _feed(broker, n=60, seed=3):
    msgs = harness_stream(n, seed=seed, num_accounts=4, num_symbols=2,
                          payout_opcode_bug=False, validate=True)
    for m in msgs:
        broker.produce(TOPIC_IN, None, dumps_order(m))
    return len(msgs)


def test_service_restart_dedups_replayed_heartbeats(tmp_path):
    """A service killed after heartbeating but before checkpointing
    replays its post-snapshot heartbeats on resume; the checkpoint's
    sample_seq cursor makes the TSDB drop them exactly the way the
    broker drops replayed (epoch, out_seq) stamps."""
    ck, store, logd = (str(tmp_path / d) for d in ("ck", "tsdb", "logs"))
    b = InProcessBroker(persist_dir=logd)
    provision(b)
    n = _feed(b)

    svc = MatchService(b, engine="oracle", compat="fixed", batch=16,
                       slots=64, max_fills=32, checkpoint_dir=ck,
                       exactly_once=True, tsdb=store)
    assert svc.run(max_messages=32) == 32
    svc._write_heartbeat(None, 32)            # TSDB-only heartbeats
    svc._write_heartbeat(None, 32)
    svc.checkpoint()                          # snapshot carries the cursor
    seq_at_ckpt = svc.sample_seq
    svc._write_heartbeat(None, 32)            # past the snapshot...
    svc._write_heartbeat(None, 32)
    svc.tsdb.close()
    del svc                                   # ...then SIGKILL

    b2 = InProcessBroker(persist_dir=logd)
    svc2 = MatchService(b2, engine="oracle", compat="fixed", batch=16,
                        slots=64, max_fills=32, checkpoint_dir=ck,
                        exactly_once=True, tsdb=store)
    # the cursor came back from checkpoint extra, NOT the disk tip
    assert svc2.sample_seq == seq_at_ckpt
    svc2._write_heartbeat(None, 32)           # replays seqs 2, 3...
    svc2._write_heartbeat(None, 32)
    assert svc2.tsdb.dup_skipped == 2
    svc2._write_heartbeat(None, 32)           # ...then new ground
    assert svc2.run(max_messages=n - 32) == n - 32
    svc2.close()

    seqs = [s for _src, _ts, s, name, _v in read_samples(store)
            if name == "service_records"]
    assert len(seqs) == len(set(seqs)), "duplicate sample_seq on disk"
    assert max(seqs) >= seq_at_ckpt + 1       # fresh samples landed


def test_plain_restart_adopts_disk_cursor(tmp_path):
    """No checkpoint to continue from: a restarted writer adopts the
    store's high-water mark instead of deduping against itself."""
    store = str(tmp_path / "tsdb")
    counts = []
    for _round in range(2):
        b = InProcessBroker()
        provision(b)
        _feed(b, n=20)
        svc = MatchService(b, engine="oracle", compat="fixed", batch=16,
                           slots=64, max_fills=32, tsdb=store)
        svc.run(max_messages=20)        # run() heartbeats on its own
        svc._write_heartbeat(None, 20)
        assert svc.tsdb.dup_skipped == 0
        svc.close()
        seqs = [s for _src, _ts, s, name, _v in read_samples(store)
                if name == "service_records"]
        assert len(seqs) == len(set(seqs)), "restart replayed a seq"
        counts.append(len(seqs))
    assert counts[1] > counts[0]        # round two kept appending


# ---------------------------------------------------------------------------
# host sampling profiler


def test_stage_profiler_attributes_synthetic_stage():
    """A thread parked inside a function named like the plan scope must
    be attributed to `plan`; unrelated stacks never count."""
    stop = threading.Event()

    def _plan():                       # name matches STAGE_FUNCS["plan"]
        stop.wait(5.0)

    def innocuous():
        stop.wait(5.0)

    threads = [threading.Thread(target=_plan, daemon=True),
               threading.Thread(target=innocuous, daemon=True)]
    for t in threads:
        t.start()
    prof = StageProfiler(interval_s=0.001)
    try:
        for _ in range(50):
            prof.sample_once()
    finally:
        stop.set()
    for t in threads:
        t.join(timeout=2.0)
    assert prof.total >= 50
    fr = prof.stage_fractions()
    assert fr["plan"] == 1.0           # only the _plan stack counted
    assert fr["dispatch"] == 0.0


def test_stage_profiler_publishes_gauges():
    from kme_tpu.telemetry import Registry

    reg = Registry()
    prof = StageProfiler(registry=reg, interval_s=0.001)
    prof.start()
    time.sleep(0.05)
    prof.stop()
    g = reg.snapshot()["gauges"]
    assert g["prof_wall_samples_total"] >= 1
    assert "prof_stage_frac_plan" in g
    assert set(k for k in g if k.startswith("prof_stage_frac_")) == {
        f"prof_stage_frac_{s}"
        for s in ("parse", "plan", "dispatch", "collect", "produce")}


# ---------------------------------------------------------------------------
# trigger capture


def test_trigger_capture_fires_on_p99_exemplar(tmp_path):
    cap = TriggerCapture(str(tmp_path / "caps"), p99_us=1_000,
                         cooldown_s=0.0, max_captures=2)
    # below threshold: armed but silent
    assert cap.maybe_fire(None, [{"e2e_us": 500, "tid": "aa" * 8}]) is None
    ex = {"e2e_us": 5_000, "tid": "deadbeef" * 4, "aid": 3, "oid": 7}
    path = cap.maybe_fire(None, [ex])
    assert path and os.path.exists(path)
    doc = json.load(open(path))
    assert doc["trigger"] == "p99_exemplar" and doc["e2e_us"] == 5_000
    # the exemplar's deterministic tid rides along — kme-trace resolves it
    assert doc["exemplars"][0]["tid"] == "deadbeef" * 4
    assert "kme-trace" in doc["resolve_with"]


def test_trigger_capture_slo_burn_cooldown_and_budget(tmp_path):
    cap = TriggerCapture(str(tmp_path), cooldown_s=3600.0, max_captures=2)
    p1 = cap.maybe_fire("checkpoint_lag", [])
    assert p1 and json.load(open(p1))["trigger"] == "slo_burn"
    # cooldown holds even under a sustained burn
    assert cap.maybe_fire("checkpoint_lag", []) is None
    cap._last_fire = -float("inf")
    assert cap.maybe_fire("checkpoint_lag", [])    # second capture
    cap._last_fire = -float("inf")
    assert cap.maybe_fire("checkpoint_lag", []) is None  # budget spent
    assert cap.captures == 2


# ---------------------------------------------------------------------------
# per-backend transfer artifact


def test_transfer_artifact_merges_in_place(tmp_path):
    path = str(tmp_path / "transfer.json")
    # a previously recorded TPU ratio is already on disk
    with open(path, "w") as f:
        json.dump({"tpu": {"transfer_compute_ratio": 0.4,
                           "h2d_bytes_per_s": 1e10}}, f)
    doc = write_transfer_artifact(path, {"backend": "cpu",
                                         "h2d_bytes_per_s": 2e9,
                                         "flops_per_batch": 1e6})
    assert set(doc) == {"cpu", "tpu"}
    back = read_transfer_artifact(path)
    # CPU CI recorded its own key; the TPU entry is untouched
    assert back["tpu"]["transfer_compute_ratio"] == 0.4
    assert back["cpu"]["h2d_bytes_per_s"] == 2e9
    assert "recorded_at" in back["cpu"]

    with pytest.raises(OSError):
        read_transfer_artifact(str(tmp_path / "missing.json"))
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        f.write("[1, 2]")
    with pytest.raises(ValueError):
        read_transfer_artifact(bad)


# ---------------------------------------------------------------------------
# stage-level regression attribution


def _window(p99_device=2.0, p99_e2e=5.0, frac_dispatch=0.3):
    return {"lat_ingress.p99_ms": 0.4, "lat_plan.p99_ms": 0.6,
            "lat_device.p99_ms": p99_device, "lat_produce.p99_ms": 0.8,
            "lat_e2e.p99_ms": p99_e2e, "prof_stage_frac_parse": 0.1,
            "prof_stage_frac_plan": 0.2,
            "prof_stage_frac_dispatch": frac_dispatch,
            "prof_stage_frac_produce": 0.3}


def test_attribution_names_planted_device_regression():
    """Plant a 2x device-stage slowdown (which also moves e2e): the
    verdict must name `device`, never the e2e symptom."""
    att = perfgate.attribute_regression(
        _window(), _window(p99_device=4.0, p99_e2e=8.5, frac_dispatch=0.55))
    assert att["suspect"] == "device"
    assert att["stages"][0]["stage"] == "device"
    ev = {e["name"]: e["ratio"] for e in att["stages"][0]["evidence"]}
    assert ev["lat_device.p99_ms"] == 2.0
    txt = perfgate.format_attribution(att)
    assert "the device stage moved the most" in txt

    # unchanged windows: nobody accused
    att = perfgate.attribute_regression(_window(), _window())
    assert att["suspect"] is None


def test_kme_prof_diff_names_planted_regression(tmp_path, capsys):
    """End-to-end over real TSDB history: two windows, a planted
    produce-stage slowdown, kme-prof --diff names the stage."""
    from kme_tpu.cli import prof_main

    base, cur = str(tmp_path / "base"), str(tmp_path / "cur")
    for store, p99, frac in ((base, 1.0, 0.2), (cur, 3.0, 0.6)):
        db = TSDB(store, source="serve")
        for i in range(4):
            db.append_snapshot(
                {"gauges": {"prof_stage_frac_produce": frac,
                            "prof_stage_frac_plan": 0.1},
                 "latencies": {
                     "lat_produce": {"p99_ms": p99},
                     "lat_plan": {"p99_ms": 0.5},
                     "lat_e2e": {"p99_ms": 2.0 + p99}}},
                i)
        db.close()
    assert prof_main(["--diff", base, cur, "--json"]) == 0
    att = json.loads(capsys.readouterr().out)
    assert att["suspect"] == "produce"


def test_perfgate_attribute_cli_over_bench_artifacts(tmp_path):
    """kme-perfgate BASELINE CURRENT --attribute over recorded bench
    detail files: exit 1 + suspect named when a stage moved."""
    base, cur = str(tmp_path / "b.json"), str(tmp_path / "c.json")
    for path, dev in ((base, 2.0), (cur, 5.0)):
        with open(path, "w") as f:
            json.dump({"metric": "orders_per_sec", "value": 1.0,
                       "detail": {"device_ms_per_batch": dev,
                                  "p99_ms": 3.0 + dev,
                                  "plan_s": 0.1}}, f)
    rep = str(tmp_path / "att.json")
    assert perfgate.main([base, cur, "--attribute", "--report", rep]) == 1
    att = json.load(open(rep))
    assert att["suspect"] == "device"
    # clean pair: exit 0, no suspect
    assert perfgate.main([base, base, "--attribute"]) == 0


# ---------------------------------------------------------------------------
# kme-prof query surfaces over a real store


def test_kme_prof_query_csv_and_verify(tmp_path, capsys):
    from kme_tpu.cli import prof_main

    store = str(tmp_path)
    db = TSDB(store, source="serve", rotate_bytes=REC_SIZE * 8)
    for i in range(12):
        db.append_values({"service_records": float(i * 10),
                          "pipeline_depth": 2.0}, i)
    db.close()
    assert prof_main([store, "--names", "service_records"]) == 0
    out = capsys.readouterr().out
    assert "service_records" in out and "n=12" in out
    assert prof_main([store, "--csv", "--names", "pipeline_depth"]) == 0
    rows = capsys.readouterr().out.strip().splitlines()
    assert rows[0] == "name,ts_us,value" and len(rows) == 13
    assert prof_main([store, "--verify"]) == 0
    assert "segment digests verified" in capsys.readouterr().out
    assert prof_main([str(tmp_path / "empty"), "--names", "zzz"]) == 1


def test_kme_top_history_lines(tmp_path):
    from kme_tpu.telemetry.top import history_lines, sparkline

    assert sparkline([]) == ""
    assert len(sparkline(list(range(100)), width=24)) <= 24
    store = str(tmp_path)
    db = TSDB(store, source="serve")
    for i in range(6):
        db.append_snapshot(
            {"counters": {"service_records": i * 100},
             "latencies": {"lat_e2e": {"p99_ms": 1.0 + i}}}, i)
    db.close()
    lines = history_lines(store)
    joined = "\n".join(lines)
    assert "service_records" in joined and "lat_e2e.p99_ms" in joined
    # absent store degrades to a note, never a crash
    assert history_lines(str(tmp_path / "nope")) == []


# ---------------------------------------------------------------------------
# overhead ceiling: the real gate runs in CI at full size
# (`kme-bench --suite prof`, 3% ceiling); here the same code path runs
# small with the ceiling relaxed — parity + artifact asserts stay hard


def test_bench_prof_smoke(tmp_path, cpu_devices):
    from kme_tpu.benchmarks import bench_prof

    rec = bench_prof(events=1500, seed=7, batch=256, repeats=1,
                     overhead_ceiling=10.0)
    # byte parity + artifact round-trip are hard asserts INSIDE the
    # suite; reaching here means both held
    assert rec["metric"] == "orders_per_sec" and rec["value"] > 0
    d = rec["detail"]
    assert d["suite"] == "prof"
    assert d["tsdb_samples"] > 0
    assert 0.0 <= d["prof_overhead_frac"] <= 10.0
    assert set(d["prof_stage_fracs"]) == {
        "parse", "plan", "dispatch", "collect", "produce"}
