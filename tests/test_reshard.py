"""Live N→M resharding (bridge/reshard.py + GroupRouter.reshard).

Pins the contracts the reshard-under-storm drill stands on, all
in-process so they run in tier-1 time:

- the plan is deterministic and rendezvous-minimal (growing 2→4 only
  moves keys onto NEW group ids — the moved_key_frac the multihost
  bench gates);
- `partition_engines` + settlement legs + a resharded router reproduce
  the single-leader oracle byte-for-byte across the barrier
  (verify_groups_reshard);
- the coordinator journal makes every phase idempotent: a re-run after
  a mid-settle crash regenerates identical stamps and the broker
  watermark suppresses every leg that already landed;
- the old generation stays durably fenced (probe_fenced).
"""

import json
import os

import pytest

from kme_tpu.bridge import front, lease
from kme_tpu.bridge import reshard as rs
from kme_tpu.bridge.broker import InProcessBroker
from kme_tpu.oracle import OracleEngine
from kme_tpu.runtime import checkpoint as ck
from kme_tpu.wire import dumps_order, parse_order
from kme_tpu.workload import cross_account_stream

SLOTS, FILLS, PREFUND = 128, 16, 8


def _lines(events=600, symbols=128, accounts=32, n=2, seed=7,
           cross_frac=0.5):
    msgs = cross_account_stream(events, symbols, accounts, n, seed=seed,
                                cross_frac=cross_frac)
    return [dumps_order(m) for m in msgs]


def _run_group_engines(substreams):
    """Feed each substream through its own fixed-mode oracle; return
    (engines, per-group raw echo lines — internal echoes included)."""
    engines = [OracleEngine("fixed", SLOTS, FILLS) for _ in substreams]
    outs = []
    for eng, sub in zip(engines, substreams):
        out = []
        for ln in sub:
            out.extend(r.wire() for r in eng.process(parse_order(ln)))
        outs.append(out)
    return engines, outs


# -- plan --------------------------------------------------------------


def test_rendezvous_minimal_frac_values():
    assert rs.rendezvous_minimal_frac(2, 4) == pytest.approx(0.5)
    assert rs.rendezvous_minimal_frac(4, 2) == pytest.approx(0.5)
    assert rs.rendezvous_minimal_frac(1, 4) == pytest.approx(0.75)
    assert rs.rendezvous_minimal_frac(3, 3) == 0.0


def test_plan_reshard_deterministic_and_minimal():
    syms, accts = range(512), range(128)
    a = rs.plan_reshard(2, 4, syms, accts)
    b = rs.plan_reshard(2, 4, syms, accts)
    assert a == b
    # rendezvous superset property: growing 2→4, a key only ever moves
    # TO a new group id (2 or 3) — modulo hashing would scatter moves
    # across all four and inflate moved_key_frac toward 1
    for s in a["moved_symbols"]:
        assert front.symbol_group(s, 4) >= 2, s
    for acct in a["moved_accounts"]:
        assert front.account_group(acct, 4) >= 2, acct
    want = rs.rendezvous_minimal_frac(2, 4)
    assert abs(a["moved_key_frac"] - want) < 0.15
    assert a["rendezvous_minimal_frac"] == pytest.approx(want)


# -- state surgery parity ----------------------------------------------


def test_partition_engines_rejects_java_mode():
    with pytest.raises(ValueError):
        rs.partition_engines([OracleEngine("java", SLOTS, FILLS)], 4)


def test_settlement_legs_deterministic_and_dense():
    consolidation = {5: 100, 9: 0, 2: 7, 11: -3, 40: 250}
    legs = rs.settlement_legs(consolidation, 4)
    assert legs == rs.settlement_legs(consolidation, 4)
    # non-positive balances carry no leg
    assert {leg[3] for leg in legs} == {2, 5, 40}
    # out_seq is dense per group (replay-stable broker stamps)
    per = {}
    for g, seq, xid, _aid, amt, line in legs:
        assert seq == per.get(g, 0)
        per[g] = seq + 1
        assert xid >= rs.XID_BASE and amt > 0
        assert front.is_internal_line(line)


def test_reshard_parity_in_process():
    """The drill's surgery chain, no processes: N engines drain, state
    is partitioned to M engines, settlement legs land first, the SAME
    router re-routes the suffix — byte parity with the single oracle."""
    n, m = 2, 4
    lines = _lines(events=600, n=n)
    split_at = len(lines) // 2
    pre_sub, router = front.split_lines(lines[:split_at], n,
                                        prefund=PREFUND)
    old_engines, actual_pre = _run_group_engines(pre_sub)

    new_engines, consolidation = rs.partition_engines(old_engines, m)
    legs = rs.settlement_legs(consolidation, m)
    actual_post = [[] for _ in range(m)]
    for g, _seq, _xid, _aid, _amt, line in legs:
        actual_post[g].extend(
            r.wire()
            for r in new_engines[g].process(parse_order(line)))

    info = router.reshard(m)
    assert info["old_groups"] == n and info["new_groups"] == m
    for ln in lines[split_at:]:
        for g, routed in router.route_line(ln):
            actual_post[g].extend(
                r.wire()
                for r in new_engines[g].process(parse_order(routed)))

    rep = front.verify_groups_reshard(
        lines, split_at, actual_pre, actual_post, compat="fixed",
        book_slots=SLOTS, max_fills=FILLS, prefund=PREFUND)
    assert rep["ok"], rep["mismatches"][:2]
    # conservation: consolidated cash equals the sum of the drained
    # engines' balances (transfer legs cancel in the sum)
    assert sum(consolidation.values()) == sum(
        sum(e.balances.values()) for e in old_engines)


def test_router_reshard_is_deterministic():
    lines = _lines(events=400, n=2)
    split_at = 250

    def run():
        _, router = front.split_lines(lines[:split_at], 2,
                                      prefund=PREFUND)
        router.reshard(4)
        return [router.route_line(ln) for ln in lines[split_at:]]

    assert run() == run()


# -- coordinator journal -----------------------------------------------


def _seed_old_generation(root, n, lines):
    """Drained old generation on disk: per-group snapshot + broker log
    (what `--idle-exit` leaves behind, minus the serve)."""
    subs, _router = front.split_lines(lines, n, prefund=PREFUND)
    engines, outs = _run_group_engines(subs)
    for k, (eng, sub) in enumerate(zip(engines, subs)):
        gdir = os.path.join(root, f"group{k}")
        lease.acquire(gdir)     # the old leader's grant
        ck.save_oracle(gdir, eng, len(sub))
        b = InProcessBroker(
            persist_dir=os.path.join(gdir, "broker-log"))
        b.create_topic(f"MatchIn.g{k}")
        for i, ln in enumerate(sub):
            b.produce(f"MatchIn.g{k}", None, ln, out_seq=i)
        b.sync()
    return subs


def test_coordinator_idempotent_resume(tmp_path):
    n, m = 2, 4
    lines = _lines(events=300, n=n)
    old = str(tmp_path / "r0")
    new = str(tmp_path / "r1")
    _seed_old_generation(old, n, lines)

    coord = rs.ReshardCoordinator(old, new, n, m)
    j1 = coord.run()
    assert j1["done"] and j1["settle"]["legs"] > 0
    assert j1["settle"]["dup_suppressed"] == 0

    # crash-after-settle resume: wipe the settle phase from the journal
    # (as if the coordinator died before the fsync) — the re-run must
    # regenerate identical stamps and the broker must suppress ALL of
    # them, leaving the MatchIn logs byte-identical
    sizes = {k: InProcessBroker(persist_dir=os.path.join(
        new, f"group{k}", "broker-log")).end_offset(f"MatchIn.g{k}")
        for k in range(m)}
    with open(coord.journal_path, encoding="utf-8") as f:
        j = json.load(f)
    del j["settle"]
    del j["done"]
    with open(coord.journal_path, "w", encoding="utf-8") as f:
        json.dump(j, f)

    j2 = rs.ReshardCoordinator(old, new, n, m).run()
    assert j2["settle"]["legs"] == j1["settle"]["legs"]
    assert j2["settle"]["dup_suppressed"] == j1["settle"]["legs"]
    for k in range(m):
        b = InProcessBroker(persist_dir=os.path.join(
            new, f"group{k}", "broker-log"))
        assert b.end_offset(f"MatchIn.g{k}") == sizes[k]

    # every journaled leg line appears exactly once in its group's log
    for g, _seq, _xid, _aid, _amt, line in j2["migrate"]["legs"]:
        b = InProcessBroker(persist_dir=os.path.join(
            new, f"group{g}", "broker-log"))
        recs = b.fetch(f"MatchIn.g{g}", 0, 10_000)
        assert sum(1 for r in recs if r.value == line) == 1


def test_coordinator_refuses_topology_mismatch(tmp_path):
    n = 2
    lines = _lines(events=200, n=n)
    old = str(tmp_path / "r0")
    new = str(tmp_path / "r1")
    _seed_old_generation(old, n, lines)
    rs.ReshardCoordinator(old, new, n, 4).run()
    with pytest.raises(ValueError, match="different reshard"):
        rs.ReshardCoordinator(old, new, n, 8).run()


def test_old_generation_stays_fenced(tmp_path):
    n = 2
    lines = _lines(events=200, n=n)
    old = str(tmp_path / "r0")
    new = str(tmp_path / "r1")
    _seed_old_generation(old, n, lines)
    g0 = os.path.join(old, "group0")
    # before the reshard: no tombstone, probe reports unfenced
    assert rs.probe_fenced(g0) is False
    rs.ReshardCoordinator(old, new, n, 4).run()
    for k in range(n):
        gdir = os.path.join(old, f"group{k}")
        stolen = lease.current_epoch(gdir)
        assert rs.probe_fenced(gdir, epoch=stolen - 1) is True
    # the new generation's first leader acquires strictly above the
    # coordinator's settle epoch
    for k in range(4):
        gdir = os.path.join(new, f"group{k}")
        assert lease.current_epoch(gdir) >= 1
        assert lease.acquire(gdir) >= 2


def test_coordinator_needs_drained_snapshots(tmp_path):
    old = str(tmp_path / "r0")
    os.makedirs(os.path.join(old, "group0"))
    os.makedirs(os.path.join(old, "group1"))
    coord = rs.ReshardCoordinator(old, str(tmp_path / "r1"), 2, 4)
    with pytest.raises(ValueError, match="drained"):
        coord.run()
