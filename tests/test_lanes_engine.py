"""Throughput (lanes) engine vs the oracle's fixed-mode semantics.

The lanes engine + conflict-free scheduler claim bit-exact serial
equivalence (kme_tpu/engine/lanes.py docstring); these tests replay
workloads through LaneSession and the scalar oracle and require
identical wire streams and store state.
"""

import pytest

import kme_tpu.opcodes as op
from kme_tpu.engine.lanes import LaneConfig
from kme_tpu.oracle import OracleEngine
from kme_tpu.runtime.sequencer import CapacityError, EnvelopeError, Scheduler
from kme_tpu.runtime.session import LaneSession
from kme_tpu.wire import OrderMsg
from kme_tpu.workload import cancel_heavy_stream, harness_stream, zipf_symbol_stream

CFG = LaneConfig(lanes=8, slots=128, accounts=64, max_fills=32, steps=32)


def assert_lane_parity(msgs, cfg=CFG, width=16):
    ses = LaneSession(cfg, width=width)
    wire_ses = LaneSession(cfg, width=width)  # fast wire-line path
    ora = OracleEngine("fixed")
    got = ses.process(msgs)
    got_wire = wire_ses.process_wire([m.copy() for m in msgs])
    for i, m in enumerate(msgs):
        want = [r.wire() for r in ora.process(m.copy())]
        g = [r.wire() for r in got[i]]
        assert g == want, f"stream diverged at message {i}: {m}"
        assert got_wire[i] == want, f"wire path diverged at message {i}: {m}"
    exp = ses.export_state()
    assert exp["balances"] == dict(ora.balances)
    assert exp["positions"] == dict(ora.positions)
    oorders = {oid: {"aid": r.aid, "sid": r.sid, "price": r.price,
                     "size": r.size, "is_buy": r.action == op.BUY}
               for oid, r in ora.orders.items()}
    assert exp["orders"] == oorders
    return ses, ora


@pytest.mark.parametrize("width", [0, 1, 16])
def test_lane_scenario_end_to_end(width):
    """width=0 keeps the single-device full-width path covered; width=1
    forces maximal step-bumping through the compaction scheduler."""
    msgs = []
    for a in range(4):
        msgs.append(OrderMsg(action=op.CREATE_BALANCE, aid=a))
        msgs.append(OrderMsg(action=op.TRANSFER, aid=a, size=100000))
    for s in (0, 1, 2):
        msgs.append(OrderMsg(action=op.ADD_SYMBOL, sid=s))
    msgs += [
        OrderMsg(action=op.BUY, oid=10, aid=0, sid=0, price=40, size=5),
        OrderMsg(action=op.BUY, oid=11, aid=1, sid=0, price=40, size=3),
        OrderMsg(action=op.SELL, oid=12, aid=2, sid=0, price=35, size=6),
        OrderMsg(action=op.SELL, oid=13, aid=3, sid=1, price=60, size=4),
        OrderMsg(action=op.BUY, oid=14, aid=0, sid=1, price=65, size=2),
        OrderMsg(action=op.CANCEL, oid=13, aid=3),
        OrderMsg(action=op.CANCEL, oid=13, aid=3),
        OrderMsg(action=op.CANCEL, oid=999, aid=0),
        OrderMsg(action=op.BUY, oid=15, aid=1, sid=2, price=50, size=4),
        OrderMsg(action=op.BUY, oid=16, aid=2, sid=2, price=50, size=2),
        OrderMsg(action=op.SELL, oid=17, aid=3, sid=2, price=45, size=9),
        OrderMsg(action=op.PAYOUT, sid=2, size=97),
        OrderMsg(action=op.PAYOUT, sid=-1, size=97),
        OrderMsg(action=op.REMOVE_SYMBOL, sid=0),
        OrderMsg(action=op.ADD_SYMBOL, sid=0),
        OrderMsg(action=op.BUY, oid=18, aid=0, sid=0, price=30, size=1),
        OrderMsg(action=op.ADD_SYMBOL, sid=-3),
        OrderMsg(action=op.TRANSFER, aid=9, size=5),
        OrderMsg(action=99, oid=0, aid=0),
    ]
    assert_lane_parity(msgs, width=width)


def test_lane_self_cross_and_zero_residual():
    """An account trading against itself, exact-fill takers, and a taker
    sweeping an entire side."""
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=100000),
            OrderMsg(action=op.ADD_SYMBOL, sid=0),
            OrderMsg(action=op.BUY, oid=1, aid=1, sid=0, price=50, size=3),
            OrderMsg(action=op.SELL, oid=2, aid=1, sid=0, price=50, size=3),
            OrderMsg(action=op.BUY, oid=3, aid=1, sid=0, price=55, size=4),
            OrderMsg(action=op.BUY, oid=4, aid=1, sid=0, price=54, size=4),
            OrderMsg(action=op.SELL, oid=5, aid=1, sid=0, price=1, size=20)]
    assert_lane_parity(msgs)


@pytest.mark.slow
def test_lane_parity_harness_workload():
    assert_lane_parity(
        harness_stream(3000, seed=7, payout_opcode_bug=False, validate=True),
        LaneConfig(lanes=4, slots=128, accounts=16, max_fills=32, steps=32))


@pytest.mark.slow
def test_lane_parity_zipf_many_symbols():
    msgs = zipf_symbol_stream(3000, num_symbols=32, num_accounts=48, seed=5)
    assert_lane_parity(
        msgs, LaneConfig(lanes=32, slots=128, accounts=64, max_fills=32,
                         steps=32))


@pytest.mark.slow
def test_lane_parity_cancel_heavy():
    msgs = cancel_heavy_stream(3000, num_symbols=8, num_accounts=24, seed=9)
    assert_lane_parity(
        msgs, LaneConfig(lanes=8, slots=256, accounts=32, max_fills=32,
                         steps=32))


def test_scheduler_invariants():
    """Actor uniqueness per step, per-symbol FIFO, barrier exclusivity."""
    msgs = harness_stream(800, seed=3, payout_opcode_bug=False, validate=True)
    sch = Scheduler(num_lanes=4, num_accounts=16)
    plan = sch.plan(msgs)
    # (segment, step) -> actors and lanes must be unique
    seen = {}
    for p in plan.placements:
        key = (p.segment, p.step)
        actors, lanes = seen.setdefault(key, (set(), set()))
        assert p.lane not in lanes, "two messages on one lane in a step"
        lanes.add(p.lane)
        if p.lane_act != 6:  # ADD_SYMBOL has no actor
            assert p.aid_idx not in actors, "actor collision in a step"
            actors.add(p.aid_idx)
    # per-lane step order must follow arrival order within each segment
    by_lane = {}
    for p in plan.placements:
        by_lane.setdefault((p.segment, p.lane), []).append((p.msg_index, p.step))
    for lst in by_lane.values():
        idx_sorted = sorted(lst)
        steps = [s for _, s in idx_sorted]
        assert steps == sorted(steps), "lane FIFO violated"


def test_capacity_and_envelope_errors():
    sch = Scheduler(num_lanes=2, num_accounts=2)
    msgs = [OrderMsg(action=op.ADD_SYMBOL, sid=s) for s in range(3)]
    with pytest.raises(CapacityError):
        sch.plan(msgs)
    sch2 = Scheduler(num_lanes=8, num_accounts=8)
    with pytest.raises(EnvelopeError):
        sch2.plan([OrderMsg(action=op.BUY, oid=1, aid=1, sid=0, price=2**31,
                            size=1)])


def test_lane_slot_overflow_rejects_per_message():
    """H2 envelope policy: the 5th non-crossing buy into a 4-slot book is
    rejected as a unit (OUT REJECT); the batch continues, no exception.
    Byte-exact vs the enveloped oracle."""
    cfg = LaneConfig(lanes=2, slots=4, accounts=8, max_fills=4, steps=8)
    ses = LaneSession(cfg)
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=10**6),
            OrderMsg(action=op.ADD_SYMBOL, sid=0)]
    msgs += [OrderMsg(action=op.BUY, oid=10 + i, aid=1, sid=0, price=10 + i,
                      size=1) for i in range(5)]
    ora = OracleEngine("fixed", book_slots=4, max_fills=4)
    want = [[r.wire() for r in ora.process(m.copy())] for m in msgs]
    got = [[r.wire() for r in recs] for recs in ses.process(msgs)]
    assert got == want
    assert got[-1][-1].startswith('OUT {"action":7')  # the overflow reject
    assert sum(1 for recs in got for ln in recs
               if ln.startswith('OUT {"action":7')) == 1


def test_lane_fill_credit_wraps_at_int32():
    """Per-fill taker credit is Java int*int — wraps at int32 before the
    long balance add (oracle._fill_order after the round-2 fix); the
    lanes engine must wrap identically."""
    msgs = []
    for a in (0, 1):
        msgs.append(OrderMsg(action=op.CREATE_BALANCE, aid=a))
        for _ in range(3):
            msgs.append(OrderMsg(action=op.TRANSFER, aid=a, size=2**31 - 1))
    msgs.append(OrderMsg(action=op.ADD_SYMBOL, sid=0))
    msgs.append(OrderMsg(action=op.SELL, oid=1, aid=0, sid=0, price=0,
                         size=2**25))
    msgs.append(OrderMsg(action=op.BUY, oid=2, aid=1, sid=0, price=125,
                         size=2**25))
    assert_lane_parity(msgs)


def test_lane_transfer_int_min_negation_wraps():
    """`-order.size` negates in int32 (INT_MIN stays INT_MIN): the
    size=INT_MIN withdrawal is ACCEPTED — lanes must mirror the oracle."""
    msgs = [
        OrderMsg(action=op.CREATE_BALANCE, aid=1),
        OrderMsg(action=op.TRANSFER, aid=1, size=-(2**31)),
    ]
    ses, ora = assert_lane_parity(msgs)
    assert ora.balances[1] == -(2**31)
