"""The conformance pack must be exactly what the oracle regenerates —
the determinism contract that makes artifacts/conformance/ a trustable
one-JVM-run validation path (BASELINE.md)."""

import os

def conformance_dir():
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "conformance")


def test_pack_matches_regeneration(tmp_path):
    import importlib.util
    import sys

    spec = importlib.util.spec_from_file_location(
        "make_conformance",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "make_conformance.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.generate(str(tmp_path))
    packed = conformance_dir()
    names = sorted(f for f in os.listdir(packed)
                   if f.endswith((".jsonl", ".txt")))
    assert names, "conformance pack is empty"
    regen = sorted(f for f in os.listdir(str(tmp_path)))
    assert names == regen
    for f in names:
        with open(os.path.join(packed, f), "rb") as a, \
                open(os.path.join(str(tmp_path), f), "rb") as b:
            assert a.read() == b.read(), f"{f} drifted from regeneration"
