"""The conformance pack must be exactly what the oracle regenerates —
the determinism contract that makes artifacts/conformance/ a trustable
one-JVM-run validation path (BASELINE.md)."""

import os

def conformance_dir():
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "conformance")


def test_pack_matches_regeneration(tmp_path):
    import importlib.util
    import sys

    spec = importlib.util.spec_from_file_location(
        "make_conformance",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "make_conformance.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.generate(str(tmp_path))
    packed = conformance_dir()
    from kme_tpu.native.oracle import native_available

    names = sorted(f for f in os.listdir(packed)
                   if f.endswith((".jsonl", ".txt"))
                   and (native_available()
                        or not f.endswith(".store.txt")))
    assert names, "conformance pack is empty"
    regen = sorted(f for f in os.listdir(str(tmp_path)))
    assert names == regen
    for f in names:
        with open(os.path.join(packed, f), "rb") as a, \
                open(os.path.join(str(tmp_path), f), "rb") as b:
            assert a.read() == b.read(), f"{f} drifted from regeneration"


def test_real_broker_e2e_script_skip_path():
    """The one-command real-broker e2e (run_real_broker_e2e.sh): where
    docker/node/the reference exist it runs broker + kme-serve --kafka
    + the UNMODIFIED Node harness and diffs MatchOut against the oracle
    replay; in THIS environment it must skip cleanly with exit 75
    (EX_TEMPFAIL) — never half-run or fail."""
    import os
    import subprocess

    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "conformance",
        "run_real_broker_e2e.sh")
    import pytest

    try:
        # generous budget: a docker-capable host pulls images, waits
        # for kafka, drives the harness and drains the engine
        r = subprocess.run(["bash", script], capture_output=True,
                           text=True, timeout=1200)
    except subprocess.TimeoutExpired:
        pytest.fail("real-broker e2e script hung (>20min)")
    if r.returncode == 0:
        return  # a docker-capable environment ran the real thing
    assert r.returncode == 75, (r.returncode, r.stderr[-500:])
    assert "SKIP:" in r.stderr
