"""Sequential mega-kernel engine vs the oracle's fixed-mode semantics.

The seq engine claims bit-exact serial replay by construction
(kme_tpu/engine/seq.py): the kernel processes messages in arrival
order, so its wire stream and store state must equal the scalar
oracle's under the same capacity envelope. On CPU the kernel runs
under pallas interpret mode — the same kernel logic, not a shadow
implementation.
"""

import numpy as np
import pytest

import kme_tpu.opcodes as op
from kme_tpu.engine import seq as SQ
from kme_tpu.oracle import OracleEngine
from kme_tpu.runtime.seqsession import SeqSession
from kme_tpu.wire import OrderMsg
from kme_tpu.workload import harness_stream, zipf_symbol_stream

CFG = SQ.SeqConfig(lanes=8, slots=128, accounts=128, max_fills=32,
                   batch=128, pos_cap=1 << 11, fill_cap=1 << 12,
                   probe_max=16)


def assert_seq_parity(msgs, cfg=CFG):
    ses = SeqSession(cfg)
    wire_ses = SeqSession(cfg)
    ora = OracleEngine("fixed", book_slots=cfg.slots,
                      max_fills=cfg.max_fills)
    got = ses.process(msgs)
    got_wire = wire_ses.process_wire([m.copy() for m in msgs])
    for i, m in enumerate(msgs):
        want = [r.wire() for r in ora.process(m.copy())]
        g = [r.wire() for r in got[i]]
        assert g == want, f"stream diverged at message {i}: {m}\n" \
            f"got  {g}\nwant {want}"
        assert got_wire[i] == want, \
            f"wire path diverged at message {i}: {m}"
    exp = ses.export_state()
    assert exp["balances"] == dict(ora.balances)
    assert exp["positions"] == dict(ora.positions)
    oorders = {oid: {"aid": r.aid, "sid": r.sid, "price": r.price,
                     "size": r.size, "is_buy": r.action == op.BUY}
               for oid, r in ora.orders.items()}
    assert exp["orders"] == oorders
    # fixed-mode oracle book keys are 2*sid (buy) / 2*sid+1 (sell)
    assert set(exp["books"]) == {k // 2 for k in ora.books}
    return ses, ora


def test_seq_scenario_end_to_end():
    """The lanes engine's scenario stream: every opcode incl. barriers,
    double cancel, unknown oid, payout YES/NO, remove + re-add."""
    msgs = []
    for a in range(4):
        msgs.append(OrderMsg(action=op.CREATE_BALANCE, aid=a))
        msgs.append(OrderMsg(action=op.TRANSFER, aid=a, size=100000))
    for s in (0, 1, 2):
        msgs.append(OrderMsg(action=op.ADD_SYMBOL, sid=s))
    msgs += [
        OrderMsg(action=op.BUY, oid=10, aid=0, sid=0, price=40, size=5),
        OrderMsg(action=op.BUY, oid=11, aid=1, sid=0, price=40, size=3),
        OrderMsg(action=op.SELL, oid=12, aid=2, sid=0, price=35, size=6),
        OrderMsg(action=op.SELL, oid=13, aid=3, sid=1, price=60, size=4),
        OrderMsg(action=op.BUY, oid=14, aid=0, sid=1, price=65, size=2),
        OrderMsg(action=op.CANCEL, oid=13, aid=3),
        OrderMsg(action=op.CANCEL, oid=13, aid=3),
        OrderMsg(action=op.CANCEL, oid=999, aid=0),
        OrderMsg(action=op.BUY, oid=15, aid=1, sid=2, price=50, size=4),
        OrderMsg(action=op.BUY, oid=16, aid=2, sid=2, price=50, size=2),
        OrderMsg(action=op.SELL, oid=17, aid=3, sid=2, price=45, size=9),
        OrderMsg(action=op.PAYOUT, sid=2, size=97),
        OrderMsg(action=op.PAYOUT, sid=-1, size=97),
        OrderMsg(action=op.REMOVE_SYMBOL, sid=0),
        OrderMsg(action=op.ADD_SYMBOL, sid=0),
        OrderMsg(action=op.BUY, oid=18, aid=0, sid=0, price=30, size=1),
        OrderMsg(action=op.ADD_SYMBOL, sid=-3),
        OrderMsg(action=op.TRANSFER, aid=9, size=5),
        OrderMsg(action=99, oid=0, aid=0),
    ]
    assert_seq_parity(msgs)


def test_seq_same_account_same_symbol_runs():
    """The workload shape the lanes scheduler serializes (H1): one
    account hammering one symbol back-to-back — the seq kernel has no
    scheduling constraints, but must still be byte-exact."""
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=10**6),
            OrderMsg(action=op.CREATE_BALANCE, aid=2),
            OrderMsg(action=op.TRANSFER, aid=2, size=10**6),
            OrderMsg(action=op.ADD_SYMBOL, sid=5)]
    oid = 100
    for k in range(40):
        msgs.append(OrderMsg(action=op.BUY, oid=oid, aid=1, sid=5,
                             price=40 + (k % 7), size=1 + (k % 5)))
        oid += 1
        msgs.append(OrderMsg(action=op.SELL, oid=oid, aid=2, sid=5,
                             price=38 + (k % 9), size=1 + (k % 4)))
        oid += 1
        if k % 3 == 0:
            msgs.append(OrderMsg(action=op.CANCEL, oid=oid - 2, aid=1))
    assert_seq_parity(msgs)


def test_seq_max_fills_envelope_reject():
    cfg = SQ.SeqConfig(lanes=8, slots=128, accounts=128, max_fills=2,
                       batch=128, pos_cap=1 << 11, fill_cap=1 << 12,
                       probe_max=16)
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=10**6),
            OrderMsg(action=op.CREATE_BALANCE, aid=2),
            OrderMsg(action=op.TRANSFER, aid=2, size=10**6),
            OrderMsg(action=op.ADD_SYMBOL, sid=1)]
    for k in range(3):
        msgs.append(OrderMsg(action=op.SELL, oid=10 + k, aid=1, sid=1,
                             price=50, size=2))
    # sweeps 3 makers -> capacity REJECT; then a 2-maker sweep passes
    msgs.append(OrderMsg(action=op.BUY, oid=20, aid=2, sid=1,
                         price=55, size=6))
    msgs.append(OrderMsg(action=op.BUY, oid=21, aid=2, sid=1,
                         price=55, size=4))
    ses, _ = assert_seq_parity(msgs, cfg)
    m = ses.metrics()
    assert m["rej_capacity"] == 1
    assert m["trades_ok"] == 4  # 3 resting sells + the 2-maker buy


def test_seq_book_slots_envelope_reject():
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=10**8),
            OrderMsg(action=op.ADD_SYMBOL, sid=1)]
    for k in range(CFG.slots + 1):   # the last one overflows the side
        msgs.append(OrderMsg(action=op.BUY, oid=100 + k, aid=1, sid=1,
                             price=1 + (k % 30), size=1))
    ses, _ = assert_seq_parity(msgs)
    assert ses.metrics()["rej_capacity"] == 1


def test_seq_harness_stream_parity():
    """Stock harness distribution (10 accounts, 3 symbols) — the exact
    shape H1 penalizes on the lanes engine."""
    msgs = harness_stream(600, seed=7)
    assert_seq_parity(msgs, SQ.SeqConfig(
        lanes=8, slots=128, accounts=128, max_fills=64, batch=256,
        pos_cap=1 << 11, fill_cap=1 << 13, probe_max=16))


def test_seq_zipf_stream_parity():
    msgs = zipf_symbol_stream(500, num_symbols=6, num_accounts=24, seed=3)
    assert_seq_parity(msgs, SQ.SeqConfig(
        lanes=8, slots=128, accounts=128, max_fills=64, batch=256,
        pos_cap=1 << 11, fill_cap=1 << 13, probe_max=16))


def test_seq_canonical_roundtrip_and_resume():
    """Export -> import mid-stream must continue byte-exact (the
    cross-engine snapshot contract)."""
    msgs = zipf_symbol_stream(400, num_symbols=5, num_accounts=16, seed=11)
    cut = 250
    cfg = SQ.SeqConfig(lanes=8, slots=128, accounts=128, max_fills=64,
                       batch=128, pos_cap=1 << 11, fill_cap=1 << 13,
                       probe_max=16)
    full = SeqSession(cfg)
    want = full.process_wire([m.copy() for m in msgs])

    a = SeqSession(cfg)
    got_head = a.process_wire([m.copy() for m in msgs[:cut]])
    canon = SQ.export_canonical(cfg, a.state)
    b = SeqSession(cfg)
    b.state = SQ.import_canonical(cfg, canon)
    b.router = a.router
    got_tail = b.process_wire([m.copy() for m in msgs[cut:]])
    assert got_head + got_tail == want


def test_seq_hash_full_error():
    cfg = SQ.SeqConfig(lanes=8, slots=128, accounts=128, max_fills=8,
                       batch=128, pos_cap=128, fill_cap=1 << 12,
                       probe_max=1)
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=0),
            OrderMsg(action=op.TRANSFER, aid=0, size=10**9)]
    for a in range(1, 100):
        msgs.append(OrderMsg(action=op.CREATE_BALANCE, aid=a))
        msgs.append(OrderMsg(action=op.TRANSFER, aid=a, size=10**9))
    for s in range(8):
        msgs.append(OrderMsg(action=op.ADD_SYMBOL, sid=s))
    oid = 1000
    # >128 distinct (lane, account) positions at probe_max=1 must trip
    # the sticky HASH_FULL error eventually
    from kme_tpu.runtime.session import LaneEngineError
    ses = SeqSession(cfg)
    try:
        for s in range(8):
            batch = []
            for a in range(32):
                batch.append(OrderMsg(action=op.SELL, oid=oid, aid=a % 99,
                                      sid=s, price=50, size=1))
                oid += 1
                batch.append(OrderMsg(action=op.BUY, oid=oid,
                                      aid=(a + 1) % 99, sid=s, price=55,
                                      size=1))
                oid += 1
            ses.process_wire(msgs + batch if s == 0 else batch)
        raised = False
    except LaneEngineError as e:
        raised = True
        assert e.code == SQ.LERR_HASH_FULL
    assert raised


def test_seq_native_wire_equivalence():
    """The C++ reconstructor (native/kme_wire.cpp) and the pure-Python
    path must produce identical line streams; process_wire_buffer's
    offsets must re-slice to the same lines."""
    cfg = SQ.SeqConfig(lanes=8, slots=128, accounts=128, max_fills=64,
                       batch=256, pos_cap=1 << 11, fill_cap=1 << 13,
                       probe_max=16)
    msgs = harness_stream(700, seed=5)
    a = SeqSession(cfg)
    r = a.process_wire_buffer([m.copy() for m in msgs])
    if r is None:
        pytest.skip("native library unavailable")
    buf, line_off, msg_lines = r
    text = buf.decode("ascii")
    flat = [text[line_off[k]:line_off[k + 1]]
            for k in range(len(line_off) - 1)]
    b = SeqSession(cfg)
    b._use_native_wire = False
    py = b.process_wire([m.copy() for m in msgs])
    pyflat = [l for ls in py for l in ls]
    assert flat == pyflat
    assert int(msg_lines.sum()) == len(pyflat)


def test_seq_hbm_books_parity():
    """hbm_books: book planes in HBM behind the kernel's per-lane VMEM
    scratch cache — same byte parity, exercised at slots=256 (NR=2) so
    multi-row blocks and lane switches are both covered."""
    msgs = zipf_symbol_stream(500, num_symbols=6, num_accounts=24, seed=3)
    assert_seq_parity(msgs, SQ.SeqConfig(
        lanes=8, slots=256, accounts=128, max_fills=64, batch=256,
        pos_cap=1 << 11, fill_cap=1 << 13, probe_max=16, hbm_books=True))


def test_seq_service_and_cross_engine_restore(tmp_path):
    """MatchService with engine='seq': serve a stream byte-exact, crash
    after a checkpoint, resume — and restore the SAME snapshot into the
    LANES engine (snapshots are canonical across engines)."""
    from kme_tpu.bridge.broker import InProcessBroker
    from kme_tpu.bridge.provision import provision
    from kme_tpu.bridge.service import MatchService
    from kme_tpu.runtime import checkpoint as ck
    from kme_tpu.wire import dumps_order

    msgs = harness_stream(300, seed=13, num_symbols=4, num_accounts=8,
                          payout_opcode_bug=False, validate=True)
    ora = OracleEngine("fixed", book_slots=128, max_fills=32)
    per_msg = [[r.wire() for r in ora.process(m.copy())] for m in msgs]

    ck_dir = str(tmp_path / "ck")
    kw = dict(engine="seq", compat="fixed", batch=50, symbols=8,
              accounts=128, slots=128, max_fills=32,
              checkpoint_dir=ck_dir, checkpoint_every=100)
    b = InProcessBroker(persist_dir=str(tmp_path / "log"))
    provision(b)
    for m in msgs:
        b.produce("MatchIn", None, dumps_order(m))
    svc1 = MatchService(b, **kw)
    assert svc1.run(max_messages=150) == 150   # snapshot at >=100
    snap_off = svc1._last_ckpt_offset
    assert snap_off >= 100
    del svc1  # crash

    svc2 = MatchService(b, **kw)               # resume (seq -> seq)
    assert svc2.offset == snap_off
    assert svc2.run(max_messages=len(msgs) - snap_off) \
        == len(msgs) - snap_off
    from kme_tpu.bridge.consume import consume_lines
    got = list(consume_lines(b, follow=False))
    want = [ln for lines in per_msg[:150] for ln in lines]
    want += [ln for lines in per_msg[snap_off:] for ln in lines]
    assert got == want

    # cross-engine: the newest seq snapshot restores into a
    # LaneSession; the restored canonical STATE must equal the
    # oracle's stores exactly, and any remaining stream tail must
    # replay byte-exact
    ses, off = ck.load_session(ck_dir)
    assert ses is not None and off >= snap_off
    if off < len(msgs):
        tail = ses.process_wire([m.copy() for m in msgs[off:]])
        assert [ln for lines in tail for ln in lines] \
            == [ln for lines in per_msg[off:] for ln in lines]
    exp = ses.export_state()
    assert exp["balances"] == dict(ora.balances)
    assert exp["positions"] == dict(ora.positions)


def test_native_router_matches_python():
    """The C++ router must produce identical plans and id maps to the
    Python SeqRouter on a stream exercising every edge (unknown-oid
    cancels, negative-sid addsym, payout route cleanup, re-used oids)."""
    from kme_tpu.runtime.seqsession import (NativeSeqRouter, SeqRouter,
                                            make_seq_router)

    nat = make_seq_router(16, 256)
    if not isinstance(nat, NativeSeqRouter):
        pytest.skip("native library unavailable")
    py = SeqRouter(16, 256)
    msgs = harness_stream(1200, seed=21, num_symbols=6, num_accounts=12,
                          payout_opcode_bug=False, validate=False)
    INT64_MIN = -(1 << 63)
    msgs += [
        # negative-sid trade (allocates a negative map key), then the
        # INT64_MIN payout/remove edge (abs wraps; must host-reject)
        OrderMsg(action=op.BUY, oid=999001, aid=1, sid=-7, price=50,
                 size=1),
        OrderMsg(action=op.PAYOUT, sid=INT64_MIN, size=97),
        OrderMsg(action=op.REMOVE_SYMBOL, sid=INT64_MIN),
        OrderMsg(action=op.PAYOUT, sid=-7, size=97),
    ]
    for chunk in (msgs[:500], msgs[500:]):   # maps persist across calls
        cn, rn = nat.route(chunk)
        cp, rp = py.route(chunk)
        assert rn == rp
        for k in cp:
            assert cn[k].tolist() == cp[k].tolist(), k
    assert nat.aid_idx == py.aid_idx
    assert nat.sid_lane == py.sid_lane
    assert nat.oid_sid == py.oid_sid


def test_submit_collect_pipelined_byte_exact(cpu_devices):
    """The double-buffered serving API (SURVEY.md §7 H5): submit batch
    N+1 before collecting batch N; the concatenated byte stream equals
    the one-shot process_wire_buffer output exactly (incl. barriers)."""
    from kme_tpu.wire import WireBatch
    from kme_tpu.workload import zipf_symbol_stream

    msgs = zipf_symbol_stream(1500, num_symbols=8, num_accounts=32,
                              seed=8, zipf_a=1.1, payout_per_mille=4)
    cfg = SQ.SeqConfig(lanes=8, slots=128, accounts=128, max_fills=16,
                       batch=256, pos_cap=1 << 12, probe_max=8)
    a, b = SeqSession(cfg), SeqSession(cfg)
    ra = a.process_wire_buffer(msgs)
    if ra is None:
        import pytest

        pytest.skip("native toolchain unavailable")
    parts, pend = [], []
    for lo in range(0, len(msgs), 256):
        pend.append(b.submit(WireBatch.from_msgs(msgs[lo:lo + 256])))
        if len(pend) > 1:
            parts.append(b.collect(pend.pop(0)))
    while pend:
        parts.append(b.collect(pend.pop(0)))
    assert b"".join(p[0] for p in parts) == ra[0]
