"""Per-chip async dispatch (parallel/seqmesh.py, r14): byte parity of
the per-shard submission-queue dispatcher vs the single-chip SeqSession
and the lockstep mesh scan, under adversarial interleavings — zipf-hot
with live migrations, payout-storm barrier pressure, and a mid-stream
drain-to-barrier snapshot. Plus the deterministic stall accounting
(chip_stall_frac from the replayed dispatch schedules, never a wall
clock) and the H2D double-buffer overlap surface on the single-chip
pipelined path.

The async scheduler may only change WHEN cells run, never WHAT they
compute: every test here pins bytes, exported state, or both.
"""

import pytest

from kme_tpu.engine import seq as SQ

# minutes of virtual-mesh wall across the module — the CI shards job
# runs it unfiltered; tier-1 keeps async coverage via test_seqmesh's
# default-dispatch (auto -> async) parity runs
pytestmark = pytest.mark.slow
from kme_tpu.parallel.seqmesh import SeqMeshSession
from kme_tpu.runtime.seqsession import SeqSession
from kme_tpu.workload import (payout_storm_stream, zipf_hot_stream,
                              zipf_symbol_stream)

CFG = dict(lanes=8, slots=128, accounts=128, max_fills=16,
           pos_cap=1 << 10, probe_max=8)

SLICE = 300   # rebalancing fires between process_wire calls only


def _mesh(shards, **kw):
    return SeqMeshSession(SQ.SeqConfig(**CFG), shards=shards, **kw)


def _run_sliced(ses, msgs):
    got = []
    for lo in range(0, len(msgs), SLICE):
        for per in ses.process_wire(msgs[lo:lo + SLICE]):
            got.extend(per)
    return got


def _serial(msgs):
    ses = SeqSession(SQ.SeqConfig(**CFG))
    got = [ln for per in ses.process_wire(msgs) for ln in per]
    return got, ses


def test_async_zipf_hot_parity_with_migrations(cpu_devices):
    """zipf-hot through the async mesh, fed in slices so the elastic
    planner migrates accounts BETWEEN async batches: bytes and exported
    state must match the single-chip session, and migrations must have
    actually fired (otherwise the test never exercised the
    split/gather bridging of the per-shard device states)."""
    msgs = zipf_hot_stream(1200, num_symbols=8, num_accounts=24,
                           seed=7)
    # shards=4, not 8: with 8 lanes over 8 shards the planner has one
    # lane per shard and nothing to swap (same reason the elastic
    # suite pins migrations at 2 and 4)
    mesh = _mesh(4)
    assert mesh.dispatch == "async"
    got = _run_sliced(mesh, msgs)
    want, single = _serial(msgs)
    assert got == want
    assert mesh.shard_stats()["migrations"] > 0, \
        "stream never migrated — interleaving not adversarial"
    assert mesh.export_state() == single.export_state()


def test_async_payout_storm_parity(cpu_devices):
    """payout-storm: dense PAYOUT barriers force constant full merges
    between short async stretches — the worst case for the owner-
    selection merge and the barrier drain."""
    msgs = payout_storm_stream(900, num_symbols=8, num_accounts=24,
                               seed=3)
    mesh = _mesh(4)
    got = _run_sliced(mesh, msgs)
    want, single = _serial(msgs)
    assert got == want
    assert mesh.export_state() == single.export_state()


def test_async_mid_stream_drain_snapshot(cpu_devices):
    """Checkpoint mid-flight: stop the feed at an arbitrary message
    boundary, drain to the collect barrier, and export. The snapshot
    must equal the serial session's at the same prefix — this is the
    invariant the supervisor's checkpoint/restore path rides on."""
    msgs = zipf_symbol_stream(1000, num_symbols=8, num_accounts=24,
                              seed=11, zipf_a=1.0, payout_per_mille=5)
    cut = 617
    mesh = _mesh(8)
    got = _run_sliced(mesh, msgs[:cut])
    single = SeqSession(SQ.SeqConfig(**CFG))
    want = [ln for per in single.process_wire(msgs[:cut]) for ln in per]
    assert got == want
    assert mesh.export_state() == single.export_state()


def test_lockstep_dispatch_unchanged(cpu_devices):
    """--dispatch lockstep is the pre-r14 scan, byte for byte, and
    ignores the async machinery entirely."""
    msgs = zipf_hot_stream(800, num_symbols=8, num_accounts=24, seed=5)
    mesh = _mesh(8, dispatch="lockstep")
    assert mesh.dispatch == "lockstep"
    got = _run_sliced(mesh, msgs)
    want, _ = _serial(msgs)
    assert got == want


def test_stall_deterministic_and_below_lockstep(cpu_devices):
    """chip_stall_frac comes from the deterministic dispatch
    simulation: two identical runs agree exactly, and the async
    schedule never stalls MORE than its lockstep twin (strictly less
    on the skewed zipf-hot workload — the schedule this PR exists to
    beat)."""
    msgs = zipf_hot_stream(1200, num_symbols=8, num_accounts=24,
                           seed=7)
    stats = []
    for _ in range(2):
        mesh = _mesh(8)
        _run_sliced(mesh, msgs)
        stats.append(mesh.stall_stats())
    assert stats[0]["chip_stall_frac"] == stats[1]["chip_stall_frac"]
    assert (stats[0]["chip_stall_frac_lockstep"]
            == stats[1]["chip_stall_frac_lockstep"])
    assert (stats[0]["chip_stall_frac"]
            < stats[0]["chip_stall_frac_lockstep"])


def test_wall_feed_parity(cpu_devices):
    """wall_feed=True folds real per-shard walls into the rebalancer
    EWMA — placement may differ run to run, bytes may not."""
    msgs = zipf_hot_stream(900, num_symbols=8, num_accounts=24, seed=9)
    mesh = _mesh(4, wall_feed=True)
    got = _run_sliced(mesh, msgs)
    want, _ = _serial(msgs)
    assert got == want


def test_h2d_overlap_pipelined_single_chip(cpu_devices):
    """Depth-2 pipelined submit/collect on the single-chip session:
    most H2D staging must land while an earlier batch is still in
    flight (h2d_overlap_frac >= 0.5 — the serve-path gauge the bench
    reports advisory-up)."""
    from kme_tpu.native import load_library

    if load_library() is None:
        pytest.skip("native host runtime unavailable (KME_NATIVE=0 "
                    "or no toolchain) — collect() needs the "
                    "reconstructor")
    msgs = zipf_symbol_stream(1200, num_symbols=8, num_accounts=24,
                              seed=2, zipf_a=1.0)
    ses = SeqSession(SQ.SeqConfig(**CFG))
    pend, bufs = [], []
    for lo in range(0, len(msgs), 150):
        pend.append(ses.submit(msgs[lo:lo + 150]))
        while len(pend) > 2:
            bufs.append(ses.collect(pend.pop(0))[0])
    while pend:
        bufs.append(ses.collect(pend.pop(0))[0])
    assert ses.h2d_overlap_frac >= 0.5, ses.h2d_overlap_frac
    # parity of the pipelined byte stream vs the plain path
    want = SeqSession(SQ.SeqConfig(**CFG)).process_wire_buffer(msgs)[0]
    assert b"".join(bufs) == want


def test_async_numpy_fallback_parity(cpu_devices, monkeypatch):
    """KME_NATIVE=0 shape: force slice_windows onto its numpy fallback
    (the segment-staging step is the only new native entry point) —
    bytes must not move."""
    from kme_tpu.native import sched as native_sched

    monkeypatch.setattr(native_sched, "load_library", lambda: None)
    msgs = zipf_hot_stream(700, num_symbols=8, num_accounts=24,
                           seed=13)
    mesh = _mesh(4)
    got = _run_sliced(mesh, msgs)
    want, _ = _serial(msgs)
    assert got == want
