"""Elastic symbol->shard scheduling (parallel/seqmesh.py): byte-exact
MatchOut parity vs the scalar oracle WITH migrations observed under the
zipf-hot adversary, strict imbalance improvement over the static-hash
placement, the per-(window, shard) batch_occupancy convention, the
placement-table fast path (native/sched.apply_placement), and the
per-shard telemetry surfaces (/metrics text, snapshot JSON).

The stream is fed in slices because rebalancing happens BETWEEN
process_wire calls only — one giant batch would never migrate.
"""

import numpy as np
import pytest

from kme_tpu.engine import seq as SQ
from kme_tpu.native.sched import apply_placement
from kme_tpu.oracle import OracleEngine
from kme_tpu.parallel.seqmesh import SeqMeshSession, plan_rebalance
from kme_tpu.telemetry.registry import bucket_index
from kme_tpu.workload import zipf_hot_stream

CFG = dict(lanes=8, slots=128, accounts=128, max_fills=16,
           pos_cap=1 << 10, probe_max=8)
SLICE = 300


def _stream(n=1200, seed=7):
    return zipf_hot_stream(n, num_symbols=8, num_accounts=24, seed=seed)


def _oracle_lines(msgs):
    ora = OracleEngine("fixed", book_slots=CFG["slots"],
                       max_fills=CFG["max_fills"])
    return [r.wire() for m in msgs for r in ora.process(m.copy())]


def _run_sliced(ses, msgs, sl=SLICE):
    got = []
    for lo in range(0, len(msgs), sl):
        for per in ses.process_wire(msgs[lo:lo + sl]):
            got.extend(per)
    return got


# ---------------------------------------------------------------------------
# pure host pieces (no device)


def test_plan_rebalance_pure_and_deterministic():
    perm = np.arange(8, dtype=np.int64)
    # balanced or empty load: stay put
    assert plan_rebalance(np.ones(8), perm, 4) is None
    assert plan_rebalance(np.zeros(8), perm, 4) is None
    # hot lane 0 + warm lane 1 co-located by the identity layout
    load = np.array([10, 5, 1, 1, 1, 1, 1, 1], float)
    new = plan_rebalance(load, perm, 4)
    assert new is not None
    assert sorted(new.tolist()) == list(range(8))  # a permutation
    Sl = 2

    def shard_loads(p):
        out = [0.0] * 4
        for lane in range(8):
            out[int(p[lane]) // Sl] += load[lane]
        return out

    static_peak = max(shard_loads(perm))      # 15: hot+warm together
    assert max(shard_loads(new)) < static_peak
    # byte-for-byte deterministic (KME-D002: replay-safe, no RNG)
    again = plan_rebalance(load, perm, 4)
    assert np.array_equal(new, again)
    # single-shard degenerates to None via the threshold check
    assert plan_rebalance(load, perm, 1) is None


def test_apply_placement_matches_scalar():
    rng = np.random.default_rng(3)
    perm = rng.permutation(8).astype(np.int64)
    lanes = rng.integers(0, 8, size=64).astype(np.int32)
    slot, shard, row = apply_placement(perm, lanes, 2)
    for k in range(len(lanes)):
        g = int(perm[int(lanes[k])])
        assert int(slot[k]) == g
        assert int(shard[k]) == g // 2
        assert int(row[k]) == g % 2
    # identity table == the pre-elastic static layout
    ident = np.arange(8, dtype=np.int64)
    _s, sh, ro = apply_placement(ident, lanes, 2)
    assert np.array_equal(sh, lanes.astype(np.int64) // 2)
    assert np.array_equal(ro, lanes.astype(np.int64) % 2)


# ---------------------------------------------------------------------------
# device: parity with migrations + telemetry surfaces


def test_zipf_hot_parity_with_migrations_shards2(cpu_devices):
    """Acceptance: byte-exact MatchOut vs the single-chip oracle at
    shards=2 under zipf-hot WITH shard_migrations_total > 0, and the
    per-shard telemetry visible on every surface."""
    msgs = _stream()
    ses = SeqMeshSession(SQ.SeqConfig(**CFG), shards=2)
    got = _run_sliced(ses, msgs)
    assert got == _oracle_lines(msgs), "elastic placement diverged"
    stats = ses.shard_stats()
    assert stats["migrations"] > 0, "planner never migrated"
    assert stats["rebalances"] > 0

    # metrics(): the counter projection carries the shard surface
    mets = ses.metrics()
    assert mets["shard_migrations"] == stats["migrations"]
    assert mets["shard_imbalance"] == stats["imbalance"] > 0

    # /metrics.json (registry snapshot)
    snap = ses.telemetry.snapshot()
    assert snap["counters"]["shard_migrations_total"] > 0
    assert snap["gauges"]["shard_imbalance"] > 0
    assert snap["gauges"]["shard_count"] == 2
    for s in range(2):
        assert snap["gauges"][f"shard{s}_occupancy"] > 0
        assert snap["latencies"][f"device_shard{s}"]["count"] > 0
    assert (snap["gauges"]["shard0_occupancy"]
            + snap["gauges"]["shard1_occupancy"]
            == sum(stats["occupancy"]))

    # /metrics (Prometheus text): gauge + per-shard summary quantiles
    text = ses.telemetry.prometheus_text()
    assert "shard_imbalance" in text
    assert 'device_shard0{quantile="0.99"}' in text
    assert "shard_migrations_total" in text

    # per-shard occupancy histograms ride histograms()
    hists = ses.histograms()
    blended = np.asarray(hists["batch_occupancy"])
    per = sum(np.asarray(hists[f"batch_occupancy_shard{s}"])
              for s in range(2))
    assert np.array_equal(blended, per)

    # the window invariant survives the migrated placement table: plan
    # a fresh slice against the permuted state (host-only)
    assert not np.array_equal(ses._perm, np.arange(CFG["lanes"])), \
        "migrations observed but the table is still the identity"
    cols, _ = ses.router.route(_stream(n=400, seed=8))
    _w, placements, _c, _K = ses.plan_windows(cols)
    binds = (SQ.L_BUY, SQ.L_SELL, SQ.L_CANCEL, SQ.L_CREATE,
             SQ.L_TRANSFER)
    seen = {}
    for k, w, s, p in placements:
        if int(cols["act"][k]) in binds:
            a = int(cols["aid"][k])
            assert seen.setdefault((w, a), s) == s, \
                f"account {a} on two shards in window {w}"


@pytest.mark.slow
def test_zipf_hot_shards4_beats_static_hash(cpu_devices):
    """Acceptance at shards=4: parity + migrations, AND the elastic
    placement's cumulative occupancy imbalance strictly below the
    rebalance=False static-hash control on the same stream."""
    msgs = _stream()
    want = _oracle_lines(msgs)

    elastic = SeqMeshSession(SQ.SeqConfig(**CFG), shards=4)
    assert _run_sliced(elastic, msgs) == want, "elastic diverged"
    est = elastic.shard_stats()
    assert est["migrations"] > 0

    static = SeqMeshSession(SQ.SeqConfig(**CFG), shards=4,
                            rebalance=False)
    assert _run_sliced(static, msgs) == want, "static diverged"
    sst = static.shard_stats()
    assert sst["migrations"] == 0

    assert est["imbalance"] < sst["imbalance"], (
        f"elastic {est['imbalance']} did not beat "
        f"static {sst['imbalance']}")


def test_batch_occupancy_per_window_shard_convention(cpu_devices):
    """The documented convention at the _run fetch loop: one
    batch_occupancy observation per NON-EMPTY (window, shard) cell,
    valued at that cell's message count — not one blended observation
    per host batch. Reconstructed exactly from the planner's cnts."""
    msgs = _stream(n=600, seed=13)
    ses = SeqMeshSession(SQ.SeqConfig(**CFG), shards=2,
                         rebalance=False)
    planned = []
    orig = ses.plan_windows

    def spy(cols):
        wins, placements, cnts, K = orig(cols)
        planned.append(cnts.copy())
        return wins, placements, cnts, K

    ses.plan_windows = spy
    _run_sliced(ses, msgs)
    hists = ses.histograms()
    idx = SQ.HIST_NAMES.index("batch_occupancy")

    def expect(cells):
        out = np.zeros(SQ.N_HIST_BUCKETS, np.int64)
        for c in cells:
            out[bucket_index(int(c))] += 1
        return out

    all_cells = np.concatenate([c.reshape(-1) for c in planned])
    nonempty = all_cells[all_cells > 0]
    assert np.array_equal(np.asarray(hists["batch_occupancy"]),
                          expect(nonempty)), \
        "batch_occupancy is not per-(window, shard)"
    # and the per-shard planes decompose it by the shard column
    for s in range(2):
        cells_s = np.concatenate([c[:, s] for c in planned])
        assert np.array_equal(
            np.asarray(hists[f"batch_occupancy_shard{s}"]),
            expect(cells_s[cells_s > 0])), f"shard {s} plane wrong"
    # occupancy totals agree with the planner exactly
    assert ses.shard_stats()["occupancy"] == [
        int(sum(c[:, s].sum() for c in planned)) for s in range(2)]
    assert idx >= 0
