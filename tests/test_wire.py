"""Wire serde byte-parity with the reference's Jackson stack
(KProcessor.java:477-530)."""

import pytest

from kme_tpu.wire import OrderMsg, OutRecord, dumps_order, parse_order


def test_dumps_matches_jackson_layout():
    o = OrderMsg(action=2, oid=123, aid=4, sid=1, price=50, size=10)
    assert dumps_order(o) == (
        '{"action":2,"oid":123,"aid":4,"sid":1,"price":50,"size":10,'
        '"next":null,"prev":null}')


def test_dumps_with_prev_set():
    o = OrderMsg(action=2, oid=9, aid=1, sid=0, price=50, size=3, prev=77)
    assert dumps_order(o).endswith('"next":null,"prev":77}')


def test_parse_defaults_missing_fields():
    o = parse_order('{"action":100,"aid":7}')
    assert (o.action, o.oid, o.aid, o.sid, o.price, o.size) == (100, 0, 7, 0, 0, 0)
    assert o.next is None and o.prev is None


def test_parse_binds_input_pointers():
    # Jackson binds the public next/prev fields from input when present
    # (the @JsonCreator ctor only covers the six value fields)
    o = parse_order('{"action":2,"oid":1,"aid":1,"sid":0,"price":5,"size":5,'
                    '"next":9,"prev":8}')
    assert o.next == 9 and o.prev == 8
    o2 = parse_order('{"action":2,"next":null,"prev":null}')
    assert o2.next is None and o2.prev is None


def test_parse_negative_values():
    o = parse_order('{"action":101,"aid":3,"size":-5000,"sid":-2}')
    assert o.size == -5000 and o.sid == -2


def test_parse_rejects_non_integer():
    with pytest.raises(ValueError):
        parse_order('{"action":"BUY"}')


def test_roundtrip_is_canonical():
    raw = '{"size":10,"price":50,"action":2,"oid":1,"aid":2,"sid":3}'
    assert dumps_order(parse_order(raw)) == (
        '{"action":2,"oid":1,"aid":2,"sid":3,"price":50,"size":10,'
        '"next":null,"prev":null}')


def test_out_record_wire_line():
    rec = OutRecord("OUT", OrderMsg(action=7))
    assert rec.wire() == ('OUT {"action":7,"oid":0,"aid":0,"sid":0,"price":0,'
                          '"size":0,"next":null,"prev":null}')
