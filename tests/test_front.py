"""kme-front (bridge/front.py): the multi-leader front door.

Pins the three contracts the symbol-sharded scale-out stands on:
- assignment parity: the C++ columnar pass (kme_group_assign), the
  numpy fallback and the scalar reference produce bit-identical
  group ids (the split is part of the durable stream — drift between
  the twins would silently re-partition every topic);
- deterministic merge: the global feed is a pure function of the
  per-group streams, whatever interleaving the racing consumers saw;
- transfer dedup: injected reserve→settle legs are replay-regenerated
  with identical (epoch, out_seq) stamps, and the broker/consumer
  dedup layers suppress duplicate delivery — zero double-settles.
"""

import random

import pytest

import kme_tpu.opcodes as op
from kme_tpu.bridge import front
from kme_tpu.oracle import OracleEngine
from kme_tpu.wire import dumps_order, order_json, parse_order
from kme_tpu.workload import cross_account_stream, zipf_symbol_stream


def _lines(events=600, symbols=24, accounts=12, seed=3):
    msgs = zipf_symbol_stream(events, num_symbols=symbols,
                              num_accounts=accounts, seed=seed)
    return [dumps_order(m) for m in msgs]


# -- assignment parity -------------------------------------------------


def test_scalar_vs_numpy_assignment(monkeypatch):
    import kme_tpu.native

    monkeypatch.setattr(kme_tpu.native, "load_library", lambda: None)
    keys = [0, 1, 2, -1, -7, 12345, 2 ** 53, -(2 ** 62), (1 << 63) - 1]
    for n in (1, 2, 3, 4, 7):
        for salt in (front.SALT_SYMBOL, front.SALT_ACCOUNT):
            got = front.assign_groups(keys, n, salt).tolist()
            want = [front.group_of(k, n, salt) for k in keys]
            assert got == want, (n, salt)


def test_native_vs_python_assignment():
    from kme_tpu.native import load_library

    if load_library() is None:
        pytest.skip("native library unavailable")
    rng = random.Random(11)
    keys = [rng.randrange(-(2 ** 63), 2 ** 63) for _ in range(4096)]
    keys += [0, -1, (1 << 63) - 1, -(1 << 63)]
    for n in (2, 3, 4, 8):
        got = front.assign_groups(keys, n, front.SALT_SYMBOL).tolist()
        want = [front.group_of(k, n, front.SALT_SYMBOL) for k in keys]
        assert got == want, f"native/python drift at ngroups={n}"


def test_symbol_group_ignores_payout_sign():
    # a payout (negative sid) must land on its book's group
    for sid in (1, 7, 123456789, 2 ** 40):
        for n in (2, 3, 4):
            assert (front.symbol_group(sid, n)
                    == front.symbol_group(-sid, n))


def test_assignment_balances():
    # rendezvous over a wide universe: no group starves (the bound is
    # loose on purpose — placement quality, not an exact split)
    n = 4
    counts = [0] * n
    for sid in range(1, 2049):
        counts[front.symbol_group(sid, n)] += 1
    assert min(counts) > 2048 / n / 2, counts


# -- deterministic merge -----------------------------------------------


def test_merge_records_interleaving_invariant():
    per, _ = front.split_lines(_lines(), 3)
    engines = [OracleEngine("fixed") for _ in range(3)]
    records = []
    for g in range(3):
        seq = 0
        for ln in per[g]:
            for rec in engines[g].process(parse_order(ln)):
                records.append((g, seq, rec.wire()))
                seq += 1
    want = front.merge_records(records)
    rng = random.Random(5)
    for _ in range(5):
        shuffled = records[:]
        rng.shuffle(shuffled)
        assert front.merge_records(shuffled) == want
    # merge_streams over the in-order per-group streams is the same
    # convention
    per_out = [[], [], []]
    for g, _seq, ln in sorted(records, key=lambda r: (r[0], r[1])):
        per_out[g].append(ln)
    assert front.merge_streams(per_out) == want


def test_merge_filters_internal_echoes():
    internal = front.make_internal_transfer(7, -100, 0)
    assert front.is_internal_line(internal)
    assert front.is_internal_line(f"OUT {internal}")  # engine echo too
    out = front.merge_streams([[internal, 'OUT {"action":2,"oid":1}'],
                               [front.make_internal_create(7, 1)]])
    assert out == ['OUT {"action":2,"oid":1}']


def test_organic_stream_never_carries_the_marker():
    assert not any(front.is_internal_line(ln) for ln in _lines())


# -- split semantics ---------------------------------------------------


def test_split_is_replay_deterministic():
    lines = _lines()
    a, ra = front.split_lines(lines, 4)
    b, rb = front.split_lines(lines, 4)
    assert a == b
    assert ra.counters == rb.counters


def test_original_line_lands_on_exactly_one_group():
    lines = _lines()
    router = front.GroupRouter(4)
    for ln in lines:
        routed = router.route_line(ln)
        organic = [g for g, out in routed
                   if not front.is_internal_line(out)]
        assert len(organic) == 1
        assert any(out == ln for _g, out in routed)


def test_create_balance_broadcasts_to_every_group():
    router = front.GroupRouter(3)
    routed = router.route_line(order_json(op.CREATE_BALANCE, 0, 42,
                                          0, 0, 0))
    assert sorted(g for g, _ in routed) == [0, 1, 2]
    internal = [ln for _g, ln in routed if front.is_internal_line(ln)]
    assert len(internal) == 2
    assert router.counters["balance_broadcasts_total"] == 2


def _cross_pair(n=2):
    """(aid, sid) such that the account's home differs from the
    symbol's group under n groups."""
    for aid in range(1, 200):
        for sid in range(1, 200):
            if (front.account_group(aid, n)
                    != front.symbol_group(sid, n)):
                return aid, sid
    raise AssertionError("no cross pair found")


def test_prefund_chunks_transfer_legs():
    aid, sid = _cross_pair()
    deposit = order_json(op.TRANSFER, 0, aid, 0, 0, 10 ** 9)
    create = order_json(op.CREATE_BALANCE, 0, aid, 0, 0, 0)
    adds = [order_json(op.ADD_SYMBOL, 0, 0, sid, 0, 0)]
    orders = [order_json(op.BUY, 100 + i, aid, sid, 10, 5)
              for i in range(16)]
    lines = [create, deposit] + adds + orders

    per1, r1 = front.split_lines(lines, 2, prefund=1)
    assert r1.counters["cross_shard_transfers_total"] == 16
    per8, r8 = front.split_lines(lines, 2, prefund=8)
    # 16 identical orders at prefund=8 need exactly two grants
    assert r8.counters["cross_shard_transfers_total"] == 2
    assert r8.counters["transfer_shortfall_total"] == 0
    # the chunking changes WHICH legs ride the stream, never the
    # oracle-visible outcome
    for prefund, per in ((1, per1), (8, per8)):
        engines = [OracleEngine("fixed") for _ in range(2)]
        outs = [[rec.wire() for ln in per[g]
                 for rec in engines[g].process(parse_order(ln))]
                for g in range(2)]
        rep = front.verify_groups(lines, outs, prefund=prefund)
        assert rep["ok"], rep["mismatches"]


def test_underfunded_cross_order_counts_a_shortfall():
    aid, sid = _cross_pair()
    lines = [order_json(op.CREATE_BALANCE, 0, aid, 0, 0, 0),
             order_json(op.ADD_SYMBOL, 0, 0, sid, 0, 0),
             order_json(op.BUY, 100, aid, sid, 10, 5)]  # no deposit
    _per, router = front.split_lines(lines, 2)
    assert router.counters["transfer_shortfall_total"] == 1
    assert router.counters["cross_shard_transfers_total"] == 0


# -- end-to-end parity -------------------------------------------------


@pytest.mark.parametrize("ngroups", [1, 2, 4])
def test_front_to_engines_to_merge_parity(ngroups):
    lines = _lines(events=500, symbols=16, accounts=10, seed=9)
    per, _router = front.split_lines(lines, ngroups)
    engines = [OracleEngine("fixed") for _ in range(ngroups)]
    outs = [[rec.wire() for ln in per[g]
             for rec in engines[g].process(parse_order(ln))]
            for g in range(ngroups)]
    rep = front.verify_groups(lines, outs)
    assert rep["ok"], rep["mismatches"][:1]


def test_cross_account_workload_parity():
    msgs = cross_account_stream(400, 32, 16, 2, seed=4, cross_frac=1.0)
    lines = [dumps_order(m) for m in msgs]
    per, router = front.split_lines(lines, 2)
    assert router.counters["cross_shard_transfers_total"] > 0
    engines = [OracleEngine("fixed") for _ in range(2)]
    outs = [[rec.wire() for ln in per[g]
             for rec in engines[g].process(parse_order(ln))]
            for g in range(2)]
    rep = front.verify_groups(lines, outs)
    assert rep["ok"], rep["mismatches"][:1]


# -- transfer dedup under duplicate delivery ---------------------------


def test_duplicate_transfer_stamps_are_suppressed_by_the_broker():
    from kme_tpu.bridge.broker import InProcessBroker

    b = InProcessBroker()
    topic = "Xfer.g0"
    b.create_topic(topic)
    leg = front.make_internal_transfer(7, -500, 0)
    assert b.produce(topic, "OUT", leg, epoch=2, out_seq=10) == 0
    # the crash-replay regenerates the identical leg with the identical
    # stamp: the watermark must swallow it, not append a double-settle
    assert b.produce(topic, "OUT", leg, epoch=2, out_seq=10) == -1
    assert b.dup_suppressed == 1
    assert b.produce(topic, "OUT", leg, epoch=2, out_seq=11) == 1
    recs = b.fetch(topic, 0, 100, timeout=0.0)
    assert len(recs) == 2
    assert [r.out_seq for r in recs] == [10, 11]


def test_duplicate_transfer_delivery_deduped_at_the_consumer():
    from kme_tpu.bridge.consume import DedupRing

    ring = DedupRing()
    assert not ring.is_dup(2, 10)
    assert ring.is_dup(2, 10)          # redelivery of the same leg
    assert not ring.is_dup(3, 10)      # new epoch, new identity
    assert ring.suppressed == 1
