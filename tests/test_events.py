"""Control-plane flight recorder (telemetry/events.py) contracts:
writer durability round-trips (rotation + sha256 sidecars, torn-tail
recovery, crash-replay dedup on explicit seqs), the merge laws
(offset-anchored causal order, first-wins dedup that keeps colliding
DISTINCT writers, byte-deterministic digests), the supervisor's
decision sequence under a fake clock, and the kme-events CLI —
filters, artifacts, and --why attribution against a planted TSDB
regression."""

import json
import os

from kme_tpu.telemetry import events as ev
from kme_tpu.telemetry import events_cli


def _kinds(events):
    return [e["kind"] for e in events]


# -- writer round-trips -----------------------------------------------------


def test_emit_persist_roundtrip(tmp_path):
    log = ev.open_log(str(tmp_path), "serve", clock=lambda: 12.5)
    assert log.emit("lease.grant", epoch=3, group=1, offset=40,
                    role="leader")
    assert log.emit("overload.transition", severity="warn",
                    from_state="admit", to_state="shed")
    log.close()
    got = ev.read_log(ev.log_path(str(tmp_path), "serve"))
    assert _kinds(got) == ["lease.grant", "overload.transition"]
    assert [e["seq"] for e in got] == [0, 1]
    first = got[0]
    assert first["src"] == "serve"
    assert first["ts"] == int(12.5e6)
    assert first["sev"] == "info"
    assert first["g"] == 1 and first["epoch"] == 3 and first["off"] == 40
    assert first["detail"] == {"role": "leader"}
    assert got[1]["sev"] == "warn"


def test_seq_resumes_across_reopen(tmp_path):
    log = ev.open_log(str(tmp_path), "s")
    for _ in range(3):
        log.emit("a")
    log.close()
    log = ev.open_log(str(tmp_path), "s")
    assert log.last_seq == 2
    log.emit("b")
    log.close()
    got = ev.read_log(ev.log_path(str(tmp_path), "s"))
    assert [e["seq"] for e in got] == [0, 1, 2, 3]
    rep = ev.verify_log(ev.log_path(str(tmp_path), "s"))
    assert rep["ok"] and rep["seq_gaps"] == 0 and rep["events"] == 4


def test_torn_tail_recovered_on_reopen(tmp_path):
    log = ev.open_log(str(tmp_path), "s")
    for _ in range(3):
        log.emit("a")
    log.close()
    path = ev.log_path(str(tmp_path), "s")
    with open(path, "ab") as f:
        f.write(b'{"src": "s", "seq": 3, "kind": "torn-mid-app')
    # readers skip the torn tail ...
    assert [e["seq"] for e in ev.iter_log(path)] == [0, 1, 2]
    # ... and the writer truncates it, then continues the cursor
    log = ev.open_log(str(tmp_path), "s")
    assert log.last_seq == 2
    log.emit("b")
    log.close()
    got = ev.read_log(path)
    assert [e["seq"] for e in got] == [0, 1, 2, 3]
    assert _kinds(got)[-1] == "b"


def test_explicit_seq_crash_replay_dedup(tmp_path):
    # the reshard-coordinator discipline: seq = durable phase ordinal,
    # re-emitted wholesale by a post-crash re-run
    log = ev.open_log(str(tmp_path), "reshard")
    assert log.emit("reshard.fence", seq=0)
    assert log.emit("reshard.migrate", seq=1)
    log.close()
    rerun = ev.open_log(str(tmp_path), "reshard")
    assert rerun.emit("reshard.fence", seq=0) is False
    assert rerun.emit("reshard.migrate", seq=1) is False
    assert rerun.emit("reshard.settle", seq=2)
    assert rerun.emit("reshard.done", seq=3)
    assert rerun.dup_skipped == 2
    rerun.close()
    got = ev.read_log(ev.log_path(str(tmp_path), "reshard"))
    assert _kinds(got) == ["reshard.fence", "reshard.migrate",
                           "reshard.settle", "reshard.done"]


def test_rotation_sidecars_and_cursor_seed(tmp_path):
    log = ev.open_log(str(tmp_path), "s", rotate_bytes=4096)
    for _ in range(60):
        log.emit("tick", pad="x" * 200)
    log.close()
    path = ev.log_path(str(tmp_path), "s")
    assert os.path.exists(f"{path}.1")
    with open(f"{path}.1.sha256") as f:
        side = json.load(f)
    assert side["bytes"] == os.path.getsize(f"{path}.1")
    got = ev.read_log(path)
    assert [e["seq"] for e in got] == list(range(60))
    assert ev.verify_log(path)["ok"]
    # crash exactly at the rotation boundary: live file empty, cursor
    # must seed from the newest rotated segment or dedup dies
    os.truncate(path, 0)
    log = ev.open_log(str(tmp_path), "s", rotate_bytes=4096)
    assert log.last_seq == 59
    log.close()


def test_rotated_segment_corruption_detected(tmp_path):
    log = ev.open_log(str(tmp_path), "s", rotate_bytes=4096)
    for _ in range(60):
        log.emit("tick", pad="x" * 200)
    log.close()
    path = ev.log_path(str(tmp_path), "s")
    with open(f"{path}.1", "r+b") as f:
        f.seek(10)
        f.write(b"CORRUPT")
    rep = ev.verify_log(path)
    assert rep["ok"] is False
    assert any(s["digest_ok"] is False for s in rep["segments"])


def test_prune_beyond_retain(tmp_path):
    log = ev.open_log(str(tmp_path), "s", rotate_bytes=4096, retain=1)
    for _ in range(120):
        log.emit("tick", pad="x" * 200)
    log.close()
    path = ev.log_path(str(tmp_path), "s")
    assert os.path.exists(f"{path}.1")
    assert not os.path.exists(f"{path}.2")
    assert ev.verify_log(path)["ok"]


def test_disabled_recorder_touches_no_disk(tmp_path, monkeypatch):
    monkeypatch.setenv("KME_EVENTS", "0")
    log = ev.open_log(str(tmp_path / "sub"), "s")
    assert log.emit("a") is False
    assert not os.path.exists(str(tmp_path / "sub"))
    log.close()
    monkeypatch.delenv("KME_EVENTS")
    off = ev.EventLog(str(tmp_path / "off.jsonl"), "s", enabled=False)
    assert off.emit("a") is False
    assert not os.path.exists(str(tmp_path / "off.jsonl"))


def test_last_offset_monotonic_and_lag_bytes(tmp_path):
    log = ev.open_log(str(tmp_path), "s", rotate_bytes=4096,
                      fsync=False)
    log.emit("a")
    assert log.lag_bytes > 0          # written, not yet fsync'd
    log.flush()
    assert log.lag_bytes == 0
    before = log.last_offset
    for _ in range(60):               # forces at least one rotation
        log.emit("tick", pad="x" * 200)
    assert log.last_offset > before + 60 * 200   # never rewound
    log.close()


# -- merge laws -------------------------------------------------------------


def test_offset_anchors_beat_skewed_walltime_within_group():
    # src A's clock runs 1000s ahead; both events carry group-7 offset
    # anchors, so replay position must win over walltime
    late_clock = ev.make_event("serve.g7", 0, "late-but-first",
                               int(2000e6), group=7, offset=10)
    early_clock = ev.make_event("standby.g7", 0, "early-but-second",
                                int(1000e6), group=7, offset=20)
    merged = ev.merge_events([[late_clock], [early_clock]])
    assert _kinds(merged) == ["late-but-first", "early-but-second"]
    # unanchored events keep walltime order
    a = ev.make_event("x", 0, "first", int(1e6))
    b = ev.make_event("y", 0, "second", int(2e6))
    assert _kinds(ev.merge_events([[b], [a]])) == ["first", "second"]


def test_dedup_drops_replays_keeps_colliding_writers():
    e1 = ev.make_event("serve.g0", 0, "lease.grant", int(1e6))
    # same (src, seq) but DIFFERENT bytes: a second writer of the same
    # source name (e.g. the next reshard generation's serve.g0), not a
    # replay — both must survive the merge
    e2 = ev.make_event("serve.g0", 0, "lease.grant", int(9e6))
    assert len(ev.merge_events([[e1], [e1]])) == 1      # true replay
    assert len(ev.merge_events([[e1], [e2]])) == 2      # collision
    assert len(ev.merge_events([[e1, e2], [e2, e1]])) == 2


def test_timeline_digest_input_order_independent(tmp_path):
    evs = [ev.make_event(f"s{i % 3}", i // 3, "k", int((9 - i) * 1e6))
           for i in range(9)]
    d1 = ev.timeline_digest(ev.merge_events([evs]))
    d2 = ev.timeline_digest(ev.merge_events([list(reversed(evs))]))
    assert d1 == d2
    # and the merged artifact re-merges to the same digest
    out = str(tmp_path / "events.jsonl")
    ev.write_merged(ev.merge_events([evs]), out)
    assert ev.timeline_digest(ev.merge_logs([str(tmp_path)])) == d1


# -- the supervisor's decision sequence under a fake clock ------------------


def test_supervisor_crash_restart_sequence_under_fake_clock(tmp_path):
    from test_supervise_unit import Harness

    h = Harness(tmp_path)
    h._pending[0].exit_after, h._pending[0].rc = 1.0, 1
    h._pending[1].exit_after, h._pending[1].rc = 1.0, 0
    assert h.sup.run() == 0
    got = ev.read_log(ev.log_path(str(tmp_path), "supervisor"))
    assert _kinds(got) == [
        "supervisor.spawn", "supervisor.crash", "supervisor.backoff",
        "supervisor.restart", "supervisor.recover", "supervisor.exit"]
    assert [e["seq"] for e in got] == list(range(6))
    # stamps come from the injected fake clock (seconds from 0), not
    # the wall — and never run backwards
    ts = [e["ts"] for e in got]
    assert ts == sorted(ts) and ts[-1] < int(1e9)
    crash = got[1]
    assert crash["sev"] == "error"
    assert crash["detail"]["fingerprint"] == "exit:1"
    assert got[2]["detail"]["seconds"] > 0


def test_supervisor_promotion_sequence_under_fake_clock(tmp_path):
    from test_supervise_unit import StandbyHarness

    h = StandbyHarness(tmp_path)
    h._pending[0].exit_after, h._pending[0].rc = 2.0, 1
    adoptee = h._standby_pending[0]
    adoptee.exit_after, adoptee.rc = 8.0, 0
    assert h.sup.run() == 0
    got = ev.read_log(ev.log_path(str(tmp_path), "supervisor"))
    assert _kinds(got) == [
        "supervisor.spawn", "supervisor.standby_spawn",
        "supervisor.crash", "supervisor.promote", "supervisor.adopt",
        "supervisor.standby_spawn", "supervisor.recover",
        "supervisor.exit"]
    promote = got[3]
    assert promote["detail"]["pid"] == adoptee.pid
    recover = got[6]
    assert recover["detail"]["promoted"] is True
    assert recover["detail"]["failover_seconds"] > 0
    rep = ev.verify_log(ev.log_path(str(tmp_path), "supervisor"))
    assert rep["ok"] and rep["seq_gaps"] == 0


# -- kme-events CLI ---------------------------------------------------------


def _write_two_logs(root):
    a = ev.open_log(str(root), "serve.g0", clock=lambda: 10.0)
    a.emit("lease.grant", epoch=1, group=0, role="leader")
    a.emit("overload.transition", severity="warn", group=0,
           from_state="admit", to_state="shed")
    a.close()
    b = ev.open_log(str(root), "supervisor", clock=lambda: 11.0)
    b.emit("supervisor.spawn", pid=123)
    b.close()


def test_cli_filters_and_artifacts(tmp_path, capsys):
    _write_two_logs(tmp_path)
    out_path = str(tmp_path / "merged" / "events.jsonl")
    os.makedirs(str(tmp_path / "merged"))
    chrome = str(tmp_path / "trace.json")
    rc = events_cli.main([str(tmp_path), "--kind", "lease", "--json",
                          "--out", out_path,
                          "--chrome-out", chrome])
    assert rc == 0
    printed = [json.loads(ln) for ln in
               capsys.readouterr().out.strip().splitlines()]
    assert _kinds(printed) == ["lease.grant"]
    # --out holds the FULL merged timeline, filter notwithstanding
    merged = ev.read_log(out_path, include_rotated=False)
    assert len(merged) == 3
    with open(chrome) as f:
        doc = json.load(f)
    assert doc["traceEvents"]
    # human (non-json) mode renders the canonical line format
    rc = events_cli.main([str(tmp_path), "--source", "supervisor"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "supervisor#0" in out and "supervisor.spawn" in out


def test_cli_why_resolves_planted_regression(tmp_path, capsys):
    from kme_tpu.telemetry.tsdb import TSDB

    t_event = 1000.0
    store = str(tmp_path / "tsdb")
    db = TSDB(store, source="serve")
    db.append_snapshot(
        {"latencies": {"lat_e2e": {"p99_ms": 5.0}}}, 1,
        ts_us=int((t_event - 3.0) * 1e6))
    db.append_snapshot(
        {"latencies": {"lat_e2e": {"p99_ms": 50.0}},
         "gauges": {"steady_gauge": 7.0}}, 2,
        ts_us=int((t_event + 3.0) * 1e6))
    db.close()
    log = ev.open_log(str(tmp_path), "serve", clock=lambda: t_event)
    log.emit("overload.transition", severity="warn",
             from_state="admit", to_state="shed")
    log.close()
    rc = events_cli.main([str(tmp_path), "--why", "serve:0",
                          "--store", store, "--window", "5"])
    assert rc == 0
    out = capsys.readouterr().out
    # the planted latency jump is attributed as the top delta
    assert "overload.transition" in out
    assert "lat_e2e.p99_ms" in out
    assert "5 -> 50" in out
    # a bare-kind ref resolves too, and a miss exits non-zero
    assert events_cli.main([str(tmp_path), "--why", "overload",
                            "--store", store]) == 0
    capsys.readouterr()
    assert events_cli.main([str(tmp_path), "--why", "nope:77",
                            "--store", store]) == 2
