"""Native C++ quirk-exact engine vs the Python oracle (the authority).

Byte parity on the wire-line stream AND deep equality of all five
stores, across both compat modes, the capacity envelope, multi-batch
continuation, and reference-death paths."""

import pytest

import kme_tpu.opcodes as op
from kme_tpu.oracle import OracleEngine
from kme_tpu.oracle.engine import ReferenceHang
from kme_tpu.wire import OrderMsg
from kme_tpu.workload import (cancel_heavy_stream, harness_stream,
                              zipf_symbol_stream)

native = pytest.importorskip("kme_tpu.native.oracle")
if not native.native_available():
    import os
    import shutil

    if os.environ.get("KME_NATIVE") == "0":
        # deliberate disable (the fallback tier-1 leg), not a build
        # failure — these tests compare native vs Python, so there is
        # nothing to test
        pytest.skip("native explicitly disabled (KME_NATIVE=0)",
                    allow_module_level=True)
    if shutil.which("g++"):
        pytest.fail("g++ is available but the native library failed to "
                    "build — a real regression, not a missing toolchain "
                    "(rerun with the kme_tpu.native build stderr)")
    pytest.skip("native library unavailable (no toolchain)",
                allow_module_level=True)


def _oracle_state(ora):
    orders = {oid: {"action": r.action, "aid": r.aid, "sid": r.sid,
                    "price": r.price, "size": r.size, "next": r.next,
                    "prev": r.prev}
              for oid, r in ora.orders.items()}
    return {"balances": dict(ora.balances), "positions": dict(ora.positions),
            "orders": orders, "books": dict(ora.books),
            "buckets": dict(ora.buckets)}


def assert_native_parity(msgs, compat, batch=None, **envelope):
    ora = OracleEngine(compat, **envelope)
    nat = native.NativeOracleEngine(compat, **envelope)
    want = [[r.wire() for r in ora.process(m.copy())] for m in msgs]
    if batch is None:
        got = nat.process_wire([m.copy() for m in msgs])
    else:
        got = []
        for lo in range(0, len(msgs), batch):
            got.extend(nat.process_wire(
                [m.copy() for m in msgs[lo:lo + batch]]))
    for i in range(len(msgs)):
        assert got[i] == want[i], f"diverged at message {i}: {msgs[i]}"
    assert nat.export_state() == _oracle_state(ora)


def test_native_java_harness_quirk_exact():
    """Stock harness (Q1 sid=0 trading, Q2 ghost trades, Q5 payout-as-
    cancel, Q9 echoes, Q11 garbage positions) — byte and store parity."""
    assert_native_parity(harness_stream(3000, seed=7), "java")


def test_native_java_harness_second_seed_multibatch():
    assert_native_parity(harness_stream(2000, seed=123), "java", batch=333)


def test_native_fixed_with_envelope():
    msgs = harness_stream(2000, seed=5, num_symbols=4, num_accounts=8,
                          payout_opcode_bug=False, validate=True)
    assert_native_parity(msgs, "fixed", book_slots=16, max_fills=8)


def test_native_fixed_zipf_with_barriers():
    msgs = zipf_symbol_stream(2000, num_symbols=16, num_accounts=24, seed=11,
                              zipf_a=1.0, payout_per_mille=5)
    assert_native_parity(msgs, "fixed")


def test_native_fixed_cancel_heavy():
    msgs = cancel_heavy_stream(2000, num_symbols=8, num_accounts=16, seed=3)
    assert_native_parity(msgs, "fixed")


def test_native_reference_hang_death_path():
    """Q4: REMOVE_SYMBOL on a non-empty book hangs the reference — both
    engines raise ReferenceHang at the same message with the same state."""
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=100000),
            OrderMsg(action=op.ADD_SYMBOL, sid=1),
            OrderMsg(action=op.BUY, oid=5, aid=1, sid=1, price=50, size=3)]
    kill = OrderMsg(action=op.REMOVE_SYMBOL, sid=1)
    ora = OracleEngine("java")
    nat = native.NativeOracleEngine("java")
    for m in msgs:
        ora.process(m.copy())
    nat.process_wire([m.copy() for m in msgs])
    with pytest.raises(ReferenceHang):
        ora.process(kill.copy())
    with pytest.raises(ReferenceHang):
        nat.process_wire([kill.copy()])
    assert nat.export_state() == _oracle_state(ora)


def test_native_wire_pointer_fields_roundtrip():
    """Messages arriving with non-null next/prev enter the engine with
    them set (Jackson field binding) and echo/rest verbatim (Q9).
    (Cancelling such a poisoned order dies in BOTH engines — the oracle
    with a raw KeyError at the dangling prev, the native engine with
    ReferenceCrash — so the comparison stops at the rest/echo.)"""
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=100000),
            OrderMsg(action=op.ADD_SYMBOL, sid=1),
            OrderMsg(action=op.BUY, oid=5, aid=1, sid=1, price=50, size=3,
                     next=777, prev=888),
            OrderMsg(action=op.BUY, oid=6, aid=1, sid=1, price=50, size=2)]
    assert_native_parity(msgs, "java")
