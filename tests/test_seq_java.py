"""Java-compat mode ON the sequential kernel vs the java oracle.

The round-3 COMPAT.md argument proved quirk-exact PARALLEL execution
impossible under Q11; the sequential kernel has no such obstacle — it
executes the reference's own serial semantics, quirks included: Q1
(merged sid-0 book), Q2 (ghost trades), Q9 (prev echo), Q11
(value-as-key position corruption via a 128-bit-key tombstoned hash).
Scope: the stock wire surface (no barriers / negative sids — dead or
broken reference paths, COMPAT.md); the java ORACLE is the judge.
"""

import pytest

import kme_tpu.opcodes as op
from kme_tpu.engine import seq as SQ
from kme_tpu.oracle import OracleEngine
from kme_tpu.runtime.seqsession import SeqSession, UnsupportedJavaOp
from kme_tpu.wire import OrderMsg
from kme_tpu.workload import harness_stream

JCFG = SQ.SeqConfig(lanes=8, slots=128, accounts=128, max_fills=64,
                    batch=256, pos_cap=1 << 12, fill_cap=1 << 13,
                    probe_max=16, compat="java")


def assert_java_parity(msgs, cfg=JCFG):
    ses = SeqSession(cfg)
    ora = OracleEngine("java")
    got = ses.process_wire(msgs)
    for i, m in enumerate(msgs):
        want = [r.wire() for r in ora.process(m.copy())]
        g = got[i]
        assert g == want, (f"java stream diverged at message {i}: {m}\n"
                           f"got  {g}\nwant {want}")
    exp = ses.export_state()
    assert exp["balances"] == dict(ora.balances)
    assert exp["positions"] == dict(ora.positions)
    oorders = {oid: {"aid": r.aid, "sid": r.sid, "price": r.price,
                     "size": r.size, "is_buy": r.action == op.BUY}
               for oid, r in ora.orders.items()}
    assert exp["orders"] == oorders
    return ses, ora


def test_java_basic_and_q9():
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=100000),
            OrderMsg(action=op.CREATE_BALANCE, aid=2),
            OrderMsg(action=op.TRANSFER, aid=2, size=100000),
            OrderMsg(action=op.ADD_SYMBOL, sid=1),
            OrderMsg(action=op.BUY, oid=10, aid=1, sid=1, price=40, size=5),
            OrderMsg(action=op.BUY, oid=11, aid=2, sid=1, price=40, size=3),
            OrderMsg(action=op.SELL, oid=12, aid=2, sid=1, price=35,
                     size=6),
            OrderMsg(action=op.CANCEL, oid=11, aid=2),
            OrderMsg(action=op.CANCEL, oid=11, aid=2)]
    assert_java_parity(msgs)


def test_java_q2_ghost_trade():
    """Simultaneous taker/maker exhaustion with another crossing maker
    left: the reference emits one zero-size BOUGHT/SOLD pair (Q2)."""
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=10**6),
            OrderMsg(action=op.CREATE_BALANCE, aid=2),
            OrderMsg(action=op.TRANSFER, aid=2, size=10**6),
            OrderMsg(action=op.ADD_SYMBOL, sid=1),
            # two bids at 50; a sell for exactly the first bid's size
            OrderMsg(action=op.BUY, oid=10, aid=1, sid=1, price=50,
                     size=4),
            OrderMsg(action=op.BUY, oid=11, aid=1, sid=1, price=50,
                     size=3),
            OrderMsg(action=op.SELL, oid=12, aid=2, sid=1, price=45,
                     size=4),
            # and the BUY-side ghost: asks at 55, buy exactly consumes
            OrderMsg(action=op.SELL, oid=13, aid=2, sid=1, price=55,
                     size=2),
            OrderMsg(action=op.SELL, oid=14, aid=2, sid=1, price=55,
                     size=9),
            OrderMsg(action=op.BUY, oid=15, aid=1, sid=1, price=60,
                     size=2)]
    ses, ora = assert_java_parity(msgs)
    # the sell at 45 must have produced a zero-size trade pair
    flat = [l for ls in ses.process_wire([]) for l in ls]  # no-op
    del flat


def test_java_q1_merged_sid0_book():
    """sid=0: -0 == 0, so buys and sells share one book — buys match
    against resting buys (the reference's own behavior)."""
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=10**6),
            OrderMsg(action=op.CREATE_BALANCE, aid=2),
            OrderMsg(action=op.TRANSFER, aid=2, size=10**6),
            OrderMsg(action=op.ADD_SYMBOL, sid=0),
            OrderMsg(action=op.BUY, oid=10, aid=1, sid=0, price=50,
                     size=5),
            # a second buy at a lower price CROSSES the resting buy
            OrderMsg(action=op.BUY, oid=11, aid=2, sid=0, price=50,
                     size=3),
            OrderMsg(action=op.SELL, oid=12, aid=2, sid=0, price=40,
                     size=4),
            OrderMsg(action=op.CANCEL, oid=10, aid=1)]
    assert_java_parity(msgs)


def test_java_q11_value_as_key():
    """Repeated fills on one (aid, sid): the second fill writes a
    garbage (amount, available) key while the real key stays stale —
    and margin netting reads the stale available (Q11)."""
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=1),
            OrderMsg(action=op.TRANSFER, aid=1, size=10**6),
            OrderMsg(action=op.CREATE_BALANCE, aid=2),
            OrderMsg(action=op.TRANSFER, aid=2, size=10**6),
            OrderMsg(action=op.ADD_SYMBOL, sid=1)]
    oid = 100
    for k in range(10):
        msgs.append(OrderMsg(action=op.BUY, oid=oid, aid=1, sid=1,
                             price=50, size=2 + k))
        oid += 1
        msgs.append(OrderMsg(action=op.SELL, oid=oid, aid=2, sid=1,
                             price=45, size=1 + k))
        oid += 1
    ses, ora = assert_java_parity(msgs)
    # the oracle must have accumulated garbage-keyed entries
    garbage = [k for k in ora.positions if k not in
               {(1, 1), (2, 1)}]
    assert garbage, "workload failed to exercise Q11"


@pytest.mark.slow
def test_java_harness_parity():
    """The stock harness distribution (incl. Q5 payouts-as-cancels and
    sid=0 trading) byte-exact vs the java oracle."""
    msgs = harness_stream(1500, seed=3)
    assert_java_parity(msgs, SQ.SeqConfig(
        lanes=8, slots=256, accounts=128, max_fills=64, batch=256,
        pos_cap=1 << 13, fill_cap=1 << 14, probe_max=16, compat="java",
        hbm_books=True))


def test_java_unsupported_ops_raise():
    ses = SeqSession(JCFG)
    with pytest.raises(UnsupportedJavaOp):
        ses.process_wire([OrderMsg(action=op.PAYOUT, sid=1, size=97)])
    with pytest.raises(UnsupportedJavaOp):
        ses.process_wire([OrderMsg(action=op.ADD_SYMBOL, sid=-3)])


def test_java_seq_service(tmp_path):
    """kme-serve's engine='seq' + compat='java': the full service loop
    byte-exact vs the java oracle on the stock harness shape."""
    from kme_tpu.bridge.broker import InProcessBroker
    from kme_tpu.bridge.consume import consume_lines
    from kme_tpu.bridge.provision import provision
    from kme_tpu.bridge.service import MatchService
    from kme_tpu.wire import dumps_order

    msgs = harness_stream(400, seed=5)
    ora = OracleEngine("java")
    want = []
    for m in msgs:
        for r in ora.process(m.copy()):
            want.append(r.wire())
    b = InProcessBroker()
    provision(b)
    for m in msgs:
        b.produce("MatchIn", None, dumps_order(m))
    svc = MatchService(b, engine="seq", compat="java", batch=64,
                       symbols=8, accounts=128, slots=256, max_fills=64)
    assert svc.run(max_messages=len(msgs)) == len(msgs)
    got = list(consume_lines(b, follow=False))
    assert got == want
    # durable java serving works since round 5 (seqjava snapshots,
    # runtime/javasnap.py) — the constructor must ACCEPT a checkpoint
    # dir (kill/resume itself is covered by
    # tests/test_checkpoint.py::test_seqjava_service_kill_resume)
    svc2 = MatchService(b, engine="seq", compat="java", symbols=8,
                        accounts=128, slots=256, max_fills=64,
                        checkpoint_dir=str(tmp_path))
    assert svc2 is not None


def test_java_seq_service_degrades_on_barrier(tmp_path):
    """COMPAT.md closure: a java-mode stream that hits a REAL barrier
    (PAYOUT opcode — outside the device surface, Q3-Q6) mid-stream.
    The service converts the seq session's state to the native engine
    (runtime/javasnap.py) and continues there; the full MatchOut
    stream is byte-exact vs an uninterrupted java-oracle run."""
    from kme_tpu.bridge.broker import InProcessBroker
    from kme_tpu.bridge.consume import consume_lines
    from kme_tpu.bridge.provision import provision
    from kme_tpu.bridge.service import MatchService
    from kme_tpu.wire import OrderMsg, dumps_order
    from kme_tpu import opcodes as op

    msgs = harness_stream(600, seed=21)
    # inject a REAL payout barrier (the harness's own payouts carry the
    # CANCEL opcode, Q5) on an ABSENT book — a payout on a non-empty
    # book is a ReferenceHang (Q4), which no engine may survive; the
    # absent-book payout is the processable barrier shape
    barrier = OrderMsg(action=op.PAYOUT, sid=99, size=3)
    mixed = msgs[:400] + [barrier] + msgs[400:]
    ora = OracleEngine("java")
    want = [r.wire() for m in mixed for r in ora.process(m.copy())]

    b = InProcessBroker()
    provision(b)
    for m in mixed:
        b.produce("MatchIn", None, dumps_order(m))
    svc = MatchService(b, engine="seq", compat="java", batch=64,
                       symbols=8, accounts=128, slots=256, max_fills=64)
    assert svc.run(max_messages=len(mixed)) == len(mixed)
    assert svc._native is not None and svc._session is None, \
        "service should have degraded to the native engine"
    got = list(consume_lines(b, follow=False))
    assert got == want
