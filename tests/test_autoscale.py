"""Autoscaling policy (bridge/autoscale.py): a pure state machine.

Pins determinism (same trace → byte-identical decisions, the
simulate_overload twin), the threefold hysteresis (dwell, watermark
gap, cooldown — the no-flap guarantees), and the power-of-two group
ladder with its min/max clamps.
"""

import json

import pytest

from kme_tpu.bridge.autoscale import (AutoscaleConfig,
                                      AutoscaleController,
                                      shard_imbalance,
                                      simulate_autoscale)

CFG = AutoscaleConfig(dwell=3, cooldown=4, high_lag=48.0, low_lag=4.0)


def _hot(groups=2):
    return {"groups": groups, "lags": [100.0] * groups}


def _cold(groups=2):
    return {"groups": groups, "lags": [0.0] * groups}


# -- config + imbalance ------------------------------------------------


def test_config_validation():
    with pytest.raises(ValueError):
        AutoscaleConfig(min_groups=0)
    with pytest.raises(ValueError):
        AutoscaleConfig(min_groups=4, max_groups=2)
    with pytest.raises(ValueError):
        AutoscaleConfig(high_lag=4.0, low_lag=4.0)  # no watermark gap
    with pytest.raises(ValueError):
        AutoscaleConfig(dwell=0)
    with pytest.raises(ValueError):
        AutoscaleConfig(cooldown=-1)


def test_shard_imbalance():
    assert shard_imbalance([]) == 1.0
    assert shard_imbalance([0.0, 0.0]) == 1.0   # mean 0 guard
    assert shard_imbalance([5.0, 5.0]) == 1.0
    assert shard_imbalance([30.0, 10.0]) == pytest.approx(1.5)


# -- hysteresis --------------------------------------------------------


def test_dwell_delays_the_split():
    ctl = AutoscaleController(CFG)
    assert ctl.observe(2, [100.0, 100.0]) is None
    assert ctl.observe(2, [100.0, 100.0]) is None
    d = ctl.observe(2, [100.0, 100.0])
    assert d is not None and d["action"] == "split"
    assert d["from"] == 2 and d["to"] == 4 and d["streak"] == 3


def test_streak_resets_on_a_calm_tick():
    ctl = AutoscaleController(CFG)
    ctl.observe(2, [100.0, 100.0])
    ctl.observe(2, [100.0, 100.0])
    ctl.observe(2, [10.0, 10.0])    # neither hot nor cold: resets both
    assert ctl.observe(2, [100.0, 100.0]) is None
    assert ctl.observe(2, [100.0, 100.0]) is None
    assert ctl.observe(2, [100.0, 100.0])["action"] == "split"


def test_cooldown_swallows_ticks():
    ctl = AutoscaleController(CFG)
    for _ in range(3):
        d = ctl.observe(2, [100.0, 100.0])
    assert d["action"] == "split"
    # still red-hot, but the reshard in flight must not be
    # second-guessed: cooldown ticks propose nothing (the streak keeps
    # accumulating, so a STILL-hot system escalates right after)
    for _ in range(CFG.cooldown):
        assert ctl.observe(4, [100.0] * 4) is None
    d = ctl.observe(4, [100.0] * 4)
    assert d is not None and d["to"] == 8


def test_overload_state_counts_as_hot():
    ctl = AutoscaleController(CFG)
    for _ in range(2):
        assert ctl.observe(2, [1.0, 1.0], overload_states=[1, 0]) is None
    d = ctl.observe(2, [1.0, 1.0], overload_states=[0, 2])
    assert d is not None and d["action"] == "split" and d["overloaded"]


def test_imbalance_counts_as_hot():
    ctl = AutoscaleController(CFG)
    lags = [20.0, 0.0, 0.0, 0.0]  # below high_lag, imbalance 4.0
    assert shard_imbalance(lags) >= CFG.high_imbalance
    for _ in range(2):
        assert ctl.observe(4, lags) is None
    assert ctl.observe(4, lags)["action"] == "split"


def test_merge_on_cold_streak_and_min_clamp():
    ctl = AutoscaleController(CFG)
    for _ in range(2):
        assert ctl.observe(2, [0.0, 0.0]) is None
    d = ctl.observe(2, [0.0, 0.0])
    assert d is not None and d["action"] == "merge" and d["to"] == 1
    for _ in range(CFG.cooldown):
        ctl.observe(1, [0.0])
    # at min_groups a cold streak proposes nothing
    for _ in range(6):
        assert ctl.observe(1, [0.0]) is None


def test_max_clamp():
    cfg = AutoscaleConfig(dwell=1, cooldown=0, max_groups=8)
    ctl = AutoscaleController(cfg)
    d = ctl.observe(6, [100.0] * 6)
    assert d["to"] == 8           # min(max, 2N)
    assert ctl.observe(8, [100.0] * 8) is None   # at the ceiling


# -- replay ------------------------------------------------------------


def _trace():
    t = []
    t.append(_hot(2))                       # first sample pins groups
    for _ in range(20):
        t.append({"lags": [100.0, 100.0], "overload": [1, 1]})
    for _ in range(20):
        t.append({"lags": [0.5] * 4})
    return t


def test_simulate_autoscale_deterministic():
    a = simulate_autoscale(_trace(), CFG)
    b = simulate_autoscale(_trace(), CFG)
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    assert a["decisions"], "trace must trigger at least one decision"
    # groups follow proposals during replay: the hot phase splits 2→4,
    # the cold phase merges back down
    actions = [d["action"] for d in a["decisions"]]
    assert actions[0] == "split"
    assert "merge" in actions
    assert a["final_groups"] <= 4


def test_simulate_requires_initial_groups():
    with pytest.raises(ValueError, match="groups"):
        simulate_autoscale([{"lags": [1.0]}])


def test_no_flapping_on_oscillating_trace():
    """A trace that alternates hot/cold every tick must produce ZERO
    decisions: dwell demands consecutive ticks of the same colour."""
    ctl = AutoscaleController(CFG)
    for i in range(40):
        lags = [100.0, 100.0] if i % 2 == 0 else [0.0, 0.0]
        assert ctl.observe(2, lags) is None
