"""Binary wire protocol + native front door (ISSUE r11).

The contract under test, layer by layer:

- frame codec: encode_frames/decode_frames round-trip, and the batch
  parser (native kme_parse_frames or the numpy fallback) agrees with
  the scalar authority column-for-column;
- acceptor: bridge/front.accept_frames routes every row exactly like
  the numpy accept_routes authority (and the scalar group functions),
  and its chained one-call plan equals sched.plan_batch's output;
- broker: produce_frames stores records byte-identical to a loop of
  produce() over the same stream — stamps, ats, dup suppression,
  admission classes and the admitted prefix under a mid-batch refusal;
- transport: the binary PRODUCE envelope and fetch_bin round-trip over
  a real socket, JSON and binary interleave on one connection, and the
  client's admission stamp survives a reconnect retry (the
  coordinated-omission fix).
"""

import threading
import time

import numpy as np
import pytest

from kme_tpu import faults, wire
from kme_tpu.bridge.broker import (BrokerError, BrokerOverload,
                                   InProcessBroker)
from kme_tpu.bridge.tcp import TcpBroker, serve_broker


def _msgs(n, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        out.append(wire.OrderMsg(
            action=int(rng.choice([0, 1, 2, 3, 4, 100, 101, 200])),
            oid=i + 1, aid=int(rng.integers(1, 64)),
            sid=int(rng.integers(-4, 9)),
            price=int(rng.integers(1, 1000)),
            size=int(rng.integers(1, 10)),
            next=None if i % 3 else i + 2,
            prev=None if i % 5 else -i))
    return out


def test_frame_roundtrip_and_batch_parity():
    msgs = _msgs(64)
    buf = wire.encode_frames(msgs)
    assert len(buf) == 64 * wire.FRAME_SIZE
    assert wire.decode_frames(buf) == msgs
    wb = wire.WireBatch.parse_frames(buf)
    for i, m in enumerate(msgs):
        assert (int(wb.action[i]), int(wb.oid[i]), int(wb.aid[i]),
                int(wb.sid[i]), int(wb.price[i]), int(wb.size[i])) == (
            m.action, m.oid, m.aid, m.sid, m.price, m.size)
        assert bool(wb.hnext[i]) == (m.next is not None)
        assert bool(wb.hprev[i]) == (m.prev is not None)


def test_frames_to_values_matches_canonical_order_json():
    """The broker stores canonical order_json for every frame — the
    encoding must be invisible to the durable log and the oracle."""
    msgs = _msgs(48, seed=3)
    _wb, values = wire.frames_to_values(wire.encode_frames(msgs))
    assert values == [wire.dumps_order(m) for m in msgs]


def test_accept_frames_routes_like_numpy_authority():
    from kme_tpu.bridge import front

    msgs = _msgs(200, seed=1)
    buf = wire.encode_frames(msgs)
    for ngroups in (1, 2, 4, 7):
        wb, groups, plan = front.accept_frames(buf, ngroups)
        want = front.accept_routes(wb.action, wb.oid, wb.aid, wb.sid,
                                   ngroups)
        assert groups.dtype == np.int32
        assert np.array_equal(groups, want), f"ngroups={ngroups}"
        assert plan is None
        # scalar authority spot-check over every row
        for i, m in enumerate(msgs):
            if m.action in (100, 101):
                exp = front.account_group(m.aid, ngroups)
            elif m.action == 4:
                exp = front.group_of(m.oid, ngroups, front.SALT_SYMBOL)
            else:
                exp = front.symbol_group(m.sid, ngroups)
            assert int(groups[i]) == exp, f"row {i} action {m.action}"


def test_accept_frames_one_call_plan_matches_plan_batch():
    from kme_tpu.bridge import front
    from kme_tpu.native import load_library
    from kme_tpu.native import sched
    from kme_tpu.runtime.seqsession import NativeSeqRouter

    lib = load_library()
    if lib is None:
        pytest.skip("native library unavailable")
    msgs = [m for m in _msgs(100, seed=2)
            if m.action in (0, 1, 2, 3, 4)]    # router-plannable ops
    buf = wire.encode_frames(msgs)
    B = 16
    r1 = NativeSeqRouter(64, 512, lib)
    r2 = NativeSeqRouter(64, 512, lib)
    wb, _groups, plan = front.accept_frames(buf, 1, router=r1, B=B)
    want = sched.plan_batch(r2, wire.WireBatch.parse_frames(buf), B)
    assert plan is not None and want is not None
    cols_a, rej_a, stacked_a, cnts_a, k_a = plan
    cols_b, rej_b, stacked_b, cnts_b, k_b = want
    assert k_a == k_b
    assert rej_a == rej_b
    assert cnts_a == cnts_b
    assert set(stacked_a) == set(stacked_b)
    for name in stacked_a:
        assert np.array_equal(stacked_a[name], stacked_b[name]), name
    assert set(cols_a) == set(cols_b)
    for name in cols_a:
        assert np.array_equal(cols_a[name], cols_b[name]), name


def test_produce_frames_parity_with_produce_loop():
    msgs = _msgs(40, seed=4)
    buf = wire.encode_frames(msgs)
    b1 = InProcessBroker()
    b1.create_topic("in")
    b2 = InProcessBroker()
    b2.create_topic("in")
    n, last = b1.produce_frames("in", "K", buf, epoch=3, seq0=100,
                                ats=777)
    for i, m in enumerate(msgs):
        b2.produce("in", "K", wire.dumps_order(m), epoch=3,
                   out_seq=100 + i, ats=777)
    assert (n, last) == (40, 39)
    rows = lambda b: [(r.offset, r.key, r.value, r.epoch, r.out_seq,
                       r.ats) for r in b.fetch("in", 0, 100)]
    assert rows(b1) == rows(b2)
    assert b1.wire_binary_records == 40
    assert b1.wire_parse_ns > 0
    # replaying the same (epoch, seq0) batch is fully dup-suppressed,
    # mirroring produce() returning -1 for a suppressed record
    n2, last2 = b1.produce_frames("in", "K", buf, epoch=3, seq0=100)
    assert (n2, last2) == (0, -1)
    assert b1.end_offset("in") == 40


def test_produce_frames_durable_log_identical(tmp_path):
    """The durable rows a binary batch writes are byte-identical to the
    JSON path's — reload proves it."""
    msgs = _msgs(16, seed=5)
    buf = wire.encode_frames(msgs)
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    b1 = InProcessBroker(persist_dir=d1)
    b1.create_topic("in")
    b1.produce_frames("in", None, buf, epoch=1, seq0=0)
    b2 = InProcessBroker(persist_dir=d2)
    b2.create_topic("in")
    for i, m in enumerate(msgs):
        b2.produce("in", None, wire.dumps_order(m), epoch=1, out_seq=i)
    b1.sync()
    b2.sync()
    log1 = (tmp_path / "a" / "in.log").read_bytes()
    log2 = (tmp_path / "b" / "in.log").read_bytes()
    assert log1 == log2
    rb = InProcessBroker(persist_dir=d1)
    rb.create_topic("in")
    assert [r.value for r in rb.fetch("in", 0, 100)] == [
        wire.dumps_order(m) for m in msgs]


def test_produce_frames_mid_batch_refusal_keeps_admitted_prefix():
    msgs = _msgs(20, seed=6)
    buf = wire.encode_frames(msgs)
    b = InProcessBroker(max_lag=5)
    b.create_topic("in")
    b.commit("in", 0)       # arm bounded ingress
    with pytest.raises(BrokerOverload) as e:
        b.produce_frames("in", None, buf)
    assert e.value.admitted == 5
    assert b.end_offset("in") == 5
    # the resume contract: back off, then continue from the prefix
    b.commit("in", 5)
    with pytest.raises(BrokerOverload) as e2:
        b.produce_frames("in", None,
                         buf[e.value.admitted * wire.FRAME_SIZE:])
    assert e2.value.admitted == 5 and b.end_offset("in") == 10


def test_produce_frames_admission_classes_match_json_path():
    """classify_actions (columnar) must agree with classify_produce
    (per-JSON-record) for every opcode, so the overload controller
    sheds identically whichever encoding carried the record."""
    from kme_tpu.bridge.broker import (classify_actions,
                                       classify_produce)

    msgs = _msgs(200, seed=7)
    acts = np.array([m.action for m in msgs], np.int64)
    want = [classify_produce(wire.dumps_order(m))[0] for m in msgs]
    assert classify_actions(acts).tolist() == want


def test_tcp_binary_produce_and_fetch_bin_roundtrip():
    msgs = _msgs(40, seed=8)
    buf = wire.encode_frames(msgs)
    srv, broker = serve_broker("127.0.0.1", 0)
    broker.create_topic("t")
    cli = TcpBroker(*srv.server_address[:2])
    try:
        n, last = cli.produce_frames("t", "K", buf, epoch=1, seq0=0)
        assert (n, last) == (40, 39)
        ra = cli.fetch("t", 0, 100)
        rb = cli.fetch_bin("t", 0, 100)
        assert [(r.offset, r.key, r.value, r.epoch, r.out_seq, r.ats)
                for r in ra] == \
               [(r.offset, r.key, r.value, r.epoch, r.out_seq, r.ats)
                for r in rb]
        assert [r.value for r in rb] == [wire.dumps_order(m)
                                        for m in msgs]
        # JSON and binary interleave on the same connection
        off = cli.produce("t", None, wire.dumps_order(msgs[0]))
        assert off == 40
        n2, _ = cli.produce_frames("t", None, buf[:wire.FRAME_SIZE])
        assert n2 == 1
    finally:
        cli.close()
        srv.shutdown()


def test_tcp_overload_reply_carries_admitted():
    msgs = _msgs(20, seed=9)
    buf = wire.encode_frames(msgs)
    b = InProcessBroker(max_lag=5)
    srv, broker = serve_broker("127.0.0.1", 0, b)
    broker.create_topic("t")
    broker.commit("t", 0)
    cli = TcpBroker(*srv.server_address[:2])
    try:
        with pytest.raises(BrokerOverload) as e:
            cli.produce_frames("t", None, buf)
        assert e.value.admitted == 5
        assert broker.end_offset("t") == 5
    finally:
        cli.close()
        srv.shutdown()


def test_ats_survives_reconnect_retry():
    """The coordinated-omission fix: a produce that dies on a transport
    fault keeps its original admission stamp when the caller retries
    the same record over the reconnected socket — for both the JSON
    and the binary path. A different record gets a fresh stamp."""
    msgs = _msgs(4, seed=10)
    buf = wire.encode_frames(msgs)
    for binary in (False, True):
        srv, broker = serve_broker("127.0.0.1", 0)
        broker.create_topic("t")
        cli = TcpBroker(*srv.server_address[:2])
        faults.configure("tcp.disconnect:n=1")
        try:
            send = ((lambda: cli.produce_frames("t", None, buf))
                    if binary else
                    (lambda: cli.produce("t", None,
                                         wire.dumps_order(msgs[0]))))
            with pytest.raises(BrokerError):
                send()
            kept = cli._pending[1]
            time.sleep(0.02)
            send()      # same record(s): stamp must be reused
            assert cli._pending is None
            recs = cli.fetch("t", 0, 10)
            assert all(r.ats == kept for r in recs), (
                binary, [r.ats for r in recs], kept)
            # a different record restarts the clock
            off = cli.produce("t", None, wire.dumps_order(msgs[1]))
            assert cli.fetch("t", off, 1)[0].ats > kept
        finally:
            faults.clear()
            cli.close()
            srv.shutdown()


def test_wire_gauges_published():
    """kme-serve's telemetry surface: wire_binary_frac and
    parse_ns_per_msg ride _publish_batch off the broker counters, and
    kme-top renders the wire row when the gauge is present."""
    from kme_tpu.telemetry import top

    b = InProcessBroker()
    b.create_topic("in")
    b.commit("in", 0)       # admission-bounded: JSON produces count
    b.produce("in", None, wire.dumps_order(_msgs(1)[0]))
    b.produce_frames("in", None, wire.encode_frames(_msgs(3, seed=11)),
                     epoch=1, seq0=0)
    assert b.wire_json_records == 1 and b.wire_binary_records == 3
    frac = b.wire_binary_records / (b.wire_binary_records
                                    + b.wire_json_records)
    view = {
        "leader": {"ok": True, "metrics": {
            "gauges": {"wire_binary_frac": round(frac, 6),
                       "parse_ns_per_msg": 1234},
            "counters": {}, "latencies": {}}, "hb": {}},
        "standby": {"ok": False},
        "supervisor": None,
    }
    lines = top.render(top.build_view(view))
    wire_rows = [ln for ln in lines if "wire binary=" in ln]
    assert wire_rows and "75.0%" in wire_rows[0] \
        and "1,234ns/msg" in wire_rows[0]


def test_loadgen_connections_binary_exactly_once():
    """kme-loadgen --connections --binary against a served broker:
    every simulated client's records land exactly once (unique
    out_seq stamps, no gaps) and the report is written."""
    import json as _json

    from kme_tpu import cli as kcli
    from kme_tpu.bridge.service import TOPIC_IN

    srv, broker = serve_broker("127.0.0.1", 0)
    host, port = srv.server_address[:2]
    try:
        report = None
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            rp = td + "/report.json"
            rc = kcli.loadgen_main(
                ["--events", "600", "--broker", f"{host}:{port}",
                 "--connections", "100", "--binary", "--report", rp])
            assert rc == 0
            report = _json.load(open(rp))
        n = report["events"]
        assert report["produced"] == n == broker.end_offset(TOPIC_IN)
        recs = broker.fetch(TOPIC_IN, 0, 10_000)
        seqs = sorted(r.out_seq for r in recs)
        assert seqs == list(range(n))       # zero dup stamps, no gaps
        assert all(r.ats is not None for r in recs)
    finally:
        srv.shutdown()
