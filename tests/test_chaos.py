"""kme-chaos: the at-least-once stream verifier (pure logic, fast) and
a small end-to-end chaos run under the full fault schedule (slow)."""

import json
import os
import subprocess
import sys

import pytest

from kme_tpu.bridge.chaos import default_schedule, verify_stream

# oracle groups: message 0 -> [a0, a1], message 1 -> [b0], message 2 ->
# [] (a dropped/rejected-silent record), message 3 -> [d0, d1, d2]
G = [["a0", "a1"], ["b0"], [], ["d0", "d1", "d2"]]
FLAT = [ln for g in G for ln in g]


def test_verify_exact_stream_passes():
    ok, d = verify_stream(list(FLAT), G)
    assert ok
    assert d["replays"] == 0 and d["replayed_messages"] == 0
    assert d["messages"] == 4 and d["expected_lines"] == len(FLAT)


def test_verify_replay_from_snapshot_passes():
    # crash after message 1, resume from a snapshot at message 0:
    # messages 0..1 replay before the stream completes
    got = ["a0", "a1", "b0"] + FLAT
    ok, d = verify_stream(got, G)
    assert ok
    assert d["replays"] == 1 and d["replayed_messages"] == 2


def test_verify_partial_group_then_replay_passes():
    # crash MID-message-3 (one of three lines produced), resume from
    # message 1
    got = ["a0", "a1", "b0", "d0", "b0", "d0", "d1", "d2"]
    ok, d = verify_stream(got, G)
    assert ok
    assert d["replays"] == 1 and d["replayed_messages"] == 2


def test_verify_trailing_replay_passes():
    # crash after everything was produced but before the snapshot
    # caught up: the restart re-produces a tail
    got = FLAT + ["d0", "d1", "d2"]
    ok, d = verify_stream(got, G)
    assert ok and d["replays"] == 1


def test_verify_double_replay_passes():
    got = (["a0", "a1"]                 # crash after msg 0
           + ["a0", "a1", "b0"]        # replay, crash after msg 1
           + FLAT)                     # replay from 0, complete
    ok, d = verify_stream(got, G)
    assert ok and d["replays"] == 2 and d["replayed_messages"] == 3


def test_verify_rejects_divergence():
    bad = list(FLAT)
    bad[2] = "WRONG"
    ok, d = verify_stream(bad, G)
    assert not ok and "divergence" in d["error"]


def test_verify_rejects_missing_tail():
    ok, d = verify_stream(FLAT[:-1], G)
    assert not ok and "incomplete" in d["error"]


def test_verify_rejects_skipped_message():
    # message 1's output missing entirely: looks like a replay that
    # never completes group 1
    got = ["a0", "a1", "d0", "d1", "d2"]
    ok, _ = verify_stream(got, G)
    assert not ok


def test_verify_empty_inputs():
    ok, _ = verify_stream([], [])
    assert ok
    ok, _ = verify_stream([], [["x"]])
    assert not ok


def test_default_schedule_covers_required_fault_classes():
    sched = default_schedule(0, 1000, journal=True)
    for point in ("broker.produce", "broker.fetch", "tcp.partial",
                  "ckpt.torn", "ckpt.bitflip", "serve.kill",
                  "serve.stuck", "journal.torn"):
        assert point in sched
    assert "seed=0" in sched
    assert "serve.kill:at=500" in sched    # scales with the workload
    assert "journal.torn" not in default_schedule(0, 1000, journal=False)


@pytest.mark.slow
def test_chaos_end_to_end_byte_exact(tmp_path):
    """The acceptance run, scaled down: a seeded schedule covering
    broker I/O errors, torn + bit-flipped checkpoints, a SIGKILL at an
    exact offset and a stuck step() — the completed MatchOut stream
    must verify byte-exactly against the oracle with >= 1 automatic
    restart."""
    run_dir = str(tmp_path / "run")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "kme_tpu.cli", "chaos", "--seed", "0",
         "--events", "600", "--dir", run_dir, "--timeout", "180"],
        env=env, capture_output=True, text=True, timeout=300)
    sys.stderr.write(proc.stderr[-4000:])
    assert proc.returncode == 0, proc.stderr[-2000:]
    with open(os.path.join(run_dir, "chaos-report.json")) as f:
        report = json.load(f)
    assert report["ok"] and not report["failures"]
    assert report["restarts_total"] >= 1
    assert report["verify"]["replayed_messages"] >= 0
    fired_points = {k.split(".", 1)[1] for k in report["fault_fires"]}
    assert {"serve.kill", "ckpt.torn", "ckpt.bitflip"} <= fired_points
    assert report["recovery_seconds_max"] is not None
    # the flight recorder survived the crashes too
    assert os.path.exists(os.path.join(run_dir, "journal.jsonl"))


# ---------------------------------------------------------------------------
# failover scenario: schedule, exactly-once verifier, end to end


def _recs(*rows):
    """(value, epoch, out_seq) triples -> stamped MatchOut Records."""
    from kme_tpu.bridge.broker import Record

    return [Record(i, "OUT", v, e, s)
            for i, (v, e, s) in enumerate(rows)]


OUT_G = [["OUT a0", "OUT a1"], ["OUT b0"], [], ["OUT d0"]]


def test_failover_schedule_is_one_seeded_midstream_kill():
    from kme_tpu.bridge.chaos import failover_schedule

    sched = failover_schedule(3, 600)
    assert "seed=3" in sched
    assert "serve.kill:at=300" in sched
    # ONLY the kill: nothing else may muddy the failure fingerprint
    assert sched.count(";") == 1
    assert failover_schedule(0, 1) == "seed=0;serve.kill:at=1"


def test_verify_failover_passes_clean_two_epoch_stream():
    from kme_tpu.bridge.chaos import verify_failover

    ok, d = verify_failover(_recs(("a0", 1, 0), ("a1", 1, 1),
                                  ("b0", 2, 2), ("d0", 2, 3)), OUT_G)
    assert ok, d
    assert d["epochs"] == [1, 2]
    assert d["duplicates_in_log"] == 0


def test_verify_failover_rejects_duplicate_stamps_in_the_log():
    from kme_tpu.bridge.chaos import verify_failover

    ok, d = verify_failover(_recs(("a0", 1, 0), ("a1", 1, 1),
                                  ("a1", 1, 1),      # escaped dedup
                                  ("b0", 2, 2), ("d0", 2, 3)), OUT_G)
    assert not ok
    assert d["duplicates_in_log"] == 1
    assert "duplicate produce stamp" in d["error"]


def test_verify_failover_rejects_divergence():
    from kme_tpu.bridge.chaos import verify_failover

    ok, d = verify_failover(_recs(("a0", 1, 0), ("aX", 1, 1),
                                  ("b0", 2, 2), ("d0", 2, 3)), OUT_G)
    assert not ok
    assert "diverges" in d["error"]


def test_verify_failover_requires_a_promoted_epoch():
    from kme_tpu.bridge.chaos import verify_failover

    ok, d = verify_failover(_recs(("a0", 1, 0), ("a1", 1, 1),
                                  ("b0", 1, 2), ("d0", 1, 3)), OUT_G)
    assert not ok
    assert "failover never happened" in d["error"]
    assert d["epochs"] == [1]


@pytest.mark.slow
def test_chaos_failover_end_to_end_exactly_once(tmp_path):
    """The failover acceptance run: a hot standby follows the leader,
    the leader is SIGKILLed at a seeded offset, the supervisor promotes
    the replica, and the durable MatchOut stream stays exactly-once
    (byte-exact after dedup, dedup actually exercised, zombie produces
    fenced) with the promotion under the failover bound."""
    run_dir = str(tmp_path / "run")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "kme_tpu.cli", "chaos",
         "--scenario", "failover", "--seed", "0", "--events", "600",
         "--engine", "oracle", "--checkpoint-every", "60",
         "--dir", run_dir, "--timeout", "120"],
        env=env, capture_output=True, text=True, timeout=300)
    sys.stderr.write(proc.stderr[-4000:])
    assert proc.returncode == 0, proc.stderr[-2000:]
    with open(os.path.join(run_dir, "chaos-report.json")) as f:
        report = json.load(f)
    assert report["ok"] and not report["failures"]
    fo = report["failover"]
    assert fo["promotions"] >= 1
    assert fo["failover_seconds"] and max(fo["failover_seconds"]) <= 2.0
    assert fo["dup_suppressed_total"] > 0
    assert fo["stale_epoch_fenced"] is True
    assert fo["leader_epoch"] >= 2
    assert report["verify"]["epochs"][-1] >= 2
    assert report["verify"]["duplicates_in_log"] == 0
