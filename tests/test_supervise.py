"""Failure detection + supervised restart (kme-supervise).

The reference delegates liveness to Kafka Streams group membership:
a dead instance is detected by missed heartbeats and its work resumes
elsewhere from changelog state (KProcessor.java:59-60, library). Here
kme-supervise watches a heartbeat file and the child's exit status,
and relaunches kme-serve from its newest checkpoint + durable broker
logs. This test SIGKILLs the serve child mid-stream and requires the
completed MatchOut stream to be the documented at-least-once shape:
an uninterrupted prefix up to the crash plus a bit-exact replay from
the last snapshot offset.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from kme_tpu.bridge.broker import InProcessBroker
from kme_tpu.bridge.consume import consume_lines
from kme_tpu.bridge.tcp import TcpBroker
from kme_tpu.oracle import OracleEngine
from kme_tpu.wire import dumps_order
from kme_tpu.workload import harness_stream

TOPIC_IN = "MatchIn"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_broker(port: int, timeout: float = 90.0) -> TcpBroker:
    t0 = time.time()
    while True:
        try:
            b = TcpBroker("127.0.0.1", port)
            b.end_offset(TOPIC_IN)
            return b
        except Exception:
            if time.time() - t0 > timeout:
                raise
            time.sleep(0.2)


@pytest.mark.slow
def test_supervised_kill9_resume_byte_exact(tmp_path):
    msgs = harness_stream(400, seed=41, num_symbols=4, num_accounts=8,
                          payout_opcode_bug=False, validate=True)
    per_msg = []
    ora = OracleEngine("fixed", book_slots=64, max_fills=32)
    for m in msgs:
        per_msg.append([r.wire() for r in ora.process(m.copy())])
    flat = [ln for lines in per_msg for ln in lines]

    ck = str(tmp_path / "root")
    os.makedirs(ck)
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # keep the serve children off the TPU claim path (see
    # test_multihost.py: the axon sitecustomize registers the chip at
    # interpreter startup when PALLAS_AXON_POOL_IPS is set)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    sup = subprocess.Popen(
        [sys.executable, "-m", "kme_tpu.bridge.supervise",
         "--checkpoint-dir", ck, "--stale-after", "15",
         "--max-restarts", "3", "--grace", "30", "--",
         "--listen", f"127.0.0.1:{port}", "--auto-provision",
         "--engine", "oracle", "--batch", "20",
         "--checkpoint-every", "60", "--symbols", "8", "--accounts", "16",
         "--slots", "64", "--max-fills", "32",
         "--idle-exit", "6", "--health-every", "0.2"],
        env=env, stderr=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    hb = os.path.join(ck, "serve.health")
    try:
        broker = _wait_broker(port)
        for m in msgs:
            broker.produce(TOPIC_IN, None, dumps_order(m))

        # wait until the engine is past at least one checkpoint interval
        t0 = time.time()
        child_pid = None
        while True:
            try:
                with open(hb) as f:
                    h = json.load(f)
                if h["offset"] >= 100:
                    child_pid = h["pid"]
                    break
            except (OSError, ValueError):
                pass
            assert time.time() - t0 < 60, "engine made no progress"
            time.sleep(0.1)

        os.kill(child_pid, signal.SIGKILL)     # the failure

        # the supervisor must detect, restart, and the stream must
        # complete; serve idle-exits cleanly -> supervisor exits 0
        serr = ""
        try:
            _, serr = sup.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            sup.kill()
            _, serr = sup.communicate()
            pytest.fail(f"supervisor did not finish\n{serr[-3000:]}")
        assert sup.returncode == 0, serr[-3000:]
        assert "FAILURE DETECTED" in serr
        assert "restart 1/" in serr
    finally:
        if sup.poll() is None:
            sup.kill()

    # read the completed stream back from the durable broker logs
    b = InProcessBroker(persist_dir=os.path.join(ck, "broker-log"))
    got = list(consume_lines(b, follow=False))
    # at-least-once shape: flat(per_msg[:K]) + flat(per_msg[S:]) for the
    # crash point K and snapshot offset S (a checkpoint-every multiple,
    # S <= K <= len(msgs))
    n = len(msgs)
    lens = [len(x) for x in per_msg]
    starts = [0]
    for ln in lens:
        starts.append(starts[-1] + ln)
    okshape = False
    for S in range(0, n + 1):  # checkpoint offsets need not be
        # checkpoint_every multiples (partial fetches shift them)
        tail = [ln for lines in per_msg[S:] for ln in lines]
        if len(got) < len(tail) or got[len(got) - len(tail):] != tail:
            continue
        head_len = len(got) - len(tail)
        for K in range(S, n + 1):
            if starts[K] == head_len:
                okshape = got[:head_len] == flat[:head_len]
                break
        if okshape:
            break
    assert okshape, (
        f"stream is not an at-least-once prefix+replay composition "
        f"({len(got)} lines)")


@pytest.mark.slow
def test_supervised_stall_restart_byte_exact(tmp_path):
    """The HANG branch: the serve loop freezes mid-stream (tick stops
    advancing) while the heartbeat THREAD stays alive — process-exit
    and stale-mtime detection cannot fire. The supervisor must detect
    the frozen tick (--stall-after), restart from the newest
    checkpoint, and the completed stream must be the at-least-once
    prefix+replay shape, byte-exact. Reference analog: Streams
    rebalancing away from a wedged instance, KProcessor.java:59-60."""
    msgs = harness_stream(400, seed=43, num_symbols=4, num_accounts=8,
                          payout_opcode_bug=False, validate=True)
    per_msg = []
    ora = OracleEngine("fixed", book_slots=64, max_fills=32)
    for m in msgs:
        per_msg.append([r.wire() for r in ora.process(m.copy())])

    ck = str(tmp_path / "root")
    os.makedirs(ck)
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # stall exactly once, after ~150 messages (past >= 1 checkpoint);
    # the hook only arms under KME_TEST_HOOKS=1 (production safety)
    env["KME_TEST_HOOKS"] = "1"
    env["KME_TEST_STALL_ONCE"] = str(tmp_path / "stalled.flag")
    env["KME_TEST_STALL_AT"] = "150"
    sup = subprocess.Popen(
        [sys.executable, "-m", "kme_tpu.bridge.supervise",
         "--checkpoint-dir", ck,
         # the heartbeat stays FRESH during the stall: only the tick
         # branch may fire (stale-after is set far beyond the test)
         "--stale-after", "120", "--stall-after", "4",
         "--max-restarts", "3", "--grace", "30", "--",
         "--listen", f"127.0.0.1:{port}", "--auto-provision",
         "--engine", "oracle", "--batch", "20",
         "--checkpoint-every", "60", "--symbols", "8", "--accounts", "16",
         "--slots", "64", "--max-fills", "32",
         "--idle-exit", "6", "--health-every", "0.2"],
        env=env, stderr=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        broker = _wait_broker(port)
        for m in msgs:
            broker.produce(TOPIC_IN, None, dumps_order(m))
        serr = ""
        try:
            _, serr = sup.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            sup.kill()
            _, serr = sup.communicate()
            pytest.fail(f"supervisor did not finish\n{serr[-3000:]}")
        assert sup.returncode == 0, serr[-3000:]
        assert "serve loop stalled" in serr, serr[-3000:]
        assert "restart 1/" in serr
    finally:
        if sup.poll() is None:
            sup.kill()

    b = InProcessBroker(persist_dir=os.path.join(ck, "broker-log"))
    got = list(consume_lines(b, follow=False))
    n = len(msgs)
    okshape = False
    for S in range(0, n + 1):
        tail = [ln for lines in per_msg[S:] for ln in lines]
        if len(got) < len(tail) or got[len(got) - len(tail):] != tail:
            continue
        head = got[:len(got) - len(tail)]
        want_prefix = [ln for lines in per_msg for ln in lines]
        if head == want_prefix[:len(head)]:
            okshape = True
            break
    assert okshape, "stream is not the at-least-once prefix+replay shape"
