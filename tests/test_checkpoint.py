"""Checkpoint / resume + fault injection.

The durability contract (SURVEY.md §5, replacing the reference's
RocksDB+changelog restore, KProcessor.java:30-49): kill the engine
mid-stream, resume from the snapshot, and the continuation is
bit-identical to an uninterrupted run — with at-least-once replay of
the tail after the last snapshot, exactly like the reference (EOS is
commented out at KProcessor.java:29).
"""

import os

import pytest

from kme_tpu.bridge.broker import InProcessBroker
from kme_tpu.bridge.consume import consume_lines
from kme_tpu.bridge.provision import provision
from kme_tpu.bridge.service import TOPIC_IN, MatchService
from kme_tpu.engine.lanes import LaneConfig
from kme_tpu.oracle import OracleEngine
from kme_tpu.runtime import checkpoint as ck
from kme_tpu.runtime.session import LaneSession
from kme_tpu.wire import dumps_order
from kme_tpu.workload import harness_stream, zipf_symbol_stream

CFG = LaneConfig(lanes=8, slots=64, accounts=32, max_fills=32, steps=16)


def _stream(n=600, seed=21):
    return zipf_symbol_stream(n, num_symbols=8, num_accounts=24, seed=seed,
                              zipf_a=1.0)


def test_session_kill_resume_bit_identical(tmp_path):
    """Kill the session after 300 of 600 messages; the resumed session's
    tail output and final state match the uninterrupted run exactly."""
    msgs = _stream()
    cut = 300

    full = LaneSession(CFG)
    want_lines = full.process_wire([m.copy() for m in msgs[:cut]])
    want_lines += full.process_wire([m.copy() for m in msgs[cut:]])
    want_state = full.export_state()

    ses = LaneSession(CFG)
    got_head = ses.process_wire([m.copy() for m in msgs[:cut]])
    ck.save_session(str(tmp_path), ses, offset=cut)
    del ses  # the crash

    resumed, offset = ck.load_session(str(tmp_path))
    assert offset == cut
    got_tail = resumed.process_wire([m.copy() for m in msgs[cut:]])
    assert got_head + got_tail == want_lines
    assert resumed.export_state() == want_state


def test_session_resume_across_width_configs(tmp_path):
    """Snapshots are canonical: a compact-width session's snapshot
    restores into a full-width session (and vice versa) bit-exactly."""
    msgs = _stream(400, seed=4)
    cut = 200

    full = LaneSession(CFG, width=0)
    want = full.process_wire([m.copy() for m in msgs])

    a = LaneSession(CFG, width=16)
    head = a.process_wire([m.copy() for m in msgs[:cut]])
    ck.save_session(str(tmp_path), a, offset=cut)
    _, meta = ck._load_file(ck.snapshot_path(str(tmp_path), cut))
    assert meta["width"] == 8  # clamped to cfg.lanes

    # restore the compact snapshot into a FULL-WIDTH session
    b, offset = ck.load_session(str(tmp_path), width=0)
    assert offset == cut and b.dev_cfg.width == 0
    tail = b.process_wire([m.copy() for m in msgs[cut:]])
    assert head + tail == want


def test_session_elastic_reshard_on_restore(tmp_path):
    """The rebalance analog (SURVEY.md §2.3): a single-device session's
    snapshot restores onto a 4-shard mesh (and back) mid-stream, and the
    continuation is bit-identical — symbol->shard reassignment is a
    checkpoint/restore cycle, replacing Kafka Streams' group rebalance +
    changelog restore."""
    cfg = LaneConfig(lanes=8, slots=64, accounts=32, max_fills=32, steps=16)
    msgs = _stream(600, seed=12)
    cut1, cut2 = 200, 400

    full = LaneSession(cfg)
    want = full.process_wire([m.copy() for m in msgs])
    want_state = full.export_state()

    a = LaneSession(cfg)  # 1 device, compact
    got = a.process_wire([m.copy() for m in msgs[:cut1]])
    ck.save_session(str(tmp_path), a, offset=cut1)

    b, off = ck.load_session(str(tmp_path), shards=4)  # scale OUT to 4
    assert off == cut1 and b.shards == 4
    got += b.process_wire([m.copy() for m in msgs[cut1:cut2]])
    ck.save_session(str(tmp_path), b, offset=cut2)

    c, off = ck.load_session(str(tmp_path), shards=1)  # scale back IN
    assert off == cut2 and c.shards == 1
    got += c.process_wire([m.copy() for m in msgs[cut2:]])

    assert got == want
    assert c.export_state() == want_state


def test_corrupt_latest_snapshot_falls_back(tmp_path):
    msgs = _stream(300, seed=9)
    ses = LaneSession(CFG)
    ses.process_wire([m.copy() for m in msgs[:100]])
    ck.save_session(str(tmp_path), ses, offset=100)
    ses.process_wire([m.copy() for m in msgs[100:200]])
    ck.save_session(str(tmp_path), ses, offset=200)
    # torn write of the newest snapshot
    with open(ck.snapshot_path(str(tmp_path), 200), "r+b") as f:
        f.truncate(100)
    resumed, offset = ck.load_session(str(tmp_path))
    assert offset == 100  # fell back to the previous good snapshot
    assert resumed is not None


def test_snapshot_requires_drained_fill_log(tmp_path):
    ses = LaneSession(CFG)
    ses.process_wire([m.copy() for m in _stream(50, seed=2)])
    import jax.numpy as jnp

    ses.state = dict(ses.state)
    ses.state["filloff"] = jnp.ones((1,), jnp.int64)
    with pytest.raises(ValueError, match="drained fill log"):
        ck.save_session(str(tmp_path), ses, offset=50)


def test_service_crash_resume_at_least_once(tmp_path):
    """Service-level fault injection: crash a checkpointing service
    mid-stream (after its last snapshot), restart it on the same broker
    and checkpoint dir. The tail after the snapshot replays (at-least-
    once) and every replayed record's output is bit-identical."""
    msgs = harness_stream(400, seed=13, num_symbols=4, num_accounts=8,
                          payout_opcode_bug=False, validate=True)
    per_msg = []
    ora = OracleEngine("fixed", book_slots=64, max_fills=32)
    for m in msgs:
        per_msg.append([r.wire() for r in ora.process(m.copy())])

    broker = InProcessBroker()
    provision(broker)
    for m in msgs:
        broker.produce(TOPIC_IN, None, dumps_order(m))

    kw = dict(engine="lanes", compat="fixed", batch=50, symbols=8,
              accounts=16, slots=64, max_fills=32,
              checkpoint_dir=str(tmp_path), checkpoint_every=100)
    svc = MatchService(broker, **kw)
    assert svc.run(max_messages=250) == 250  # snapshots at 100, 200
    assert svc._last_ckpt_offset == 200
    del svc  # crash: 50 records past the last snapshot

    svc2 = MatchService(broker, **kw)
    assert svc2.offset == 200  # resumed
    rest = len(msgs) - 200  # replays 200..end (at-least-once tail)
    assert svc2.run(max_messages=rest) == rest

    got = list(consume_lines(broker, follow=False))
    want = [ln for lines in per_msg[:250] for ln in lines]
    want += [ln for lines in per_msg[200:] for ln in lines]
    assert got == want


def test_native_engine_crash_resume(tmp_path):
    """The native quirk-exact engine's checkpoint: crash the service
    mid-stream, restart from the snapshot + durable broker log, and the
    quirk-exact java-mode stream completes byte-identically (with the
    documented at-least-once replay of the post-snapshot tail)."""
    nat = pytest.importorskip("kme_tpu.native.oracle")
    if not nat.native_available():
        pytest.skip("native library unavailable")
    msgs = harness_stream(400, seed=77)
    per_msg = []
    ora = OracleEngine("java")
    for m in msgs:
        per_msg.append([r.wire() for r in ora.process(m.copy())])

    log_dir = str(tmp_path / "broker-log")
    ck_dir = str(tmp_path / "ckpt")
    kw = dict(engine="native", compat="java", batch=50,
              checkpoint_dir=ck_dir, checkpoint_every=100)

    b1 = InProcessBroker(persist_dir=log_dir)
    provision(b1)
    for m in msgs:
        b1.produce(TOPIC_IN, None, dumps_order(m))
    svc1 = MatchService(b1, **kw)
    assert svc1.run(max_messages=150) == 150  # snapshot at 100
    del svc1, b1  # crash

    b2 = InProcessBroker(persist_dir=log_dir)
    svc2 = MatchService(b2, **kw)
    assert svc2.offset == 100
    rest = len(msgs) - 100
    assert svc2.run(max_messages=rest) == rest

    got = list(consume_lines(b2, follow=False))
    want = [ln for lines in per_msg[:150] for ln in lines]
    want += [ln for lines in per_msg[100:] for ln in lines]
    assert got == want


def test_broker_log_persistence_and_torn_tail(tmp_path):
    """The broker's append-only topic logs survive a restart; a torn
    trailing line (crash mid-append) is dropped on reload."""
    d = str(tmp_path)
    b1 = InProcessBroker(persist_dir=d)
    provision(b1)
    b1.produce(TOPIC_IN, None, '{"action":100,"aid":1}')
    b1.produce(TOPIC_IN, "k", '{"action":101,"aid":1,"size":5}')

    b2 = InProcessBroker(persist_dir=d)  # restart
    recs = b2.fetch(TOPIC_IN, 0)
    assert [(r.offset, r.key, r.value) for r in recs] == [
        (0, None, '{"action":100,"aid":1}'),
        (1, "k", '{"action":101,"aid":1,"size":5}')]
    assert b2.produce(TOPIC_IN, None, "x") == 2  # offsets continue

    with open(tmp_path / f"{TOPIC_IN}.log", "a", encoding="utf-8") as f:
        f.write('["k", "torn')  # no newline: crash mid-append
    with open(tmp_path / f"{TOPIC_IN}.log", "rb") as f:
        pre_torn = f.read()
    b3 = InProcessBroker(persist_dir=d)
    assert b3.end_offset(TOPIC_IN) == 3  # torn tail dropped
    # the repair is a TRUNCATE at the torn byte offset — committed
    # records are never rewritten (crash during a full rewrite would
    # lose them)
    with open(tmp_path / f"{TOPIC_IN}.log", "rb") as f:
        assert f.read() == pre_torn[:pre_torn.rfind(b"\n") + 1]


def test_broker_log_corruption_refuses_load(tmp_path):
    """Any undecodable newline-TERMINATED line — interior or final — is
    corruption of committed data (produce appends one whole line per
    record; partial writes are prefixes, so a torn append can never have
    its newline): the broker refuses to load rather than silently
    truncating committed records a checkpoint offset may still address."""
    import pytest

    from kme_tpu.bridge.broker import BrokerError

    d = str(tmp_path)
    b1 = InProcessBroker(persist_dir=d)
    provision(b1)
    for i in range(3):
        b1.produce(TOPIC_IN, None, f'{{"action":100,"aid":{i}}}')
    path = tmp_path / f"{TOPIC_IN}.log"
    pristine = path.read_bytes()
    lines = pristine.splitlines(keepends=True)
    path.write_bytes(b"".join([lines[0], b'NOT JSON\n'] + lines[2:]))
    with pytest.raises(BrokerError, match="corrupt record"):
        InProcessBroker(persist_dir=d)
    # newline-terminated garbage FINAL line: still committed-data
    # corruption, not a repairable torn tail
    path.write_bytes(b"".join(lines[:2] + [b'NOT JSON\n']))
    with pytest.raises(BrokerError, match="corrupt record"):
        InProcessBroker(persist_dir=d)


def test_broker_sync_and_consume_waits_for_topic(tmp_path):
    """broker.sync() fsyncs the topic logs (checkpoint calls it before
    committing an offset); consume_lines with follow=True polls for a
    not-yet-provisioned MatchOut instead of crashing."""
    from kme_tpu.bridge.consume import consume_lines

    d = str(tmp_path)
    b = InProcessBroker(persist_dir=d)
    provision(b)
    b.produce(TOPIC_IN, None, '{"action":100,"aid":1}')
    b.sync()  # must not raise; records durable
    assert InProcessBroker(persist_dir=d).end_offset(TOPIC_IN) == 1

    b2 = InProcessBroker()  # nothing provisioned: MatchOut missing
    # follow=False propagates (fail fast for one-shot reads)
    import pytest

    from kme_tpu.bridge.broker import BrokerError

    with pytest.raises(BrokerError):
        list(consume_lines(b2, follow=False))
    # follow=True + idle_exit polls, then exits cleanly when the topic
    # never appears
    assert list(consume_lines(b2, follow=True, poll_timeout=0.02,
                              idle_exit=0.1)) == []
    # and picks records up once the topic exists
    provision(b2)
    b2.produce("MatchOut", "OUT", "x")
    assert list(consume_lines(b2, follow=True, poll_timeout=0.02,
                              idle_exit=0.2)) == ["OUT x"]


def test_service_crash_resume_full_process_restart(tmp_path):
    """The kme-serve topology: broker log AND engine snapshot both live
    on disk; a full restart (fresh broker + fresh service) resumes and
    the stream completes bit-identically (at-least-once tail replay)."""
    msgs = harness_stream(300, seed=31, num_symbols=4, num_accounts=8,
                          payout_opcode_bug=False, validate=True)
    per_msg = []
    ora = OracleEngine("fixed", book_slots=64, max_fills=32)
    for m in msgs:
        per_msg.append([r.wire() for r in ora.process(m.copy())])

    log_dir = str(tmp_path / "broker-log")
    ck_dir = str(tmp_path / "ckpt")
    kw = dict(engine="lanes", compat="fixed", batch=50, symbols=8,
              accounts=16, slots=64, max_fills=32,
              checkpoint_dir=ck_dir, checkpoint_every=100)

    b1 = InProcessBroker(persist_dir=log_dir)
    provision(b1)
    for m in msgs:
        b1.produce(TOPIC_IN, None, dumps_order(m))
    svc1 = MatchService(b1, **kw)
    assert svc1.run(max_messages=150) == 150  # snapshot at 100
    del svc1, b1  # the whole process dies

    b2 = InProcessBroker(persist_dir=log_dir)  # broker log reloaded
    svc2 = MatchService(b2, **kw)
    assert svc2.offset == 100
    rest = len(msgs) - 100
    assert svc2.run(max_messages=rest) == rest

    got = list(consume_lines(b2, follow=False))
    want = [ln for lines in per_msg[:150] for ln in lines]
    want += [ln for lines in per_msg[100:] for ln in lines]
    assert got == want


# ---------------------------------------------------------------------------
# java-mode seq checkpoints (runtime/javasnap.py): the 128-bit-key
# canonical form incl. Q11 garbage keys, and cross-engine restore
# seq-java <-> native with byte-identical continuation
# (VERDICT r4 #4; reference: the changelog-restore contract,
# KProcessor.java:30-49)

def _java_cfg():
    from kme_tpu.engine import seq as SQ

    return SQ.SeqConfig(lanes=8, slots=512, accounts=128, max_fills=128,
                        batch=512, pos_cap=1 << 12, probe_max=16,
                        compat="java")


def _java_stream(n=2400, seed=7):
    from kme_tpu.workload import harness_stream

    return harness_stream(n, seed=seed)


def _judge_java(msgs):
    from kme_tpu.native.oracle import NativeOracleEngine, native_available

    if not native_available():
        import pytest

        pytest.skip("native toolchain unavailable")
    judge = NativeOracleEngine("java")
    return judge.process_wire([m.copy() for m in msgs])


@pytest.mark.slow
def test_seqjava_checkpoint_mid_stream_resume(cpu_devices, tmp_path):
    """Kill/resume mid-stream: process a prefix on a java-mode
    SeqSession, snapshot, restore into a FRESH session, continue — the
    combined stream is byte-identical to an uninterrupted judge run,
    and the garbage-key position store survives exactly."""
    from kme_tpu.engine import seq as SQ
    from kme_tpu.runtime.checkpoint import (load_seq_session,
                                            save_seq_session)
    from kme_tpu.runtime.seqsession import SeqSession

    cfg = _java_cfg()
    msgs = _java_stream()
    cut = 1500
    ses = SeqSession(cfg)
    head = ses.process_wire(msgs[:cut])
    save_seq_session(str(tmp_path), ses, cut)

    ses2, offset = load_seq_session(str(tmp_path))
    assert offset == cut
    assert ses2.cfg.compat == "java"
    # store parity incl. Q11 garbage keys before continuing
    want_store = SQ.export_java(cfg, ses.state)
    got_store = SQ.export_java(ses2.cfg, ses2.state)
    assert got_store["positions"] == want_store["positions"]
    tail = ses2.process_wire(msgs[cut:])
    got = [ln for per in head + tail for ln in per]
    want = [ln for per in _judge_java(msgs) for ln in per]
    assert got == want


def test_seqjava_to_native_continuation(cpu_devices):
    """seq-java -> native: snapshot the device session, convert to the
    native engine's dump, continue there — byte-identical to the
    uninterrupted judge."""
    from kme_tpu.native.oracle import NativeOracleEngine, native_available
    from kme_tpu.runtime.javasnap import export_seqjava, to_native_dump
    from kme_tpu.runtime.seqsession import SeqSession

    if not native_available():
        import pytest

        pytest.skip("native toolchain unavailable")
    cfg = _java_cfg()
    msgs = _java_stream(n=2000, seed=13)
    cut = 1200
    ses = SeqSession(cfg)
    head = ses.process_wire(msgs[:cut])
    dump = to_native_dump(export_seqjava(ses))
    eng = NativeOracleEngine("java")
    eng.load_state(dump)
    tail = eng.process_wire([m.copy() for m in msgs[cut:]])
    got = [ln for per in head + tail for ln in per]
    want = [ln for per in _judge_java(msgs) for ln in per]
    assert got == want


def test_native_to_seqjava_continuation(cpu_devices):
    """native -> seq-java: the native engine's checkpoint dump restores
    into a java-mode device session which continues byte-identically."""
    from kme_tpu.native.oracle import NativeOracleEngine, native_available
    from kme_tpu.runtime.javasnap import from_native_dump, import_seqjava

    if not native_available():
        import pytest

        pytest.skip("native toolchain unavailable")
    cfg = _java_cfg()
    msgs = _java_stream(n=2000, seed=29)
    cut = 1100
    eng = NativeOracleEngine("java")
    head = eng.process_wire([m.copy() for m in msgs[:cut]])
    ses = import_seqjava(cfg, from_native_dump(eng.dump_state()))
    tail = ses.process_wire(msgs[cut:])
    got = [ln for per in head + tail for ln in per]
    want = [ln for per in _judge_java(msgs) for ln in per]
    assert got == want


def test_seqjava_snapshot_refuses_fixed_restore(cpu_devices, tmp_path):
    """Engine-kind mismatches surface as SnapshotCapacityError /
    ValueError, never silent fallback."""
    import pytest

    from kme_tpu.engine import seq as SQ
    from kme_tpu.runtime.checkpoint import (SnapshotCapacityError,
                                            load_seq_session,
                                            save_seq_session)
    from kme_tpu.runtime.seqsession import SeqSession

    cfg = _java_cfg()
    ses = SeqSession(cfg)
    ses.process_wire(_java_stream(n=400))
    save_seq_session(str(tmp_path), ses, 400)
    with pytest.raises(SnapshotCapacityError):
        load_seq_session(str(tmp_path),
                         SQ.SeqConfig(lanes=8, slots=512, accounts=128,
                                      max_fills=128, batch=512,
                                      pos_cap=1 << 12, probe_max=16))


def test_seqjava_service_kill_resume(cpu_devices, tmp_path):
    """Durable java-mode seq SERVING: a MatchService with engine='seq'
    compat='java' checkpoints mid-stream and a fresh service resumes
    from the snapshot, producing the byte-exact at-least-once stream."""
    from kme_tpu.bridge.broker import InProcessBroker
    from kme_tpu.bridge.provision import provision
    from kme_tpu.bridge.service import MatchService
    from kme_tpu.wire import dumps_order

    msgs = _java_stream(n=1400, seed=3)
    ck = str(tmp_path / "ck")
    broker = InProcessBroker(str(tmp_path / "log"))
    provision(broker)
    for m in msgs[:900]:
        broker.produce("MatchIn", None, dumps_order(m))
    kw = dict(engine="seq", compat="java", symbols=8, accounts=128,
              slots=512, max_fills=128, batch=256, checkpoint_dir=ck,
              checkpoint_every=256)
    svc = MatchService(broker, **kw)
    while svc.step(timeout=0.05):
        pass
    n_first = sum(1 for _ in broker.fetch("MatchOut", 0, 10**9))
    del svc   # "crash" after an arbitrary number of checkpoints
    for m in msgs[900:]:
        broker.produce("MatchIn", None, dumps_order(m))
    svc2 = MatchService(broker, **kw)
    while svc2.step(timeout=0.05):
        pass
    out = [f"{r.key} {r.value}"
           for r in broker.fetch("MatchOut", 0, 10**9)]
    groups = _judge_java(msgs)
    # at-least-once: first-run output for msgs[:900] stands; the
    # resumed service replays from its snapshot offset k <= 900 and the
    # replayed+new segment must be byte-exact for msgs[k:]
    assert out[:n_first] == [ln for per in groups[:900] for ln in per]
    tail = out[n_first:]
    ok = any(tail == [ln for per in groups[k:] for ln in per]
             for k in range(901))
    assert ok, "replayed stream is not an exact judge segment"


def test_journal_across_crash_resume(tmp_path):
    """Flight-recorder round-trip over a crash/resume cycle: the
    service replays the post-snapshot tail (at-least-once), but the
    journal rewinds to the snapshot offset first — so the final
    journal holds every lifecycle event exactly once, with strictly
    monotonic sequence numbers, and byte-agrees (canonical form) with
    an independent oracle replay of the whole input stream."""
    from kme_tpu.telemetry.journal import (canonical_lines,
                                           oracle_events, read_events)

    msgs = harness_stream(400, seed=13, num_symbols=4, num_accounts=8,
                          payout_opcode_bug=False, validate=True)
    broker = InProcessBroker()
    provision(broker)
    for m in msgs:
        broker.produce(TOPIC_IN, None, dumps_order(m))

    jp = str(tmp_path / "journal.jsonl")
    kw = dict(engine="lanes", compat="fixed", batch=50, symbols=8,
              accounts=16, slots=64, max_fills=32,
              checkpoint_dir=str(tmp_path / "ck"),
              checkpoint_every=100, journal=jp)
    svc = MatchService(broker, **kw)
    assert svc.run(max_messages=250) == 250  # snapshots at 100, 200
    del svc  # crash: 50 journaled records past the last snapshot

    svc2 = MatchService(broker, **kw)
    assert svc2.offset == 200                # resumed from snapshot
    rest = len(msgs) - 200
    assert svc2.run(max_messages=rest) == rest
    svc2.close()

    evs = read_events(jp)
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    # exactly-once despite the at-least-once input replay
    offs = [e["off"] for e in evs if e["e"] == "submit"]
    assert offs == list(range(len(msgs)))
    want = canonical_lines(oracle_events(
        [dumps_order(m) for m in msgs], book_slots=64, max_fills=32))
    assert canonical_lines(evs) == want


# ---------------------------------------------------------------------------
# corrupt-newest-snapshot fallback (silent corruption, not just torn
# writes) and retention depth


def test_digest_mismatch_snapshot_falls_back(tmp_path):
    """Silent corruption: the newest snapshot still np.load-parses (so
    zipfile CRCs pass) but one array was modified while its stored
    digest went stale — the CONTENT digest must catch it and the loader
    falls back to the previous snapshot."""
    import numpy as np

    msgs = _stream(300, seed=9)
    ses = LaneSession(CFG)
    ses.process_wire([m.copy() for m in msgs[:100]])
    ck.save_session(str(tmp_path), ses, offset=100)
    ses.process_wire([m.copy() for m in msgs[100:200]])
    ck.save_session(str(tmp_path), ses, offset=200)

    path = ck.snapshot_path(str(tmp_path), 200)
    data = {k: v.copy() for k, v in np.load(path).items()}
    tampered = data["pos_amt"].copy()
    tampered.flat[0] += 1                 # one balance, one tick off
    data["pos_amt"] = tampered            # digest array kept STALE
    with open(path, "wb") as f:
        np.savez(f, **data)

    resumed, offset = ck.load_session(str(tmp_path))
    assert offset == 100 and resumed is not None
    with pytest.raises(ValueError, match="digest mismatch"):
        ck._load_file(path)


def test_oracle_bitflip_inside_engine_falls_back(tmp_path):
    """A bit-flip INSIDE the pickled engine bytes leaves the outer blob
    parseable — only the engine_pkl sha256 can catch it; load_oracle
    must skip to the previous snapshot."""
    ora = OracleEngine("fixed", book_slots=64, max_fills=32)
    msgs = harness_stream(60, seed=11, num_accounts=4, num_symbols=2,
                          payout_opcode_bug=False, validate=True)
    for m in msgs[:30]:
        ora.process(m)
    ck.save_oracle(str(tmp_path), ora, 100)
    for m in msgs[30:]:
        ora.process(m)
    ck.save_oracle(str(tmp_path), ora, 200)

    import pickle

    path = os.path.join(str(tmp_path), "ckpt-200.pkl")
    with open(path, "rb") as f:
        raw = bytearray(f.read())
    engine_pkl = pickle.loads(bytes(raw))["engine_pkl"]
    at = raw.index(engine_pkl) + len(engine_pkl) // 2
    raw[at] ^= 0x10
    with open(path, "wb") as f:
        f.write(raw)
    # the outer blob still parses — the digest is the only defence
    assert pickle.loads(bytes(raw))["engine_pkl"] != engine_pkl

    loaded, offset = ck.load_oracle(str(tmp_path))
    assert offset == 100 and loaded is not None


def test_all_snapshots_corrupt_cold_start(tmp_path):
    """Every snapshot unreadable: the loader returns (None, 0) rather
    than raising, and a service pointed at the wreckage starts cold at
    offset 0 and replays the whole stream byte-exactly."""
    msgs = harness_stream(80, seed=17, num_accounts=4, num_symbols=2,
                          payout_opcode_bug=False, validate=True)
    ora = OracleEngine("fixed", book_slots=64, max_fills=32)
    want = [r.wire() for m in msgs for r in ora.process(m.copy())]

    ck_dir = str(tmp_path / "ck")
    ses = LaneSession(CFG)
    ses.process_wire([m.copy() for m in _stream(100, seed=3)])
    ck.save_session(ck_dir, ses, offset=50)
    ck.save_session(ck_dir, ses, offset=100)
    for off, path in ck.list_snapshots(ck_dir):
        with open(path, "r+b") as f:
            f.truncate(64)
    assert ck.load_session(ck_dir) == (None, 0)

    broker = InProcessBroker()
    provision(broker)
    for m in msgs:
        broker.produce(TOPIC_IN, None, dumps_order(m))
    svc = MatchService(broker, engine="oracle", compat="fixed", batch=16,
                       slots=64, max_fills=32, checkpoint_dir=ck_dir,
                       checkpoint_every=1000)
    assert svc.offset == 0                 # cold start, not a crash
    assert svc.run(max_messages=len(msgs)) == len(msgs)
    got = [f"{r.key} {r.value}" for r in broker.fetch("MatchOut", 0, 10**6)]
    assert got == want


def test_retention_keep_depth(tmp_path, monkeypatch):
    """keep= bounds the snapshot tail; KME_CKPT_KEEP sets the default
    (3 — newest + two fallbacks, since kme-chaos both tears AND
    bit-flips)."""
    ses = LaneSession(CFG)
    ses.process_wire([m.copy() for m in _stream(50, seed=2)])

    d1 = str(tmp_path / "explicit")
    for off in (10, 20, 30, 40):
        ck.save_session(d1, ses, offset=off, keep=2)
    assert [o for o, _ in ck.list_snapshots(d1)] == [40, 30]

    d2 = str(tmp_path / "default")
    monkeypatch.delenv("KME_CKPT_KEEP", raising=False)
    for off in (10, 20, 30, 40, 50):
        ck.save_session(d2, ses, offset=off)
    assert [o for o, _ in ck.list_snapshots(d2)] == [50, 40, 30]

    d3 = str(tmp_path / "env")
    monkeypatch.setenv("KME_CKPT_KEEP", "1")
    for off in (10, 20):
        ck.save_session(d3, ses, offset=off)
    assert [o for o, _ in ck.list_snapshots(d3)] == [20]


def test_snapshot_extra_meta_round_trips(tmp_path):
    """The additive `extra` dict (the exactly-once epoch/out_seq
    cursor) survives both the pkl and npz snapshot kinds, and degrades
    to {} when absent."""
    d = str(tmp_path)
    ora = OracleEngine("fixed", book_slots=64, max_fills=32)
    ck.save_oracle(d, ora, 40, extra={"epoch": 3, "out_seq": 99})
    assert ck.snapshot_extra(d, 40) == {"epoch": 3, "out_seq": 99}
    ck.save_oracle(d, ora, 80)                 # no extra stored
    assert ck.snapshot_extra(d, 80) == {}
    assert ck.snapshot_extra(d, 999) == {}     # no snapshot at all

    ses = LaneSession(CFG)
    ses.process_wire([m.copy() for m in _stream(50, seed=9)])
    ck.save_session(d, ses, offset=50, extra={"epoch": 1, "out_seq": 7})
    assert ck.snapshot_extra(d, 50) == {"epoch": 1, "out_seq": 7}
    # ...and the snapshot still restores normally alongside the meta
    resumed, offset = ck.load_session(d)
    assert offset == 50
    assert resumed.export_state() == ses.export_state()


def test_oldest_retained_offset_tracks_pruning(tmp_path):
    """The journal retention guard's anchor: the smallest snapshot
    offset on disk, across snapshot kinds, moving forward as `keep`
    prunes old snapshots."""
    d = str(tmp_path / "ck")
    assert ck.oldest_retained_offset(d) is None        # no dir yet
    ora = OracleEngine("fixed", book_slots=64, max_fills=32)
    ck.save_oracle(d, ora, 128)
    ck.save_oracle(d, ora, 64)
    assert ck.oldest_retained_offset(d) == 64
    ses = LaneSession(CFG)
    ck.save_session(d, ses, offset=32)                 # other kind
    assert ck.oldest_retained_offset(d) == 32
    ck.save_oracle(d, ora, 192, keep=2)                # prunes 64
    assert ck.oldest_retained_offset(d) == 32          # npz untouched
