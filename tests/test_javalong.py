"""Exact Java arithmetic + the Q7 bit-scan equivalence proof
(SURVEY.md §2.5 Q7: replicate float scans with integer ops only after
confirming equivalence on the used range)."""

import random

from kme_tpu.oracle import javalong as jl


def test_jlong_wrap():
    assert jl.jlong(2 ** 63) == -(2 ** 63)
    assert jl.jlong(2 ** 64) == 0
    assert jl.jlong(-(2 ** 63) - 1) == 2 ** 63 - 1
    assert jl.jlong(5) == 5
    assert jl.jadd(2 ** 63 - 1, 1) == -(2 ** 63)
    assert jl.jmul(2 ** 32, 2 ** 32) == 0


def test_jint_wrap():
    assert jl.jint(2 ** 31) == -(2 ** 31)
    assert jl.jint(-(2 ** 31) - 1) == 2 ** 31 - 1


def test_java_shift_masks_count():
    # Java masks long shift counts to 6 bits: n << 64 == n
    assert jl.jshl(1, 64) == 1
    assert jl.jshl(1, 65) == 2
    assert jl.jshl(1, -3) == jl.jshl(1, 61)
    assert jl.jshr(-1, 63) == -1  # arithmetic shift


def test_bit_ops_match_java():
    assert jl.set_bit(0, 5) == 32
    assert jl.unset_bit(33, 5) == 1
    assert jl.get_bit(33, 5)
    assert not jl.get_bit(33, 4)
    # negative k: Java masks to 6 bits
    assert jl.set_bit(0, -1) == jl.set_bit(0, 63)


def test_float_bitscan_equivalence_first():
    """getFirstSetBitPos (KProcessor.java:371-373) operates on n & -n, an
    exact power of two: the float formula is exact for every bit 0..62 —
    so the device engine's integer count-trailing-zeros is equivalent on
    the entire book-bitmap domain."""
    for k in range(63):
        assert jl.first_set_bit_pos_float(1 << k) == k == jl.first_set_bit_pos(1 << k)
    rng = random.Random(1)
    for _ in range(50_000):
        n = rng.getrandbits(63)
        if n == 0:
            continue
        assert jl.first_set_bit_pos_float(n) == jl.first_set_bit_pos(n)


def test_float_bitscan_last_overshoot_domain():
    """getLastSetBitPos (KProcessor.java:375-377) is exact for every
    single-bit value and for all values below 2^47, but overshoots by one
    on dense values with top bit >= 47 (log10 ratio rounds up to the next
    integer). In the reference that overshoot makes getMaxPriceBucketPointer
    return a price with no bucket -> NPE -> engine crash. The oracle
    reproduces the float semantics (raising ReferenceCrash); the device
    engine uses the exact integer scan, which only diverges where the
    reference self-destructs."""
    for k in range(63):
        assert jl.last_set_bit_pos_float(1 << k) == k == jl.last_set_bit_pos(1 << k)
    # documented overshoot: 2^48 - 1 (bits 0..47 all set)
    assert jl.last_set_bit_pos(2 ** 48 - 1) == 47
    assert jl.last_set_bit_pos_float(2 ** 48 - 1) == 48
    # exactness below the overshoot domain
    rng = random.Random(2)
    for _ in range(50_000):
        n = rng.getrandbits(46)
        if n == 0:
            continue
        assert jl.last_set_bit_pos_float(n) == jl.last_set_bit_pos(n)


def test_bitscan_zero_and_negative_edges():
    # Java: (int)(-Infinity) == Integer.MIN_VALUE; (int)NaN == 0
    assert jl.last_set_bit_pos_float(0) == -(1 << 31)
    assert jl.last_set_bit_pos_float(-5) == 0
    assert jl.first_set_bit_pos_float(0) == -(1 << 31)
