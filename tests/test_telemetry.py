"""The unified telemetry subsystem: registry semantics, Prometheus
exposition, phase timers / trace recording, the metrics HTTP surface,
cross-engine counter-name parity, and checkpoint round-trips of the
observability state."""

import json
import urllib.request

import pytest

from kme_tpu.telemetry import (BUCKET_LE, N_BUCKETS, PhaseTimer, Registry,
                               TraceRecorder, bucket_index, get_tracer,
                               install, start_metrics_server)


# ---------------------------------------------------------------------------
# registry semantics


def test_counter_gauge_semantics():
    reg = Registry()
    c = reg.counter("msgs", help="messages")
    c.inc()
    c.inc(41)
    assert c.value == 42
    assert reg.counter("msgs") is c          # same instance on re-access
    g = reg.gauge("depth")
    g.set(7)
    g.set(3)
    assert g.value == 3
    with pytest.raises(TypeError):
        reg.gauge("msgs")                    # kind mismatch is loud


def test_histogram_semantics():
    reg = Registry()
    h = reg.histogram("fills")
    for v in (0, 1, 1, 2, 3, 4, 100, 20000):
        h.observe(v)
    assert h.count == 8
    assert h.sum == 0 + 1 + 1 + 2 + 3 + 4 + 100 + 20000
    assert h.buckets[0] == 1                  # v <= 0
    assert h.buckets[1] == 2                  # v == 1
    assert h.buckets[2] == 2                  # v in [2, 4)
    assert h.buckets[3] == 1                  # v in [4, 8)
    assert h.buckets[7] == 1                  # 100 in [64, 128)
    assert h.buckets[15] == 1                 # 20000 >= 2^14
    counts = [0] * N_BUCKETS
    counts[5] = 9
    h.set_buckets(counts)
    assert h.buckets == counts
    with pytest.raises(ValueError):
        h.set_buckets([0] * (N_BUCKETS - 1))


def test_bucket_index_boundaries():
    # idx = #{k in 0..14 : v >= 2^k}: 0 for v<=0, 1 for v==1,
    # i for v in [2^(i-1), 2^i), 15 for v >= 2^14
    assert bucket_index(-5) == 0
    assert bucket_index(0) == 0
    assert bucket_index(1) == 1
    assert bucket_index(2) == 2
    assert bucket_index(3) == 2
    assert bucket_index(4) == 3
    assert bucket_index(2 ** 14 - 1) == 14
    assert bucket_index(2 ** 14) == 15
    assert bucket_index(10 ** 9) == 15
    assert len(BUCKET_LE) == N_BUCKETS
    assert BUCKET_LE[0] == "0" and BUCKET_LE[-1] == "+Inf"


def test_prometheus_exposition():
    reg = Registry()
    reg.counter("trades_ok", help="accepted trades").inc(5)
    reg.gauge("open_orders").set(3)
    h = reg.histogram("fills_per_order")
    h.observe(1)
    h.observe(3)
    text = reg.prometheus_text()
    assert "# TYPE trades_ok counter" in text
    assert "trades_ok 5" in text
    assert "# HELP trades_ok accepted trades" in text
    assert "# TYPE open_orders gauge" in text
    assert "# TYPE fills_per_order histogram" in text
    # cumulative buckets: le="1" holds 1 obs, le="3" holds both
    assert 'fills_per_order_bucket{le="1"} 1' in text
    assert 'fills_per_order_bucket{le="3"} 2' in text
    assert 'fills_per_order_bucket{le="+Inf"} 2' in text
    assert "fills_per_order_sum 4" in text
    assert "fills_per_order_count 2" in text


def test_publish_and_snapshot():
    reg = Registry()
    reg.publish_counters({"msgs": 10, "fills": 2})
    reg.publish_gauges({"books": 4})
    reg.publish_histograms({"depth": [1] + [0] * (N_BUCKETS - 1)})
    snap = reg.snapshot()
    assert snap["counters"] == {"msgs": 10, "fills": 2}
    assert snap["gauges"] == {"books": 4}
    assert snap["histograms"]["depth"]["count"] == 1
    assert json.loads(reg.to_json())  # valid JSON export


# ---------------------------------------------------------------------------
# phase timing + tracing


def test_phase_timer_accumulates():
    t = PhaseTimer(track="test")
    with t.phase("plan_s"):
        pass
    first = t.totals["plan_s"]
    with t.phase("plan_s"):
        pass
    assert t.totals["plan_s"] > first    # cumulative, not overwritten
    t.add("fetch_s", 1.5)
    assert t.totals["fetch_s"] == 1.5
    t.reset()
    assert t.totals == {}


def test_trace_recorder(tmp_path):
    rec = TraceRecorder()
    install(rec)
    try:
        assert get_tracer() is rec
        t = PhaseTimer(track="unit")
        with t.phase("dispatch_s", batch=3):
            pass
        out = tmp_path / "trace.json"
        rec.save(str(out))
    finally:
        install(None)
    doc = json.loads(out.read_text())
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert evs and evs[0]["name"] == "dispatch_s"
    assert evs[0]["args"] == {"batch": 3}
    assert any(e.get("name") == "thread_name"
               for e in doc["traceEvents"])


# ---------------------------------------------------------------------------
# session integration: the legacy phase keys are load-bearing
# (benchmarks.py, tests/test_bench_smoke.py) and must ACCUMULATE across
# batches — the bug this PR fixes was SeqSession overwriting them


def _stream(n=300):
    from kme_tpu.workload import zipf_symbol_stream

    return zipf_symbol_stream(n, num_symbols=8, num_accounts=24, seed=3,
                              zipf_a=1.0, payout_per_mille=4)


PHASE_KEYS = {"plan_s", "dispatch_s", "fetch_s", "recon_s"}


def test_lanes_phases_accumulate():
    from kme_tpu.engine.lanes import LaneConfig
    from kme_tpu.runtime.session import LaneSession

    ses = LaneSession(LaneConfig(lanes=8, slots=32, accounts=32,
                                 max_fills=16, steps=16))
    msgs = _stream()
    ses.process_wire([m.copy() for m in msgs])
    assert PHASE_KEYS <= set(ses.phases)
    first = dict(ses.phases)
    ses.process_wire([m.copy() for m in msgs[:100]])
    for k in PHASE_KEYS:
        assert ses.phases[k] >= first[k]
    assert ses.phases["dispatch_s"] > first["dispatch_s"]


def test_seq_phases_accumulate():
    from kme_tpu.engine import seq as SQ
    from kme_tpu.runtime.seqsession import SeqSession

    ses = SeqSession(SQ.SeqConfig(lanes=8, slots=128, accounts=128,
                                  max_fills=16))
    msgs = _stream()
    ses.process_wire([m.copy() for m in msgs])
    assert PHASE_KEYS <= set(ses.phases)
    first = dict(ses.phases)
    ses.process_wire([m.copy() for m in msgs[:100]])
    assert ses.phases["dispatch_s"] > first["dispatch_s"]


def test_counter_names_identical_seq_vs_lanes():
    """The same stream exposes the SAME counter names from either
    engine's registry (the operator's dashboards don't care which
    engine serves)."""
    from kme_tpu.engine import seq as SQ
    from kme_tpu.engine.lanes import LaneConfig
    from kme_tpu.runtime.seqsession import SeqSession
    from kme_tpu.runtime.session import LaneSession

    msgs = _stream()
    lanes = LaneSession(LaneConfig(lanes=8, slots=32, accounts=32,
                                   max_fills=16, steps=16))
    lanes.process_wire([m.copy() for m in msgs])
    lanes.metrics()
    lanes.histograms()
    seq = SeqSession(SQ.SeqConfig(lanes=8, slots=128, accounts=128,
                                  max_fills=16))
    seq.process_wire([m.copy() for m in msgs])
    seq.metrics()
    seq.histograms()
    a, b = lanes.telemetry.snapshot(), seq.telemetry.snapshot()
    assert set(a["counters"]) == set(b["counters"])
    assert set(a["gauges"]) == set(b["gauges"])
    assert set(a["histograms"]) == set(b["histograms"])


@pytest.mark.slow
def test_counter_names_identical_seqmesh():
    from kme_tpu.engine import seq as SQ
    from kme_tpu.parallel.seqmesh import SeqMeshSession
    from kme_tpu.runtime.seqsession import SeqSession

    msgs = _stream()
    cfg = SQ.SeqConfig(lanes=8, slots=128, accounts=128, max_fills=16)
    seq = SeqSession(cfg)
    seq.process_wire([m.copy() for m in msgs])
    seq.metrics()
    seq.histograms()
    mesh = SeqMeshSession(cfg, shards=2)
    mesh.process_wire([m.copy() for m in msgs])
    mesh.metrics()
    mesh.histograms()
    a, b = seq.telemetry.snapshot(), mesh.telemetry.snapshot()
    assert set(a["counters"]) == set(b["counters"])
    assert set(a["histograms"]) == set(b["histograms"])
    assert PHASE_KEYS <= set(mesh.phases)
    # seqmesh phase totals accumulate too (it used to zero recon_s)
    first = dict(mesh.phases)
    mesh.process_wire([m.copy() for m in msgs[:100]])
    assert mesh.phases["dispatch_s"] > first["dispatch_s"]


# ---------------------------------------------------------------------------
# the live HTTP surface


def test_metrics_http_server():
    reg = Registry()
    reg.counter("msgs").inc(3)
    reg.histogram("depth").observe(2)
    srv = start_metrics_server(reg, 0, host="127.0.0.1")
    try:
        host, port = srv.server_address[:2]
        text = urllib.request.urlopen(
            f"http://{host}:{port}/metrics").read().decode()
        assert "msgs 3" in text
        assert 'depth_bucket{le="+Inf"} 1' in text
        doc = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/metrics.json").read().decode())
        assert doc["counters"]["msgs"] == 3
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://{host}:{port}/nope")
    finally:
        srv.shutdown()


def test_metrics_http_concurrent_scrape_with_engine_steps():
    """Scrapers hammering /metrics and /metrics.json WHILE the engine
    steps and republishes must never see an error or torn exposition —
    the registry surface is read concurrently with session writes."""
    import threading

    from kme_tpu.engine.lanes import LaneConfig
    from kme_tpu.runtime.session import LaneSession

    ses = LaneSession(LaneConfig(lanes=8, slots=32, accounts=32,
                                 max_fills=16, steps=16))
    msgs = _stream(400)
    srv = start_metrics_server(ses.telemetry, 0, host="127.0.0.1")
    host, port = srv.server_address[:2]
    stop = threading.Event()
    errs, bodies = [], []

    def scrape():
        while not stop.is_set():
            try:
                bodies.append(urllib.request.urlopen(
                    f"http://{host}:{port}/metrics",
                    timeout=5).read().decode())
                json.loads(urllib.request.urlopen(
                    f"http://{host}:{port}/metrics.json",
                    timeout=5).read().decode())
            except Exception as e:  # noqa: BLE001 - collected + asserted
                errs.append(e)
                return

    threads = [threading.Thread(target=scrape) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for lo in range(0, len(msgs), 50):
            ses.process_wire([m.copy() for m in msgs[lo:lo + 50]])
            ses.metrics()        # republishes counters mid-scrape
            ses.histograms()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        srv.shutdown()
    assert errs == []
    assert bodies
    # post-publish scrapes carry complete histogram families
    final = bodies[-1]
    assert "# TYPE" in final
    for text in bodies:
        # exposition is never torn mid-family: every bucket line that
        # appears belongs to a family whose _count line also appears
        if "fills_per_order_bucket" in text:
            assert "fills_per_order_count" in text


# ---------------------------------------------------------------------------
# checkpoint round-trips: counters and histogram buckets are part of the
# resume contract (a restart must not zero the operator's dashboards)


def test_lanes_checkpoint_roundtrip_telemetry(tmp_path):
    from kme_tpu.engine.lanes import LaneConfig
    from kme_tpu.runtime import checkpoint as ck
    from kme_tpu.runtime.session import LaneSession

    ses = LaneSession(LaneConfig(lanes=8, slots=32, accounts=32,
                                 max_fills=16, steps=16))
    ses.process_wire(_stream())
    met, hist = ses.metrics(), ses.histograms()
    assert sum(hist["fills_per_order"]) > 0
    ck.save_session(str(tmp_path), ses, 300)
    ses2, off = ck.load_session(str(tmp_path))
    assert off == 300
    assert ses2.metrics() == met
    assert ses2.histograms() == hist


def test_seq_checkpoint_roundtrip_telemetry(tmp_path):
    from kme_tpu.engine import seq as SQ
    from kme_tpu.runtime import checkpoint as ck
    from kme_tpu.runtime.seqsession import SeqSession

    ses = SeqSession(SQ.SeqConfig(lanes=8, slots=128, accounts=128,
                                  max_fills=16))
    ses.process_wire(_stream())
    met, hist = ses.metrics(), ses.histograms()
    assert sum(hist["book_depth"]) > 0
    ck.save_seq_session(str(tmp_path), ses, 300)
    ses2, off = ck.load_seq_session(str(tmp_path))
    assert off == 300
    assert ses2.metrics() == met
    assert ses2.histograms() == hist


# ---------------------------------------------------------------------------
# Chrome trace flow arrows: the serve pipeline links each batch's engine
# span to its produce span


def test_trace_flow_events():
    tr = TraceRecorder()
    tr.flow("batch", "s", 7, track="serve")
    tr.flow("batch", "f", 7, track="serve")
    evs = [e for e in tr.trace_events() if e.get("cat") == "flow"]
    assert [e["ph"] for e in evs] == ["s", "f"]
    assert all(e["id"] == 7 and e["name"] == "batch" for e in evs)
    assert "bp" not in evs[0]
    assert evs[1]["bp"] == "e"          # bind finish to enclosing slice
    assert evs[1]["ts"] >= evs[0]["ts"]
    with pytest.raises(ValueError):
        tr.flow("batch", "x", 1)


def test_serve_emits_flow_arrows_per_batch(tmp_path):
    from kme_tpu.bridge.broker import InProcessBroker
    from kme_tpu.bridge.provision import provision
    from kme_tpu.bridge.service import TOPIC_IN, MatchService
    from kme_tpu.wire import dumps_order
    from kme_tpu.workload import harness_stream

    tr = TraceRecorder()
    install(tr)
    try:
        br = InProcessBroker()
        provision(br)
        msgs = harness_stream(60, seed=2, num_accounts=4,
                              num_symbols=2, payout_opcode_bug=False,
                              validate=True)
        for m in msgs:
            br.produce(TOPIC_IN, None, dumps_order(m))
        svc = MatchService(br, engine="oracle", compat="fixed",
                           batch=16)
        svc.run(max_messages=len(msgs))
        svc.close()
    finally:
        install(None)
    evs = tr.trace_events()
    starts = [e for e in evs
              if e.get("cat") == "flow" and e["ph"] == "s"]
    finishes = [e for e in evs
                if e.get("cat") == "flow" and e["ph"] == "f"]
    # one arrow per batch, start/finish ids pair up
    assert starts and len(starts) == len(finishes)
    assert ([e["id"] for e in starts] ==
            [e["id"] for e in finishes])
    # arrows bind to real spans: engine + produce phase slices exist
    names = {e["name"] for e in evs if e["ph"] == "X"}
    assert "serve_engine" in names and "serve_produce" in names
