"""kme-top: source scraping (metrics URL vs heartbeat file), view
derivation (rates, replica lag), the pure renderer, and a live smoke
against a running leader + standby pair."""

import json
import os
import threading
import time

import pytest

from kme_tpu.bridge.broker import InProcessBroker
from kme_tpu.bridge.provision import provision
from kme_tpu.bridge.replica import Replica
from kme_tpu.bridge.service import TOPIC_IN, MatchService
from kme_tpu.telemetry import start_metrics_server
from kme_tpu.telemetry.top import (build_view, collect, main, render,
                                   scrape)
from kme_tpu.wire import dumps_order
from kme_tpu.workload import harness_stream


# ---------------------------------------------------------------------------
# scraping


def test_scrape_heartbeat_file_vs_registry_snapshot(tmp_path):
    hb = str(tmp_path / "hb.json")
    with open(hb, "w") as f:
        json.dump({"role": "leader", "offset": 7, "degraded": None,
                   "metrics": {"counters": {"service_records": 7},
                               "gauges": {}, "latencies": {}}}, f)
    out = scrape(hb)
    assert out["ok"] and out["hb"]["offset"] == 7
    assert out["metrics"]["counters"]["service_records"] == 7

    snap = str(tmp_path / "snap.json")
    with open(snap, "w") as f:
        json.dump({"counters": {"service_records": 3}, "gauges": {},
                   "histograms": {}, "latencies": {}}, f)
    out = scrape(snap)              # bare registry snapshot, no hb
    assert out["ok"] and "hb" not in out
    assert out["metrics"]["counters"]["service_records"] == 3


def test_scrape_missing_sources_are_soft():
    assert scrape(None)["ok"] is False
    out = scrape("/nonexistent/path.json")
    assert out["ok"] is False and "error" in out
    out = scrape("http://127.0.0.1:9/", timeout=0.2)   # closed port
    assert out["ok"] is False and "error" in out
    # an unreachable node must not crash the frame
    view = build_view(collect("/nonexistent", None, None))
    assert any("unreachable" in ln for ln in render(view))


# ---------------------------------------------------------------------------
# view derivation + rendering (pure)


def _node(records=None, gauges=None, lats=None, hb=None):
    m = {"counters": ({} if records is None
                      else {"service_records": records}),
         "gauges": gauges or {}, "latencies": lats or {}}
    out = {"source": "x", "ok": True, "metrics": m}
    if hb is not None:
        out["hb"] = hb
    return out


def test_build_view_rate_and_lag():
    prev = {"t": 0.0, "leader": _node(records=100),
            "standby": _node(), "supervisor": None}
    cur = {"t": 2.0, "leader": _node(records=300),
           "standby": _node(gauges={"replica_lag_records": 5}),
           "supervisor": None}
    view = build_view(cur, prev)
    assert view["records_per_s"] == pytest.approx(100.0)
    assert view["replica_lag"] == 5
    # lag falls back to heartbeat applied/leader_offset
    cur["standby"] = _node(hb={"applied": 40, "leader_offset": 52})
    assert build_view(cur, prev)["replica_lag"] == 12
    # no prev sample -> no rate, never a crash
    assert build_view(cur)["records_per_s"] is None


def test_render_shows_stages_slo_and_supervisor():
    lats = {"lat_e2e": {"count": 10, "sum_s": 0.1, "p50_ms": 4.0,
                        "p90_ms": 8.0, "p99_ms": 9.0, "p999_ms": 9.5},
            "lat_ingress": {"count": 10, "sum_s": 0.01, "p50_ms": 0.5,
                            "p90_ms": 1.0, "p99_ms": 2.0,
                            "p999_ms": 2.5}}
    view = build_view({
        "t": 1.0,
        "leader": _node(records=10,
                        gauges={"slo_ok": 0, "slo_burn_rate": 3.5,
                                "pipeline_warning": 1},
                        lats=lats,
                        hb={"epoch": 2, "offset": 9,
                            "degraded": "slo burn 3.5x"}),
        "standby": _node(hb={"applied": 8, "leader_offset": 9,
                             "out_seq": 4, "discarded": 0}),
        "supervisor": {"restarts_total": 1, "budget_used": 1,
                       "max_restarts": 5, "standby_restarts": 0,
                       "recoveries": [{"t": 1.0, "kind": "leader"}]}})
    text = "\n".join(render(view))
    assert "epoch=2" in text and "offset=9" in text
    assert "DEGRADED: slo burn 3.5x" in text
    assert "slo=BREACH burn=3.50x" in text
    assert "pipeline_warning" in text
    assert "e2e" in text and "ingress" in text and "9.500" in text
    assert "applied=8" in text and "lag=1" in text
    assert "restarts=1" in text and "kind=leader" in text
    # empty view renders too (all sources down)
    assert render(build_view(collect(None, None, None)))


def test_render_shows_shard_rows():
    """Per-shard straggler attribution (SeqMeshSession gauges): the
    shard section appears iff shard_count is present, with occupancy
    and the device_shard{N} quantiles per row."""
    lats = {"device_shard0": {"count": 90, "sum_s": 0.4, "p50_ms": 3.0,
                              "p90_ms": 5.0, "p99_ms": 6.0,
                              "p999_ms": 6.5},
            "device_shard1": {"count": 30, "sum_s": 0.1, "p50_ms": 1.0,
                              "p90_ms": 1.5, "p99_ms": 2.0,
                              "p999_ms": 2.2}}
    node = _node(records=120,
                 gauges={"shard_count": 2, "shard_imbalance": 1.5,
                         "shard0_occupancy": 90,
                         "shard1_occupancy": 30})
    node["metrics"]["counters"].update(
        {"shard_migrations_total": 3, "shard_rebalances_total": 1})
    node["metrics"]["latencies"] = lats
    view = build_view({"t": 1.0, "leader": node, "standby": _node(),
                       "supervisor": None})
    text = "\n".join(render(view))
    assert "shards=2" in text
    assert "imbalance=1.500" in text
    assert "migrations=3" in text and "rebalances=1" in text
    assert "occupancy" in text
    # one row per shard: occupancy gauge + p50/p99 from the summary
    row0 = next(ln for ln in text.splitlines()
                if ln.strip().startswith("0 "))
    assert "90" in row0 and "3.000" in row0 and "6.000" in row0
    row1 = next(ln for ln in text.splitlines()
                if ln.strip().startswith("1 "))
    assert "30" in row1 and "2.000" in row1
    # without the gauge the section stays hidden
    plain = "\n".join(render(build_view(
        {"t": 1.0, "leader": _node(records=1), "standby": _node(),
         "supervisor": None})))
    assert "shards=" not in plain


def test_render_shows_group_section():
    """Multi-leader shard group (bridge/front.py): the group line
    appears iff group_count > 1, with the leader's lag and the
    cross-shard transfer gauges + RTT quantiles."""
    node = _node(records=50,
                 gauges={"group_id": 1, "group_count": 4,
                         "group1_lag": 7,
                         "cross_shard_transfers_total": 12,
                         "cross_shard_transfer_volume": 90000,
                         "balance_broadcasts_total": 3})
    node["metrics"]["latencies"] = {
        "transfer_rtt": {"count": 12, "sum_s": 0.02, "p50_ms": 1.1,
                         "p90_ms": 2.0, "p99_ms": 3.3, "p999_ms": 3.5}}
    text = "\n".join(render(build_view(
        {"t": 1.0, "leader": node, "standby": _node(),
         "supervisor": None})))
    assert "group=1/4" in text
    assert "lag=7" in text
    assert "xfers=12" in text and "volume=90,000" in text
    assert "transfer_rtt" in text and "p99=3.300ms" in text
    # a single-group leader renders no group section
    solo = _node(records=1, gauges={"group_id": 0, "group_count": 1})
    plain = "\n".join(render(build_view(
        {"t": 1.0, "leader": solo, "standby": _node(),
         "supervisor": None})))
    assert "group=" not in plain


def test_main_once_plain_frame_with_shards(tmp_path, capsys):
    """--once over a heartbeat file carrying the mesh session's shard
    gauges prints the shard rows in the plain frame."""
    hb = str(tmp_path / "serve.health")
    with open(hb, "w") as f:
        json.dump({"role": "leader", "offset": 5, "epoch": 1,
                   "degraded": None,
                   "metrics": {
                       "counters": {"service_records": 5,
                                    "shard_migrations_total": 2,
                                    "shard_rebalances_total": 1},
                       "gauges": {"shard_count": 2,
                                  "shard_imbalance": 1.18,
                                  "shard0_occupancy": 40,
                                  "shard1_occupancy": 60},
                       "latencies": {
                           "device_shard0": {"count": 40, "sum_s": 0.1,
                                             "p50_ms": 2.0,
                                             "p90_ms": 3.0,
                                             "p99_ms": 4.0,
                                             "p999_ms": 4.4},
                           "device_shard1": {"count": 60, "sum_s": 0.2,
                                             "p50_ms": 2.5,
                                             "p90_ms": 3.5,
                                             "p99_ms": 4.5,
                                             "p999_ms": 5.0}}}}, f)
    rc = main(["--leader", hb, "--once", "--no-rate-sample"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "shards=2" in out and "imbalance=1.180" in out
    assert "migrations=2" in out


def test_main_requires_a_source():
    with pytest.raises(SystemExit):
        main(["--once"])


def test_main_state_root_once_over_files(tmp_path, capsys):
    root = str(tmp_path)
    with open(os.path.join(root, "serve.health"), "w") as f:
        json.dump({"role": "leader", "offset": 3, "epoch": 1,
                   "degraded": None,
                   "metrics": {"counters": {"service_records": 3},
                               "gauges": {}, "latencies": {}}}, f)
    with open(os.path.join(root, "supervisor.json"), "w") as f:
        json.dump({"restarts_total": 0, "budget_used": 0,
                   "max_restarts": 5, "standby_restarts": 0,
                   "recoveries": []}, f)
    rc = main(["--state-root", root, "--once", "--no-rate-sample"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "offset=3" in out and "restarts=0" in out
    assert "standby" in out      # missing standby.health shown as down


# ---------------------------------------------------------------------------
# live smoke: leader + standby pair (ISSUE acceptance)


def test_top_live_leader_standby_pair(tmp_path, capsys):
    ck = str(tmp_path / "ck")
    os.makedirs(ck)
    log_dir = os.path.join(ck, "broker-log")
    msgs = [dumps_order(m) for m in harness_stream(
        80, seed=7, num_accounts=4, num_symbols=2,
        payout_opcode_bug=False, validate=True)]

    br = InProcessBroker(persist_dir=log_dir)
    provision(br)
    for m in msgs:
        br.produce(TOPIC_IN, None, m)
    leader = MatchService(br, engine="oracle", compat="fixed",
                          batch=16, slots=64, max_fills=32,
                          checkpoint_dir=ck, exactly_once=True)
    leader.run(max_messages=len(msgs))
    serve_health = os.path.join(ck, "serve.health")
    leader._write_heartbeat(serve_health, len(msgs))
    msrv = start_metrics_server(leader.telemetry, 0, host="127.0.0.1")
    lh, lp = msrv.server_address[:2]

    standby_health = os.path.join(ck, "standby.health")
    rep = Replica(ck, listen="127.0.0.1:0", engine="oracle", batch=16,
                  slots=64, max_fills=32, poll=0.02, health_every=0.05,
                  idle_exit=0.4, health_file=standby_health,
                  metrics_port=0)
    rc = [None]
    t = threading.Thread(target=lambda: rc.__setitem__(0, rep.run()),
                         daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 10.0
        while (not os.path.exists(standby_health)
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert os.path.exists(standby_health), "standby never heartbeat"

        code = main(["--leader", f"http://{lh}:{lp}",
                     "--standby", standby_health,
                     "--supervisor", os.path.join(ck,
                                                  "supervisor.json"),
                     "--once", "--interval", "0.05"])
        assert code == 0
        out = capsys.readouterr().out
        # leader metrics surface: throughput + the stage table
        assert f"records={len(msgs):,}" in out
        assert "e2e" in out and "p99 ms" in out
        # standby heartbeat surfaced with applied offset + lag
        assert "standby  applied=" in out
        assert "unreachable" not in out.split("standby")[1]

        # the standby's own metrics URL also scrapes (replica gauges)
        sh, sp = rep.metrics_server.server_address[:2]
        node = scrape(f"http://{sh}:{sp}")
        assert node["ok"]
        assert "replica_applied_offset" in node["metrics"]["gauges"]
    finally:
        # the follow loop only exits via promotion: issue a pid-less
        # (manual) promote order, after which idle_exit winds it down
        leader.close()
        msrv.shutdown()
        with open(rep.promote_file, "w") as f:
            json.dump({"failed_at": time.time()}, f)
        t.join(timeout=30)
        if rep.metrics_server is not None:
            rep.metrics_server.shutdown()
    assert rc[0] == 0


def test_feed_section_gated_on_feed_gauges():
    """The feed tier renders iff a scraped feed source carries the
    feed gauges (ISSUE 13); absent feeds leave the frame unchanged."""
    from kme_tpu.telemetry.top import feed_lines

    feed = _node(gauges={"feed_subscribers": 12, "feed_group": 0,
                         "feed_offset": 900})
    feed["metrics"]["counters"] = {
        "feed_frames_total": 300, "feed_delivered_total": 3600,
        "feed_conflated_frames_total": 400,
        "feed_conflations_total": 2, "feed_resyncs_total": 2,
        "feed_snapshots_served_total": 12,
        "feed_disconnects_total": 1}
    feed["metrics"]["latencies"] = {
        "feed_lag": {"count": 3600, "sum_s": 1.0, "p50_ms": 0.8,
                     "p90_ms": 2.0, "p99_ms": 4.5, "p999_ms": 9.0}}
    view = build_view({"t": 1.0, "leader": _node(records=5),
                       "standby": _node(), "supervisor": None,
                       "feed": feed})
    text = "\n".join(render(view))
    assert "feed     subs=12" in text
    assert "conflation rate=10.0%" in text     # 400 / (3600 + 400)
    assert "feed_lag p50=0.800ms p99=4.500ms" in text
    assert "snapshots=12" in text and "disconnects=1" in text
    # indent-prefixed variant used by the --cluster frame
    assert feed_lines(feed, indent="  ")[0].startswith("  feed")
    # no feed source (or one without the gauges): section absent
    view = build_view({"t": 1.0, "leader": _node(records=5),
                       "standby": _node(), "supervisor": None,
                       "feed": _node()})
    assert "feed " not in "\n".join(render(view))


def test_discover_endpoints_include_feed_surfaces(tmp_path):
    from kme_tpu.telemetry.top import discover_endpoints

    os.makedirs(tmp_path / "group0" / "state")
    eps = discover_endpoints(str(tmp_path))
    assert eps["feed"] == str(tmp_path / "feed.health")
    assert eps["groups"][0]["feed"] == str(
        tmp_path / "group0" / "state" / "feed.health")
