"""Adaptive overload control (ISSUE 10): the degradation state machine,
priority-aware admission with per-account fairness, the AIMD backoff
contract, the deterministic shed-policy replay the CI gate rides on,
and the broker/service integration (shed_observer -> annotated REJ rows,
backoff hints on the TCP wire, the binary max_lag path untouched)."""

import json

import pytest

from kme_tpu.bridge.broker import (CLS_ADMIN, CLS_DRAIN, CLS_ORDER,
                                   BrokerOverload, InProcessBroker,
                                   OverloadController, classify_produce,
                                   simulate_overload)
from kme_tpu.bridge.provision import provision
from kme_tpu.bridge.service import TOPIC_IN, TOPIC_OUT
from kme_tpu.wire import (REJ_OVERLOAD, OrderMsg, dumps_order,
                          rej_record_json)


def _order(aid=1, oid=100, action=2):
    return dumps_order(OrderMsg(action=action, aid=aid, oid=oid,
                                sid=0, price=50, size=1))


CANCEL = _order(action=4)
PAYOUT = dumps_order(OrderMsg(action=200, sid=0, price=1))
TRANSFER = dumps_order(OrderMsg(action=101, aid=1, size=10))
ORDER = _order()


# -- classification ----------------------------------------------------


def test_classify_produce_priority_classes():
    assert classify_produce(CANCEL)[0] == CLS_DRAIN
    assert classify_produce(PAYOUT)[0] == CLS_DRAIN
    assert classify_produce(TRANSFER)[0] == CLS_ADMIN
    assert classify_produce(ORDER)[0] == CLS_ORDER
    # malformed input never gets the drain-priority fast lane
    assert classify_produce("not json")[0] == CLS_ORDER
    assert classify_produce('{"action": null}')[0] == CLS_ORDER
    cls, oid, aid = classify_produce(_order(aid=7, oid=42))
    assert (cls, oid, aid) == (CLS_ORDER, 42, 7)


# -- state machine -----------------------------------------------------


def test_state_machine_hysteresis():
    c = OverloadController(high_lag=10)     # low=5, drain=20
    assert c.state == c.NORMAL
    c.admit(ORDER, 9)
    assert c.state == c.NORMAL
    c.admit(ORDER, 10)                      # high watermark
    assert c.state == c.SHEDDING
    # stays shedding in the hysteresis band (low < backlog < high)
    c.admit(ORDER, 7)
    assert c.state == c.SHEDDING
    c.admit(ORDER, 5)                       # low watermark
    assert c.state == c.NORMAL
    # normal jumps straight to draining past drain_lag
    c.admit(ORDER, 20)
    assert c.state == c.DRAINING
    # draining exits ONLY through shedding, never direct to normal
    c.admit(ORDER, 0)
    assert c.state == c.SHEDDING
    c.admit(ORDER, 0)
    assert c.state == c.NORMAL
    assert c.transitions == 5


def test_latency_drives_shedding_below_backlog_threshold():
    c = OverloadController(high_lag=100, p99_budget_ms=10.0)
    for _ in range(50):
        c.observe_latency(0.100)            # 100 ms >> 10 ms budget
    assert c.lat_ewma_ms > 10.0
    ok, detail = c.admit(ORDER, 0)          # zero backlog, hot latency
    assert c.state == c.SHEDDING
    # ...and cool latency lets it recover
    for _ in range(100):
        c.observe_latency(0.0001)
    c.admit(ORDER, 0)
    assert c.state == c.NORMAL


def test_invalid_watermarks_rejected():
    with pytest.raises(ValueError):
        OverloadController(high_lag=1)
    with pytest.raises(ValueError):
        OverloadController(high_lag=10, low_lag=10)
    with pytest.raises(ValueError):
        OverloadController(high_lag=10, drain_lag=9)


# -- priority admission ------------------------------------------------


def test_draining_admits_only_book_shrinking_ops():
    c = OverloadController(high_lag=4, drain_lag=8)
    c.admit(ORDER, 8)                       # -> draining
    assert c.state == c.DRAINING
    assert c.admit(CANCEL, 8)[0] is True
    assert c.admit(PAYOUT, 8)[0] is True
    ok, detail = c.admit(TRANSFER, 8)
    assert ok is False and detail["state"] == "draining"
    ok, detail = c.admit(ORDER, 8)
    assert ok is False
    assert detail["threshold"] == 8 and detail["backlog"] == 8


def test_shedding_admits_drain_and_admin_rations_orders():
    c = OverloadController(high_lag=4, drain_lag=8)
    c.admit(ORDER, 5)                       # -> shedding
    assert c.state == c.SHEDDING
    assert c.admit(CANCEL, 5)[0] is True
    assert c.admit(TRANSFER, 5)[0] is True
    # the order ration shrinks as backlog approaches drain_lag: offer a
    # burst at high backlog and most must shed, but not all (linear
    # ramp, not a cliff)
    got = [c.admit(ORDER, 7)[0] for _ in range(20)]
    assert 0 < sum(got) < 20
    # at backlog >= drain_lag the ration hits zero
    assert not any(c.admit(ORDER, 8)[0] for _ in range(10))


def test_per_account_fairness_cap_blocks_flooder():
    c = OverloadController(high_lag=4, drain_lag=400, account_cap=0.5,
                           fair_window=16)
    c.admit(ORDER, 4)                       # -> shedding
    flooder_shed = other_admitted = 0
    for i in range(200):
        # flooder (aid=9) offers twice as often as the rotating others
        if i % 3 != 2:
            ok, detail = c.admit(_order(aid=9, oid=1000 + i), 4)
            if not ok and detail["fairness"]:
                flooder_shed += 1
        else:
            ok, _ = c.admit(_order(aid=i % 7, oid=2000 + i), 4)
            other_admitted += ok
    assert flooder_shed > 0
    assert other_admitted > 0
    assert c.fairness_sheds == flooder_shed


def test_aimd_backoff_grows_on_shed_halves_in_normal():
    c = OverloadController(high_lag=4, backoff_step_ms=5,
                           backoff_max_ms=20)
    c.admit(ORDER, 8)                       # draining -> shed
    for _ in range(10):
        c.admit(ORDER, 8)
    assert c.backoff_ms == 20               # additive growth, bounded
    # recovery: draining -> shedding -> normal, then halving decay
    c.admit(CANCEL, 0)
    c.admit(CANCEL, 0)
    assert c.state == c.NORMAL
    before = c.backoff_ms
    c.admit(ORDER, 0)
    assert c.backoff_ms == before // 2


# -- deterministic replay (the CI gate's substrate) --------------------


def test_simulate_overload_deterministic_and_sheds():
    from kme_tpu.workload import storm_stream, storm_windows

    lines = [dumps_order(m) for m in storm_stream(
        "flash-crowd", 1500, num_symbols=8, num_accounts=16, seed=0)]
    wins = storm_windows("flash-crowd", 1500, num_symbols=8,
                         num_accounts=16)
    a = simulate_overload(lines, wins, OverloadController(high_lag=32))
    b = simulate_overload(lines, wins, OverloadController(high_lag=32))
    assert a["admitted_idx"] == b["admitted_idx"]
    assert a["shed"] > 0
    assert a["admitted"] + a["shed"] == a["total"] == len(lines)


def test_simulate_cancels_shed_strictly_less_than_orders():
    # the acceptance criterion: under a cancel-storm / flash-crowd
    # style mix that sheds, class-0 (cancel/payout) shed rate is
    # STRICTLY below class-2 (new order) shed rate
    from kme_tpu.workload import storm_stream, storm_windows

    for name in ("cancel-storm", "flash-crowd"):
        lines = [dumps_order(m) for m in storm_stream(
            name, 2000, num_symbols=8, num_accounts=16, seed=0)]
        wins = storm_windows(name, 2000, num_symbols=8,
                             num_accounts=16)
        ctl = OverloadController(high_lag=24)
        sim = simulate_overload(lines, wins, ctl)
        assert sim["shed"] > 0, name
        snap = sim["controller"]
        offered = {c: snap["admitted_by_class"][c]
                   + snap["shed_by_class"][c] for c in range(3)}
        assert offered[CLS_ORDER] > 0, name
        rate_order = (snap["shed_by_class"][CLS_ORDER]
                      / offered[CLS_ORDER])
        if offered[CLS_DRAIN]:
            rate_drain = (snap["shed_by_class"][CLS_DRAIN]
                          / offered[CLS_DRAIN])
            assert rate_drain < rate_order, name


# -- wire: annotated REJ rows ------------------------------------------


def test_rej_record_json_detail_is_additive():
    # without detail the bytes are unchanged from every prior release
    base = rej_record_json(5, 7, REJ_OVERLOAD)
    assert base == ('{"oid":5,"aid":7,"reason":9,'
                    '"rej":"rej_overload"}')
    assert rej_record_json(5, 7, REJ_OVERLOAD, detail=None) == base
    assert rej_record_json(5, 7, REJ_OVERLOAD, detail={}) == base
    got = rej_record_json(5, 7, REJ_OVERLOAD, detail={
        "threshold": 48, "backlog": 50, "state": "shedding",
        "backoff_ms": 15})
    doc = json.loads(got)
    assert doc["backlog"] == 50 and doc["state"] == "shedding"
    assert doc["rej"] == "rej_overload"
    # keys append in sorted order (stable bytes for parity tooling)
    assert got.index('"backlog"') < got.index('"backoff_ms"') \
        < got.index('"state"') < got.index('"threshold"')


# -- broker integration ------------------------------------------------


def _armed_broker(**kw):
    """Broker with the controller armed: the commit watermark must
    exist before backlog is measurable (same contract as max_lag)."""
    b = InProcessBroker(overload=OverloadController(**kw))
    provision(b)
    b.commit(TOPIC_IN, 0)
    return b


def test_broker_sheds_orders_admits_cancels_with_backoff_hint():
    b = _armed_broker(high_lag=2, drain_lag=4)
    admitted, first = 0, None
    for i in range(12):
        try:
            b.produce(TOPIC_IN, None, _order(aid=i % 5, oid=i))
            admitted += 1
        except BrokerOverload as e:
            if first is None:
                first = e
    assert first is not None and admitted > 0
    assert first.backoff_ms and first.backoff_ms > 0
    assert first.detail["state"] in ("shedding", "draining")
    assert first.detail["backlog"] >= 2
    assert first.detail["threshold"] in (2, 4)
    assert b.overload_rejects == 12 - admitted
    # ...while a cancel still gets through (book-shrinking fast lane),
    # even with the backlog pinned at its worst
    off = b.produce(TOPIC_IN, None, CANCEL)
    assert off == admitted
    # consuming drains the backlog and re-opens admission (two drain
    # ops walk draining -> shedding -> normal)
    b.commit(TOPIC_IN, admitted + 1)
    b.produce(TOPIC_IN, None, CANCEL)
    b.commit(TOPIC_IN, admitted + 2)
    b.produce(TOPIC_IN, None, _order(aid=99, oid=100))


def test_broker_shed_observer_fires_outside_lock():
    seen = []
    b = _armed_broker(high_lag=2, drain_lag=4)
    b.shed_observer = lambda topic, d: seen.append((topic, d))
    shed_oids = []
    for i in range(12):
        try:
            b.produce(TOPIC_IN, None, _order(aid=i % 5, oid=i))
        except BrokerOverload:
            shed_oids.append(i)
    assert shed_oids
    assert [d["oid"] for _, d in seen] == shed_oids
    assert all(t == TOPIC_IN for t, _ in seen)
    assert all(d["aid"] == d["oid"] % 5 for _, d in seen)
    # the observer must be able to call back INTO the broker (it runs
    # outside the data lock) — e.g. to annotate the shed on MatchOut
    b.shed_observer = lambda topic, d: b.produce(
        TOPIC_OUT, "REJ", rej_record_json(d["oid"], d["aid"],
                                          REJ_OVERLOAD, detail={
                                              "backlog": d["backlog"],
                                              "state": d["state"]}))
    got = None
    for i in range(20):
        try:
            b.produce(TOPIC_IN, None, _order(aid=i % 5, oid=100 + i))
        except BrokerOverload:
            got = 100 + i
            break
    assert got is not None
    rej = [r for r in b.fetch(TOPIC_OUT, 0, 100) if r.key == "REJ"]
    assert rej and json.loads(rej[-1].value)["oid"] == got


def test_binary_max_lag_path_unchanged_and_composable():
    # the historical binary shed must keep working without a controller
    b = InProcessBroker(max_lag=2)
    provision(b)
    b.commit(TOPIC_IN, 0)
    b.produce(TOPIC_IN, None, _order(oid=1))
    b.produce(TOPIC_IN, None, _order(oid=2))
    with pytest.raises(BrokerOverload) as ei:
        b.produce(TOPIC_IN, None, CANCEL)   # binary: sheds EVERYTHING
    assert ei.value.backoff_ms is None      # no AIMD hint on this path
    assert b.overload_rejects == 1
    # and it takes precedence when both are configured
    b2 = InProcessBroker(max_lag=2,
                         overload=OverloadController(high_lag=50))
    provision(b2)
    b2.commit(TOPIC_IN, 0)
    b2.produce(TOPIC_IN, None, _order(oid=1))
    b2.produce(TOPIC_IN, None, _order(oid=2))
    with pytest.raises(BrokerOverload):
        b2.produce(TOPIC_IN, None, CANCEL)


def test_unarmed_controller_broker_admits_everything():
    # no commit watermark -> no backlog signal -> no shedding (matches
    # the max_lag arming contract)
    b = InProcessBroker(overload=OverloadController(high_lag=2))
    provision(b)
    for i in range(50):
        b.produce(TOPIC_IN, None, _order(oid=i))
    assert b.overload_rejects == 0


# -- service integration -----------------------------------------------


def test_service_publishes_controller_gauges_and_annotates_sheds():
    from kme_tpu.bridge.service import MatchService

    b = InProcessBroker(overload=OverloadController(high_lag=4,
                                                    drain_lag=8))
    provision(b)
    svc = MatchService(b, engine="oracle", compat="fixed", batch=16,
                       annotate_rejects=True)
    assert b.shed_observer is not None      # annotation tap installed
    admitted = sheds = 0
    for i in range(60):
        try:
            b.produce(TOPIC_IN, None, _order(aid=i % 5, oid=i))
            admitted += 1
        except BrokerOverload:
            sheds += 1
    assert sheds > 0 and admitted > 0
    svc.run(max_messages=admitted)
    g = svc.telemetry.snapshot()["gauges"]
    assert g["overload_state"] is not None
    assert g["shed_by_class2"] == sheds
    assert g["admitted_by_class2"] == admitted
    assert "overload_backoff_ms" in g and "overload_transitions" in g
    # every shed produced an annotated REJ row on MatchOut
    rej = [r for r in b.fetch(TOPIC_OUT, 0, 4096) if r.key == "REJ"]
    over = [json.loads(r.value) for r in rej
            if json.loads(r.value)["reason"] == REJ_OVERLOAD]
    assert len(over) == sheds
    for doc in over:
        assert {"backlog", "threshold", "state",
                "backoff_ms"} <= set(doc)


# -- chaos scenario registry -------------------------------------------


def test_chaos_scenario_registry_lists_all_scenarios():
    from kme_tpu.bridge.chaos import scenario_registry
    from kme_tpu.workload import STORM_PROFILES

    reg = scenario_registry()
    assert {"default", "failover", "shard-failover"} <= set(reg)
    assert set(STORM_PROFILES) <= set(reg)
    assert all(isinstance(v, str) and v for v in reg.values())


def test_chaos_list_scenarios_flag(capsys):
    from kme_tpu.bridge import chaos

    assert chaos.main(["--list-scenarios"]) == 0
    out = capsys.readouterr().out
    for name in ("default", "failover", "payout-storm-wide",
                 "liquidation-cascade"):
        assert name in out
