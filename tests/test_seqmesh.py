"""Multi-chip SEQ fleet (parallel/seqmesh.py): bit-exactness of the
symbol-sharded seq kernels + psum balance merges vs the scalar oracle
and the single-chip SeqSession, at shards 1/2/8 on the virtual mesh.

Reference analog: partitioned scale-out, topic.js:18 +
KProcessor.java:59-60 (Streams instances splitting partitions of one
topic), with sequential consistency preserved by the account-disjoint
window plan instead of single-instance serialization.
"""

import numpy as np
import pytest

from kme_tpu.engine import seq as SQ
from kme_tpu.oracle import OracleEngine
from kme_tpu.parallel.seqmesh import SeqMeshSession
from kme_tpu.runtime.seqsession import SeqSession
from kme_tpu.workload import zipf_symbol_stream

CFG = dict(lanes=8, slots=128, accounts=128, max_fills=16,
           pos_cap=1 << 10, probe_max=8)


def _stream(n=900, seed=11):
    return zipf_symbol_stream(n, num_symbols=8, num_accounts=24,
                              seed=seed, zipf_a=1.0, payout_per_mille=5)


def _oracle_lines(msgs):
    ora = OracleEngine("fixed", book_slots=CFG["slots"],
                       max_fills=CFG["max_fills"])
    return [r.wire() for m in msgs for r in ora.process(m.copy())]


# shards=2 is the tier-1 representative (it exercises the cross-shard
# halo path at a quarter of the cost); 1 and 8 ride in the slow lane
@pytest.mark.parametrize("shards", [
    pytest.param(1, marks=pytest.mark.slow),
    2,
    pytest.param(8, marks=pytest.mark.slow),
])
def test_seqmesh_oracle_exact(cpu_devices, shards):
    """Full wire stream bit-exact vs the scalar oracle at every shard
    count — mixed trades/cancels/transfers and true PAYOUT barriers."""
    msgs = _stream()
    ses = SeqMeshSession(SQ.SeqConfig(**CFG), shards=shards)
    got = [ln for per in ses.process_wire(msgs) for ln in per]
    assert got == _oracle_lines(msgs), f"shards={shards} diverged"


def test_seqmesh_matches_single_chip(cpu_devices):
    """The sharded session's wire stream equals the single-chip
    SeqSession's byte for byte (same engine, same stream)."""
    msgs = _stream(n=700, seed=23)
    mesh = SeqMeshSession(SQ.SeqConfig(**CFG), shards=8)
    single = SeqSession(SQ.SeqConfig(**CFG))
    got = mesh.process_wire(msgs)
    want = single.process_wire(msgs)
    assert got == want


def test_seqmesh_window_invariant(cpu_devices):
    """plan_windows: within every window an account appears on at most
    one shard, and barriers sit alone."""
    msgs = _stream(n=1200, seed=5)
    ses = SeqMeshSession(SQ.SeqConfig(**CFG), shards=8)
    cols, _ = ses.router.route(msgs)
    wins, placements, cnts, K = ses.plan_windows(cols)
    acts = cols["act"]
    barrier = {int(k) for k in range(len(acts))
               if acts[k] in (SQ.L_PAYOUT_YES, SQ.L_PAYOUT_NO,
                              SQ.L_REMOVE_SYMBOL)}
    by_window = {}
    for k, w, s, p in placements:
        by_window.setdefault(w, []).append((k, s))
    n_placed = sum(len(v) for v in by_window.values())
    assert n_placed == len(acts)
    binds = (SQ.L_BUY, SQ.L_SELL, SQ.L_CANCEL, SQ.L_CREATE,
             SQ.L_TRANSFER)
    for w, entries in by_window.items():
        ks = [k for k, _ in entries]
        if any(k in barrier for k in ks):
            assert len(ks) == 1, "barrier must run alone"
        seen = {}
        for k, s in entries:
            if int(acts[k]) in binds:
                a = int(cols["aid"][k])
                assert seen.setdefault(a, s) == s, \
                    f"account {a} on two shards in window {w}"
