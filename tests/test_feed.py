"""Feed read path (ISSUE 13): frame codec, deriver-vs-oracle book
reconstruction, determinism, durable snapshots, the snapshot-then-
deltas splice edge cases (checkpoint boundary / mid-payout-storm /
during a PR 8 shard migration), and a live server/client round trip.
"""

import json
import os
import struct
import time

import pytest

from kme_tpu.feed import frames as ff
from kme_tpu.feed.derive import (BookBuilder, BookState, FeedDeriver,
                                 books_from_oracle, canonical_books)
from kme_tpu.feed.frames import (FeedFrameError, decode_feed,
                                 decode_feed_frames)
from kme_tpu.feed.snapshot import (feed_snapshot_path,
                                   list_feed_snapshots,
                                   load_feed_snapshot,
                                   save_feed_snapshot, snapshot_frames)
from kme_tpu.oracle import OracleEngine
from kme_tpu.wire import WIRE_MAGIC, WIRE_VERSION
from kme_tpu.workload import harness_stream, storm_stream


def oracle_lines(msgs, compat="fixed", **kw):
    eng = OracleEngine(compat, **kw)
    lines = []
    for m in msgs:
        lines.extend(r.wire() for r in eng.process(m))
    return eng, lines


def run_deriver(lines, **kw):
    d = FeedDeriver(**kw)
    raw = b""
    for i, ln in enumerate(lines):
        for f in d.on_line(ln, 1, i):
            raw += f.raw
    return d, raw


# ---------------------------------------------------------------------------
# frame codec


def test_codec_roundtrip_every_kind():
    d = decode_feed(ff.encode_delta(3, 7, 2, 99, 11, 1, 500, 40))[0]
    assert (d.kind, d.group, d.seq, d.src_epoch, d.src_seq) == (
        ff.FEED_DELTA, 3, 7, 2, 99)
    assert (d.sid, d.side, d.price, d.size) == (11, 1, 500, 40)

    t = decode_feed(ff.encode_tob(0, 1, 5, 6, 9, 100, 2, 101, 3,
                                  conflated=True))[0]
    assert t.kind == ff.FEED_TOB and t.conflated
    assert (t.bid_price, t.bid_size, t.ask_price, t.ask_size) == (
        100, 2, 101, 3)

    dp = decode_feed(ff.encode_depth(
        1, 4, 5, 6, 9, [(100, 2), (99, 1)], [(101, 7)],
        refresh=True))[0]
    assert dp.kind == ff.FEED_DEPTH and dp.refresh
    assert dp.bids == ((100, 2), (99, 1)) and dp.asks == ((101, 7),)

    sb = decode_feed(ff.encode_snap_begin(2, 5, 6, 12, depth=8))[0]
    assert (sb.kind, sb.count, sb.depth) == (ff.FEED_SNAP_BEGIN, 12, 8)
    se = decode_feed(ff.encode_snap_end(2, 5, 6, 12, b"payload"))[0]
    assert se.kind == ff.FEED_SNAP_END and se.count == 12
    import zlib

    assert se.crc == zlib.crc32(b"payload") & 0xFFFFFFFF

    rs = decode_feed(ff.encode_resync(0, 9, 5, 6, -1))[0]
    assert rs.kind == ff.FEED_RESYNC and rs.sid == -1 and rs.conflated

    # raw preserves the exact encoded bytes on decode
    raw = ff.encode_delta(0, 1, 1, 0, 1, 0, 10, 1)
    assert decode_feed(raw)[0].raw == raw


def _reason(buf):
    with pytest.raises(FeedFrameError) as ei:
        decode_feed(buf)
    return ei.value.reason


def test_codec_error_reasons_mirror_wire():
    good = ff.encode_delta(0, 1, 1, 0, 1, 0, 10, 1)
    assert _reason(good[:4]) == "truncated"
    assert _reason(good[:-1]) == "truncated"
    assert _reason(b"\x00" + good[1:]) == "bad_magic"
    assert _reason(good[:1] + b"\xfe" + good[2:]) == "version_skew"
    bad_kind = bytearray(good)
    bad_kind[2] = 0            # order-frame kind on a feed socket
    assert _reason(bytes(bad_kind)) == "bad_kind"
    bad_len = bytearray(good)
    struct.pack_into("<I", bad_len, 4, 8)     # < common prefix
    assert _reason(bytes(bad_len)) == "bad_length"
    # kind-specific body-size mismatch: delta envelope, tob-sized body
    mixed = bytearray(ff.encode_tob(0, 1, 1, 0, 1, 1, 1, 2, 2))
    mixed[2] = ff.FEED_DELTA
    assert _reason(bytes(mixed)) == "bad_length"
    # depth pair-count vs body-length cross check
    dep = bytearray(ff.encode_depth(0, 1, 1, 0, 1, [(1, 1)], []))
    struct.pack_into("<I", dep, 44, 2)        # nbid lies
    assert _reason(bytes(dep)) == "bad_length"


def test_codec_fuzz_never_hangs_or_misreports(monkeypatch=None):
    import random

    rng = random.Random(13)
    base = (ff.encode_delta(0, 1, 1, 0, 1, 0, 10, 1)
            + ff.encode_tob(0, 2, 1, 1, 1, 10, 1, 11, 2)
            + ff.encode_depth(0, 3, 1, 2, 1, [(10, 1)], [(11, 2)]))
    for _ in range(300):
        buf = bytearray(base)
        for _k in range(rng.randint(1, 4)):
            buf[rng.randrange(len(buf))] = rng.randrange(256)
        try:
            decode_feed_frames(bytes(buf))
        except FeedFrameError as e:
            assert e.reason in ("truncated", "bad_magic",
                                "version_skew", "bad_kind",
                                "bad_length")
    # truncation at every boundary of a valid frame
    f = ff.encode_tob(0, 1, 1, 0, 1, 10, 1, 11, 2)
    for cut in range(len(f)):
        assert ff.feed_frame_length(f[:cut], 0) is None or cut >= 8


def test_frame_constants_share_the_wire_envelope():
    raw = ff.encode_delta(0, 1, 1, 0, 1, 0, 10, 1)
    magic, version, kind, _fl, length = struct.unpack_from("<BBBBI",
                                                           raw)
    assert magic == WIRE_MAGIC and version == WIRE_VERSION
    assert kind == ff.FEED_DELTA and length == len(raw) == ff.DELTA_SIZE


# ---------------------------------------------------------------------------
# deriver vs oracle


@pytest.mark.parametrize("compat", ["fixed", "java"])
def test_deriver_books_match_oracle(compat):
    msgs = harness_stream(800, seed=11, num_accounts=8, num_symbols=4,
                          payout_opcode_bug=(compat == "java"),
                          validate=(compat == "fixed"))
    eng, lines = oracle_lines(msgs, compat)
    _d, raw = run_deriver(lines)
    bb = BookBuilder()
    assert bb.apply_buffer(raw) == len(raw)
    assert not bb.errors and not bb.gaps and bb.dups == 0
    assert canonical_books(bb.book) == canonical_books(
        books_from_oracle(eng))


@pytest.mark.parametrize("profile", ["payout-storm-wide", "hot-book"])
def test_deriver_books_match_oracle_under_storms(profile):
    msgs = storm_stream(profile, 1500, num_symbols=16, seed=3)
    eng, lines = oracle_lines(msgs)
    _d, raw = run_deriver(lines, depth_every=64)
    bb = BookBuilder()
    assert bb.apply_buffer(raw) == len(raw)
    assert not bb.errors and not bb.gaps and bb.dups == 0
    assert canonical_books(bb.book) == canonical_books(
        books_from_oracle(eng))
    # depth views agree at every requested depth, not just full book
    want = BookState()
    want.levels = books_from_oracle(eng)
    for sid in bb.book.sids():
        for n in (1, 4, 8, 0):
            assert bb.book.depth(sid, n) == want.depth(sid, n)
        assert bb.tob.get(sid, (0, 0, 0, 0)) == want.tob(sid)


def test_deriver_is_deterministic_and_densely_sequenced():
    msgs = storm_stream("flash-crowd", 900, num_symbols=8, seed=5)
    _eng, lines = oracle_lines(msgs)
    _d1, raw1 = run_deriver(lines, depth_every=32)
    _d2, raw2 = run_deriver(lines, depth_every=32)
    assert raw1 == raw2, "same stream, different frame bytes"
    # per-symbol seq is dense 1..N: a filtered subscriber still sees
    # no gaps (the reason seq is per-symbol, not per-channel)
    frames = decode_feed_frames(raw1)
    per = {}
    for f in frames:
        if f.kind in (ff.FEED_DELTA, ff.FEED_TOB) or (
                f.kind == ff.FEED_DEPTH and not f.refresh):
            per.setdefault(f.sid, []).append(f.seq)
    assert per, "stream derived no sequenced frames"
    for sid, seqs in per.items():
        assert seqs == list(range(1, len(seqs) + 1)), f"sid {sid}"
    # symbol-filtered builder: gap-free on its subset
    keep = sorted(per)[0]
    bb = BookBuilder()
    for f in frames:
        if f.sid == keep:
            bb.apply(f)
    assert not bb.gaps and bb.dups == 0
    # ... and a dropped frame IS a gap; a replayed one IS a dup
    seq_frames = [f for f in frames if f.sid == keep]
    bb2 = BookBuilder()
    for f in seq_frames[:1] + seq_frames[2:]:
        bb2.apply(f)
    assert bb2.gaps
    bb3 = BookBuilder()
    for f in seq_frames[:2] + seq_frames[1:2]:
        bb3.apply(f)
    assert bb3.dups == 1


# ---------------------------------------------------------------------------
# durable snapshots (checkpoint discipline)


def test_feed_snapshot_roundtrip_continues_byte_identically(tmp_path):
    msgs = harness_stream(600, seed=2, num_accounts=6, num_symbols=3,
                          payout_opcode_bug=False, validate=True)
    _eng, lines = oracle_lines(msgs)
    cut = len(lines) // 2
    d = FeedDeriver(depth_every=16)
    for i, ln in enumerate(lines[:cut]):
        d.on_line(ln, 1, i)
    path = save_feed_snapshot(str(tmp_path), d, cut)
    assert path == feed_snapshot_path(str(tmp_path), cut)
    off, restored = load_feed_snapshot(str(tmp_path))
    assert off == cut
    tail = b""
    tail_restored = b""
    for i, ln in enumerate(lines[cut:], start=cut):
        for f in d.on_line(ln, 1, i):
            tail += f.raw
        for f in restored.on_line(ln, 1, i):
            tail_restored += f.raw
    assert tail == tail_restored, "restored deriver forked the stream"


def test_feed_snapshot_corrupt_falls_back_then_none(tmp_path):
    msgs = harness_stream(200, seed=6, num_accounts=4, num_symbols=2,
                          payout_opcode_bug=False, validate=True)
    _eng, lines = oracle_lines(msgs)
    cut = len(lines) // 2
    d = FeedDeriver()
    for i, ln in enumerate(lines[:cut]):
        d.on_line(ln, 1, i)
    older = canonical_books(d.book)
    save_feed_snapshot(str(tmp_path), d, cut)
    for i, ln in enumerate(lines[cut:], start=cut):
        d.on_line(ln, 1, i)
    newest = save_feed_snapshot(str(tmp_path), d, len(lines))
    # flip a digit inside the newest state: digest verify must reject
    # it and the loader must fall back to the older snapshot
    blob = bytearray(open(newest, "rb").read())
    idx = blob.index(b'"watermark"') + len(b'"watermark":[')
    blob[idx] = ord("7") if blob[idx] != ord("7") else ord("8")
    open(newest, "wb").write(bytes(blob))
    off, restored = load_feed_snapshot(str(tmp_path))
    assert off == cut
    assert canonical_books(restored.book) == older
    # every snapshot corrupt -> None, not an exception
    for _o, p in list_feed_snapshots(str(tmp_path)):
        open(p, "w").write("{not json")
    assert load_feed_snapshot(str(tmp_path)) is None


def test_feed_snapshot_prunes_like_engine_checkpoints(tmp_path):
    d = FeedDeriver()
    for off in range(6):
        save_feed_snapshot(str(tmp_path), d, off, keep=3)
    offs = [o for o, _p in list_feed_snapshots(str(tmp_path))]
    assert offs == [5, 4, 3]


# ---------------------------------------------------------------------------
# snapshot-then-deltas splice edge cases (ISSUE 13 satellite)


def _splice(lines, cut, eng, depth_every=16, sids=None):
    """Serve a snapshot at `cut`, splice deltas from there, and return
    the late joiner's builder (asserting zero gap/dup/error)."""
    server = FeedDeriver(depth_every=depth_every)
    for i, ln in enumerate(lines[:cut]):
        server.on_line(ln, 1, i)
    handover = snapshot_frames(server, sids=sids)
    bb = BookBuilder()
    assert bb.apply_buffer(handover) == len(handover)
    assert bb.watermark == (1, cut - 1 if cut else -1)
    tail = b""
    for i, ln in enumerate(lines[cut:], start=cut):
        for f in server.on_line(ln, 1, i):
            if sids is None or f.sid in sids or f.kind in (
                    ff.FEED_SNAP_BEGIN, ff.FEED_SNAP_END):
                tail += f.raw
    assert bb.apply_buffer(tail) == len(tail)
    assert not bb.errors, bb.errors
    assert not bb.gaps and bb.dups == 0
    want = books_from_oracle(eng)
    if sids is not None:
        want = {k: v for k, v in want.items() if k[0] in sids}
    assert canonical_books(bb.book) == canonical_books(want)
    return bb


def test_splice_exactly_at_checkpoint_boundary(tmp_path):
    """A subscriber that joins at the precise offset a durable feed
    snapshot was written sees the identical reconstruction whether it
    splices off the live deriver or the restored one."""
    msgs = harness_stream(700, seed=9, num_accounts=8, num_symbols=4,
                          payout_opcode_bug=False, validate=True)
    eng, lines = oracle_lines(msgs)
    cut = len(lines) // 3
    live = FeedDeriver(depth_every=16)
    for i, ln in enumerate(lines[:cut]):
        live.on_line(ln, 1, i)
    save_feed_snapshot(str(tmp_path), live, cut)
    _off, restored = load_feed_snapshot(str(tmp_path))
    assert snapshot_frames(restored) == snapshot_frames(live), (
        "restored deriver serves a different wire snapshot")
    _splice(lines, cut, eng)


def test_splice_mid_payout_storm():
    """PAYOUT sweeps whole books away; joining in the middle of the
    storm must still reconstruct exactly (snapshot carries the swept
    state, deltas carry the rest of the sweep)."""
    msgs = storm_stream("payout-storm-wide", 1200, num_symbols=12,
                        seed=7)
    eng, lines = oracle_lines(msgs)
    payout_offs = [i for i, ln in enumerate(lines)
                   if ln.startswith("OUT") and " P " in f" {ln} "]
    # splice inside the storm body: between two payout records
    cut = (len(lines) // 2) | 1
    _splice(lines, cut, eng)
    # and with a filtered subscription (per-symbol seq must stay dense
    # through the sweep for the watched subset)
    sids = {m.sid for m in msgs if m.sid > 0}
    keep = {sorted(sids)[0], sorted(sids)[-1]}
    _splice(lines, cut, eng, sids=keep)


@pytest.mark.slow
def test_splice_during_shard_migration(cpu_devices):
    """PR 8: the elastic mesh migrates hot lanes between shards
    mid-stream. MatchOut bytes are placement-invariant, so a feed
    subscriber splicing while migrations are happening reconstructs
    the identical book — proven against the mesh's own output with
    migrations observed.

    slow: the mesh compile alone is ~60s on CPU; the CI feed job runs
    this file without the tier-1 marker filter, so the splice drill
    still gates every PR."""
    from kme_tpu.engine import seq as SQ
    from kme_tpu.parallel.seqmesh import SeqMeshSession
    from kme_tpu.workload import zipf_hot_stream

    cfg = dict(lanes=8, slots=128, accounts=128, max_fills=16,
               pos_cap=1 << 10, probe_max=8)
    msgs = zipf_hot_stream(1200, num_symbols=8, num_accounts=24,
                           seed=7)
    ses = SeqMeshSession(SQ.SeqConfig(**cfg), shards=2)
    lines = []
    for lo in range(0, len(msgs), 300):
        for per in ses.process_wire(msgs[lo:lo + 300]):
            lines.extend(per)
    assert ses.shard_stats()["migrations"] > 0, (
        "stream produced no migrations; splice test is vacuous")
    eng = OracleEngine("fixed", book_slots=cfg["slots"],
                       max_fills=cfg["max_fills"])
    want = []
    for m in msgs:
        want.extend(r.wire() for r in eng.process(m.copy()))
    assert lines == want, "mesh diverged from oracle"
    # splice mid-stream (migrations happen between batches throughout)
    bb = _splice(lines, len(lines) // 2, eng)
    # the full-replay builder agrees byte-for-byte with the splicer
    _d, raw = run_deriver(lines, depth_every=16)
    full = BookBuilder()
    assert full.apply_buffer(raw) == len(raw)
    assert canonical_books(bb.book) == canonical_books(full.book)


# ---------------------------------------------------------------------------
# server/client integration


def test_feed_server_fanout_filtered_and_wildcard(tmp_path):
    import threading

    from kme_tpu.bridge.broker import InProcessBroker
    from kme_tpu.feed.client import FeedClient
    from kme_tpu.feed.server import FeedServer, write_health
    from kme_tpu.telemetry.registry import Registry

    msgs = harness_stream(400, seed=4, num_accounts=6, num_symbols=3,
                          payout_opcode_bug=False, validate=True)
    eng, lines = oracle_lines(msgs)
    books = books_from_oracle(eng)
    sids = sorted({s for s, _side in books})
    broker = InProcessBroker(persist_dir=str(tmp_path / "b"))
    broker.create_topic("MatchOut")
    srv = FeedServer(broker, port=0, topic="MatchOut", depth_every=64,
                     registry=Registry())
    host, port = srv.address
    stop = threading.Event()
    th = threading.Thread(target=srv.serve_forever, args=(stop,),
                          daemon=True)
    th.start()
    clients = [FeedClient(host, port, symbols=None, timeout=5.0),
               FeedClient(host, port, symbols={sids[0]}, timeout=5.0)]
    try:
        deadline = time.monotonic() + 10
        while srv.stats()["subscribers"] < 2:
            assert time.monotonic() < deadline, "subscribe stalled"
            time.sleep(0.01)
        for i, ln in enumerate(lines):
            broker.produce("MatchOut", None, ln, epoch=1, out_seq=i,
                           ats=time.time_ns() // 1000)
        deadline = time.monotonic() + 15
        while srv.offset < len(lines) or srv.stats()["subscribers"]:
            if srv.offset >= len(lines):
                break
            assert time.monotonic() < deadline, "fan-out stalled"
            time.sleep(0.01)
        srv.drain(10.0)
        write_health(str(tmp_path / "feed.health"), srv)
    finally:
        srv.stop()
        stop.set()
        th.join(10)
        srv.close()
    for c in clients:
        c.drain()                       # to EOF after close()
        c.close()
        bb = c.builder
        assert not bb.errors and not bb.gaps and bb.dups == 0
    assert canonical_books(clients[0].builder.book) == canonical_books(
        books)
    assert canonical_books(clients[1].builder.book) == canonical_books(
        {k: v for k, v in books.items() if k[0] == sids[0]})
    # the heartbeat carries the registry snapshot kme-top renders
    doc = json.load(open(tmp_path / "feed.health"))
    assert doc["role"] == "feed"
    assert doc["metrics"]["gauges"]["feed_offset"] == len(lines)


def test_feed_cli_entrypoint_exists():
    from kme_tpu.cli import feed_main

    with pytest.raises(SystemExit):
        feed_main(["--help"])
