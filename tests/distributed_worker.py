"""Worker process for the 2-process jax.distributed multi-host test.

Each worker owns 4 virtual CPU devices; the two workers form one
8-device mesh via jax.distributed, and the sharded LaneSession runs
SPMD across the process boundary — the DCN topology of SURVEY.md §2.3
("cross-node comm backend"), validated without real hosts the idiomatic
JAX way. Usage (spawned by tests/test_multihost.py):

    python distributed_worker.py <coordinator> <nprocs> <pid> <outfile>
"""

import hashlib
import os
import sys

# The spawning test pins JAX_PLATFORMS=cpu and the 4-device XLA flag in
# this process's ENVIRONMENT (the axon site can initialize jax at
# interpreter startup, so setting os.environ here would be too late).

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    coordinator, nprocs, pid, outfile = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    import jax

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=nprocs, process_id=pid)
    assert jax.device_count() == 4 * nprocs, jax.devices()
    assert jax.process_count() == nprocs

    from kme_tpu.engine.lanes import LaneConfig
    from kme_tpu.runtime.session import LaneSession
    from kme_tpu.workload import zipf_symbol_stream

    cfg = LaneConfig(lanes=16, slots=128, accounts=64, max_fills=32,
                     steps=32)
    msgs = zipf_symbol_stream(1500, num_symbols=12, num_accounts=24,
                              seed=17)
    ses = LaneSession(cfg, shards=8)   # mesh spans both processes
    out = ses.process_wire(msgs)
    blob = "\n".join(l for ls in out for l in ls).encode()
    digest = hashlib.sha256(blob).hexdigest()
    with open(outfile, "w") as f:
        f.write(f"{digest} {len(blob)}\n")
    # keep both processes alive until collectives drain
    jax.effects_barrier()
    return 0


if __name__ == "__main__":
    sys.exit(main())
