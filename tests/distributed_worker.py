"""Worker process for the 2-process jax.distributed multi-host test.

Each worker owns 4 virtual CPU devices; the two workers form one
8-device mesh via jax.distributed, and the sharded LaneSession runs
SPMD across the process boundary — the DCN topology of SURVEY.md §2.3
("cross-node comm backend"), validated without real hosts the idiomatic
JAX way. Usage (spawned by tests/test_multihost.py):

    python distributed_worker.py <coordinator> <nprocs> <pid> <outfile> \
        [engine]

engine: 'lanes' (sharded sweep session, default) or 'seq' (the
symbol-sharded seq-kernel fleet, parallel/seqmesh.py).
"""

import hashlib
import os
import sys

# The spawning test pins JAX_PLATFORMS=cpu and the 4-device XLA flag in
# this process's ENVIRONMENT (the axon site can initialize jax at
# interpreter startup, so setting os.environ here would be too late).

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_session_and_stream(engine: str):
    """The (session, stream) pair for an engine — ONE definition shared
    by the workers and the in-test golden (the sha256 compare requires
    exact lockstep)."""
    from kme_tpu.workload import zipf_symbol_stream

    if engine == "seq":
        from kme_tpu.engine import seq as SQ
        from kme_tpu.parallel.seqmesh import SeqMeshSession

        msgs = zipf_symbol_stream(900, num_symbols=8, num_accounts=24,
                                  seed=17, zipf_a=1.0, payout_per_mille=5)
        ses = SeqMeshSession(
            SQ.SeqConfig(lanes=8, slots=128, accounts=128, max_fills=16,
                         pos_cap=1 << 10, probe_max=8), shards=8)
    else:
        from kme_tpu.engine.lanes import LaneConfig
        from kme_tpu.runtime.session import LaneSession

        cfg = LaneConfig(lanes=16, slots=128, accounts=64, max_fills=32,
                         steps=32)
        msgs = zipf_symbol_stream(1500, num_symbols=12, num_accounts=24,
                                  seed=17)
        ses = LaneSession(cfg, shards=8)   # mesh spans both processes
    return ses, msgs


def main() -> int:
    coordinator, nprocs, pid, outfile = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    engine = sys.argv[5] if len(sys.argv) > 5 else "lanes"
    import jax

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=nprocs, process_id=pid)
    assert jax.device_count() == 4 * nprocs, jax.devices()
    assert jax.process_count() == nprocs

    ses, msgs = build_session_and_stream(engine)
    out = ses.process_wire(msgs)
    blob = "\n".join(l for ls in out for l in ls).encode()
    digest = hashlib.sha256(blob).hexdigest()
    with open(outfile, "w") as f:
        f.write(f"{digest} {len(blob)}\n")
    # keep both processes alive until collectives drain
    jax.effects_barrier()
    return 0


if __name__ == "__main__":
    sys.exit(main())
