"""Test environment: force an 8-device virtual CPU mesh before JAX import.

The idiomatic JAX answer to "test distributed without a cluster"
(SURVEY.md §4): XLA's host platform is told to expose 8 devices, and every
sharding test runs over a real Mesh on them. The real-TPU bench path is
exercised separately by bench.py / the driver.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The axon terminal's sitecustomize registers the tunneled TPU and sets
# jax_platforms="axon,cpu" programmatically, which overrides the env var.
# Re-assert CPU before any backend initialization so tests run on the
# 8-device virtual host platform, not through the TPU tunnel.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs


@pytest.fixture(scope="session", autouse=True)
def _lockcheck_gate():
    """When tier-1 runs under KME_LOCKCHECK=1 (kme_tpu/__init__ patched
    the lock factories), fail the session if any lock-order inversion
    was observed across the whole run."""
    yield
    from kme_tpu.analysis import lockcheck

    if lockcheck.enabled():
        lockcheck.assert_clean()
