"""Codec parity fuzz: the native C++ wire codec (kme_wire.cpp) vs the
Python authority (wire.py), byte-exact.

Three surfaces, each fuzzed with seeded randomness so failures replay:

- parse: random order JSON (field subsets, nulls, negatives, int64
  extremes, junk that must force the Python re-parse) through
  WireBatch.parse_buffer vs parse_order per line;
- reconstruction: a random op-code-covering stream through one
  SeqSession on the native reconstructor and one forced onto the
  pure-Python path (_use_native_wire=False) — output lines AND
  per-order reject reason codes must match exactly;
- transport rows: the TCP wire's 3/5/6-element record rows ([o,k,v],
  +[epoch,out_seq], +[ats]) round-tripped through a real serve_broker
  socket.
"""

import json
import random

import pytest

from kme_tpu.wire import REJ_NAMES, OrderMsg, WireBatch, parse_order

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")

I64 = (1 << 63) - 1


def _random_order_line(rng: random.Random) -> str:
    """One order JSON line over the full field/value space the codec
    must agree on: any subset of fields, declaration order or not,
    null/absent/int pointers, negatives, int64 extremes."""
    fields = ["action", "oid", "aid", "sid", "price", "size",
              "next", "prev"]
    picks = [f for f in fields if rng.random() < 0.8]
    if rng.random() < 0.3:
        rng.shuffle(picks)
    obj = {}
    for f in picks:
        r = rng.random()
        if f in ("next", "prev") and r < 0.4:
            obj[f] = None
        elif r < 0.1:
            obj[f] = rng.choice([-I64 - 1, I64, 0, -1])
        elif r < 0.3:
            obj[f] = -rng.randrange(1 << 31)
        else:
            obj[f] = rng.randrange(1 << 31)
    return json.dumps(obj, separators=(",", ":"))


def test_parse_buffer_fuzz_matches_parse_order():
    rng = random.Random(0xC0DEC)
    lines = [_random_order_line(rng) for _ in range(500)]
    # spice with shapes that must kick the native parser onto the
    # Python authority (integral floats coerce, whitespace variants)
    lines += ['{"action":2.0,"oid":5}', '{"action":1,  "oid" : 9 }',
              '{}']
    buf = "\n".join(lines).encode()
    wb = WireBatch.parse_buffer(buf)
    want = [parse_order(ln) for ln in lines]
    assert wb.n == len(want)
    for i, m in enumerate(want):
        got = OrderMsg(int(wb.action[i]), int(wb.oid[i]), int(wb.aid[i]),
                       int(wb.sid[i]), int(wb.price[i]), int(wb.size[i]),
                       int(wb.next[i]) if wb.hnext[i] else None,
                       int(wb.prev[i]) if wb.hprev[i] else None)
        assert got == m, f"line {i}: {lines[i]!r}"


def test_parse_buffer_rejects_malformed_exactly_like_python():
    for bad in ('{"action":2 "oid":1}', "not json", '{"action":}',
                '{"action":"3","size":"7"}', '{"price":2.5}'):
        buf = ("\n".join(['{"action":2,"oid":1}', bad])).encode()
        with pytest.raises(ValueError):
            WireBatch.parse_buffer(buf)


def _fuzz_stream(rng: random.Random, n: int):
    """An op-covering message stream: deposits, both order sides at
    colliding price levels (fills + partial fills), cancels (live and
    bogus), oversized orders (risk rejects), unknown accounts
    (unroutable), payouts — every reject reason code reachable in
    fixed mode shows up."""
    from kme_tpu.wire import OrderMsg

    msgs = []
    oid = 1
    for a in range(6):
        msgs.append(OrderMsg(action=0, oid=0, aid=a + 1, sid=0,
                             price=0, size=1_000_000))
    live = []
    for _ in range(n):
        r = rng.random()
        aid = rng.randrange(1, 7)
        sid = rng.randrange(2)
        price = rng.randrange(1, 50)
        size = rng.randrange(1, 20)
        if r < 0.35:
            msgs.append(OrderMsg(action=1, oid=oid, aid=aid, sid=sid,
                                 price=price, size=size))
            live.append(oid)
            oid += 1
        elif r < 0.7:
            msgs.append(OrderMsg(action=2, oid=oid, aid=aid, sid=sid,
                                 price=price, size=size))
            live.append(oid)
            oid += 1
        elif r < 0.8 and live:
            msgs.append(OrderMsg(action=3,
                                 oid=rng.choice(live), aid=aid,
                                 sid=sid, price=0, size=0))
        elif r < 0.85:
            # unknown-oid cancel: host router reject (rej_unroutable)
            msgs.append(OrderMsg(action=3, oid=99_999_999, aid=aid,
                                 sid=sid, price=0, size=0))
        elif r < 0.9:
            # oversized: margin check refuses (rej_risk)
            msgs.append(OrderMsg(action=1, oid=oid, aid=aid, sid=sid,
                                 price=10_000_000, size=10_000_000))
            oid += 1
        elif r < 0.95:
            # unknown account (never deposited): unroutable
            msgs.append(OrderMsg(action=2, oid=oid, aid=777_777,
                                 sid=sid, price=price, size=size))
            oid += 1
        else:
            msgs.append(OrderMsg(action=0, oid=0, aid=aid, sid=sid,
                                 price=0, size=size))
    return msgs


def test_recon_fuzz_native_vs_python_byte_exact():
    from kme_tpu.engine import seq as SQ
    from kme_tpu.native import load_library
    from kme_tpu.runtime.seqsession import SeqSession

    if load_library() is None:
        pytest.skip("native library unavailable (KME_NATIVE=0 or no "
                    "toolchain): both paths would be Python")
    rng = random.Random(7)
    msgs = _fuzz_stream(rng, 400)
    cfg = SQ.SeqConfig(lanes=8, slots=128, accounts=128, max_fills=32,
                       batch=128, pos_cap=1 << 11, fill_cap=1 << 12,
                       probe_max=16)
    nat, py = SeqSession(cfg), SeqSession(cfg)
    py._use_native_wire = False
    for lo in range(0, len(msgs), 128):
        chunk = msgs[lo:lo + 128]
        out_n = nat.process_wire(chunk)
        out_p = py.process_wire(chunk)
        assert out_n == out_p, f"batch at {lo} diverged"
        rn = [REJ_NAMES.get(int(c), c) for c in nat.last_reasons]
        rp = [REJ_NAMES.get(int(c), c) for c in py.last_reasons]
        assert rn == rp, f"reject reason codes diverged at {lo}"


def _random_frame_msg(rng: random.Random) -> OrderMsg:
    def i64():
        r = rng.random()
        if r < 0.1:
            return rng.choice([-I64 - 1, I64, 0, -1])
        if r < 0.3:
            return -rng.randrange(1 << 31)
        return rng.randrange(1 << 31)

    return OrderMsg(action=i64(), oid=i64(), aid=i64(), sid=i64(),
                    price=i64(), size=i64(),
                    next=None if rng.random() < 0.4 else i64(),
                    prev=None if rng.random() < 0.4 else i64())


def _mangle(rng: random.Random, buf: bytes):
    """One seeded corruption of a valid frame buffer -> (bad_buf,
    expected reason). Covers the ISSUE's fuzz classes: truncation
    (header- and body-level), version skew, flipped kind byte,
    oversized/undersized length prefix, trashed magic."""
    from kme_tpu.wire import FRAME_SIZE

    b = bytearray(buf)
    nf = len(b) // FRAME_SIZE
    fo = rng.randrange(nf) * FRAME_SIZE
    kind = rng.randrange(5)
    if kind == 0:       # truncate inside a header or body
        cut = fo + rng.randrange(1, FRAME_SIZE)
        return bytes(b[:cut]), "truncated"
    if kind == 1:       # version skew
        b[fo + 1] = rng.choice([0, 2, 7, 255])
        return bytes(b), "version_skew"
    if kind == 2:       # flipped kind byte
        b[fo + 2] = rng.choice([1, 2, 3, 255])
        return bytes(b), "bad_kind"
    if kind == 3:       # oversized / undersized length prefix
        bad_len = rng.choice([0, 8, FRAME_SIZE - 1, FRAME_SIZE + 1,
                              1 << 20, 0xFFFFFFFF])
        b[fo + 4:fo + 8] = bad_len.to_bytes(4, "little")
        return bytes(b), "bad_length"
    b[fo] = rng.choice([0, ord("{"), 0xB0, 0xFF])   # trashed magic
    return bytes(b), "bad_magic"


def test_binary_frame_fuzz_rejects_cleanly():
    """Corrupted 72-byte frame buffers must raise WireFrameError with
    the right reason and the rej_malformed class — never crash, never
    mis-parse — through BOTH parse entry points (decode authority and
    the batch parser, native or numpy)."""
    from kme_tpu.wire import (REJ_MALFORMED, WireBatch, WireFrameError,
                              decode_frames, encode_frames)

    rng = random.Random(0xF4A3)
    for trial in range(200):
        msgs = [_random_frame_msg(rng)
                for _ in range(rng.randrange(1, 12))]
        buf = encode_frames(msgs)
        bad, reason = _mangle(rng, buf)
        with pytest.raises(WireFrameError) as e1:
            decode_frames(bad)
        with pytest.raises(WireFrameError) as e2:
            WireBatch.parse_frames(bad)
        for exc in (e1.value, e2.value):
            assert exc.reason == reason, (
                f"trial {trial}: want {reason}, got {exc.reason}")
            assert exc.code == REJ_MALFORMED
        # both entry points walk back through the same authority, so
        # the message text is identical too
        assert str(e1.value) == str(e2.value), f"trial {trial}"


def test_binary_frame_fuzz_roundtrip_clean_buffers():
    """Seeded clean buffers round-trip byte-exactly: encode -> batch
    parse -> per-column compare vs the scalar decoder."""
    from kme_tpu.wire import WireBatch, decode_frames, encode_frames

    rng = random.Random(0xBEEF)
    for _ in range(50):
        msgs = [_random_frame_msg(rng)
                for _ in range(rng.randrange(0, 32))]
        buf = encode_frames(msgs)
        wb = WireBatch.parse_frames(buf)
        want = decode_frames(buf)
        assert wb.n == len(want) == len(msgs)
        for i, m in enumerate(want):
            got = OrderMsg(
                int(wb.action[i]), int(wb.oid[i]), int(wb.aid[i]),
                int(wb.sid[i]), int(wb.price[i]), int(wb.size[i]),
                int(wb.next[i]) if wb.hnext[i] else None,
                int(wb.prev[i]) if wb.hprev[i] else None)
            assert got == m == msgs[i]


def test_binary_envelope_fuzz_over_tcp():
    """Malformed binary PRODUCE envelopes through a real socket: the
    server answers a clean rej_malformed JSON error and the connection
    stays in lockstep for the next (valid) request."""
    import struct

    from kme_tpu.bridge.tcp import (_ENV_HDR, _ENV_META, TcpBroker,
                                    serve_broker)
    from kme_tpu.wire import (FRAME_PRODUCE, WIRE_MAGIC, WIRE_VERSION,
                              encode_frames)

    srv, broker = serve_broker("127.0.0.1", 0)
    broker.create_topic("T")
    cli = TcpBroker(*srv.server_address[:2])
    rng = random.Random(0x7CB)
    try:
        frames = encode_frames([_random_frame_msg(rng)
                                for _ in range(4)])
        tb = b"T"
        good_body = (struct.pack("<H", len(tb)) + tb + bytes([255])
                     + _ENV_META.pack(-(1 << 63), -(1 << 63),
                                      -(1 << 63)) + frames)
        cases = [
            # version skew in the envelope header
            _ENV_HDR.pack(WIRE_MAGIC, 9, FRAME_PRODUCE, 0,
                          len(good_body)) + good_body,
            # flipped kind byte
            _ENV_HDR.pack(WIRE_MAGIC, WIRE_VERSION, 7, 0,
                          len(good_body)) + good_body,
            # body too short for its own topic/meta header
            _ENV_HDR.pack(WIRE_MAGIC, WIRE_VERSION, FRAME_PRODUCE,
                          0, 1) + b"\x00",
            # frames themselves corrupted (version skew inside frame 0;
            # same byte count, so the stream cannot desync)
            _ENV_HDR.pack(WIRE_MAGIC, WIRE_VERSION, FRAME_PRODUCE,
                          0, len(good_body))
            + good_body[:-len(frames)]
            + bytes([frames[0], 9]) + frames[2:],
        ]
        for i, payload in enumerate(cases):
            with pytest.raises(ValueError):
                cli._roundtrip(payload)
            # stream must still be usable: a valid produce lands
            n, _last = cli.produce_frames("T", None, frames)
            assert n == 4, f"case {i} poisoned the connection"
        assert broker.end_offset("T") == 4 * len(cases)
    finally:
        cli.close()
        srv.shutdown()


def test_tcp_rows_roundtrip_3_5_6_elements():
    """The transport's shortest-lossless row shapes: [o,k,v] (reloaded
    log records, no stamps), +[epoch,out_seq] (exactly-once stamped),
    +[ats] (broker-admitted). A fetch through a real socket must hand
    back exactly what the broker holds."""
    from kme_tpu.bridge.broker import InProcessBroker, Record
    from kme_tpu.bridge.tcp import TcpBroker, serve_broker

    broker = InProcessBroker()
    broker.create_topic("T")
    srv, broker = serve_broker("127.0.0.1", 0, broker)
    try:
        host, port = srv.server_address[:2]
        cli = TcpBroker(host, port)
        # produce through the broker API stamps ats (6-element row)
        # and epoch/out_seq when given (still 6 with ats)
        broker.produce("T", "IN", '{"action":0}')
        broker.produce("T", "OUT", '{"action":2}', epoch=3, out_seq=0)
        # a reloaded-log record carries no ats: forge the in-memory
        # shape the loader produces (3- and 5-element rows)
        t = broker._topics["T"]
        t.log.append(Record(len(t.log), "K3", "v3"))
        t.log.append(Record(len(t.log), "K5", "v5", epoch=7, out_seq=9))
        got = cli.fetch("T", 0, 16, timeout=0.2)
        assert [r.key for r in got] == ["IN", "OUT", "K3", "K5"]
        assert got[0].ats is not None and got[0].epoch is None
        assert got[1].epoch == 3 and got[1].out_seq == 0
        assert got[1].ats is not None
        assert (got[2].epoch, got[2].out_seq, got[2].ats) == (
            None, None, None)
        assert (got[3].epoch, got[3].out_seq, got[3].ats) == (7, 9, None)
        # round-trip: what came over the socket re-serializes to the
        # identical row shape the server sent
        from kme_tpu.bridge.tcp import _row
        assert _row(got[2]) == [2, "K3", "v3"]
        assert _row(got[3]) == [3, "K5", "v5", 7, 9]
        assert len(_row(got[0])) == 6 and len(_row(got[1])) == 6
    finally:
        srv.shutdown()


def test_traced_frame_fuzz_roundtrip_and_rejects():
    """Trace-word carriage (80-byte FLAG_TID frames) fuzzed through
    both parse entry points: uniform-traced buffers (the vectorized
    fast path), mixed 72/80-byte buffers (the walking authority), and
    untraced controls must all hand back identical (msg, tid) pairs —
    and seeded corruption must reject with identical reason AND
    message text from both, including the traced-specific length
    confusion (a 72-byte length prefix on a frame whose flags claim
    FLAG_TID, and vice versa)."""
    from kme_tpu.wire import (FRAME_SIZE, FRAME_SIZE_TRACED, WireBatch,
                              WireFrameError, decode_frames_tid,
                              encode_frames)

    rng = random.Random(0x71D)
    for trial in range(120):
        n = rng.randrange(1, 16)
        msgs = [_random_frame_msg(rng) for _ in range(n)]
        style = trial % 3
        if style == 0:      # uniform traced: vectorized decode
            tids = [rng.randrange(1, 1 << 63) for _ in range(n)]
        elif style == 1:    # mixed: must fall to the walking decoder
            tids = [rng.randrange(1, 1 << 63) if rng.random() < 0.5
                    else None for _ in range(n)]
        else:               # untraced control
            tids = [None] * n
        buf = encode_frames(msgs, tids=tids)
        assert decode_frames_tid(buf) == list(zip(msgs, tids))
        wb = WireBatch.parse_frames(buf)
        assert wb.n == n
        for i in range(n):
            assert wb.record_tid(i) == tids[i], f"trial {trial} row {i}"
        # seeded corruption: walk the mixed-length layout so the
        # mangled byte lands inside a chosen real frame
        offs, lens, off = [], [], 0
        for t in tids:
            offs.append(off)
            ln = FRAME_SIZE_TRACED if t is not None else FRAME_SIZE
            lens.append(ln)
            off += ln
        j = rng.randrange(n)
        b = bytearray(buf)
        kind = rng.randrange(3)
        if kind == 0:       # truncate inside frame j
            bad = bytes(b[:offs[j] + rng.randrange(1, lens[j])])
            reason = "truncated"
        elif kind == 1:     # trashed magic
            b[offs[j]] = rng.choice([0, ord("{"), 0xB0, 0xFF])
            bad, reason = bytes(b), "bad_magic"
        else:               # length prefix contradicts the FLAG_TID bit
            wrong = (FRAME_SIZE if tids[j] is not None
                     else FRAME_SIZE_TRACED)
            b[offs[j] + 4:offs[j] + 8] = wrong.to_bytes(4, "little")
            bad, reason = bytes(b), "bad_length"
        with pytest.raises(WireFrameError) as e1:
            decode_frames_tid(bad)
        with pytest.raises(WireFrameError) as e2:
            WireBatch.parse_frames(bad)
        assert e1.value.reason == e2.value.reason == reason, (
            f"trial {trial}: want {reason}, got "
            f"{e1.value.reason}/{e2.value.reason}")
        assert str(e1.value) == str(e2.value), f"trial {trial}"
