"""Exactly-once visible output: leader-epoch lease grants, broker-side
fencing + idempotent produce (in-process and over the TCP wire),
consumer-side dedup, the service's crash-replay stamp regeneration and
the lease.steal self-fence."""

import json
import os

import pytest

from kme_tpu import faults
from kme_tpu.bridge import lease
from kme_tpu.bridge.broker import (BrokerFenced, InProcessBroker)
from kme_tpu.bridge.consume import DedupRing
from kme_tpu.bridge.provision import provision
from kme_tpu.bridge.service import TOPIC_IN, TOPIC_OUT, MatchService
from kme_tpu.wire import dumps_order
from kme_tpu.workload import harness_stream


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# the lease


def test_lease_epochs_are_monotonic(tmp_path):
    d = str(tmp_path)
    assert lease.current_epoch(d) == 0
    assert lease.acquire(d) == 1
    assert lease.acquire(d) == 2
    assert lease.current_epoch(d) == 2
    rec = lease.read(d)
    assert rec["epoch"] == 2 and rec["role"] == "leader"
    assert rec["pid"] == os.getpid()


def test_lease_steal_advances_without_cooperation(tmp_path):
    d = str(tmp_path)
    assert lease.acquire(d) == 1
    assert lease.steal(d) == 2
    assert lease.read(d)["role"] == "stolen"
    assert lease.acquire(d) == 3       # a later grant continues past it


def test_lease_corruption_degrades_to_epoch_zero(tmp_path):
    d = str(tmp_path)
    lease.acquire(d)
    with open(os.path.join(d, lease.LEASE_FILE), "w") as f:
        f.write("{torn")
    assert lease.read(d) == {}
    assert lease.current_epoch(d) == 0
    assert lease.acquire(d) == 1       # restart is slower, never dupes


# ---------------------------------------------------------------------------
# broker-side fencing + idempotent produce


def test_stamped_produce_fences_stale_epochs():
    b = InProcessBroker()
    provision(b)
    assert b.produce(TOPIC_OUT, "OUT", "a", epoch=2, out_seq=0) == 0
    with pytest.raises(BrokerFenced) as ei:
        b.produce(TOPIC_OUT, "OUT", "zombie", epoch=1, out_seq=99)
    assert ei.value.code == "fenced"
    assert b.fenced_produces == 1
    assert b.fence_epoch == 2
    # nothing was appended by the fenced produce
    assert [r.value for r in b.fetch(TOPIC_OUT, 0)] == ["a"]


def test_idempotent_produce_suppresses_replayed_stamps():
    b = InProcessBroker()
    provision(b)
    for i in range(3):
        b.produce(TOPIC_OUT, "OUT", f"v{i}", epoch=1, out_seq=i)
    # the deterministic replay: same stamps, same payloads
    for i in range(3):
        assert b.produce(TOPIC_OUT, "OUT", f"v{i}", epoch=1,
                         out_seq=i) == -1
    assert b.dup_suppressed == 3
    assert b.produce(TOPIC_OUT, "OUT", "v3", epoch=1, out_seq=3) == 3
    assert [r.value for r in b.fetch(TOPIC_OUT, 0)] == \
        ["v0", "v1", "v2", "v3"]


def test_explicit_fence_rejects_the_previous_epoch():
    """A promoted leader must fence BEFORE the zombie's next produce:
    the reloaded log only teaches prior epochs, fence() closes the
    same-epoch gap."""
    b = InProcessBroker()
    provision(b)
    b.produce(TOPIC_OUT, "OUT", "a", epoch=1, out_seq=0)
    b.fence(2)
    with pytest.raises(BrokerFenced):
        b.produce(TOPIC_OUT, "OUT", "late", epoch=1, out_seq=1)
    assert b.produce(TOPIC_OUT, "OUT", "new", epoch=2, out_seq=1) == 1
    b.fence(1)                         # fence never regresses
    assert b.fence_epoch == 2


def test_stamps_watermark_and_fence_recover_from_reload(tmp_path):
    d = str(tmp_path)
    b = InProcessBroker(persist_dir=d)
    provision(b)
    b.produce(TOPIC_OUT, "OUT", "plain")            # unstamped: 2-elem
    for i in range(4):
        b.produce(TOPIC_OUT, "OUT", f"s{i}", epoch=3, out_seq=i)
    rows = [json.loads(ln)
            for ln in open(os.path.join(d, f"{TOPIC_OUT}.log"))]
    assert [len(r) for r in rows] == [2, 4, 4, 4, 4]
    assert rows[1][2:] == [3, 0]

    b2 = InProcessBroker(persist_dir=d)             # crash + reload
    assert b2.fence_epoch == 3
    # replayed stamps vanish; stale epochs die
    assert b2.produce(TOPIC_OUT, "OUT", "s3", epoch=3, out_seq=3) == -1
    assert b2.dup_suppressed == 1
    with pytest.raises(BrokerFenced):
        b2.produce(TOPIC_OUT, "OUT", "x", epoch=2, out_seq=10)
    assert b2.produce(TOPIC_OUT, "OUT", "s4", epoch=3, out_seq=4) >= 0
    recs = b2.fetch(TOPIC_OUT, 0, 100)
    assert [r.value for r in recs] == ["plain", "s0", "s1", "s2",
                                      "s3", "s4"]
    assert recs[0].epoch is None and recs[1].epoch == 3


def test_stamps_round_trip_over_tcp():
    from kme_tpu.bridge.tcp import TcpBroker, serve_broker

    srv, broker = serve_broker("127.0.0.1", 0)
    try:
        host, port = srv.server_address[:2]
        c = TcpBroker(host, port, timeout=5.0)
        provision(c)
        assert c.produce(TOPIC_OUT, "OUT", "a", epoch=2, out_seq=0) == 0
        assert c.produce(TOPIC_OUT, "OUT", "a", epoch=2, out_seq=0) == -1
        with pytest.raises(BrokerFenced):
            c.produce(TOPIC_OUT, "OUT", "z", epoch=1, out_seq=5)
        c.fence(3)
        with pytest.raises(BrokerFenced):
            c.produce(TOPIC_OUT, "OUT", "z", epoch=2, out_seq=5)
        recs = c.fetch(TOPIC_OUT, 0, 10)
        assert [(r.value, r.epoch, r.out_seq) for r in recs] == \
            [("a", 2, 0)]
        # unstamped records keep the 3-element wire row
        c.produce(TOPIC_IN, None, "plain")
        rec = c.fetch(TOPIC_IN, 0, 1)[0]
        assert rec.epoch is None and rec.out_seq is None
        c.close()
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# consumer-side dedup ring


def test_dedup_ring_counts_and_passes_unstamped():
    ring = DedupRing(capacity=128)
    assert not ring.is_dup(1, 0)
    assert ring.is_dup(1, 0)
    assert not ring.is_dup(2, 0)       # same seq, new epoch: distinct
    assert not ring.is_dup(None, None)
    assert not ring.is_dup(None, None)  # unstamped never dedups
    assert ring.suppressed == 1


def test_dedup_ring_capacity_evicts_oldest():
    ring = DedupRing(capacity=2)
    assert not ring.is_dup(1, 0)
    assert not ring.is_dup(1, 1)
    assert not ring.is_dup(1, 2)       # evicts (1, 0)
    assert not ring.is_dup(1, 0)       # forgotten: passes again
    assert ring.is_dup(1, 2)           # still in the ring


# ---------------------------------------------------------------------------
# the service: crash, resume, replay — zero visible duplicates


def _feed(broker, n=80, seed=3):
    msgs = harness_stream(n, seed=seed, num_accounts=4, num_symbols=2,
                          payout_opcode_bug=False, validate=True)
    for m in msgs:
        broker.produce(TOPIC_IN, None, dumps_order(m))
    return len(msgs)


def test_crash_replay_regenerates_identical_stamps(tmp_path):
    """The whole point of the stamps: a leader killed AFTER producing
    but BEFORE checkpointing re-produces its post-snapshot tail with
    the same (epoch, out_seq) stamps, and the broker's watermark keeps
    the durable log duplicate-free — byte-exact, exactly once."""
    ck = str(tmp_path / "ck")
    logd = str(tmp_path / "logs")
    b = InProcessBroker(persist_dir=logd)
    provision(b)
    n = _feed(b)

    svc = MatchService(b, engine="oracle", compat="fixed", batch=16,
                       slots=64, max_fills=32, checkpoint_dir=ck,
                       exactly_once=True)
    assert svc.epoch == 1
    assert svc.run(max_messages=48) == 48
    svc.checkpoint()                  # snapshot carries out_seq cursor
    seq_at_ckpt = svc.out_seq
    assert svc.run(max_messages=16) == 16   # past the snapshot...
    produced = b.end_offset(TOPIC_OUT)
    del svc                           # ...then SIGKILL (no teardown)

    b2 = InProcessBroker(persist_dir=logd)  # broker reload
    svc2 = MatchService(b2, engine="oracle", compat="fixed", batch=16,
                        slots=64, max_fills=32, checkpoint_dir=ck,
                        exactly_once=True)
    assert svc2.epoch == 2            # fresh epoch, predecessors fenced
    assert svc2.offset == 48 and svc2.out_seq == seq_at_ckpt
    assert svc2.run(max_messages=n - 48) == n - 48

    recs = b2.fetch(TOPIC_OUT, 0, 10 ** 6)
    # the 16-message overlap was re-produced and suppressed
    assert b2.dup_suppressed > 0
    ring = DedupRing()
    assert not any(ring.is_dup(r.epoch, r.out_seq) for r in recs)
    # byte-exact against a clean single-incarnation run
    b3 = InProcessBroker()
    provision(b3)
    _feed(b3)
    ref = MatchService(b3, engine="oracle", compat="fixed", batch=16,
                       slots=64, max_fills=32)
    ref.run(max_messages=n)
    want = [r.value for r in b3.fetch(TOPIC_OUT, 0, 10 ** 6)]
    assert [r.value for r in recs] == want
    assert produced <= len(recs)      # nothing visible was lost
    snap = svc2.telemetry.snapshot()["gauges"]
    assert snap["leader_epoch"] == 2
    assert snap["dup_suppressed_total"] == b2.dup_suppressed


def test_follower_counts_but_discards_output(tmp_path):
    """Follower mode: produces are discarded by the follow broker, but
    the out_seq cursor still advances so a promotion continues the
    stamp stream exactly where the durable log ends."""
    ck = str(tmp_path / "ck")
    b = InProcessBroker()
    provision(b)
    n = _feed(b, n=40)
    svc = MatchService(b, engine="oracle", compat="fixed", batch=16,
                       slots=64, max_fills=32, checkpoint_dir=ck,
                       exactly_once=True, follower=True)
    assert svc.epoch is None          # no lease held while following
    assert svc.run(max_messages=n) == n
    assert svc.out_seq > 0
    assert lease.current_epoch(ck) == 0


def test_lease_steal_self_fences_the_checkpoint(tmp_path):
    """The lease.steal drill: a rival grabs the next epoch right before
    our checkpoint — the deposed leader must refuse to write (its
    snapshot would roll the new leader's state machine back) and die
    fenced."""
    ck = str(tmp_path / "ck")
    b = InProcessBroker()
    provision(b)
    _feed(b, n=30)
    svc = MatchService(b, engine="oracle", compat="fixed", batch=16,
                       slots=64, max_fills=32, checkpoint_dir=ck,
                       exactly_once=True)
    svc.run(max_messages=30)
    faults.configure("lease.steal")
    with pytest.raises(BrokerFenced, match="superseded"):
        svc.checkpoint()
    assert lease.current_epoch(ck) == 2        # the rival's epoch
    with pytest.raises(BrokerFenced):          # and we are broker-fenced
        b.produce(TOPIC_OUT, "OUT", "late", epoch=svc.epoch,
                  out_seq=svc.out_seq)
