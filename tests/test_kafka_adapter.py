"""Contract tests for the aiokafka transport (bridge/kafka.py).

aiokafka is not installed in CI; a minimal FAKE of the client API the
adapter uses (producer, consumer, admin, TopicPartition) backed by an
in-memory log stands in, so what is pinned here is the ADAPTER's logic:
offset bookkeeping across seeks, key/value codecs, partition-0 pinning,
create-topic-exists semantics — and that the full MatchService engine
loop runs end-to-end against the adapter surface, byte-exact vs the
oracle."""

import asyncio
import sys
import types

import pytest

from kme_tpu.oracle import OracleEngine
from kme_tpu.wire import dumps_order
from kme_tpu.workload import harness_stream

TOPIC_IN, TOPIC_OUT = "MatchIn", "MatchOut"


# ---------------------------------------------------------------------------
# the fake aiokafka

class _TP:
    def __init__(self, topic, partition):
        self.topic, self.partition = topic, partition

    def __hash__(self):
        return hash((self.topic, self.partition))

    def __eq__(self, o):
        return (self.topic, self.partition) == (o.topic, o.partition)


class _Msg:
    def __init__(self, offset, key, value):
        self.offset, self.key, self.value = offset, key, value


class _Meta:
    def __init__(self, offset):
        self.offset = offset


class _Cluster:
    def __init__(self):
        self.logs = {}          # topic -> list[(key, value)]


class _Producer:
    def __init__(self, cluster, **kw):
        self._c = cluster

    async def start(self):
        pass

    async def stop(self):
        pass

    async def flush(self):
        pass

    async def send_and_wait(self, topic, value, key=None, partition=0):
        assert partition == 0
        log = self._c.logs.setdefault(topic, [])
        log.append((key, value))
        return _Meta(len(log) - 1)


class _Consumer:
    def __init__(self, cluster, **kw):
        self._c = cluster
        self._pos = {}

    async def start(self):
        pass

    async def stop(self):
        pass

    def assign(self, tps):
        for tp in tps:
            self._pos.setdefault(tp, 0)

    def seek(self, tp, offset):
        self._pos[tp] = offset

    async def getmany(self, *tps, timeout_ms=0, max_records=1024):
        out = {}
        for tp in tps:
            log = self._c.logs.get(tp.topic, [])
            pos = self._pos.get(tp, 0)
            msgs = [_Msg(o, k, v)
                    for o, (k, v) in enumerate(log[pos:pos + max_records],
                                               start=pos)]
            if msgs:
                self._pos[tp] = msgs[-1].offset + 1
                out[tp] = msgs
        return out

    async def end_offsets(self, tps):
        return {tp: len(self._c.logs.get(tp.topic, [])) for tp in tps}


class _Admin:
    def __init__(self, cluster, **kw):
        self._c = cluster

    async def start(self):
        pass

    async def close(self):
        pass

    async def list_topics(self):
        return list(self._c.logs)

    async def create_topics(self, news):
        for n in news:
            self._c.logs.setdefault(n.name, [])


class _NewTopic:
    def __init__(self, name, num_partitions, replication_factor):
        self.name = name


def _install_fake(monkeypatch):
    cluster = _Cluster()
    mod = types.ModuleType("aiokafka")
    mod.TopicPartition = _TP
    mod.AIOKafkaProducer = lambda **kw: _Producer(cluster, **kw)
    mod.AIOKafkaConsumer = lambda **kw: _Consumer(cluster, **kw)
    admin = types.ModuleType("aiokafka.admin")
    admin.AIOKafkaAdminClient = lambda **kw: _Admin(cluster, **kw)
    admin.NewTopic = _NewTopic
    mod.admin = admin
    monkeypatch.setitem(sys.modules, "aiokafka", mod)
    monkeypatch.setitem(sys.modules, "aiokafka.admin", admin)
    return cluster


# ---------------------------------------------------------------------------

def test_kafka_adapter_contract(monkeypatch):
    _install_fake(monkeypatch)
    from kme_tpu.bridge.kafka import KafkaBroker

    b = KafkaBroker("fake:9092")
    assert b.create_topic(TOPIC_IN) is True
    assert b.create_topic(TOPIC_IN) is False        # kafkajs semantics
    assert b.create_topic(TOPIC_OUT) is True
    assert set(b.topics()) == {TOPIC_IN, TOPIC_OUT}

    assert b.produce(TOPIC_IN, None, "a") == 0
    assert b.produce(TOPIC_IN, "IN", "b") == 1
    assert b.end_offset(TOPIC_IN) == 2
    recs = b.fetch(TOPIC_IN, 0)
    assert [(r.offset, r.key, r.value) for r in recs] == [
        (0, None, "a"), (1, "IN", "b")]
    # re-fetch from an arbitrary offset (seek path)
    recs = b.fetch(TOPIC_IN, 1)
    assert [(r.offset, r.value) for r in recs] == [(1, "b")]
    # sequential fetch continues without a seek
    b.produce(TOPIC_IN, None, "c")
    recs = b.fetch(TOPIC_IN, 2)
    assert [(r.offset, r.value) for r in recs] == [(2, "c")]
    b.sync()
    b.close()


def test_match_service_over_kafka_adapter(monkeypatch):
    """The full engine loop against the Kafka transport surface:
    provision, produce the harness stream, run MatchService, and the
    MatchOut stream must equal the oracle's byte-for-byte."""
    _install_fake(monkeypatch)
    from kme_tpu.bridge.kafka import KafkaBroker
    from kme_tpu.bridge.provision import provision
    from kme_tpu.bridge.service import MatchService

    msgs = harness_stream(300, seed=9, num_symbols=4, num_accounts=8,
                          payout_opcode_bug=False, validate=True)
    ora = OracleEngine("fixed", book_slots=64, max_fills=32)
    want = []
    for m in msgs:
        for r in ora.process(m.copy()):
            want.append(f"{r.key} {dumps_order(r.msg)}"
                        if hasattr(r, "msg") else r.wire())

    b = KafkaBroker("fake:9092")
    provision(b)
    for m in msgs:
        b.produce(TOPIC_IN, None, dumps_order(m))
    svc = MatchService(b, engine="oracle", compat="fixed", batch=32,
                       symbols=8, accounts=16, slots=64, max_fills=32)
    assert svc.run(max_messages=len(msgs)) == len(msgs)
    out = b.fetch(TOPIC_OUT, 0, max_records=10_000)
    got = [f"{r.key} {r.value}" for r in out]
    assert got == want
