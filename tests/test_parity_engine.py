"""Device parity engine vs the scalar oracle — the central correctness
claim of the framework (SURVEY.md §4): for every message stream in the
Jackson envelope, the device engine's output record stream and store
state equal the oracle's byte for byte, in both compat modes, and
reference-death paths surface at the same message index.
"""

import pytest

import kme_tpu.opcodes as op
from kme_tpu.engine.parity import (
    ERR_CRASH, ERR_HANG, ERR_TABLE_FULL, DeviceParityError, ParityCaps,
    ParityEngine)
from kme_tpu.oracle import OracleEngine
from kme_tpu.wire import OrderMsg
from kme_tpu.workload import harness_stream

CAPS = ParityCaps(balances=16, positions=1024, books=16, buckets=256,
                  orders=2048, max_events=32, batch=128)


def run_oracle(msgs, compat):
    """-> (list of wire-line lists per message, death index or None)."""
    eng = OracleEngine(compat)
    recs, death = [], None
    for i, m in enumerate(msgs):
        try:
            recs.append([r.wire() for r in eng.process(m.copy())])
        except Exception:  # ReferenceHang/Crash and dict/None-access deaths
            death = i
            break
    return recs, death, eng


def run_device(msgs, compat, caps=CAPS):
    eng = ParityEngine(compat, caps)
    try:
        out = eng.process_batch(msgs)
        return [[r.wire() for r in recs] for recs in out], None, eng
    except DeviceParityError as e:
        return [[r.wire() for r in recs] for recs in e.records], e.index, eng


def oracle_state(ora: OracleEngine):
    orders = {oid: {"action": r.action, "aid": r.aid, "sid": r.sid,
                    "price": r.price, "size": r.size, "next": r.next,
                    "prev": r.prev}
              for oid, r in ora.orders.items()}
    return {"balances": dict(ora.balances), "positions": dict(ora.positions),
            "books": dict(ora.books), "buckets": dict(ora.buckets),
            "orders": orders}


def assert_parity(msgs, compat, caps=CAPS, check_state=True):
    ora_recs, ora_death, ora = run_oracle(msgs, compat)
    dev_recs, dev_death, dev = run_device(msgs, compat, caps)
    assert dev_death == ora_death, (
        f"death index diverged: device={dev_death} oracle={ora_death}")
    assert len(dev_recs) == len(ora_recs)
    for i, (g, w) in enumerate(zip(dev_recs, ora_recs)):
        assert g == w, f"record stream diverged at message {i}: {msgs[i]}"
    if check_state and ora_death is None:
        assert dev.export_state() == oracle_state(ora)
    return ora, dev


# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_parity_java_stock_workload():
    """The reference harness distribution (exchange_test.js), java compat:
    exercises Q1 (sid-0 trades), Q2 (unclamped sizes), Q9 (prev leaks),
    Q11 (position garbage keys) on 1.5k events."""
    assert_parity(harness_stream(1500, seed=0), "java")


@pytest.mark.slow
def test_parity_fixed_stock_workload():
    """Fixed mode on the validated-domain workload with real PAYOUT
    opcodes (payout bug disabled)."""
    assert_parity(
        harness_stream(1500, seed=3, payout_opcode_bug=False, validate=True),
        "fixed")


def _seeded(num_accounts=4, deposit=200_000, symbols=(1, 2)):
    msgs = []
    for a in range(num_accounts):
        msgs.append(OrderMsg(action=op.CREATE_BALANCE, aid=a))
        msgs.append(OrderMsg(action=op.TRANSFER, aid=a, size=deposit))
    for s in symbols:
        msgs.append(OrderMsg(action=op.ADD_SYMBOL, sid=s))
    return msgs


def test_parity_payout_and_remove_symbol_fixed():
    """Dense coverage of the fixed-mode settlement paths: resting books
    wiped with margin release, YES/NO payouts, re-add after removal."""
    msgs = _seeded()
    oid = 100
    for sid in (1, 2):
        for price, size in ((40, 5), (40, 3), (55, 7), (60, 2)):
            msgs.append(OrderMsg(action=op.BUY, oid=oid, aid=oid % 4, sid=sid,
                                 price=price, size=size))
            oid += 1
        for price, size in ((70, 4), (80, 6)):
            msgs.append(OrderMsg(action=op.SELL, oid=oid, aid=oid % 4, sid=sid,
                                 price=price, size=size))
            oid += 1
    # cross some orders to create positions
    msgs.append(OrderMsg(action=op.BUY, oid=oid, aid=3, sid=1, price=75, size=5))
    msgs.append(OrderMsg(action=op.SELL, oid=oid + 1, aid=2, sid=2, price=35, size=6))
    msgs += [
        OrderMsg(action=op.PAYOUT, sid=1, size=97),    # YES: credit longs
        OrderMsg(action=op.PAYOUT, sid=-2, size=97),   # NO: delete uncredited
        OrderMsg(action=op.PAYOUT, sid=1, size=97),    # symbol gone -> reject
        OrderMsg(action=op.ADD_SYMBOL, sid=1),         # re-add after payout
        OrderMsg(action=op.REMOVE_SYMBOL, sid=1),      # empty remove
        OrderMsg(action=op.REMOVE_SYMBOL, sid=9),      # missing -> reject
    ]
    assert_parity(msgs, "fixed")


def test_parity_java_remove_symbol_quirks():
    """Q3: removeSymbol on existing-but-empty books rejects (inverted);
    on missing books succeeds."""
    msgs = _seeded(symbols=(1,))
    msgs += [
        OrderMsg(action=op.REMOVE_SYMBOL, sid=1),  # exists+empty -> REJECT (Q3)
        OrderMsg(action=op.REMOVE_SYMBOL, sid=5),  # missing -> "success"
        OrderMsg(action=op.ADD_SYMBOL, sid=1),     # still exists -> reject
    ]
    assert_parity(msgs, "java")


def test_parity_java_hang_on_nonempty_remove():
    """Q4: REMOVE_SYMBOL with resting orders = the reference's infinite
    loop; both engines must die at the same message index."""
    msgs = _seeded(symbols=(1,))
    msgs.append(OrderMsg(action=op.BUY, oid=7, aid=0, sid=1, price=40, size=5))
    msgs.append(OrderMsg(action=op.REMOVE_SYMBOL, sid=1))
    ora_recs, ora_death, _ = run_oracle(msgs, "java")
    dev_recs, dev_death, dev = run_device(msgs, "java")
    assert ora_death == dev_death == len(msgs) - 1
    assert dev_recs == ora_recs
    with pytest.raises(DeviceParityError) as ei:
        ParityEngine("java", CAPS).process_batch(msgs)
    assert ei.value.code == ERR_HANG


def test_parity_java_payout_credits_on_missing_books():
    """Q3+Q5/Q6 interplay: java PAYOUT proceeds only when the symbol's
    books are MISSING, crediting any stale positions — and the OUT echo
    is still REJECT because the dispatcher drops the result."""
    msgs = _seeded(symbols=(1,))
    # create a position on symbol 1 via a cross
    msgs.append(OrderMsg(action=op.BUY, oid=1, aid=0, sid=1, price=50, size=4))
    msgs.append(OrderMsg(action=op.SELL, oid=2, aid=1, sid=1, price=50, size=4))
    # cancel nothing; payout sid=3 (books missing): succeeds internally,
    # echo REJECT; no positions match sid 3 so nothing credited
    msgs.append(OrderMsg(action=op.PAYOUT, sid=3, size=97))
    ora, dev = assert_parity(msgs, "java")
    # position on (aid, sid=1) survived; balances unchanged by the payout
    assert any(k[1] == 1 for k in ora.positions)


def test_parity_q1_sid0_merged_book():
    """Q1: symbol 0's buy and sell sides share one book; a buy can match
    a resting buy."""
    msgs = _seeded(symbols=(0,))
    msgs.append(OrderMsg(action=op.BUY, oid=1, aid=0, sid=0, price=40, size=5))
    msgs.append(OrderMsg(action=op.BUY, oid=2, aid=1, sid=0, price=45, size=5))
    msgs.append(OrderMsg(action=op.SELL, oid=3, aid=2, sid=0, price=80, size=2))
    msgs.append(OrderMsg(action=op.SELL, oid=4, aid=3, sid=0, price=10, size=2))
    ora, dev = assert_parity(msgs, "java")


def test_parity_q2_ghost_trades():
    """Q2: a fully-filled sell taker still executes one zero-size trade
    when the next maker crosses; zero-size orders behave asymmetrically."""
    msgs = _seeded(symbols=(1,))
    msgs.append(OrderMsg(action=op.BUY, oid=1, aid=0, sid=1, price=50, size=3))
    msgs.append(OrderMsg(action=op.BUY, oid=2, aid=1, sid=1, price=50, size=3))
    # sell exactly 3: fills vs oid 1, then ghost zero-size trade vs oid 2
    msgs.append(OrderMsg(action=op.SELL, oid=3, aid=2, sid=1, price=40, size=3))
    # zero-size buy rests/not per crossing rules
    msgs.append(OrderMsg(action=op.BUY, oid=4, aid=3, sid=1, price=10, size=0))
    ora, dev = assert_parity(msgs, "java")
    # confirm the ghost trade actually happened (size-0 fills emitted)
    eng = OracleEngine("java")
    ghost = 0
    for m in msgs:
        for r in eng.process(m.copy()):
            if r.key == "OUT" and r.value.action in (op.BOUGHT, op.SOLD) \
                    and r.value.size == 0:
                ghost += 1
    assert ghost >= 2


def test_parity_q9_prev_leak_and_residual_echo():
    """Q9: the OUT echo of a rested order appended to a bucket carries
    the tail's oid in `prev`; a partially-filled taker echoes residual
    size."""
    msgs = _seeded(symbols=(1,))
    msgs.append(OrderMsg(action=op.BUY, oid=1, aid=0, sid=1, price=50, size=3))
    msgs.append(OrderMsg(action=op.BUY, oid=2, aid=1, sid=1, price=50, size=3))
    msgs.append(OrderMsg(action=op.SELL, oid=3, aid=2, sid=1, price=45, size=10))
    _, dev = assert_parity(msgs, "java")
    # device echo of msg 2 (append path) must carry prev=1
    out = ParityEngine("java", CAPS).process_batch(msgs)
    echo2 = out[len(msgs) - 2][-1].value
    assert echo2.prev == 1
    echo3 = out[len(msgs) - 1][-1].value
    assert echo3.size == 10 - 6  # residual after sweeping both makers


def test_parity_cancel_all_link_cases():
    """Cancel only/head/tail/middle unlink cases + margin release, and
    cancels of unknown/foreign oids."""
    msgs = _seeded(symbols=(1,))
    for i, (price, size) in enumerate(
            ((50, 1), (50, 2), (50, 3), (50, 4), (50, 5))):
        msgs.append(OrderMsg(action=op.BUY, oid=10 + i, aid=i % 4, sid=1,
                             price=price, size=size))
    msgs += [
        OrderMsg(action=op.CANCEL, oid=12, aid=2),   # middle
        OrderMsg(action=op.CANCEL, oid=10, aid=0),   # head
        OrderMsg(action=op.CANCEL, oid=14, aid=0),   # tail, wrong owner
        OrderMsg(action=op.CANCEL, oid=14, aid=3),   # tail
        OrderMsg(action=op.CANCEL, oid=999, aid=0),  # unknown
        OrderMsg(action=op.CANCEL, oid=11, aid=1),   # head again
        OrderMsg(action=op.CANCEL, oid=13, aid=3),   # only
        OrderMsg(action=op.CANCEL, oid=13, aid=3),   # already gone
    ]
    assert_parity(msgs, "java")
    assert_parity(msgs, "fixed")


def test_device_capacity_overflow_is_flagged():
    tiny = ParityCaps(balances=2, positions=8, books=4, buckets=8,
                      orders=8, max_events=8, batch=16)
    msgs = [OrderMsg(action=op.CREATE_BALANCE, aid=a) for a in range(3)]
    with pytest.raises(DeviceParityError) as ei:
        ParityEngine("java", tiny).process_batch(msgs)
    assert ei.value.code == ERR_TABLE_FULL
    assert ei.value.index == 2


def test_parity_transfer_and_balance_edges():
    msgs = [
        OrderMsg(action=op.TRANSFER, aid=1, size=100),   # no account -> reject
        OrderMsg(action=op.CREATE_BALANCE, aid=1),
        OrderMsg(action=op.CREATE_BALANCE, aid=1),       # duplicate -> reject
        OrderMsg(action=op.TRANSFER, aid=1, size=500),
        OrderMsg(action=op.TRANSFER, aid=1, size=-500),  # to exactly 0
        OrderMsg(action=op.TRANSFER, aid=1, size=-1),    # overdraft -> reject
        OrderMsg(action=op.TRANSFER, aid=1, size=0),
        OrderMsg(action=op.BUY, oid=1, aid=1, sid=9, price=50, size=1),  # no book
        OrderMsg(action=99, oid=1, aid=1),               # unknown opcode
    ]
    assert_parity(msgs, "java")
    assert_parity(msgs, "fixed")


def test_parity_fill_credit_wraps_at_int32():
    """fillOrder's balance credit is `size * order.price` — an int*int
    product that wraps at 2^31 BEFORE the long promotion of the balance
    add (KProcessor.java:286). size=65536 at improvement=32768 crosses
    the boundary exactly."""
    msgs = []
    for a in (0, 1):
        msgs.append(OrderMsg(action=op.CREATE_BALANCE, aid=a))
        for _ in range(2):
            msgs.append(OrderMsg(action=op.TRANSFER, aid=a, size=2**30))
    msgs.append(OrderMsg(action=op.ADD_SYMBOL, sid=1))
    msgs.append(OrderMsg(action=op.SELL, oid=1, aid=0, sid=1, price=0,
                         size=65536))
    msgs.append(OrderMsg(action=op.BUY, oid=2, aid=1, sid=1, price=32768,
                         size=65536))
    ora, dev = assert_parity(msgs, "java")
    # taker: margin debit 2^31 (long), fill credit jint(2^31) = -2^31
    assert ora.balances[1] == 2 * 2**30 - 2**31 - 2**31


def test_parity_transfer_int_min_negation_wraps():
    """`balance < -order.size` negates in 32-bit int: -INT_MIN stays
    INT_MIN, so a withdrawal of 2^31 is ACCEPTED by the JVM."""
    msgs = [
        OrderMsg(action=op.CREATE_BALANCE, aid=1),
        OrderMsg(action=op.TRANSFER, aid=1, size=-(2**31)),
    ]
    ora, dev = assert_parity(msgs, "java")
    assert ora.balances[1] == -(2**31)
    assert_parity(msgs, "fixed")


def test_parity_negative_size_buy_npe():
    """A BUY with negative size and no position: checkBalance's adj-write
    hits getPositionAmount(null) (KProcessor.java:179-180) AFTER the
    balance debit persisted — both engines die at the same index."""
    msgs = _seeded(num_accounts=1, symbols=(1,))
    msgs.append(OrderMsg(action=op.BUY, oid=1, aid=0, sid=1, price=50,
                         size=-5))
    ora_recs, ora_death, _ = run_oracle(msgs, "java")
    dev_recs, dev_death, _ = run_device(msgs, "java")
    assert ora_death == dev_death == len(msgs) - 1
    assert dev_recs == ora_recs
