"""On-device distribution histograms vs full host-side recomputation.

The kernels accumulate three power-of-two-bucket histograms alongside
the metrics vector (engine/lanes.py one_step, engine/seq.py kernel
epilogue) — fetched with the same transfers, never an extra device
round-trip:

- fills_per_order: one observation per ACCEPTED trade, value = number
  of maker fills (a resting 0-fill trade lands in bucket 0);
- book_depth: one observation per book-mutating message (accepted
  trade or cancel), value = the touched lane's occupied slot count
  (both sides) AFTER the message;
- batch_occupancy: one observation per non-empty dispatch unit (seq:
  messages per kernel call; lanes: scheduled messages per scan step).

The host recomputations here share NO code with the kernels: fills and
depth replay the stream through the quirk-exact oracle, occupancy
replays the host planners."""

from collections import Counter

import pytest

from kme_tpu import opcodes as op
from kme_tpu.engine import seq as SQ
from kme_tpu.engine.lanes import LaneConfig
from kme_tpu.oracle import OracleEngine
from kme_tpu.runtime.seqsession import SeqSession, make_seq_router
from kme_tpu.runtime.sequencer import make_scheduler
from kme_tpu.runtime.session import LaneSession
from kme_tpu.telemetry import N_BUCKETS, bucket_index
from kme_tpu.workload import zipf_symbol_stream


def host_fills_and_depth(msgs, book_slots, max_fills):
    """Expected fills_per_order / book_depth via oracle replay."""
    ora = OracleEngine("fixed", book_slots=book_slots, max_fills=max_fills)
    fills = [0] * N_BUCKETS
    depth = [0] * N_BUCKETS
    for m in msgs:
        is_trade = m.action in (op.BUY, op.SELL)
        is_cancel = m.action == op.CANCEL
        # a cancel's sid comes from the resting order it targets
        sid = m.sid
        if is_cancel:
            rest = ora.orders.get(m.oid)
            sid = rest.sid if rest is not None else None
        recs = ora.process(m.copy())
        accepted = recs[-1].value.action != op.REJECT
        if not accepted:
            continue
        if is_trade:
            fills[bucket_index((len(recs) - 2) // 2)] += 1
        if is_trade or is_cancel:
            d = sum(1 for o in ora.orders.values() if o.sid == sid)
            depth[bucket_index(d)] += 1
    return fills, depth


def host_occupancy_lanes(msgs, cfg, width):
    """Scheduled messages per (segment, scan step) — an independent
    scheduler instance replays the plan."""
    sch = make_scheduler(cfg.lanes, cfg.accounts, width=width)
    sched = sch.plan([m.copy() for m in msgs])
    occ = [0] * N_BUCKETS
    per_step = Counter(zip(sched.cols["segment"].tolist(),
                           sched.cols["step"].tolist()))
    for c in per_step.values():
        occ[bucket_index(c)] += 1
    return occ


def host_occupancy_seq(msgs, cfg):
    """Routed messages per kernel call: the dispatch chunks the routed
    stream into cfg.batch-sized calls (runtime/seqsession.py _plan)."""
    r = make_seq_router(cfg.lanes, cfg.accounts, compat=cfg.compat)
    cols, _ = r.route([m.copy() for m in msgs])
    n = len(cols["act"])
    occ = [0] * N_BUCKETS
    for ci in range(max(-(-n // cfg.batch), 1)):
        c = max(min(cfg.batch, n - ci * cfg.batch), 0)
        if c > 0:
            occ[bucket_index(c)] += 1
    return occ


def _stream(n, symbols=8, accounts=24, seed=5, payout_per_mille=3):
    return zipf_symbol_stream(n, num_symbols=symbols,
                              num_accounts=accounts, seed=seed,
                              zipf_a=1.0,
                              payout_per_mille=payout_per_mille)


def _check_seq(msgs, cfg):
    ses = SeqSession(cfg)
    ses.process_wire([m.copy() for m in msgs])
    h = ses.histograms()
    fills, depth = host_fills_and_depth(msgs, cfg.slots, cfg.max_fills)
    assert h["fills_per_order"] == fills
    assert h["book_depth"] == depth
    assert h["batch_occupancy"] == host_occupancy_seq(msgs, cfg)
    assert sum(fills) > 0 and sum(depth) > 0


def _check_lanes(msgs, cfg):
    W = cfg.lanes   # width == lanes: the per-step cap never binds, so
    ses = LaneSession(cfg, width=W)  # compact == full-width occupancy
    ses.process_wire([m.copy() for m in msgs])
    h = ses.histograms()
    fills, depth = host_fills_and_depth(msgs, cfg.slots, cfg.max_fills)
    assert h["fills_per_order"] == fills
    assert h["book_depth"] == depth
    assert h["batch_occupancy"] == host_occupancy_lanes(msgs, cfg, W)
    assert sum(fills) > 0 and sum(depth) > 0


def test_seq_histograms_match_host():
    _check_seq(_stream(600),
               SQ.SeqConfig(lanes=8, slots=128, accounts=128,
                            max_fills=16))


def test_lanes_histograms_match_host():
    _check_lanes(_stream(600),
                 LaneConfig(lanes=8, slots=32, accounts=32, max_fills=16,
                            steps=16))


def test_seq_java_fills_histogram():
    """Java mode has no book-depth plane (the merged-book layout has no
    per-lane occupancy), but fills and occupancy still accumulate."""
    msgs = _stream(400, payout_per_mille=0)  # no barriers in java mode
    cfg = SQ.SeqConfig(lanes=8, slots=128, accounts=128, max_fills=16,
                       compat="java")
    ses = SeqSession(cfg)
    ses.process_wire([m.copy() for m in msgs])
    h = ses.histograms()
    met = ses.metrics()
    assert sum(h["fills_per_order"]) == met["trades_ok"]
    assert h["book_depth"] == [0] * N_BUCKETS
    assert sum(h["batch_occupancy"]) > 0


def test_lanes_histograms_shard_invariant():
    msgs = _stream(800, payout_per_mille=4)
    cfg = LaneConfig(lanes=8, slots=32, accounts=32, max_fills=16,
                     steps=16)
    base = None
    for shards in (1, 2, 8):
        ses = LaneSession(cfg, shards=shards)
        ses.process_wire([m.copy() for m in msgs])
        h = ses.histograms()
        if base is None:
            base = h
        else:
            assert h == base, f"histograms diverged at shards={shards}"


def test_hist_observation_counts_match_metrics():
    """Structural invariants tying the histograms to the counters:
    one fills observation per accepted trade, one depth observation per
    accepted trade or cancel."""
    msgs = _stream(600)
    cfg = LaneConfig(lanes=8, slots=32, accounts=32, max_fills=16,
                     steps=16)
    ses = LaneSession(cfg)
    ses.process_wire([m.copy() for m in msgs])
    h = ses.histograms()
    met = ses.metrics()
    assert sum(h["fills_per_order"]) == met["trades_ok"]
    assert sum(h["book_depth"]) == met["trades_ok"] + met["cancels_ok"]


@pytest.mark.slow
def test_seq_histograms_match_host_10k():
    """The acceptance-criterion conformance stream: 10k orders."""
    _check_seq(_stream(10_000, symbols=16, accounts=64, seed=7),
               SQ.SeqConfig(lanes=16, slots=128, accounts=128,
                            max_fills=16))


@pytest.mark.slow
def test_lanes_histograms_match_host_10k():
    _check_lanes(_stream(10_000, symbols=16, accounts=64, seed=7),
                 LaneConfig(lanes=16, slots=128, accounts=128,
                            max_fills=16, steps=64))
