"""Deterministic whole-cluster simulation (kme_tpu/sim/).

Pins the simulation's own contracts — the ones every nightly seed
sweep stands on:

- one seed fully determines a run: byte-identical event-trace and
  MatchOut digests across re-runs, divergent digests across seeds;
- the virtual clock and seeded scheduler are the only time/ordering
  sources (SimClockView, sleep charging, insertion-order tie-breaks);
- schedule generation draws offset gates that can actually fire
  (`after=` for the offset-less broker./ckpt. call sites, `at=` for
  the net./clock sites) and reshard targets that keep grouped topic
  namespacing valid;
- the transport delivers strictly in stamp order across crash windows
  (the FIFO-vs-restart bug class: later stamps must never advance the
  broker watermark past parked earlier ones — silent input loss);
- a calm run, a crash-recovery run and a mid-run reshard are all
  green under the full verdict set;
- the planted stamp-reset bug is found by a sweep, shrinks to a
  minimal schedule (a single crash), and the written repro replays
  red offline.
"""

import json
import os

import pytest

from kme_tpu.sim.sched import SimClockView, SimScheduler
from kme_tpu.sim.schedule import (SIM_POINTS, SIM_STORMS, FaultSchedule,
                                  generate_schedule)
from kme_tpu.sim.cluster import PLANTED_BUGS, SimConfig, run_sim
from kme_tpu.sim.transport import SimTransport


# ---------------------------------------------------------------------------
# scheduler + clock units


def test_virtual_clock_view_shares_now_with_private_skew():
    sched = SimScheduler(seed=1)
    a, b = SimClockView(sched), SimClockView(sched)
    sched.now = 5.0
    a.skew = 0.25
    assert a.time() == 5.25 and b.time() == 5.0
    assert a.monotonic() == 5.0     # skew never touches monotonic
    assert a.time_ns() == int(5.25e9)


def test_virtual_sleep_charges_scheduler_not_wall_clock():
    sched = SimScheduler(seed=1)
    view = SimClockView(sched)
    view.sleep(3.0)
    assert sched.sleep_charge == 3.0
    assert sched.now == 0.0         # nothing blocked, nothing advanced


def test_scheduler_same_seed_same_interleaving():
    def run(seed):
        sched = SimScheduler(seed=seed)

        class A:
            def __init__(self, name):
                self.name, self.n, self.stopped = name, 0, False

            def step(self):
                self.n += 1
                sched.trace(self.name, "step", n=self.n)
                if self.n >= 5:
                    self.stopped = True
                return True

        for name in ("x", "y", "z"):
            sched.add_actor(name, A(name))
        sched.run(until=lambda: False, max_vtime=10.0)
        return sched.digest()

    assert run(7) == run(7)
    assert run(7) != run(8)


def test_scheduler_tie_break_is_insertion_order():
    sched = SimScheduler(seed=1)
    seen = []
    for i in range(5):
        sched.post(1.0, lambda i=i: seen.append(i))
    sched.run(until=lambda: False, max_vtime=10.0)
    assert seen == [0, 1, 2, 3, 4]


# ---------------------------------------------------------------------------
# schedule generation


def test_generate_schedule_is_deterministic_and_serializable():
    a = generate_schedule(42, num_events=200)
    b = generate_schedule(42, num_events=200)
    assert a.to_json() == b.to_json()
    assert generate_schedule(43, num_events=200).to_json() != a.to_json()
    rt = FaultSchedule.from_json(a.to_json())
    assert rt.to_json() == a.to_json()


def test_generated_gates_can_actually_fire():
    """broker./ckpt. call sites pass no offset to faults.fire, so an
    `at=` gate there would silently never trigger — the generator must
    use hit-count (`after=`) gates for them."""
    for seed in range(60):
        s = generate_schedule(seed, num_events=100)
        for clause in s.clauses:
            point = clause.split(":", 1)[0]
            assert point in SIM_POINTS
            if point.startswith(("broker.", "ckpt.")):
                assert "after=" in clause and "at=" not in clause, clause
            else:
                assert "at=" in clause, clause
        for ev in s.events:
            if ev["kind"] == "reshard":
                assert ev["to"] in (2, 3, 4) and ev["to"] != s.ngroups
            if ev["kind"] == "storm":
                assert ev["profile"] in SIM_STORMS


def test_schedule_spec_prefixes_grammar_seed():
    s = FaultSchedule(seed=9, clauses=["broker.produce:n=1:after=3"])
    assert s.spec() == "seed=9;broker.produce:n=1:after=3"
    assert FaultSchedule(seed=9).spec() is None


# ---------------------------------------------------------------------------
# transport: stamp-ordered delivery across a crash window


def test_transport_fifo_survives_crash_window(tmp_path):
    from kme_tpu.bridge.broker import InProcessBroker
    from kme_tpu.bridge.provision import provision

    sched = SimScheduler(seed=3)
    view = SimClockView(sched)
    broker = InProcessBroker(persist_dir=str(tmp_path / "log"),
                             clock=view)
    provision(broker, topics=("MatchIn.g0",))
    up = [True]
    t = SimTransport(sched, 1,
                     broker_for=lambda g: broker if up[0] else None,
                     topic_for=lambda g: "MatchIn.g0")

    def feeder():
        # 30 sends; the "leader" dies under the middle third, so those
        # deliveries park while later ones keep arriving
        for i in range(30):
            t.send(0, None, f"rec{i}")

    sched.post(0.0, feeder)
    sched.post(0.003, lambda: up.__setitem__(0, False))

    def restart():
        up[0] = True
        t.flush_held(0)

    sched.post(0.010, restart)
    sched.run(until=lambda: False, max_vtime=5.0)

    recs = broker.fetch("MatchIn.g0", 0, 10 ** 6)
    assert [r.value for r in recs] == [f"rec{i}" for i in range(30)]
    assert [r.out_seq for r in recs] == list(range(30))
    assert broker.dup_suppressed == 0       # no input loss, no dups
    assert t.idle()


def test_transport_reshape_resumes_cursors():
    sched = SimScheduler(seed=3)
    t = SimTransport(sched, 2, broker_for=lambda g: None,
                     topic_for=lambda g: f"MatchIn.g{g}")
    t.reshape(3, cursors=[5, 0, 7])
    assert [l.seq for l in t.links] == [5, 0, 7]
    assert [l.next_deliver for l in t.links] == [5, 0, 7]


# ---------------------------------------------------------------------------
# whole-cluster runs (small workloads: tier-1 budget)


def _calm(seed, num_events=40, **kw):
    return FaultSchedule(seed=seed, num_events=num_events, **kw)


def test_sim_calm_run_is_green(tmp_path):
    res = run_sim(_calm(3), str(tmp_path))
    assert res.ok, res.verdicts
    assert res.red_verdicts() == []
    assert res.counters["crashes"] == 0
    assert res.counters["delivered"] > 0


def test_sim_same_seed_byte_identical_digests(tmp_path):
    sched = FaultSchedule(
        seed=11, num_events=40,
        clauses=["net.delay:n=1:at=9:ms=50"],
        events=[{"kind": "crash", "group": 0, "at": 25}])
    a = run_sim(sched, str(tmp_path / "a"))
    b = run_sim(sched, str(tmp_path / "b"))
    assert a.trace_digest == b.trace_digest
    assert a.out_digest == b.out_digest
    assert a.ok and b.ok and a.counters == b.counters


def test_sim_different_seeds_diverge(tmp_path):
    a = run_sim(_calm(21), str(tmp_path / "a"))
    b = run_sim(_calm(22), str(tmp_path / "b"))
    assert a.trace_digest != b.trace_digest
    assert a.out_digest != b.out_digest


def test_sim_crash_recovery_is_green(tmp_path):
    sched = FaultSchedule(
        seed=5, num_events=40,
        events=[{"kind": "crash", "group": 1, "at": 20}])
    res = run_sim(sched, str(tmp_path))
    assert res.ok, res.verdicts
    assert res.counters["crashes"] == 1


def test_sim_reshard_mid_run_is_green(tmp_path):
    sched = FaultSchedule(
        seed=13, num_events=40,
        events=[{"kind": "reshard", "at": 22, "to": 3}])
    res = run_sim(sched, str(tmp_path))
    assert res.ok, res.verdicts
    assert res.counters["resharded"] == 1
    # post-reshard topology really served: three final-gen groups
    assert len(res.verdicts["conservation"]["pending_reserve"]) == 3


def test_sim_grammar_faults_fire_and_stay_green(tmp_path):
    sched = FaultSchedule(
        seed=17, num_events=40,
        clauses=["net.partition:n=1:at=7:ms=50",
                 "net.reorder:n=1:at=30:ms=20",
                 "broker.produce:n=1:after=25"])
    res = run_sim(sched, str(tmp_path))
    assert res.ok, res.verdicts
    assert res.counters["faults_fired"] >= 2


def test_sim_faults_never_leak_into_process_plan(tmp_path):
    from kme_tpu import faults

    run_sim(_calm(3, clauses=["broker.fetch:n=1:after=5"]),
            str(tmp_path))
    assert not faults.active()      # run_sim clears on every exit


def test_sim_rejects_ungrouped_topology(tmp_path):
    with pytest.raises(ValueError):
        run_sim(_calm(3, ngroups=1), str(tmp_path))


# ---------------------------------------------------------------------------
# the planted-bug drill: find -> shrink -> offline red replay


def test_planted_bug_is_red_only_when_armed(tmp_path):
    sched = FaultSchedule(
        seed=5, num_events=40,
        events=[{"kind": "crash", "group": 0, "at": 20}])
    clean = run_sim(sched, str(tmp_path / "clean"))
    assert clean.ok
    assert "stamp-reset" in PLANTED_BUGS
    bugged = run_sim(sched, str(tmp_path / "bug"),
                     planted_bug="stamp-reset")
    assert not bugged.ok
    assert "stamps" in bugged.red_verdicts()


def test_unknown_planted_bug_is_an_error(tmp_path):
    with pytest.raises(ValueError):
        run_sim(_calm(3), str(tmp_path), planted_bug="nope")


def test_shrinker_reduces_to_minimal_crash_and_repro_replays_red(
        tmp_path):
    from kme_tpu.sim.shrink import shrink_schedule

    # a noisy schedule: the bug needs only the crash; everything else
    # is shrinkable adversity
    sched = FaultSchedule(
        seed=6, num_events=40,
        clauses=["net.delay:n=1:at=9:ms=20",
                 "broker.fetch:n=1:after=30"],
        events=[{"kind": "crash", "group": 0, "at": 20},
                {"kind": "storm", "profile": "cancel-storm",
                 "at": 28, "n": 30}])
    sr = shrink_schedule(sched, str(tmp_path), max_runs=32,
                         planted_bug="stamp-reset")
    assert sr is not None and sr.removed >= 2
    assert sr.schedule.size() <= 3
    assert any(ev["kind"] == "crash" for ev in sr.schedule.events)
    assert not sr.result.ok

    # the written repro is self-contained and replays red offline
    with open(sr.repro_path) as f:
        rt = FaultSchedule.from_json(f.read())
    replay = run_sim(rt, str(tmp_path / "replay"),
                     planted_bug="stamp-reset")
    assert not replay.ok
    # and the same schedule without the bug is green (the shrink kept
    # a real repro, not a broken harness state)
    assert run_sim(rt, str(tmp_path / "replay-clean")).ok

    # audit.py-format dump with a ready-to-run xray bisect line
    with open(sr.dump_path) as f:
        doc = json.load(f)
    assert doc["violations"] and doc["inputs"]
    assert doc["xray"] and doc["xray"].startswith("kme-xray --bisect")
    assert os.path.exists(doc["checkpoint_ref"])


def test_shrink_returns_none_for_green_schedule(tmp_path):
    from kme_tpu.sim.shrink import shrink_schedule

    assert shrink_schedule(_calm(3), str(tmp_path), max_runs=4) is None


# ---------------------------------------------------------------------------
# CLI


def test_cli_dump_schedule_roundtrip(capsys):
    from kme_tpu.sim.cli import sim_main

    assert sim_main(["--seed", "4", "--dump-schedule"]) == 0
    dumped = capsys.readouterr().out.strip()
    assert FaultSchedule.from_json(dumped).seed == 4


def test_cli_single_seed_green(tmp_path, capsys):
    from kme_tpu.sim.cli import sim_main

    rc = sim_main(["--seed", "3", "--events", "40",
                   "--out", str(tmp_path), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["ok"] is True
    assert out["trace_digest"] and out["out_digest"]


def test_cli_repro_red_exit_code(tmp_path, capsys):
    from kme_tpu.sim.cli import sim_main

    sched = FaultSchedule(
        seed=5, num_events=40,
        events=[{"kind": "crash", "group": 0, "at": 20}])
    path = tmp_path / "r.json"
    path.write_text(sched.to_json())
    assert sim_main(["--repro", str(path), "--out",
                     str(tmp_path / "g")]) == 0
    capsys.readouterr()
    assert sim_main(["--repro", str(path), "--planted-bug",
                     "stamp-reset", "--out",
                     str(tmp_path / "r")]) == 1


def test_cli_requires_exactly_one_mode():
    from kme_tpu.sim.cli import sim_main

    with pytest.raises(SystemExit):
        sim_main([])
    with pytest.raises(SystemExit):
        sim_main(["--seed", "1", "--seeds", "0..2"])


@pytest.mark.slow
def test_cli_sweep_finds_and_shrinks_planted_bug(tmp_path, capsys):
    """The CI drill at test scale: a short sweep with the bug armed
    must go red on a crash-bearing seed and print a one-line repro."""
    from kme_tpu.sim.cli import sim_main

    rc = sim_main(["--seeds", "5..9", "--events", "60",
                   "--planted-bug", "stamp-reset",
                   "--out", str(tmp_path), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["red"]
    shrunk = [s for s in out["shrunk"] if s.get("reproduced")]
    assert shrunk and all(s["size"] <= 3 for s in shrunk)
    assert all(s["repro"].startswith("kme-sim --repro") for s in shrunk)
