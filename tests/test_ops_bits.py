"""Device bit ops vs the oracle's java-exact host implementations.

The device layer claims bit-identical semantics with javalong's float
scans (including the Q7 overshoot) and the book/bucket codecs; these
tests drive both over adversarial int64 inputs.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kme_tpu.oracle import javalong as jl
from kme_tpu.ops import bits


def _rand64(rng, n):
    vals = []
    for _ in range(n):
        kind = rng.randrange(4)
        if kind == 0:
            vals.append(rng.getrandbits(64))
        elif kind == 1:  # sparse
            v = 0
            for _ in range(rng.randrange(1, 4)):
                v |= 1 << rng.randrange(64)
            vals.append(v)
        elif kind == 2:  # dense top region (Q7 frontier)
            t = rng.randrange(40, 63)
            vals.append(((1 << (t + 1)) - 1) - rng.randrange(1 << 8))
        else:
            vals.append(rng.getrandbits(rng.randrange(1, 64)))
    return [jl.jlong(v) for v in vals]


@pytest.fixture(scope="module")
def samples():
    rng = random.Random(7)
    vals = _rand64(rng, 4000)
    vals += [0, 1, -1, jl.jlong(1 << 63), (1 << 62), (1 << 63) - 1]
    # exact overshoot frontiers
    for t, thr in enumerate(int(x) for x in bits._OVERSHOOT):
        if thr > 0:
            vals += [thr - 1, thr, jl.jlong(thr + 1)]
    return vals


def test_first_set_bit_matches_oracle(samples):
    got = np.asarray(jax.jit(bits.first_set_bit_pos)(jnp.asarray(samples, jnp.int64)))
    want = [jl.first_set_bit_pos_float(v) for v in samples]
    np.testing.assert_array_equal(got, want)


def test_last_set_bit_matches_oracle(samples):
    got = np.asarray(jax.jit(bits.last_set_bit_pos)(jnp.asarray(samples, jnp.int64)))
    want = [jl.last_set_bit_pos_float(v) for v in samples]
    np.testing.assert_array_equal(got, want)


def test_bit_ops_match_java_semantics():
    rng = random.Random(11)
    ns = jnp.asarray(_rand64(rng, 512), jnp.int64)
    # prices incl. negatives and >125 (java shift masking paths)
    ks = jnp.asarray([rng.randrange(-130, 260) for _ in range(512)], jnp.int32)
    get, st, un = (np.asarray(x) for x in jax.jit(
        lambda n, k: (bits.jget_bit(n, k), bits.jset_bit(n, k),
                      bits.junset_bit(n, k)))(ns, ks))
    ns_h, ks_h = np.asarray(ns), np.asarray(ks)
    for i in range(512):
        n, k = int(ns_h[i]), int(ks_h[i])
        assert bool(get[i]) == jl.get_bit(n, k)
        assert int(st[i]) == jl.set_bit(n, k)
        assert int(un[i]) == jl.unset_bit(n, k)


def test_book_scan_and_bitmask_roundtrip():
    """Drive the book codec through the oracle's helpers on random
    (msb, lsb) pairs and price operations (vectorized: one device call
    per op, host loop only for the oracle side)."""
    from kme_tpu.oracle import engine as oe

    rng = random.Random(3)
    n = 300
    msbs = [jl.jlong(rng.getrandbits(rng.randrange(0, 63))) for _ in range(n)]
    lsbs = [jl.jlong(rng.getrandbits(rng.randrange(0, 63))) for _ in range(n)]
    prices = [rng.randrange(-5, 130) for _ in range(n)]
    m = jnp.asarray(msbs, jnp.int64)
    l = jnp.asarray(lsbs, jnp.int64)
    p = jnp.asarray(prices, jnp.int32)
    mn, mx, cb, (sm, sl), (um, ul) = jax.tree.map(np.asarray, jax.jit(
        lambda m, l, p: (bits.book_min_price(m, l), bits.book_max_price(m, l),
                         bits.book_check_bit(m, l, p),
                         bits.book_with_bit_set(m, l, p),
                         bits.book_with_bit_unset(m, l, p)))(m, l, p))
    for i in range(n):
        book = (msbs[i], lsbs[i])
        price = prices[i]
        assert int(mn[i]) == oe._book_min_price(book)
        assert int(mx[i]) == oe._book_max_price(book)
        assert bool(cb[i]) == oe._check_bit(book, price)
        assert (int(sm[i]), int(sl[i])) == oe._with_bit_set(book, price)
        assert (int(um[i]), int(ul[i])) == oe._with_bit_unset(book, price)


def test_bucket_key_matches_java_promotion():
    from kme_tpu.oracle.engine import OracleEngine

    eng = OracleEngine("java")
    rng = random.Random(5)
    n = 200
    bkeys = [jl.jlong(rng.getrandbits(64)) for _ in range(n)]
    prices = [rng.randrange(-300, 300) for _ in range(n)]
    got = np.asarray(bits.bucket_key(jnp.asarray(bkeys, jnp.int64),
                                     jnp.asarray(prices, jnp.int32)))
    for i in range(n):
        assert int(got[i]) == eng._bucket_key(bkeys[i], prices[i])


def test_tables_find_put_delete():
    from kme_tpu.ops import tables

    @jax.jit
    def drive(keys, used, full):
        idx9, found9 = tables.find(keys, used, jnp.asarray(9, jnp.int64))
        return ((idx9, found9),
                tables.find(keys, used, jnp.asarray(0, jnp.int64)),
                tables.alloc(used),
                tables.put_idx(keys, used, jnp.asarray(7, jnp.int64)),
                tables.alloc(full),
                tables.delete_at(used, idx9, found9))

    keys = jnp.asarray([5, 9, 0, 7], jnp.int64)
    used = jnp.asarray([True, True, False, True])
    (f9, f0, al, up, alf, deleted) = drive(keys, used, jnp.ones(4, bool))
    assert bool(f9[1]) and int(f9[0]) == 1
    assert not bool(f0[1])  # slot 2 holds key 0 but is unused
    assert bool(al[1]) and int(al[0]) == 2
    assert bool(up[1]) and int(up[0]) == 3  # upsert hits existing slot
    assert not bool(alf[1])  # full table reports overflow
    assert list(np.asarray(deleted)) == [True, False, False, True]


def test_ops_are_jittable_and_vmappable():
    f = jax.jit(jax.vmap(bits.last_set_bit_pos))
    out = f(jnp.asarray([0, 1, 6, -2], jnp.int64))
    assert out.shape == (4,)
    assert int(out[2]) == 2
