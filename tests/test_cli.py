"""CLI surfaces not covered by the bridge e2e tests: the kme-oracle
stdin/stdout replica and the loadgen stdout mode."""

import subprocess
import sys

from kme_tpu.oracle import OracleEngine
from kme_tpu.wire import dumps_order
from kme_tpu.workload import harness_stream


def test_kme_oracle_pipe_roundtrip():
    """`kme-loadgen | kme-oracle` reproduces the consumer.js line stream
    byte-for-byte (the documented manual-verification flow)."""
    msgs = harness_stream(300, seed=9)
    stdin = "\n".join(dumps_order(m) for m in msgs) + "\n"
    r = subprocess.run(
        [sys.executable, "-m", "kme_tpu.cli", "oracle", "--compat", "java"],
        input=stdin, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stderr
    ora = OracleEngine("java")
    want = [rec.wire() for m in msgs for rec in ora.process(m.copy())]
    assert r.stdout.splitlines() == want


def test_kme_trace_self_check():
    """The CI smoke: synthetic journal/oracle/lifecycle round-trip."""
    r = subprocess.run(
        [sys.executable, "-m", "kme_tpu.cli", "trace", "--self-check"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stderr


def test_kme_trace_query_and_verify(tmp_path):
    """Write a journal from an oracle run, then reconstruct one order
    and verify the whole file against an independent replay."""
    import json

    from kme_tpu.telemetry.journal import Journal
    from kme_tpu.wire import parse_order

    msgs = harness_stream(200, seed=6, num_accounts=6, num_symbols=2,
                          payout_opcode_bug=False, validate=True)
    lines = [dumps_order(m) for m in msgs]
    inp = tmp_path / "input.jsonl"
    inp.write_text("\n".join(lines) + "\n")
    eng = OracleEngine("fixed")
    groups = [[r.wire() for r in eng.process(parse_order(ln))]
              for ln in lines]
    jp = str(tmp_path / "j.jsonl")
    j = Journal(jp)
    j.record_batch(groups, offsets=list(range(len(groups))))
    j.close()

    r = subprocess.run(
        [sys.executable, "-m", "kme_tpu.cli", "trace", jp,
         "--verify", str(inp)],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "matches oracle replay" in r.stderr

    fill = next(json.loads(ln) for ln in open(jp)
                if json.loads(ln)["e"] == "fill")
    r = subprocess.run(
        [sys.executable, "-m", "kme_tpu.cli", "trace", jp,
         "--order", str(fill["oid"]), "--json"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    evs = [json.loads(ln) for ln in r.stdout.splitlines()]
    assert [e["e"] for e in evs][:2] == ["submit", "accept"]
    assert any(e["e"] == "fill" for e in evs)
    assert f"order {fill['oid']}" in r.stderr

    # divergence detection: verify against a shuffled input must fail
    bad = tmp_path / "bad.jsonl"
    bad.write_text("\n".join(lines[::-1]) + "\n")
    r = subprocess.run(
        [sys.executable, "-m", "kme_tpu.cli", "trace", jp,
         "--verify", str(bad)],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 1
    assert "DIVERGENCE" in r.stderr


def test_kme_loadgen_stdout_deterministic():
    out = []
    for _ in range(2):
        r = subprocess.run(
            [sys.executable, "-m", "kme_tpu.cli", "loadgen", "--events",
             "50", "--seed", "4"],
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        out.append(r.stdout)
    assert out[0] == out[1]
    assert out[0].count('"action"') == len(out[0].splitlines())
