"""CLI surfaces not covered by the bridge e2e tests: the kme-oracle
stdin/stdout replica and the loadgen stdout mode."""

import subprocess
import sys

from kme_tpu.oracle import OracleEngine
from kme_tpu.wire import dumps_order
from kme_tpu.workload import harness_stream


def test_kme_oracle_pipe_roundtrip():
    """`kme-loadgen | kme-oracle` reproduces the consumer.js line stream
    byte-for-byte (the documented manual-verification flow)."""
    msgs = harness_stream(300, seed=9)
    stdin = "\n".join(dumps_order(m) for m in msgs) + "\n"
    r = subprocess.run(
        [sys.executable, "-m", "kme_tpu.cli", "oracle", "--compat", "java"],
        input=stdin, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stderr
    ora = OracleEngine("java")
    want = [rec.wire() for m in msgs for rec in ora.process(m.copy())]
    assert r.stdout.splitlines() == want


def test_kme_loadgen_stdout_deterministic():
    out = []
    for _ in range(2):
        r = subprocess.run(
            [sys.executable, "-m", "kme_tpu.cli", "loadgen", "--events",
             "50", "--seed", "4"],
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        out.append(r.stdout)
    assert out[0] == out[1]
    assert out[0].count('"action"') == len(out[0].splitlines())
