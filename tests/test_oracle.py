"""Scenario tests for the golden oracle, pinning the reference semantics
(KProcessor.java:63-445) including the quirk ledger (SURVEY.md §2.5)."""

import pytest

from kme_tpu import opcodes as op
from kme_tpu.oracle import OracleEngine, ReferenceHang
from kme_tpu.wire import OrderMsg


def eng(compat="java"):
    return OracleEngine(compat)


def msg(action, oid=0, aid=0, sid=0, price=0, size=0):
    return OrderMsg(action=action, oid=oid, aid=aid, sid=sid, price=price, size=size)


def seed(e, accounts=(0, 1, 2), deposit=100_000, symbols=(1,)):
    for a in accounts:
        e.process(msg(op.CREATE_BALANCE, aid=a))
        e.process(msg(op.TRANSFER, aid=a, size=deposit))
    for s in symbols:
        e.process(msg(op.ADD_SYMBOL, sid=s))


def out_actions(records):
    return [(r.key, r.value.action) for r in records]


# ---------------------------------------------------------------- ledger

def test_create_balance_idempotent():
    e = eng()
    r1 = e.process(msg(op.CREATE_BALANCE, aid=5))
    assert out_actions(r1) == [("IN", 100), ("OUT", 100)]
    r2 = e.process(msg(op.CREATE_BALANCE, aid=5))
    assert out_actions(r2) == [("IN", 100), ("OUT", op.REJECT)]
    assert e.balances[5] == 0


def test_transfer_guard():
    e = eng()
    e.process(msg(op.CREATE_BALANCE, aid=1))
    assert e.process(msg(op.TRANSFER, aid=1, size=50))[-1].value.action == op.TRANSFER
    # withdraw exactly to zero allowed: balance < -size is 50 < 50 -> false
    assert e.process(msg(op.TRANSFER, aid=1, size=-50))[-1].value.action == op.TRANSFER
    assert e.balances[1] == 0
    # overdraw rejected
    assert e.process(msg(op.TRANSFER, aid=1, size=-1))[-1].value.action == op.REJECT
    # unknown account rejected
    assert e.process(msg(op.TRANSFER, aid=9, size=5))[-1].value.action == op.REJECT


# ---------------------------------------------------------------- margin

def test_buy_margin_debit():
    e = eng()
    seed(e, accounts=(0,))
    e.process(msg(op.BUY, oid=1, aid=0, sid=1, price=60, size=10))
    assert e.balances[0] == 100_000 - 600


def test_sell_margin_debit():
    e = eng()
    seed(e, accounts=(0,))
    e.process(msg(op.SELL, oid=1, aid=0, sid=1, price=60, size=10))
    # sells reserve (100 - price) per unit (KProcessor.java:176)
    assert e.balances[0] == 100_000 - 400


def test_insufficient_balance_rejects():
    e = eng()
    seed(e, accounts=(0,), deposit=100)
    r = e.process(msg(op.BUY, oid=1, aid=0, sid=1, price=60, size=10))
    assert r[-1].value.action == op.REJECT
    assert e.balances[0] == 100


def test_missing_book_rejects():
    e = eng()
    seed(e, accounts=(0,), symbols=())
    r = e.process(msg(op.BUY, oid=1, aid=0, sid=1, price=60, size=10))
    assert r[-1].value.action == op.REJECT


def test_netting_closing_trade_needs_no_margin():
    e = eng()
    seed(e)
    # account 0 ends long 10 via a trade with account 1
    e.process(msg(op.SELL, oid=1, aid=1, sid=1, price=50, size=10))
    e.process(msg(op.BUY, oid=2, aid=0, sid=1, price=50, size=10))
    assert e.positions[(0, 1)] == (10, 10)
    bal_before = e.balances[0]
    # selling against a long available position reserves nothing
    e.process(msg(op.SELL, oid=3, aid=0, sid=1, price=40, size=10))
    assert e.balances[0] == bal_before
    # the long 'available' is now blocked
    assert e.positions[(0, 1)] == (10, 0)


# ---------------------------------------------------------------- matching

def test_simple_full_match():
    e = eng()
    seed(e)
    e.process(msg(op.SELL, oid=1, aid=1, sid=1, price=50, size=10))
    r = e.process(msg(op.BUY, oid=2, aid=0, sid=1, price=55, size=10))
    # IN echo, maker fill (SOLD, price 0), taker fill (BOUGHT, improvement 5), OUT echo
    assert out_actions(r) == [
        ("IN", op.BUY), ("OUT", op.SOLD), ("OUT", op.BOUGHT), ("OUT", op.BUY)]
    maker_fill, taker_fill = r[1].value, r[2].value
    assert (maker_fill.oid, maker_fill.price, maker_fill.size) == (1, 0, 10)
    assert (taker_fill.oid, taker_fill.price, taker_fill.size) == (2, 5, 10)
    # OUT echo has residual size 0 (Q9)
    assert r[3].value.size == 0
    # positions: maker short, taker long
    assert e.positions[(1, 1)] == (-10, -10)
    assert e.positions[(0, 1)] == (10, 10)
    # taker paid maker's price: 55*10 reserved, 5*10 credited back
    assert e.balances[0] == 100_000 - 500
    assert e.balances[1] == 100_000 - 500


def test_partial_fill_rests_remainder():
    e = eng()
    seed(e)
    e.process(msg(op.SELL, oid=1, aid=1, sid=1, price=50, size=4))
    r = e.process(msg(op.BUY, oid=2, aid=0, sid=1, price=55, size=10))
    assert r[-1].value.size == 6  # residual rested (Q9 echo)
    assert e.orders[2].size == 6
    # maker gone
    assert 1 not in e.orders


def test_price_priority_walks_levels():
    e = eng()
    seed(e)
    e.process(msg(op.SELL, oid=1, aid=1, sid=1, price=52, size=5))
    e.process(msg(op.SELL, oid=2, aid=2, sid=1, price=50, size=5))
    r = e.process(msg(op.BUY, oid=3, aid=0, sid=1, price=55, size=10))
    fills = [rec.value for rec in r if rec.value.action in (op.BOUGHT, op.SOLD)]
    # best price (50, oid 2) trades first, then 52
    assert [f.oid for f in fills] == [2, 3, 1, 3]
    assert [f.price for f in fills] == [0, 5, 0, 3]


def test_time_priority_fifo_within_level():
    e = eng()
    seed(e)
    e.process(msg(op.SELL, oid=1, aid=1, sid=1, price=50, size=5))
    e.process(msg(op.SELL, oid=2, aid=2, sid=1, price=50, size=5))
    r = e.process(msg(op.BUY, oid=3, aid=0, sid=1, price=50, size=7))
    fills = [rec.value for rec in r if rec.value.action == op.SOLD]
    assert [f.oid for f in fills] == [1, 2]
    assert [f.size for f in fills] == [5, 2]
    # oid 2 remains with 3 left, still head of its bucket
    assert e.orders[2].size == 3


def test_non_crossing_rests():
    e = eng()
    seed(e)
    e.process(msg(op.SELL, oid=1, aid=1, sid=1, price=60, size=5))
    r = e.process(msg(op.BUY, oid=2, aid=0, sid=1, price=55, size=5))
    assert out_actions(r) == [("IN", op.BUY), ("OUT", op.BUY)]
    assert e.orders[2].size == 5


def test_q9_prev_pointer_leaks_in_echo():
    e = eng()
    seed(e)
    e.process(msg(op.SELL, oid=1, aid=1, sid=1, price=60, size=5))
    r = e.process(msg(op.SELL, oid=2, aid=2, sid=1, price=60, size=5))
    assert r[-1].value.prev == 1
    assert r[-1].value.next is None


def test_q2_sell_taker_ghost_trade():
    """Q2: a sell taker that exactly exhausts a maker performs one extra
    zero-size trade with the next still-crossing maker."""
    e = eng()
    seed(e)
    e.process(msg(op.BUY, oid=1, aid=1, sid=1, price=50, size=5))
    e.process(msg(op.BUY, oid=2, aid=2, sid=1, price=50, size=5))
    r = e.process(msg(op.SELL, oid=3, aid=0, sid=1, price=50, size=5))
    fills = [rec.value for rec in r if rec.value.action in (op.BOUGHT, op.SOLD)]
    # real fill with oid 1, then ghost zero-size fill pair with oid 2
    assert [(f.oid, f.size) for f in fills] == [(1, 5), (3, 5), (2, 0), (3, 0)]
    # fixed mode: no ghost
    e2 = eng("fixed")
    seed(e2)
    e2.process(msg(op.BUY, oid=1, aid=1, sid=1, price=50, size=5))
    e2.process(msg(op.BUY, oid=2, aid=2, sid=1, price=50, size=5))
    r2 = e2.process(msg(op.SELL, oid=3, aid=0, sid=1, price=50, size=5))
    fills2 = [rec.value for rec in r2 if rec.value.action in (op.BOUGHT, op.SOLD)]
    assert [(f.oid, f.size) for f in fills2] == [(1, 5), (3, 5)]


def test_q2_zero_size_buy_ghost_trade_against_non_crossing_ask():
    """Q2: a zero-size buy evaluates the sell-side comparison, producing a
    spurious zero-size trade against a NON-crossing ask."""
    e = eng()
    seed(e)
    e.process(msg(op.SELL, oid=1, aid=1, sid=1, price=60, size=5))
    r = e.process(msg(op.BUY, oid=2, aid=0, sid=1, price=50, size=0))
    fills = [rec.value for rec in r if rec.value.action in (op.BOUGHT, op.SOLD)]
    assert [(f.oid, f.size) for f in fills] == [(1, 0), (2, 0)]
    assert r[-1].value.action == op.BUY  # "matched" (size==0 -> true)


def test_q1_sid0_merged_book_buys_match_buys():
    """Q1: symbol 0's buy and sell sides share one book (-0 == 0): a
    crossing buy matches a RESTING BUY."""
    e = eng()
    seed(e, symbols=(0,))
    e.process(msg(op.BUY, oid=1, aid=1, sid=0, price=50, size=5))
    r = e.process(msg(op.BUY, oid=2, aid=0, sid=0, price=50, size=5))
    fills = [rec.value for rec in r if rec.value.action in (op.BOUGHT, op.SOLD)]
    assert [(f.action, f.oid) for f in fills] == [(op.SOLD, 1), (op.BOUGHT, 2)]
    # fixed mode: sides are disjoint, the second buy rests
    e2 = eng("fixed")
    seed(e2, symbols=(0,))
    e2.process(msg(op.BUY, oid=1, aid=1, sid=0, price=50, size=5))
    r2 = e2.process(msg(op.BUY, oid=2, aid=0, sid=0, price=50, size=5))
    assert out_actions(r2) == [("IN", op.BUY), ("OUT", op.BUY)]
    assert e2.orders[1].size == 5 and e2.orders[2].size == 5


# ---------------------------------------------------------------- cancel

def test_cancel_refunds_margin():
    e = eng()
    seed(e, accounts=(0,))
    e.process(msg(op.BUY, oid=1, aid=0, sid=1, price=60, size=10))
    assert e.balances[0] == 100_000 - 600
    r = e.process(msg(op.CANCEL, oid=1, aid=0))
    assert r[-1].value.action == op.CANCEL
    assert e.balances[0] == 100_000
    assert 1 not in e.orders


def test_cancel_auth_and_unknown():
    e = eng()
    seed(e)
    e.process(msg(op.BUY, oid=1, aid=0, sid=1, price=60, size=10))
    assert e.process(msg(op.CANCEL, oid=1, aid=2))[-1].value.action == op.REJECT
    assert e.process(msg(op.CANCEL, oid=99, aid=0))[-1].value.action == op.REJECT


def test_cancel_middle_preserves_fifo():
    e = eng()
    seed(e)
    for i, a in ((1, 0), (2, 1), (3, 2)):
        e.process(msg(op.SELL, oid=i, aid=a, sid=1, price=50, size=5))
    e.process(msg(op.CANCEL, oid=2, aid=1))
    r = e.process(msg(op.BUY, oid=4, aid=0, sid=1, price=50, size=10))
    fills = [rec.value for rec in r if rec.value.action == op.SOLD]
    assert [f.oid for f in fills] == [1, 3]


def test_cancel_head_and_tail():
    e = eng()
    seed(e)
    for i, a in ((1, 0), (2, 1), (3, 2)):
        e.process(msg(op.SELL, oid=i, aid=a, sid=1, price=50, size=5))
    e.process(msg(op.CANCEL, oid=1, aid=0))
    e.process(msg(op.CANCEL, oid=3, aid=2))
    r = e.process(msg(op.BUY, oid=4, aid=0, sid=1, price=55, size=10))
    fills = [rec.value for rec in r if rec.value.action == op.SOLD]
    assert [f.oid for f in fills] == [2]
    assert e.orders[4].size == 5  # remainder rested


def test_cancel_released_margin_reblocks_netted_position():
    """postRemoveAdjustments' adj mirrors checkBalance's netting. In java
    mode the adj-write lands on a garbage key (Q11,
    KProcessor.java:332); fixed mode restores the real position."""
    for compat in ("java", "fixed"):
        e = eng(compat)
        seed(e)
        e.process(msg(op.SELL, oid=1, aid=1, sid=1, price=50, size=10))
        e.process(msg(op.BUY, oid=2, aid=0, sid=1, price=50, size=10))
        # account 0 long 10 available; sell 10 against it (no margin), cancel
        e.process(msg(op.SELL, oid=3, aid=0, sid=1, price=40, size=10))
        bal = e.balances[0]
        e.process(msg(op.CANCEL, oid=3, aid=0))
        assert e.balances[0] == bal  # nothing was reserved, nothing refunded
        if compat == "fixed":
            assert e.positions[(0, 1)] == (10, 10)  # available restored
        else:
            # Q11: real key keeps the blocked state; garbage key (10, 0)
            # receives the "restored" value
            assert e.positions[(0, 1)] == (10, 0)
            assert e.positions[(10, 0)] == (10, 10)


def test_q11_second_fill_writes_garbage_key():
    """Q11: fillOrder's update branch keys the store by the position VALUE
    (KProcessor.java:283-284): the real (aid, sid) entry keeps its
    first-fill value forever; accumulation lands on garbage keys."""
    e = eng()
    seed(e)
    e.process(msg(op.SELL, oid=1, aid=1, sid=1, price=50, size=5))
    e.process(msg(op.BUY, oid=2, aid=0, sid=1, price=50, size=5))
    assert e.positions[(0, 1)] == (5, 5)
    assert e.positions[(1, 1)] == (-5, -5)
    e.process(msg(op.SELL, oid=3, aid=1, sid=1, price=50, size=5))
    e.process(msg(op.BUY, oid=4, aid=0, sid=1, price=50, size=5))
    # java: real keys unchanged, garbage keys hold the accumulation
    assert e.positions[(0, 1)] == (5, 5)
    assert e.positions[(5, 5)] == (10, 10)
    assert e.positions[(1, 1)] == (-5, -5)
    assert e.positions[(-5, -5)] == (-10, -10)
    # fixed: real keys accumulate, no garbage
    e2 = eng("fixed")
    seed(e2)
    for i, (act, aid) in enumerate(
            [(op.SELL, 1), (op.BUY, 0), (op.SELL, 1), (op.BUY, 0)], start=1):
        e2.process(msg(act, oid=i, aid=aid, sid=1, price=50, size=5))
    assert e2.positions[(0, 1)] == (10, 10)
    assert e2.positions[(1, 1)] == (-10, -10)
    assert (5, 5) not in e2.positions


# ------------------------------------------------------- symbol lifecycle

def test_add_symbol_duplicate_rejects():
    e = eng()
    assert e.process(msg(op.ADD_SYMBOL, sid=2))[-1].value.action == op.ADD_SYMBOL
    assert e.process(msg(op.ADD_SYMBOL, sid=2))[-1].value.action == op.REJECT


def test_q3_remove_symbol_inverted():
    e = eng()
    e.process(msg(op.ADD_SYMBOL, sid=2))
    # empty books exist -> removeAllOrders true -> removeSymbol FALSE -> REJECT
    r = e.process(msg(op.REMOVE_SYMBOL, sid=2))
    assert r[-1].value.action == op.REJECT
    assert 2 in e.books
    # symbol that never existed -> "succeeds"
    r2 = e.process(msg(op.REMOVE_SYMBOL, sid=9))
    assert r2[-1].value.action == op.REMOVE_SYMBOL


def test_q4_remove_symbol_nonempty_hangs():
    e = eng()
    seed(e)
    e.process(msg(op.BUY, oid=1, aid=0, sid=1, price=50, size=5))
    with pytest.raises(ReferenceHang):
        e.process(msg(op.REMOVE_SYMBOL, sid=1))


def test_fixed_remove_symbol_wipes_and_refunds():
    e = eng("fixed")
    seed(e)
    e.process(msg(op.BUY, oid=1, aid=0, sid=1, price=60, size=10))
    e.process(msg(op.SELL, oid=2, aid=1, sid=1, price=70, size=10))
    r = e.process(msg(op.REMOVE_SYMBOL, sid=1))
    assert r[-1].value.action == op.REMOVE_SYMBOL
    assert e.balances[0] == 100_000 and e.balances[1] == 100_000
    assert not e.orders and not e.buckets
    assert 2 not in e.books and 3 not in e.books


# ---------------------------------------------------------------- payout

def test_q5_q6_payout_always_rejects_in_java_mode():
    e = eng()
    seed(e)
    r = e.process(msg(op.PAYOUT, sid=1, size=97))
    assert r[-1].value.action == op.REJECT  # result ignored (Q6)
    # books untouched (removeAllOrders on empty book short-circuits)
    assert 1 in e.books and -1 in e.books


def test_fixed_payout_yes_resolution():
    e = eng("fixed")
    seed(e)
    e.process(msg(op.SELL, oid=1, aid=1, sid=1, price=50, size=10))
    e.process(msg(op.BUY, oid=2, aid=0, sid=1, price=50, size=10))
    r = e.process(msg(op.PAYOUT, sid=1, size=97))
    assert r[-1].value.action == op.PAYOUT
    # long credited 97*10, short debited 97*10
    assert e.balances[0] == 100_000 - 500 + 970
    assert e.balances[1] == 100_000 - 500 - 970
    assert (0, 1) not in e.positions and (1, 1) not in e.positions
    assert 2 not in e.books


def test_fixed_payout_no_resolution():
    e = eng("fixed")
    seed(e)
    e.process(msg(op.SELL, oid=1, aid=1, sid=1, price=50, size=10))
    e.process(msg(op.BUY, oid=2, aid=0, sid=1, price=50, size=10))
    r = e.process(msg(op.PAYOUT, sid=-1, size=97))
    assert r[-1].value.action == op.PAYOUT
    assert e.balances[0] == 100_000 - 500
    assert e.balances[1] == 100_000 - 500
    assert (0, 1) not in e.positions
    assert e.process(msg(op.PAYOUT, sid=1, size=97))[-1].value.action == op.REJECT


def test_fixed_payout_refunds_resting_margin():
    e = eng("fixed")
    seed(e)
    e.process(msg(op.BUY, oid=1, aid=0, sid=1, price=60, size=10))
    e.process(msg(op.PAYOUT, sid=1, size=97))
    assert e.balances[0] == 100_000  # margin released on wipe


# ----------------------------------------------------- fixed validation

def test_fixed_mode_validation():
    e = eng("fixed")
    seed(e)
    assert e.process(msg(op.BUY, oid=1, aid=0, sid=1, price=126, size=5)
                     )[-1].value.action == op.REJECT
    assert e.process(msg(op.BUY, oid=2, aid=0, sid=1, price=-1, size=5)
                     )[-1].value.action == op.REJECT
    assert e.process(msg(op.BUY, oid=3, aid=0, sid=1, price=50, size=0)
                     )[-1].value.action == op.REJECT
    assert e.process(msg(op.SELL, oid=4, aid=0, sid=1, price=125, size=1)
                     )[-1].value.action == op.SELL


# ----------------------------------------------------- unknown opcodes

def test_unknown_action_rejects():
    e = eng()
    assert e.process(msg(op.BOUGHT, aid=0))[-1].value.action == op.REJECT
    assert e.process(msg(42))[-1].value.action == op.REJECT
