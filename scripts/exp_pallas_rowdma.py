"""Chip experiment: Pallas row-DMA gather/scatter vs XLA full-array scatter.

The round-3 profile (artifacts/profile_r03_summary.md) shows ~36us/step
of the ~106us lane step going to two full-array int64 scatters into the
flat (S*A,) position arrays (XLA:TPU scatter rewrites the whole array,
~1us/MB). Replacement design validated here on the real chip:

  K1 gather_rows:  DMA the W active lanes' rows from the HBM-resident
                   flat array into a small (W, R) block.
  K2 scatter_rows: DMA updated rows back IN PLACE (input_output_aliases).

Constraint discovered on this backend: the X64-rewrite pass refuses s64
custom-call operands ("not implemented" for pallas_call), so the arrays
crossing the kernel boundary must be int32. Positions therefore live as
PLANAR lo/hi int32 pairs — flat (S*2A,) with element (lane, comp, acc)
at lane*2A + comp*A + acc — and the small (W, A) blocks are joined to
real s64 for arithmetic in XLA-land, split back before the write DMA.

Checks: parity vs the s64 scatter baseline, aliasing inside lax.scan,
marginal per-step cost via scan-length slope (wall timings are
tunnel-RTT polluted; use the T-slope).

Run: python scripts/exp_pallas_rowdma.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

S, A, W, E = 1025, 2048, 8, 16
R = 2 * A  # row length in i32 lanes: [lo x A | hi x A]
LN = 128
SUB = R // LN  # rows are (SUB, 128) tiles: Mosaic can't slice 1 sublane


def _i32(x):
    return np.int32(x)


def gather_rows_kernel(lanes_ref, flat_ref, out_ref, sem):
    for w in range(W):
        pltpu.make_async_copy(
            flat_ref.at[lanes_ref[_i32(w)]],
            out_ref.at[_i32(w)], sem.at[_i32(w)]).start()
    for w in range(W):
        pltpu.make_async_copy(
            flat_ref.at[lanes_ref[_i32(w)]],
            out_ref.at[_i32(w)], sem.at[_i32(w)]).wait()


def gather_rows(flat, lanes):
    return pl.pallas_call(
        gather_rows_kernel,
        out_shape=jax.ShapeDtypeStruct((W, SUB, LN), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.SemaphoreType.DMA((W,))],
    )(lanes, flat)


def scatter_rows_kernel(lanes_ref, flat_ref, rows_ref, out_ref, sem):
    # out_ref aliases flat_ref; skip the scrap lane S-1 (padding rows,
    # may appear multiple times — real lanes are distinct)
    for w in range(W):
        @pl.when(lanes_ref[_i32(w)] != S - 1)
        def _():
            pltpu.make_async_copy(
                rows_ref.at[_i32(w)],
                out_ref.at[lanes_ref[_i32(w)]],
                sem.at[_i32(w)]).start()
    for w in range(W):
        @pl.when(lanes_ref[_i32(w)] != S - 1)
        def _():
            pltpu.make_async_copy(
                rows_ref.at[_i32(w)],
                out_ref.at[lanes_ref[_i32(w)]],
                sem.at[_i32(w)]).wait()


def scatter_rows(flat, lanes, rows):
    return pl.pallas_call(
        scatter_rows_kernel,
        out_shape=jax.ShapeDtypeStruct(flat.shape, jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((W,))],
        input_output_aliases={1: 0},  # flat -> out, in place
    )(lanes, flat, rows)


def join64(lo, hi):
    return (lo.astype(jnp.int64) & 0xFFFFFFFF) | (hi.astype(jnp.int64) << 32)


def split64(v):
    return (v & 0xFFFFFFFF).astype(jnp.int32), (v >> 32).astype(jnp.int32)


def step_dma(carry, msg):
    """One scan step: gather W rows, s64 update on the block, scatter."""
    pa = carry
    lanes, acc, sgn = msg["lanes"], msg["acc"], msg["sgn"]
    rows = gather_rows(pa, lanes).reshape(W, R)        # (W, 2A) i32
    vals = join64(rows[:, :A], rows[:, A:])            # (W, A) s64
    oh = acc[:, :, None] == jnp.arange(A, dtype=jnp.int32)[None, None, :]
    vals = vals + jnp.sum(jnp.where(oh, sgn[:, :, None], 0), axis=1)
    lo, hi = split64(vals)
    pa = scatter_rows(pa, lanes,
                  jnp.concatenate([lo, hi], 1).reshape(W, SUB, LN))
    return pa, ()


def step_scatter(carry, msg):
    """Baseline: the engine's current flat s64 .at[idx].set scatter."""
    pa = carry
    lanes, acc, sgn = msg["lanes"], msg["acc"], msg["sgn"]
    idx = lanes[:, None] * A + acc
    a0 = pa[idx]
    pa = pa.at[idx].set(a0 + sgn)
    return pa, ()


def _msgs(T, seed):
    rng = np.random.default_rng(seed)
    return rng, {
        "lanes": jnp.asarray(
            np.stack([rng.choice(S - 1, W, replace=False)
                      for _ in range(T)]), jnp.int32),
        "acc": jnp.asarray(
            np.stack([np.stack([rng.choice(A, 2 * E, replace=False)
                                for _ in range(W)]) for _ in range(T)]),
            jnp.int32),
        "sgn": jnp.asarray(
            rng.integers(-(1 << 40), 1 << 40, (T, W, 2 * E)), jnp.int64),
    }


def run(kind, T, seed=0):
    rng, msgs = _msgs(T, seed)
    base = rng.integers(-(1 << 50), 1 << 50, S * A)
    if kind == "dma":
        pa_np = np.empty((S, 2, A), np.int32)
        pa_np[:, 0, :] = (base & 0xFFFFFFFF).reshape(S, A).astype(np.uint32).astype(np.int32)
        pa_np[:, 1, :] = (base >> 32).reshape(S, A).astype(np.int32)
        pa0 = jnp.asarray(pa_np.reshape(S, SUB, LN))
        step = step_dma
    else:
        pa0 = jnp.asarray(base, jnp.int64)
        step = step_scatter
    f = jax.jit(lambda pa, m: jax.lax.scan(step, pa, m)[0])
    out = f(pa0, msgs)
    out.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(3):
        out = f(pa0, msgs)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / 3
    out = np.asarray(out)
    if kind == "dma":
        v = out.reshape(S, 2, A)
        out = ((v[:, 0].astype(np.int64) & 0xFFFFFFFF)
               | (v[:, 1].astype(np.int64) << 32)).reshape(-1)
    return out, dt


def main():
    print(f"backend: {jax.devices()[0]}", file=sys.stderr)
    ref, _ = run("scatter", 16)
    got, _ = run("dma", 16)
    ok = np.array_equal(ref, got)
    print(f"i32-pair parity vs s64 scatter (T=16): {ok}", file=sys.stderr)
    if not ok:
        diff = np.nonzero(ref != got)[0]
        print(f"  {len(diff)} diffs, first at {diff[:10]}", file=sys.stderr)
        print(f"  ref {ref[diff[:5]]} got {got[diff[:5]]}", file=sys.stderr)
        return 1
    for kind in ("dma", "scatter"):
        _, t_lo = run(kind, 128)
        _, t_hi = run(kind, 1024)
        slope_us = (t_hi - t_lo) / (1024 - 128) * 1e6
        print(f"{kind}: T=128 {t_lo*1e3:.1f}ms  T=1024 {t_hi*1e3:.1f}ms  "
              f"slope {slope_us:.2f} us/step", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
