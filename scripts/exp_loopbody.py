"""Bisect the cost of lax.while_loop body constructs in Mosaic.

Each variant runs a sequential outer fori32 over B messages; per
message a while_loop executes exactly ITERS iterations of a candidate
body. Reports ns per message. Run on the real chip.
"""

import sys
import time

sys.path.insert(0, "/root/repo")
import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

I32 = jnp.int32
_i = np.int32
MIN32 = _i(-(1 << 31))
BIG = _i(1 << 30)
LN = 128
B = 1 << 18
ITERS = 1


def build(variant: str):
    def kernel(data_ref, out_ref, sm, vr):
        ci = jax.lax.broadcasted_iota(I32, (1, LN), 1)

        def one(m, carry):
            lane = m & _i(127)

            def body(c):
                k, acc, done = c
                row = data_ref[pl.ds(lane, 1), :]
                hit = jnp.min(jnp.where(row == acc, ci, BIG))
                emp = jnp.min(jnp.where(row == _i(0), ci, BIG))
                acc = acc + jnp.where(hit < emp, _i(1), _i(2))
                if variant in ("rmw", "branch"):
                    take = acc > _i(0)
                    if variant == "branch":
                        @pl.when(take)
                        def _():
                            r = vr[0:1, :]
                            vr[0:1, :] = jnp.where(ci == k, acc, r)
                    else:
                        r = vr[0:1, :]
                        vr[0:1, :] = jnp.where(
                            take & (ci == k), acc, r)
                if variant == "carry2":
                    pass
                return k + _i(1), acc, k + _i(1) >= _i(ITERS)

            if variant.startswith("sweep"):
                limit = m & _i(63)
                sgn = jnp.where((m & _i(1)) == _i(0), _i(1), _i(-1))

                def bodys(c):
                    remaining, e, ovf, emptied, done = c
                    fi2 = (jax.lax.broadcasted_iota(I32, (1, LN), 0)
                           * _i(LN)
                           + jax.lax.broadcasted_iota(I32, (1, LN), 1))
                    ci2 = jax.lax.broadcasted_iota(I32, (1, LN), 1)
                    p_blk = data_ref[pl.ds(lane * _i(2), 1), :]
                    q_blk = data_ref[pl.ds(lane * _i(2) + _i(1), 1), :]
                    wsize = vr[0:1, :]
                    cross = (wsize > _i(0)) & (
                        (p_blk - limit) * sgn <= _i(0))
                    pstar = jnp.min(jnp.where(cross, p_blk * sgn, BIG))
                    anyc = (pstar < BIG) & (remaining > _i(0))
                    at = cross & (p_blk * sgn == pstar)
                    sstar = jnp.min(jnp.where(at, q_blk, BIG))
                    at2 = at & (q_blk == sstar)
                    flat = jnp.min(jnp.where(at2, fi2, BIG))
                    have = MIN32 ^ jnp.max(
                        jnp.where(fi2 == flat, wsize ^ MIN32, MIN32))
                    fill = jnp.minimum(remaining, have)
                    exceed = anyc & (e >= _i(16))
                    take = anyc & ~exceed

                    @pl.when(take)
                    def _():
                        vr[0:1, :] = jnp.where(fi2 == flat,
                                               wsize - fill, wsize)

                    remaining = remaining - jnp.where(take, fill, _i(0))
                    e = e + jnp.where(take, _i(1), _i(0))
                    ovf = ovf | exceed
                    emptied = jnp.where(take, have - fill == _i(0),
                                        emptied)
                    done = ((~anyc) | exceed | (remaining == _i(0))
                            | (e >= _i(ITERS)))
                    return remaining, e, ovf, emptied, done

                vr[0:1, :] = data_ref[pl.ds(lane, 1), :]
                want = _i(0) if variant == "sweep0" else (m & _i(31))
                (res, e, _o, _em, _d) = jax.lax.while_loop(
                    lambda c: ~c[4], bodys,
                    (want, _i(0), False, False, want == _i(0)))
                sm[0] = sm[0] + res + e
                return carry
            if variant == "carryvec":
                def bodyv(c):
                    k, accv, done = c
                    row = data_ref[pl.ds(lane, 1), :]
                    hit = jnp.min(jnp.where(row == k, ci, BIG))
                    accv = jnp.where(ci == hit, accv + _i(1), accv)
                    return k + _i(1), accv, k + _i(1) >= _i(ITERS)

                _, accv, _ = jax.lax.while_loop(
                    lambda c: ~c[2], bodyv,
                    (_i(0), jnp.zeros((1, LN), I32), ITERS <= 0))
                res = jnp.max(accv)
            else:
                _, res, _ = jax.lax.while_loop(
                    lambda c: ~c[2], body, (_i(0), m, ITERS <= 0))
            sm[0] = sm[0] + res
            return carry

        def cond(c):
            return c[0] < _i(B)

        def step(c):
            i, x = c
            return i + _i(1), one(i, x)

        sm[0] = _i(0)
        jax.lax.while_loop(cond, step, (_i(0), _i(0)))
        out_ref[0:1, :] = jnp.where(ci == _i(0), sm[0], _i(0))

    def call(data):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((1, LN), I32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SMEM((4,), I32),
                            pltpu.VMEM((2, LN), I32)],
            interpret=jax.default_backend() != "tpu",
        )(data)

    return jax.jit(call)


def main():
    global ITERS
    data = jnp.asarray(np.random.default_rng(0)
                       .integers(1, 99, (256, LN)).astype(np.int32))
    for variant in ("sweep0", "sweep1"):
        for it in (1, 2):
            ITERS = it
            fn = build(f"{variant}")
            c = fn.lower(data).compile()
            t0 = time.perf_counter()
            np.asarray(c(data))
            _ = time.perf_counter() - t0
            ts = []
            for _r in range(3):
                t0 = time.perf_counter()
                np.asarray(c(data))
                ts.append(time.perf_counter() - t0)
            print(f"{variant:9s} iters={it}: {min(ts)/B*1e9:7.0f} ns/msg",
                  file=sys.stderr)


if __name__ == "__main__":
    main()
