"""Measure the seq kernel's transfer-free device path on the real chip.

Method (the axon tunnel forbids naive timing — see
utils.async_prefetch / ROUND4.md): AOT-compile the K-chunk scan, then
time [enqueue + device + one small fetch barrier] for the FULL stream
and for a single-chunk scan; the difference cancels the constant
tunnel round trip. Each timing is repeated and the minimum taken.
block_until_ready has shown not-actually-blocking behavior on axon, so
the barrier is an np.asarray of the (1,128) err plane.

Usage: python scripts/exp_devpath.py [slots] [events] [reps]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import kme_tpu._jaxsetup  # noqa: F401
import jax
import numpy as np

from kme_tpu.engine import seq as SQ
from kme_tpu.runtime.seqsession import SeqSession
from kme_tpu.wire import WireBatch, dumps_order
from kme_tpu.workload import zipf_symbol_stream


def main():
    slots = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    events = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000
    reps = int(sys.argv[3]) if len(sys.argv) > 3 else 3
    print(f"backend={jax.devices()[0].platform} slots={slots}", file=sys.stderr)

    msgs = zipf_symbol_stream(events, num_symbols=1024, num_accounts=2048,
                              seed=0, zipf_a=1.2)
    batch = WireBatch.from_msgs(msgs)
    cfg = SQ.SeqConfig(lanes=1024, slots=slots, accounts=2048,
                       max_fills=16, batch=4096, hbm_books=slots > 512)
    ses = SeqSession(cfg)
    t0 = time.perf_counter()
    cols, hr, stacked, cnts, K = ses._plan(batch)
    print(f"plan {time.perf_counter()-t0:.3f}s K={K} n={len(cols['act'])}",
          file=sys.stderr)

    state0 = ses.state
    small = {f: v[:1] for f, v in stacked.items()}
    full_d = jax.device_put(stacked)
    small_d = jax.device_put(small)

    scanK = SQ.build_seq_scan(cfg, K)
    scan1 = SQ.build_seq_scan(cfg, 1)
    t0 = time.perf_counter()
    cK = scanK.lower(state0, full_d).compile()
    c1 = scan1.lower(state0, small_d).compile()
    print(f"AOT compile {time.perf_counter()-t0:.1f}s", file=sys.stderr)

    def timed(compiled, st, inp):
        t0 = time.perf_counter()
        st2, _out = compiled(st, inp)
        np.asarray(st2["err"])  # completion barrier (512B fetch)
        return time.perf_counter() - t0

    # warm both (first dispatch may carry lazy init)
    timed(c1, state0, small_d)
    timed(cK, state0, full_d)
    t_small = [timed(c1, state0, small_d) for _ in range(reps)]
    t_full = [timed(cK, state0, full_d) for _ in range(reps)]
    n = len(cols["act"])
    dev = min(t_full) - min(t_small)
    print(f"t_full={[round(x,4) for x in t_full]}", file=sys.stderr)
    print(f"t_small={[round(x,4) for x in t_small]}", file=sys.stderr)
    print(f"device ~= {dev*1e3:.1f} ms for {n} msgs "
          f"({n/max(dev,1e-9)/1e6:.2f} M msg/s)", file=sys.stderr)


if __name__ == "__main__":
    main()
