"""Capture a jax.profiler trace of the lane scan and print the top
device ops by self-time, aggregated from the trace JSON."""

import glob
import gzip
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

from kme_tpu.engine import lanes as L


def main():
    S, N, A, E, T = 1024, 128, 2048, 16, 128
    if len(sys.argv) > 2:
        S, N, A, E, T = map(int, sys.argv[2:7])
    out_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/kme_trace"
    cfg = L.LaneConfig(lanes=S, slots=N, accounts=A, max_fills=E, steps=T)
    state = L.make_lane_state(cfg)
    rng = np.random.default_rng(0)
    batch = {
        "act": jnp.asarray(rng.integers(0, 3, (T, S)), jnp.int32),
        "oid": jnp.asarray(rng.integers(1, 1 << 50, (T, S)), jnp.int64),
        "aid": jnp.asarray(rng.integers(0, A, (T, S)), jnp.int32),
        "price": jnp.asarray(rng.integers(0, 126, (T, S)), jnp.int32),
        "size": jnp.asarray(rng.integers(1, 100, (T, S)), jnp.int32),
    }
    step = jax.jit(L.build_lane_step(cfg))
    state, outs = step(state, batch)   # compile + warm
    np.asarray(state["err"])

    jax.profiler.start_trace(out_dir)
    state, outs = step(state, batch)
    np.asarray(state["err"])
    jax.profiler.stop_trace()

    paths = glob.glob(os.path.join(out_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        print("no trace json found under", out_dir, file=sys.stderr)
        return
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # device-side complete events: pick pids whose process name mentions
    # TPU; fall back to all X events
    pid_names = {e["pid"]: e["args"].get("name", "")
                 for e in events if e.get("ph") == "M"
                 and e.get("name") == "process_name" and "args" in e}
    dev_pids = {p for p, n in pid_names.items()
                if "TPU" in n or "tpu" in n or "Device" in n}
    agg = defaultdict(float)
    cnt = defaultdict(int)
    total = 0.0
    for e in events:
        if e.get("ph") != "X":
            continue
        if dev_pids and e.get("pid") not in dev_pids:
            continue
        name = e.get("name", "?")
        dur = float(e.get("dur", 0.0))
        agg[name] += dur
        cnt[name] += 1
        total += dur
    print(f"pids seen: {sorted(pid_names.items())}", file=sys.stderr)
    print(f"total device op time: {total/1e3:.1f} ms", file=sys.stderr)
    for name, dur in sorted(agg.items(), key=lambda kv: -kv[1])[:30]:
        print(f"{dur/1e3:10.2f} ms  x{cnt[name]:<6d} {name[:110]}",
              file=sys.stderr)


if __name__ == "__main__":
    main()
