"""De-risk experiment for the sequential Pallas mega-kernel (round 4).

Question: can a Pallas TPU kernel process a micro-batch of B messages
STRICTLY SEQUENTIALLY (the reference's own semantics,
KProcessor.java:95-126) fast enough to beat the vectorized sweep engine
— i.e. what does one message cost in device time when the hot state is
VMEM-resident and the per-message work is scalar-driven row ops?

This is NOT the engine: it runs a simplified trade-only core (match
sweep against the opposite side + rest of the residual) with none of the
balance/position/i64 machinery. What it shares with the real kernel is
the *cost model*: SMEM scalar message reads driving dynamic (1, N) row
loads/stores, masked vector reductions for best-maker search, predicated
fill iterations, and per-message output row RMW.

Usage: python scripts/exp_seqkernel.py [B] [E] [S]
Prints us/msg for the kernel and a numpy replica check.

RESULTS (v5e chip, 2026-07-30): with the correctness phase's np.asarray
fetch removed from the process, the bare sweep body runs at **~64 ns/msg
(15.5M msg/s)** at B=2048, S=1024 — the sequential-kernel design beats
the vectorized sweep engine's per-step op-count floor by ~2 orders of
magnitude. CAVEAT: after any output fetch, the axon tunnel degrades
subsequent dispatches to ~RTT (~100-160ms) each, so THIS script's timed
numbers (which run after the correctness fetch) are tunnel-bound, not
kernel-bound. Mosaic constraints discovered here (i64 fori index, weak
literals, scalar jnp.sum, i1-vector select, aliased-out-ref reads) are
recorded in the engine module's docstring.
"""

import functools
import os
import sys
import time

sys.setrecursionlimit(100_000)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

I32 = jnp.int32
BIG = np.int32(1 << 30)


def fori32(n, body, init):
    """fori_loop with an int32 induction variable. Under x64,
    lax.fori_loop always carries an i64 counter, which Mosaic cannot
    convert back to i32 (the convert lowering recurses) — so roll the
    loop with while_loop and an explicit np.int32 counter."""
    def cond(c):
        return c[0] < np.int32(n)

    def step(c):
        i, carry = c
        return i + np.int32(1), body(i, carry)

    return jax.lax.while_loop(cond, step, (np.int32(0), init))[1]


def build(B, E, S, N=128):
    """price/size planes are (2S, N): row 2*lane+side. Buy=side 0 rests
    on row 2l+0, sweeps row 2l+1 (asks, min price first); sell mirrors.
    Outputs: residual per message."""

    def kernel(lane_s, isbuy_s, price_s, size_s,
               price_ref, size_ref, oprice_ref, osize_ref, resid_ref):
        # aliased in/out: copy happens via aliasing (same buffers)
        iota = jax.lax.broadcasted_iota(I32, (1, N), 1)
        def one(m, _):
            lane = lane_s[m]
            isbuy = isbuy_s[m]
            limit = price_s[m]
            want = size_s[m]
            opp = lane * 2 + isbuy          # isbuy=1 -> sweep asks row
            own = lane * 2 + (1 - isbuy)

            # state lives in the ALIASED OUTPUT refs: read and write
            # through them only, so message m sees m-1's writes (the
            # input refs are just the aliasing anchors)
            prow = oprice_ref[pl.ds(opp, 1), :]
            srow = osize_ref[pl.ds(opp, 1), :]

            # Mosaic cannot select between i1 vectors: fold the side
            # into an i32 sign so one compare serves both directions
            sgn = np.int32(1) - np.int32(2) * (np.int32(1) - isbuy)

            def fill_iter(e, carry):
                srow, remaining = carry
                live = srow > 0
                cross = live & ((prow - limit) * sgn <= np.int32(0))
                cross = cross & (remaining > 0)
                # best price level (buy: lowest ask; sell: highest bid),
                # then FIFO proxy: lowest slot index at that price
                keyp = jnp.where(cross, prow * sgn, BIG)
                best_p = jnp.min(keyp)
                at = cross & (keyp == best_p)
                idx = jnp.min(jnp.where(at, iota, BIG))
                have = jnp.max(jnp.where(iota == idx, srow, np.int32(0)))
                can = (best_p < BIG).astype(I32)
                fill = jnp.minimum(remaining, have) * can
                srow = jnp.where(iota == idx, srow - fill, srow)
                return srow, remaining - fill

            srow, remaining = fori32(E, fill_iter, (srow, want))
            osize_ref[pl.ds(opp, 1), :] = srow

            # rest the residual on own side at the first free slot
            @pl.when(remaining > 0)
            def _():
                oprow = oprice_ref[pl.ds(own, 1), :]
                osrow = osize_ref[pl.ds(own, 1), :]
                free = jnp.min(jnp.where(osrow == 0, iota, BIG))
                hit = iota == free
                oprice_ref[pl.ds(own, 1), :] = jnp.where(hit, limit, oprow)
                osize_ref[pl.ds(own, 1), :] = jnp.where(hit, remaining, osrow)

            # per-message output: residual -> row RMW
            r = resid_ref[pl.ds(m >> 7, 1), :]
            resid_ref[pl.ds(m >> 7, 1), :] = jnp.where(
                iota == (m & np.int32(127)), remaining, r)
            return np.int32(0)

        fori32(B, one, np.int32(0))

    @jax.jit
    def run(lane, isbuy, price, size, bprice, bsize):
        return pl.pallas_call(
            kernel,
            out_shape=(jax.ShapeDtypeStruct((2 * S, N), jnp.int32),
                       jax.ShapeDtypeStruct((2 * S, N), jnp.int32),
                       jax.ShapeDtypeStruct((B // 128, 128), jnp.int32)),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 4
            + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
            out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                       pl.BlockSpec(memory_space=pltpu.VMEM),
                       pl.BlockSpec(memory_space=pltpu.VMEM)),
            input_output_aliases={4: 0, 5: 1},
            interpret=jax.default_backend() != "tpu",
        )(lane, isbuy, price, size, bprice, bsize)

    return run


def replica(lane, isbuy, price, size, bprice, bsize, E):
    bprice = bprice.copy()
    bsize = bsize.copy()
    resid = np.zeros(len(lane), np.int32)
    for m in range(len(lane)):
        l, b, p, want = lane[m], isbuy[m], price[m], size[m]
        opp, own = 2 * l + b, 2 * l + (1 - b)
        remaining = want
        for _ in range(E):
            if remaining <= 0:
                break
            live = bsize[opp] > 0
            cross = live & ((bprice[opp] <= p) if b else (bprice[opp] >= p))
            if not cross.any():
                break
            keyp = np.where(cross, bprice[opp] if b else -bprice[opp], BIG)
            bp = keyp.min()
            idx = np.where(cross & (keyp == bp))[0][0]
            fill = min(remaining, bsize[opp][idx])
            bsize[opp][idx] -= fill
            remaining -= fill
        if remaining > 0:
            free = np.where(bsize[own] == 0)[0]
            if len(free):
                bprice[own][free[0]] = p
                bsize[own][free[0]] = remaining
        resid[m] = remaining
    return bprice, bsize, resid


def main():
    B = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    E = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    S = int(sys.argv[3]) if len(sys.argv) > 3 else 1024
    N = 128
    rng = np.random.default_rng(0)
    lane = rng.integers(0, S, B).astype(np.int32)
    isbuy = rng.integers(0, 2, B).astype(np.int32)
    price = rng.integers(1, 126, B).astype(np.int32)
    size = rng.integers(1, 100, B).astype(np.int32)
    bprice = np.zeros((2 * S, N), np.int32)
    bsize = np.zeros((2 * S, N), np.int32)

    run = build(B, E, S, N)
    t0 = time.perf_counter()
    out = jax.tree.map(np.asarray, run(lane, isbuy, price, size,
                                       jnp.asarray(bprice),
                                       jnp.asarray(bsize)))
    print(f"compile+first: {time.perf_counter()-t0:.1f}s", file=sys.stderr)

    wp, ws, wr = replica(lane, isbuy, price, size, bprice, bsize, E)
    ok_s = (out[1] == ws).all()
    ok_r = (out[2].reshape(-1)[:B] == wr).all()
    # price plane only meaningful where size>0
    ok_p = (np.where(ws > 0, out[0], 0) == np.where(ws > 0, wp, 0)).all()
    print(f"correct: size={ok_s} resid={ok_r} price={ok_p}", file=sys.stderr)

    # timing: state round-trips through the jit boundary each call
    args = (lane, isbuy, price, size)
    st = (jnp.asarray(bprice), jnp.asarray(bsize))
    for _ in range(2):
        o = run(*args, *st)
        st = (o[0], o[1])
    jax.block_until_ready(st)
    t0 = time.perf_counter()
    reps = 20
    for _ in range(reps):
        o = run(*args, *st)
        st = (o[0], o[1])
    jax.block_until_ready(st)
    dt = (time.perf_counter() - t0) / reps
    print(f"B={B} E={E} S={S}: {dt*1e3:.2f} ms/call, "
          f"{dt/B*1e6:.3f} us/msg, {B/dt/1e6:.2f} M msg/s", file=sys.stderr)


if __name__ == "__main__":
    main()
