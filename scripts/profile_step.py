"""Microbenchmark the lane-engine scan step on the active backend.

Times a T-step scan at bench shapes, then times isolated candidate ops at
the same shapes to locate the per-step cost. Details to stderr.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

from kme_tpu.engine import lanes as L


def _force(out):
    """Materialize on host — block_until_ready alone has shown
    not-actually-blocking behavior on the experimental axon backend."""
    leaves = jax.tree.leaves(out)
    np.asarray(leaves[0])
    np.asarray(leaves[-1])


def timeit(fn, *args, n=5, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    _force(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
        _force(out)
    return (time.perf_counter() - t0) / n


def main():
    S, N, A, E, T = 1024, 128, 2048, 16, 128
    if len(sys.argv) > 1:
        S, N, A, E, T = map(int, sys.argv[1:6])
    cfg = L.LaneConfig(lanes=S, slots=N, accounts=A, max_fills=E, steps=T)
    print(f"backend={jax.devices()[0].platform} S={S} N={N} A={A} E={E} T={T}",
          file=sys.stderr)

    state = L.make_lane_state(cfg)
    rng = np.random.default_rng(0)
    batch = {
        "act": jnp.asarray(rng.integers(0, 3, (T, S)), jnp.int32),
        "oid": jnp.asarray(rng.integers(1, 1 << 50, (T, S)), jnp.int64),
        "aid": jnp.asarray(rng.integers(0, A, (T, S)), jnp.int32),
        "price": jnp.asarray(rng.integers(0, 126, (T, S)), jnp.int32),
        "size": jnp.asarray(rng.integers(1, 100, (T, S)), jnp.int32),
    }
    step = jax.jit(L.build_lane_step(cfg))
    dt = timeit(step, state, batch)
    print(f"full scan: {dt*1e3:.1f} ms total, {dt/T*1e6:.0f} us/step",
          file=sys.stderr)

    # isolated candidate ops at step shapes
    key64 = jnp.asarray(rng.integers(0, 1 << 60, (S, N)), jnp.int64)
    aid1 = jnp.asarray(rng.integers(0, A, (S,)), jnp.int32)
    delta = jnp.asarray(rng.integers(-5, 5, (S,)), jnp.int64)
    bal = jnp.zeros((A,), jnp.int64)
    acc = jnp.asarray(rng.integers(0, A, (S, 2 * E)), jnp.int32)
    vals = jnp.asarray(rng.integers(0, 9, (S, 2 * E)), jnp.int64)
    posA = jnp.zeros((S, A), jnp.int64)
    sgn = vals
    idx2 = jnp.arange(2 * E, dtype=jnp.int32)

    cands = {
        "argsort(S,N) i64": jax.jit(lambda k: jnp.argsort(k, axis=1)),
        "2x argsort (order+inv)": jax.jit(
            lambda k: jnp.argsort(jnp.argsort(k, axis=1), axis=1)),
        "bal gather bal[aid]": jax.jit(lambda b, a: b[a]),
        "bal scatter .at[aid].add": jax.jit(
            lambda b, a, d: b.at[a].add(d)),
        "pos take_along (S,A)": jax.jit(
            lambda p, a: jnp.take_along_axis(p, a[:, None], axis=1)),
        "pos put_along (S,A)": jax.jit(
            lambda p, a, d: jnp.put_along_axis(
                p, a[:, None], d[:, None], axis=1, inplace=False)),
        "replay eq/le reductions": jax.jit(
            lambda ac, sg: (
                jnp.sum(jnp.where((ac[:, :, None] == ac[:, None, :])
                                  & (idx2[:, None] <= idx2[None, :])[None],
                                  sg[:, :, None], 0), axis=1))),
        "scat put_along (S,A) from (S,2E)": jax.jit(
            lambda p, ac, v: jnp.put_along_axis(
                jnp.concatenate([p, jnp.zeros((S, 1), p.dtype)], axis=1),
                ac, v, axis=1, inplace=False)[:, :A]),
    }
    args = {
        "argsort(S,N) i64": (key64,),
        "2x argsort (order+inv)": (key64,),
        "bal gather bal[aid]": (bal, aid1),
        "bal scatter .at[aid].add": (bal, aid1, delta),
        "pos take_along (S,A)": (posA, aid1),
        "pos put_along (S,A)": (posA, aid1, delta),
        "replay eq/le reductions": (acc, sgn),
        "scat put_along (S,A) from (S,2E)": (posA, acc, vals),
    }
    for name, fn in cands.items():
        dt = timeit(fn, *args[name])
        print(f"{name:38s} {dt*1e6:8.0f} us", file=sys.stderr)


if __name__ == "__main__":
    main()
