"""Microbenchmark the COMPACT lane-engine scan step.

Times a T-step compact scan at several (S, A, W, N) shapes to locate
the per-step cost (flat position-array scatters vs sort vs replay).
Usage: python scripts/bench_compact.py [T]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import kme_tpu._jaxsetup  # noqa: F401
import jax
import numpy as np

from kme_tpu.engine import lanes as L


def _force(out):
    leaves = jax.tree.leaves(out)
    np.asarray(leaves[0])
    np.asarray(leaves[-1])


def bench_shape(S, N, A, E, W, T, n=3, unroll=1):
    """Time a T-step compact scan; returns seconds per step."""
    cfg = L.LaneConfig(lanes=S + 1, slots=N, accounts=A, max_fills=E,
                       steps=T, width=W, unroll=unroll)
    state = L.make_lane_state(cfg)
    rng = np.random.default_rng(0)
    batch = {
        "act": rng.integers(1, 3, (T, W)).astype(np.int32),
        "oid": rng.integers(1, 1 << 40, (T, W)).astype(np.int64),
        "aid": rng.integers(0, A, (T, W)).astype(np.int32),
        "price": rng.integers(0, 126, (T, W)).astype(np.int32),
        "size": rng.integers(1, 40, (T, W)).astype(np.int32),
        "lane": rng.permuted(
            np.broadcast_to(np.arange(W, dtype=np.int32) % S, (T, W)).copy(),
            axis=1),
    }
    step = jax.jit(L.build_lane_step(cfg), donate_argnums=(0,))
    state, out = step(state, batch)  # compile + warmup
    _force(out)
    t0 = time.perf_counter()
    for _ in range(n):
        state, out = step(state, batch)
    _force(out)
    dt = (time.perf_counter() - t0) / n
    return dt / T


def main():
    T = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    print(f"backend={jax.devices()[0].platform} T={T}", file=sys.stderr)
    shapes = [
        # (S, N, A, E, W, unroll) — vary one axis around the bench point
        (1024, 128, 2048, 16, 16, 1),
        (1024, 128, 64, 16, 16, 1),     # tiny-A control: fixed-base size
        (1024, 128, 2048, 16, 16, 2),
        (1024, 128, 2048, 16, 16, 4),
        (1024, 128, 2048, 16, 16, 8),
    ]
    for S, N, A, E, W, U in shapes:
        us = bench_shape(S, N, A, E, W, T, unroll=U) * 1e6
        print(f"S={S:5d} N={N:4d} A={A:5d} E={E:3d} W={W:3d} U={U}  "
              f"{us:8.1f} us/step", file=sys.stderr)


if __name__ == "__main__":
    main()
