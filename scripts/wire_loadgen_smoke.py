#!/usr/bin/env python
"""CI smoke for the binary loadgen front door.

Boots a loopback TCP broker, runs ``kme-loadgen --connections N
--binary`` against it as a subprocess, then checks the exactly-once
invariants on the durable log: record count matches the report, and
every out_seq stamp is unique (zero duplicate stamps even though the
client retries transport faults).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--connections", type=int, default=10_000)
    ap.add_argument("--events", type=int, default=20_000)
    ap.add_argument("--report", default="wire-ci/loadgen-report.json")
    args = ap.parse_args()

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from kme_tpu.bridge.service import TOPIC_IN
    from kme_tpu.bridge.tcp import serve_broker

    os.makedirs(os.path.dirname(args.report) or ".", exist_ok=True)
    srv, broker = serve_broker("127.0.0.1", 0)
    try:
        host, port = srv.server_address[:2]
        rc = subprocess.call(
            [sys.executable, "-m", "kme_tpu.cli", "loadgen",
             "--events", str(args.events),
             "--broker", f"{host}:{port}",
             "--connections", str(args.connections), "--binary",
             "--report", args.report])
        if rc != 0:
            print(f"loadgen exited {rc}", file=sys.stderr)
            return 1
        with open(args.report) as fh:
            rep = json.load(fh)
        assert rep["produced"] == rep["events"], rep
        n = broker.end_offset(TOPIC_IN)
        assert n == rep["events"], (n, rep["events"])
        recs = broker.fetch(TOPIC_IN, 0, n)
        stamps = {r.out_seq for r in recs}
        assert len(stamps) == n, f"dup out_seq stamps: {n - len(stamps)}"
        print(f"loadgen smoke ok: {rep['produced']} records, "
              f"{rep['rate_rps']:.0f} rps, {rep['sheds']} sheds")
        return 0
    finally:
        srv.shutdown()
        srv.server_close()


if __name__ == "__main__":
    sys.exit(main())
