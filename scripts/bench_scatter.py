"""Scatter/gather strategy shootout at lane-step shapes, measured as
device time via chained fori_loop (carry-dependent indices defeat
hoisting; only a scalar crosses the tunnel)."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

S, N, A, E = 1024, 128, 2048, 16
K = 64
TWOE = 2 * E


def measure(body, init):
    fn = jax.jit(lambda k, x: jax.lax.fori_loop(0, k, body, x),
                 static_argnums=0)

    def t(k):
        out = fn(k, init)
        np.asarray(jax.tree.leaves(out)[0]).sum()
        t0 = time.perf_counter()
        out = fn(k, init)
        np.asarray(jax.tree.leaves(out)[0]).sum()
        return time.perf_counter() - t0

    t(1)
    return (t(K + 1) - t(1)) / K


def main():
    rng = np.random.default_rng(0)
    pos = jnp.zeros((S, A), jnp.int64)
    pos_w = jnp.zeros((S, A + TWOE), jnp.int64)   # scrap columns baked in
    acc0 = jnp.asarray(rng.integers(0, A, (S, TWOE)), jnp.int32)
    vals = jnp.asarray(rng.integers(1, 9, (S, TWOE)), jnp.int64)

    def perturb(k, ac):
        # carry-dependent indices so nothing hoists; stays in [0, A)
        return (ac + k) % A

    # baseline: put_along dup indices into (S, A)
    def body_base(k, carry):
        p, ac = carry
        ac = perturb(k, ac)
        cur = jnp.take_along_axis(p, ac, axis=1)
        p = jnp.put_along_axis(p, ac, cur + vals, axis=1, inplace=False)
        return (p, ac)

    print(f"base put_along+gather dup   {measure(body_base, (pos, acc0))*1e6:8.0f} us",
          file=sys.stderr)

    # sorted-unique lax.scatter into (S, A+2E)
    dn = jax.lax.ScatterDimensionNumbers(
        update_window_dims=(), inserted_window_dims=(1,),
        scatter_dims_to_operand_dims=(1,),
        operand_batching_dims=(0,), scatter_indices_batching_dims=(0,))

    def body_uniq(k, carry):
        p, ac = carry
        ac = perturb(k, ac)
        ac_s, val_s = jax.lax.sort((ac, vals), num_keys=1, dimension=1)
        dup = jnp.concatenate(
            [jnp.zeros((S, 1), bool), ac_s[:, 1:] == ac_s[:, :-1]], axis=1)
        j = jnp.arange(TWOE, dtype=jnp.int32)[None, :]
        idx = jnp.where(dup, A + j, ac_s)
        upd = jax.lax.scatter(
            p, idx[:, :, None], val_s, dn,
            indices_are_sorted=False, unique_indices=True,
            mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS)
        return (upd, ac)

    print(f"uniq lax.scatter (S,A+2E)   {measure(body_uniq, (pos_w, acc0))*1e6:8.0f} us",
          file=sys.stderr)

    # sorted+unique scatter
    def body_sortuniq(k, carry):
        p, ac = carry
        ac = perturb(k, ac)
        ac_s, val_s = jax.lax.sort((ac, vals), num_keys=1, dimension=1)
        dup = jnp.concatenate(
            [jnp.zeros((S, 1), bool), ac_s[:, 1:] == ac_s[:, :-1]], axis=1)
        j = jnp.arange(TWOE, dtype=jnp.int32)[None, :]
        idx = jnp.where(dup, A + j, ac_s)   # NOT sorted once redirected
        # re-sort so indices really are ascending per row
        idx2, val2 = jax.lax.sort((idx, val_s), num_keys=1, dimension=1)
        upd = jax.lax.scatter(
            p, idx2[:, :, None], val2, dn,
            indices_are_sorted=True, unique_indices=True,
            mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS)
        return (upd, ac)

    print(f"sorted-uniq scatter         {measure(body_sortuniq, (pos_w, acc0))*1e6:8.0f} us",
          file=sys.stderr)

    # gather with sorted indices
    def body_gsorted(k, carry):
        p, ac = carry
        ac = perturb(k, ac)
        ac_s, inv = jax.lax.sort(
            (ac, jnp.broadcast_to(jnp.arange(TWOE, dtype=jnp.int32),
                                  (S, TWOE))), num_keys=1, dimension=1)
        g = jnp.take_along_axis(p, ac_s, axis=1)
        _, g_back = jax.lax.sort((inv, g), num_keys=1, dimension=1)
        return (p + g_back.sum() * 0, (ac + g_back[:, :TWOE].astype(jnp.int32)) % A)

    print(f"gather via sorted idx       {measure(body_gsorted, (pos, acc0))*1e6:8.0f} us",
          file=sys.stderr)

    # plain gather baseline
    def body_g(k, carry):
        p, ac = carry
        ac = perturb(k, ac)
        g = jnp.take_along_axis(p, ac, axis=1)
        return (p + g.sum() * 0, (ac + g[:, :TWOE].astype(jnp.int32)) % A)

    print(f"gather dup baseline         {measure(body_g, (pos, acc0))*1e6:8.0f} us",
          file=sys.stderr)


if __name__ == "__main__":
    main()
