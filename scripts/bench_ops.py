"""Device-time microbenchmarks for candidate hot-op rewrites.

Each candidate is wrapped in a lax.fori_loop of K iterations inside one
jit and only a scalar checksum crosses the tunnel, so the measurement is
pure device compute: per-iter = (t(K) - t(0)) / K using two calls.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

S, N, A, E = 1024, 128, 2048, 16
K = 64


def chain(body, init):
    def run(k, x):
        return jax.lax.fori_loop(0, k, body, x)

    fn = jax.jit(run, static_argnums=0)

    def measure():
        out0 = fn(1, init)
        np.asarray(jax.tree.leaves(out0)[0]).sum()
        t0 = time.perf_counter()
        out0 = fn(1, init)
        np.asarray(jax.tree.leaves(out0)[0]).sum()
        t1 = time.perf_counter() - t0
        outk = fn(K + 1, init)
        np.asarray(jax.tree.leaves(outk)[0]).sum()
        t0 = time.perf_counter()
        outk = fn(K + 1, init)
        np.asarray(jax.tree.leaves(outk)[0]).sum()
        tk = time.perf_counter() - t0
        return (tk - t1) / K

    return measure()


def main():
    rng = np.random.default_rng(0)
    key64 = jnp.asarray(rng.integers(0, 1 << 60, (S, N)), jnp.int64)
    m_size = jnp.asarray(rng.integers(1, 100, (S, N)), jnp.int32)
    m_oid = jnp.asarray(rng.integers(1, 1 << 50, (S, N)), jnp.int64)
    m_aid = jnp.asarray(rng.integers(0, A, (S, N)), jnp.int32)
    m_price = jnp.asarray(rng.integers(0, 126, (S, N)), jnp.int32)
    slot_idx = jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32), (S, N))

    # A. current design: argsort + payload gathers + inverse-perm gather
    def body_a(_, carry):
        key, sz, oid, aid, price = carry
        order = jnp.argsort(key, axis=1)
        take = lambda a: jnp.take_along_axis(a, order, axis=1)
        sz_s, oid_s, aid_s, price_s = take(sz), take(oid), take(aid), take(price)
        inv = jnp.argsort(order, axis=1)
        back = jnp.take_along_axis(sz_s, inv, axis=1)
        return (key + 1, back, oid_s, aid_s + 1, price_s)

    dt = chain(body_a, (key64, m_size, m_oid, m_aid, m_price))
    print(f"A argsort+6 gathers        {dt*1e6:8.0f} us/iter", file=sys.stderr)

    # B. multi-operand lax.sort + inverse by second sort on slot index
    def body_b(_, carry):
        key, sz, oid, aid, price = carry
        key_s, sz_s, oid_s, aid_s, price_s, idx_s = jax.lax.sort(
            (key, sz, oid, aid, price, slot_idx), num_keys=1)
        new_sz = sz_s - 1
        _, back = jax.lax.sort((idx_s, new_sz), num_keys=1)
        return (key + 1, back, oid_s, aid_s + 1, price_s)

    dt = chain(body_b, (key64, m_size, m_oid, m_aid, m_price))
    print(f"B 2x multi-operand sort    {dt*1e6:8.0f} us/iter", file=sys.stderr)

    posA = jnp.zeros((S, A), jnp.int64)
    acc = jnp.asarray(rng.integers(0, A, (S, 2 * E)), jnp.int32)
    vals = jnp.asarray(rng.integers(0, 9, (S, 2 * E)), jnp.int64)

    # C. current: put_along_axis into (S, A+1) with dup indices
    def body_c(_, carry):
        p, ac = carry
        pad = jnp.concatenate([p, jnp.zeros((S, 1), p.dtype)], axis=1)
        pad = jnp.put_along_axis(pad, ac, vals, axis=1, inplace=False)
        return (pad[:, :A], ac)

    dt = chain(body_c, (posA, acc))
    print(f"C put_along dup (S,A+1)    {dt*1e6:8.0f} us/iter", file=sys.stderr)

    # D. unique-index scatter into (S, A+2E) scrap columns
    j = jnp.arange(2 * E, dtype=jnp.int32)[None, :]
    write = jnp.asarray(rng.random((S, 2 * E)) < 0.4)

    def body_d(_, carry):
        p, ac = carry
        pad = jnp.concatenate([p, jnp.zeros((S, 2 * E), p.dtype)], axis=1)
        idx = jnp.where(write, ac, A + j)
        pad = pad.at[jnp.arange(S)[:, None], idx].set(
            vals, unique_indices=True)
        return (pad[:, :A], ac)

    dt = chain(body_d, (posA, acc))
    print(f"D unique scatter (S,A+2E)  {dt*1e6:8.0f} us/iter", file=sys.stderr)

    # E. one-hot masked rebuild: where over (S, A, 2E) compare
    def body_e(_, carry):
        p, ac = carry
        onehot = ac[:, None, :] == jnp.arange(A, dtype=jnp.int32)[None, :, None]
        onehot = onehot & write[:, None, :]
        hit = jnp.any(onehot, axis=2)
        val = jnp.max(jnp.where(onehot, vals[:, None, :], -(1 << 62)), axis=2)
        return (jnp.where(hit, val, p), ac)

    dt = chain(body_e, (posA, acc))
    print(f"E one-hot where rebuild    {dt*1e6:8.0f} us/iter", file=sys.stderr)

    # F. single-column put_along (S,A) one index per row (the _pa1 form)
    aid1 = jnp.asarray(rng.integers(0, A, (S,)), jnp.int32)
    d1 = jnp.asarray(rng.integers(-5, 5, (S,)), jnp.int64)

    def body_f(_, carry):
        p, a = carry
        p = jnp.put_along_axis(p, a[:, None], d1[:, None], axis=1,
                               inplace=False)
        return (p, a)

    dt = chain(body_f, (posA, aid1))
    print(f"F put_along 1col (S,A)     {dt*1e6:8.0f} us/iter", file=sys.stderr)

    # G. 1-col unique scatter
    def body_g(_, carry):
        p, a = carry
        p = p.at[jnp.arange(S), a].set(d1, unique_indices=True)
        return (p, a)

    dt = chain(body_g, (posA, aid1))
    print(f"G at-set 1col unique       {dt*1e6:8.0f} us/iter", file=sys.stderr)

    # H. balance scatter-add (A,) from (S,) dup indices
    bal = jnp.zeros((A,), jnp.int64)

    def body_h(_, carry):
        b, a = carry
        return (b.at[a].add(d1), a)

    dt = chain(body_h, (bal, aid1))
    print(f"H bal scatter-add (A,)     {dt*1e6:8.0f} us/iter", file=sys.stderr)

    # I. replay reductions (S,2E,2E) masked where+sum
    idx2 = jnp.arange(2 * E, dtype=jnp.int32)
    sgn = vals

    def body_i(_, carry):
        ac, sg = carry
        eq = ac[:, :, None] == ac[:, None, :]
        le = (idx2[:, None] <= idx2[None, :])[None]
        pre = jnp.sum(jnp.where(eq & le, sg[:, :, None], 0), axis=1)
        return (ac + 1, sg + pre)

    dt = chain(body_i, (acc, sgn))
    print(f"I replay eq/le (S,2E,2E)   {dt*1e6:8.0f} us/iter", file=sys.stderr)


if __name__ == "__main__":
    main()
