"""Regenerate the JVM conformance pack (artifacts/conformance/).

The pack is the one-JVM-run validation path for the whole oracle chain
(BASELINE.md): seeded input fixtures + the byte streams our java-mode
oracle expects the real KProcessor to emit. Anyone with a JVM + Kafka
replays the fixtures through the reference (replay_jvm.sh /
docker-compose.yml in the pack) and diffs — a single run validates
every quirk Q1-Q11 the parity engines replicate.

Deterministic by construction: fixtures come from the seeded harness
port (kme_tpu/workload.py — the exchange_test.js distribution) and
expectations from the java-mode oracle; tests/test_conformance.py
regenerates and requires byte-identical files.

Usage: python scripts/make_conformance.py [outdir]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kme_tpu.oracle import OracleEngine  # noqa: E402
from kme_tpu.native.oracle import NativeOracleEngine, \
    native_available  # noqa: E402
from kme_tpu.wire import dumps_order  # noqa: E402
from kme_tpu.workload import harness_stream  # noqa: E402

FIXTURES = (
    # (name, events, seed) — stock harness shape: 10 accounts, 3
    # symbols, Q5 payout-opcode bug intact, no validation (the exact
    # exchange_test.js distribution)
    ("smoke_50", 50, 7),
    ("harness_1k", 1000, 0),
    ("harness_2k", 2000, 1),
)


def generate(outdir: str) -> None:
    os.makedirs(outdir, exist_ok=True)
    for name, events, seed in FIXTURES:
        msgs = harness_stream(events, seed=seed)
        eng = OracleEngine("java")
        in_path = os.path.join(outdir, f"{name}.in.jsonl")
        out_path = os.path.join(outdir, f"{name}.expected.txt")
        with open(in_path, "w") as fi, open(out_path, "w") as fo:
            for m in msgs:
                fi.write(dumps_order(m) + "\n")
                for rec in eng.process(m.copy()):
                    fo.write(rec.wire() + "\n")
        # post-replay STORE STATE (VERDICT r4: conformance must pin
        # java-mode store dumps, not just wire bytes): the native
        # engine's dump, sorted for a canonical line order, so a JVM
        # replay can diff end-state stores too (the reference's
        # RocksDB contents map 1:1 onto these records)
        if native_available():
            nat = NativeOracleEngine("java")
            nat.process_wire([m.copy() for m in msgs])
            store_path = os.path.join(outdir, f"{name}.store.txt")
            with open(store_path, "w") as fs:
                for line in sorted(nat.dump_state().splitlines()):
                    fs.write(line + "\n")
        print(f"{name}: {len(msgs)} messages "
              f"({os.path.getsize(out_path)} expected bytes)")


if __name__ == "__main__":
    generate(sys.argv[1] if len(sys.argv) > 1 else
             os.path.join(os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__))), "artifacts", "conformance"))
