#!/usr/bin/env python
"""Build the native host-runtime library out-of-band.

Normally kme_tpu.native.load_library() compiles on demand; this script
exists for the two cases that need a *specific* build up front:

  * CI warming the build cache:     python scripts/build_native.py
  * sanitizer runs (ASan + UBSan):  python scripts/build_native.py --sanitize

A sanitized .so cannot live in the normal cache (its tag would collide
with the -O3 build of the same sources), so it is written next to the
cache as kme_host_<tag>.asan.so and selected explicitly via the
KME_NATIVE_SO environment variable. Because the Python interpreter
itself is not instrumented, running under the sanitized library needs
libasan preloaded; the script prints the exact recipe, which is:

  LD_PRELOAD="$(gcc -print-file-name=libasan.so) \
              $(g++ -print-file-name=libstdc++.so.6)" \
  ASAN_OPTIONS=detect_leaks=0 \
  KME_NATIVE_SO=<path> python -m pytest tests/test_wire_fuzz.py ...

(leak checking is off because CPython "leaks" interned objects by
design and the noise would bury real findings; heap-buffer-overflow,
use-after-free and all UBSan checks stay fatal. libstdc++ rides in
LD_PRELOAD too: python itself doesn't link it, so without it ASan's
__cxa_throw interceptor can't resolve the real symbol at startup and
aborts the process the first time a bundled C++ extension -- jaxlib's
MLIR -- throws an exception.)
"""

import argparse
import glob
import hashlib
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
NATIVE = os.path.join(REPO, "kme_tpu", "native")
# every translation unit in the package, so a newly added source can
# never be silently missing from the sanitized build (the runtime
# loader in kme_tpu/native/__init__.py compiles the same set)
SRCS = sorted(glob.glob(os.path.join(NATIVE, "kme_*.cpp")))

BASE = ["-shared", "-fPIC", "-std=c++17"]
SAN = ["-g", "-O1", "-fno-omit-frame-pointer",
       "-fsanitize=address,undefined", "-fno-sanitize-recover=all"]


def source_tag() -> str:
    h = hashlib.sha256()
    for src in SRCS:
        with open(src, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sanitize", action="store_true",
                    help="ASan+UBSan build (kme_host_<tag>.asan.so)")
    ap.add_argument("--cxx", default=os.environ.get("CXX", "g++"))
    ap.add_argument("--out", default=None,
                    help="output path (default: the cache path "
                         "load_library() uses, or .asan.so beside it)")
    args = ap.parse_args(argv)

    tag = source_tag()
    build_dir = os.path.join(NATIVE, "_build")
    os.makedirs(build_dir, exist_ok=True)
    if args.out:
        out = args.out
    elif args.sanitize:
        out = os.path.join(build_dir, f"kme_host_{tag}.asan.so")
    else:
        out = os.path.join(build_dir, f"kme_host_{tag}.so")

    flags = BASE + (SAN if args.sanitize else ["-O3"])
    cmd = [args.cxx] + flags + SRCS + ["-o", out]
    print("+ " + " ".join(cmd), file=sys.stderr)
    rc = subprocess.run(cmd).returncode
    if rc != 0:
        return rc
    print(out)
    if args.sanitize:
        def probe(flag):
            r = subprocess.run([args.cxx, flag], capture_output=True,
                               text=True)
            return r.stdout.strip()

        libasan = probe("-print-file-name=libasan.so") or "libasan.so"
        libcxx = (probe("-print-file-name=libstdc++.so.6")
                  or "libstdc++.so.6")
        print(f"\nrun tests under it with:\n"
              f"  LD_PRELOAD=\"{libasan} {libcxx}\" \\\n"
              f"  ASAN_OPTIONS=detect_leaks=0 \\\n"
              f"  KME_NATIVE_SO={out} \\\n"
              f"  python -m pytest tests/test_wire_fuzz.py "
              f"tests/test_host_path.py -q", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
