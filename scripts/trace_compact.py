"""Trace the COMPACT lane scan step (width W, optional pos_dma) and
print top device ops by self-time per scan iteration.

Usage: python scripts/trace_compact.py [W] [pos_dma 0|1] [T]
"""

import glob
import gzip
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import kme_tpu._jaxsetup  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

from kme_tpu.engine import lanes as L


def main():
    W = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    dma = bool(int(sys.argv[2])) if len(sys.argv) > 2 else True
    T = int(sys.argv[3]) if len(sys.argv) > 3 else 256
    S, N, A, E = 1024, 128, 2048, 16
    cfg = L.LaneConfig(lanes=S + 1, slots=N, accounts=A, max_fills=E,
                       steps=T, width=W, pos_dma=dma)
    state = L.make_lane_state(cfg)
    rng = np.random.default_rng(0)
    lanes = np.stack([rng.choice(S, W, replace=False) for _ in range(T)])
    batch = {
        "act": jnp.asarray(rng.integers(0, 3, (T, W)), jnp.int32),
        "oid": jnp.asarray(rng.integers(1, 1 << 40, (T, W)), jnp.int64),
        "aid": jnp.asarray(rng.integers(0, A, (T, W)), jnp.int32),
        "price": jnp.asarray(rng.integers(0, 126, (T, W)), jnp.int32),
        "size": jnp.asarray(rng.integers(1, 100, (T, W)), jnp.int32),
        "lane": jnp.asarray(lanes, jnp.int32),
    }
    step = jax.jit(L.build_lane_step(cfg))
    st, outs = step(state, batch)   # compile + warm
    np.asarray(st["err"])
    import time
    t0 = time.perf_counter()
    st2, _ = step(st, batch)
    np.asarray(st2["err"])
    wall = time.perf_counter() - t0
    print(f"W={W} pos_dma={dma} T={T}: warm wall {wall*1e3:.1f}ms "
          f"({wall/T*1e6:.1f} us/step incl. RTT)", file=sys.stderr)

    out_dir = f"/tmp/kme_trace_compact_{W}_{int(dma)}"
    jax.profiler.start_trace(out_dir)
    st3, outs = step(st2, batch)
    np.asarray(st3["err"])
    jax.profiler.stop_trace()

    paths = glob.glob(os.path.join(out_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        print("no trace json found under", out_dir, file=sys.stderr)
        return
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    dur = defaultdict(float)
    cnt = defaultdict(int)
    for e in events:
        if e.get("ph") != "X":
            continue
        name = e.get("name", "")
        if "$" in name or ".py" in name:
            continue  # host events
        dur[name] += e.get("dur", 0.0)
        cnt[name] += 1
    # per-iteration ops: count divisible by T
    tot = 0.0
    rows = []
    for name, d in dur.items():
        if cnt[name] % T == 0 and cnt[name] > 0:
            per = d / T
            tot += per
            rows.append((per, cnt[name] // T, name))
    rows.sort(reverse=True)
    print(f"per-iteration device total: {tot:.1f} us/step", file=sys.stderr)
    for per, c, name in rows[:25]:
        print(f"  {per:7.2f} us x{c:2d}  {name}", file=sys.stderr)


if __name__ == "__main__":
    main()
