#!/usr/bin/env python
"""Driver benchmark entry: prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N} on stdout.

Details go to stderr. Run on the active backend (real TPU under the
driver). See kme_tpu/benchmarks.py for methodology and the baseline
assumption.
"""

import sys

from kme_tpu.benchmarks import main

if __name__ == "__main__":
    sys.exit(main())
