"""Conflict-free scheduler: wire messages -> (step, lane) placements.

The exactness contract (kme_tpu/engine/lanes.py docstring): a parallel
step is bit-exact with serial replay iff
  (a) each symbol's messages stay in arrival order in its lane,
  (b) no two messages in a step share an actor account,
  (c) PAYOUT / REMOVE_SYMBOL run as exclusive barrier steps.
The greedy placement below enforces all three with two monotone clocks:
`lane_next[lane]` (first free step of the lane) and `actor_next[aid]`
(first step after the account's last message). Both only move forward,
so per-symbol FIFO and per-account ordering hold by construction.

The scheduler also owns the id spaces: raw aid -> dense account index
(device arrays are dense — the reference's Long-keyed RocksDB maps,
KProcessor.java:30-33, have no device equivalent), raw sid -> lane, and
the oid -> sid routing map for cancels (the reference resolves cancels
through the global Orders store, KProcessor.java:290; here the host
routes them to the owning lane). Messages the device cannot act on
(unknown-oid cancels, negative-sid ADD_SYMBOL, unmapped-symbol
REMOVE/PAYOUT) are resolved host-side as synthesized rejects — state-free
in the reference too.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from kme_tpu import opcodes as op
from kme_tpu.engine import lanes as L
from kme_tpu.wire import OrderMsg


class CapacityError(RuntimeError):
    """The workload exceeds a static device capacity (symbols, accounts)."""


class EnvelopeError(RuntimeError):
    """A wire value falls outside the Jackson-parseable envelope (int32
    price/size) — input on which the reference's deserializer throws and
    its Streams thread dies (KProcessor.java:513-517)."""


@dataclasses.dataclass
class Placed:
    """A device-executed message: its (segment, step, lane) coordinates.
    Under active-lane compaction `slot` is the message's position within
    its step (0..width-1) — the column of the (T, W) scan grid."""
    msg_index: int
    segment: int
    step: int       # step within segment
    lane: int
    lane_act: int   # L_* opcode
    aid_idx: int
    oid: int
    price: int
    size: int
    slot: int = 0


@dataclasses.dataclass
class Barrier:
    """A barrier-executed message (PAYOUT / REMOVE_SYMBOL)."""
    msg_index: int
    lane: int
    mode: int       # 0 remove, 1 payout YES, 2 payout NO
    credit_size: int


@dataclasses.dataclass
class HostReject:
    """Resolved host-side: emit IN + OUT(REJECT) without device work."""
    msg_index: int


_COL_DTYPES = (
    ("msg_index", "int64"), ("segment", "int32"), ("step", "int32"),
    ("lane", "int32"), ("act", "int32"), ("aidx", "int32"),
    ("oid", "int64"), ("price", "int32"), ("size", "int32"),
    ("slot", "int32"),
)


@dataclasses.dataclass
class Schedule:
    """segments[i] = number of steps in scan segment i; the executable
    plan alternates scan segments and barriers in `program` order.

    Placements are COLUMNAR (`cols`: one numpy array per field, rows in
    arrival order — so `segment` and, per lane, `step` are nondecreasing
    by construction); the device pack path slices them without touching
    Python objects. `placements` materializes row objects for tests."""
    cols: dict                # field -> np.ndarray, aligned rows
    barriers: List[Barrier]
    host_rejects: List[HostReject]
    segment_steps: List[int]
    program: List[tuple]  # ("scan", seg_idx) | ("barrier", barrier_idx)

    _placements_cache: Optional[List[Placed]] = None

    @property
    def placements(self) -> List[Placed]:
        """Row-object view of `cols` (tests/debugging; O(n) to build,
        cached on first access)."""
        if self._placements_cache is None:
            c = self.cols
            self._placements_cache = [
                Placed(*(int(c[name][i]) for name, _ in _COL_DTYPES))
                for i in range(len(c["msg_index"]))]
        return self._placements_cache


_TRADE_ACTS = {op.BUY: L.L_BUY, op.SELL: L.L_SELL}


def make_scheduler(num_lanes: int, num_accounts: int, width: int = 0):
    """The native C++ scheduler when the toolchain/library is available
    (KME_NATIVE=0 disables), else this module's Python implementation —
    identical plans either way (tests/test_native_sched.py)."""
    try:
        from kme_tpu.native.sched import NativeScheduler, native_available

        if native_available():
            return NativeScheduler(num_lanes, num_accounts, width)
    except Exception as e:  # pragma: no cover - defensive fallback
        import sys

        print(f"kme_tpu: native scheduler unavailable ({e}); "
              f"using the Python fallback", file=sys.stderr)
    return Scheduler(num_lanes, num_accounts, width)


class Scheduler:
    def __init__(self, num_lanes: int, num_accounts: int,
                 width: int = 0) -> None:
        if width < 0:
            raise ValueError(f"width must be >= 0, got {width}")
        self.S = num_lanes
        self.A = num_accounts
        self.width = width  # >0: at most `width` messages per scan step
        self.aid_idx: Dict[int, int] = {}
        self.sid_lane: Dict[int, int] = {}
        self.oid_sid: Dict[int, int] = {}
        self._rr_lane = 0  # round-robin for lane-free (account) ops

    # -- id spaces ---------------------------------------------------------

    def _acct(self, aid: int) -> int:
        idx = self.aid_idx.get(aid)
        if idx is None:
            if len(self.aid_idx) >= self.A:
                raise CapacityError(
                    f"account capacity {self.A} exhausted (aid={aid})")
            idx = len(self.aid_idx)
            self.aid_idx[aid] = idx
        return idx

    def _lane(self, sid: int) -> int:
        lane = self.sid_lane.get(sid)
        if lane is None:
            if len(self.sid_lane) >= self.S:
                raise CapacityError(
                    f"symbol capacity {self.S} exhausted (sid={sid})")
            lane = len(self.sid_lane)
            self.sid_lane[sid] = lane
        return lane

    def acct_of_idx(self) -> List[int]:
        """Dense index -> raw aid (for fill-event reconstruction)."""
        out = [0] * len(self.aid_idx)
        for aid, idx in self.aid_idx.items():
            out[idx] = aid
        return out

    def sid_of_lane(self) -> Dict[int, int]:
        return {lane: sid for sid, lane in self.sid_lane.items()}

    # -- planning ----------------------------------------------------------

    def plan(self, msgs: Sequence[OrderMsg]) -> Schedule:
        """Greedy conflict-free placement of a message batch."""
        from kme_tpu.oracle import javalong as jl

        rows = {name: [] for name, _ in _COL_DTYPES}
        barriers: List[Barrier] = []
        host_rejects: List[HostReject] = []
        segment_steps: List[int] = []
        program: List[tuple] = []

        lane_next = [0] * self.S
        actor_next: Dict[int, int] = {}
        step_fill: Dict[int, int] = {}  # step -> messages placed (width cap)
        first_open = 0  # monotone watermark: every step below it is full
        seg = 0
        seg_height = 0  # steps used so far in the current segment

        def close_segment():
            nonlocal seg, seg_height, lane_next, step_fill, first_open
            if seg_height > 0:
                segment_steps.append(seg_height)
                program.append(("scan", len(segment_steps) - 1))
                seg += 1
            lane_next = [0] * self.S
            for k in actor_next:
                actor_next[k] = 0
            step_fill = {}
            first_open = 0
            seg_height = 0

        def place(i: int, lane: int, lane_act: int, aidx: int,
                  m: OrderMsg, actor_key: Optional[int]) -> None:
            nonlocal seg_height, first_open
            step = lane_next[lane]
            if actor_key is not None:
                step = max(step, actor_next.get(actor_key, 0))
            slot = 0
            if self.width > 0:
                # step_fill counts only grow, so all steps below
                # first_open stay full — start the scan there
                step = max(step, first_open)
                while step_fill.get(step, 0) >= self.width:
                    step += 1
                slot = step_fill.get(step, 0)
                step_fill[step] = slot + 1
                while step_fill.get(first_open, 0) >= self.width:
                    first_open += 1
            r = rows
            r["msg_index"].append(i)
            r["segment"].append(seg)
            r["step"].append(step)
            r["lane"].append(lane)
            r["act"].append(lane_act)
            r["aidx"].append(aidx)
            r["oid"].append(jl.jlong(m.oid))
            r["price"].append(m.price)
            r["size"].append(m.size)
            r["slot"].append(slot)
            lane_next[lane] = step + 1
            if actor_key is not None:
                actor_next[actor_key] = step + 1
            seg_height = max(seg_height, step + 1)

        def free_lane(step_floor: int) -> int:
            # prefer a lane whose clock is <= the actor clock (no stall)
            for probe in range(self.S):
                lane = (self._rr_lane + probe) % self.S
                if lane_next[lane] <= step_floor:
                    self._rr_lane = (lane + 1) % self.S
                    return lane
            lane = min(range(self.S), key=lane_next.__getitem__)
            self._rr_lane = (lane + 1) % self.S
            return lane

        for i, m in enumerate(msgs):
            a = m.action
            if not (-2**31 <= m.price < 2**31 and -2**31 <= m.size < 2**31):
                raise EnvelopeError(
                    f"message {i}: price/size outside int32 "
                    f"(price={m.price}, size={m.size})")
            # the id spaces are Java longs (the Jackson envelope,
            # KProcessor.java:451-455): wrap ONCE here so the Python and
            # native schedulers key their maps identically
            aid, sid, oid = jl.jlong(m.aid), jl.jlong(m.sid), jl.jlong(m.oid)
            if a in _TRADE_ACTS:
                lane = self._lane(sid)
                aidx = self._acct(aid)
                self.oid_sid[oid] = sid
                place(i, lane, _TRADE_ACTS[a], aidx, m, actor_key=aid)
            elif a == op.CANCEL:
                # route stays mapped even after a cancel attempt: a cancel
                # can fail (wrong owner) and be retried, and a second
                # cancel of a gone order correctly rejects on device
                rsid = self.oid_sid.get(oid)
                if rsid is None:
                    host_rejects.append(HostReject(i))
                    continue
                lane = self._lane(rsid)
                aidx = self._acct(aid)
                place(i, lane, L.L_CANCEL, aidx, m, actor_key=aid)
            elif a == op.CREATE_BALANCE:
                aidx = self._acct(aid)
                step_floor = actor_next.get(aid, 0)
                lane = free_lane(step_floor)
                place(i, lane, L.L_CREATE, aidx, m, actor_key=aid)
            elif a == op.TRANSFER:
                aidx = self._acct(aid)
                step_floor = actor_next.get(aid, 0)
                lane = free_lane(step_floor)
                place(i, lane, L.L_TRANSFER, aidx, m, actor_key=aid)
            elif a == op.ADD_SYMBOL:
                if sid < 0:
                    host_rejects.append(HostReject(i))
                    continue
                lane = self._lane(sid)
                place(i, lane, L.L_ADD_SYMBOL, 0, m, actor_key=None)
            elif a in (op.REMOVE_SYMBOL, op.PAYOUT):
                # abs(INT64_MIN) = 2^63 can never be a (wrapped) map key,
                # so a payout/remove of that sid host-rejects
                s = abs(sid)
                if s not in self.sid_lane:
                    host_rejects.append(HostReject(i))
                    continue
                lane = self.sid_lane[s]
                close_segment()
                if a == op.REMOVE_SYMBOL:
                    mode = 0
                else:
                    mode = 1 if sid >= 0 else 2
                barriers.append(Barrier(i, lane, mode, m.size))
                program.append(("barrier", len(barriers) - 1))
                # a wiped lane may be re-added later; resting-oid routes
                # die with the wipe
                dead = [o for o, s2 in self.oid_sid.items() if s2 == s]
                for o in dead:
                    del self.oid_sid[o]
            else:
                host_rejects.append(HostReject(i))  # unknown opcode
        close_segment()
        cols = {name: np.array(vals, dtype=dt)
                for (name, dt), vals in zip(_COL_DTYPES, rows.values())}
        return Schedule(cols, barriers, host_rejects, segment_steps,
                        program)
