"""Java-mode seq checkpoints: canonical snapshot form + cross-engine
conversion (seq-java device state <-> the native C++ engine's stores).

The java-mode device state (engine/seq.py compat='java') is a
128-bit-key tombstoned position hash (real (aid, sid) keys AND Q11
garbage (amount, available) keys — both parity-relevant), direction-
tagged merged books (Q1), and raw-id lookup tables. The canonical
snapshot stores the SEMANTIC content, not the physical layout:

- positions: flat (ka, kb) -> (amt, avail) arrays, garbage keys
  included, sorted by key (hash slot placement and tombstones are
  probe-path artifacts with no observable semantics — the reference's
  store is a plain map — so re-import inserts fresh);
- resting orders: (oid, aidx, is_buy, price, size, seq, lane) in
  (lane, side, slot) order. Slot POSITIONS are not semantic (the kernel
  orders by (price, seq)); within-bucket seq order is;
- balances / book-exists / seq counters / router id maps.

Cross-engine: `to_native_dump` emits the native engine's checkpoint
text (kme_oracle.cpp dump_state grammar: B/P/K/U/O lines) with bucket
chains rebuilt from (price, seq) order; `from_native_dump` parses one
back. `prev` pointers are NORMALIZED (head: none; body: predecessor
oid): the stored prev leaks onto the wire only at REST time (Q9), never
from a restored resting order, so continuation streams are byte-
identical either way (pinned by tests/test_checkpoint.py).

Reference: the changelog-restore contract, KProcessor.java:30-49.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from kme_tpu.oracle import javalong as jl

OP_BUY, OP_SELL = 2, 3   # wire opcodes (KProcessor.java:65-75)


def _wrap32(x: int) -> int:
    return ((int(x) + (1 << 31)) & 0xFFFFFFFF) - (1 << 31)


def _lo32(v: int) -> int:
    return _wrap32(int(v) & 0xFFFFFFFF)


def _hi32(v: int) -> int:
    return _wrap32((int(v) >> 32) & 0xFFFFFFFF)


def _jhome(ka: int, kb: int, tilemask: int) -> int:
    """Host mirror of the kernel's 128-bit-key Fibonacci tile hash
    (engine/seq.py jhome), int32 wrap arithmetic."""
    h = (_wrap32(_lo32(ka) * -1640531527)
         ^ _wrap32(_hi32(ka) * -2048144789)
         ^ _wrap32(_lo32(kb) * -1028477387)
         ^ _wrap32(_hi32(kb) * 69069))
    return (_wrap32(h) >> 7) & tilemask


# ---------------------------------------------------------------------------
# canonical form <-> SeqSession (device)

def export_seqjava(session) -> dict:
    """SeqSession(compat='java') -> canonical snapshot dict (numpy
    arrays + plain dicts; see module docstring)."""
    from kme_tpu.engine import seq as SQ

    cfg = session.cfg
    assert cfg.compat == "java"
    j = SQ.export_java(cfg, session.state)
    h = {k: np.asarray(session.state[k])
         for k in ("bq", "seqc")}
    S, N, NR = cfg.lanes, cfg.slots, cfg.nr
    slot_seq = (h["bq"].reshape(S, 2, NR * 128)[:, :, :N]).astype(np.int32)
    keys = sorted(j["positions"])
    rest = []
    AM = (1 << 30) - 1
    for lane in range(S):
        for side in range(2):
            for nn in range(N):
                if j["slot_size"][lane, side, nn] > 0:
                    ba = int(j["slot_ba"][lane, side, nn])
                    rest.append((
                        int(j["slot_oid"][lane, side, nn]), ba & AM,
                        (ba >> 30) & 1,
                        int(j["slot_price"][lane, side, nn]),
                        int(j["slot_size"][lane, side, nn]),
                        int(slot_seq[lane, side, nn]), lane))
    r = session.router
    return {
        "pos_ka": np.array([k[0] for k in keys], np.int64),
        "pos_kb": np.array([k[1] for k in keys], np.int64),
        "pos_amt": np.array([j["positions"][k][0] for k in keys],
                            np.int64),
        "pos_av": np.array([j["positions"][k][1] for k in keys],
                           np.int64),
        "rest": np.array(rest, np.int64).reshape(-1, 7),
        "seqc": h["seqc"].reshape(-1)[:S].astype(np.int32),
        "book_exists": j["book_exists"].astype(np.int32),
        "bal": np.asarray(j["bal"], np.int64),
        "bal_used": j["bal_used"].astype(np.int32),
        "err": np.int32(j["err"]),
        "aid_idx": dict(r.aid_idx),
        "sid_lane": dict(r.sid_lane),
        "oid_sid": dict(r.oid_sid),
    }


def import_seqjava(cfg, snap) -> "SeqSession":
    """Canonical java snapshot -> a live SeqSession(compat='java').
    The position hash is re-inserted fresh (no tombstones) with the
    kernel's probe bound enforced; slot planes pack from slot 0."""
    import jax.numpy as jnp

    from kme_tpu.engine import seq as SQ
    from kme_tpu.runtime.seqsession import SeqSession

    assert cfg.compat == "java"
    S, N, A, NR = cfg.lanes, cfg.slots, cfg.accounts, cfg.nr
    LN = 128
    rest = np.asarray(snap["rest"]).reshape(-1, 7)
    sid_lane = {int(k): int(v) for k, v in snap["sid_lane"].items()}
    aid_idx = {int(k): int(v) for k, v in snap["aid_idx"].items()}
    lane_sid = {v: k for k, v in sid_lane.items()}
    if len(aid_idx) > A:
        raise ValueError(f"snapshot has {len(aid_idx)} accounts; "
                         f"cfg.accounts={A} cannot hold them")
    if sid_lane and max(sid_lane.values()) >= S:
        raise ValueError(f"snapshot lanes exceed cfg.lanes={S}")

    slot = {f: np.zeros((S, 2, NR * LN), np.int64)
            for f in ("oid", "ba", "price", "size", "seq")}
    fill_ptr = np.zeros((S, 2), np.int64)
    for oid, aidx, isbuy, price, size, seq, lane in rest.tolist():
        if int(lane) not in lane_sid:
            raise ValueError(
                f"snapshot rest entry references lane {lane} absent "
                f"from sid_lane — inconsistent snapshot")
        sid = lane_sid[int(lane)]
        side = 0 if sid == 0 else (0 if isbuy else 1)
        p = int(fill_ptr[lane, side])
        if p >= N:
            raise ValueError(
                f"lane {lane} side {side} holds {p + 1}+ resting "
                f"orders; cfg.slots={N} cannot hold them")
        fill_ptr[lane, side] = p + 1
        slot["oid"][lane, side, p] = oid
        slot["ba"][lane, side, p] = aidx | (isbuy << 30)
        slot["price"][lane, side, p] = price
        slot["size"][lane, side, p] = size
        slot["seq"][lane, side, p] = seq

    def planes(v, split=False):
        flat = v.reshape(2 * S * NR, LN)
        if split:
            lo = (flat & 0xFFFFFFFF).astype(np.uint32).astype(np.int32)
            return lo, (flat >> 32).astype(np.int32)
        return flat.astype(np.int32)

    def padplane(v, rows):
        a = np.zeros(rows * LN, np.int32)
        a[:len(v)] = v
        return a.reshape(rows, LN)

    # position hash: fresh insertion, kernel-identical home tile and
    # probe bound (entries past the bound would be device-invisible)
    capr = cfg.caprows
    tilemask = capr - 1
    probe_lim = min(cfg.probe_max, capr)
    ka = np.asarray(snap["pos_ka"], np.int64)
    kb = np.asarray(snap["pos_kb"], np.int64)
    if len(ka) > cfg.pos_cap // 2:
        raise ValueError(f"{len(ka)} positions exceed half the hash "
                         f"capacity {cfg.pos_cap} — raise pos_cap")
    hp = {f: np.zeros(cfg.pos_cap, np.int32)
          for f in ("ka_lo", "ka_hi", "kb_lo", "kb_hi", "state",
                    "a_lo", "a_hi", "v_lo", "v_hi")}
    amt = np.asarray(snap["pos_amt"], np.int64)
    av = np.asarray(snap["pos_av"], np.int64)
    for i in range(len(ka)):
        t = _jhome(int(ka[i]), int(kb[i]), tilemask)
        placed = False
        for p in range(probe_lim):
            base = ((t + p) & tilemask) * LN
            row = hp["state"][base:base + LN]
            empt = np.nonzero(row == 0)[0]
            if len(empt):
                s = base + empt[0]
                hp["state"][s] = 1
                hp["ka_lo"][s] = _lo32(ka[i])
                hp["ka_hi"][s] = _hi32(ka[i])
                hp["kb_lo"][s] = _lo32(kb[i])
                hp["kb_hi"][s] = _hi32(kb[i])
                hp["a_lo"][s] = _lo32(amt[i])
                hp["a_hi"][s] = _hi32(amt[i])
                hp["v_lo"][s] = _lo32(av[i])
                hp["v_hi"][s] = _hi32(av[i])
                placed = True
                break
        if not placed:
            raise ValueError(
                "position hash import overflow: entry unreachable "
                "within probe_max tiles — raise pos_cap or probe_max")

    araw_lo = np.zeros(cfg.arows * LN, np.int32)
    araw_hi = np.zeros(cfg.arows * LN, np.int32)
    for raw, idx in aid_idx.items():
        araw_lo[idx] = _lo32(raw)
        araw_hi[idx] = _hi32(raw)
    sraw_lo = np.zeros(cfg.srows * LN, np.int32)
    sraw_hi = np.zeros(cfg.srows * LN, np.int32)
    for raw, lane in sid_lane.items():
        sraw_lo[lane] = _lo32(raw)
        sraw_hi[lane] = _hi32(raw)

    bal = np.zeros(A, np.int64)
    bal[:len(snap["bal"])] = np.asarray(snap["bal"], np.int64)
    bal_u = np.zeros(A, np.int32)
    bal_u[:len(snap["bal_used"])] = np.asarray(snap["bal_used"],
                                               np.int32)
    bex = np.zeros(S, np.int32)
    bex[:len(snap["book_exists"])] = np.asarray(snap["book_exists"],
                                                np.int32)
    seqc = np.zeros(S, np.int32)
    seqc[:len(snap["seqc"])] = np.asarray(snap["seqc"], np.int32)

    lo, hi = planes(slot["oid"], split=True)
    state = {
        "bo_lo": jnp.asarray(lo), "bo_hi": jnp.asarray(hi),
        "ba": jnp.asarray(planes(slot["ba"])),
        "bp": jnp.asarray(planes(slot["price"])),
        "bs": jnp.asarray(planes(slot["size"])),
        "bq": jnp.asarray(planes(slot["seq"])),
        "seqc": jnp.asarray(padplane(seqc, cfg.srows)),
        "bex": jnp.asarray(padplane(bex, cfg.srows)),
        "bal_lo": jnp.asarray(padplane(
            (bal & 0xFFFFFFFF).astype(np.uint32).astype(np.int32),
            cfg.arows)),
        "bal_hi": jnp.asarray(padplane((bal >> 32).astype(np.int32),
                                       cfg.arows)),
        "bal_u": jnp.asarray(padplane(bal_u, cfg.arows)),
        "hka_lo": jnp.asarray(hp["ka_lo"].reshape(capr, LN)),
        "hka_hi": jnp.asarray(hp["ka_hi"].reshape(capr, LN)),
        "hkb_lo": jnp.asarray(hp["kb_lo"].reshape(capr, LN)),
        "hkb_hi": jnp.asarray(hp["kb_hi"].reshape(capr, LN)),
        "hstate": jnp.asarray(hp["state"].reshape(capr, LN)),
        "ha_lo": jnp.asarray(hp["a_lo"].reshape(capr, LN)),
        "ha_hi": jnp.asarray(hp["a_hi"].reshape(capr, LN)),
        "hv_lo": jnp.asarray(hp["v_lo"].reshape(capr, LN)),
        "hv_hi": jnp.asarray(hp["v_hi"].reshape(capr, LN)),
        "araw_lo": jnp.asarray(araw_lo.reshape(cfg.arows, LN)),
        "araw_hi": jnp.asarray(araw_hi.reshape(cfg.arows, LN)),
        "sraw_lo": jnp.asarray(sraw_lo.reshape(cfg.srows, LN)),
        "sraw_hi": jnp.asarray(sraw_hi.reshape(cfg.srows, LN)),
        "err": jnp.asarray(padplane(
            np.array([int(snap.get("err", 0))], np.int32), 1)),
    }
    ses = SeqSession(cfg)
    ses.state = state
    r = ses.router
    r.aid_idx = aid_idx
    r.sid_lane = sid_lane
    r.oid_sid = {int(k): int(v) for k, v in snap["oid_sid"].items()}
    return ses


# ---------------------------------------------------------------------------
# canonical form <-> the native engine's dump grammar

def _book_key(sid: int, is_buy: bool) -> int:
    return jl.jmul(sid, 1 if is_buy else -1)


def _bucket_key(book_key: int, price: int) -> int:
    return jl.jor(jl.jshl(book_key, 8), jl.jlong(price))


def to_native_dump(snap) -> str:
    """Canonical java snapshot -> the native engine's checkpoint text
    (kme_oracle.cpp dump_state grammar). Bucket chains rebuild from
    (price, seq); prev pointers normalize (see module docstring);
    position seq numbers synthesize in key order (iteration order is
    not observable — credits commute)."""
    lines: List[str] = []
    idx_aid = {v: k for k, v in snap["aid_idx"].items()}
    lane_sid = {v: k for k, v in snap["sid_lane"].items()}
    bal = np.asarray(snap["bal"], np.int64)
    for raw, idx in sorted(snap["aid_idx"].items(), key=lambda kv: kv[1]):
        if snap["bal_used"][idx]:
            lines.append(f"B {raw} {int(bal[idx])}")
    for i in range(len(snap["pos_ka"])):
        lines.append(f"P {int(snap['pos_ka'][i])} {int(snap['pos_kb'][i])} "
                     f"{int(snap['pos_amt'][i])} {int(snap['pos_av'][i])} "
                     f"{i + 1}")
    # books: every existing book gets its key pair (sid 0 merges, Q1).
    # Bitmap halves split at bit 63 — `price < 63 -> lsb bit price,
    # else msb bit price-63` (the reference's Q7/Q8 codec,
    # kme_oracle.cpp with_bit_set / ops/bits.py)
    books: Dict[int, List[int]] = {}   # key -> [msb, lsb]
    for lane in range(len(snap["book_exists"])):
        if snap["book_exists"][lane] and lane in lane_sid:
            sid = lane_sid[lane]
            books.setdefault(_book_key(sid, True), [0, 0])
            books.setdefault(_book_key(sid, False), [0, 0])
    buckets: Dict[int, List[Tuple]] = {}
    rest = np.asarray(snap["rest"]).reshape(-1, 7)
    for oid, aidx, isbuy, price, size, seq, lane in rest.tolist():
        sid = lane_sid[int(lane)]
        bk = _book_key(sid, bool(isbuy))
        bm = books.setdefault(bk, [0, 0])
        if price < 63:
            bm[1] |= 1 << int(price)
        else:
            bm[0] |= 1 << (int(price) - 63)
        buckets.setdefault(_bucket_key(bk, int(price)), []).append(
            (int(seq), int(oid), int(idx_aid[int(aidx)]), sid,
             int(price), int(size), bool(isbuy)))
    for bk, (msb, lsb) in sorted(books.items()):
        lines.append(f"K {bk} {jl.jlong(msb)} {jl.jlong(lsb)}")
    order_lines = []
    for bkt, entries in sorted(buckets.items()):
        entries.sort()
        lines.append(f"U {bkt} {entries[0][1]} {entries[-1][1]}")
        for i, (seq, oid, aid, sid, price, size, isbuy) in \
                enumerate(entries):
            nxt = entries[i + 1][1] if i + 1 < len(entries) else 0
            nh = 1 if i + 1 < len(entries) else 0
            prv = entries[i - 1][1] if i > 0 else 0
            ph = 1 if i > 0 else 0
            act = OP_BUY if isbuy else OP_SELL
            order_lines.append(
                f"O {oid} {act} {aid} {sid} {price} {size} "
                f"{nh} {nxt} {ph} {prv}")
    lines += order_lines
    return "\n".join(lines) + ("\n" if lines else "")


def from_native_dump(text: str) -> dict:
    """Native checkpoint text -> canonical java snapshot. Router maps
    rebuild deterministically (dense ids in key-sorted order — the id
    assignment is internal; any bijection yields the same wire). The
    device seq numbers renumber per lane in bucket-chain order, which
    preserves the only observable ordering (within-bucket FIFO)."""
    balances: Dict[int, int] = {}
    positions: List[Tuple[int, int, int, int]] = []
    books: Dict[int, Tuple[int, int]] = {}
    buckets: Dict[int, Tuple[int, int]] = {}
    orders: Dict[int, tuple] = {}
    for line in text.splitlines():
        if not line:
            continue
        f = line.split()
        if f[0] == "B":
            balances[int(f[1])] = int(f[2])
        elif f[0] == "P":
            positions.append((int(f[1]), int(f[2]), int(f[3]),
                              int(f[4])))
        elif f[0] == "K":
            books[int(f[1])] = (int(f[2]), int(f[3]))
        elif f[0] == "U":
            buckets[int(f[1])] = (int(f[2]), int(f[3]))
        elif f[0] == "O":
            orders[int(f[1])] = (int(f[2]), int(f[3]), int(f[4]),
                                 int(f[5]), int(f[6]), int(f[7]) != 0,
                                 int(f[8]))
        else:
            raise ValueError(f"unknown dump line {line!r}")
    # id maps: dense ids in sorted-key order (deterministic)
    sids = sorted({abs(k) for k in books}
                  | {o[2] for o in orders.values()})
    sid_lane = {s: i for i, s in enumerate(sids)}
    aids = sorted(balances)
    aid_idx = {a: i for i, a in enumerate(aids)}
    positions.sort()
    rest = []
    seqc = {}
    for bkt, (first, last) in sorted(buckets.items()):
        ptr, guard = first, 0
        while True:
            act, aid, sid, price, size, nh, nxt = orders[ptr]
            if sid < 0:
                raise ValueError(
                    f"resting order with negative sid {sid} — the ±sid "
                    f"book coupling is outside the seq device surface; "
                    f"this state must stay on the native engine "
                    f"(COMPAT.md)")
            if not (0 <= price < 126):
                raise ValueError(
                    f"resting price {price} outside the seq device "
                    f"domain [0,126) — this stream needs the native "
                    f"engine (COMPAT.md)")
            lane = sid_lane[abs(sid)]
            seq = seqc.get(lane, 0)
            seqc[lane] = seq + 1
            if aid not in aid_idx:
                aid_idx[aid] = len(aid_idx)
            rest.append((ptr, aid_idx[aid], 1 if act == OP_BUY else 0,
                         price, size, seq, lane))
            guard += 1
            if guard > len(orders):
                raise ValueError("cyclic bucket chain in dump")
            if not nh or ptr == last:
                break
            ptr = nxt
    S = max(sid_lane.values()) + 1 if sid_lane else 0
    book_exists = np.zeros(max(S, 1), np.int32)
    for k in books:
        s = abs(k)
        if s in sid_lane:
            book_exists[sid_lane[s]] = 1
    A = len(aid_idx)
    bal = np.zeros(max(A, 1), np.int64)
    bal_used = np.zeros(max(A, 1), np.int32)
    for a, v in balances.items():
        bal[aid_idx[a]] = v
        bal_used[aid_idx[a]] = 1
    seqc_arr = np.zeros(max(S, 1), np.int32)
    for lane, c in seqc.items():
        seqc_arr[lane] = c
    lane_sid = {v: k for k, v in sid_lane.items()}
    return {
        "pos_ka": np.array([p[0] for p in positions], np.int64),
        "pos_kb": np.array([p[1] for p in positions], np.int64),
        "pos_amt": np.array([p[2] for p in positions], np.int64),
        "pos_av": np.array([p[3] for p in positions], np.int64),
        "rest": np.array(rest, np.int64).reshape(-1, 7),
        "seqc": seqc_arr,
        "book_exists": book_exists,
        "bal": bal,
        "bal_used": bal_used,
        "err": np.int32(0),
        "aid_idx": aid_idx,
        "sid_lane": sid_lane,
        # resting oids route to their symbol; non-resting oids need no
        # route (a device REJECT and a host REJECT are the same bytes)
        "oid_sid": {int(r[0]): int(lane_sid[int(r[6])]) for r in rest},
    }
