"""SeqSession: host half of the sequential mega-kernel engine.

Unlike LaneSession, there is NO conflict-free scheduler: the kernel
processes messages strictly sequentially (engine/seq.py), so planning
reduces to ID ROUTING — dense aid/sid maps, oid -> lane routing for
cancels, and host-resolved rejects for messages the device cannot act
on (unknown-oid cancels, negative-sid ADD_SYMBOL, unmapped
payout/remove) — the same edge semantics as runtime/sequencer.py.
Barriers (PAYOUT / REMOVE_SYMBOL) are ordinary device messages here
(act codes 7/8/9), not separate settle calls.

I/O design (the tunnel lesson, round 4): ONE packed (rows, 128) i32
output plane per kernel call, all calls dispatched before any fetch,
fetches started concurrently — every np.asarray round trip after the
first costs a tunnel RTT (~100ms+ through the driver's tunnel).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

import kme_tpu._jaxsetup  # noqa: F401

from kme_tpu import opcodes as op
from kme_tpu.engine import seq as SQ
from kme_tpu.runtime import session as _session
from kme_tpu.runtime.session import LaneEngineError
from kme_tpu.runtime.sequencer import CapacityError, EnvelopeError
from kme_tpu.telemetry import PhaseTimer, Registry
from kme_tpu.wire import (OrderMsg, OutRecord, WireBatch, order_json,
                          reject_reason_codes)

# register the seq-specific sticky-error name so LaneEngineError renders
# it (the code space is shared with the lanes engine's LERR_*)
_session._LERR_NAMES[SQ.LERR_HASH_FULL] = \
    "position hash exhausted (pos_cap knob)"
_session._LERR_NAMES[SQ.LERR_JAVA_DOMAIN] = \
    "java mode: price/size outside the device domain (the reference " \
    "runs unvalidated fields; this stream needs the native engine)"
_session._LERR_NAMES[SQ.LERR_JAVA_CAP] = \
    "java mode: device capacity exceeded (reference stores are " \
    "unbounded -- raise slots/max_fills or use the native engine)"

_TRADE_ACTS = {op.BUY: SQ.L_BUY, op.SELL: SQ.L_SELL}


class UnsupportedJavaOp(RuntimeError):
    """The java-compat DEVICE surface excludes barriers and negative-sid
    symbols (dead or broken reference paths — Q3-Q6 and the ±sid book
    cross-coupling); streams containing them belong on the native/oracle
    engines (COMPAT.md)."""


class SeqRouter:
    """Arrival-order ID routing (no conflict analysis). Mirrors the
    sequencer's id spaces and host-reject edge semantics. compat='java'
    additionally emits the raw Java-long aid/sid columns and the Q1
    merged-book flag the kernel needs, and REFUSES the opcodes outside
    the java device surface."""

    def __init__(self, num_lanes: int, num_accounts: int,
                 compat: str = "fixed") -> None:
        self.S = num_lanes
        self.A = num_accounts
        self.compat = compat
        self.aid_idx: Dict[int, int] = {}
        self.sid_lane: Dict[int, int] = {}
        self.oid_sid: Dict[int, int] = {}

    def _acct(self, aid: int) -> int:
        idx = self.aid_idx.get(aid)
        if idx is None:
            if len(self.aid_idx) >= self.A:
                raise CapacityError(
                    f"account capacity {self.A} exhausted (aid={aid})")
            idx = len(self.aid_idx)
            self.aid_idx[aid] = idx
        return idx

    def _lane(self, sid: int) -> int:
        lane = self.sid_lane.get(sid)
        if lane is None:
            if len(self.sid_lane) >= self.S:
                raise CapacityError(
                    f"symbol capacity {self.S} exhausted (sid={sid})")
            lane = len(self.sid_lane)
            self.sid_lane[sid] = lane
        return lane

    def acct_of_idx(self) -> List[int]:
        out = [0] * len(self.aid_idx)
        for aid, idx in self.aid_idx.items():
            out[idx] = aid
        return out

    def sid_of_lane(self) -> Dict[int, int]:
        return {lane: sid for sid, lane in self.sid_lane.items()}

    def route(self, msgs):
        """-> (cols dict incl. msg_index, host_reject msg indices)."""
        from kme_tpu.oracle import javalong as jl

        if isinstance(msgs, WireBatch):
            msgs = msgs.msgs()
        java = self.compat == "java"
        cols = {k: [] for k in ("msg_index", "act", "aid", "price",
                                "size", "lane", "oid", "aid_raw",
                                "sid_raw", "flags")}
        host_rejects = set()

        def emit(i, act, aidx, lane, m, oid, aid=0, sid=0):
            cols["msg_index"].append(i)
            cols["act"].append(act)
            cols["aid"].append(aidx)
            cols["price"].append(m.price)
            cols["size"].append(m.size)
            cols["lane"].append(lane)
            cols["oid"].append(oid)
            if java:
                cols["aid_raw"].append(aid)
                cols["sid_raw"].append(sid)
                cols["flags"].append(1 if sid == 0 else 0)

        # envelope-check the WHOLE batch up front so an EnvelopeError
        # leaves the id maps untouched (the native router's contract;
        # native/sched.py documents the same for the scheduler)
        for i, m in enumerate(msgs):
            if not (-2**31 <= m.price < 2**31 and -2**31 <= m.size < 2**31):
                raise EnvelopeError(
                    f"message {i}: price/size outside int32 "
                    f"(price={m.price}, size={m.size})")
        for i, m in enumerate(msgs):
            a = m.action
            aid, sid, oid = jl.jlong(m.aid), jl.jlong(m.sid), jl.jlong(m.oid)
            if a in _TRADE_ACTS:
                if java and sid < 0:
                    raise UnsupportedJavaOp(
                        f"message {i}: negative-sid trade (sid={sid}) — "
                        f"java ±sid book coupling is outside the device "
                        f"surface; use the native engine")
                # mutation order (lane, oid_sid, acct) is the authority
                # contract: the native router replicates it exactly so
                # partial map state after a CapacityError is identical
                lane = self._lane(sid)
                self.oid_sid[oid] = sid
                emit(i, _TRADE_ACTS[a], self._acct(aid), lane, m, oid,
                     aid, sid)
            elif a == op.CANCEL:
                rsid = self.oid_sid.get(oid)
                if rsid is None:
                    host_rejects.add(i)
                    continue
                emit(i, SQ.L_CANCEL, self._acct(aid), self._lane(rsid),
                     m, oid, aid, rsid)
            elif a == op.CREATE_BALANCE:
                emit(i, SQ.L_CREATE, self._acct(aid), 0, m, oid, aid, 0)
            elif a == op.TRANSFER:
                emit(i, SQ.L_TRANSFER, self._acct(aid), 0, m, oid,
                     aid, 0)
            elif a == op.ADD_SYMBOL:
                if java and sid < 0:
                    raise UnsupportedJavaOp(
                        f"message {i}: negative-sid ADD_SYMBOL "
                        f"(sid={sid}) — outside the java device surface")
                if sid < 0:
                    host_rejects.add(i)
                    continue
                emit(i, SQ.L_ADD_SYMBOL, 0, self._lane(sid), m, oid,
                     aid, sid)
            elif a in (op.REMOVE_SYMBOL, op.PAYOUT):
                if java:
                    raise UnsupportedJavaOp(
                        f"message {i}: {'REMOVE_SYMBOL' if a == 1 else 'PAYOUT'} "
                        f"in java mode — Q3-Q6 barrier paths are outside "
                        f"the device surface; use the native engine")
                s = abs(sid)
                if s not in self.sid_lane:
                    host_rejects.add(i)
                    continue
                lane = self.sid_lane[s]
                if a == op.REMOVE_SYMBOL:
                    act = SQ.L_REMOVE_SYMBOL
                else:
                    act = SQ.L_PAYOUT_YES if sid >= 0 else SQ.L_PAYOUT_NO
                emit(i, act, 0, lane, m, oid)
                dead = [o for o, s2 in self.oid_sid.items() if s2 == s]
                for o in dead:
                    del self.oid_sid[o]
            else:
                host_rejects.add(i)
        out = {
            "msg_index": np.array(cols["msg_index"], np.int64),
            "act": np.array(cols["act"], np.int32),
            "aid": np.array(cols["aid"], np.int32),
            "price": np.array(cols["price"], np.int32),
            "size": np.array(cols["size"], np.int32),
            "lane": np.array(cols["lane"], np.int32),
            "oid": np.array(cols["oid"], np.int64),
        }
        if java:
            out["aid_raw"] = np.array(cols["aid_raw"], np.int64)
            out["sid_raw"] = np.array(cols["sid_raw"], np.int64)
            out["flags"] = np.array(cols["flags"], np.int32)
        return out, host_rejects


class NativeSeqRouter:
    """C++ twin of SeqRouter (native/kme_router.cpp): identical routing
    over columnar int64 arrays. The id maps live in C++; the dict
    properties export/import them for the checkpoint contract. A CALL
    whose fields overflow int64 routes through a temporary Python
    router (maps synced both ways); subsequent calls are native
    again."""

    def __init__(self, num_lanes: int, num_accounts: int, lib) -> None:
        import weakref

        self.S = num_lanes
        self.A = num_accounts
        self._lib = lib
        self._h = lib.kme_router_new(num_lanes, num_accounts)
        self._fin = weakref.finalize(self, lib.kme_router_free, self._h)
        # bumped on every wholesale map import (checkpoint restore):
        # SeqSession's recon-LUT cache keys on (map sizes, epoch), and
        # sizes alone can collide across an import
        self._map_epoch = 0

    # -- map views (checkpoint save/load reads+writes these) -----------
    def _export(self, nfn, efn, vdt):
        import ctypes

        n = nfn(self._h)
        keys = np.empty(n, np.int64)
        vals = np.empty(n, vdt)
        P64 = ctypes.POINTER(ctypes.c_int64)
        PV = ctypes.POINTER(
            ctypes.c_int32 if vdt == np.int32 else ctypes.c_int64)
        efn(self._h, keys.ctypes.data_as(P64), vals.ctypes.data_as(PV))
        return dict(zip(keys.tolist(), vals.tolist()))

    def _import(self, ifn, d, vdt):
        import ctypes

        self._map_epoch += 1
        keys = np.fromiter(d.keys(), np.int64, len(d))
        vals = np.fromiter(d.values(), vdt, len(d))
        P64 = ctypes.POINTER(ctypes.c_int64)
        PV = ctypes.POINTER(
            ctypes.c_int32 if vdt == np.int32 else ctypes.c_int64)
        ifn(self._h, len(d), keys.ctypes.data_as(P64),
            vals.ctypes.data_as(PV))

    @property
    def aid_idx(self):
        lib = self._lib
        return self._export(lib.kme_router_n_accounts,
                            lib.kme_router_export_accounts, np.int32)

    @aid_idx.setter
    def aid_idx(self, d):
        self._import(self._lib.kme_router_import_accounts, d, np.int32)

    @property
    def sid_lane(self):
        lib = self._lib
        return self._export(lib.kme_router_n_symbols,
                            lib.kme_router_export_symbols, np.int32)

    @sid_lane.setter
    def sid_lane(self, d):
        self._import(self._lib.kme_router_import_symbols, d, np.int32)

    @property
    def oid_sid(self):
        lib = self._lib
        return self._export(lib.kme_router_n_routes,
                            lib.kme_router_export_routes, np.int64)

    @oid_sid.setter
    def oid_sid(self, d):
        self._import(self._lib.kme_router_import_routes, d, np.int64)

    def acct_of_idx(self) -> List[int]:
        m = self.aid_idx
        out = [0] * len(m)
        for aid, idx in m.items():
            out[idx] = aid
        return out

    def sid_of_lane(self) -> Dict[int, int]:
        return {lane: sid for sid, lane in self.sid_lane.items()}

    def route(self, msgs):
        import ctypes

        n = len(msgs)
        try:
            if isinstance(msgs, WireBatch):
                # columnar fast path: zero per-message Python work
                raw = {f: np.ascontiguousarray(getattr(msgs, f))
                       for f in ("action", "oid", "aid", "sid",
                                 "price", "size")}
            else:
                raw = {
                    "action": np.fromiter((m.action for m in msgs),
                                          np.int64, n),
                    "oid": np.fromiter((m.oid for m in msgs),
                                       np.int64, n),
                    "aid": np.fromiter((m.aid for m in msgs),
                                       np.int64, n),
                    "sid": np.fromiter((m.sid for m in msgs),
                                       np.int64, n),
                    "price": np.fromiter((m.price for m in msgs),
                                         np.int64, n),
                    "size": np.fromiter((m.size for m in msgs),
                                        np.int64, n),
                }
        except OverflowError:
            # a field beyond int64: the columnar path cannot carry it
            py = SeqRouter(self.S, self.A)
            py.aid_idx = self.aid_idx
            py.sid_lane = self.sid_lane
            py.oid_sid = self.oid_sid
            cols, rejects = py.route(msgs)
            self.aid_idx = py.aid_idx
            self.sid_lane = py.sid_lane
            self.oid_sid = py.oid_sid
            return cols, rejects
        bad = ((raw["price"] < -(2**31)) | (raw["price"] >= 2**31)
               | (raw["size"] < -(2**31)) | (raw["size"] >= 2**31))
        if bad.any():
            i = int(np.argmax(bad))
            raise EnvelopeError(
                f"message {i}: price/size outside int32 "
                f"(price={int(raw['price'][i])}, "
                f"size={int(raw['size'][i])})")
        lib = self._lib
        P64 = ctypes.POINTER(ctypes.c_int64)
        rc = lib.kme_router_route(
            self._h, n, *(raw[f].ctypes.data_as(P64)
                          for f in ("action", "oid", "aid", "sid",
                                    "price", "size")))
        if rc != 0:
            raise CapacityError(
                f"{'account' if rc == 1 else 'symbol'} capacity "
                f"exhausted (id={lib.kme_router_err_value(self._h)})")
        nr = lib.kme_router_n_routed(self._h)
        nj = lib.kme_router_n_rejects(self._h)

        from kme_tpu.native.sched import _arr

        arr = lambda fn, dt, cnt: _arr(fn(self._h), cnt, dt)

        cols = {
            "msg_index": arr(lib.kme_router_o_msg, np.int64, nr),
            "act": arr(lib.kme_router_o_act, np.int32, nr),
            "aid": arr(lib.kme_router_o_aidx, np.int32, nr),
            "price": arr(lib.kme_router_o_price, np.int32, nr),
            "size": arr(lib.kme_router_o_size, np.int32, nr),
            "lane": arr(lib.kme_router_o_lane, np.int32, nr),
            "oid": arr(lib.kme_router_o_oid, np.int64, nr),
        }
        rejects = set(arr(lib.kme_router_o_rej, np.int64, nj).tolist())
        return cols, rejects


def make_seq_router(num_lanes: int, num_accounts: int,
                    compat: str = "fixed"):
    """The native router when the toolchain/library is available
    (KME_NATIVE=0 disables), else the Python implementation — identical
    routing either way (tests/test_seq_engine.py). java mode always
    uses the Python router (it carries the raw-id/flag columns)."""
    if compat == "java":
        return SeqRouter(num_lanes, num_accounts, compat="java")
    try:
        from kme_tpu.native import load_library

        lib = load_library()
        if lib is not None:
            return NativeSeqRouter(num_lanes, num_accounts, lib)
    except Exception as e:  # pragma: no cover - defensive fallback
        import sys

        print(f"kme_tpu: native seq router unavailable ({e}); "
              f"using the Python fallback", file=sys.stderr)
    return SeqRouter(num_lanes, num_accounts)


class SeqSession:
    """Drop-in fixed-mode engine over the sequential mega-kernel.

    Same public surface as LaneSession (process / process_wire /
    metrics / export_state); single-device (the sharded path stays on
    the lanes engine)."""

    def __init__(self, cfg: SQ.SeqConfig) -> None:
        self.cfg = cfg
        self.state = SQ.make_seq_state(cfg)
        self.router = make_seq_router(cfg.lanes, cfg.accounts,
                                      compat=cfg.compat)
        self._metrics = np.zeros(SQ.N_METRICS, np.int64)
        self._hist = np.zeros((SQ.N_HIST, SQ.N_HIST_BUCKETS), np.int64)
        self._recon = None          # native reconstructor handle
        self.telemetry = Registry()
        self.timer = PhaseTimer(track="seq")
        # CUMULATIVE wall time per phase across every batch (the timer's
        # totals dict IS this attribute; snapshot/reset via self.timer)
        self.phases = self.timer.totals
        self._use_native_wire = True
        # adaptive fill-slice hint (fill groups per call fetched in the
        # single-round fetch; grows to the observed high-water mark)
        self._ghint = 8
        # per-message REJ_* reason codes for the last processed batch
        # (np.uint8 (nmsg,), wire.REJ_NAMES) — the flight recorder and
        # the REJ annotation records read this after each batch
        self.last_reasons = None
        # ("submit"|"collect", pipeline-batch-idx, t0, t1) wall windows
        # from the pipelined path, for measured-overlap reporting
        self.windows: List[tuple] = []
        self._n_submit = 0
        self._n_collect = 0
        # H2D overlap accounting: staging time spent while a previous
        # submit was still in flight (device busy) counts as overlapped
        self._h2d_total_s = 0.0
        self._h2d_overlap_s = 0.0

    # ------------------------------------------------------------------

    def _plan(self, msgs):
        """Route + pack: columnar router output -> the stacked (K, B)
        i32 input planes of one scan dispatch. Returns
        (cols, host_rejects, stacked, cnts, K). Fixed-mode WireBatches
        take the single-call native path (kme_plan_batch) when the
        library is built; the numpy pack below is the byte-exact
        fallback (and the only path for java mode, whose extra
        aidr/sidr/flags planes ride the Python router)."""
        from kme_tpu.utils import pow2_bucket

        if (isinstance(msgs, WireBatch)
                and isinstance(self.router, NativeSeqRouter)):
            from kme_tpu.native.sched import plan_batch

            r = plan_batch(self.router, msgs, self.cfg.batch)
            if r is not None:
                return r
        cols, host_rejects = self.router.route(msgs)
        n = len(cols["act"])
        B = self.cfg.batch
        nk = max(-(-n // B), 1)
        K = pow2_bucket(nk, lo=1)
        total = K * B

        # vectorized pack over ALL chunks at once (pack_msgs per chunk
        # was a measurable slice of the plan phase at 100k+ messages);
        # zero padding is L_NOP by construction
        def pad32(src):
            a = np.zeros(total, np.int32)
            a[:n] = src[:n]
            return a.reshape(K, B)

        def split64(name, src):
            v = np.zeros(total, np.int64)
            v[:n] = src[:n]
            return {f"{name}_lo": (v & 0xFFFFFFFF).astype(np.uint32)
                    .astype(np.int32).reshape(K, B),
                    f"{name}_hi": (v >> 32).astype(np.int32).reshape(K, B)}

        stacked = {f: pad32(cols[f])
                   for f in ("act", "aid", "price", "size", "lane")}
        stacked.update(split64("oid", cols["oid"]))
        if self.cfg.compat == "java":
            stacked.update(split64("aidr", cols["aid_raw"]))
            stacked.update(split64("sidr", cols["sid_raw"]))
            stacked["flags"] = pad32(cols["flags"])
        cnts = [max(min(B, n - ci * B), 0) for ci in range(K)]
        return cols, host_rejects, stacked, cnts, K

    def _run(self, msgs):
        """Plan (route + pack) + dispatch (ONE lax.scan jit call over
        all chunks), then fetch in one concurrent round (headers +
        adaptive fill prefix; rare overflow slices in a second round).
        Phase wall times ACCUMULATE in self.phases (the bench and the
        service read them; reset via self.timer.reset()).
        Returns (cols, host_rejects, host dict, fills (4, F))."""
        with self.timer.phase("plan_s"):
            cols, host_rejects, stacked, cnts, K = self._plan(msgs)
        with self.timer.phase("dispatch_s"):
            self.state, outp = SQ.build_seq_scan(self.cfg, K)(
                self.state, stacked)
            import jax as _jax
            _jax.block_until_ready(self.state)
        with self.timer.phase("fetch_s"):
            host, fills = self._fetch_outputs(outp, cnts, K)
        return cols, host_rejects, host, fills

    def _fetch_outputs(self, outp, cnts, K):
        """Fetch + unpack one dispatch's output planes: ONE fetch round
        in the common case (headers + the adaptive fill-group hint's
        worth of fill rows per call; calls whose fill_total overflows
        the hint get a rare second-round slice)."""
        from kme_tpu.utils import async_prefetch, pow2_bucket

        HR = SQ.hdr_rows(self.cfg)
        ghint = min(pow2_bucket(self._ghint, lo=1),
                    self.cfg.fill_cap // 128)
        fdev = outp[:, :HR + 5 * ghint, :]
        async_prefetch([fdev])
        fetched = np.asarray(fdev)
        host = {k: [] for k in ("ok", "cap_reject", "append", "residual",
                                "nfill", "prev_oid")}
        results = []
        mets = np.zeros(SQ.N_METRICS, np.int64)
        hists = np.zeros((SQ.N_HIST, SQ.N_HIST_BUCKETS), np.int64)
        for ci in range(K):
            res = SQ.unpack_hdr(self.cfg, fetched[ci][:HR], cnts[ci])
            if res["err"] != SQ.LERR_OK:
                raise LaneEngineError(res["err"])
            results.append(res)
            mets += res["metrics"]
            hists += res["hist"]
        gneed = [-(-max(r["fill_total"], 1) // 128) for r in results]
        self._ghint = max(self._ghint, *gneed)
        over = [ci for ci in range(K) if gneed[ci] > ghint]
        extra = {}
        if over:
            slices = [outp[ci, HR:HR + 5 * pow2_bucket(gneed[ci], lo=1)]
                      for ci in over]
            async_prefetch(slices)
            extra = {ci: np.asarray(s) for ci, s in zip(over, slices)}
        fills = []
        for ci, res in enumerate(results):
            if ci in extra:
                groups = extra[ci][:5 * gneed[ci]]
            else:
                groups = fetched[ci][HR:HR + 5 * gneed[ci]]
            fills.append(SQ.unpack_fills(groups, res["fill_total"]))
        for res in results:
            for k in host:
                host[k].append(res[k])
        self._metrics += mets
        self._hist += hists
        host = {k: np.concatenate(v) if v else np.zeros(0)
                for k, v in host.items()}
        fills = (np.concatenate(fills, axis=1) if fills
                 else np.zeros((4, 0), np.int64))
        return host, fills

    # -- pipelined serving (H5): dispatch batch N+1 before fetching N --

    def submit(self, msgs):
        """Route + pack + DISPATCH a micro-batch without fetching its
        outputs; returns an opaque handle for collect(). Multiple
        handles may be in flight — state threads through dispatch
        order, so collect order must match submit order. This is the
        double-buffered serving shape (SURVEY.md §7 H5): the device
        executes batch N+1 while the host fetches and reconstructs
        batch N."""
        from time import perf_counter

        t0 = perf_counter()
        if not isinstance(msgs, WireBatch):
            try:
                msgs = WireBatch.from_msgs(msgs)
            except OverflowError:
                raise ValueError(
                    "pipelined serving requires int64-range ids — "
                    "route beyond-int64 streams through process_wire")
        with self.timer.phase("plan_s"):
            cols, host_rejects, stacked, cnts, K = self._plan(msgs)
        with self.timer.phase("stage_s"):
            # explicit async H2D staging: device_put enqueues the copy
            # of batch N+1's input planes while the device still runs
            # batch N's scan — the jit call below then consumes
            # already-on-device buffers instead of paying a sync
            # transfer at dispatch time. (State donation is NOT an
            # option here: it clobbers the kernel's
            # input_output_aliases — see build_seq_scan.)
            import jax as _jax

            t_st = perf_counter()
            stacked = _jax.device_put(stacked)
            dt_st = perf_counter() - t_st
        # the copy is overlapped exactly when an earlier submit is
        # still un-collected: the device runs batch N's scan while
        # batch N+1's planes stream in (the device-side half of the
        # PR 6 double buffer)
        self._h2d_total_s += dt_st
        if self._n_submit > self._n_collect:
            self._h2d_overlap_s += dt_st
        # advisory gauges (never perfgate-enforced: pure wall time):
        # cumulative host cost of the async staging enqueues + the
        # fraction of it hidden under in-flight device compute
        self.telemetry.publish_gauges(
            {"h2d_stage_s": round(self.phases.get("stage_s", 0.0), 6),
             "h2d_overlap_frac": self.h2d_overlap_frac})
        with self.timer.phase("dispatch_s"):
            # async enqueue: NO block_until_ready here — the device
            # runs this batch while the host plans/collects others
            self.state, outp = SQ.build_seq_scan(self.cfg, K)(
                self.state, stacked)
        self.windows.append(("submit", self._n_submit, t0,
                             perf_counter()))
        self._n_submit += 1
        return (msgs, cols, host_rejects, outp, cnts, K)

    @property
    def h2d_overlap_frac(self) -> float:
        """Fraction of H2D staging wall hidden under in-flight device
        compute. Serial process() paths report 0.0; a depth-N pipeline
        approaches (N-1)/N and the gate expects >= 0.5 at depth 2."""
        if self._h2d_total_s <= 0.0:
            return 0.0
        return round(self._h2d_overlap_s / self._h2d_total_s, 4)

    def collect(self, handle):
        """Complete a submit(): fetch + reconstruct the byte stream.
        Returns (buf, line_off, msg_lines) like process_wire_buffer
        (requires the native reconstructor and a WireBatch handle)."""
        from time import perf_counter

        t0 = perf_counter()
        batch, cols, host_rejects, outp, cnts, K = handle
        with self.timer.phase("fetch_s"):
            host, fills = self._fetch_outputs(outp, cnts, K)
        with self.timer.phase("recon_s"):
            r = self._recon_buffer(batch, cols, host_rejects, host,
                                   fills)
        self.windows.append(("collect", self._n_collect, t0,
                             perf_counter()))
        self._n_collect += 1
        return r

    # ------------------------------------------------------------------

    def process_wire_buffer(self, msgs):
        """Serving/bench fast path: the full byte-exact record stream as
        ONE utf-8 buffer + line offsets + per-message line counts, built
        by the native C++ reconstructor (kme_tpu/native/kme_wire.cpp).
        `msgs` may be a WireBatch (zero per-message Python work — the
        1M/s-class local path) or an OrderMsg sequence (columnarized
        here, one attribute walk). Returns (buf: bytes, line_off:
        (L+1,) np.int64 incl. end sentinel, msg_lines: (nmsg,)
        np.int32), or None when the native library is unavailable or a
        field exceeds int64 (callers fall back to process_wire)."""
        import ctypes

        from kme_tpu.native import load_library

        lib = load_library()
        if lib is None:
            return None
        if not len(msgs):
            return b"", np.zeros(1, np.int64), np.zeros(0, np.int32)
        if isinstance(msgs, WireBatch):
            batch = msgs
        else:
            try:
                batch = WireBatch.from_msgs(msgs)
            except OverflowError:
                return None  # beyond-int64 ids ride the Python path
        cols, host_rejects, host, fills = self._run(batch)
        with self.timer.phase("recon_s"):
            r = self._recon_buffer(batch, cols, host_rejects, host,
                                   fills)
        return r

    def _recon_luts(self):
        """lane -> sid and account-idx -> aid LUTs for reconstruction,
        cached against the router's id-map sizes: the maps only grow
        (REMOVE_SYMBOL wipes books, not the lane mapping), and
        exporting them was O(accounts) dict traffic per batch on the
        hot path. Wholesale imports (checkpoint restore) bump
        _map_epoch, so same-size-different-content restores can never
        serve a stale cache; Python routers are uncached (their dicts
        mutate without a hook)."""
        r = self.router
        key = None
        if isinstance(r, NativeSeqRouter):
            key = (int(r._lib.kme_router_n_symbols(r._h)),
                   int(r._lib.kme_router_n_accounts(r._h)),
                   r._map_epoch)
            cached = getattr(self, "_lut_cache", None)
            if cached is not None and cached[0] == key:
                return cached[1], cached[2]
        lut = np.zeros(self.cfg.lanes, np.int64)
        for lane, sid in r.sid_of_lane().items():
            lut[lane] = sid
        idx2aid = np.array(r.acct_of_idx() or [0], np.int64)
        if key is not None:
            self._lut_cache = (key, lut, idx2aid)
        return lut, idx2aid

    def _recon_buffer(self, batch, cols, host_rejects, host, fills):
        """Columnar inputs + device results -> the byte-exact record
        stream via the native C++ reconstructor (kme_wire.cpp).
        Prefers the one-pass kme_recon_batch entry (a single merge
        walk in C++, no numpy scatter); the kme_recon_wire scatter
        path below remains as the fallback for libraries built from
        older sources."""
        import ctypes

        from kme_tpu.native import load_library
        from kme_tpu.native.sched import recon_batch

        lib = load_library()
        if lib is None:
            raise RuntimeError(
                "the native reconstructor (kme_wire.cpp) is required "
                "for the pipelined/buffer serving path — use "
                "process_wire on hosts without the native toolchain")
        nmsg = batch.n
        self.last_reasons = reject_reason_codes(
            nmsg, cols["msg_index"], cols["act"], host["ok"],
            host["cap_reject"], host_rejects)
        if self._recon is None:
            import weakref

            self._recon = lib.kme_recon_new()
            # release the native buffer with the session (no __del__:
            # a finalizer survives interpreter-shutdown ordering)
            self._recon_fin = weakref.finalize(
                self, lib.kme_recon_free, self._recon)
        lane_sid, idx2aid = self._recon_luts()
        r = recon_batch(lib, self._recon, batch, cols, host, fills,
                        lane_sid, idx2aid)
        if r is not None:
            return r
        m_action, m_oid, m_aid = batch.action, batch.oid, batch.aid
        m_sid, m_price, m_size = batch.sid, batch.price, batch.size
        m_next, m_hnext = batch.next, batch.hnext
        m_prev, m_hprev = batch.prev, batch.hprev

        mi = cols["msg_index"]
        d_isdev = np.zeros(nmsg, np.uint8)
        d_isdev[mi] = 1
        d_act = np.zeros(nmsg, np.int32)
        d_act[mi] = cols["act"]
        d_ok = np.zeros(nmsg, np.uint8)
        d_nfill = np.zeros(nmsg, np.int32)
        d_off = np.zeros(nmsg, np.int64)
        d_resid = np.zeros(nmsg, np.int64)
        d_prev = np.zeros(nmsg, np.int64)
        d_append = np.zeros(nmsg, np.uint8)
        d_sid = np.zeros(nmsg, np.int64)
        if len(mi):
            d_ok[mi] = host["ok"].astype(np.uint8)
            d_nfill[mi] = host["nfill"].astype(np.int32)
            offs = np.cumsum(host["nfill"]) - host["nfill"]
            d_off[mi] = offs
            d_resid[mi] = host["residual"]
            d_prev[mi] = host["prev_oid"]
            d_append[mi] = host["append"].astype(np.uint8)
            lut = np.zeros(self.cfg.lanes, np.int64)
            for lane, sid in self.router.sid_of_lane().items():
                lut[lane] = sid
            d_sid[mi] = lut[cols["lane"]]
        idx2aid = np.array(self.router.acct_of_idx() or [0], np.int64)
        f_aid = (idx2aid[fills[1]] if fills.shape[1]
                 else np.zeros(0, np.int64))
        f_oid = np.ascontiguousarray(fills[0])
        f_aid = np.ascontiguousarray(f_aid)
        f_price = np.ascontiguousarray(fills[2])
        f_size = np.ascontiguousarray(fills[3])

        c = ctypes
        P64 = c.POINTER(c.c_int64)
        P32 = c.POINTER(c.c_int32)
        PU8 = c.POINTER(c.c_uint8)
        pp = lambda a, t: a.ctypes.data_as(t)
        rc = lib.kme_recon_wire(
            nmsg, pp(m_action, P64), pp(m_oid, P64), pp(m_aid, P64),
            pp(m_sid, P64), pp(m_price, P64), pp(m_size, P64),
            pp(m_next, P64), pp(m_hnext, PU8), pp(m_prev, P64),
            pp(m_hprev, PU8),
            pp(d_isdev, PU8), pp(d_act, P32), pp(d_ok, PU8),
            pp(d_nfill, P32), pp(d_off, P64), pp(d_resid, P64),
            pp(d_prev, P64), pp(d_append, PU8), pp(d_sid, P64),
            fills.shape[1], pp(f_oid, P64), pp(f_aid, P64),
            pp(f_price, P64), pp(f_size, P64), self._recon)
        if rc != 0:
            raise RuntimeError(f"kme_recon_wire failed rc={rc}")
        blen = lib.kme_recon_len(self._recon)
        nlines = lib.kme_recon_n_lines(self._recon)
        buf = c.string_at(lib.kme_recon_buf(self._recon), blen)
        line_off = np.empty(nlines + 1, np.int64)
        line_off[:nlines] = np.ctypeslib.as_array(
            lib.kme_recon_line_off(self._recon), (nlines,))
        line_off[nlines] = blen
        msg_lines = np.ctypeslib.as_array(
            lib.kme_recon_msg_lines(self._recon), (nmsg,)).copy()
        return buf, line_off, msg_lines

    def process_wire(self, msgs) -> List[List[str]]:
        if getattr(self, "_use_native_wire", True):
            r = self.process_wire_buffer(msgs)
            if r is not None:
                buf, line_off, msg_lines = r
                text = buf.decode("ascii")
                out = []
                li = 0
                for nl in msg_lines.tolist():
                    out.append([text[line_off[li + k]:line_off[li + k + 1]]
                                for k in range(nl)])
                    li += nl
                return out
        if isinstance(msgs, WireBatch):
            msgs = msgs.msgs()
        cols, host_rejects, host, fills = self._run(msgs)
        idx_to_aid = self.router.acct_of_idx()
        lane_to_sid = self.router.sid_of_lane()

        nmsg = len(msgs)
        self.last_reasons = reject_reason_codes(
            nmsg, cols["msg_index"], cols["act"], host["ok"],
            host["cap_reject"], host_rejects)
        ok_of = [False] * nmsg
        nfill_of = [0] * nmsg
        off_of = [0] * nmsg
        resid_of = [0] * nmsg
        prev_of = [0] * nmsg
        append_of = [False] * nmsg
        act_of = [0] * nmsg
        lane_of = [0] * nmsg
        mis = cols["msg_index"].tolist()
        offs = (np.cumsum(host["nfill"]) - host["nfill"]).tolist() \
            if len(mis) else []
        for arr, dst in ((host["ok"], ok_of), (host["nfill"], nfill_of),
                         (host["residual"], resid_of),
                         (host["prev_oid"], prev_of),
                         (host["append"], append_of)):
            vals = arr.tolist()
            for k, mi in enumerate(mis):
                dst[mi] = vals[k]
        acts = cols["act"].tolist()
        lanes_l = cols["lane"].tolist()
        for k, mi in enumerate(mis):
            off_of[mi] = offs[k]
            act_of[mi] = acts[k]
            lane_of[mi] = lanes_l[k]
        f_oid, f_aid, f_price, f_size = (fills[c].tolist() for c in range(4))

        out: List[List[str]] = []
        for i, m in enumerate(msgs):
            in_body = order_json(m.action, m.oid, m.aid, m.sid, m.price,
                                 m.size, m.next, m.prev)
            lines = [f'IN {in_body}']
            if i in host_rejects or not ok_of[i]:
                lines.append('OUT ' + order_json(
                    op.REJECT, m.oid, m.aid, m.sid, m.price, m.size,
                    m.next, m.prev))
            else:
                lane_act = act_of[i]
                is_trade = lane_act in (SQ.L_BUY, SQ.L_SELL)
                if is_trade:
                    sid = lane_to_sid[lane_of[i]]
                    is_buy = lane_act == SQ.L_BUY
                    mk_act = op.SOLD if is_buy else op.BOUGHT
                    tk_act = op.BOUGHT if is_buy else op.SOLD
                    o0 = off_of[i]
                    for e in range(nfill_of[i]):
                        moid = f_oid[o0 + e]
                        maid = idx_to_aid[f_aid[o0 + e]]
                        mprice = f_price[o0 + e]
                        fsz = f_size[o0 + e]
                        lines.append('OUT ' + order_json(
                            mk_act, moid, maid, sid, 0, fsz))
                        lines.append('OUT ' + order_json(
                            tk_act, m.oid, m.aid, sid, m.price - mprice,
                            fsz))
                    lines.append('OUT ' + order_json(
                        m.action, m.oid, m.aid, m.sid, m.price,
                        resid_of[i], m.next,
                        int(prev_of[i]) if append_of[i] else m.prev))
                else:
                    lines.append(f'OUT {in_body}')
            out.append(lines)
        return out

    def process(self, msgs) -> List[List[OutRecord]]:
        if isinstance(msgs, WireBatch):
            msgs = msgs.msgs()
        cols, host_rejects, host, fills = self._run(msgs)
        idx_to_aid = self.router.acct_of_idx()
        lane_to_sid = self.router.sid_of_lane()
        nmsg = len(msgs)
        self.last_reasons = reject_reason_codes(
            nmsg, cols["msg_index"], cols["act"], host["ok"],
            host["cap_reject"], host_rejects)
        dev = {}
        offs = np.cumsum(host["nfill"]) - host["nfill"] \
            if len(cols["msg_index"]) else np.zeros(0)
        for k, mi in enumerate(cols["msg_index"].tolist()):
            dev[mi] = k

        out: List[List[OutRecord]] = []
        for i, m in enumerate(msgs):
            recs = [OutRecord("IN", m.copy())]
            if i in host_rejects:
                echo = m.copy()
                echo.action = op.REJECT
                recs.append(OutRecord("OUT", echo))
            else:
                k = dev[i]
                ok = bool(host["ok"][k])
                lane_act = int(cols["act"][k])
                is_trade = lane_act in (SQ.L_BUY, SQ.L_SELL)
                if is_trade and ok:
                    sid = lane_to_sid[int(cols["lane"][k])]
                    is_buy = lane_act == SQ.L_BUY
                    o0 = int(offs[k])
                    for e in range(int(host["nfill"][k])):
                        moid = int(fills[0, o0 + e])
                        maid = idx_to_aid[int(fills[1, o0 + e])]
                        mprice = int(fills[2, o0 + e])
                        fsz = int(fills[3, o0 + e])
                        recs.append(OutRecord("OUT", OrderMsg(
                            action=op.SOLD if is_buy else op.BOUGHT,
                            oid=moid, aid=maid, sid=sid, price=0, size=fsz)))
                        recs.append(OutRecord("OUT", OrderMsg(
                            action=op.BOUGHT if is_buy else op.SOLD,
                            oid=m.oid, aid=m.aid, sid=sid,
                            price=m.price - mprice, size=fsz)))
                echo = m.copy()
                if not ok:
                    echo.action = op.REJECT
                if is_trade and ok:
                    echo.size = int(host["residual"][k])
                    if bool(host["append"][k]):
                        echo.prev = int(host["prev_oid"][k])
                recs.append(OutRecord("OUT", echo))
            out.append(recs)
        return out

    # ------------------------------------------------------------------

    def metrics(self) -> Dict[str, int]:
        counters = dict(zip(SQ.METRIC_NAMES, self._metrics.tolist()))
        if self.cfg.compat == "java":
            j = SQ.export_java(self.cfg, self.state)
            used = j["slot_size"] > 0
            counters.update({
                "open_orders": int(used.sum()),
                "books": int(j["book_exists"].sum()),
                "accounts": int(j["bal_used"].sum()),
                "positions": len(j["positions"]),
                "max_book_depth": int(used.sum(axis=2).max())
                if used.size else 0,
            })
        else:
            canon = SQ.export_canonical(self.cfg, self.state)
            used = canon["slot_used"]
            depth = used.sum(axis=2)
            counters.update({
                "open_orders": int(used.sum()),
                "books": int(canon["book_exists"].sum()),
                "accounts": int(canon["bal_used"].sum()),
                "positions": int((canon["pos_amt"] != 0).sum()),
                "max_book_depth": int(depth.max()) if depth.size else 0,
            })
        self._publish(counters)
        return counters

    def histograms(self) -> Dict[str, list]:
        """Device-accumulated distribution histograms (HIST_NAMES ->
        16 power-of-two bucket counts); published into the registry.
        book_depth stays empty in java mode (Q1 merged books have no
        per-lane occupancy plane)."""
        h = {name: self._hist[i].tolist()
             for i, name in enumerate(SQ.HIST_NAMES)}
        self.telemetry.publish_histograms(h)
        return h

    def _publish(self, counters: Dict[str, int]) -> None:
        self.telemetry.publish_counters(
            {k: counters[k] for k in SQ.METRIC_NAMES})
        self.telemetry.publish_gauges(
            {k: v for k, v in counters.items()
             if k not in SQ.METRIC_NAMES})

    def export_state(self) -> Dict[str, dict]:
        """Oracle-comparable host dict view."""
        if self.cfg.compat == "java":
            return self._export_state_java()
        canon = SQ.export_canonical(self.cfg, self.state)
        return self._canon_to_export(canon)

    def _canon_to_export(self, canon: dict) -> Dict[str, dict]:
        """Canonical engine export -> oracle-comparable dict view.
        Shared with SeqMeshSession, whose canon is stitched from
        per-shard exports through the placement table."""
        idx_to_aid = self.router.acct_of_idx()
        lane_to_sid = self.router.sid_of_lane()
        A = self.cfg.accounts
        balances = {idx_to_aid[i]: int(canon["bal"][i])
                    for i in range(len(idx_to_aid)) if canon["bal_used"][i]}
        positions = {}
        pos_amt = canon["pos_amt"].reshape(self.cfg.lanes, A)
        pos_avail = canon["pos_avail"].reshape(self.cfg.lanes, A)
        orders = {}
        S, _, N = canon["slot_oid"].shape
        for lane in range(S):
            sid = lane_to_sid.get(lane)
            if sid is None:
                continue
            for a in range(len(idx_to_aid)):
                if pos_amt[lane, a] != 0:
                    positions[(idx_to_aid[a], sid)] = (
                        int(pos_amt[lane, a]), int(pos_avail[lane, a]))
            for side in range(2):
                for nn in range(N):
                    if canon["slot_used"][lane, side, nn]:
                        orders[int(canon["slot_oid"][lane, side, nn])] = {
                            "aid": idx_to_aid[int(
                                canon["slot_aid"][lane, side, nn])],
                            "sid": sid,
                            "price": int(canon["slot_price"][lane, side, nn]),
                            "size": int(canon["slot_size"][lane, side, nn]),
                            "is_buy": side == 0,
                        }
        books = {sid: True for sid, lane in self.router.sid_lane.items()
                 if canon["book_exists"][lane]}
        return {"balances": balances, "positions": positions,
                "orders": orders, "books": books}

    def _export_state_java(self) -> Dict[str, dict]:
        """Java-mode stores, oracle-comparable: positions keyed by the
        raw 128-bit pairs (real AND Q11 garbage keys), orders with the
        original direction from the ba tag bit."""
        j = SQ.export_java(self.cfg, self.state)
        idx_to_aid = self.router.acct_of_idx()
        lane_to_sid = self.router.sid_of_lane()
        balances = {idx_to_aid[i]: int(j["bal"][i])
                    for i in range(len(idx_to_aid)) if j["bal_used"][i]}
        orders = {}
        S, _, N = j["slot_oid"].shape
        AM = (1 << 30) - 1
        for lane in range(S):
            sid = lane_to_sid.get(lane)
            if sid is None:
                continue
            for side in range(2):
                for nn in range(N):
                    if j["slot_size"][lane, side, nn] > 0:
                        ba = int(j["slot_ba"][lane, side, nn])
                        orders[int(j["slot_oid"][lane, side, nn])] = {
                            "aid": idx_to_aid[ba & AM],
                            "sid": sid,
                            "price": int(j["slot_price"][lane, side, nn]),
                            "size": int(j["slot_size"][lane, side, nn]),
                            "is_buy": (ba >> 30) & 1 == 1,
                        }
        books = {sid: True for sid, lane in self.router.sid_lane.items()
                 if j["book_exists"][lane]}
        return {"balances": balances, "positions": j["positions"],
                "orders": orders, "books": books}
