"""Host runtime: the role Kafka Streams' StreamThread plays in the
reference (poll loop, store management, forwarding — KProcessor.java:50-61)
— here: conflict-free scheduling of wire messages onto (step, lane)
slots, device dispatch, and byte-exact output-stream reconstruction."""
