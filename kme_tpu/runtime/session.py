"""LaneSession: the host half of the throughput engine.

Plans a message batch (runtime/sequencer.py), packs each scan segment
into COMPACT (M,) message vectors with (t, lane) schedule coordinates,
dispatches the device chunks + barrier ops fully asynchronously, then
fetches the compacted outputs once and reconstructs the byte-exact
record stream in arrival order — the same IN / fills / OUT contract the
reference forwards per message (KProcessor.java:97, 272-273, 124).

I/O design (round 2): the driver's TPU sits behind a tunnel with
~10-20 MB/s of host<->device bandwidth and ~126 ms round trips, and even
locally the dense (T, S, E) grids are >95% padding. So the session never
moves a grid: inputs are scattered to (T, S) on device, fill outputs
come back as ONE packed (4, F) buffer per segment, per-message results
as (M,) vectors, and every dispatch is queued without host sync — the
sticky error code in the device state is checked once at the end.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

import numpy as np

import kme_tpu._jaxsetup  # noqa: F401
import jax

from kme_tpu import opcodes as op
from kme_tpu.engine import lanes as L
from kme_tpu.runtime.sequencer import Schedule, Scheduler
from kme_tpu.wire import OrderMsg, OutRecord

_LERR_NAMES = {
    L.LERR_FILLBUF_FULL: "session fill log exhausted (fill_buffer knob)",
}


class LaneEngineError(RuntimeError):
    def __init__(self, code: int) -> None:
        self.code = int(code)
        super().__init__(
            f"lane engine error: {_LERR_NAMES.get(self.code, self.code)}")


def _bucket(n: int, lo: int = 64) -> int:
    """Round up to a power-of-two bucket to bound XLA recompiles."""
    b = lo
    while b < n:
        b *= 2
    return b


@dataclasses.dataclass
class _WindowRun:
    """A dispatched window: its compact device outputs + bookkeeping.

    placements are sorted by (t, lane) — the exact order the device
    appends fills to the persistent fill log, so host fill offsets are
    the running cumsum of nfill in placement order across windows in
    dispatch order."""
    placements: list          # Placed, sorted by (step-in-window, lane)
    outs: dict                # device arrays (fetched lazily)
    host: dict = None         # np arrays after fetch
    offs: np.ndarray = None   # (M,) absolute fill-log offsets


class LaneSession:
    """Drop-in fixed-mode engine over the vmapped lane kernel.

    With shards > 1 the lane axis is sharded over a device mesh
    (kme_tpu/parallel/mesh.py); the output stream is bit-identical for
    any shard count — the determinism contract of SURVEY.md §5."""

    def __init__(self, cfg: L.LaneConfig, shards: int = 1,
                 width: int = 16) -> None:
        """width > 0 (single-device only) enables active-lane compaction:
        the scheduler caps each scan step at `width` messages and the
        device computes (T, width) message slots instead of (T, S) lanes
        — per-step work drops from O(S·(N+A)) to O(width·N). cfg.width,
        if set, wins over the argument; the sharded path is always
        full-width (GSPMD owns the lane axis there)."""
        W = cfg.width if cfg.width > 0 else width
        # at most one message per lane per step can ever be scheduled, so
        # wider-than-S slots would be permanently dead padding
        W = min(W, cfg.lanes)
        if shards > 1 or W < 0:
            W = 0
        self.cfg = cfg = dataclasses.replace(cfg, width=0)
        # device config: compaction reserves the last lane as the padding
        # scrap row, so the device state carries one extra lane
        self.dev_cfg = (dataclasses.replace(cfg, lanes=cfg.lanes + 1,
                                            width=W) if W else cfg)
        self.shards = shards
        self._chunk_cache: Dict[tuple, object] = {}
        if shards > 1:
            from kme_tpu.parallel import mesh as M

            self.mesh = M.build_mesh(shards)
            self.state = M.shard_state(L.make_lane_state(cfg), self.mesh)
            self._settle = jax.jit(M.build_sharded_settle(cfg, self.mesh),
                                   donate_argnums=(0,))
        else:
            self.mesh = None
            self.state = L.make_lane_state(self.dev_cfg)
            self._settle = jax.jit(L.build_barrier_ops(self.dev_cfg),
                                   donate_argnums=(0,))
        self.scheduler = Scheduler(cfg.lanes, cfg.accounts, width=W)

    # ------------------------------------------------------------------

    def _chunk_fn(self, T: int, M: int):
        if self.shards == 1:
            return L.build_lane_chunk(self.dev_cfg, T, M)
        key = (T, M)
        fn = self._chunk_cache.get(key)
        if fn is None:
            from kme_tpu.parallel import mesh as MM

            raw = MM.build_sharded_chunk(self.cfg, self.mesh, T, M)
            fn = jax.jit(raw, donate_argnums=(0,))
            self._chunk_cache[key] = fn
        return fn

    def _pack_window(self, placements, t0: int, T: int,
                     M: int) -> Dict[str, np.ndarray]:
        from kme_tpu.oracle import javalong as jl

        cb = {
            "t": np.full(M, T, np.int32),     # t >= T marks padding
            "lane": np.zeros(M, np.int32),
            "slot": np.zeros(M, np.int32),
            "act": np.zeros(M, np.int32),
            "oid": np.zeros(M, np.int64),
            "aid": np.zeros(M, np.int32),
            "price": np.zeros(M, np.int32),
            "size": np.zeros(M, np.int32),
        }
        for m, p in enumerate(placements):
            cb["t"][m] = p.step - t0
            cb["lane"][m] = p.lane
            cb["slot"][m] = p.slot
            cb["act"][m] = p.lane_act
            cb["oid"][m] = jl.jlong(p.oid)
            cb["aid"][m] = p.aid_idx
            cb["price"][m] = p.price  # int32 by EnvelopeError
            cb["size"][m] = p.size
        return cb

    def _dispatch(self, sched: Schedule) -> tuple:
        """Queue every dispatch window + barrier asynchronously. Long
        segments are split into windows of <= cfg.window scan steps (the
        HBM bound for the per-step output grids); nothing syncs with the
        device here. Returns (window runs in dispatch order, barrier-ok
        device scalars by msg index)."""
        by_seg: Dict[int, list] = {}
        for p in sched.placements:
            by_seg.setdefault(p.segment, []).append(p)

        runs: List[_WindowRun] = []
        barrier_ok: Dict[int, object] = {}
        from kme_tpu.oracle import javalong as jl

        W = self.cfg.window
        for kind, idx in sched.program:
            if kind == "scan":
                placements = by_seg.get(idx, [])
                height = sched.segment_steps[idx]
                by_win: Dict[int, list] = {}
                for p in placements:
                    by_win.setdefault(p.step // W, []).append(p)
                for w in range((height + W - 1) // W):
                    wp = sorted(by_win.get(w, []),
                                key=lambda p: (p.step, p.lane))
                    T = _bucket(min(height - w * W, W), lo=self.cfg.steps)
                    M = _bucket(max(len(wp), 1))
                    cb = self._pack_window(wp, w * W, T, M)
                    self.state, outs = self._chunk_fn(T, M)(self.state, cb)
                    runs.append(_WindowRun(wp, outs))
            else:
                b = sched.barriers[idx]
                self.state, ok = self._settle(
                    self.state, np.int32(b.lane),
                    np.int64(jl.jlong(b.credit_size)), np.int32(b.mode))
                barrier_ok[b.msg_index] = ok
        return runs, barrier_ok

    def _fetch(self, runs: List[_WindowRun]) -> np.ndarray:
        """One sync phase: start every device->host copy asynchronously,
        then materialize; check the sticky error; slice the used prefix
        of the persistent fill log and rewind it. Returns the packed
        (4, F_used) fill log [oid, aid, price, size]."""
        for run in runs:
            for v in run.outs.values():
                try:
                    v.copy_to_host_async()
                except AttributeError:  # older jax / non-array leaf
                    pass
        base = 0
        for run in runs:
            host = {k: np.asarray(v) for k, v in run.outs.items()}
            err = int(host["err"])
            if err != L.LERR_OK:
                raise LaneEngineError(err)
            run.host = host
            run.offs = base + np.cumsum(host["nfill"]) - host["nfill"]
            base += int(host["nfill_total"])
            run.outs = None
        if self.shards == 1:
            if base:
                fills = np.asarray(self.state["fillbuf"][:, :base])
            else:
                fills = np.zeros((4, 0), np.int64)
            self.state = L.build_fill_reset(self.dev_cfg)(self.state)
            return fills
        return np.zeros((4, 0), np.int64)

    # ------------------------------------------------------------------

    def process(self, msgs: Sequence[OrderMsg]) -> List[List[OutRecord]]:
        sched = self.scheduler.plan(msgs)
        runs, barrier_ok_dev = self._dispatch(sched)
        fills = self._fetch(runs)
        return self._reconstruct(msgs, sched, runs, barrier_ok_dev, fills)

    def _reconstruct(self, msgs, sched, runs, barrier_ok_dev, fills):
        idx_to_aid = self.scheduler.acct_of_idx()
        lane_to_sid = self.scheduler.sid_of_lane()
        barrier_ok = {i: bool(np.asarray(okd))
                      for i, okd in barrier_ok_dev.items()}

        # m-position of each device message within its window run
        pos_of_msg: Dict[int, tuple] = {}
        for run in runs:
            for m, p in enumerate(run.placements):
                pos_of_msg[p.msg_index] = (run, m)
        rejects = {r.msg_index for r in sched.host_rejects}
        barriers_by_msg = {b.msg_index: b for b in sched.barriers}
        dense = self.shards > 1

        out: List[List[OutRecord]] = []
        for i, m in enumerate(msgs):
            recs = [OutRecord("IN", m.copy())]
            if i in rejects:
                echo = m.copy()
                echo.action = op.REJECT
                recs.append(OutRecord("OUT", echo))
            elif i in barriers_by_msg:
                echo = m.copy()
                if not barrier_ok[i]:
                    echo.action = op.REJECT
                recs.append(OutRecord("OUT", echo))
            else:
                run, mm = pos_of_msg[i]
                h = run.host
                p = run.placements[mm]
                ok = bool(h["ok"][mm])
                is_trade = p.lane_act in (L.L_BUY, L.L_SELL)
                if is_trade and ok:
                    sid = lane_to_sid[p.lane]
                    is_buy = p.lane_act == L.L_BUY
                    o0 = int(run.offs[mm])
                    for e in range(int(h["nfill"][mm])):
                        if dense:
                            moid = int(h["fill_oid"][mm, e])
                            maid = idx_to_aid[int(h["fill_aid"][mm, e])]
                            mprice = int(h["fill_price"][mm, e])
                            fsz = int(h["fill_size"][mm, e])
                        else:
                            moid = int(fills[0, o0 + e])
                            maid = idx_to_aid[int(fills[1, o0 + e])]
                            mprice = int(fills[2, o0 + e])
                            fsz = int(fills[3, o0 + e])
                        recs.append(OutRecord("OUT", OrderMsg(
                            action=op.SOLD if is_buy else op.BOUGHT,
                            oid=moid, aid=maid, sid=sid, price=0, size=fsz)))
                        recs.append(OutRecord("OUT", OrderMsg(
                            action=op.BOUGHT if is_buy else op.SOLD,
                            oid=m.oid, aid=m.aid, sid=sid,
                            price=m.price - mprice, size=fsz)))
                echo = m.copy()
                if not ok:
                    echo.action = op.REJECT
                if is_trade and ok:
                    echo.size = int(h["residual"][mm])
                    if bool(h["append"][mm]):
                        echo.prev = int(h["prev_oid"][mm])
                recs.append(OutRecord("OUT", echo))
            out.append(recs)
        return out

    # ------------------------------------------------------------------

    def export_state(self) -> Dict[str, dict]:
        """Host dict view comparable to the oracle's stores (fixed mode)."""
        s = jax.tree.map(np.asarray, self.state)
        idx_to_aid = self.scheduler.acct_of_idx()
        lane_to_sid = self.scheduler.sid_of_lane()
        balances = {idx_to_aid[i]: int(s["bal"][i])
                    for i in range(len(idx_to_aid)) if s["bal_used"][i]}
        positions = {}
        orders = {}
        S, _, N = s["slot_oid"].shape
        for lane in range(S):
            sid = lane_to_sid.get(lane)
            if sid is None:
                continue
            for a in range(len(idx_to_aid)):
                if s["pos_used"][lane, a]:
                    positions[(idx_to_aid[a], sid)] = (
                        int(s["pos_amt"][lane, a]), int(s["pos_avail"][lane, a]))
            for side in range(2):
                for n in range(N):
                    if s["slot_used"][lane, side, n]:
                        orders[int(s["slot_oid"][lane, side, n])] = {
                            "aid": idx_to_aid[int(s["slot_aid"][lane, side, n])],
                            "sid": sid,
                            "price": int(s["slot_price"][lane, side, n]),
                            "size": int(s["slot_size"][lane, side, n]),
                            "is_buy": side == 0,
                        }
        books = {sid: True for sid, lane in self.scheduler.sid_lane.items()
                 if s["book_exists"][lane]}
        return {"balances": balances, "positions": positions,
                "orders": orders, "books": books}
