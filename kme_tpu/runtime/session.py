"""LaneSession: the host half of the throughput engine.

Plans a message batch (runtime/sequencer.py), packs each scan segment
into COMPACT (M,) message vectors with (t, lane) schedule coordinates,
dispatches the device chunks + barrier ops fully asynchronously, then
fetches the compacted outputs once and reconstructs the byte-exact
record stream in arrival order — the same IN / fills / OUT contract the
reference forwards per message (KProcessor.java:97, 272-273, 124).

I/O design (round 2): the driver's TPU sits behind a tunnel with
~10-20 MB/s of host<->device bandwidth and ~126 ms round trips, and even
locally the dense (T, S, E) grids are >95% padding. So the session never
moves a grid: inputs are scattered to (T, S) on device, fill outputs
come back as ONE packed (4, F) buffer per segment, per-message results
as (M,) vectors, and every dispatch is queued without host sync — the
sticky error code in the device state is checked once at the end.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

import numpy as np

import kme_tpu._jaxsetup  # noqa: F401
import jax

from kme_tpu import opcodes as op
from kme_tpu.engine import lanes as L
from kme_tpu.runtime.sequencer import Schedule, make_scheduler
from kme_tpu.telemetry import PhaseTimer, Registry
from kme_tpu import wire as W
from kme_tpu.wire import OrderMsg, OutRecord

_LERR_NAMES = {
    L.LERR_FILLBUF_FULL: "session fill log exhausted (fill_buffer knob)",
}


def _device_reason(lane_act: int, cap: bool) -> int:
    """REJ_* code for a device not-ok result: the capacity flag wins,
    else classify by the internal lane act."""
    if cap:
        return W.REJ_CAPACITY
    if lane_act in (L.L_BUY, L.L_SELL):
        return W.REJ_RISK
    if lane_act == L.L_CANCEL:
        return W.REJ_CANCEL
    return W.REJ_OTHER


class LaneEngineError(RuntimeError):
    def __init__(self, code: int) -> None:
        self.code = int(code)
        super().__init__(
            f"lane engine error: {_LERR_NAMES.get(self.code, self.code)}")


from kme_tpu.utils import pow2_bucket as _bucket


@dataclasses.dataclass
class _WindowRun:
    """A dispatched window: its compact device outputs + bookkeeping.

    `idx` are placement ROW ids into the schedule's columnar arrays,
    sorted by (step-in-window, lane) — the exact order the device
    appends fills to the persistent fill log, so host fill offsets are
    the running cumsum of nfill in row order across windows in dispatch
    order."""
    idx: np.ndarray           # placement rows, sorted by (step, lane)
    outs: dict                # device arrays (fetched lazily)
    host: dict = None         # np arrays after fetch
    offs: np.ndarray = None   # (M,) absolute fill-log offsets


class LaneSession:
    """Drop-in fixed-mode engine over the vmapped lane kernel.

    With shards > 1 the lane axis is sharded over a device mesh
    (kme_tpu/parallel/mesh.py); the output stream is bit-identical for
    any shard count — the determinism contract of SURVEY.md §5."""

    def __init__(self, cfg: L.LaneConfig, shards: int = 1,
                 width: int = 16) -> None:
        """width > 0 (single-device only) enables active-lane compaction:
        the scheduler caps each scan step at `width` messages and the
        device computes (T, width) message slots instead of (T, S) lanes
        — per-step work drops from O(S·(N+A)) to O(width·N). cfg.width,
        if set, wins over the argument; the sharded path is always
        full-width (GSPMD owns the lane axis there)."""
        W = cfg.width if cfg.width > 0 else width
        # at most one message per lane per step can ever be scheduled, so
        # wider-than-S slots would be permanently dead padding
        W = min(W, cfg.lanes)
        if shards > 1 or W < 0:
            W = 0
        self.cfg = cfg = dataclasses.replace(cfg, width=0, pos_dma=False)
        # device config: compaction reserves the last lane as the padding
        # scrap row, so the device state carries one extra lane. The
        # compact path keeps positions as planar i32 rows updated in
        # place by Pallas row-DMA (engine/lanes.py pos_dma) whenever the
        # row width tiles cleanly (accounts % 64 == 0).
        use_dma = W > 0 and (2 * cfg.accounts) % 128 == 0
        self.dev_cfg = (dataclasses.replace(cfg, lanes=cfg.lanes + 1,
                                            width=W, pos_dma=use_dma)
                        if W else cfg)
        self.shards = shards
        if shards > 1:
            from kme_tpu.parallel import mesh as M

            self.mesh = M.build_mesh(shards)
            self.state = M.shard_state(L.make_lane_state(cfg), self.mesh)
            self._settle = M.build_sharded_settle_jit(cfg, shards)
        else:
            self.mesh = None
            self.state = L.make_lane_state(self.dev_cfg)
            self._settle = jax.jit(L.build_barrier_ops(self.dev_cfg),
                                   donate_argnums=(0,))
        self.scheduler = make_scheduler(cfg.lanes, cfg.accounts, width=W)
        self.telemetry = Registry()
        self.timer = PhaseTimer(track="lanes")
        # the timer owns the dict: phase totals ACCUMULATE across batches
        self.phases = self.timer.totals
        # per-message REJ_* reason codes for the last processed batch
        # (np.uint8 (nmsg,), wire.REJ_NAMES) — read by the flight
        # recorder and the opt-in REJ annotation records
        self.last_reasons = None

    # ------------------------------------------------------------------

    def _chunk_fn(self, T: int, M: int):
        if self.shards == 1:
            return L.build_lane_chunk(self.dev_cfg, T, M)
        from kme_tpu.parallel import mesh as MM

        return MM.build_sharded_chunk_jit(self.cfg, self.shards, T, M)

    def _pack_window(self, cols: Dict[str, np.ndarray], widx: np.ndarray,
                     t0: int, T: int, M: int) -> Dict[str, np.ndarray]:
        n = len(widx)
        cb = {
            "t": np.full(M, T, np.int32),     # t >= T marks padding
            "lane": np.zeros(M, np.int32),
            "slot": np.zeros(M, np.int32),
            "act": np.zeros(M, np.int32),
            "oid": np.zeros(M, np.int64),
            "aid": np.zeros(M, np.int32),
            "price": np.zeros(M, np.int32),
            "size": np.zeros(M, np.int32),
        }
        cb["t"][:n] = cols["step"][widx] - t0
        cb["lane"][:n] = cols["lane"][widx]
        cb["slot"][:n] = cols["slot"][widx]
        cb["act"][:n] = cols["act"][widx]
        cb["oid"][:n] = cols["oid"][widx]
        cb["aid"][:n] = cols["aidx"][widx]
        cb["price"][:n] = cols["price"][widx]
        cb["size"][:n] = cols["size"][widx]
        return cb

    def _dispatch(self, sched: Schedule) -> tuple:
        """Queue every dispatch window + barrier asynchronously. Long
        segments are split into windows of <= cfg.window scan steps (the
        HBM bound for the per-step output grids); nothing syncs with the
        device here. Returns (window runs in dispatch order, barrier-ok
        device scalars by msg index)."""
        cols = sched.cols
        nseg = len(sched.segment_steps)
        # rows are appended in arrival order, so `segment` is sorted
        seg_bounds = np.searchsorted(cols["segment"], np.arange(nseg + 1))

        runs: List[_WindowRun] = []
        barrier_ok: Dict[int, object] = {}
        from kme_tpu.oracle import javalong as jl

        W = self.cfg.window
        for kind, idx in sched.program:
            if kind == "scan":
                lo, hi = int(seg_bounds[idx]), int(seg_bounds[idx + 1])
                height = sched.segment_steps[idx]
                order = lo + np.lexsort((cols["lane"][lo:hi],
                                         cols["step"][lo:hi]))
                sorted_steps = cols["step"][order]
                for w in range((height + W - 1) // W):
                    a = np.searchsorted(sorted_steps, w * W, "left")
                    b = np.searchsorted(sorted_steps, (w + 1) * W, "left")
                    widx = order[a:b]
                    T = _bucket(min(height - w * W, W), lo=self.cfg.steps)
                    M = _bucket(max(len(widx), 1))
                    cb = self._pack_window(cols, widx, w * W, T, M)
                    self.state, outs = self._chunk_fn(T, M)(self.state, cb)
                    runs.append(_WindowRun(widx, outs))
            else:
                b = sched.barriers[idx]
                self.state, ok = self._settle(
                    self.state, np.int32(b.lane),
                    np.int64(jl.jlong(b.credit_size)), np.int32(b.mode))
                barrier_ok[b.msg_index] = ok
        return runs, barrier_ok

    def _fetch(self, runs: List[_WindowRun]) -> np.ndarray:
        """One sync phase: start every device->host copy asynchronously,
        then materialize; check the sticky error; slice the used prefix
        of the persistent fill log and rewind it. Returns the packed
        (4, F_used) fill log [oid, aid, price, size]."""
        from kme_tpu.utils import async_prefetch

        for run in runs:
            async_prefetch(run.outs.values())
        base = 0
        for run in runs:
            # one (8, M) packed array per window — a single transfer
            # (chunk_compaction packs all per-message outputs + the
            # err/total scalars into it)
            p = np.asarray(run.outs["packed"])
            err = int(p[6, 0])
            if err != L.LERR_OK:
                raise LaneEngineError(err)
            host = {
                "ok": p[0] != 0,
                "residual": p[1],
                "append": p[2] != 0,
                "prev_oid": p[3],
                "cap_reject": p[4] != 0,
                "nfill": p[5],
                "nfill_total": p[7, 0],
            }
            run.host = host
            run.offs = base + np.cumsum(host["nfill"]) - host["nfill"]
            base += int(host["nfill_total"])
            run.outs = None
        if base:
            fills = np.asarray(self.state["fillbuf"][:, :base])
        else:
            fills = np.zeros((4, 0), np.int64)
        self.state = L.build_fill_reset(self.dev_cfg)(self.state)
        return fills

    # ------------------------------------------------------------------

    def process(self, msgs: Sequence[OrderMsg]) -> List[List[OutRecord]]:
        with self.timer.phase("plan_s"):
            sched = self.scheduler.plan(msgs)
        with self.timer.phase("dispatch_s"):
            runs, barrier_ok_dev = self._dispatch(sched)
        with self.timer.phase("fetch_s"):
            fills = self._fetch(runs)
        with self.timer.phase("recon_s"):
            return self._reconstruct(msgs, sched, runs, barrier_ok_dev,
                                     fills)

    def process_wire(self, msgs: Sequence[OrderMsg]) -> List[List[str]]:
        """Like process(), but returns the byte-exact `<key> <json>` wire
        lines (consumer.js:19 format) directly — no per-record Python
        objects. This is the serving/bench path; equivalence with
        process() is pinned by tests/test_lanes_engine.py."""
        with self.timer.phase("plan_s"):
            sched = self.scheduler.plan(msgs)
        with self.timer.phase("dispatch_s"):
            runs, barrier_ok_dev = self._dispatch(sched)
        with self.timer.phase("fetch_s"):
            fills = self._fetch(runs)
        with self.timer.phase("recon_s"):
            return self._reconstruct_wire(msgs, sched, runs, barrier_ok_dev,
                                          fills)

    def _reconstruct_wire(self, msgs, sched, runs, barrier_ok_dev, fills):
        idx_to_aid = self.scheduler.acct_of_idx()
        lane_to_sid = self.scheduler.sid_of_lane()
        barrier_ok = {i: bool(np.asarray(okd))
                      for i, okd in barrier_ok_dev.items()}
        cols = sched.cols
        nmsg = len(msgs)
        # Per-message scalar state, extracted in BULK (tolist() — numpy
        # scalar-by-scalar extraction dominates reconstruction otherwise).
        ok_of = [False] * nmsg
        nfill_of = [0] * nmsg
        off_of = [0] * nmsg
        resid_of = [0] * nmsg
        prev_of = [0] * nmsg
        append_of = [False] * nmsg
        act_of = [0] * nmsg
        lane_of = [0] * nmsg
        cap_of = [False] * nmsg
        for run in runs:
            n = len(run.idx)
            h = run.host
            mis = cols["msg_index"][run.idx].tolist()
            for name, dst in (("ok", ok_of), ("nfill", nfill_of),
                              ("residual", resid_of), ("prev_oid", prev_of),
                              ("append", append_of),
                              ("cap_reject", cap_of)):
                vals = h[name][:n].tolist()
                for k, mi in enumerate(mis):
                    dst[mi] = vals[k]
            offs = run.offs[:n].tolist()
            acts = cols["act"][run.idx].tolist()
            lanes_l = cols["lane"][run.idx].tolist()
            for k, mi in enumerate(mis):
                off_of[mi] = offs[k]
                act_of[mi] = acts[k]
                lane_of[mi] = lanes_l[k]
        f_oid, f_aid, f_price, f_size = (fills[c].tolist() for c in range(4))
        rejects = {r.msg_index for r in sched.host_rejects}
        barriers = {b.msg_index for b in sched.barriers}

        from kme_tpu.wire import order_json

        reasons = np.zeros(nmsg, np.uint8)
        out: List[List[str]] = []
        for i, m in enumerate(msgs):
            in_body = order_json(m.action, m.oid, m.aid, m.sid, m.price,
                                 m.size, m.next, m.prev)
            lines = [f'IN {in_body}']
            if i in rejects or (i in barriers and not barrier_ok[i]):
                reasons[i] = (W.REJ_UNROUTABLE if i in rejects
                              else W.REJ_BARRIER)
                lines.append('OUT ' + order_json(
                    op.REJECT, m.oid, m.aid, m.sid, m.price, m.size,
                    m.next, m.prev))
            elif i in barriers:
                lines.append(f'OUT {in_body}')
            else:
                lane_act = act_of[i]
                ok = ok_of[i]
                is_trade = lane_act in (L.L_BUY, L.L_SELL)
                if is_trade and ok:
                    sid = lane_to_sid[lane_of[i]]
                    is_buy = lane_act == L.L_BUY
                    mk_act = op.SOLD if is_buy else op.BOUGHT
                    tk_act = op.BOUGHT if is_buy else op.SOLD
                    o0 = off_of[i]
                    for e in range(nfill_of[i]):
                        moid = f_oid[o0 + e]
                        maid = idx_to_aid[f_aid[o0 + e]]
                        mprice = f_price[o0 + e]
                        fsz = f_size[o0 + e]
                        lines.append('OUT ' + order_json(
                            mk_act, moid, maid, sid, 0, fsz))
                        lines.append('OUT ' + order_json(
                            tk_act, m.oid, m.aid, sid, m.price - mprice,
                            fsz))
                    lines.append('OUT ' + order_json(
                        m.action, m.oid, m.aid, m.sid, m.price,
                        resid_of[i], m.next,
                        prev_of[i] if append_of[i] else m.prev))
                else:
                    if not ok:
                        reasons[i] = _device_reason(lane_act, cap_of[i])
                    lines.append('OUT ' + order_json(
                        m.action if ok else op.REJECT, m.oid, m.aid,
                        m.sid, m.price, m.size, m.next, m.prev))
            out.append(lines)
        self.last_reasons = reasons
        return out

    def _reconstruct(self, msgs, sched, runs, barrier_ok_dev, fills):
        idx_to_aid = self.scheduler.acct_of_idx()
        lane_to_sid = self.scheduler.sid_of_lane()
        barrier_ok = {i: bool(np.asarray(okd))
                      for i, okd in barrier_ok_dev.items()}

        # run + m-position of each device message within its window run
        cols = sched.cols
        run_of_msg = np.full(len(msgs), -1, np.int64)
        m_of_msg = np.zeros(len(msgs), np.int64)
        for ri, run in enumerate(runs):
            mi = cols["msg_index"][run.idx]
            run_of_msg[mi] = ri
            m_of_msg[mi] = np.arange(len(run.idx))
        rejects = {r.msg_index for r in sched.host_rejects}
        barriers_by_msg = {b.msg_index: b for b in sched.barriers}

        reasons = np.zeros(len(msgs), np.uint8)
        out: List[List[OutRecord]] = []
        for i, m in enumerate(msgs):
            recs = [OutRecord("IN", m.copy())]
            if i in rejects:
                reasons[i] = W.REJ_UNROUTABLE
                echo = m.copy()
                echo.action = op.REJECT
                recs.append(OutRecord("OUT", echo))
            elif i in barriers_by_msg:
                echo = m.copy()
                if not barrier_ok[i]:
                    reasons[i] = W.REJ_BARRIER
                    echo.action = op.REJECT
                recs.append(OutRecord("OUT", echo))
            else:
                run = runs[run_of_msg[i]]
                mm = int(m_of_msg[i])
                h = run.host
                row = run.idx[mm]
                lane_act = int(cols["act"][row])
                ok = bool(h["ok"][mm])
                is_trade = lane_act in (L.L_BUY, L.L_SELL)
                if is_trade and ok:
                    sid = lane_to_sid[int(cols["lane"][row])]
                    is_buy = lane_act == L.L_BUY
                    o0 = int(run.offs[mm])
                    for e in range(int(h["nfill"][mm])):
                        moid = int(fills[0, o0 + e])
                        maid = idx_to_aid[int(fills[1, o0 + e])]
                        mprice = int(fills[2, o0 + e])
                        fsz = int(fills[3, o0 + e])
                        recs.append(OutRecord("OUT", OrderMsg(
                            action=op.SOLD if is_buy else op.BOUGHT,
                            oid=moid, aid=maid, sid=sid, price=0, size=fsz)))
                        recs.append(OutRecord("OUT", OrderMsg(
                            action=op.BOUGHT if is_buy else op.SOLD,
                            oid=m.oid, aid=m.aid, sid=sid,
                            price=m.price - mprice, size=fsz)))
                echo = m.copy()
                if not ok:
                    reasons[i] = _device_reason(
                        lane_act, bool(h["cap_reject"][mm]))
                    echo.action = op.REJECT
                if is_trade and ok:
                    echo.size = int(h["residual"][mm])
                    if bool(h["append"][mm]):
                        echo.prev = int(h["prev_oid"][mm])
                recs.append(OutRecord("OUT", echo))
            out.append(recs)
        self.last_reasons = reasons
        return out

    # ------------------------------------------------------------------

    def metrics(self) -> Dict[str, int]:
        """On-device observability: cumulative counters (accumulated in
        the scan carry, psum-merged under sharding) + point-in-time
        gauges. One tiny device reduce per call — never per message."""
        m = self.state["metrics"]
        if isinstance(m, tuple):  # compact-mode scalar-tuple carry:
            m = jax.numpy.stack(m)  # stack on device, ONE transfer
        counters = dict(zip(L.METRIC_NAMES, np.asarray(m).tolist()))
        gauges = L.build_gauges(self.dev_cfg)(self.state)
        counters.update({k: int(np.asarray(v)) for k, v in gauges.items()})
        self._publish(counters)
        return counters

    def histograms(self) -> Dict[str, list]:
        """In-kernel distribution histograms (power-of-two buckets), read
        back with the same one-transfer discipline as metrics()."""
        h = self.state["hist"]
        if isinstance(h, tuple):  # compact-mode per-hist rows
            h = jax.numpy.stack(h)  # stack on device, ONE transfer
        rows = np.asarray(h)
        out = {name: rows[i].tolist() for i, name in enumerate(L.HIST_NAMES)}
        self.telemetry.publish_histograms(out)
        return out

    def _publish(self, counters: Dict[str, int]) -> None:
        self.telemetry.publish_counters(
            {k: counters[k] for k in L.METRIC_NAMES})
        self.telemetry.publish_gauges(
            {k: v for k, v in counters.items()
             if k not in L.METRIC_NAMES})

    def export_state(self) -> Dict[str, dict]:
        """Host dict view comparable to the oracle's stores (fixed mode)."""
        s = jax.tree.map(np.asarray, self.state)
        idx_to_aid = self.scheduler.acct_of_idx()
        lane_to_sid = self.scheduler.sid_of_lane()
        balances = {idx_to_aid[i]: int(s["bal"][i])
                    for i in range(len(idx_to_aid)) if s["bal_used"][i]}
        positions = {}
        orders = {}
        S, _, N = s["slot_oid"].shape
        for k in ("pos_amt", "pos_avail"):
            if self.dev_cfg.pos_dma:  # planar lo/hi i32 rows -> s64
                from kme_tpu.ops.rowdma import unpack64_np

                s[k] = unpack64_np(s[k], S)
            else:
                s[k] = s[k].reshape(S, -1)  # flat (S*A,) device layout
        # a position exists iff amt != 0 (no-used-flag invariant)
        s["pos_used"] = s["pos_amt"] != 0
        for lane in range(S):
            sid = lane_to_sid.get(lane)
            if sid is None:
                continue
            for a in range(len(idx_to_aid)):
                if s["pos_used"][lane, a]:
                    positions[(idx_to_aid[a], sid)] = (
                        int(s["pos_amt"][lane, a]), int(s["pos_avail"][lane, a]))
            for side in range(2):
                for n in range(N):
                    if s["slot_used"][lane, side, n]:
                        orders[int(s["slot_oid"][lane, side, n])] = {
                            "aid": idx_to_aid[int(s["slot_aid"][lane, side, n])],
                            "sid": sid,
                            "price": int(s["slot_price"][lane, side, n]),
                            "size": int(s["slot_size"][lane, side, n]),
                            "is_buy": side == 0,
                        }
        books = {sid: True for sid, lane in self.scheduler.sid_lane.items()
                 if s["book_exists"][lane]}
        return {"balances": balances, "positions": positions,
                "orders": orders, "books": books}
