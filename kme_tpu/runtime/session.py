"""LaneSession: the host half of the throughput engine.

Plans a message batch (runtime/sequencer.py), packs scan segments into
(T, S) device arrays, dispatches the lane step / barrier ops, and
reconstructs the byte-exact output record stream in arrival order — the
same IN / fills / OUT contract the reference forwards per message
(KProcessor.java:97, 272-273, 124) and the oracle reproduces.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

import kme_tpu._jaxsetup  # noqa: F401
import jax

from kme_tpu import opcodes as op
from kme_tpu.engine import lanes as L
from kme_tpu.runtime.sequencer import Schedule, Scheduler
from kme_tpu.wire import OrderMsg, OutRecord

_LERR_NAMES = {
    L.LERR_BOOK_FULL: "book slot capacity exhausted",
    L.LERR_FILLS_FULL: "sweep crossed more makers than max_fills",
}


class LaneEngineError(RuntimeError):
    def __init__(self, code: int) -> None:
        self.code = int(code)
        super().__init__(
            f"lane engine error: {_LERR_NAMES.get(self.code, self.code)}")


class LaneSession:
    """Drop-in fixed-mode engine over the vmapped lane kernel.

    With shards > 1 the lane axis is sharded over a device mesh
    (kme_tpu/parallel/mesh.py); the output stream is bit-identical for
    any shard count — the determinism contract of SURVEY.md §5."""

    def __init__(self, cfg: L.LaneConfig, shards: int = 1) -> None:
        self.cfg = cfg
        self.shards = shards
        if shards > 1:
            from kme_tpu.parallel import mesh as M

            self.mesh = M.build_mesh(shards)
            self.state = M.shard_state(L.make_lane_state(cfg), self.mesh)
            self._step = jax.jit(M.build_sharded_step(cfg, self.mesh),
                                 donate_argnums=(0,))
            self._settle = jax.jit(M.build_sharded_settle(cfg, self.mesh),
                                   donate_argnums=(0,))
        else:
            self.mesh = None
            self.state = L.make_lane_state(cfg)
            self._step = jax.jit(L.build_lane_step(cfg), donate_argnums=(0,))
            self._settle = jax.jit(L.build_barrier_ops(cfg), donate_argnums=(0,))
        self.scheduler = Scheduler(cfg.lanes, cfg.accounts)

    # ------------------------------------------------------------------

    def _pack_segment(self, sched: Schedule, seg: int) -> Dict[str, np.ndarray]:
        T, S = self.cfg.steps, self.cfg.lanes
        height = sched.segment_steps[seg]
        padded = ((height + T - 1) // T) * T
        arr = {
            "act": np.zeros((padded, S), np.int32),
            "oid": np.zeros((padded, S), np.int64),
            "aid": np.zeros((padded, S), np.int32),
            "price": np.zeros((padded, S), np.int32),
            "size": np.zeros((padded, S), np.int32),
        }
        from kme_tpu.oracle import javalong as jl

        for p in sched.placements:
            if p.segment != seg:
                continue
            arr["act"][p.step, p.lane] = p.lane_act
            arr["oid"][p.step, p.lane] = jl.jlong(p.oid)
            arr["aid"][p.step, p.lane] = p.aid_idx
            arr["price"][p.step, p.lane] = p.price  # int32 by EnvelopeError
            arr["size"][p.step, p.lane] = p.size
        return arr

    def _run_segment(self, arrs: Dict[str, np.ndarray]):
        """Dispatch in T-sized chunks; returns list of chunk outputs."""
        T = self.cfg.steps
        chunks = []
        total = arrs["act"].shape[0]
        for t0 in range(0, total, T):
            batch = {k: v[t0:t0 + T] for k, v in arrs.items()}
            self.state, outs = self._step(self.state, batch)
            outs = jax.tree.map(np.asarray, outs)
            err = outs["err"]
            if err[-1] != L.LERR_OK:
                raise LaneEngineError(int(err[-1]))
            chunks.append(outs)
        return chunks

    # ------------------------------------------------------------------

    def process(self, msgs: Sequence[OrderMsg]) -> List[List[OutRecord]]:
        sched = self.scheduler.plan(msgs)
        idx_to_aid = self.scheduler.acct_of_idx()
        lane_to_sid = self.scheduler.sid_of_lane()

        seg_out = {}
        barrier_ok = {}
        for kind, idx in sched.program:
            if kind == "scan":
                seg_out[idx] = self._run_segment(self._pack_segment(sched, idx))
            else:
                b = sched.barriers[idx]
                from kme_tpu.oracle import javalong as jl

                self.state, ok = self._settle(
                    self.state, np.int32(b.lane),
                    np.int64(jl.jlong(b.credit_size)), np.int32(b.mode))
                barrier_ok[b.msg_index] = bool(np.asarray(ok))

        placed_by_msg = {p.msg_index: p for p in sched.placements}
        rejects = {r.msg_index for r in sched.host_rejects}
        barriers_by_msg = {b.msg_index: b for b in sched.barriers}

        out: List[List[OutRecord]] = []
        T = self.cfg.steps
        for i, m in enumerate(msgs):
            recs = [OutRecord("IN", m.copy())]
            if i in rejects:
                echo = m.copy()
                echo.action = op.REJECT
                recs.append(OutRecord("OUT", echo))
            elif i in barriers_by_msg:
                echo = m.copy()
                if not barrier_ok[i]:
                    echo.action = op.REJECT
                recs.append(OutRecord("OUT", echo))
            else:
                p = placed_by_msg[i]
                chunk = seg_out[p.segment][p.step // T]
                t = p.step % T
                lane = p.lane
                ok = bool(chunk["ok"][t, lane])
                is_trade = p.lane_act in (L.L_BUY, L.L_SELL)
                if is_trade and ok:
                    sid = lane_to_sid[lane]
                    is_buy = p.lane_act == L.L_BUY
                    nf = int(chunk["nfill"][t, lane])
                    for e in range(nf):
                        fsz = int(chunk["fill_size"][t, lane, e])
                        moid = int(chunk["fill_oid"][t, lane, e])
                        maid = idx_to_aid[int(chunk["fill_aid"][t, lane, e])]
                        mprice = int(chunk["fill_price"][t, lane, e])
                        recs.append(OutRecord("OUT", OrderMsg(
                            action=op.SOLD if is_buy else op.BOUGHT,
                            oid=moid, aid=maid, sid=sid, price=0, size=fsz)))
                        recs.append(OutRecord("OUT", OrderMsg(
                            action=op.BOUGHT if is_buy else op.SOLD,
                            oid=m.oid, aid=m.aid, sid=sid,
                            price=m.price - mprice, size=fsz)))
                echo = m.copy()
                if not ok:
                    echo.action = op.REJECT
                if is_trade and ok:
                    echo.size = int(chunk["residual"][t, lane])
                    if bool(chunk["append"][t, lane]):
                        echo.prev = int(chunk["prev_oid"][t, lane])
                recs.append(OutRecord("OUT", echo))
            out.append(recs)
        return out

    # ------------------------------------------------------------------

    def export_state(self) -> Dict[str, dict]:
        """Host dict view comparable to the oracle's stores (fixed mode)."""
        s = jax.tree.map(np.asarray, self.state)
        idx_to_aid = self.scheduler.acct_of_idx()
        lane_to_sid = self.scheduler.sid_of_lane()
        balances = {idx_to_aid[i]: int(s["bal"][i])
                    for i in range(len(idx_to_aid)) if s["bal_used"][i]}
        positions = {}
        orders = {}
        S, _, N = s["slot_oid"].shape
        for lane in range(S):
            sid = lane_to_sid.get(lane)
            if sid is None:
                continue
            for a in range(len(idx_to_aid)):
                if s["pos_used"][lane, a]:
                    positions[(idx_to_aid[a], sid)] = (
                        int(s["pos_amt"][lane, a]), int(s["pos_avail"][lane, a]))
            for side in range(2):
                for n in range(N):
                    if s["slot_used"][lane, side, n]:
                        orders[int(s["slot_oid"][lane, side, n])] = {
                            "aid": idx_to_aid[int(s["slot_aid"][lane, side, n])],
                            "sid": sid,
                            "price": int(s["slot_price"][lane, side, n]),
                            "size": int(s["slot_size"][lane, side, n]),
                            "is_buy": side == 0,
                        }
        books = {sid: True for sid, lane in self.scheduler.sid_lane.items()
                 if s["book_exists"][lane]}
        return {"balances": balances, "positions": positions,
                "orders": orders, "books": books}
