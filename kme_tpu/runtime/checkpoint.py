"""Checkpoint / resume: the durability story.

The reference gets durability from RocksDB-backed stores + Kafka
changelog topics; resume = Kafka Streams restoring store state and
continuing from the committed input offset
(/root/reference/src/main/java/KProcessor.java:30-49; commit :125).
Exactly-once is commented out (:29), so its guarantee is AT-LEAST-ONCE:
on crash, records after the last commit replay.

The TPU-native equivalent: an explicit `(state_pytree, input_offset)`
snapshot at a batch boundary (SURVEY.md §5). Because the engine is
deterministic, resume = load snapshot + replay the input tail, and the
replayed outputs are bit-identical — the same at-least-once contract
with replay bounded by the checkpoint interval instead of one record.

The exactly-once layer (bridge/broker.py fencing + idempotent produce)
upgrades that: every save accepts an additive ``extra`` meta dict — the
service stores its ``{"epoch", "out_seq"}`` produce-stamp cursor there —
and `snapshot_extra` reads it back on resume, so the replayed tail
re-produces with the SAME stamps and the broker suppresses it.

Snapshots are self-describing single files: every state array plus a
JSON `meta` blob (config, compaction width, shard count, input offset,
scheduler id-maps) in one .npz, written atomically (tmp + rename) and
named ckpt-<offset>.npz so the latest valid one wins; a torn or corrupt
file falls back to the previous snapshot.

The device fill log is intentionally NOT saved: at a batch boundary it
has been drained to the host and rewound (filloff == 0), so restore
recreates it as zeros.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
from typing import List, Optional, Tuple

import numpy as np

from kme_tpu import faults

_CKPT_RE = re.compile(r"^ckpt-(\d+)\.npz$")


def _keep_default() -> int:
    """Snapshot retention depth. Two is the bare minimum (newest + one
    fallback); the default keeps a deeper tail so several consecutive
    corrupt/torn snapshots still leave a valid restore point
    (kme-chaos tears AND bit-flips). KME_CKPT_KEEP / --checkpoint-keep
    override."""
    try:
        return max(1, int(os.environ.get("KME_CKPT_KEEP", "3")))
    except ValueError:
        return 3


class SnapshotCapacityError(ValueError):
    """The snapshot cannot restore into the requested capacity/engine
    config (a state migration, not a resume) — callers must NOT
    silently fall back to a fresh engine."""


_SKIP_KEYS = ("fillbuf",)
# arrays whose leading axis is the lane axis (stored in CANONICAL form:
# user lanes only — the compact path's scrap row is provably all-zero,
# so it is stripped at save and recreated at load; this makes snapshots
# portable across width/shard configurations)
_LANE_KEYS = ("slot_oid", "slot_aid", "slot_price", "slot_size",
              "slot_seq", "slot_used", "seq", "book_exists")
_POS_KEYS = ("pos_amt", "pos_avail")  # flat (S*A,) lane-major


def snapshot_path(ckpt_dir: str, offset: int) -> str:
    return os.path.join(ckpt_dir, f"ckpt-{offset}.npz")


def _payload_digest(payload: dict) -> str:
    """sha256 over every array's dtype/shape/bytes (sorted key order,
    'digest' excluded) — the content integrity check _load_file
    verifies. A bit-flipped payload that still np.load-parses fails
    HERE instead of silently restoring wrong state."""
    h = hashlib.sha256()
    for k in sorted(payload):
        if k == "digest":
            continue
        arr = np.ascontiguousarray(np.asarray(payload[k]))
        h.update(k.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def _atomic_savez(ckpt_dir: str, offset: int, payload: dict,
                  keep: Optional[int] = None) -> str:
    """THE durable snapshot write: content digest + tmp file + fsync +
    atomic rename + directory fsync + prune. Every .npz save path goes
    through here so the crash-safety sequence cannot fork."""
    payload = dict(payload)
    payload["digest"] = np.frombuffer(
        _payload_digest(payload).encode(), dtype=np.uint8)
    path = snapshot_path(ckpt_dir, offset)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(ckpt_dir)
    _post_write_faults(path)
    _prune(ckpt_dir, _CKPT_RE, keep=keep)
    return path


def _post_write_faults(path: str) -> None:
    """kme-chaos injection points: tear or bit-flip the snapshot that
    was just made durable (the load path must detect either and fall
    back to the previous snapshot)."""
    faults.damage_file("ckpt.torn", path)
    faults.damage_file("ckpt.bitflip", path)


def list_snapshots(ckpt_dir: str) -> List[Tuple[int, str]]:
    """(offset, path) pairs, newest first."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(ckpt_dir, name)))
    out.sort(reverse=True)
    return out


def save_session(ckpt_dir: str, session, offset: int,
                 keep: Optional[int] = None,
                 extra: Optional[dict] = None) -> str:
    """Snapshot `session` (a LaneSession) at input offset `offset`.
    Must be called at a batch boundary (the fill log drained)."""
    import jax

    os.makedirs(ckpt_dir, exist_ok=True)
    state = jax.tree.map(np.asarray, session.state)
    if int(state["filloff"][0]) != 0:
        raise ValueError("snapshot requires a drained fill log "
                         "(call at a batch boundary)")
    sch = session.scheduler
    meta = {
        "version": 1,
        "kind": "lanes",
        "offset": int(offset),
        "cfg": dataclasses.asdict(session.cfg),
        "width": int(session.dev_cfg.width),
        "shards": int(session.shards),
        "aid_idx": sorted(sch.aid_idx.items()),
        "sid_lane": sorted(sch.sid_lane.items()),
        "oid_sid": sorted(sch.oid_sid.items()),
        "rr_lane": sch._rr_lane,
    }
    if extra:
        meta["extra"] = dict(extra)
    S = session.cfg.lanes  # canonical lane count (no scrap row)
    A = session.cfg.accounts
    payload = {}
    for k, v in state.items():
        if k in _SKIP_KEYS:
            continue
        if k in _LANE_KEYS:
            v = v[:S]
        elif k in _POS_KEYS:
            if v.ndim == 3:  # pos_dma planar i32 rows -> canonical s64
                from kme_tpu.ops.rowdma import unpack64_np

                v = unpack64_np(v, v.shape[0]).reshape(-1)
            v = v[:S * A]
        payload[k] = v
    payload["meta"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    return _atomic_savez(ckpt_dir, offset, payload, keep=keep)


def _fsync_dir(d: str) -> None:
    fd = os.open(d, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _prune(ckpt_dir: str, pattern, keep: Optional[int] = None) -> None:
    """Unlink all but the newest `keep` snapshots. keep=None uses the
    configured default (_keep_default) — deep enough that multi-step
    fallback past several corrupt snapshots still finds a valid one."""
    if keep is None:
        keep = _keep_default()
    keep = max(1, int(keep))
    cands = []
    for name in os.listdir(ckpt_dir):
        m = pattern.match(name)
        if m:
            cands.append((int(m.group(1)), name))
    cands.sort(reverse=True)
    for _, name in cands[keep:]:
        try:
            os.unlink(os.path.join(ckpt_dir, name))
        except OSError:
            pass


def _load_file(path: str):
    data = np.load(path)
    if "digest" in data.files:
        want = bytes(data["digest"]).decode()
        got = _payload_digest({k: data[k] for k in data.files})
        if got != want:
            raise ValueError(
                f"content digest mismatch in {path} (stored "
                f"{want[:12]}…, computed {got[:12]}…): corrupt snapshot")
    # pre-digest snapshots (older writers) load unverified
    meta = json.loads(bytes(data["meta"]).decode())
    # "lanes" and "seq" snapshots share the canonical payload layout
    # and restore into EITHER engine (cross-engine restore); "seqjava"
    # is the java-mode canonical form (runtime/javasnap.py), restorable
    # into SeqSession(compat='java') and convertible to/from the native
    # engine's dump
    if meta.get("version") != 1 or meta.get("kind") not in (
            "lanes", "seq", "seqjava"):
        raise ValueError(f"unsupported snapshot {path}")
    return data, meta


def load_session(ckpt_dir: str, shards: Optional[int] = None,
                 width: Optional[int] = None):
    """Restore the newest valid snapshot in `ckpt_dir`.
    Returns (session, offset) or (None, 0) when no usable snapshot
    exists. A corrupt newest file (torn write) falls back to the next.
    `shards`/`width` override the snapshot's values (elastic restore
    onto a different mesh or compaction width — snapshots are canonical,
    so any combination restores bit-exactly)."""
    for offset, path in list_snapshots(ckpt_dir):
        try:
            return _restore_one(path, shards, width), offset
        except SnapshotCapacityError:
            raise          # operator error, not corruption: surface it
        except Exception as e:  # torn/corrupt snapshot: fall back
            import sys

            print(f"kme_tpu.checkpoint: skipping unreadable snapshot "
                  f"{path}: {e}", file=sys.stderr)
    return None, 0


def _restore_one(path: str, shards: Optional[int], width: Optional[int]):
    """Restore one snapshot file into a live LaneSession (raises on any
    corruption — load_session falls back to the previous snapshot)."""
    import jax.numpy as jnp

    from kme_tpu.engine.lanes import LaneConfig, make_lane_state
    from kme_tpu.runtime.session import LaneSession

    data, meta = _load_file(path)
    if meta.get("kind") == "seqjava":
        raise SnapshotCapacityError(
            "java-mode snapshot cannot restore into the (fixed-mode) "
            "lanes engine — restore with load_seq_session into "
            "SeqConfig(compat='java') or convert to the native engine "
            "(runtime/javasnap.py)")
    if meta.get("kind") == "seq":  # cross-engine restore (canonical)
        mc = meta["cfg"]
        cfg = LaneConfig(lanes=int(mc["lanes"]), slots=int(mc["slots"]),
                         accounts=int(mc["accounts"]),
                         max_fills=int(mc["max_fills"]))
    else:
        cfg = LaneConfig(**meta["cfg"])
    use_shards = meta["shards"] if shards is None else shards
    use_width = meta["width"] if width is None else width
    ses = LaneSession(cfg, shards=use_shards, width=use_width or 0)
    fresh = make_lane_state(ses.dev_cfg)
    S, A = cfg.lanes, cfg.accounts
    state = {}
    for k, v in fresh.items():
        if k in _SKIP_KEYS:
            state[k] = v  # recreated empty (drained at snapshot)
            continue
        if k == "metrics":
            if k not in data.files:
                state[k] = v  # pure observability counter: pre-metrics
                continue      # snapshots restore with fresh zeros
            arr = np.asarray(data[k])
            want = (len(v),) if isinstance(v, tuple) else tuple(v.shape)
            if arr.shape != want:
                raise ValueError(
                    f"snapshot {path}: shape mismatch for metrics: "
                    f"{arr.shape} vs {want}")
            # compact device state carries the counters as a scalar
            # tuple; the canonical form is the (12,) array
            state[k] = (tuple(jnp.asarray(x) for x in arr)
                        if isinstance(v, tuple) else jnp.asarray(arr))
            continue
        if k == "hist":
            if k not in data.files:
                state[k] = v  # pure observability: pre-histogram
                continue      # snapshots restore with fresh zeros
            arr = np.asarray(data[k])
            want = ((len(v), len(v[0])) if isinstance(v, tuple)
                    else tuple(v.shape))
            if arr.shape != want:
                raise ValueError(
                    f"snapshot {path}: shape mismatch for hist: "
                    f"{arr.shape} vs {want}")
            # compact device state carries one (16,) bucket row per
            # histogram as a tuple; the canonical form is (3, 16)
            state[k] = (tuple(jnp.asarray(x) for x in arr)
                        if isinstance(v, tuple) else jnp.asarray(arr))
            continue
        arr = np.asarray(data[k])
        if k in _POS_KEYS:
            # canonical form is ALWAYS flat (S*A,) s64; the device
            # layout may be pos_dma planar i32 rows
            if arr.shape != (S * A,):
                raise ValueError(
                    f"snapshot {path}: shape mismatch for {k}: "
                    f"{arr.shape} vs canonical ({S * A},)")
            if v.ndim == 3:  # pack into planar rows, scrap row zero
                from kme_tpu.ops.rowdma import pack64_np

                S_dev = v.shape[0]
                full64 = np.zeros((S_dev, A), np.int64)
                full64[:S] = arr.reshape(S, A)
                state[k] = jnp.asarray(pack64_np(full64, S_dev))
            else:
                full = np.array(v)
                full[:S * A] = arr
                state[k] = jnp.asarray(full)
        elif k in _LANE_KEYS:
            n = S
            if arr.shape[:1] != (n,) or arr.shape[1:] != v.shape[1:]:
                raise ValueError(
                    f"snapshot {path}: shape mismatch for {k}: "
                    f"{arr.shape} vs canonical ({n},)+{v.shape[1:]}")
            full = np.array(v)  # writable zeros incl. scrap row
            full[:n] = arr
            state[k] = jnp.asarray(full)
        else:
            if arr.shape != tuple(v.shape):
                raise ValueError(
                    f"snapshot {path}: shape mismatch for {k}: "
                    f"{arr.shape} vs {tuple(v.shape)}")
            state[k] = jnp.asarray(arr)
    if use_shards > 1:
        from kme_tpu.parallel import mesh as M

        state = M.shard_state(state, ses.mesh)
    ses.state = state
    sch = ses.scheduler
    sch.aid_idx = {int(k): int(i) for k, i in meta["aid_idx"]}
    sch.sid_lane = {int(k): int(l) for k, l in meta["sid_lane"]}
    sch.oid_sid = {int(k): int(s) for k, s in meta["oid_sid"]}
    sch._rr_lane = int(meta["rr_lane"])
    return ses


def save_seq_session(ckpt_dir: str, session, offset: int,
                     keep: Optional[int] = None,
                     extra: Optional[dict] = None) -> str:
    """Snapshot a SeqSession at input offset `offset` in the SAME
    canonical layout as lanes snapshots (slot_* / flat s64 positions /
    bal), so snapshots restore across ENGINES as well as across
    shard/width topologies."""
    from kme_tpu.engine import seq as SQ

    if session.cfg.compat == "java":
        return _save_seqjava(ckpt_dir, session, offset, keep=keep,
                             extra=extra)
    os.makedirs(ckpt_dir, exist_ok=True)
    canon = SQ.export_canonical(session.cfg, session.state)
    r = session.router
    meta = {
        "version": 1,
        "kind": "seq",
        "offset": int(offset),
        "cfg": dataclasses.asdict(session.cfg),
        "metrics": [int(x) for x in session._metrics],
        "hist": [[int(x) for x in row] for row in session._hist],
        "aid_idx": sorted(r.aid_idx.items()),
        "sid_lane": sorted(r.sid_lane.items()),
        "oid_sid": sorted(r.oid_sid.items()),
        "rr_lane": 0,   # lanes-session cross-restore compatibility
        "width": 0,
        "shards": 1,
    }
    if extra:
        meta["extra"] = dict(extra)
    payload = {k: v for k, v in canon.items()
               if k != "metrics" and v is not None}
    payload["err"] = np.asarray(canon["err"])
    # lanes-session cross-restore expects the drained fill-log cursor
    payload["filloff"] = np.zeros(1, np.int64)
    payload["meta"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    return _atomic_savez(ckpt_dir, offset, payload, keep=keep)


def _save_seqjava(ckpt_dir: str, session, offset: int,
                  keep: Optional[int] = None,
                  extra: Optional[dict] = None) -> str:
    """Snapshot a java-mode SeqSession: the canonical java form
    (runtime/javasnap.py) — flat 128-bit-key position arrays (Q11
    garbage keys included: they are parity-relevant state), resting
    orders with direction tags and bucket seq, balances, and the
    router id maps."""
    from kme_tpu.runtime.javasnap import export_seqjava

    os.makedirs(ckpt_dir, exist_ok=True)
    snap = export_seqjava(session)
    meta = {
        "version": 1,
        "kind": "seqjava",
        "offset": int(offset),
        "cfg": dataclasses.asdict(session.cfg),
        "metrics": [int(x) for x in session._metrics],
        "hist": [[int(x) for x in row] for row in session._hist],
        "aid_idx": sorted(snap["aid_idx"].items()),
        "sid_lane": sorted(snap["sid_lane"].items()),
        "oid_sid": sorted(snap["oid_sid"].items()),
    }
    if extra:
        meta["extra"] = dict(extra)
    payload = {k: np.asarray(v) for k, v in snap.items()
               if k not in ("aid_idx", "sid_lane", "oid_sid")}
    payload["meta"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    return _atomic_savez(ckpt_dir, offset, payload, keep=keep)


def _seqjava_snap_from_file(data, meta) -> dict:
    snap = {k: np.asarray(data[k]) for k in data.files if k != "meta"}
    snap["aid_idx"] = {int(k): int(v) for k, v in meta["aid_idx"]}
    snap["sid_lane"] = {int(k): int(v) for k, v in meta["sid_lane"]}
    snap["oid_sid"] = {int(k): int(v) for k, v in meta["oid_sid"]}
    return snap


def load_seq_session(ckpt_dir: str, cfg=None):
    """Restore the newest valid snapshot into a SeqSession. `cfg` (a
    SeqConfig) sets the RESTORE topology — snapshots are canonical, so
    any slots >= the snapshot's depth works, and lanes-engine snapshots
    restore here too (cross-engine). Returns (session, offset) or
    (None, 0)."""
    for offset, path in list_snapshots(ckpt_dir):
        try:
            return _restore_seq_one(path, cfg), offset
        except SnapshotCapacityError:
            raise          # operator error, not corruption: surface it
        except Exception as e:
            import sys

            print(f"kme_tpu.checkpoint: skipping unreadable snapshot "
                  f"{path}: {e}", file=sys.stderr)
    return None, 0


def _restore_seq_one(path: str, cfg):
    from kme_tpu.engine import seq as SQ
    from kme_tpu.runtime.seqsession import SeqSession

    data, meta = _load_file(path)
    explicit_cfg = cfg is not None
    if meta["kind"] == "seqjava":
        from kme_tpu.runtime.javasnap import import_seqjava

        if cfg is None:
            cfg = SQ.SeqConfig(**meta["cfg"])
        if cfg.compat != "java":
            raise SnapshotCapacityError(
                "java-mode snapshot requires SeqConfig(compat='java') "
                "(or conversion to the native engine, "
                "runtime/javasnap.py)")
        if explicit_cfg:
            # same contract as the fixed path: the device capacity
            # envelope must not change across a resume (a changed
            # slots/max_fills alters where the fatal java capacity
            # error trips mid-stream)
            n0 = int(meta["cfg"]["slots"])
            mf = int(meta["cfg"]["max_fills"])
            if cfg.slots != n0 or cfg.max_fills != mf:
                raise SnapshotCapacityError(
                    f"snapshot capacity (slots={n0}, max_fills={mf}) "
                    f"!= requested (slots={cfg.slots}, max_fills="
                    f"{cfg.max_fills}) — capacity changes need a "
                    f"state migration, not a resume")
        try:
            ses = import_seqjava(cfg, _seqjava_snap_from_file(data, meta))
        except ValueError as e:
            raise SnapshotCapacityError(str(e)) from e
        if "metrics" in meta:
            ses._metrics = np.asarray(meta["metrics"], np.int64)
        if "hist" in meta:
            ses._hist = np.asarray(meta["hist"], np.int64)
        return ses
    if cfg is not None and cfg.compat == "java":
        raise SnapshotCapacityError(
            "fixed-mode snapshot cannot restore into a java-mode "
            "session")
    if cfg is None:
        if meta["kind"] == "seq":
            cfg = SQ.SeqConfig(**meta["cfg"])
        else:  # a lanes snapshot: map the shared capacity fields
            mc = meta["cfg"]
            slots = -(-int(mc["slots"]) // 128) * 128
            cfg = SQ.SeqConfig(
                lanes=int(mc["lanes"]), slots=slots,
                accounts=-(-int(mc["accounts"]) // 128) * 128,
                max_fills=int(mc["max_fills"]),
                hbm_books=slots > 512)
    canon = {k: np.asarray(data[k]) for k in data.files if k != "meta"}
    canon.setdefault("err", np.int32(0))
    if explicit_cfg:
        # service resume: the matching ENVELOPE must not change across
        # a resume (the lanes/native paths enforce the same; deeper
        # books or a different max_fills alter reject behavior
        # mid-stream — that is a state migration, not a resume)
        n0 = int(np.asarray(canon["slot_oid"]).shape[2])
        mf = int(meta["cfg"].get("max_fills", cfg.max_fills))
        if cfg.slots != n0 or cfg.max_fills != mf:
            raise SnapshotCapacityError(
                f"snapshot envelope (slots={n0}, max_fills={mf}) != "
                f"requested (slots={cfg.slots}, max_fills="
                f"{cfg.max_fills}) — capacity changes need a state "
                f"migration, not a resume")
    ses = SeqSession(cfg)
    try:
        # every ValueError here is a config-vs-snapshot mismatch
        # (corruption surfaces earlier, in _load_file) — never treat it
        # as a skippable corrupt snapshot
        ses.state = SQ.import_canonical(cfg, canon)
    except ValueError as e:
        raise SnapshotCapacityError(str(e)) from e
    if "metrics" in meta:
        ses._metrics = np.asarray(meta["metrics"], np.int64)
    if "hist" in meta:
        ses._hist = np.asarray(meta["hist"], np.int64)
    r = ses.router
    r.aid_idx = {int(k): int(i) for k, i in meta["aid_idx"]}
    r.sid_lane = {int(k): int(l) for k, l in meta["sid_lane"]}
    r.oid_sid = {int(k): int(s) for k, s in meta["oid_sid"]}
    return ses


# ---------------------------------------------------------------------------
# native-engine snapshots (text store dump + a JSON header line)

def save_native(ckpt_dir: str, engine, offset: int,
                keep: Optional[int] = None,
                extra: Optional[dict] = None) -> str:
    """Snapshot a NativeOracleEngine: JSON header (compat + envelope +
    offset + dump digest) on line one, then the store dump."""
    os.makedirs(ckpt_dir, exist_ok=True)
    dump = engine.dump_state()
    head = {
        "version": 1, "kind": "native", "offset": int(offset),
        "compat": "java" if engine.java else "fixed",
        "book_slots": engine.book_slots, "max_fills": engine.max_fills,
        "digest": hashlib.sha256(dump.encode("utf-8")).hexdigest(),
    }
    if extra:
        head["extra"] = dict(extra)
    header = json.dumps(head)
    path = os.path.join(ckpt_dir, f"ckpt-{offset}.nat")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(header + "\n")
        f.write(dump)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(ckpt_dir)
    _post_write_faults(path)
    _prune(ckpt_dir, re.compile(r"^ckpt-(\d+)\.nat$"), keep=keep)
    return path


def load_native(ckpt_dir: str):
    """Returns (engine, offset) or (None, 0); corrupt files fall back."""
    import sys

    from kme_tpu.native.oracle import NativeOracleEngine

    if not os.path.isdir(ckpt_dir):
        return None, 0
    cands = []
    for name in os.listdir(ckpt_dir):
        m = re.match(r"^ckpt-(\d+)\.nat$", name)
        if m:
            cands.append((int(m.group(1)), os.path.join(ckpt_dir, name)))
    cands.sort(reverse=True)
    for offset, path in cands:
        try:
            with open(path, "r", encoding="utf-8") as f:
                header = json.loads(f.readline())
                if header.get("version") != 1 or header.get("kind") != "native":
                    raise ValueError("unsupported snapshot")
                dump = f.read()
                want = header.get("digest")
                if want is not None:  # pre-digest snapshots load as-is
                    got = hashlib.sha256(dump.encode("utf-8")).hexdigest()
                    if got != want:
                        raise ValueError(
                            f"content digest mismatch (stored "
                            f"{want[:12]}…, computed {got[:12]}…): "
                            f"corrupt snapshot")
                eng = NativeOracleEngine(header["compat"],
                                         book_slots=header["book_slots"],
                                         max_fills=header["max_fills"])
                eng.load_state(dump)
            return eng, offset
        except Exception as e:
            print(f"kme_tpu.checkpoint: skipping unreadable snapshot "
                  f"{path}: {e}", file=sys.stderr)
    return None, 0


# ---------------------------------------------------------------------------
# oracle-engine snapshots (the scalar replica is plain host state)

def save_oracle(ckpt_dir: str, oracle, offset: int,
                keep: Optional[int] = None,
                extra: Optional[dict] = None) -> str:
    """The engine is pickled to bytes FIRST so the blob can carry a
    sha256 of exactly those bytes — load verifies the digest before
    unpickling, so a bit-flip that still pickle-parses is caught."""
    import pickle

    os.makedirs(ckpt_dir, exist_ok=True)
    engine_pkl = pickle.dumps(oracle)
    path = os.path.join(ckpt_dir, f"ckpt-{offset}.pkl")
    tmp = path + ".tmp"
    blob = {"version": 1, "kind": "oracle", "offset": int(offset),
            "engine_pkl": engine_pkl,
            "digest": hashlib.sha256(engine_pkl).hexdigest()}
    if extra:
        blob["extra"] = dict(extra)
    with open(tmp, "wb") as f:
        pickle.dump(blob, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(ckpt_dir)
    _post_write_faults(path)
    _prune(ckpt_dir, re.compile(r"^ckpt-(\d+)\.pkl$"), keep=keep)
    return path


def load_oracle_file(path: str):
    """Restore ONE oracle snapshot file (digest-verified). Raises on
    corruption — callers own the fallback-to-older decision."""
    import pickle

    with open(path, "rb") as f:
        blob = pickle.load(f)
    if blob.get("version") != 1 or blob.get("kind") != "oracle":
        raise ValueError("unsupported snapshot")
    if "engine_pkl" in blob:
        got = hashlib.sha256(blob["engine_pkl"]).hexdigest()
        if got != blob.get("digest"):
            raise ValueError(
                f"content digest mismatch (stored "
                f"{str(blob.get('digest'))[:12]}…, computed "
                f"{got[:12]}…): corrupt snapshot")
        return pickle.loads(blob["engine_pkl"])
    return blob["engine"]   # pre-digest snapshot format


def load_oracle(ckpt_dir: str):
    """Returns (oracle, offset) or (None, 0)."""
    import sys

    if not os.path.isdir(ckpt_dir):
        return None, 0
    cands = []
    for name in os.listdir(ckpt_dir):
        m = re.match(r"^ckpt-(\d+)\.pkl$", name)
        if m:
            cands.append((int(m.group(1)), os.path.join(ckpt_dir, name)))
    cands.sort(reverse=True)
    for offset, path in cands:
        try:
            return load_oracle_file(path), offset
        except Exception as e:
            print(f"kme_tpu.checkpoint: skipping unreadable snapshot "
                  f"{path}: {e}", file=sys.stderr)
    return None, 0


def restore_seq_snapshot(path: str, cfg=None):
    """Restore ONE .npz snapshot file (lanes/seq/seqjava canonical
    form) into a SeqSession. Raises on corruption or capacity mismatch
    — the offset-addressed loaders (telemetry/xray.py) use this to
    restore a SPECIFIC anchor instead of the newest snapshot."""
    return _restore_seq_one(path, cfg)


# ---------------------------------------------------------------------------
# cross-kind snapshot metadata (the exactly-once produce-stamp cursor)

_ALL_SNAP_RES = (_CKPT_RE,
                 re.compile(r"^ckpt-(\d+)\.nat$"),
                 re.compile(r"^ckpt-(\d+)\.pkl$"))


def snapshot_extra(ckpt_dir: str, offset: int) -> dict:
    """The additive ``extra`` meta dict stored with the snapshot at
    exactly `offset` (any snapshot kind); {} when absent or unreadable.
    The caller already loaded the snapshot itself, so failures here
    degrade to an empty cursor (epoch 0 / out_seq 0), which the broker's
    recovered watermark still keeps duplicate-free."""
    import pickle

    npz = snapshot_path(ckpt_dir, offset)
    if os.path.exists(npz):
        try:
            data = np.load(npz)
            meta = json.loads(bytes(data["meta"]).decode())
            return dict(meta.get("extra") or {})
        except Exception:
            return {}
    nat = os.path.join(ckpt_dir, f"ckpt-{offset}.nat")
    if os.path.exists(nat):
        try:
            with open(nat, "r", encoding="utf-8") as f:
                header = json.loads(f.readline())
            return dict(header.get("extra") or {})
        except Exception:
            return {}
    pkl = os.path.join(ckpt_dir, f"ckpt-{offset}.pkl")
    if os.path.exists(pkl):
        try:
            with open(pkl, "rb") as f:
                blob = pickle.load(f)
            return dict(blob.get("extra") or {})
        except Exception:
            return {}
    return {}


def all_snapshots(ckpt_dir: str) -> List[Tuple[int, str]]:
    """(offset, path) pairs across ALL snapshot kinds (.npz/.nat/.pkl),
    newest first. The offset-addressed restore path (telemetry/xray.py)
    walks this to find the nearest anchor <= a target offset; ties at
    the same offset sort .pkl > .npz > .nat so the exact-state oracle
    snapshot wins when several kinds exist."""
    if not os.path.isdir(ckpt_dir):
        return []
    rank = {".pkl": 2, ".npz": 1, ".nat": 0}
    out = []
    for name in os.listdir(ckpt_dir):
        for pat in _ALL_SNAP_RES:
            m = pat.match(name)
            if m:
                ext = os.path.splitext(name)[1]
                out.append((int(m.group(1)), rank.get(ext, 0),
                            os.path.join(ckpt_dir, name)))
                break
    out.sort(reverse=True)
    return [(off, path) for off, _r, path in out]


def oldest_retained_offset(ckpt_dir: str) -> Optional[int]:
    """Smallest snapshot offset still on disk (any kind), or None when
    there are no snapshots. The journal's retention guard
    (telemetry/journal.py): a rotated journal segment may only be
    pruned once every event in it is OLDER than this — a standby
    restoring the oldest snapshot must still be able to replay to the
    tip."""
    if not os.path.isdir(ckpt_dir):
        return None
    oldest = None
    for name in os.listdir(ckpt_dir):
        for pat in _ALL_SNAP_RES:
            m = pat.match(name)
            if m:
                off = int(m.group(1))
                if oldest is None or off < oldest:
                    oldest = off
                break
    return oldest
