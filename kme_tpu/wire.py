"""Wire schema: the reference's JSON Order message, byte-compatible.

The reference's serde is Jackson over a POJO with public fields declared in
the order action, oid, aid, sid, price, size, next, prev
(/root/reference/src/main/java/KProcessor.java:448-475), serialized with
`writeValueAsString(...).getBytes()` (KProcessor.java:488-490): compact JSON
(no spaces), fields in declaration order, `next`/`prev` always present
(null when unset — quirk Q9: the intrusive list pointers leak onto the
wire). Incoming messages are parsed by field name; missing fields default
to 0 / null (Jackson primitive defaults). Note Jackson binds `next`/`prev`
FROM input too — the @JsonCreator ctor covers the six value fields, and
the remaining public fields are bound by field access afterward — so a
message carrying non-null pointers (e.g. a replayed OUT echo) enters the
engine with them set, and a new-bucket rest stores them verbatim (only the
append path overwrites `prev`, KProcessor.java:217). Parsed faithfully
here; the device engine's compat envelope excludes such inputs (COMPAT.md).

`dumps_order` reproduces the exact byte stream so the reference's
consumer.js output is byte-identical under our engine.
"""

from __future__ import annotations

import dataclasses
import json
import struct
from typing import Iterator, List, Optional, Tuple

_FIELDS = ("action", "oid", "aid", "sid", "price", "size")

# ---------------------------------------------------------------------------
# Binary order frame (ISSUE 11): the length-prefixed fixed-width twin of
# the JSON order message — the same zero-copy idea as the journal's
# 96-byte record framing (telemetry/journal.py MAGIC/_REC), promoted to
# a first-class wire protocol. JSON stays accepted on the same socket
# (COMPAT.md): every JSON message begins with '{' (0x7B) and every
# binary frame with WIRE_MAGIC (0xB1), so one peek at the first byte
# negotiates the encoding per message with zero configuration.
#
# Layout (little-endian, 72 bytes, struct "<BBBBI8q"):
#
#   off size field
#   0   1    magic    0xB1 (never 0x7B — JSON auto-detect)
#   1   1    version  WIRE_VERSION (1); anything else is version skew
#   2   1    kind     FRAME_ORDER (0) order; FRAME_PRODUCE (2) is the
#                     TCP produce envelope (bridge/tcp.py) — same
#                     header so one validator covers both
#   3   1    flags    bit0 next present, bit1 prev present (the
#                     nullable POJO pointer fields, quirk Q9); bit2
#                     trace word present (ISSUE 12): the frame carries
#                     one trailing int64 — the deterministic per-order
#                     trace id (telemetry/dtrace.py) — and its length
#                     prefix is FRAME_SIZE_TRACED
#   4   4    length   total frame bytes (= FRAME_SIZE for kind 0, or
#                     FRAME_SIZE_TRACED when flags bit2 is set) — the
#                     length prefix; a mismatch is rejected before
#                     any field is read, so a corrupt/oversized prefix
#                     can never walk the decoder off the buffer
#   8   64   action oid aid sid price size next prev, int64 each
#   72  8    trace id (int64) — ONLY when flags bit2 is set
#
# The admitted VALUE is unchanged: a binary frame decodes to the exact
# OrderMsg its JSON twin parses to, and the broker stores the canonical
# Jackson line (order_json) — durable logs, oracle replay and MatchOut
# bytes cannot tell which encoding carried a record. The trace word is
# transport-additive the same way the (epoch, out_seq) stamps are: it
# rides ALONGSIDE the record (broker.Record.tid), never inside the
# stored value, so tracing on/off cannot change a durable byte.

WIRE_MAGIC = 0xB1
WIRE_VERSION = 1
FRAME_ORDER = 0
FRAME_PRODUCE = 2      # TCP request envelope kind (bridge/tcp.py)
FLAG_NEXT = 1
FLAG_PREV = 2
FLAG_TID = 4           # trace word present (+8 byte frame)
_FRAME = struct.Struct("<BBBBI8q")
FRAME_SIZE = _FRAME.size          # 72
_TID_WORD = struct.Struct("<q")
FRAME_SIZE_TRACED = FRAME_SIZE + _TID_WORD.size   # 80
_FRAME_HDR = struct.Struct("<BBBBI")


class WireFrameError(ValueError):
    """A binary frame failed validation. `reason` is one of
    "truncated", "bad_magic", "version_skew", "bad_kind",
    "bad_length"; `code` is always REJ_MALFORMED — a broken frame is
    dropped before the engine exactly like broken JSON (rej table
    code 6), never silently skipped."""

    def __init__(self, reason: str, detail: str) -> None:
        super().__init__(f"bad wire frame ({reason}): {detail}")
        self.reason = reason
        self.code = REJ_MALFORMED


def encode_frame(m: "OrderMsg", tid: Optional[int] = None) -> bytes:
    """One OrderMsg -> one 72-byte binary frame (80 with a trace id:
    flags bit2 + trailing int64). Values beyond int64 raise
    (struct.error is a ValueError subclass here via OverflowError
    semantics) — callers stay on the JSON path, which carries arbitrary
    ints."""
    flags = (FLAG_NEXT if m.next is not None else 0) | \
            (FLAG_PREV if m.prev is not None else 0)
    length, tail = FRAME_SIZE, b""
    if tid is not None:
        flags |= FLAG_TID
        length = FRAME_SIZE_TRACED
        tail = _TID_WORD.pack(tid)
    return _FRAME.pack(WIRE_MAGIC, WIRE_VERSION, FRAME_ORDER, flags,
                       length, m.action, m.oid, m.aid, m.sid,
                       m.price, m.size,
                       0 if m.next is None else m.next,
                       0 if m.prev is None else m.prev) + tail


def encode_frames(msgs, tids=None) -> bytes:
    """OrderMsg sequence -> one contiguous buffer of binary frames.
    `tids` (parallel sequence, None entries allowed) attaches the
    per-order trace words."""
    if tids is None:
        return b"".join(encode_frame(m) for m in msgs)
    return b"".join(encode_frame(m, t) for m, t in zip(msgs, tids))


def _check_frame_header(buf, off: int, remaining: int) -> int:
    """Validate one frame header at `off`; returns the frame length.
    Raises WireFrameError exactly like the native validator
    (kme_front.cpp) — same checks, same order, same reasons."""
    if remaining < _FRAME_HDR.size:
        raise WireFrameError(
            "truncated", f"{remaining} byte(s) at offset {off}, header "
            f"needs {_FRAME_HDR.size}")
    magic, version, kind, flags, length = _FRAME_HDR.unpack_from(
        buf, off)
    if magic != WIRE_MAGIC:
        raise WireFrameError(
            "bad_magic", f"0x{magic:02X} at offset {off} "
            f"(expected 0x{WIRE_MAGIC:02X})")
    if version != WIRE_VERSION:
        raise WireFrameError(
            "version_skew", f"version {version} at offset {off} "
            f"(this build speaks {WIRE_VERSION})")
    if kind != FRAME_ORDER:
        raise WireFrameError(
            "bad_kind", f"kind {kind} at offset {off} (expected "
            f"{FRAME_ORDER})")
    expected = FRAME_SIZE_TRACED if flags & FLAG_TID else FRAME_SIZE
    if length != expected:
        raise WireFrameError(
            "bad_length", f"length prefix {length} at offset {off} "
            f"(order frames are exactly {expected} bytes with these "
            f"flags)")
    if remaining < expected:
        raise WireFrameError(
            "truncated", f"{remaining} byte(s) at offset {off}, frame "
            f"declares {expected}")
    return expected


def decode_frame_tid(buf, off: int = 0
                     ) -> Tuple["OrderMsg", Optional[int], int]:
    """Decode one frame at `off`; returns (msg, trace_id_or_None,
    next_offset). THE Python authority for the frame format — the
    native acceptor (kme_front.cpp) and the numpy batch path
    (parse_frames) are pinned byte-exact against it by
    tests/test_wire_fuzz.py."""
    flen = _check_frame_header(buf, off, len(buf) - off)
    (_m, _v, _k, flags, _len, action, oid, aid, sid, price, size,
     nxt, prv) = _FRAME.unpack_from(buf, off)
    tid = (_TID_WORD.unpack_from(buf, off + FRAME_SIZE)[0]
           if flags & FLAG_TID else None)
    return OrderMsg(action, oid, aid, sid, price, size,
                    nxt if flags & FLAG_NEXT else None,
                    prv if flags & FLAG_PREV else None), tid, off + flen


def decode_frame(buf, off: int = 0) -> Tuple["OrderMsg", int]:
    """decode_frame_tid without the trace word (the pre-ISSUE-12
    shape; existing callers keep their two-tuple)."""
    m, _tid, nxt = decode_frame_tid(buf, off)
    return m, nxt


def decode_frames(buf) -> List["OrderMsg"]:
    """Whole-buffer decode through the per-frame authority."""
    out: List[OrderMsg] = []
    off = 0
    while off < len(buf):
        m, off = decode_frame(buf, off)
        out.append(m)
    return out


def decode_frames_tid(buf) -> List[Tuple["OrderMsg", Optional[int]]]:
    """Whole-buffer decode keeping the per-frame trace words."""
    out: List[Tuple[OrderMsg, Optional[int]]] = []
    off = 0
    while off < len(buf):
        m, tid, off = decode_frame_tid(buf, off)
        out.append((m, tid))
    return out


def is_binary_frame(first_byte: int) -> bool:
    """The per-message encoding negotiation: 0xB1 opens a binary
    frame, anything else (in practice '{' = 0x7B) is JSON."""
    return first_byte == WIRE_MAGIC

# ---------------------------------------------------------------------------
# Reject reason codes (wire-level / journal-level).
#
# The reference collapses every refusal into an action=7 REJECT echo with
# no cause; the device engines DO know why (the rej_* metric counters of
# engine/lanes.py / engine/seq.py are incremented per cause). This table
# names the per-order code the sessions surface alongside reconstruction
# (`last_reasons`), the flight-recorder journal records, and the opt-in
# "REJ"-keyed MatchOut annotation carries. The default IN/OUT stream is
# byte-pinned against the reference and never changes; reason codes ride
# in ADDITIVE records/journals only.
#
#   code  name             meaning
#   0     ok               not rejected
#   1     rej_capacity     device capacity envelope (book slots / fill
#                          buffer) refused the order
#   2     rej_risk         margin/balance check or fixed-mode validation
#                          (price domain, missing book) failed
#   3     rej_cancel       cancel target unknown to the book / not owned
#   4     rej_unroutable   host router resolved the reject (unknown-oid
#                          cancel, unmapped payout/remove, bad action)
#   5     rej_barrier      payout/remove barrier refused on device
#   6     rej_malformed    record dropped before the engine (serde)
#   7     rej_other        non-trade device op refused (create/transfer/
#                          add_symbol)
#   8     rej_unspecified  host engines (native/oracle) report no cause
#   9     rej_overload     bounded ingress queue shed the record before
#                          the engine (broker backpressure — the
#                          producer saw BrokerOverload and should back
#                          off and retry; never silently dropped)
REJ_NONE = 0
REJ_CAPACITY = 1
REJ_RISK = 2
REJ_CANCEL = 3
REJ_UNROUTABLE = 4
REJ_BARRIER = 5
REJ_MALFORMED = 6
REJ_OTHER = 7
REJ_UNSPECIFIED = 8
REJ_OVERLOAD = 9

REJ_NAMES = {
    REJ_NONE: "ok",
    REJ_CAPACITY: "rej_capacity",
    REJ_RISK: "rej_risk",
    REJ_CANCEL: "rej_cancel",
    REJ_UNROUTABLE: "rej_unroutable",
    REJ_BARRIER: "rej_barrier",
    REJ_MALFORMED: "rej_malformed",
    REJ_OTHER: "rej_other",
    REJ_UNSPECIFIED: "rej_unspecified",
    REJ_OVERLOAD: "rej_overload",
}


def rej_name(code: int) -> str:
    return REJ_NAMES.get(code, f"rej_{code}")


def reason_for_reject(action: int) -> int:
    """Heuristic reason for engines that report no per-order cause
    (native/oracle): classify by the rejected wire action. Device
    sessions report exact codes instead (runtime/session.py)."""
    if action in (2, 3):          # BUY / SELL
        return REJ_RISK
    if action == 4:               # CANCEL
        return REJ_CANCEL
    if action in (1, 200):        # REMOVE_SYMBOL / PAYOUT
        return REJ_BARRIER
    if action in (0, 100, 101):   # ADD_SYMBOL / CREATE / TRANSFER
        return REJ_OTHER
    return REJ_UNSPECIFIED


def reject_reason_codes(nmsg, msg_index, act, ok, cap_reject, host_rejects):
    """Vectorized per-message reason codes from one device batch's
    routing + results: host-resolved rejects are unroutable; a device
    not-ok is capacity when the cap flag fired, else classified by the
    internal lane act (1/2 trade -> risk, 3 cancel, 7/8/9 barrier,
    other device ops -> other). Returns a (nmsg,) uint8 array."""
    import numpy as np

    reasons = np.zeros(nmsg, np.uint8)
    if host_rejects:
        reasons[list(host_rejects)] = REJ_UNROUTABLE
    if len(msg_index):
        act = np.asarray(act)
        bad = ~np.asarray(ok, bool)
        by_act = np.where(
            (act == 1) | (act == 2), REJ_RISK,
            np.where(act == 3, REJ_CANCEL,
                     np.where((act >= 7) & (act <= 9), REJ_BARRIER,
                              REJ_OTHER)))
        r = np.where(np.asarray(cap_reject, bool), REJ_CAPACITY,
                     by_act).astype(np.uint8)
        mi = np.asarray(msg_index)
        reasons[mi[bad]] = r[bad]
    return reasons


def rej_record_json(oid: int, aid: int, code: int,
                    detail: Optional[dict] = None) -> str:
    """The value of an opt-in "REJ"-keyed MatchOut annotation record
    (kme-serve --annotate-rejects): compact JSON naming the per-order
    reject cause. ADDITIVE — consumers keyed on IN/OUT are unaffected
    and the default stream stays byte-identical to the reference.

    `detail` appends extra keys in sorted order (rej_overload rows
    carry the observed backlog, active threshold, degradation state and
    backoff hint — the shed never reached the engine, so this record is
    its only durable trace). Without detail the bytes are unchanged
    from every prior release."""
    base = (f'{{"oid":{oid},"aid":{aid},"reason":{code},'
            f'"rej":"{rej_name(code)}"}}')
    if not detail:
        return base
    extra = ",".join(
        f'"{k}":{json.dumps(detail[k], separators=(",", ":"))}'
        for k in sorted(detail))
    return base[:-1] + "," + extra + "}"


@dataclasses.dataclass
class OrderMsg:
    """One wire message. Mirrors the reference Order POJO
    (KProcessor.java:448-475)."""

    action: int = 0
    oid: int = 0
    aid: int = 0
    sid: int = 0
    price: int = 0
    size: int = 0
    next: Optional[int] = None
    prev: Optional[int] = None

    def copy(self) -> "OrderMsg":
        return dataclasses.replace(self)


def parse_order(data: bytes | str) -> OrderMsg:
    """Parse an input JSON message the way Jackson does on the reference
    POJO (KProcessor.java:448-475): creator-bound value fields default to
    0 when absent; the public `next`/`prev` fields are bound by name when
    present (null/absent -> None)."""
    obj = json.loads(data)
    if not isinstance(obj, dict):
        raise ValueError(f"order message must be a JSON object, got {type(obj)}")
    kw = {}
    for f in _FIELDS:
        v = obj.get(f, 0)
        if v is None:
            v = 0
        kw[f] = _as_int(f, v)
    msg = OrderMsg(**kw)
    for f in ("next", "prev"):
        v = obj.get(f)
        if v is not None:
            setattr(msg, f, _as_int(f, v))
    return msg


def _as_int(field: str, v) -> int:
    if not isinstance(v, int) or isinstance(v, bool):
        # Jackson would coerce or throw; we accept exact ints only
        # (floats with integral value are coerced like Jackson does).
        if isinstance(v, float) and v.is_integer():
            return int(v)
        raise ValueError(f"field {field!r} must be an integer, got {v!r}")
    return v


def order_json(action: int, oid, aid, sid, price, size,
               next: Optional[int] = None,
               prev: Optional[int] = None) -> str:
    """THE Jackson wire template (compact, declaration field order,
    next/prev always present — KProcessor.java:488). Every serializer in
    the tree — dumps_order on OrderMsg objects and the session's bulk
    scalar reconstruction (runtime/session.py) — goes through this one
    function, so a format change cannot fork the serving path from the
    record path (the hazard is also pinned by tests/test_lanes_engine's
    process/process_wire equivalence check)."""
    nxt = "null" if next is None else str(next)
    prv = "null" if prev is None else str(prev)
    return (
        f'{{"action":{action},"oid":{oid},"aid":{aid},"sid":{sid},'
        f'"price":{price},"size":{size},"next":{nxt},"prev":{prv}}}'
    )


def dumps_order(o: OrderMsg) -> str:
    """Serialize exactly like Jackson on the reference POJO: compact,
    declaration field order, next/prev always present (KProcessor.java:488)."""
    return order_json(o.action, o.oid, o.aid, o.sid, o.price, o.size,
                      o.next, o.prev)


class WireBatch:
    """Columnar view of a message batch: the zero-Python-loop input
    format of the serving/bench fast path (SeqSession.process_wire_buffer
    consumes it directly — router and reconstructor read the columns, so
    no per-message attribute walk ever runs on the hot path).

    Columns (numpy): action/oid/aid/sid/price/size/next/prev int64,
    hnext/hprev uint8 (1 = pointer present — Jackson binds next/prev
    from input too, see module docstring), plus tid int64 / htid uint8
    for the additive trace word (zeros when no frame carried one).
    Values beyond int64 cannot be represented; builders raise
    OverflowError and callers stay on the OrderMsg-list path (which
    carries arbitrary ints)."""

    __slots__ = ("n", "action", "oid", "aid", "sid", "price", "size",
                 "next", "prev", "hnext", "hprev", "tid", "htid",
                 "_msgs")

    _COLS = ("action", "oid", "aid", "sid", "price", "size", "next",
             "prev")

    def __init__(self, n, cols, hnext, hprev, msgs=None, tid=None,
                 htid=None):
        self.n = n
        for f, v in zip(self._COLS, cols):
            setattr(self, f, v)
        self.hnext = hnext
        self.hprev = hprev
        if tid is None or htid is None:
            import numpy as np

            tid = np.zeros(n, np.int64)
            htid = np.zeros(n, np.uint8)
        self.tid = tid
        self.htid = htid
        self._msgs = msgs

    def record_tid(self, i: int) -> Optional[int]:
        """The trace word carried by row `i`, or None."""
        return int(self.tid[i]) if self.htid[i] else None

    def __len__(self) -> int:
        return self.n

    @classmethod
    def from_msgs(cls, msgs) -> "WireBatch":
        """OrderMsg sequence -> columns (ONE attribute walk; raises
        OverflowError on values beyond int64)."""
        import numpy as np

        n = len(msgs)
        cols = [np.fromiter((m.action for m in msgs), np.int64, n),
                np.fromiter((m.oid for m in msgs), np.int64, n),
                np.fromiter((m.aid for m in msgs), np.int64, n),
                np.fromiter((m.sid for m in msgs), np.int64, n),
                np.fromiter((m.price for m in msgs), np.int64, n),
                np.fromiter((m.size for m in msgs), np.int64, n),
                np.fromiter((0 if m.next is None else m.next
                             for m in msgs), np.int64, n),
                np.fromiter((0 if m.prev is None else m.prev
                             for m in msgs), np.int64, n)]
        hnext = np.fromiter((m.next is not None for m in msgs),
                            np.uint8, n)
        hprev = np.fromiter((m.prev is not None for m in msgs),
                            np.uint8, n)
        return cls(n, cols, hnext, hprev,
                   msgs if isinstance(msgs, list) else list(msgs))

    @classmethod
    def parse_buffer(cls, buf: bytes) -> "WireBatch":
        """Newline-separated order JSON -> columns, via the native
        parser (kme_wire.cpp kme_parse_*) when available; any line
        outside its integer/null subset re-parses the WHOLE buffer
        through parse_order so coercions and error behavior are exactly
        the Python authority's."""
        import numpy as np

        if not buf:
            # empty payload = zero messages (the native column pointers
            # are unallocated at n == 0)
            return cls(0, [np.zeros(0, np.int64) for _ in range(8)],
                       np.zeros(0, np.uint8), np.zeros(0, np.uint8), [])
        lib = None
        try:
            from kme_tpu.native import load_library

            lib = load_library()
        except ImportError:  # pragma: no cover - packaging edge
            pass
        if lib is not None:
            h = lib.kme_parse_new()
            try:
                rc = lib.kme_parse_lines(h, buf, len(buf))
                if rc >= 0:
                    n = int(rc)
                    cols = [np.ctypeslib.as_array(
                        lib.kme_parse_col(h, i), (max(n, 1),))[:n].copy()
                        for i in range(8)]
                    hnext = np.ctypeslib.as_array(
                        lib.kme_parse_hnext(h), (max(n, 1),))[:n].copy()
                    hprev = np.ctypeslib.as_array(
                        lib.kme_parse_hprev(h), (max(n, 1),))[:n].copy()
                    return cls(n, cols, hnext, hprev)
            finally:
                lib.kme_parse_free(h)
        msgs = [parse_order(ln) for ln in buf.split(b"\n") if ln]
        return cls.from_msgs(msgs)

    @classmethod
    def _empty(cls) -> "WireBatch":
        import numpy as np

        return cls(0, [np.zeros(0, np.int64) for _ in range(8)],
                   np.zeros(0, np.uint8), np.zeros(0, np.uint8), [])

    @classmethod
    def parse_frames(cls, buf: bytes) -> "WireBatch":
        """Concatenated binary order frames -> columns, via the native
        decoder (kme_wire.cpp kme_parse_frames) when available, else a
        vectorized numpy view of the same fixed-width layout. Raises
        WireFrameError (always through the per-frame Python authority,
        so native and fallback surface identical errors) on the first
        invalid frame."""
        if not buf:
            return cls._empty()
        r = _parse_frames_native(buf, emit=False)
        if r is not None:
            return r[0]
        return cls._parse_frames_py(buf)

    @classmethod
    def _parse_frames_py(cls, buf: bytes) -> "WireBatch":
        """Pure-numpy frame decode: one frombuffer over the fixed
        72-byte records, vectorized validation; a traced (80-byte)
        frame anywhere drops to the variable-stride authority walk,
        and ANY invalidity re-walks the buffer through decode_frame so
        the raised error is exactly the authority's (first bad frame,
        field-priority order)."""
        import numpy as np

        nf, tail = divmod(len(buf), FRAME_SIZE)
        dt = np.dtype([("hdr", "<u1", (4,)), ("length", "<u4"),
                       ("v", "<i8", (8,))])
        a = np.frombuffer(buf, dt, count=nf)
        hdr = a["hdr"]
        bad = ((hdr[:, 0] != WIRE_MAGIC) | (hdr[:, 1] != WIRE_VERSION)
               | (hdr[:, 2] != FRAME_ORDER)
               | (a["length"] != FRAME_SIZE))
        if tail or bad.any() or (hdr[:, 3] & FLAG_TID).any():
            # traced frames shift every subsequent header, so the
            # fixed-stride view above is meaningless the moment one
            # appears. A uniformly-traced buffer (loadgen/bench stamp
            # EVERY frame) re-views at the 80-byte stride and stays
            # vectorized; only mixed/invalid buffers pay the walk,
            # which is the single authority for the error surface
            wb = cls._parse_frames_traced_py(buf)
            if wb is not None:
                return wb
            return cls._parse_frames_walk(buf)
        v = a["v"]
        cols = [np.ascontiguousarray(v[:, i]) for i in range(8)]
        flags = hdr[:, 3]
        return cls(nf, cols, (flags & 1).astype(np.uint8),
                   ((flags >> 1) & 1).astype(np.uint8))

    @classmethod
    def _parse_frames_traced_py(cls, buf: bytes
                                ) -> Optional["WireBatch"]:
        """Vectorized decode for a buffer of UNIFORM 80-byte traced
        frames (every header valid, every frame FLAG_TID): one
        frombuffer at the wider stride, same checks as the untraced
        fast path. Returns None — caller falls to the authority walk —
        for anything mixed, torn, or invalid."""
        import numpy as np

        nf, tail = divmod(len(buf), FRAME_SIZE_TRACED)
        if tail or nf == 0:
            return None
        dt = np.dtype([("hdr", "<u1", (4,)), ("length", "<u4"),
                       ("v", "<i8", (8,)), ("tid", "<i8")])
        a = np.frombuffer(buf, dt, count=nf)
        hdr = a["hdr"]
        bad = ((hdr[:, 0] != WIRE_MAGIC)
               | (hdr[:, 1] != WIRE_VERSION)
               | (hdr[:, 2] != FRAME_ORDER)
               | (a["length"] != FRAME_SIZE_TRACED)
               | ((hdr[:, 3] & FLAG_TID) == 0))
        if bad.any():
            return None
        v = a["v"]
        cols = [np.ascontiguousarray(v[:, i]) for i in range(8)]
        flags = hdr[:, 3]
        return cls(nf, cols, (flags & 1).astype(np.uint8),
                   ((flags >> 1) & 1).astype(np.uint8),
                   tid=np.ascontiguousarray(a["tid"]),
                   htid=np.ones(nf, np.uint8))

    @classmethod
    def _parse_frames_walk(cls, buf: bytes) -> "WireBatch":
        """Per-frame authority walk (decode_frame_tid): handles mixed
        72/80-byte buffers and raises the authoritative WireFrameError
        at the first bad frame."""
        import numpy as np

        pairs = decode_frames_tid(buf)
        wb = cls.from_msgs([m for m, _t in pairs])
        n = len(pairs)
        wb.tid = np.fromiter((0 if t is None else t
                              for _m, t in pairs), np.int64, n)
        wb.htid = np.fromiter((t is not None for _m, t in pairs),
                              np.uint8, n)
        return wb

    def msgs(self) -> list:
        """Materialize the OrderMsg view (lazily, for oracle/judge
        paths; the fast path never calls this)."""
        if self._msgs is None:
            act, oid, aid = self.action, self.oid, self.aid
            sid, pr, sz = self.sid, self.price, self.size
            nx, pv = self.next, self.prev
            hn, hp = self.hnext, self.hprev
            self._msgs = [
                OrderMsg(int(act[i]), int(oid[i]), int(aid[i]),
                         int(sid[i]), int(pr[i]), int(sz[i]),
                         int(nx[i]) if hn[i] else None,
                         int(pv[i]) if hp[i] else None)
                for i in range(self.n)]
        return self._msgs


def _parse_frames_native(buf: bytes, emit: bool):
    """Native frame decode (+ optional canonical-JSON emission).
    Returns (WireBatch, values-or-None), or None when the native
    library is unavailable (callers fall back to numpy/Python).
    Validation failures re-raise through decode_frames so the error is
    byte-identical to the pure-Python path's."""
    try:
        from kme_tpu.native import load_library

        lib = load_library()
    except ImportError:  # pragma: no cover - packaging edge
        return None
    if lib is None:
        return None
    import ctypes

    import numpy as np

    h = lib.kme_parse_new()
    try:
        rc = lib.kme_parse_frames(h, buf, len(buf))
        if rc < 0:
            decode_frames(buf)  # raises the authoritative error
            raise AssertionError(
                "native rejected a buffer the authority accepts "
                f"(code {rc} at offset {lib.kme_parse_err_off(h)})")
        n = int(rc)
        if n == 0:
            return WireBatch._empty(), ([] if emit else None)
        cols = [np.ctypeslib.as_array(
            lib.kme_parse_col(h, i), (n,)).copy() for i in range(8)]
        hnext = np.ctypeslib.as_array(lib.kme_parse_hnext(h), (n,)).copy()
        hprev = np.ctypeslib.as_array(lib.kme_parse_hprev(h), (n,)).copy()
        tid = np.ctypeslib.as_array(lib.kme_parse_tid(h), (n,)).copy()
        htid = np.ctypeslib.as_array(lib.kme_parse_htid(h), (n,)).copy()
        wb = WireBatch(n, cols, hnext, hprev, tid=tid, htid=htid)
        values = None
        if emit:
            nbytes = int(lib.kme_parse_emit(h))
            raw = ctypes.string_at(lib.kme_parse_emit_buf(h), nbytes)
            off = np.ctypeslib.as_array(lib.kme_parse_emit_off(h),
                                        (n + 1,))
            values = [raw[off[i]:off[i + 1]].decode("ascii")
                      for i in range(n)]
        return wb, values
    finally:
        lib.kme_parse_free(h)


def batch_values(wb: "WireBatch") -> List[str]:
    """Canonical Jackson value line per row (order_json — the bytes
    the broker stores whatever encoding carried the record)."""
    act, oid, aid = wb.action, wb.oid, wb.aid
    sid, pr, sz = wb.sid, wb.price, wb.size
    nx, pv, hn, hp = wb.next, wb.prev, wb.hnext, wb.hprev
    return [order_json(int(act[i]), int(oid[i]), int(aid[i]),
                       int(sid[i]), int(pr[i]), int(sz[i]),
                       int(nx[i]) if hn[i] else None,
                       int(pv[i]) if hp[i] else None)
            for i in range(wb.n)]


def frames_to_values(buf: bytes) -> Tuple["WireBatch", List[str]]:
    """Binary produce path decode: concatenated frames -> (columns,
    canonical JSON value per record) without materializing per-record
    dicts. Native when available (kme_parse_frames + the pinned
    kme_parse_emit emitter, two C calls per batch); numpy + order_json
    otherwise. The values are byte-identical either way — the durable
    log cannot tell which encoding carried a record."""
    if not buf:
        return WireBatch._empty(), []
    r = _parse_frames_native(buf, emit=True)
    if r is not None:
        return r[0], r[1]
    wb = WireBatch._parse_frames_py(buf)
    return wb, batch_values(wb)


@dataclasses.dataclass(frozen=True)
class ProduceStamp:
    """The exactly-once produce stamp carried ALONGSIDE each MatchOut
    record (never inside the value — the visible `<key> <value>` stream
    stays byte-pinned against the reference, which shipped with Kafka's
    exactly-once path commented out, KProcessor.java:29).

    `epoch` is the producing leader's fencing token (bridge/lease.py —
    monotonic across incarnations and failovers); `out_seq` is the
    0-based position of the record in the deterministic output stream.
    Because the engine is deterministic, a crashed leader's replayed
    tail regenerates records with IDENTICAL stamps, which is exactly
    what lets the broker suppress them (bridge/broker.py idempotent
    produce) and consumers dedup defensively
    (bridge/consume.py DedupRing): duplicate detection needs no record
    hashing, only the cursor."""

    epoch: int
    out_seq: int


@dataclasses.dataclass(frozen=True)
class OutRecord:
    """One record on the output stream: key is "IN" (pre-processing echo,
    KProcessor.java:97) or "OUT" (result echo / fill event,
    KProcessor.java:124, 272-273)."""

    key: str
    value: OrderMsg

    def wire(self) -> str:
        """The `<key> <value>` line consumer.js:19 prints."""
        return f"{self.key} {dumps_order(self.value)}"


def wire_lines(records: Iterator[OutRecord]) -> Iterator[str]:
    for r in records:
        yield r.wire()
