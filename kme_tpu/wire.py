"""Wire schema: the reference's JSON Order message, byte-compatible.

The reference's serde is Jackson over a POJO with public fields declared in
the order action, oid, aid, sid, price, size, next, prev
(/root/reference/src/main/java/KProcessor.java:448-475), serialized with
`writeValueAsString(...).getBytes()` (KProcessor.java:488-490): compact JSON
(no spaces), fields in declaration order, `next`/`prev` always present
(null when unset — quirk Q9: the intrusive list pointers leak onto the
wire). Incoming messages are parsed by field name; missing fields default
to 0 / null (Jackson primitive defaults). Note Jackson binds `next`/`prev`
FROM input too — the @JsonCreator ctor covers the six value fields, and
the remaining public fields are bound by field access afterward — so a
message carrying non-null pointers (e.g. a replayed OUT echo) enters the
engine with them set, and a new-bucket rest stores them verbatim (only the
append path overwrites `prev`, KProcessor.java:217). Parsed faithfully
here; the device engine's compat envelope excludes such inputs (COMPAT.md).

`dumps_order` reproduces the exact byte stream so the reference's
consumer.js output is byte-identical under our engine.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterator, Optional

_FIELDS = ("action", "oid", "aid", "sid", "price", "size")


@dataclasses.dataclass
class OrderMsg:
    """One wire message. Mirrors the reference Order POJO
    (KProcessor.java:448-475)."""

    action: int = 0
    oid: int = 0
    aid: int = 0
    sid: int = 0
    price: int = 0
    size: int = 0
    next: Optional[int] = None
    prev: Optional[int] = None

    def copy(self) -> "OrderMsg":
        return dataclasses.replace(self)


def parse_order(data: bytes | str) -> OrderMsg:
    """Parse an input JSON message the way Jackson does on the reference
    POJO (KProcessor.java:448-475): creator-bound value fields default to
    0 when absent; the public `next`/`prev` fields are bound by name when
    present (null/absent -> None)."""
    obj = json.loads(data)
    if not isinstance(obj, dict):
        raise ValueError(f"order message must be a JSON object, got {type(obj)}")
    kw = {}
    for f in _FIELDS:
        v = obj.get(f, 0)
        if v is None:
            v = 0
        kw[f] = _as_int(f, v)
    msg = OrderMsg(**kw)
    for f in ("next", "prev"):
        v = obj.get(f)
        if v is not None:
            setattr(msg, f, _as_int(f, v))
    return msg


def _as_int(field: str, v) -> int:
    if not isinstance(v, int) or isinstance(v, bool):
        # Jackson would coerce or throw; we accept exact ints only
        # (floats with integral value are coerced like Jackson does).
        if isinstance(v, float) and v.is_integer():
            return int(v)
        raise ValueError(f"field {field!r} must be an integer, got {v!r}")
    return v


def order_json(action: int, oid, aid, sid, price, size,
               next: Optional[int] = None,
               prev: Optional[int] = None) -> str:
    """THE Jackson wire template (compact, declaration field order,
    next/prev always present — KProcessor.java:488). Every serializer in
    the tree — dumps_order on OrderMsg objects and the session's bulk
    scalar reconstruction (runtime/session.py) — goes through this one
    function, so a format change cannot fork the serving path from the
    record path (the hazard is also pinned by tests/test_lanes_engine's
    process/process_wire equivalence check)."""
    nxt = "null" if next is None else str(next)
    prv = "null" if prev is None else str(prev)
    return (
        f'{{"action":{action},"oid":{oid},"aid":{aid},"sid":{sid},'
        f'"price":{price},"size":{size},"next":{nxt},"prev":{prv}}}'
    )


def dumps_order(o: OrderMsg) -> str:
    """Serialize exactly like Jackson on the reference POJO: compact,
    declaration field order, next/prev always present (KProcessor.java:488)."""
    return order_json(o.action, o.oid, o.aid, o.sid, o.price, o.size,
                      o.next, o.prev)


@dataclasses.dataclass(frozen=True)
class OutRecord:
    """One record on the output stream: key is "IN" (pre-processing echo,
    KProcessor.java:97) or "OUT" (result echo / fill event,
    KProcessor.java:124, 272-273)."""

    key: str
    value: OrderMsg

    def wire(self) -> str:
        """The `<key> <value>` line consumer.js:19 prints."""
        return f"{self.key} {dumps_order(self.value)}"


def wire_lines(records: Iterator[OutRecord]) -> Iterator[str]:
    for r in records:
        yield r.wire()
