"""Feed snapshots: cold-start book state off the checkpoint machinery.

Two related artifacts share the name "snapshot" on the read path:

* the DURABLE deriver snapshot (`feed-%09d.json` in a checkpoint
  directory) — the deriver's restore-complete state at a MatchOut
  offset, written with the same atomic-rename + fsync + digest-verify
  + prune discipline as the engine checkpoints (runtime/checkpoint.py;
  the chaos `ckpt.torn` / `ckpt.bitflip` injection points fire here
  too, and the loader falls back past corrupt files the same way). A
  restarted `kme-feed` loads the newest valid one and replays the
  MatchOut tail from its offset — byte-identical frames come out, by
  deriver purity.

* the WIRE snapshot (`snapshot_frames`) — the SNAP_BEGIN / REFRESH
  depth images / SNAP_END sequence a subscriber receives on connect:
  the snapshot-then-deltas handover. The images carry each symbol's
  CURRENT per-symbol seq, and SNAP_END carries the `(group, epoch,
  out_seq)` watermark, so the subscriber knows exactly where the
  delta splice begins; every symbol the deriver has ever sequenced is
  included (empty books ship as empty images) so a late joiner's seq
  accounting starts aligned for all of them.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import List, Optional, Tuple

from kme_tpu.feed import frames as ff
from kme_tpu.feed.derive import FeedDeriver
from kme_tpu.runtime.checkpoint import (_fsync_dir, _post_write_faults,
                                        _prune)

_FEED_RE = re.compile(r"^feed-(\d+)\.json$")


def feed_snapshot_path(ckpt_dir: str, offset: int) -> str:
    return os.path.join(ckpt_dir, f"feed-{offset:09d}.json")


def _state_digest(state: dict) -> str:
    blob = json.dumps(state, sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()


def save_feed_snapshot(ckpt_dir: str, deriver: FeedDeriver, offset: int,
                       keep: Optional[int] = None) -> str:
    """Persist the deriver's state at MatchOut `offset` (the NEXT
    offset to consume). Atomic: tmp write + fsync + rename + dir
    fsync, then the chaos injection points and the prune, exactly like
    _atomic_savez."""
    os.makedirs(ckpt_dir, exist_ok=True)
    state = deriver.state()
    doc = {"version": 1, "kind": "feed", "offset": int(offset),
           "digest": _state_digest(state), "state": state}
    path = feed_snapshot_path(ckpt_dir, offset)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, separators=(",", ":"))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(ckpt_dir)
    _post_write_faults(path)
    _prune(ckpt_dir, _FEED_RE, keep=keep)
    return path


def list_feed_snapshots(ckpt_dir: str) -> List[Tuple[int, str]]:
    """(offset, path) pairs, newest first."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        m = _FEED_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(ckpt_dir, name)))
    out.sort(reverse=True)
    return out


def _load_one(path: str) -> Tuple[int, dict]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("kind") != "feed":
        raise ValueError(f"{path}: not a feed snapshot")
    state = doc["state"]
    got = _state_digest(state)
    want = doc.get("digest")
    if want and got != want:
        raise ValueError(
            f"content digest mismatch in {path} (stored {want[:12]}…, "
            f"computed {got[:12]}…): corrupt snapshot")
    return int(doc["offset"]), state


def load_feed_snapshot(ckpt_dir: str
                       ) -> Optional[Tuple[int, FeedDeriver]]:
    """Newest valid (offset, restored deriver), falling back past
    torn/corrupt files like the engine checkpoint loader; None when no
    usable snapshot exists."""
    for _off, path in list_feed_snapshots(ckpt_dir):
        try:
            offset, state = _load_one(path)
        except (ValueError, KeyError, OSError):
            continue
        return offset, FeedDeriver.from_state(state)
    return None


def snapshot_frames(deriver: FeedDeriver, sids=None) -> bytes:
    """The wire handover: SNAP_BEGIN, one REFRESH depth image per
    symbol (current seq — images never consume new sequence numbers,
    so serving a snapshot cannot fork the frame stream), SNAP_END with
    the crc of the image bytes and the deriver's source watermark.
    `sids` restricts to a subscription subset; None means every symbol
    the deriver has ever sequenced."""
    ep, sq = deriver.watermark
    known = sorted(deriver._seqs)
    if sids is not None:
        want = set(sids)
        known = [s for s in known if s in want]
    images = b""
    for sid in known:
        bids, asks = deriver.book.depth(sid, 0)
        images += ff.encode_depth(deriver.group,
                                  deriver._seqs.get(sid, 0), ep, sq,
                                  sid, bids, asks, refresh=True)
    return (ff.encode_snap_begin(deriver.group, ep, sq, len(known))
            + images
            + ff.encode_snap_end(deriver.group, ep, sq, len(known),
                                 images))
