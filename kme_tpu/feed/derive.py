"""Deterministic book-delta derivation from the MatchOut stream.

`FeedDeriver` is a PURE function of the MatchOut record sequence: no
clock, no RNG, no I/O (enforced by the kme-lint FEED_SCOPES table),
so any two derivers at the same `(group, out_seq)` watermark emit
byte-identical frames — which is what makes feed failover trivial: a
promoted leader's deriver regenerates the exact frames the dead one
would have sent, and the consumer-side DedupRing plus per-symbol
sequence numbers absorb the overlap.

The deriver never talks to the engine. It reconstructs resting-order
state purely from the `<key> <value>` output records, using invariants
of the reference output shape (oracle/engine.py is the executable
spec):

  * every input message produces `IN <echo>`, zero or more fill pairs
    `OUT <maker>` / `OUT <taker>` (actions SOLD/BOUGHT, maker first,
    maker fill price always 0), then exactly one `OUT <result>` echo
    whose action is the ORIGINAL action on success or REJECT on
    failure. A result echo can therefore never carry BOUGHT/SOLD —
    those actions mark fill events unambiguously.
  * fills alternate maker (even position) / taker (odd position)
    within a message; the IN record resets the parity. The maker fill
    reduces the resting order `oid` by the fill size (Java int
    arithmetic) and the engine deletes it at exactly zero; the taker
    fill never touches the book (the taker is the in-flight message).
  * a BUY/SELL result echo with size != 0 rested exactly `size` at
    (sid, action, price) — tryMatch returns taker.size == 0, so a
    non-zero echo size is equivalent to "the residual rested". A
    duplicate oid overwrites the stored order, like the store does.
  * a CANCEL success echo removed `oid` from the store.
  * a REMOVE_SYMBOL or PAYOUT success echo wiped every resting order
    with abs(sid) == abs(echo.sid) (vacuous under java compat, where
    removal only succeeds on empty books; exact in fixed mode).
  * REJECT / ADD_SYMBOL / CREATE_BALANCE / TRANSFER echoes never
    touch a book. The capacity-envelope rollback emits only
    [IN, OUT REJECT], so it needs no special case.

Frames are sequenced PER SYMBOL (frames.py) and emitted in a sorted,
restore-invariant order, so a deriver restored from a feed snapshot
continues the exact byte stream the original would have produced.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Set, Tuple

from kme_tpu import opcodes as op
from kme_tpu.feed import frames as ff
from kme_tpu.feed.frames import FeedFrame, decode_feed
from kme_tpu.oracle import javalong as jl
from kme_tpu.wire import OrderMsg, parse_order

SIDE_BUY = 0
SIDE_SELL = 1

# resting-order tuple indices (oid -> (sid, side, price, size))
_R_SID, _R_SIDE, _R_PRICE, _R_SIZE = range(4)

_EMPTY_TOB = (0, 0, 0, 0)


class BookState:
    """Aggregated price levels: (sid, side) -> {price: total_size}.
    Levels are deleted at a total of exactly 0 (Java int sums can pass
    through 0 with negative-size java-mode orders; the engine's store
    view and this one agree because both apply the same arithmetic)."""

    def __init__(self) -> None:
        self.levels: Dict[Tuple[int, int], Dict[int, int]] = {}

    def set_level(self, sid: int, side: int, price: int,
                  size: int) -> None:
        key = (sid, side)
        if size == 0:
            lv = self.levels.get(key)
            if lv is not None:
                lv.pop(price, None)
                if not lv:
                    del self.levels[key]
            return
        self.levels.setdefault(key, {})[price] = size

    def get_level(self, sid: int, side: int, price: int) -> int:
        return self.levels.get((sid, side), {}).get(price, 0)

    def tob(self, sid: int) -> Tuple[int, int, int, int]:
        """(bid_price, bid_size, ask_price, ask_size); size 0 = side
        empty (price then 0). Best bid = highest buy price, best ask =
        lowest sell price."""
        bids = self.levels.get((sid, SIDE_BUY))
        asks = self.levels.get((sid, SIDE_SELL))
        bp = bs = ap = asz = 0
        if bids:
            bp = max(bids)
            bs = bids[bp]
        if asks:
            ap = min(asks)
            asz = asks[ap]
        return (bp, bs, ap, asz)

    def depth(self, sid: int, n: int = 0
              ) -> Tuple[List[Tuple[int, int]], List[Tuple[int, int]]]:
        """(bids, asks) as (price, size) lists, best price first;
        n = 0 returns the full book."""
        bids = sorted(self.levels.get((sid, SIDE_BUY), {}).items(),
                      key=lambda kv: -kv[0])
        asks = sorted(self.levels.get((sid, SIDE_SELL), {}).items())
        if n:
            bids, asks = bids[:n], asks[:n]
        return bids, asks

    def sids(self) -> List[int]:
        return sorted({sid for sid, _side in self.levels})


def canonical_books(book) -> bytes:
    """Canonical byte encoding of a book state (BookState or a raw
    levels dict): one sorted `sid side price size` line per level.
    THE byte-exactness comparator — deriver, subscribers and the
    oracle aggregate are all reduced to this before comparison, at
    every depth (it IS the full depth)."""
    levels = book.levels if isinstance(book, BookState) else book
    rows = []
    for (sid, side), lv in levels.items():
        for price, size in lv.items():
            if size != 0:
                rows.append((sid, side, price, size))
    rows.sort()
    return "\n".join(f"{s} {d} {p} {z}" for s, d, p, z in rows).encode()


def books_from_oracle(engine) -> Dict[Tuple[int, int], Dict[int, int]]:
    """Aggregate an OracleEngine's resting-order store into the
    (sid, side) -> {price: size} level view — the independent ground
    truth the deriver is pinned against (it sums the store directly,
    never the MatchOut stream)."""
    levels: Dict[Tuple[int, int], Dict[int, int]] = {}
    for o in engine.orders.values():
        side = SIDE_SELL if o.action == op.SELL else SIDE_BUY
        lv = levels.setdefault((o.sid, side), {})
        lv[o.price] = lv.get(o.price, 0) + o.size
    for key in [k for k, lv in levels.items()
                if not any(v != 0 for v in lv.values())]:
        del levels[key]
    for lv in levels.values():
        for price in [p for p, v in lv.items() if v == 0]:
            del lv[price]
    return levels


class FeedDeriver:
    """Incremental MatchOut -> feed-frame derivation for one group.

    depth_every > 0 additionally emits an advisory top-`depth_levels`
    depth frame for every touched symbol each `depth_every` input
    messages — periodic by MESSAGE COUNT, never by clock, so the
    emission schedule replays identically."""

    def __init__(self, group: int = 0, depth_every: int = 0,
                 depth_levels: int = 8) -> None:
        self.group = int(group)
        self.depth_every = int(depth_every)
        self.depth_levels = int(depth_levels)
        self.book = BookState()
        # oid -> (sid, side, price, size): mirror of the engine's
        # resting-order store, rebuilt purely from output records
        self.resting: Dict[int, Tuple[int, int, int, int]] = {}
        self._seqs: Dict[int, int] = {}      # sid -> last seq assigned
        self._tob: Dict[int, Tuple[int, int, int, int]] = {}
        self._fills = 0                      # fill parity in this message
        self.groups_seen = 0                 # input messages (IN records)
        self._dirty_depth: Set[int] = set()
        self.watermark = (-1, -1)            # (src_epoch, src_seq)
        self.frames_out = 0

    # -- frame emission -------------------------------------------------

    def _next_seq(self, sid: int) -> int:
        seq = self._seqs.get(sid, 0) + 1
        self._seqs[sid] = seq
        self.frames_out += 1
        return seq

    def _frame(self, raw: bytes) -> FeedFrame:
        f, _ = decode_feed(raw)
        return f

    def _emit_delta(self, out: List[FeedFrame], sid: int, side: int,
                    price: int, size: int) -> None:
        ep, sq = self.watermark
        out.append(self._frame(ff.encode_delta(
            self.group, self._next_seq(sid), ep, sq, sid, side, price,
            size)))

    def _emit_tob(self, out: List[FeedFrame], sid: int) -> None:
        view = self.book.tob(sid)
        if view == self._tob.get(sid, _EMPTY_TOB):
            return
        self._tob[sid] = view
        ep, sq = self.watermark
        out.append(self._frame(ff.encode_tob(
            self.group, self._next_seq(sid), ep, sq, sid, *view)))

    def _emit_depth(self, out: List[FeedFrame], sid: int,
                    refresh: bool = False) -> None:
        bids, asks = self.book.depth(
            sid, 0 if refresh else self.depth_levels)
        ep, sq = self.watermark
        out.append(self._frame(ff.encode_depth(
            self.group, self._next_seq(sid), ep, sq, sid, bids, asks,
            refresh=refresh)))

    # -- book mutation --------------------------------------------------

    def _level_add(self, sid: int, side: int, price: int, delta: int,
                   touched: Dict[Tuple[int, int, int], int]) -> None:
        """Apply a signed size delta to a level, remembering the
        pre-record total on first touch so the record's net effect is
        emitted once per level."""
        pre = self.book.get_level(sid, side, price)
        tkey = (sid, side, price)
        if tkey not in touched:
            touched[tkey] = pre
        self.book.set_level(sid, side, price, pre + delta)

    def _drop_resting(self, oid: int,
                      touched: Dict[Tuple[int, int, int], int]) -> None:
        r = self.resting.pop(oid, None)
        if r is not None and r[_R_SIZE] != 0:
            self._level_add(r[_R_SID], r[_R_SIDE], r[_R_PRICE],
                            -r[_R_SIZE], touched)

    def _apply_out(self, m: OrderMsg,
                   touched: Dict[Tuple[int, int, int], int]) -> None:
        a = m.action
        if a in (op.BOUGHT, op.SOLD):
            parity = self._fills
            self._fills += 1
            if parity % 2:
                return              # taker fill: never on the book
            r = self.resting.get(m.oid)
            if r is None:
                return              # unreachable on well-formed streams
            new_size = jl.jint(r[_R_SIZE] - m.size)
            if new_size == 0:
                self.resting.pop(m.oid, None)
            else:
                self.resting[m.oid] = (r[_R_SID], r[_R_SIDE],
                                       r[_R_PRICE], new_size)
            self._level_add(r[_R_SID], r[_R_SIDE], r[_R_PRICE],
                            new_size - r[_R_SIZE], touched)
        elif a in (op.BUY, op.SELL):
            if m.size == 0:
                return              # fully filled, nothing rested
            side = SIDE_SELL if a == op.SELL else SIDE_BUY
            self._drop_resting(m.oid, touched)   # duplicate-oid overwrite
            self.resting[m.oid] = (m.sid, side, m.price, m.size)
            self._level_add(m.sid, side, m.price, m.size, touched)
        elif a == op.CANCEL:
            self._drop_resting(m.oid, touched)
        elif a in (op.REMOVE_SYMBOL, op.PAYOUT):
            target = abs(m.sid)
            for oid in sorted(self.resting):
                if abs(self.resting[oid][_R_SID]) == target:
                    self._drop_resting(oid, touched)
        # REJECT / ADD_SYMBOL / CREATE_BALANCE / TRANSFER: no book effect

    # -- record entry points --------------------------------------------

    def on_record(self, key: str, msg: Optional[OrderMsg],
                  epoch: Optional[int] = None,
                  src_seq: Optional[int] = None) -> List[FeedFrame]:
        """Process one MatchOut record; returns the frames it caused,
        in emission order. `msg` may be None for non-OUT keys (their
        payload is never inspected)."""
        self.watermark = (-1 if epoch is None else int(epoch),
                          -1 if src_seq is None else int(src_seq))
        out: List[FeedFrame] = []
        if key == "IN":
            self._fills = 0
            self.groups_seen += 1
            if (self.depth_every > 0 and self._dirty_depth
                    and self.groups_seen % self.depth_every == 0):
                for sid in sorted(self._dirty_depth):
                    self._emit_depth(out, sid)
                self._dirty_depth.clear()
            return out
        if key != "OUT" or msg is None:
            return out
        touched: Dict[Tuple[int, int, int], int] = {}
        self._apply_out(msg, touched)
        changed_sids: Set[int] = set()
        for tkey in sorted(touched):
            sid, side, price = tkey
            now = self.book.get_level(sid, side, price)
            if now != touched[tkey]:
                self._emit_delta(out, sid, side, price, now)
                changed_sids.add(sid)
        for sid in sorted(changed_sids):
            self._emit_tob(out, sid)
            self._dirty_depth.add(sid)
        return out

    def on_line(self, line: str, epoch: Optional[int] = None,
                src_seq: Optional[int] = None) -> List[FeedFrame]:
        """`<key> <value>` consumer-line entry point (the kme-consume
        stream shape). Only OUT payloads are parsed."""
        key, _, rest = line.partition(" ")
        msg = parse_order(rest) if key == "OUT" else None
        return self.on_record(key, msg, epoch, src_seq)

    # -- snapshot state -------------------------------------------------

    def state(self) -> dict:
        """Restore-complete state: everything frame emission depends
        on, in sorted (insertion-order-free) form, so a restored
        deriver continues the byte-identical frame stream."""
        return {
            "group": self.group,
            "depth_every": self.depth_every,
            "depth_levels": self.depth_levels,
            "groups_seen": self.groups_seen,
            "fills": self._fills,
            "frames_out": self.frames_out,
            "watermark": list(self.watermark),
            "resting": [[oid] + list(self.resting[oid])
                        for oid in sorted(self.resting)],
            "seqs": [[sid, self._seqs[sid]]
                     for sid in sorted(self._seqs)],
            "tob": [[sid] + list(self._tob[sid])
                    for sid in sorted(self._tob)],
            "dirty": sorted(self._dirty_depth),
        }

    @classmethod
    def from_state(cls, st: dict) -> "FeedDeriver":
        d = cls(st["group"], st["depth_every"], st["depth_levels"])
        d.groups_seen = st["groups_seen"]
        d._fills = st["fills"]
        d.frames_out = st["frames_out"]
        d.watermark = tuple(st["watermark"])
        for oid, sid, side, price, size in st["resting"]:
            d.resting[oid] = (sid, side, price, size)
            lv = d.book.levels.setdefault((sid, side), {})
            lv[price] = lv.get(price, 0) + size
        for key in [k for k, lv in d.book.levels.items()
                    if not any(v != 0 for v in lv.values())]:
            del d.book.levels[key]
        for lv in d.book.levels.values():
            for price in [p for p, v in lv.items() if v == 0]:
                del lv[price]
        d._seqs = {sid: seq for sid, seq in st["seqs"]}
        d._tob = {row[0]: tuple(row[1:]) for row in st["tob"]}
        d._dirty_depth = set(st["dirty"])
        return d


class BookBuilder:
    """Subscriber-side reconstruction: applies feed frames, tracks
    per-symbol sequence continuity (gap/dup detection survives
    server-side symbol filtering because seq is per-symbol), and
    understands the three server-originated repair shapes — snapshot
    (SNAP_BEGIN / REFRESH depth images / SNAP_END with crc), resync
    after conflation (RESYNC + REFRESH image), and conflated
    top-of-book frames (advisory: never touch levels or seq
    accounting)."""

    def __init__(self) -> None:
        self.book = BookState()
        self.tob: Dict[int, Tuple[int, int, int, int]] = {}
        self.last_seq: Dict[int, int] = {}
        self.gaps: List[Tuple[int, int, int]] = []   # (sid, expected, got)
        self.dups = 0
        self.conflated_tobs = 0
        self.resyncs = 0
        self.snapshots = 0
        self.frames = 0
        self.watermark = (-1, -1)
        self.errors: List[str] = []
        self._snap_left = 0
        self._snap_payload = b""

    # -- helpers --------------------------------------------------------

    def _seq_ok(self, f: FeedFrame) -> bool:
        """Advance per-symbol seq accounting; False = duplicate (drop)."""
        last = self.last_seq.get(f.sid, 0)
        if f.seq <= last:
            self.dups += 1
            return False
        if f.seq != last + 1:
            self.gaps.append((f.sid, last + 1, f.seq))
        self.last_seq[f.sid] = f.seq
        return True

    def _apply_image(self, f: FeedFrame) -> None:
        """Replace a symbol's whole book with a REFRESH depth image."""
        for key in ((f.sid, SIDE_BUY), (f.sid, SIDE_SELL)):
            self.book.levels.pop(key, None)
        for price, size in f.bids:
            self.book.set_level(f.sid, SIDE_BUY, price, size)
        for price, size in f.asks:
            self.book.set_level(f.sid, SIDE_SELL, price, size)
        self.last_seq[f.sid] = f.seq
        self.tob[f.sid] = self.book.tob(f.sid)

    # -- frame application ----------------------------------------------

    def apply(self, f: FeedFrame) -> None:
        self.frames += 1
        k = f.kind
        if k == ff.FEED_SNAP_BEGIN:
            self.snapshots += 1
            self._snap_left = f.count
            self._snap_payload = b""
            return
        if k == ff.FEED_SNAP_END:
            if self._snap_left != 0:
                self.errors.append(
                    f"snapshot ended with {self._snap_left} image(s) "
                    f"missing")
            crc = zlib.crc32(self._snap_payload) & 0xFFFFFFFF
            if f.count and crc != f.crc:
                self.errors.append(
                    f"snapshot crc mismatch: got {crc:#x}, frame says "
                    f"{f.crc:#x}")
            self.watermark = (f.src_epoch, f.src_seq)
            self._snap_left = 0
            return
        if k == ff.FEED_RESYNC:
            self.resyncs += 1
            return
        if k == ff.FEED_DEPTH:
            if f.refresh:
                if self._snap_left > 0:
                    self._snap_left -= 1
                    self._snap_payload += f.raw
                self._apply_image(f)
            else:
                self._seq_ok(f)      # advisory: seq accounting only
            return
        if k == ff.FEED_TOB:
            if f.conflated:
                self.conflated_tobs += 1
                self.tob[f.sid] = (f.bid_price, f.bid_size,
                                   f.ask_price, f.ask_size)
                return
            if self._seq_ok(f):
                self.tob[f.sid] = (f.bid_price, f.bid_size,
                                   f.ask_price, f.ask_size)
            return
        if k == ff.FEED_DELTA:
            if self._seq_ok(f):
                self.book.set_level(f.sid, f.side, f.price, f.size)
            return

    def apply_buffer(self, buf) -> int:
        """Decode and apply a contiguous frame buffer; returns the
        number of bytes consumed (a trailing partial frame stays for
        the caller to re-buffer)."""
        off = 0
        n = len(buf)
        while True:
            length = ff.feed_frame_length(buf, off)
            if length is None or off + length > n:
                return off
            f, off = decode_feed(buf, off)
            self.apply(f)
