"""`kme-feed`: the market-data fan-out tier (sibling of kme-consume).

One single-threaded selectors loop per group does everything:

  broker fetch (MatchOut / MatchOut.gK, nonblocking)
    -> DedupRing on the (epoch, out_seq) produce stamps (replayed
       failover tails vanish here, exactly like kme-consume)
    -> FeedDeriver (pure; byte-identical frames on any replica)
    -> per-symbol fan-out to subscribers
    -> socket pump

A subscriber connects, sends ONE JSON line
`{"op":"subscribe","symbols":[...]|null}` (null = wildcard), and
receives the snapshot-then-deltas handover: SNAP_BEGIN / REFRESH depth
images at the current per-symbol seqs / SNAP_END carrying the
(group, epoch, out_seq) watermark, then the live frame stream.

Slow consumers are never buffered unboundedly (the PR 10 shedding
philosophy applied to readers): past `queue_bytes` of backlog the
queue is DROPPED and the subscriber degrades to conflated top-of-book
— only the latest TOB per touched symbol is retained — until its
socket drains, at which point the server emits RESYNC + a full REFRESH
depth image per conflated symbol and resumes the live stream. The
subscriber's book is correct again after the resync; what it lost is
intermediate states, never the end state.

Feed lag is measured with the admission-stamp convention
(broker-admission `ats` -> frame derivation) into a LatencyHistogram
on /metrics, next to the write-path stages; the heartbeat file
(`feed.health` under --state-root) embeds the registry snapshot so
kme-top / kme-agg discover the feed tier like any other node.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import selectors
import socket
import sys
import time
from typing import Dict, Optional, Set

from kme_tpu.bridge.consume import DedupRing
from kme_tpu.bridge.service import TOPIC_OUT
from kme_tpu.feed import frames as ff
from kme_tpu.feed.derive import FeedDeriver
from kme_tpu.feed.snapshot import (load_feed_snapshot,
                                   save_feed_snapshot, snapshot_frames)
from kme_tpu.telemetry import LatencyHistogram, Registry
from kme_tpu.wire import parse_order

_FETCH_BATCH = 2048
_SEND_CHUNK = 1 << 16


class _Sub:
    __slots__ = ("sock", "addr", "symbols", "live", "rbuf", "queue",
                 "qbytes", "conflating", "dirty", "ctob", "sent_frames")

    def __init__(self, sock, addr) -> None:
        self.sock = sock
        self.addr = addr
        self.symbols: Optional[Set[int]] = None   # None = wildcard
        self.live = False
        self.rbuf = b""
        self.queue = collections.deque()          # (bytes, ) payloads
        self.qbytes = 0
        self.conflating = False
        self.dirty: Set[int] = set()
        self.ctob: Dict[int, tuple] = {}
        self.sent_frames = 0

    def wants(self, sid: int) -> bool:
        return self.symbols is None or sid in self.symbols


class FeedServer:
    """One feed fan-out loop. `broker` is anything with
    fetch(topic, offset, max, timeout) — a TcpBroker for real
    deployments, an InProcessBroker in benches/tests. `reconnect` (a
    zero-arg factory returning a fresh broker) arms failover survival:
    on a broker error the server reconnects and resumes from its
    offset, with the DedupRing suppressing the replayed tail."""

    def __init__(self, broker, host: str = "127.0.0.1", port: int = 0,
                 group: int = 0, topic: str = TOPIC_OUT,
                 depth_every: int = 256, depth_levels: int = 8,
                 queue_bytes: int = 256 * 1024,
                 registry: Optional[Registry] = None,
                 ckpt_dir: Optional[str] = None,
                 snapshot_every: int = 0,
                 reconnect=None, events=None) -> None:
        self.broker = broker
        self.topic = topic
        self.group = group
        self.queue_bytes = int(queue_bytes)
        self.ckpt_dir = ckpt_dir
        self.snapshot_every = int(snapshot_every)
        self.reconnect = reconnect
        self.registry = registry or Registry()
        self.offset = 0
        self.deriver = FeedDeriver(group=group, depth_every=depth_every,
                                   depth_levels=depth_levels)
        if ckpt_dir:
            loaded = load_feed_snapshot(ckpt_dir)
            if loaded is not None:
                self.offset, self.deriver = loaded
        self.dedup = DedupRing()
        self.lag = self.registry.latency("feed_lag")
        r = self.registry
        self.c_frames = r.counter("feed_frames_total")
        self.c_delivered = r.counter("feed_delivered_total")
        self.c_conflations = r.counter("feed_conflations_total")
        self.c_conflated_drop = r.counter("feed_conflated_frames_total")
        self.c_resyncs = r.counter("feed_resyncs_total")
        self.c_snapshots = r.counter("feed_snapshots_served_total")
        self.c_disconnects = r.counter("feed_disconnects_total")
        self.g_subs = r.gauge("feed_subscribers")
        self.g_group = r.gauge("feed_group")
        self.g_offset = r.gauge("feed_offset")
        self.g_group.set(group)
        self._subs: Dict[int, _Sub] = {}          # fd -> sub
        self._by_sid: Dict[int, Set[_Sub]] = {}
        self._wild: Set[_Sub] = set()
        self._sel = selectors.DefaultSelector()
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, port))
        self._lsock.listen(1024)
        self._lsock.setblocking(False)
        self._sel.register(self._lsock, selectors.EVENT_READ, None)
        self.address = self._lsock.getsockname()
        self._stop = False
        self._snap_countdown = self.snapshot_every
        # control-plane flight recorder (telemetry/events.py): each
        # slow-consumer degradation and its heal is a timeline event
        self.events = events

    def _event(self, kind: str, severity: str = "info", **kw) -> None:
        if self.events is None:
            return
        try:
            self.events.emit(kind, severity=severity, group=self.group,
                             offset=self.offset, **kw)
        except Exception:
            pass

    # -- subscriber management ------------------------------------------

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self._lsock.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            sub = _Sub(sock, addr)
            self._subs[sock.fileno()] = sub
            self._sel.register(sock, selectors.EVENT_READ, sub)
            self.g_subs.set(len(self._subs))

    def _drop(self, sub: _Sub) -> None:
        try:
            self._sel.unregister(sub.sock)
        except (KeyError, ValueError):
            pass
        self._subs.pop(sub.sock.fileno(), None)
        if sub.symbols is None:
            self._wild.discard(sub)
        else:
            for sid in sub.symbols:
                peers = self._by_sid.get(sid)
                if peers is not None:
                    peers.discard(sub)
                    if not peers:
                        self._by_sid.pop(sid, None)
        try:
            sub.sock.close()
        except OSError:
            pass
        self.c_disconnects.inc()
        self.g_subs.set(len(self._subs))

    def _handshake(self, sub: _Sub) -> None:
        try:
            data = sub.sock.recv(4096)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(sub)
            return
        if not data:
            self._drop(sub)
            return
        sub.rbuf += data
        if b"\n" not in sub.rbuf:
            if len(sub.rbuf) > 65536:
                self._drop(sub)
            return
        line, _, sub.rbuf = sub.rbuf.partition(b"\n")
        try:
            req = json.loads(line)
            syms = req.get("symbols")
            if syms is not None:
                syms = {int(s) for s in syms}
        except (ValueError, TypeError):
            self._drop(sub)
            return
        sub.symbols = syms
        sub.live = True
        if syms is None:
            self._wild.add(sub)
        else:
            for sid in syms:
                self._by_sid.setdefault(sid, set()).add(sub)
        self._enqueue_bytes(sub, snapshot_frames(self.deriver, syms))
        self.c_snapshots.inc()

    # -- queueing / conflation ------------------------------------------

    def _enqueue_bytes(self, sub: _Sub, payload: bytes) -> None:
        sub.queue.append(payload)
        sub.qbytes += len(payload)
        self._want_write(sub, True)

    def _want_write(self, sub: _Sub, on: bool) -> None:
        ev = selectors.EVENT_READ | (selectors.EVENT_WRITE if on else 0)
        try:
            self._sel.modify(sub.sock, ev, sub)
        except (KeyError, ValueError):
            pass

    def _fan_out(self, frame) -> None:
        sid = frame.sid
        targets = self._by_sid.get(sid, ())
        for group in (targets, self._wild):
            for sub in group:
                if not sub.live:
                    continue
                if sub.conflating:
                    self.c_conflated_drop.inc()
                    if frame.kind == ff.FEED_TOB:
                        sub.ctob[sid] = (frame.seq, frame.src_epoch,
                                         frame.src_seq, frame.bid_price,
                                         frame.bid_size, frame.ask_price,
                                         frame.ask_size)
                    sub.dirty.add(sid)
                    continue
                self._enqueue_bytes(sub, frame.raw)
                self.c_delivered.inc()
                if sub.qbytes > self.queue_bytes:
                    # slow consumer: drop the backlog, remember which
                    # symbols it covered, degrade to conflated TOB
                    for payload in sub.queue:
                        for f in ff.decode_feed_frames(payload):
                            sub.dirty.add(f.sid)
                    sub.queue.clear()
                    sub.qbytes = 0
                    sub.conflating = True
                    self.c_conflations.inc()
                    self._event("feed.conflate", severity="warn",
                                peer=f"{sub.addr[0]}:{sub.addr[1]}",
                                dirty=len(sub.dirty))
                    # keep WRITE interest: the next writable event with
                    # an empty queue IS the drain signal that triggers
                    # the resync
                    self._want_write(sub, True)

    def _resync(self, sub: _Sub) -> None:
        """The socket drained while conflated: ship the latest TOB per
        touched symbol (CONFLATED flag), then RESYNC + an authoritative
        REFRESH image per symbol, and go live again."""
        ep, sq = self.deriver.watermark
        out = b""
        for sid in sorted(sub.ctob):
            seq, fep, fsq, bp, bs, ap, asz = sub.ctob[sid]
            out += ff.encode_tob(self.group, seq, fep, fsq, sid,
                                 bp, bs, ap, asz, conflated=True)
        for sid in sorted(sub.dirty):
            seq = self.deriver._seqs.get(sid, 0)
            bids, asks = self.deriver.book.depth(sid, 0)
            out += ff.encode_resync(self.group, seq, ep, sq, sid)
            out += ff.encode_depth(self.group, seq, ep, sq, sid,
                                   bids, asks, refresh=True)
        healed = len(sub.dirty)
        sub.ctob.clear()
        sub.dirty.clear()
        sub.conflating = False
        self.c_resyncs.inc()
        self._event("feed.resync", epoch=ep,
                    peer=f"{sub.addr[0]}:{sub.addr[1]}",
                    symbols=healed, src_seq=sq)
        if out:
            self._enqueue_bytes(sub, out)

    def _pump(self, sub: _Sub) -> None:
        try:
            while sub.queue:
                head = sub.queue[0]
                n = sub.sock.send(head[:_SEND_CHUNK])
                sub.sent_frames += 1
                if n < len(head):
                    sub.queue[0] = head[n:]
                    sub.qbytes -= n
                    return
                sub.queue.popleft()
                sub.qbytes -= n
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(sub)
            return
        if sub.conflating:
            self._resync(sub)
        if not sub.queue:
            self._want_write(sub, False)

    # -- source consumption ---------------------------------------------

    def _reconnect_broker(self) -> None:
        try:
            self.broker.close()
        except Exception:
            pass
        while not self._stop:
            try:
                self.broker = self.reconnect()
                return
            except Exception:
                time.sleep(0.1)

    def _poll_source(self) -> int:
        from kme_tpu.bridge.broker import BrokerError

        try:
            recs = self.broker.fetch(self.topic, self.offset,
                                     _FETCH_BATCH, timeout=0.0)
        except BrokerError as e:
            if "unknown topic" in str(e):
                return 0              # not provisioned yet: keep waiting
            if self.reconnect is None:
                raise
            self._reconnect_broker()
            return 0
        except OSError:
            if self.reconnect is None:
                raise
            self._reconnect_broker()
            return 0
        if not recs:
            return 0
        now_us = time.time_ns() // 1000
        for r in recs:
            if self.dedup.is_dup(getattr(r, "epoch", None),
                                 getattr(r, "out_seq", None)):
                continue
            key, _, rest = r.value.partition(" ") if r.key is None \
                else (r.key, None, r.value)
            msg = parse_order(rest) if key == "OUT" else None
            frames = self.deriver.on_record(
                key, msg, epoch=getattr(r, "epoch", None),
                src_seq=(r.out_seq if getattr(r, "out_seq", None)
                         is not None else r.offset))
            ats = getattr(r, "ats", None)
            if ats is not None:
                self.lag.observe(max(0, now_us - ats) * 1e-6)
            for f in frames:
                self.c_frames.inc()
                self._fan_out(f)
        self.offset = recs[-1].offset + 1
        self.g_offset.set(self.offset)
        if self.ckpt_dir and self.snapshot_every > 0:
            self._snap_countdown -= len(recs)
            if self._snap_countdown <= 0:
                save_feed_snapshot(self.ckpt_dir, self.deriver,
                                   self.offset)
                self._snap_countdown = self.snapshot_every
        return len(recs)

    # -- main loop ------------------------------------------------------

    def step(self, select_timeout: float = 0.01) -> int:
        """One loop iteration: poll the source, then pump sockets.
        Returns the number of source records consumed."""
        n = self._poll_source()
        events = self._sel.select(timeout=0 if n else select_timeout)
        for key, mask in events:
            if key.data is None:
                self._accept()
                continue
            sub = key.data
            if mask & selectors.EVENT_READ:
                if not sub.live:
                    self._handshake(sub)
                else:
                    # live subscribers never send again; readable
                    # means EOF/garbage -> drop
                    try:
                        data = sub.sock.recv(4096)
                    except (BlockingIOError, InterruptedError):
                        data = b"\x00"
                    except OSError:
                        data = b""
                    if not data:
                        self._drop(sub)
                        continue
            if mask & selectors.EVENT_WRITE and sub.live:
                self._pump(sub)
        return n

    def serve_forever(self, stop=None) -> None:
        while not self._stop and (stop is None or not stop.is_set()):
            self.step()

    def stop(self) -> None:
        self._stop = True

    def drain(self, timeout: float = 10.0) -> bool:
        """Pump until every subscriber queue is empty (bench shutdown:
        everything derived has hit the sockets). Source polling
        continues, so only call once the write path is quiescent."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.step(select_timeout=0.005)
            if not any(s.queue or s.conflating
                       for s in self._subs.values()):
                return True
        return False

    def close(self) -> None:
        self._stop = True
        for sub in list(self._subs.values()):
            self._drop(sub)
        try:
            self._sel.unregister(self._lsock)
        except (KeyError, ValueError):
            pass
        self._lsock.close()
        self._sel.close()

    def stats(self) -> dict:
        return {"offset": self.offset,
                "subscribers": len(self._subs),
                "frames": int(self.c_frames.value),
                "delivered": int(self.c_delivered.value),
                "conflations": int(self.c_conflations.value),
                "resyncs": int(self.c_resyncs.value),
                "dup_suppressed": self.dedup.suppressed}


def write_health(path: str, server: FeedServer) -> None:
    """Heartbeat + embedded registry snapshot (the scrape() shape
    kme-top/kme-agg already understand), atomically."""
    doc = {"t": time.time(), "role": "feed", "group": server.group,
           "addr": list(server.address),
           "metrics": server.registry.snapshot()}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kme-feed", description=__doc__)
    p.add_argument("--broker", default="127.0.0.1:9092",
                   metavar="HOST:PORT")
    p.add_argument("--listen", default="127.0.0.1:9310",
                   metavar="HOST:PORT",
                   help="subscriber-facing address")
    p.add_argument("--topic", default=None,
                   help="source topic (default MatchOut, or "
                        "MatchOut.gK with --group k/n)")
    p.add_argument("--group", default="0/1", metavar="K/N",
                   help="group index / count (selects MatchOut.gK "
                        "when N > 1)")
    p.add_argument("--depth-every", type=int, default=256,
                   help="advisory depth frame cadence (input messages)")
    p.add_argument("--depth-levels", type=int, default=8)
    p.add_argument("--queue-bytes", type=int, default=256 * 1024,
                   help="per-subscriber backlog bound before "
                        "conflation")
    p.add_argument("--metrics-port", type=int, default=None)
    p.add_argument("--state-root", default=None, metavar="DIR",
                   help="write feed.health heartbeats here "
                        "(kme-top/kme-agg discovery)")
    p.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                   help="feed snapshot directory (cold-start resume)")
    p.add_argument("--snapshot-every", type=int, default=0,
                   metavar="RECORDS")
    p.add_argument("--tsdb", default=None, metavar="DIR",
                   help="append the fan-out metrics snapshot to the "
                        "shared on-disk time-series store every "
                        "heartbeat (source 'feed'; kme-prof queries "
                        "it)")
    args = p.parse_args(argv)
    from kme_tpu.bridge.tcp import TcpBroker, parse_addr

    bhost, bport = parse_addr(args.broker)
    lhost, lport = parse_addr(args.listen)
    k, _, n = args.group.partition("/")
    k, n = int(k), int(n or 1)
    topic = args.topic or (f"{TOPIC_OUT}.g{k}" if n > 1 else TOPIC_OUT)
    registry = Registry()
    evlog = None
    if args.state_root:
        from kme_tpu.telemetry import events as cpevents

        os.makedirs(args.state_root, exist_ok=True)
        try:
            evlog = cpevents.open_log(
                args.state_root, f"feed.g{k}" if n > 1 else "feed")
        except OSError:
            evlog = None
    server = FeedServer(
        TcpBroker(bhost, bport), host=lhost, port=lport, group=k,
        topic=topic, depth_every=args.depth_every,
        depth_levels=args.depth_levels, queue_bytes=args.queue_bytes,
        registry=registry, ckpt_dir=args.checkpoint_dir,
        snapshot_every=args.snapshot_every,
        reconnect=lambda: TcpBroker(bhost, bport), events=evlog)
    httpd = None
    if args.metrics_port is not None:
        from kme_tpu.telemetry.httpd import start_metrics_server

        httpd = start_metrics_server(registry, args.metrics_port)
        print(f"kme-feed: metrics on "
              f"http://127.0.0.1:{httpd.server_address[1]}/metrics",
              file=sys.stderr)
    health = None
    if args.state_root:
        os.makedirs(args.state_root, exist_ok=True)
        health = os.path.join(args.state_root, "feed.health")
    tsdb = None
    tsdb_seq = 0
    if args.tsdb is not None:
        from kme_tpu.telemetry import TSDB

        source = f"feed.g{k}" if n > 1 else "feed"
        try:
            tsdb = TSDB(args.tsdb, source=source)
            tsdb_seq = tsdb.next_seq()  # no durable cursor: adopt disk
        except (OSError, ValueError) as e:
            print(f"kme-feed: TSDB disabled: {e}", file=sys.stderr)
    print(f"kme-feed: group {k} serving {topic} on "
          f"{server.address[0]}:{server.address[1]}", file=sys.stderr)
    last_hb = 0.0
    try:
        while True:
            server.step()
            if health is not None or tsdb is not None:
                now = time.monotonic()
                if now - last_hb >= 1.0:
                    if health is not None:
                        write_health(health, server)
                    if tsdb is not None:
                        try:
                            tsdb.append_snapshot(registry.snapshot(),
                                                 tsdb_seq)
                            tsdb_seq += 1
                        except OSError:
                            tsdb = None   # history is best-effort
                    last_hb = now
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
        if evlog is not None:
            evlog.close()
        if tsdb is not None:
            tsdb.close()
        if httpd is not None:
            httpd.shutdown()
    return 0
