"""Feed subscriber client: subscribe-line handshake + frame stream.

The blocking counterpart of the server's wire contract, used by tests,
the chaos drill and `kme-feed --tail`. Scale consumers (the 10k-sub
bench) drive raw nonblocking sockets instead — the wire bytes are the
same; this class is the readable reference implementation."""

from __future__ import annotations

import json
import socket
from typing import Iterator, List, Optional

from kme_tpu.feed import frames as ff
from kme_tpu.feed.derive import BookBuilder
from kme_tpu.feed.frames import FeedFrame


def subscribe_line(symbols=None) -> bytes:
    """The one-line JSON handshake. symbols None = wildcard."""
    syms = None if symbols is None else sorted(int(s) for s in symbols)
    return (json.dumps({"op": "subscribe", "symbols": syms},
                       separators=(",", ":")) + "\n").encode()


class FeedClient:
    """Blocking subscriber: connects, handshakes, then yields decoded
    frames. `builder` (a BookBuilder) is fed every frame, so
    `client.builder.book` is always the reconstructed view."""

    def __init__(self, host: str, port: int, symbols=None,
                 timeout: float = 5.0) -> None:
        self.symbols = symbols
        self.builder = BookBuilder()
        self.frames: List[FeedFrame] = []
        self._buf = b""
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        self.sock.sendall(subscribe_line(symbols))

    def recv_frames(self, max_frames: Optional[int] = None
                    ) -> Iterator[FeedFrame]:
        """Yield frames until EOF, a socket timeout, or `max_frames`.
        Every yielded frame has already been applied to the builder."""
        n = 0
        while max_frames is None or n < max_frames:
            got: List[FeedFrame] = []
            off = 0
            while True:
                length = ff.feed_frame_length(self._buf, off)
                if length is None or off + length > len(self._buf):
                    break
                f, off = ff.decode_feed(self._buf, off)
                got.append(f)
            self._buf = self._buf[off:]
            if got:
                for f in got:
                    self.builder.apply(f)
                    self.frames.append(f)
                    yield f
                    n += 1
                    if max_frames is not None and n >= max_frames:
                        return
                continue
            try:
                data = self.sock.recv(1 << 16)
            except socket.timeout:
                return
            except OSError:
                return
            if not data:
                return
            self._buf += data

    def drain(self) -> int:
        """Consume until EOF/timeout; returns frames received."""
        return sum(1 for _ in self.recv_frames())

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
