"""Feed frame codec: the sequenced binary market-data frames.

Same envelope discipline as the order wire (wire.py): every frame
opens with the 8-byte header `<BBBBI` — magic 0xB1, version, kind,
flags, u32 total length — validated in the same order with the same
error reasons, so one mental model covers order frames (kinds 0/2)
and feed frames (kinds 8-13). A feed socket never carries JSON after
the subscribe line, but the 0xB1 magic keeps the frames distinguishable
from JSON ('{' = 0x7B) anyway, like every other binary surface here.

All feed frames share a 28-byte common body prefix `<IQqq`:

  group      u32   producing group index (PR 9 topic MatchOut.gK)
  seq        u64   PER-SYMBOL sequence number (see below)
  src_epoch  i64   producing leader epoch of the source MatchOut
                   record (-1 when the record was unstamped)
  src_seq    i64   source out_seq stamp (or topic offset for
                   unstamped streams; -1 when unknown)

`(group, src_epoch, src_seq)` is the WATERMARK — where in the write
stream this frame was derived. `seq` is the dissemination sequence in
the ITCH/MoldUDP sense (PAPERS.md), but numbered PER SYMBOL rather
than per channel: a subscriber filtered to a symbol subset still sees
a dense 1,2,3,... sequence for every symbol it watches, so gap/dup
detection survives server-side filtering (a global counter would look
full of holes to any filtered subscriber).

Kinds and kind-specific bodies (after the common prefix):

  FEED_DELTA  8   <qqq>  sid, price, size — the ABSOLUTE new total
                  size at (sid, side, price); size 0 deletes the
                  level. Side rides in flags bit0 (0=buy, 1=sell).
  FEED_TOB    9   <qqqqq> sid, bid_price, bid_size, ask_price,
                  ask_size (size 0 = that side empty; prices then 0)
  FEED_DEPTH  10  <qII>  sid, nbid, nask, then nbid+nask <qq>
                  (price, size) pairs, bids best-first then asks
                  best-first. flags bit2 (REFRESH) marks a full-book
                  authoritative image (snapshot / resync); without it
                  the frame is an advisory top-N view and builders
                  must not apply it.
  FEED_SNAP_BEGIN 11  <II> n_frames, depth (0 = full) — opens a
                  snapshot: the next n_frames frames are REFRESH
                  depth images.
  FEED_SNAP_END   12  <II> n_frames, crc32 of the n_frames depth
                  frame bytes between BEGIN and END.
  FEED_RESYNC 13  <q> sid — the server conflated this symbol for
                  this subscriber; a REFRESH depth image for the sid
                  follows. sid -1 means every subscribed symbol.

Flags: bit0 SELL side (deltas), bit1 CONFLATED (server-degraded
top-of-book / advisory), bit2 REFRESH (authoritative full-depth
image).
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from typing import List, Optional, Tuple

from kme_tpu.wire import WIRE_MAGIC, WIRE_VERSION

FEED_DELTA = 8
FEED_TOB = 9
FEED_DEPTH = 10
FEED_SNAP_BEGIN = 11
FEED_SNAP_END = 12
FEED_RESYNC = 13
_FEED_KINDS = (FEED_DELTA, FEED_TOB, FEED_DEPTH, FEED_SNAP_BEGIN,
               FEED_SNAP_END, FEED_RESYNC)

FEED_FLAG_SELL = 1
FEED_FLAG_CONFLATED = 2
FEED_FLAG_REFRESH = 4

_HDR = struct.Struct("<BBBBI")
_COMMON = struct.Struct("<IQqq")          # group, seq, src_epoch, src_seq
_DELTA_BODY = struct.Struct("<qqq")       # sid, price, size
_TOB_BODY = struct.Struct("<qqqqq")       # sid, bp, bs, ap, asz
_DEPTH_HEAD = struct.Struct("<qII")       # sid, nbid, nask
_PAIR = struct.Struct("<qq")              # price, size
_SNAP_BODY = struct.Struct("<II")         # n_frames, depth / crc32
_RESYNC_BODY = struct.Struct("<q")        # sid

_PREFIX = _HDR.size + _COMMON.size        # 36
DELTA_SIZE = _PREFIX + _DELTA_BODY.size   # 60
TOB_SIZE = _PREFIX + _TOB_BODY.size       # 76
SNAP_SIZE = _PREFIX + _SNAP_BODY.size     # 44
RESYNC_SIZE = _PREFIX + _RESYNC_BODY.size # 44

# a depth image of a full 126-price-level book both sides is ~4KB;
# the cap only exists so a corrupt length prefix cannot make a reader
# allocate unbounded memory before the pair count check catches it
_MAX_FRAME = 1 << 20


class FeedFrameError(ValueError):
    """A feed frame failed validation. `reason` mirrors
    wire.WireFrameError: "truncated", "bad_magic", "version_skew",
    "bad_kind", "bad_length"."""

    def __init__(self, reason: str, detail: str) -> None:
        super().__init__(f"bad feed frame ({reason}): {detail}")
        self.reason = reason


@dataclasses.dataclass
class FeedFrame:
    """One decoded feed frame. Only the fields of its kind are
    meaningful; `raw` is the exact encoded bytes (kept on both encode
    and decode so fan-out and byte-identity checks never re-encode)."""

    kind: int
    flags: int
    group: int
    seq: int
    src_epoch: int
    src_seq: int
    sid: int = 0
    price: int = 0
    size: int = 0
    bid_price: int = 0
    bid_size: int = 0
    ask_price: int = 0
    ask_size: int = 0
    bids: Tuple[Tuple[int, int], ...] = ()
    asks: Tuple[Tuple[int, int], ...] = ()
    count: int = 0
    depth: int = 0
    crc: int = 0
    raw: bytes = b""

    @property
    def side(self) -> int:
        """0 = buy side, 1 = sell side (flags bit0)."""
        return 1 if self.flags & FEED_FLAG_SELL else 0

    @property
    def conflated(self) -> bool:
        return bool(self.flags & FEED_FLAG_CONFLATED)

    @property
    def refresh(self) -> bool:
        return bool(self.flags & FEED_FLAG_REFRESH)


def _envelope(kind: int, flags: int, group: int, seq: int,
              src_epoch: int, src_seq: int, body: bytes) -> bytes:
    length = _PREFIX + len(body)
    return (_HDR.pack(WIRE_MAGIC, WIRE_VERSION, kind, flags, length)
            + _COMMON.pack(group, seq, src_epoch, src_seq) + body)


def encode_delta(group: int, seq: int, src_epoch: int, src_seq: int,
                 sid: int, side: int, price: int, size: int) -> bytes:
    flags = FEED_FLAG_SELL if side else 0
    return _envelope(FEED_DELTA, flags, group, seq, src_epoch, src_seq,
                     _DELTA_BODY.pack(sid, price, size))


def encode_tob(group: int, seq: int, src_epoch: int, src_seq: int,
               sid: int, bp: int, bs: int, ap: int, asz: int,
               conflated: bool = False) -> bytes:
    flags = FEED_FLAG_CONFLATED if conflated else 0
    return _envelope(FEED_TOB, flags, group, seq, src_epoch, src_seq,
                     _TOB_BODY.pack(sid, bp, bs, ap, asz))


def encode_depth(group: int, seq: int, src_epoch: int, src_seq: int,
                 sid: int, bids, asks, refresh: bool = False) -> bytes:
    flags = FEED_FLAG_REFRESH if refresh else 0
    body = _DEPTH_HEAD.pack(sid, len(bids), len(asks)) + b"".join(
        _PAIR.pack(p, s) for p, s in bids) + b"".join(
        _PAIR.pack(p, s) for p, s in asks)
    return _envelope(FEED_DEPTH, flags, group, seq, src_epoch, src_seq,
                     body)


def encode_snap_begin(group: int, src_epoch: int, src_seq: int,
                      n_frames: int, depth: int = 0) -> bytes:
    return _envelope(FEED_SNAP_BEGIN, 0, group, 0, src_epoch, src_seq,
                     _SNAP_BODY.pack(n_frames, depth))


def encode_snap_end(group: int, src_epoch: int, src_seq: int,
                    n_frames: int, payload: bytes) -> bytes:
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return _envelope(FEED_SNAP_END, 0, group, 0, src_epoch, src_seq,
                     _SNAP_BODY.pack(n_frames, crc))


def encode_resync(group: int, seq: int, src_epoch: int, src_seq: int,
                  sid: int) -> bytes:
    return _envelope(FEED_RESYNC, FEED_FLAG_CONFLATED, group, seq,
                     src_epoch, src_seq, _RESYNC_BODY.pack(sid))


def _check_feed_header(buf, off: int, remaining: int) -> Tuple[int, int, int]:
    """Validate one feed frame header at `off`; returns (kind, flags,
    length). Same checks, same order, same reasons as the order-frame
    validator (wire._check_frame_header)."""
    if remaining < _HDR.size:
        raise FeedFrameError(
            "truncated", f"{remaining} byte(s) at offset {off}, header "
            f"needs {_HDR.size}")
    magic, version, kind, flags, length = _HDR.unpack_from(buf, off)
    if magic != WIRE_MAGIC:
        raise FeedFrameError(
            "bad_magic", f"0x{magic:02X} at offset {off} "
            f"(expected 0x{WIRE_MAGIC:02X})")
    if version != WIRE_VERSION:
        raise FeedFrameError(
            "version_skew", f"version {version} at offset {off} "
            f"(this build speaks {WIRE_VERSION})")
    if kind not in _FEED_KINDS:
        raise FeedFrameError(
            "bad_kind", f"kind {kind} at offset {off} (feed frames are "
            f"{_FEED_KINDS[0]}..{_FEED_KINDS[-1]})")
    if length < _PREFIX or length > _MAX_FRAME:
        raise FeedFrameError(
            "bad_length", f"length prefix {length} at offset {off} "
            f"(feed frames are {_PREFIX}..{_MAX_FRAME} bytes)")
    if remaining < length:
        raise FeedFrameError(
            "truncated", f"{remaining} byte(s) at offset {off}, frame "
            f"declares {length}")
    return kind, flags, length


def decode_feed(buf, off: int = 0) -> Tuple[FeedFrame, int]:
    """Decode one feed frame at `off`; returns (frame, next_offset).
    THE authority for the feed format — every reader (builder, bench
    subscribers, chaos assertions) decodes through here."""
    kind, flags, length = _check_feed_header(buf, off, len(buf) - off)
    group, seq, src_epoch, src_seq = _COMMON.unpack_from(
        buf, off + _HDR.size)
    f = FeedFrame(kind, flags, group, seq, src_epoch, src_seq,
                  raw=bytes(buf[off:off + length]))
    body_off = off + _PREFIX
    body_len = length - _PREFIX
    if kind == FEED_DELTA:
        if body_len != _DELTA_BODY.size:
            raise FeedFrameError(
                "bad_length", f"delta body {body_len} bytes at offset "
                f"{off} (expected {_DELTA_BODY.size})")
        f.sid, f.price, f.size = _DELTA_BODY.unpack_from(buf, body_off)
    elif kind == FEED_TOB:
        if body_len != _TOB_BODY.size:
            raise FeedFrameError(
                "bad_length", f"tob body {body_len} bytes at offset "
                f"{off} (expected {_TOB_BODY.size})")
        (f.sid, f.bid_price, f.bid_size, f.ask_price,
         f.ask_size) = _TOB_BODY.unpack_from(buf, body_off)
    elif kind == FEED_DEPTH:
        if body_len < _DEPTH_HEAD.size:
            raise FeedFrameError(
                "bad_length", f"depth body {body_len} bytes at offset "
                f"{off} (head needs {_DEPTH_HEAD.size})")
        f.sid, nbid, nask = _DEPTH_HEAD.unpack_from(buf, body_off)
        need = _DEPTH_HEAD.size + (nbid + nask) * _PAIR.size
        if body_len != need:
            raise FeedFrameError(
                "bad_length", f"depth body {body_len} bytes at offset "
                f"{off} ({nbid}+{nask} pairs need {need})")
        p = body_off + _DEPTH_HEAD.size
        f.bids = tuple(_PAIR.unpack_from(buf, p + i * _PAIR.size)
                       for i in range(nbid))
        p += nbid * _PAIR.size
        f.asks = tuple(_PAIR.unpack_from(buf, p + i * _PAIR.size)
                       for i in range(nask))
    elif kind in (FEED_SNAP_BEGIN, FEED_SNAP_END):
        if body_len != _SNAP_BODY.size:
            raise FeedFrameError(
                "bad_length", f"snap body {body_len} bytes at offset "
                f"{off} (expected {_SNAP_BODY.size})")
        a, b = _SNAP_BODY.unpack_from(buf, body_off)
        f.count = a
        if kind == FEED_SNAP_BEGIN:
            f.depth = b
        else:
            f.crc = b
    else:  # FEED_RESYNC
        if body_len != _RESYNC_BODY.size:
            raise FeedFrameError(
                "bad_length", f"resync body {body_len} bytes at offset "
                f"{off} (expected {_RESYNC_BODY.size})")
        (f.sid,) = _RESYNC_BODY.unpack_from(buf, body_off)
    return f, off + length


def decode_feed_frames(buf) -> List[FeedFrame]:
    """Whole-buffer decode through the per-frame authority."""
    out: List[FeedFrame] = []
    off = 0
    while off < len(buf):
        f, off = decode_feed(buf, off)
        out.append(f)
    return out


def feed_frame_length(buf, off: int = 0) -> Optional[int]:
    """Length of the frame starting at `off`, or None when fewer than
    8 header bytes are buffered. For socket readers: the fixed header
    fields are validated now so garbage fails fast, but an incomplete
    BODY is not an error here — the caller is still buffering."""
    if len(buf) - off < _HDR.size:
        return None
    magic, version, kind, _flags, length = _HDR.unpack_from(buf, off)
    if magic != WIRE_MAGIC:
        raise FeedFrameError(
            "bad_magic", f"0x{magic:02X} at offset {off} "
            f"(expected 0x{WIRE_MAGIC:02X})")
    if version != WIRE_VERSION:
        raise FeedFrameError(
            "version_skew", f"version {version} at offset {off} "
            f"(this build speaks {WIRE_VERSION})")
    if kind not in _FEED_KINDS:
        raise FeedFrameError(
            "bad_kind", f"kind {kind} at offset {off} (feed frames are "
            f"{_FEED_KINDS[0]}..{_FEED_KINDS[-1]})")
    if length < _PREFIX or length > _MAX_FRAME:
        raise FeedFrameError(
            "bad_length", f"length prefix {length} at offset {off} "
            f"(feed frames are {_PREFIX}..{_MAX_FRAME} bytes)")
    return length
