"""Market-data read path (ISSUE 13): deterministic book-delta
derivation from the MatchOut stream, depth snapshots served off the
checkpoint machinery, and a TCP fan-out tier (`kme-feed`).

The write path never changes: feed frames are derived FROM MatchOut
records and ride on their own sockets, so MatchIn/MatchOut bytes are
untouched (COMPAT.md — the reference has no read path at all).
"""

from kme_tpu.feed.frames import (FEED_DELTA, FEED_DEPTH, FEED_RESYNC,
                                 FEED_SNAP_BEGIN, FEED_SNAP_END,
                                 FEED_TOB, FeedFrame, FeedFrameError,
                                 decode_feed_frames)
from kme_tpu.feed.derive import (BookBuilder, BookState, FeedDeriver,
                                 books_from_oracle, canonical_books)

__all__ = [
    "FEED_DELTA", "FEED_TOB", "FEED_DEPTH", "FEED_SNAP_BEGIN",
    "FEED_SNAP_END", "FEED_RESYNC", "FeedFrame", "FeedFrameError",
    "decode_feed_frames", "BookBuilder", "BookState", "FeedDeriver",
    "books_from_oracle", "canonical_books",
]
