"""Perf-regression gate over recorded benchmark artifacts.

`kme-bench --baseline BENCH.json --gate` runs the bench, then compares
its detail metrics against a recorded baseline and exits non-zero on a
regression beyond the noise tolerance. CI wires this against the
repo's BENCH_r0N.json artifacts.

Two artifact realities shape the loader:

- The recorded baselines hold the bench's stderr under a "tail" key
  that is the LAST N BYTES of the stream — routinely TRUNCATED
  mid-JSON (BENCH_r05.json starts mid-object). So metrics are
  extracted with a `"name": number` regex over the raw text, never by
  parsing the whole document; the first occurrence wins (the root
  detail object precedes the nested java/ sub-dicts that repeat metric
  names).
- Baselines may be recorded on a different backend (the checked-in
  ones are TPU; CI gates on CPU). Cross-backend magnitudes are not
  comparable, so a backend mismatch demotes the gate to ADVISORY:
  the report is still printed/written, but the exit code stays 0.

Direction matters: throughput regresses by FALLING, latency by RISING.
`pipeline_speedup` stays advisory — it is a ratio of two wall clocks
and flaps across runs. `measured_overlap_frac` IS gated since its
redefinition over the collect wall (overlap / collect_wall converges
structurally to ~1.0 under working double-buffering), as is `local_s`
(the host-path wall the native layer exists to shrink).
"""

from __future__ import annotations

import json
import re
from typing import Dict, List, Optional

# metric name -> direction ("up" = bigger is better, "down" = smaller
# is better). Anything not listed is reported but never enforced.
GATED_METRICS = {
    "local_orders_per_sec": "up",
    "streamed_orders_per_sec": "up",
    "serial_orders_per_sec": "up",
    "orders_per_sec": "up",
    "engine_side_p50_ms": "down",
    "engine_side_p90_ms": "down",
    "engine_side_p99_ms": "down",
    "device_ms_per_batch": "down",
    "p50_ms": "down",
    "p90_ms": "down",
    "p99_ms": "down",
    # host-path metrics (ISSUE r06): the wall the host spends off the
    # device, and the fraction of collect wall hidden under device
    # execution (defined over the collect wall, so it is stable enough
    # to gate — unlike the wall-clock speedup ratio)
    "local_s": "down",
    "measured_overlap_frac": "up",
    # elastic sharding (ISSUE r08): max/mean per-shard occupancy under
    # the skewed suite — scale-free like measured_overlap_frac, so it
    # gates tightly even on jittery shared runners
    "shard_imbalance": "down",
    # multi-leader groups (ISSUE r09): transfer legs per order under
    # the fixed-seed suite — fully deterministic (router + prefund
    # policy, no wall-clock term), so it gates at zero noise
    "cross_shard_transfer_frac": "down",
    # adversarial storms (ISSUE r10): per-profile shed fraction from
    # the deterministic overload replay (broker.simulate_overload —
    # no wall clock, no RNG), gated vs BASELINE_storms.json at zero
    # noise; a drift means the admission policy or a profile generator
    # changed behavior
    "shed_frac_payout_storm_wide": "down",
    "shed_frac_flash_crowd": "down",
    "shed_frac_cancel_storm": "down",
    "shed_frac_hot_book": "down",
    "shed_frac_liquidation_cascade": "down",
    # binary wire ingress (ISSUE r11): loopback-TCP binary produce rate
    # and the frame-decode wall of the timed binary run — wall-clock
    # metrics, so they gate on CPU baselines with the host-gate
    # tolerance (BASELINE_wire.json)
    "ingress_msgs_per_sec": "up",
    "wire_parse_s": "down",
    # market-data fan-out (ISSUE r13): frames delivered to subscriber
    # sockets per second of fan-out wall, and the admission-stamp ->
    # frame-derivation p99 — wall-clock metrics, gated vs
    # BASELINE_feed.json on CPU with the host-gate tolerance
    "feed_msgs_per_sec": "up",
    "feed_lag_p99_ms": "down",
    # per-chip async dispatch (ISSUE r14): fraction of simulated chip
    # time spent stalled under the deterministic dispatch schedule
    # (weighted message costs, no wall clock, no RNG) — replay-stable,
    # so it gates at zero noise vs BASELINE_shards.json
    "chip_stall_frac": "down",
    # live resharding (ISSUE r15): fraction of the symbol+account key
    # universe the N→M reshard plan moves (reshard.plan_reshard) —
    # pure rendezvous arithmetic, no wall clock, gated at zero noise
    # vs BASELINE_multihost.json; a consistent-hashing regression
    # (salt drift, modulo hashing) jumps it toward 1.0
    "moved_key_frac": "down",
}

# reported-only: too noisy to gate on (documented flappers).
# h2d_overlap_frac and chip_msgs_per_sec ride wall clocks on shared
# runners, so they report advisory-up instead of gating.
ADVISORY_METRICS = ("pipeline_speedup", "journal_overhead_frac",
                    "h2d_overlap_frac", "chip_msgs_per_sec",
                    # continuous profiling (ISSUE r16): both ride wall
                    # clocks/bandwidth probes on shared runners — the
                    # prof suite enforces its own 3% overhead ceiling
                    # in-process instead
                    "prof_overhead_frac", "transfer_compute_ratio",
                    # control-plane timeline (ISSUE r20): the reshard
                    # drill's migration pause decomposed by phase
                    # (chaos.py reshard-under-storm report) — process
                    # spawns and drill pacing dominate these walls on
                    # shared runners, so they trend advisory-down
                    # rather than gate
                    "reshard_pause_ms", "reshard_drain_ms",
                    "reshard_fence_ms", "reshard_migrate_ms",
                    "reshard_settle_ms", "reshard_relaunch_ms",
                    "reshard_unattributed_ms")

_NUM = r"(-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)"


def extract_metrics(text: str) -> Dict[str, float]:
    """Regex-scrape `"name": number` pairs from artifact text.

    Tolerates truncated JSON (recorded tails start mid-object). First
    occurrence of each name wins — the root detail object precedes the
    nested sub-dicts (e.g. "java": {...}) that reuse metric names."""
    out: Dict[str, float] = {}
    for m in re.finditer(rf'"([A-Za-z_][A-Za-z0-9_]*)"\s*:\s*{_NUM}',
                         text):
        name, val = m.group(1), float(m.group(2))
        if name not in out:
            out[name] = val
    return out


def extract_backend(text: str) -> Optional[str]:
    m = re.search(r'"backend"\s*:\s*"([a-z]+)"', text)
    return m.group(1) if m else None


def load_artifact(path: str) -> Dict:
    """Load a benchmark artifact into {"metrics", "backend", "source"}.

    Accepts any of: a recorded driver artifact {"cmd","rc","tail",...}
    (metrics live in the tail text), a bench detail JSON, a headline
    JSON, or raw mixed stdout+stderr text."""
    with open(path, encoding="utf-8", errors="replace") as f:
        text = f.read()
    source = "text"
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and isinstance(doc.get("tail"), str):
        text = doc["tail"]
        source = "driver-tail"
    elif doc is not None:
        source = "json"
    return {"metrics": extract_metrics(text),
            "backend": extract_backend(text), "source": source}


def detail_to_artifact(detail: dict) -> Dict:
    """Adapt a live bench `detail` dict to the artifact shape."""
    text = json.dumps(detail)
    return {"metrics": extract_metrics(text),
            "backend": extract_backend(text), "source": "live"}


def compare(baseline: Dict, current: Dict,
            tolerance: float = 0.25) -> Dict:
    """Direction-aware comparison of two artifacts.

    A gated metric regresses when it is worse than baseline by more
    than `tolerance` (fractional: 0.25 allows a 25 % degradation
    before failing — wide enough for shared-CI noise, far inside the
    2x slowdown the gate exists to catch). Returns a report dict;
    `ok` is False only when a gated metric regressed AND the backends
    match (else `advisory` is True and exit stays 0)."""
    bm, cm = baseline["metrics"], current["metrics"]
    rows: List[dict] = []
    regressions: List[str] = []
    for name, direction in GATED_METRICS.items():
        if name not in bm or name not in cm:
            continue
        base, cur = bm[name], cm[name]
        if base <= 0:
            continue
        ratio = cur / base
        # normalize so ratio > 1 always means WORSE
        worse = 1.0 / ratio if direction == "up" else ratio
        status = "ok"
        if worse > 1.0 + tolerance:
            status = "regressed"
            regressions.append(name)
        rows.append({"name": name, "direction": direction,
                     "baseline": base, "current": cur,
                     "ratio": round(ratio, 4), "status": status})
    for name in ADVISORY_METRICS:
        if name in bm and name in cm:
            rows.append({"name": name, "direction": "advisory",
                         "baseline": bm[name], "current": cm[name],
                         "ratio": (round(cm[name] / bm[name], 4)
                                   if bm[name] else None),
                         "status": "advisory"})
    mismatch = (baseline.get("backend") and current.get("backend")
                and baseline["backend"] != current["backend"])
    return {
        "tolerance": tolerance,
        "baseline_backend": baseline.get("backend"),
        "current_backend": current.get("backend"),
        "backend_mismatch": bool(mismatch),
        "advisory": bool(mismatch),
        "compared": len(rows),
        "regressions": regressions,
        "metrics": rows,
        "ok": not regressions or bool(mismatch),
    }


def format_report(report: Dict) -> str:
    lines = []
    for row in report["metrics"]:
        mark = {"ok": " ", "regressed": "!", "advisory": "~"}[
            row["status"]]
        lines.append(
            f"{mark} {row['name']:<28s} base={row['baseline']:<14g} "
            f"cur={row['current']:<14g} ratio={row['ratio']}")
    if report["backend_mismatch"]:
        lines.append(
            f"~ backend mismatch: baseline={report['baseline_backend']} "
            f"current={report['current_backend']} — gate is ADVISORY "
            f"(exit 0)")
    if report["regressions"] and not report["advisory"]:
        lines.append(f"! REGRESSION beyond {report['tolerance']:.0%} "
                     f"tolerance: {', '.join(report['regressions'])}")
    elif report["regressions"]:
        lines.append(f"~ would-be regressions (advisory): "
                     f"{', '.join(report['regressions'])}")
    else:
        lines.append(f"gate clean: {report['compared']} metric(s) "
                     f"within {report['tolerance']:.0%}")
    return "\n".join(lines)


# -- stage-level regression attribution (ISSUE 16) ---------------------
#
# Given two metric dicts (TSDB window summaries via
# telemetry.tsdb.window_summary, or BENCH artifact metrics via
# load_artifact), name the pipeline stage whose evidence moved the
# most. Each stage lists every metric that testifies about it: the
# per-stage latency quantiles (lat_<stage>.p99_ms, flattened TSDB
# names), the host sampling profiler's stage fractions
# (prof_stage_frac_*), and the bench-artifact spellings
# (device_ms_per_batch, p99_ms). A metric missing on either side is
# simply skipped — the verdict is built from whatever evidence both
# windows share.
STAGE_ATTRIBUTION: Dict[str, tuple] = {
    "parse": ("lat_ingress.p99_ms", "prof_stage_frac_parse",
              "wire_parse_s"),
    "plan": ("lat_plan.p99_ms", "prof_stage_frac_plan", "plan_s"),
    "device": ("lat_device.p99_ms", "prof_stage_frac_dispatch",
               "prof_stage_frac_collect", "device_ms_per_batch",
               "engine_side_p99_ms"),
    "produce": ("lat_produce.p99_ms", "prof_stage_frac_produce"),
    "e2e": ("lat_e2e.p99_ms", "p99_ms"),
}


def attribute_regression(base: Dict[str, float],
                         cur: Dict[str, float]) -> Dict:
    """Rank pipeline stages by how much their evidence degraded
    between two metric dicts. Returns {"stages": [...worst first...],
    "suspect": <stage name or None>}; a stage's score is the worst
    relative increase among its shared metrics (1.0 = unchanged)."""
    stages: List[dict] = []
    for stage, names in STAGE_ATTRIBUTION.items():
        evidence = []
        score = 1.0
        for name in names:
            b, c = base.get(name), cur.get(name)
            if b is None or c is None or b <= 0:
                continue
            ratio = c / b
            evidence.append({"name": name, "baseline": b,
                             "current": c, "ratio": round(ratio, 4)})
            score = max(score, ratio)
        if evidence:
            stages.append({"stage": stage, "score": round(score, 4),
                           "evidence": evidence})
    stages.sort(key=lambda s: -s["score"])
    # "e2e" restates the symptom, never the cause: only name it when
    # no concrete stage moved with it
    suspect = None
    for s in stages:
        if s["score"] > 1.05 and s["stage"] != "e2e":
            suspect = s["stage"]
            break
    if suspect is None and stages and stages[0]["score"] > 1.05:
        suspect = stages[0]["stage"]
    return {"stages": stages, "suspect": suspect}


def format_attribution(att: Dict) -> str:
    lines = []
    for s in att["stages"]:
        mark = "!" if s["stage"] == att["suspect"] else " "
        ev = ", ".join(f"{e['name']} x{e['ratio']}"
                       for e in s["evidence"][:3])
        lines.append(f"{mark} stage {s['stage']:<8s} "
                     f"x{s['score']:<8g} {ev}")
    if att["suspect"]:
        lines.append(f"! attribution: the {att['suspect']} stage moved "
                     f"the most")
    else:
        lines.append("attribution: no stage moved beyond 5%")
    return "\n".join(lines)


def run_gate(baseline_path: str, current: Dict,
             tolerance: float = 0.25,
             report_path: Optional[str] = None) -> int:
    """Compare, print, optionally persist the report; return the exit
    code (0 clean/advisory, 1 regression, 2 unusable baseline)."""
    import sys

    baseline = load_artifact(baseline_path)
    if not baseline["metrics"]:
        print(f"kme-bench --gate: no metrics found in "
              f"{baseline_path!r}; cannot gate", file=sys.stderr)
        return 2
    report = compare(baseline, current, tolerance=tolerance)
    print(format_report(report), file=sys.stderr)
    if report["regressions"]:
        # a failing (or would-fail) gate names its suspect stage too —
        # the same attribution kme-prof --diff prints over TSDB windows
        att = attribute_regression(baseline["metrics"],
                                   current["metrics"])
        report["attribution"] = att
        print(format_attribution(att), file=sys.stderr)
    if report_path is not None:
        with open(report_path, "w") as f:
            json.dump(report, f, indent=2)
        print(f"kme-bench --gate: report written to {report_path}",
              file=sys.stderr)
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    """Standalone gate/attribution CLI:
    `python -m kme_tpu.perfgate BASELINE CURRENT [--attribute]`.
    Both operands are benchmark artifacts (driver tails, detail JSON,
    or raw text). --attribute prints the per-stage verdict instead of
    gating."""
    import argparse
    import sys

    p = argparse.ArgumentParser(prog="kme-perfgate",
                                description=main.__doc__)
    p.add_argument("baseline", help="recorded artifact (BENCH_*.json)")
    p.add_argument("current", help="artifact to judge against it")
    p.add_argument("--tolerance", type=float, default=0.25)
    p.add_argument("--attribute", action="store_true",
                   help="per-stage regression attribution only "
                        "(exit 0 clean, 1 when a stage moved >5%%)")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="write the JSON report here")
    args = p.parse_args(argv)
    if args.attribute:
        base = load_artifact(args.baseline)
        cur = load_artifact(args.current)
        if not base["metrics"] or not cur["metrics"]:
            print("kme-perfgate: no metrics on one side; cannot "
                  "attribute", file=sys.stderr)
            return 2
        att = attribute_regression(base["metrics"], cur["metrics"])
        print(format_attribution(att))
        if args.report is not None:
            with open(args.report, "w") as f:
                json.dump(att, f, indent=2)
        return 1 if att["suspect"] else 0
    return run_gate(args.baseline, load_artifact(args.current),
                    tolerance=args.tolerance, report_path=args.report)


if __name__ == "__main__":
    import sys

    sys.exit(main())
