"""The simulated front→group transport: in-memory FrontLinks.

Models what `bridge/front.FrontLinks` gives the real cluster — one
ordered, stamped produce link per group — under the scheduler's
control:

- per-link FIFO: a group's durable MatchIn order always equals its
  routed substream order, so `verify_groups` parity holds under ANY
  fault schedule.  The guarantee is structural, not scheduling luck:
  arrivals land in a per-link reorder buffer and are produced strictly
  in stamp order (`next_deliver`), so a crash window — during which
  earlier records park while later ones keep arriving — can never let
  a later stamp reach the broker first and dup-suppress the earlier
  ones into silent input loss;
- idempotent stamps: every delivery carries the link's monotone
  `out_seq` cursor (epoch-less, like the live front), so duplicate
  re-sends vanish at the broker's watermark — which is exactly what
  the `net.reorder` fault exercises: it re-sends an EARLIER record
  after newer ones (the out-of-order-duplicate shape a buggy retry
  path would produce) and the verdicts prove the broker swallowed it;
- `net.partition` severs a link for the rule's `ms` virtual
  milliseconds (deliveries queue and flush in order on heal — never
  drop, like a sender with a deep retry budget);
- `net.delay` stalls a link by `ms` (everything behind shifts too);
- a crashed leader's deliveries park in the reorder buffer and flush
  in stamp order on restart (connection-refused + retry, collapsed to
  its effect).

Faults are drawn from the process-global `faults` plan (the KME_FAULTS
grammar — clauses generated per seed by `schedule.py`), with the
delivery ordinal as the `at=` offset domain.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from kme_tpu import faults


class _Link:
    __slots__ = ("g", "next_free", "down_until", "seq", "pending",
                 "next_deliver", "delivered", "dup_resends", "last")

    def __init__(self, g: int, cursor: int = 0) -> None:
        self.g = g
        self.next_free = 0.0        # link-FIFO serialization point
        self.down_until = 0.0       # net.partition window end
        self.seq = cursor           # per-link idempotent produce cursor
        self.pending: Dict[int, tuple] = {}  # arrived, not yet produced
        self.next_deliver = cursor  # the stamp the broker gets next
        self.delivered = 0
        self.dup_resends = 0
        self.last: Optional[tuple] = None   # last sent (for net.reorder)


class SimTransport:
    """`send()` at route time, scheduled arrival at virtual delivery
    time, strictly stamp-ordered produce. `broker_for(g)` comes from
    the cluster and returns None while group g's leader is down."""

    def __init__(self, sched, ngroups: int, broker_for: Callable,
                 topic_for: Callable[[int], str],
                 base_latency: float = 0.0005) -> None:
        self.sched = sched
        self.broker_for = broker_for
        self.topic_for = topic_for
        self.base = base_latency
        self.links = [_Link(g) for g in range(ngroups)]
        self.sent = 0               # global delivery ordinal (at= domain)
        self.in_flight = 0

    def reshape(self, ngroups: int,
                cursors: Optional[List[int]] = None) -> None:
        """New topology after a reshard: fresh links. `cursors` is the
        coordinator's settle-phase `resume_cursors` — the new MatchIn
        logs already hold that many stamped settlement legs, so each
        link's produce cursor must START above them or the first real
        delivery would be dup-suppressed (silent input loss)."""
        assert all(not l.pending for l in self.links), \
            "reshard barrier requires a drained transport"
        self.links = [_Link(g, int(cursors[g]) if cursors else 0)
                      for g in range(ngroups)]

    def idle(self) -> bool:
        return self.in_flight == 0

    # -- send path -----------------------------------------------------

    def send(self, g: int, key: Optional[str], value: str) -> None:
        link = self.links[g]
        self.sent += 1
        ordinal = self.sent
        stamped = (key, value, link.seq)
        link.seq += 1
        rule = faults.fire("net.partition", offset=ordinal)
        if rule is not None:
            link.down_until = max(link.down_until,
                                  self.sched.now + rule.ms / 1000.0)
            self.sched.trace(f"link{g}", "partition", ms=rule.ms)
        extra = 0.0
        rule = faults.fire("net.delay", offset=ordinal)
        if rule is not None:
            extra = rule.ms / 1000.0
            self.sched.trace(f"link{g}", "delay", ms=rule.ms)
        self._enqueue(link, stamped, extra)
        if link.last is not None \
                and faults.fire("net.reorder", offset=ordinal) is not None:
            # out-of-order duplicate: the previous record rides AGAIN
            # behind this one with its ORIGINAL stamp — the broker's
            # idempotence watermark must swallow it
            link.dup_resends += 1
            self.sched.trace(f"link{g}", "reorder_dup",
                             seq=link.last[2])
            self._enqueue(link, link.last, 0.0)
        link.last = stamped

    def _enqueue(self, link: _Link, stamped: tuple,
                 extra: float) -> None:
        at = max(self.sched.now, link.next_free, link.down_until) \
            + self.base + extra
        link.next_free = at
        self.in_flight += 1
        self.sched.post(at - self.sched.now,
                        lambda: self._arrive(link, stamped))

    # -- delivery ------------------------------------------------------

    def _arrive(self, link: _Link, stamped: tuple) -> None:
        if self.sched.now < link.down_until:
            # partitioned after scheduling: requeue at heal, preserving
            # FIFO (next_free only grows)
            delay = link.down_until - self.sched.now
            link.next_free = max(link.next_free,
                                 link.down_until + self.base)
            self.sched.post(delay,
                            lambda: self._arrive(link, stamped))
            return
        seq = stamped[2]
        if seq < link.next_deliver:
            # a re-sent duplicate of an ALREADY-produced stamp: goes
            # straight to the broker for watermark suppression
            self._produce_dup(link, stamped)
            return
        if seq in link.pending:
            # duplicate of a stamp still waiting in the buffer —
            # collapses into the one pending entry
            self.in_flight -= 1
        else:
            link.pending[seq] = stamped
        self._drain(link)

    def _drain(self, link: _Link) -> None:
        """Produce pending records strictly in stamp order; stop at a
        gap (an earlier stamp still in transit), a downed leader, or
        an injected broker error (which reposts the drain)."""
        from kme_tpu.bridge.broker import BrokerError

        while link.next_deliver in link.pending:
            broker = self.broker_for(link.g)
            if broker is None:
                return          # parked: flush_held drains on restart
            key, value, seq = link.pending[link.next_deliver]
            try:
                off = broker.produce(self.topic_for(link.g), key,
                                     value, out_seq=seq)
            except BrokerError:
                # injected broker.produce fault (or overload): retry
                # the SAME stamped record shortly, like FrontLinks
                self.sched.trace(f"link{link.g}", "produce_retry",
                                 seq=seq)
                self.sched.post(0.01, lambda: self._drain(link))
                return
            del link.pending[link.next_deliver]
            link.next_deliver += 1
            self.in_flight -= 1
            link.delivered += 1
            if off < 0:
                self.sched.trace(f"link{link.g}", "dup_suppressed",
                                 seq=seq)

    def _produce_dup(self, link: _Link, stamped: tuple) -> None:
        from kme_tpu.bridge.broker import BrokerError

        broker = self.broker_for(link.g)
        if broker is None:
            # leader down mid-duplicate: retry after a beat (the sim
            # never drops — determinism over realism of loss, which
            # the broker watermark would mask anyway)
            self.sched.post(0.05,
                            lambda: self._produce_dup(link, stamped))
            return
        key, value, seq = stamped
        try:
            off = broker.produce(self.topic_for(link.g), key, value,
                                 out_seq=seq)
        except BrokerError:
            self.sched.trace(f"link{link.g}", "produce_retry", seq=seq)
            self.sched.post(0.01,
                            lambda: self._produce_dup(link, stamped))
            return
        self.in_flight -= 1
        link.delivered += 1
        if off < 0:
            self.sched.trace(f"link{link.g}", "dup_suppressed", seq=seq)

    def flush_held(self, g: int) -> None:
        """Leader back up: drain the records parked in stamp order."""
        link = self.links[g]
        n = len(link.pending)
        self._drain(link)
        if n:
            self.sched.trace(f"link{g}", "flush_held", n=n)
