"""Red-seed shrinking: ddmin over the fault schedule, then input-size
reduction — from "seed 1337 is red under 6 faults and 700 inputs" to
the minimal adversity that still trips the verdict.

The shrink target is the whole ``FaultSchedule``: grammar clauses and
cluster events are the removable units (classic delta debugging — try
dropping complements at coarsening granularity, keep any candidate
that stays red), then ``num_events`` is walked down by halving while
the failure survives (event stream positions clamp to the shorter
stream so a crash-at-600 still fires in a 300-line run).

Because one seed fully determines a run, "stays red" is a pure
function: re-running a candidate schedule in a fresh directory gives
the SAME verdicts every time — no flaky-shrink loops, no
retry-to-confirm. An unexpected exception inside a candidate run
counts as red too (a schedule that crashes the harness is at least as
interesting as one that fails a verdict).

The output is a repro kit under ``out_dir``:

- ``repro.json`` — the minimal schedule, canonical one-line JSON
  (self-contained: seed, clauses, events, workload size, topology);
- ``repro.cmd``  — the one-line ``kme-sim --repro`` invocation;
- ``run/``       — the minimal schedule's final red run, on disk
  (durable logs, checkpoints, journals — everything offline tooling
  needs);
- ``sim_repro.json`` — an ``audit.py``-format dump (violations /
  events / inputs / checkpoint_ref / xray) whose ``xray`` field is a
  ready-to-run ``kme-xray --bisect`` line over the red run's journal,
  so the time-travel debugger picks up exactly where the sim verdict
  left off;
- ``events.jsonl`` — the red run's merged control-plane timeline
  (telemetry/events.py): every lease grant and reshard phase the
  cluster decided on the way to the red verdict.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

from kme_tpu.sim.cluster import SimConfig, SimResult, run_sim
from kme_tpu.sim.schedule import FaultSchedule


@dataclass
class ShrinkResult:
    schedule: FaultSchedule          # the minimal red schedule
    result: SimResult                # its (final, red) run
    runs: int                        # candidate executions spent
    removed: int                     # adversity units shrunk away
    repro_path: str = ""
    cmd_path: str = ""
    dump_path: str = ""
    repro_line: str = ""
    steps: List[str] = field(default_factory=list)


def _clamped(sched: FaultSchedule, units: List[Tuple[str, object]],
             num_events: int) -> FaultSchedule:
    """A candidate schedule: the kept adversity units over a possibly
    shorter input stream (event positions clamp into the stream)."""
    cand = FaultSchedule(seed=sched.seed, num_events=num_events,
                         ngroups=sched.ngroups)
    for kind, u in units:
        if kind == "clause":
            cand.clauses.append(u)
        else:
            ev = dict(u)
            if "at" in ev:
                ev["at"] = min(int(ev["at"]), num_events)
            cand.events.append(ev)
    cand.events.sort(key=lambda e: (e.get("at", 0), e["kind"]))
    return cand


def shrink_schedule(schedule: FaultSchedule, workdir: str,
                    cfg: Optional[SimConfig] = None,
                    planted_bug: Optional[str] = None,
                    max_runs: int = 64,
                    max_vtime: float = 600.0,
                    min_events: int = 16,
                    log: Callable[[str], None] = lambda s: None,
                    ) -> Optional[ShrinkResult]:
    """Shrink a red schedule to a locally minimal one. Returns None if
    the schedule is not red in the first place (nothing to shrink)."""
    cfg = cfg or SimConfig()
    os.makedirs(workdir, exist_ok=True)
    runs = [0]
    last_red: List[Optional[SimResult]] = [None]

    def execute(cand: FaultSchedule) -> Optional[SimResult]:
        runs[0] += 1
        root = os.path.join(workdir, f"try{runs[0]:04d}")
        try:
            return run_sim(cand, root, cfg=cfg,
                           planted_bug=planted_bug,
                           max_vtime=max_vtime)
        except Exception as e:      # harness-killing schedule: red
            log(f"candidate raised {type(e).__name__}: {e}")
            return None

    def is_red(cand: FaultSchedule) -> bool:
        if runs[0] >= max_runs:
            return False            # budget spent: stop accepting
        res = execute(cand)
        if res is None:
            last_red[0] = None
            return True
        if not res.ok:
            last_red[0] = res
            return True
        return False

    baseline = execute(schedule)
    if baseline is not None and baseline.ok:
        return None
    last_red[0] = baseline
    original_size = schedule.size()
    steps: List[str] = [f"baseline red: {schedule.describe()}"]

    # -- phase 1: ddmin over the adversity units -----------------------
    units: List[Tuple[str, object]] = (
        [("clause", c) for c in schedule.clauses]
        + [("event", ev) for ev in schedule.events])
    num_events = schedule.num_events
    n = 2
    while len(units) >= 2 and runs[0] < max_runs:
        chunk = max(1, len(units) // n)
        reduced = False
        for i in range(0, len(units), chunk):
            cand_units = units[:i] + units[i + chunk:]
            if is_red(_clamped(schedule, cand_units, num_events)):
                dropped = len(units) - len(cand_units)
                units = cand_units
                steps.append(f"dropped {dropped} unit(s) -> "
                             f"{len(units)} left")
                log(steps[-1])
                n = max(2, n - 1)
                reduced = True
                break
        if not reduced:
            if n >= len(units):
                break
            n = min(len(units), n * 2)
    # singles pass (ddmin can stall above granularity 1)
    i = 0
    while i < len(units) and len(units) > 1 and runs[0] < max_runs:
        cand_units = units[:i] + units[i + 1:]
        if is_red(_clamped(schedule, cand_units, num_events)):
            units = cand_units
            steps.append(f"dropped 1 unit -> {len(units)} left")
            log(steps[-1])
        else:
            i += 1

    # -- phase 2: input-size reduction ---------------------------------
    while num_events // 2 >= min_events and runs[0] < max_runs:
        half = num_events // 2
        if is_red(_clamped(schedule, units, half)):
            num_events = half
            steps.append(f"halved input -> {num_events} events")
            log(steps[-1])
        else:
            break
    three_q = num_events - num_events // 4
    if (min_events <= three_q < num_events and runs[0] < max_runs
            and is_red(_clamped(schedule, units, three_q))):
        num_events = three_q
        steps.append(f"trimmed input -> {num_events} events")

    minimal = _clamped(schedule, units, num_events)
    # one final run into a KEPT directory: the repro kit's artifacts
    final_root = os.path.join(workdir, "run")
    try:
        final = run_sim(minimal, final_root, cfg=cfg,
                        planted_bug=planted_bug, max_vtime=max_vtime)
    except Exception:
        final = last_red[0]
    if final is None:
        final = last_red[0]
    out = ShrinkResult(schedule=minimal, result=final,
                       runs=runs[0],
                       removed=original_size - minimal.size(),
                       steps=steps)
    _write_repro_kit(out, workdir, final_root, cfg, planted_bug)
    return out


# ---------------------------------------------------------------------------
# the repro kit


def _write_repro_kit(out: ShrinkResult, workdir: str, run_root: str,
                     cfg: SimConfig,
                     planted_bug: Optional[str]) -> None:
    from kme_tpu.wire import dumps_order
    from kme_tpu.workload import spliced_stream

    sched = out.schedule
    out.repro_path = os.path.join(workdir, "repro.json")
    with open(out.repro_path, "w") as f:
        f.write(sched.to_json() + "\n")

    out.repro_line = f"kme-sim --repro {out.repro_path}"
    if planted_bug:
        out.repro_line += f" --planted-bug {planted_bug}"
    out.cmd_path = os.path.join(workdir, "repro.cmd")
    with open(out.cmd_path, "w") as f:
        f.write(out.repro_line + "\n")

    # the audit.py repro-dump shape, so every offline tool that eats
    # audit dumps (and every engineer who knows them) can eat this one
    res = out.result
    violations = []
    if res is not None:
        for name in res.red_verdicts():
            violations.append({"invariant": f"sim.{name}",
                               "detail": res.verdicts[name]})
    splices = [(ev["at"], ev["profile"], ev.get("n", 100))
               for ev in sched.events if ev["kind"] == "storm"]
    inputs = [dumps_order(m) for m in
              spliced_stream(sched.num_events, seed=sched.seed,
                             splices=splices,
                             num_accounts=cfg.num_accounts,
                             num_symbols=cfg.num_symbols,
                             prefund_cash=cfg.prefund_cash)]
    gdir, xray = _xray_ref(run_root, res, cfg)
    doc = {"violations": violations,
           "batch": None,
           "pre_state": None,
           "events": list(sched.events),
           "inputs": inputs,
           "checkpoint_ref": gdir,
           "xray": xray,
           "schedule": json.loads(sched.to_json()),
           "repro": out.repro_line}
    out.dump_path = os.path.join(workdir, "sim_repro.json")
    with open(out.dump_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)

    # the red run's merged control-plane timeline rides along: what
    # the cluster DECIDED (lease grants, reshard phases) on the way to
    # the red verdict, in one causally-ordered events.jsonl artifact
    from kme_tpu.telemetry import events as cpevents

    try:
        tl = cpevents.merge_logs([run_root])
        if tl:
            cpevents.write_merged(
                tl, os.path.join(workdir, "events.jsonl"))
    except OSError:
        pass


def _xray_ref(run_root: str, res: Optional[SimResult],
              cfg: SimConfig) -> Tuple[Optional[str], Optional[str]]:
    """Point kme-xray's divergence bisector at the red run's most
    suspicious group: the first one named by a red verdict, else g0 of
    the final generation."""
    if not os.path.isdir(run_root):
        return None, None
    gens = sorted(d for d in os.listdir(run_root)
                  if d.startswith("gen"))
    if not gens:
        return None, None
    gen_root = os.path.join(run_root, gens[-1])
    suspect = 0
    if res is not None:
        par = res.verdicts.get("parity", {})
        for mm in par.get("mismatches", []):
            if isinstance(mm, dict) and "group" in mm:
                suspect = int(mm["group"])
                break
        else:
            dups = res.verdicts.get("stamps", {}).get("duplicates", [])
            if dups:
                suspect = int(dups[0]["group"])
    gdir = os.path.join(gen_root, f"group{suspect}")
    if not os.path.isdir(gdir):
        gdir = os.path.join(gen_root, "group0")
        if not os.path.isdir(gdir):
            return None, None
    journal = os.path.join(gdir, "journal.bin")
    log_dir = os.path.join(gdir, "broker-log")
    if not os.path.exists(journal):
        return gdir, None
    # hi-batch: an upper bound on the red batch index — every applied
    # batch journaled, so offset/batch rounds up past the last one
    hi = 1
    if res is not None:
        hi = max(1, (res.counters.get("routed", 0) // cfg.batch) + 1)
    xray = (f"kme-xray --bisect --journal {journal} "
            f"--log-dir {log_dir} --hi-batch {hi} "
            f"--checkpoint-dir {gdir}")
    return gdir, xray
