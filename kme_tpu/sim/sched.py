"""The seeded virtual-clock scheduler: the sim's single source of time
and interleaving.

A ``SimScheduler`` is a priority queue of ``(vtime, seq, fn)`` events
over one virtual ``now``. ``seq`` is a monotonic insertion counter —
the tie-break for same-instant events is insertion order, never object
identity or hash order, which is what makes a run replayable.

Actors are plain objects with a ``step() -> bool`` method ("did any
work"). ``add_actor`` wraps each in a pump: after every step the actor
is re-scheduled ``quantum * (0.5 + rng.random())`` virtual seconds out
(``idle_quantum`` when it did nothing), so the seeded RNG decides the
interleaving — two seeds explore two schedules, one seed explores
exactly one, every time.

Virtual sleeps: each actor sees time through a ``SimClockView``
(``bridge/clock.Clock``). A component that naps for backoff
(``clock.sleep`` inside a service retry loop) charges the nap to the
CURRENT actor's next wake-up instead of blocking the process —
simulated milliseconds, not real ones. ``clock.skew`` (the fault
point) steps a view's wall offset without touching its monotonic
domain, like NTP on a real host.

The event trace: ``trace(actor, kind, **fields)`` appends a
deterministic tuple (virtual time, actor, kind, sorted fields) to
``events``; ``digest()`` is the sha256 over their canonical reprs.
Byte-identical digests across two runs of the same seed is the
determinism acceptance gate, so NOTHING wall-clock-derived may ever be
traced.
"""

from __future__ import annotations

import hashlib
import heapq
import random
from typing import Callable, List, Optional, Tuple

from kme_tpu.bridge.clock import Clock


class SimClockView(Clock):
    """One actor's view of virtual time: shared ``now``, private skew."""

    def __init__(self, sched: "SimScheduler") -> None:
        self.sched = sched
        self.skew = 0.0

    def time(self) -> float:
        return self.sched.now + self.skew

    def time_ns(self) -> int:
        return int((self.sched.now + self.skew) * 1e9)

    def monotonic(self) -> float:
        return self.sched.now

    def sleep(self, seconds: float) -> None:
        # charged to the current actor's next wake-up by the pump
        if seconds > 0:
            self.sched.sleep_charge += seconds


class SimScheduler:
    def __init__(self, seed: int, quantum: float = 0.001,
                 idle_quantum: float = 0.005) -> None:
        self.seed = int(seed)
        self.now = 0.0
        self.quantum = quantum
        self.idle_quantum = idle_quantum
        # independent deterministic stream, insensitive to other
        # consumers of the seed (schedule generator, workload)
        self.rng = random.Random((self.seed, "sim-sched").__repr__())
        self._heap: List[Tuple[float, int, Callable[[], None]]] = []
        self._seq = 0
        self.sleep_charge = 0.0     # virtual sleeps of the running actor
        self.events: List[tuple] = []
        self.stopped = False
        self._actors: List[str] = []

    # -- event queue ---------------------------------------------------

    def post(self, delay: float, fn: Callable[[], None]) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (self.now + max(0.0, delay),
                                    self._seq, fn))

    def add_actor(self, name: str, actor, quantum: Optional[float] = None,
                  idle_quantum: Optional[float] = None) -> None:
        """Schedule `actor.step()` pumps under seeded jitter until
        `actor.stopped` goes true (the pump simply stops rescheduling —
        a crashed actor's queued wake-up is a no-op)."""
        q = self.quantum if quantum is None else quantum
        iq = self.idle_quantum if idle_quantum is None else idle_quantum
        self._actors.append(name)

        def pump() -> None:
            if self.stopped or getattr(actor, "stopped", False):
                return
            self.sleep_charge = 0.0
            busy = actor.step()
            base = q if busy else iq
            delay = base * (0.5 + self.rng.random()) + self.sleep_charge
            self.post(delay, pump)

        self.post(q * (0.5 + self.rng.random()), pump)

    # -- the loop ------------------------------------------------------

    def run(self, until: Callable[[], bool],
            max_vtime: float = 3600.0) -> None:
        """Pop events in (vtime, seq) order until `until()` is true,
        the queue drains, or virtual `max_vtime` passes (the runaway
        backstop — a sim that needs an hour of virtual time is wedged,
        and determinism means a wedge is a reproducible verdict, not a
        flaky timeout)."""
        while self._heap and not self.stopped:
            if until():
                break
            vtime, _seq, fn = heapq.heappop(self._heap)
            if vtime > self.now:
                self.now = vtime
            if self.now > max_vtime:
                self.trace("sim", "wedged", vtime=round(self.now, 6))
                break
            fn()

    # -- the deterministic event trace ---------------------------------

    def trace(self, actor: str, kind: str, **fields) -> None:
        self.events.append((round(self.now, 9), actor, kind,
                            tuple(sorted(fields.items()))))

    def digest(self) -> str:
        h = hashlib.sha256()
        for ev in self.events:
            h.update(repr(ev).encode("utf-8"))
            h.update(b"\n")
        return h.hexdigest()
