"""Deterministic whole-cluster simulation (ISSUE 19, ROADMAP item 1).

FoundationDB-style simulation testing for the matching cluster: the
supervisor's whole process tree — N group leaders, standby followers,
the front router, the feed deriver — runs in ONE process as
cooperatively scheduled actors under a virtual clock, with a seeded
``SimScheduler`` owning every source of nondeterminism:

- the clock (``bridge/clock.py`` seam — no component reads wall time),
- actor interleaving (seeded quantum jitter),
- message delivery order/delay on the in-memory transport
  (``net.partition`` / ``net.delay`` / ``net.reorder`` fault points),
- and a generated fault schedule (crash, SIGKILL-at-offset, torn
  checkpoint, broker errors, storm bursts, reshard mid-storm) drawn
  from the ``faults.py`` point grammar.

One seed fully determines a run: same seed → byte-identical event
trace, byte-identical durable MatchOut, identical verdicts. A red seed
is automatically shrunk (``shrink.py`` delta-debugging over the fault
schedule and the input stream) to a minimal one-line repro that
replays offline with no live cluster.

Entry points: ``kme-sim`` (cli.py), ``run_sim`` below.
"""

from kme_tpu.sim.cluster import SimConfig, SimResult, run_sim  # noqa: F401
from kme_tpu.sim.schedule import (FaultSchedule,  # noqa: F401
                                  generate_schedule)
from kme_tpu.sim.shrink import shrink_schedule  # noqa: F401
