"""The whole-cluster simulation harness: one process, one seed, one
verdict.

``run_sim(schedule, root)`` stands up the REAL production components —
``MatchService`` leaders (oracle engine, exactly-once stamps, periodic
checkpoints), ``Replica`` hot standbys tailing the leaders' durable
logs, the ``GroupRouter`` front, per-group ``FeedDeriver``s — as
cooperatively scheduled actors under one ``SimScheduler`` virtual
clock, wired through the in-memory ``SimTransport``. Nothing is
mocked below the process boundary: brokers persist real JSONL logs,
checkpoints are real fsync'd snapshots, recovery is the service's own
resume-and-replay path, and a mid-run reshard runs the real offline
``ReshardCoordinator`` over the drained generation.

Fault vocabulary (see ``schedule.py``):

- grammar clauses fire at the production call sites via ``faults.py``
  (broker errors, torn/bitflipped checkpoints, link partitions/delays/
  reorder-dups, clock skew);
- ``crash`` events model SIGKILL of a group leader by DROPPING its
  service and broker objects (``produce`` flushes per record, so the
  on-disk logs are exactly what a kill -9 leaves) and letting the
  supervisor actor restart it through the ordinary recovery path;
- ``reshard`` events drain the cluster at a stream barrier, close the
  generation, run the coordinator, and reopen services over the new
  topology with the settle-phase resume cursors.

Verdicts, all computed against first principles after the run:

- **parity** — durable MatchOut byte-equals the partitioned
  single-leader oracle (``verify_groups`` / ``verify_groups_reshard``);
- **stamps** — exactly-once: every stamped output row's ``out_seq`` is
  unique within its group's cursor domain (MatchOut + Xfer share one);
- **conservation** — cash summed over the live group engines equals a
  single oracle's replay of the full input stream (transfer legs net
  to zero; ``pending_reserve`` ledgers are reported alongside);
- **feed** — each group's derived book byte-equals the aggregate of
  its live engine's resting orders (``canonical_books``);
- **standby** — follower application stayed within the holdback bound;
- **completed** — the run drained fully inside the virtual deadline (a
  wedge is a red verdict, not a flaky timeout).

Determinism contract: same seed → byte-identical ``trace_digest`` AND
``out_digest``. Anything that would break that (wall time, hash-order
iteration, host identity) is a bug in this module.
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kme_tpu import faults
from kme_tpu.sim.sched import SimClockView, SimScheduler
from kme_tpu.sim.schedule import FaultSchedule
from kme_tpu.sim.transport import SimTransport

PLANTED_BUGS = ("stamp-reset",)


@dataclass
class SimConfig:
    """Knobs that are NOT part of the fault schedule (they shape every
    run identically and never participate in shrinking)."""
    slots: int = 64
    max_fills: int = 32
    batch: int = 16
    checkpoint_every: int = 48
    prefund: int = 8
    num_accounts: int = 12
    num_symbols: int = 6
    # grouped parity holds only inside the funded envelope (see
    # workload.spliced_stream): big enough that shadow cash never
    # depletes over a few hundred events + a storm burst
    prefund_cash: int = 50_000_000
    feed_rate: int = 4          # input lines routed per front step
    restart_delay: float = 0.25  # supervisor's virtual restart latency
    journal: bool = True


@dataclass
class SimResult:
    seed: int
    ok: bool
    verdicts: Dict[str, dict]
    trace_digest: str
    out_digest: str
    schedule: FaultSchedule
    counters: Dict[str, int]
    vtime: float
    events: List[tuple] = field(repr=False, default_factory=list)

    def red_verdicts(self) -> List[str]:
        return sorted(k for k, v in self.verdicts.items()
                      if not v.get("ok", False))


# ---------------------------------------------------------------------------
# actors


class _Leader:
    """One group leader: a real MatchService over a real persisted
    broker, with crash = drop-the-objects and recovery = the service's
    own resume path."""

    def __init__(self, cluster: "_SimCluster", g: int, n: int,
                 gdir: str) -> None:
        self.cluster = cluster
        self.g, self.n = g, n
        self.gdir = gdir
        self.view = SimClockView(cluster.sched)
        self.topic_in = f"MatchIn.g{g}"
        self.topic_out = f"MatchOut.g{g}"
        self.topic_xfer = f"Xfer.g{g}"
        self.broker = None
        self.svc = None
        self.down_at: Optional[float] = None
        self.crashes = 0
        self.stopped = False    # actor pump stop (generation retired)
        self._last_ckpt = 0
        self.open()

    def open(self) -> None:
        from kme_tpu.bridge.broker import InProcessBroker
        from kme_tpu.bridge.provision import group_topics, provision
        from kme_tpu.bridge.service import MatchService

        cfg = self.cluster.cfg
        self.broker = InProcessBroker(
            persist_dir=os.path.join(self.gdir, "broker-log"),
            clock=self.view)
        provision(self.broker, topics=group_topics(self.g))
        if (self.crashes and self.cluster.planted_bug == "stamp-reset"):
            # THE PLANTED BUG (shrinker drill): recovery "forgets" the
            # durable idempotence watermark on the output topics, so
            # the resumed leader's replayed tail APPENDS duplicate
            # stamped rows instead of being suppressed — parity, stamp
            # and feed verdicts all go red, deterministically, on any
            # schedule that contains at least one crash
            for t in (self.topic_out, self.topic_xfer):
                topic = self.broker._topics.get(t)
                if topic is not None:
                    topic.max_out_seq = -1
            self.cluster.sched.trace(f"leader{self.g}", "planted_bug",
                                     bug="stamp-reset")
        self.svc = MatchService(
            self.broker, engine="oracle", compat="fixed",
            batch=cfg.batch, slots=cfg.slots, max_fills=cfg.max_fills,
            checkpoint_dir=self.gdir,
            checkpoint_every=cfg.checkpoint_every,
            journal=(os.path.join(self.gdir, "journal.bin")
                     if cfg.journal else None),
            exactly_once=True, group=(self.g, self.n), clock=self.view)
        self._last_ckpt = self.svc._last_ckpt_offset
        self.down_at = None

    def crash(self) -> None:
        """kill -9 at the object layer: no close(), no final flush
        beyond what produce() already did per record."""
        self.crashes += 1
        self.svc = None
        self.broker = None
        self.down_at = self.cluster.sched.now
        self.cluster.sched.trace(f"leader{self.g}", "crash",
                                 n=self.crashes)

    def restart(self) -> None:
        self.open()
        self.cluster.sched.trace(
            f"leader{self.g}", "restart", offset=self.svc.offset,
            epoch=self.svc.epoch, out_seq=self.svc.out_seq)
        self.cluster.transport.flush_held(self.g)

    def step(self) -> bool:
        from kme_tpu.bridge.broker import BrokerFenced

        if self.stopped or self.svc is None:
            return False
        rule = faults.fire("clock.skew", offset=self.svc.offset)
        if rule is not None:
            self.view.skew += rule.ms / 1000.0
            self.cluster.sched.trace(f"leader{self.g}", "clock_skew",
                                     ms=rule.ms)
        try:
            n = self.svc.step(timeout=0.0)
        except BrokerFenced:
            # a newer epoch owns the stream: die like kme-serve (exit
            # 75) and let the supervisor restart us under a fresh epoch
            self.cluster.sched.trace(f"leader{self.g}", "fenced")
            self.crash()
            return True
        if n:
            self.cluster.sched.trace(f"leader{self.g}", "apply",
                                     offset=self.svc.offset)
        if self.svc._last_ckpt_offset != self._last_ckpt:
            self._last_ckpt = self.svc._last_ckpt_offset
            self.cluster.sched.trace(f"leader{self.g}", "ckpt",
                                     offset=self._last_ckpt)
        return n > 0


class _Standby:
    """Hot standby: the real Replica follow machinery, stepped under
    the virtual clock. Promotion is not exercised here (crash recovery
    goes through the supervisor restart path); what this actor pins is
    bounded-lag following against a leader that crashes, stalls and
    skews underneath it."""

    def __init__(self, cluster: "_SimCluster", g: int, n: int,
                 gdir: str) -> None:
        from kme_tpu.bridge.replica import Replica

        cfg = cluster.cfg
        self.cluster = cluster
        self.g = g
        self.view = SimClockView(cluster.sched)
        self.stopped = False
        self.last_seen = 0
        self.rep = Replica(
            gdir, engine="oracle", compat="fixed", batch=cfg.batch,
            slots=cfg.slots, max_fills=cfg.max_fills,
            checkpoint_every=10 ** 9, group=(g, n), clock=self.view)

    def step(self) -> bool:
        if self.stopped:
            return False
        leader = self.cluster.leaders[self.g]
        if leader.svc is not None:
            self.last_seen = leader.svc.offset
        self.rep.follow.limit = max(
            self.rep.follow.limit,
            self.last_seen - self.rep.holdback)
        n = self.rep.svc.step(timeout=0.0)
        if n:
            self.cluster.sched.trace(f"standby{self.g}", "apply",
                                     offset=self.rep.svc.offset)
        return n > 0

    def applied(self) -> int:
        return self.rep.svc.offset


class _Feed:
    """Per-group market-data deriver tailing the durable MatchOut
    log — the consumer-side actor whose book must stay byte-pinned to
    the engine through every crash/replay."""

    def __init__(self, cluster: "_SimCluster", g: int,
                 snap_engine=None) -> None:
        from kme_tpu.feed.derive import FeedDeriver

        self.cluster = cluster
        self.g = g
        self.off = 0
        self.stopped = False
        self.fd = FeedDeriver(group=g)
        if snap_engine is not None:
            # post-reshard bootstrap: the new generation's MatchOut
            # stream starts AFTER the migrated books, so the deriver
            # adopts the offset-0 snapshot's resting store (exactly
            # FeedDeriver.from_state's reconstruction)
            from kme_tpu import opcodes as op
            from kme_tpu.feed.derive import SIDE_BUY, SIDE_SELL

            for oid in sorted(snap_engine.orders):
                o = snap_engine.orders[oid]
                side = SIDE_SELL if o.action == op.SELL else SIDE_BUY
                self.fd.resting[oid] = (o.sid, side, o.price, o.size)
                lv = self.fd.book.levels.setdefault((o.sid, side), {})
                lv[o.price] = lv.get(o.price, 0) + o.size

    def step(self) -> bool:
        from kme_tpu.bridge.broker import BrokerError
        from kme_tpu.wire import parse_order

        if self.stopped:
            return False
        leader = self.cluster.leaders[self.g]
        if leader.broker is None:
            return False
        try:
            recs = leader.broker.fetch(leader.topic_out, self.off, 64)
        except BrokerError:
            return False        # injected fetch fault: retry next pump
        for r in recs:
            msg = parse_order(r.value) if r.key == "OUT" else None
            self.fd.on_record(r.key, msg, r.epoch, r.out_seq)
        self.off += len(recs)
        return bool(recs)


class _Supervisor:
    """Restart policy: a downed leader comes back after
    ``restart_delay`` virtual seconds — unless the cluster is inside a
    reshard barrier teardown, which retires generations on purpose."""

    def __init__(self, cluster: "_SimCluster") -> None:
        self.cluster = cluster
        self.stopped = False

    def step(self) -> bool:
        c = self.cluster
        acted = False
        for leader in c.leaders:
            if (leader.svc is None and not leader.stopped
                    and leader.down_at is not None
                    and c.sched.now - leader.down_at
                    >= c.cfg.restart_delay):
                leader.restart()
                acted = True
        return acted


class _Front:
    """The input side: routes the composed stream through a real
    GroupRouter into the transport, performs schedule events at their
    stream positions, and drives the reshard drain barrier."""

    def __init__(self, cluster: "_SimCluster", lines: List[str],
                 events: List[dict]) -> None:
        from kme_tpu.bridge.front import GroupRouter

        self.cluster = cluster
        self.lines = lines
        self.pos = 0
        self.router = GroupRouter(cluster.ngroups,
                                  prefund=cluster.cfg.prefund)
        self.events = sorted(
            events, key=lambda e: (e.get("at", 0), e["kind"]))
        self.state = "feeding"      # feeding | draining | done
        self.pending_reshard: Optional[dict] = None
        self.stopped = False

    def step(self) -> bool:
        c = self.cluster
        if self.state == "done":
            return False
        if self.state == "draining":
            if c.drained():
                c.do_reshard(self.pending_reshard, split_at=self.pos)
                self.pending_reshard = None
                self.state = "feeding"
            return True
        # events scheduled at (or before) the current stream position
        while self.events and self.events[0].get("at", 0) <= self.pos:
            ev = self.events.pop(0)
            if ev["kind"] == "crash":
                g = ev.get("group", 0) % c.ngroups
                leader = c.leaders[g]
                if leader.svc is not None:
                    leader.crash()
            elif ev["kind"] == "reshard":
                self.pending_reshard = ev
                self.state = "draining"
                c.sched.trace("front", "drain_begin", at=self.pos)
                return True
            # storm events shape the input stream at composition time
            # (run_sim), not here
        if self.pos >= len(self.lines):
            self.state = "done"
            c.sched.trace("front", "done", routed=self.pos)
            return False
        n = min(self.cluster.cfg.feed_rate,
                len(self.lines) - self.pos)
        for _ in range(n):
            line = self.lines[self.pos]
            self.pos += 1
            for g, routed in self.router.route_line(line):
                c.transport.send(g, None, routed)
            # re-check events between lines so `at` is exact
            if self.events and self.events[0].get("at", 0) <= self.pos:
                break
        return True


# ---------------------------------------------------------------------------
# the cluster


class _SimCluster:
    def __init__(self, sched: SimScheduler, schedule: FaultSchedule,
                 cfg: SimConfig, root: str,
                 planted_bug: Optional[str]) -> None:
        if planted_bug is not None and planted_bug not in PLANTED_BUGS:
            raise ValueError(f"unknown planted bug {planted_bug!r} "
                             f"(known: {', '.join(PLANTED_BUGS)})")
        self.sched = sched
        self.schedule = schedule
        self.cfg = cfg
        self.root = root
        self.planted_bug = planted_bug
        self.generation = 0
        self.ngroups = schedule.ngroups
        self.leaders: List[_Leader] = []
        self.standbys: List[_Standby] = []
        self.feeds: List[_Feed] = []
        self.front: Optional[_Front] = None
        self.resharded: Optional[dict] = None
        self.pre_matchout: Optional[List[List[str]]] = None
        self.split_at: Optional[int] = None
        self.old_dup_suppressed = 0
        self.old_delivered = 0

    # -- construction ---------------------------------------------------

    def gen_root(self) -> str:
        return os.path.join(self.root, f"gen{self.generation}")

    def start(self, lines: List[str], events: List[dict]) -> None:
        os.makedirs(self.gen_root(), exist_ok=True)
        self._open_generation(snap_engines=None)
        self.front = _Front(self, lines, events)
        self.transport = SimTransport(
            self.sched, self.ngroups,
            broker_for=lambda g: self.leaders[g].broker,
            topic_for=lambda g: f"MatchIn.g{g}")
        self.sched.add_actor("front", self.front, quantum=0.002)
        self.sched.add_actor("supervisor", _Supervisor(self),
                             quantum=0.01, idle_quantum=0.02)
        self._add_group_actors()

    def _open_generation(self, snap_engines) -> None:
        self.leaders = []
        self.standbys = []
        self.feeds = []
        for g in range(self.ngroups):
            gdir = os.path.join(self.gen_root(), f"group{g}")
            os.makedirs(gdir, exist_ok=True)
            self.leaders.append(_Leader(self, g, self.ngroups, gdir))
            self.standbys.append(_Standby(self, g, self.ngroups, gdir))
            self.feeds.append(_Feed(
                self, g,
                snap_engine=(snap_engines[g] if snap_engines else None)))

    def _add_group_actors(self) -> None:
        gen = self.generation
        for g in range(self.ngroups):
            self.sched.add_actor(f"g{gen}.leader{g}", self.leaders[g],
                                 quantum=0.002)
            self.sched.add_actor(f"g{gen}.standby{g}", self.standbys[g],
                                 quantum=0.004)
            self.sched.add_actor(f"g{gen}.feed{g}", self.feeds[g],
                                 quantum=0.003)

    # -- reshard barrier ------------------------------------------------

    def drained(self) -> bool:
        """Everything routed so far is durable AND applied: transport
        empty, every leader alive and caught up with its input log."""
        if not self.transport.idle():
            return False
        for leader in self.leaders:
            if leader.svc is None or leader.broker is None:
                return False
            if (leader.svc.offset
                    < leader.broker.end_offset(leader.topic_in)):
                return False
        return True

    def do_reshard(self, ev: dict, split_at: int) -> None:
        from kme_tpu.bridge.reshard import ReshardCoordinator
        from kme_tpu.runtime import checkpoint as ck

        m = max(2, int(ev.get("to", 2)))
        n = self.ngroups
        self.sched.trace("reshard", "begin", n=n, m=m,
                         split_at=split_at)
        # close the old generation cleanly: final snapshot (the
        # coordinator needs drained oracle snapshots), then record what
        # it produced for the pre-generation parity verdict
        pre: List[List[str]] = []
        for leader in self.leaders:
            leader.svc.checkpoint()
            leader.svc.close()
            pre.append([f"{r.key} {r.value}" for r in
                        leader.broker.fetch(leader.topic_out, 0,
                                            10 ** 7)])
            self.old_dup_suppressed += leader.broker.dup_suppressed
            self.old_delivered += sum(
                link.delivered for link in self.transport.links
                if link.g == leader.g)
            leader.broker.sync()
            leader.svc = None
            leader.broker = None
            leader.stopped = True
        for st in self.standbys:
            st.stopped = True
        for fd in self.feeds:
            fd.stopped = True
        old_root = self.gen_root()
        self.generation += 1
        new_root = self.gen_root()
        # the coordinator stamps its phase events with the virtual
        # clock — the timeline verdict needs seed-stable event bytes
        coord = ReshardCoordinator(old_root, new_root, n, m,
                                   clock=SimClockView(self.sched).time)
        j = coord.run()
        cursors = j["settle"]["resume_cursors"]
        self.pre_matchout = pre
        self.split_at = split_at
        self.resharded = {"n": n, "m": m, "split_at": split_at,
                          "legs": j["settle"]["legs"]}
        self.ngroups = m
        # offset-0 snapshots seed the new feed derivers' books
        snaps = [ck.load_oracle(os.path.join(new_root, f"group{g}"))[0]
                 for g in range(m)]
        self._open_generation(snap_engines=snaps)
        self.transport.reshape(m, cursors=cursors)
        self.front.router.reshard(m)
        self._add_group_actors()
        self.sched.trace("reshard", "done", m=m,
                         legs=j["settle"]["legs"])

    # -- completion -----------------------------------------------------

    def finished(self) -> bool:
        if self.front.state != "done":
            return False
        if not self.drained():
            return False
        for fd in self.feeds:
            leader = self.leaders[fd.g]
            if fd.off < leader.broker.end_offset(leader.topic_out):
                return False
        return True

    # -- verdicts -------------------------------------------------------

    def verdicts(self, lines: List[str]) -> Dict[str, dict]:
        from kme_tpu.bridge.front import (verify_groups,
                                          verify_groups_reshard)
        from kme_tpu.feed.derive import books_from_oracle, \
            canonical_books
        from kme_tpu.oracle import OracleEngine
        from kme_tpu.wire import parse_order

        cfg = self.cfg
        out: Dict[str, dict] = {}
        completed = self.finished()
        out["completed"] = {"ok": completed, "vtime": round(
            self.sched.now, 6)}

        mo = [[f"{r.key} {r.value}" for r in
               leader.broker.fetch(leader.topic_out, 0, 10 ** 7)]
              if leader.broker is not None else []
              for leader in self.leaders]

        if self.resharded is not None:
            rep = verify_groups_reshard(
                lines, self.split_at, self.pre_matchout, mo,
                compat="fixed", book_slots=cfg.slots,
                max_fills=cfg.max_fills, prefund=cfg.prefund)
        else:
            rep = verify_groups(lines, mo, compat="fixed",
                                book_slots=cfg.slots,
                                max_fills=cfg.max_fills,
                                prefund=cfg.prefund)
        out["parity"] = {"ok": bool(rep["ok"]),
                         "mismatches": rep["mismatches"][:3],
                         "merged_lines": rep["merged_lines"]}

        # exactly-once stamps: MatchOut + Xfer share one out_seq
        # cursor per leader — the union must be duplicate-free
        dup = []
        for leader in self.leaders:
            if leader.broker is None:
                continue
            seqs: List[int] = []
            for t in (leader.topic_out, leader.topic_xfer):
                try:
                    recs = leader.broker.fetch(t, 0, 10 ** 7)
                except Exception:
                    continue
                seqs.extend(r.out_seq for r in recs
                            if r.out_seq is not None)
            if len(seqs) != len(set(seqs)):
                dup.append({"group": leader.g,
                            "rows": len(seqs),
                            "unique": len(set(seqs))})
        out["stamps"] = {"ok": not dup, "duplicates": dup}

        # conservation: group engines vs one single-leader oracle
        oracle = OracleEngine("fixed", cfg.slots, cfg.max_fills)
        for ln in lines:
            oracle.process(parse_order(ln))
        want_cash = sum(oracle.balances.values())
        got_cash = sum(
            sum(leader.svc._oracle.balances.values())
            for leader in self.leaders if leader.svc is not None)
        pending = [dict(leader.svc._xfer) for leader in self.leaders
                   if leader.svc is not None]
        out["conservation"] = {"ok": got_cash == want_cash,
                               "got": got_cash, "want": want_cash,
                               "pending_reserve": pending}

        # feed books vs the live engines
        feed_bad = []
        for fd in self.feeds:
            leader = self.leaders[fd.g]
            if leader.svc is None:
                continue
            want = canonical_books(books_from_oracle(
                leader.svc._oracle))
            got = canonical_books(fd.fd.book)
            if got != want:
                feed_bad.append(fd.g)
        out["feed"] = {"ok": not feed_bad, "mismatched": feed_bad}

        lag_bad = []
        for st in self.standbys:
            leader = self.leaders[st.g]
            if leader.svc is None:
                continue
            if st.applied() > leader.svc.offset:
                lag_bad.append({"group": st.g,
                                "applied": st.applied(),
                                "leader": leader.svc.offset})
        out["standby"] = {"ok": not lag_bad, "violations": lag_bad}
        return out

    def counters(self) -> Dict[str, int]:
        dup = self.old_dup_suppressed
        delivered = self.old_delivered
        for leader in self.leaders:
            if leader.broker is not None:
                dup += leader.broker.dup_suppressed
        delivered += sum(link.delivered
                         for link in self.transport.links)
        return {
            "routed": self.front.pos,
            "delivered": delivered,
            "dup_suppressed": dup,
            "reorder_dups": sum(link.dup_resends
                                for link in self.transport.links),
            "crashes": sum(leader.crashes for leader in self.leaders),
            "resharded": 1 if self.resharded is not None else 0,
            "faults_fired": faults.fired_total(),
        }


# ---------------------------------------------------------------------------


def _compose_lines(schedule: FaultSchedule, cfg: SimConfig) -> List[str]:
    from kme_tpu.wire import dumps_order
    from kme_tpu.workload import spliced_stream

    splices = [(ev["at"], ev["profile"], ev.get("n", 100))
               for ev in schedule.events if ev["kind"] == "storm"]
    msgs = spliced_stream(schedule.num_events, seed=schedule.seed,
                          splices=splices,
                          num_accounts=cfg.num_accounts,
                          num_symbols=cfg.num_symbols,
                          prefund_cash=cfg.prefund_cash)
    return [dumps_order(m) for m in msgs]


def run_sim(schedule: FaultSchedule, root: str,
            cfg: Optional[SimConfig] = None,
            planted_bug: Optional[str] = None,
            max_vtime: float = 600.0) -> SimResult:
    """Execute one seeded simulated run under ``root`` (a fresh
    directory per run). Returns the full verdict set plus the two
    determinism digests."""
    cfg = cfg or SimConfig()
    if schedule.ngroups < 2:
        raise ValueError("the sim cluster is grouped serving; "
                         "ngroups must be >= 2")
    sched = SimScheduler(schedule.seed)
    lines = _compose_lines(schedule, cfg)
    faults.configure(schedule.spec())
    try:
        cluster = _SimCluster(sched, schedule, cfg, root, planted_bug)
        cluster.start(lines, list(schedule.events))
        sched.run(until=cluster.finished, max_vtime=max_vtime)
        counters = cluster.counters()
        verdicts = cluster.verdicts(lines)
    finally:
        faults.clear()

    h = hashlib.sha256()
    for per in ([cluster.pre_matchout] if cluster.pre_matchout else []):
        for g, ls in enumerate(per):
            h.update(f"pre.g{g}:{len(ls)}\n".encode())
            for ln in ls:
                h.update(ln.encode("utf-8"))
                h.update(b"\n")
    for leader in cluster.leaders:
        ls = ([f"{r.key} {r.value}" for r in
               leader.broker.fetch(leader.topic_out, 0, 10 ** 7)]
              if leader.broker is not None else [])
        h.update(f"g{leader.g}:{len(ls)}\n".encode())
        for ln in ls:
            h.update(ln.encode("utf-8"))
            h.update(b"\n")

    # seventh verdict: the control-plane timeline. The embedded REAL
    # components (MatchService lease grants, the reshard coordinator's
    # phase events) wrote virtual-clock-stamped event logs under the
    # run root; merge them, verify every segment (digests, seq gaps),
    # and fold the timeline digest into trace_digest so the seed-sweep
    # byte-determinism check extends to the control plane for free
    from kme_tpu.telemetry import events as cpevents

    tl = cpevents.merge_logs([root])
    tl_digest = cpevents.timeline_digest(tl)
    bad_logs = []
    for lp in cpevents.discover_logs(root):
        rep = cpevents.verify_log(lp)
        if not rep.get("ok", False) or rep.get("seq_gaps"):
            bad_logs.append({"path": os.path.relpath(lp, root),
                             "seq_gaps": rep.get("seq_gaps", 0)})
    verdicts["timeline"] = {"ok": bool(tl) and not bad_logs,
                            "events": len(tl), "digest": tl_digest,
                            "bad_logs": bad_logs}
    trace_digest = hashlib.sha256(
        (sched.digest() + tl_digest).encode("ascii")).hexdigest()

    ok = all(v.get("ok", False) for v in verdicts.values())
    return SimResult(seed=schedule.seed, ok=ok, verdicts=verdicts,
                     trace_digest=trace_digest,
                     out_digest=h.hexdigest(), schedule=schedule,
                     counters=counters, vtime=round(sched.now, 6),
                     events=list(sched.events))
