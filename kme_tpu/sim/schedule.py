"""Generative fault schedules: what one seed makes the cluster endure.

A ``FaultSchedule`` is the COMPLETE description of a simulated run's
adversity, in two layers:

- ``clauses`` — KME_FAULTS grammar clauses (``faults.py``), installed
  via ``faults.configure`` for the run.  These drive the per-call-site
  points: broker errors, torn/bitflipped checkpoints, transport
  partitions/delays/reorder-dups, clock skew.  The offset domain for
  ``at=`` gates is the transport's global delivery ordinal.
- ``events`` — cluster-level acts the harness performs at input-stream
  positions: ``crash`` a group leader (drop its process state, recover
  from durables), splice a ``storm`` burst into the workload, or
  ``reshard`` the cluster N→M mid-run through the real offline
  coordinator.

The schedule also owns the workload size (``num_events``) and topology
(``ngroups``) so that a serialized schedule is a fully self-contained
repro: ``kme-sim --repro file.json`` needs nothing else.

``generate_schedule(seed)`` draws all of it from
``random.Random((seed, "sim-schedule"))`` — an independent stream, so
adding a knob here never perturbs the scheduler's interleaving stream.
Serialization is canonical JSON (sorted keys, no spaces): one line, fit
for a failure report or a shell history.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from typing import List, Optional

from kme_tpu.workload import STORM_PROFILES

# grammar points the generator may draw (the sim-safe subset: no
# serve.kill / journal.torn / tcp.* — those SIGKILL or require a live
# TCP server, which in a single-process sim would kill the sim itself;
# crashes are modeled as `crash` EVENTS instead, which exercise the
# same recovery path without taking the harness down with them)
SIM_POINTS = ("broker.produce", "broker.fetch", "ckpt.torn",
              "ckpt.bitflip", "net.partition", "net.delay",
              "net.reorder", "clock.skew")

_MS_CHOICES = (20, 50, 100, 250)

# storm profiles the generator may splice. The PAYOUT-settlement
# profiles (liquidation-cascade, payout-storm-wide) are excluded:
# payout credits land at the SYMBOL's group engine, which the front's
# shadow-cash margin bound cannot see, so grouped parity does not hold
# for them even with zero transfer shortfall — a documented limitation
# of grouped serving, not a cluster bug the sweep should re-find on
# every third seed. `kme-sim --profile` can still force one
# explicitly.
SIM_STORMS = ("cancel-storm", "flash-crowd", "hot-book")
assert all(s in STORM_PROFILES for s in SIM_STORMS)


@dataclass
class FaultSchedule:
    seed: int
    clauses: List[str] = field(default_factory=list)
    events: List[dict] = field(default_factory=list)
    num_events: int = 400
    ngroups: int = 2

    # -- serialization (canonical, one line) ---------------------------

    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "clauses": self.clauses,
             "events": self.events, "num_events": self.num_events,
             "ngroups": self.ngroups},
            sort_keys=True, separators=(",", ":"))

    @classmethod
    def from_json(cls, text: str) -> "FaultSchedule":
        d = json.loads(text)
        return cls(seed=int(d["seed"]),
                   clauses=list(d.get("clauses", [])),
                   events=list(d.get("events", [])),
                   num_events=int(d.get("num_events", 400)),
                   ngroups=int(d.get("ngroups", 2)))

    def spec(self) -> Optional[str]:
        """The KME_FAULTS string for ``faults.configure`` (None = calm)."""
        if not self.clauses:
            return None
        return ";".join([f"seed={self.seed}"] + list(self.clauses))

    def describe(self) -> str:
        bits = [f"seed={self.seed}", f"n={self.num_events}",
                f"groups={self.ngroups}"]
        bits.extend(self.clauses)
        for ev in self.events:
            kv = ",".join(f"{k}={v}" for k, v in sorted(ev.items())
                          if k != "kind")
            bits.append(f"{ev['kind']}[{kv}]" if kv else ev["kind"])
        return " ".join(bits)

    def size(self) -> int:
        """Shrink metric: total adversity count."""
        return len(self.clauses) + len(self.events)


def generate_schedule(seed: int, num_events: int = 400,
                      ngroups: int = 2,
                      profile: Optional[str] = None) -> FaultSchedule:
    """Draw a schedule for ``seed``.  Every draw comes from one seeded
    stream in a FIXED order, so schedule generation is reproducible and
    two seeds give genuinely different adversity mixes."""
    rng = random.Random((int(seed), "sim-schedule").__repr__())
    sched = FaultSchedule(seed=int(seed), num_events=num_events,
                          ngroups=ngroups)

    # grammar clauses: 1..4 point rules gated over the run.  net.* and
    # clock.skew call sites pass an offset (the delivery ordinal /
    # applied input offset), so `at=` gates work; broker.* and ckpt.*
    # production call sites pass NO offset, so only hit-count gates
    # (`after=`) ever fire there — at= would silently never trigger.
    for _ in range(rng.randint(1, 4)):
        point = rng.choice(SIM_POINTS)
        if point.startswith("net.") or point == "clock.skew":
            gate = f"at={rng.randrange(1, max(2, num_events))}"
        else:
            gate = f"after={rng.randrange(1, max(2, num_events))}"
        parts = [point, "n=1", gate]
        if point.startswith("net.") or point == "clock.skew":
            parts.append(f"ms={rng.choice(_MS_CHOICES)}")
        if point == "ckpt.torn":
            parts.append(f"frac={rng.choice((0.25, 0.5, 0.75))}")
        sched.clauses.append(":".join(parts))

    # a leader crash + recovery, most runs (the core robustness drill)
    if rng.random() < 0.6:
        sched.events.append({
            "kind": "crash",
            "group": rng.randrange(ngroups),
            "at": rng.randrange(num_events // 4,
                                max(num_events // 4 + 1,
                                    3 * num_events // 4)),
        })

    # a storm burst spliced into the harness stream
    if rng.random() < 0.5:
        name = profile or rng.choice(SIM_STORMS)
        sched.events.append({
            "kind": "storm",
            "profile": name,
            "at": rng.randrange(num_events // 4,
                                max(num_events // 4 + 1,
                                    3 * num_events // 4)),
            "n": rng.choice((50, 100, 150)),
        })

    # a mid-run reshard (drain -> offline coordinator -> reopen).
    # Targets stay >= 2: the sim cluster is grouped serving throughout
    # (group=(k, m) topic namespacing needs m > 1).
    if rng.random() < 0.3:
        to = rng.choice([m for m in (2, 3, 4) if m != ngroups])
        sched.events.append({
            "kind": "reshard",
            "at": rng.randrange(num_events // 3,
                                max(num_events // 3 + 1,
                                    2 * num_events // 3)),
            "to": to,
        })

    # deterministic event order: by stream position, then kind
    sched.events.sort(key=lambda e: (e.get("at", 0), e["kind"]))
    return sched
