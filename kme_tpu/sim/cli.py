"""``kme-sim`` — the deterministic simulation driver.

Three modes:

- ``--seed N``      one run, full verdicts + both determinism digests;
- ``--seeds A..B``  a sweep: every seed in the range gets its own
  generated schedule and a fresh run directory; red seeds are shrunk
  (ddmin over the fault schedule + input reduction) into a repro kit
  and reported as one-line repros. ``--jobs J`` fans the sweep over
  worker PROCESSES (the fault plan is process-global state, so
  parallelism is process-level by construction — runs never share an
  interpreter);
- ``--repro FILE``  replay a schedule JSON (as written by the shrinker
  or ``--dump-schedule``) offline: no sweep, no shrink, exit red/green.

Exit codes: 0 all green, 1 red verdicts (repros printed), 2 usage.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
from typing import List, Optional, Tuple

from kme_tpu.sim.cluster import SimConfig, run_sim
from kme_tpu.sim.schedule import FaultSchedule, generate_schedule


def _parse_seeds(spec: str) -> List[int]:
    if ".." in spec:
        lo, hi = spec.split("..", 1)
        lo, hi = int(lo), int(hi)
        if hi < lo:
            raise ValueError(f"empty seed range {spec!r}")
        return list(range(lo, hi + 1))
    return [int(s) for s in spec.split(",")]


def _cfg_from(args) -> SimConfig:
    return SimConfig(checkpoint_every=args.checkpoint_every,
                     batch=args.batch)


def _one(seed: int, args, out_dir: str) -> dict:
    """Run one seed into ``out_dir`` (sweep worker body — must stay
    importable for process pools). Returns a plain-dict summary."""
    sched = generate_schedule(seed, num_events=args.events,
                              ngroups=args.groups,
                              profile=args.profile)
    root = os.path.join(out_dir, f"seed{seed}")
    try:
        res = run_sim(sched, root, cfg=_cfg_from(args),
                      planted_bug=args.planted_bug,
                      max_vtime=args.max_vtime)
    except Exception as e:
        return {"seed": seed, "ok": False, "error":
                f"{type(e).__name__}: {e}",
                "schedule": sched.to_json()}
    return {"seed": seed, "ok": res.ok,
            "red": res.red_verdicts(),
            "trace_digest": res.trace_digest,
            "out_digest": res.out_digest,
            "vtime": res.vtime,
            "counters": res.counters,
            "describe": sched.describe(),
            "schedule": sched.to_json()}


def _sweep_worker(packed: Tuple[int, dict, str]) -> dict:
    """Top-level so ProcessPoolExecutor can pickle it."""
    seed, argd, out_dir = packed
    args = argparse.Namespace(**argd)
    return _one(seed, args, out_dir)


def _shrink_red(seed: int, summary: dict, args, out_dir: str) -> dict:
    from kme_tpu.sim.shrink import shrink_schedule

    sched = FaultSchedule.from_json(summary["schedule"])
    workdir = os.path.join(out_dir, f"red-seed{seed}")
    sr = shrink_schedule(sched, workdir, cfg=_cfg_from(args),
                         planted_bug=args.planted_bug,
                         max_runs=args.shrink_runs,
                         max_vtime=args.max_vtime,
                         log=lambda s: print(f"  shrink[{seed}]: {s}",
                                             file=sys.stderr))
    if sr is None:      # did not reproduce — report, don't hide
        return {"seed": seed, "reproduced": False}
    return {"seed": seed, "reproduced": True,
            "minimal": sr.schedule.describe(),
            "size": sr.schedule.size(),
            "removed": sr.removed,
            "shrink_runs": sr.runs,
            "repro": sr.repro_line,
            "repro_json": sr.repro_path,
            "dump": sr.dump_path,
            "xray": _dump_field(sr.dump_path, "xray")}


def _dump_field(path: str, key: str):
    try:
        with open(path) as f:
            return json.load(f).get(key)
    except (OSError, ValueError):
        return None


def sim_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="kme-sim",
        description="deterministic whole-cluster simulation: seeded "
                    "virtual-clock runs, seed sweeps, shrinking repros")
    p.add_argument("--seed", type=int, default=None,
                   help="run ONE seed and print its verdicts")
    p.add_argument("--seeds", default=None,
                   help="sweep a range A..B (inclusive) or list A,B,C")
    p.add_argument("--repro", default=None, metavar="FILE",
                   help="replay a schedule JSON (from the shrinker) "
                        "and exit red/green")
    p.add_argument("--events", type=int, default=400,
                   help="baseline workload size per run (default 400)")
    p.add_argument("--groups", type=int, default=2,
                   help="initial shard-group count (default 2)")
    p.add_argument("--profile", default=None,
                   help="pin storm splices to ONE named profile "
                        "(default: schedule-generator's choice)")
    p.add_argument("--jobs", type=int, default=1,
                   help="sweep worker PROCESSES (default 1)")
    p.add_argument("--out", default=None, metavar="DIR",
                   help="keep run artifacts here (default: temp dir, "
                        "green runs deleted)")
    p.add_argument("--planted-bug", default=None,
                   help="arm a known-bug hook (shrinker drill; "
                        "see sim.cluster.PLANTED_BUGS)")
    p.add_argument("--no-shrink", action="store_true",
                   help="report red seeds without shrinking them")
    p.add_argument("--shrink-runs", type=int, default=64,
                   help="candidate-run budget per red seed (default 64)")
    p.add_argument("--max-vtime", type=float, default=600.0,
                   help="virtual-seconds wedge backstop (default 600)")
    p.add_argument("--checkpoint-every", type=int, default=48)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--trace", action="store_true",
                   help="with --seed/--repro: print the event trace")
    p.add_argument("--dump-schedule", action="store_true",
                   help="with --seed: print the generated schedule "
                        "JSON and exit (no run)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    args = p.parse_args(argv)

    modes = sum(x is not None for x in
                (args.seed, args.seeds, args.repro))
    if modes != 1:
        p.error("exactly one of --seed, --seeds, --repro is required")

    if args.repro is not None:
        return _repro_mode(args)
    if args.seed is not None:
        return _single_mode(args)
    return _sweep_mode(args)


# ---------------------------------------------------------------------------


def _print_result(res, args) -> None:
    if args.json:
        print(json.dumps(
            {"seed": res.seed, "ok": res.ok, "verdicts": res.verdicts,
             "trace_digest": res.trace_digest,
             "out_digest": res.out_digest, "vtime": res.vtime,
             "counters": res.counters,
             "schedule": json.loads(res.schedule.to_json())},
            indent=1, sort_keys=True))
        return
    print(f"kme-sim: seed {res.seed} "
          f"{'GREEN' if res.ok else 'RED'} "
          f"(vtime {res.vtime}s, {res.counters['routed']} routed, "
          f"{res.counters['crashes']} crashes, "
          f"{res.counters['faults_fired']} faults)")
    print(f"  schedule: {res.schedule.describe()}")
    for name in sorted(res.verdicts):
        v = res.verdicts[name]
        mark = "ok " if v.get("ok") else "RED"
        extra = {k: w for k, w in v.items() if k != "ok" and w}
        print(f"  [{mark}] {name}"
              + (f" {extra}" if extra and not v.get("ok") else ""))
    print(f"  trace={res.trace_digest[:16]} "
          f"out={res.out_digest[:16]}")


def _single_mode(args) -> int:
    sched = generate_schedule(args.seed, num_events=args.events,
                              ngroups=args.groups,
                              profile=args.profile)
    if args.dump_schedule:
        print(sched.to_json())
        return 0
    out_dir, cleanup = _out_dir(args)
    try:
        res = run_sim(sched, os.path.join(out_dir, f"seed{args.seed}"),
                      cfg=_cfg_from(args),
                      planted_bug=args.planted_bug,
                      max_vtime=args.max_vtime)
        if args.trace:
            for ev in res.events:
                print(f"  {ev[0]:>12.6f} {ev[1]:<14} {ev[2]:<16} "
                      + " ".join(f"{k}={v}" for k, v in ev[3]),
                      file=sys.stderr)
        _print_result(res, args)
        return 0 if res.ok else 1
    finally:
        if cleanup:
            shutil.rmtree(out_dir, ignore_errors=True)


def _repro_mode(args) -> int:
    try:
        with open(args.repro) as f:
            sched = FaultSchedule.from_json(f.read())
    except (OSError, ValueError, KeyError) as e:
        print(f"kme-sim: bad repro file: {e}", file=sys.stderr)
        return 2
    out_dir, cleanup = _out_dir(args)
    try:
        res = run_sim(sched, os.path.join(out_dir, "repro"),
                      cfg=_cfg_from(args),
                      planted_bug=args.planted_bug,
                      max_vtime=args.max_vtime)
        if args.trace:
            for ev in res.events:
                print(f"  {ev[0]:>12.6f} {ev[1]:<14} {ev[2]:<16} "
                      + " ".join(f"{k}={v}" for k, v in ev[3]),
                      file=sys.stderr)
        _print_result(res, args)
        return 0 if res.ok else 1
    finally:
        if cleanup:
            shutil.rmtree(out_dir, ignore_errors=True)


def _out_dir(args) -> Tuple[str, bool]:
    if args.out:
        os.makedirs(args.out, exist_ok=True)
        return args.out, False
    return tempfile.mkdtemp(prefix="kme-sim-"), True


def _sweep_mode(args) -> int:
    try:
        seeds = _parse_seeds(args.seeds)
    except ValueError as e:
        print(f"kme-sim: {e}", file=sys.stderr)
        return 2
    out_dir, cleanup = _out_dir(args)
    argd = vars(args)
    summaries: List[dict] = []
    try:
        if args.jobs > 1:
            import concurrent.futures as cf
            with cf.ProcessPoolExecutor(max_workers=args.jobs) as ex:
                summaries = list(ex.map(
                    _sweep_worker,
                    [(s, argd, out_dir) for s in seeds]))
        else:
            for s in seeds:
                summaries.append(_one(s, args, out_dir))

        reds = [s for s in summaries if not s["ok"]]
        digests = {}
        for s in summaries:
            if "trace_digest" in s:
                digests.setdefault(
                    (s["trace_digest"], s["out_digest"]),
                    []).append(s["seed"])
        if not args.json:
            print(f"kme-sim: swept {len(seeds)} seeds -> "
                  f"{len(seeds) - len(reds)} green, {len(reds)} red")
        shrunk = []
        for s in reds:
            if not args.json:
                why = s.get("red") or [s.get("error", "exception")]
                print(f"  RED seed {s['seed']}: {', '.join(why)}")
                print(f"    schedule: {s.get('describe', '?')}")
            if not args.no_shrink and "error" not in s:
                sk = _shrink_red(s["seed"], s, args, out_dir)
                shrunk.append(sk)
                if not args.json and sk.get("reproduced"):
                    print(f"    shrunk {sk['removed']} unit(s) away "
                          f"in {sk['shrink_runs']} runs -> "
                          f"size {sk['size']}: {sk['minimal']}")
                    print(f"    repro: {sk['repro']}")
                    if sk.get("xray"):
                        print(f"    xray:  {sk['xray']}")
        if args.json:
            print(json.dumps({"seeds": len(seeds),
                              "red": [s["seed"] for s in reds],
                              "results": summaries,
                              "shrunk": shrunk},
                             indent=1, sort_keys=True))
        # a sweep where every green seed collides on one digest pair
        # would mean the schedule generator is inert — flag it
        if (not args.json and len(seeds) > 1
                and len(digests) == 1 and not reds):
            print("kme-sim: WARNING: all seeds produced identical "
                  "digests — nondeterminism sources look disconnected",
                  file=sys.stderr)
        return 1 if reds else 0
    finally:
        if cleanup and not any(not s["ok"] for s in summaries):
            shutil.rmtree(out_dir, ignore_errors=True)
        elif cleanup:
            print(f"kme-sim: red artifacts kept in {out_dir}",
                  file=sys.stderr)
    return 0
