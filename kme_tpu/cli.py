"""Command-line entry points.

The reference splits its operational surface across three Node scripts and
a JVM main (topic.js / exchange_test.js / consumer.js / KProcessor.main,
README.md:10-30); here each role is one subcommand over a shared config.

Commands grow as the framework does; anything not yet wired reports
itself clearly instead of half-working.
"""

from __future__ import annotations

import argparse
import sys


def _not_yet(what: str) -> "int":
    print(f"kme_tpu: {what} is not wired up yet in this build", file=sys.stderr)
    return 2


def loadgen_main(argv=None) -> int:
    """Workload generator — the exchange_test.js role: emit a seeded wire
    stream (JSON lines) to stdout or a transport."""
    p = argparse.ArgumentParser(prog="kme-loadgen", description=loadgen_main.__doc__)
    p.add_argument("--events", type=int, default=1000)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--accounts", type=int, default=10)
    p.add_argument("--symbols", type=int, default=3)
    p.add_argument("--validate", action="store_true",
                   help="clamp prices/sizes to the fixed-mode domain")
    p.add_argument("--fix-payout-opcode", action="store_true",
                   help="emit real PAYOUT (200) instead of the reference "
                        "harness's action=4 bug (Q5)")
    p.add_argument("--broker", default=None, metavar="HOST:PORT",
                   help="produce to MatchIn on this broker instead of "
                        "printing to stdout (the exchange_test.js role)")
    p.add_argument("--connections", type=int, default=None, metavar="N",
                   help="simulate N independent AIMD-paced clients "
                        "multiplexed over --pool sockets (requires "
                        "--broker); client i owns every N-th event")
    p.add_argument("--binary", action="store_true",
                   help="send 72-byte binary wire frames (produce_frames)"
                        " instead of JSON records")
    p.add_argument("--pool", type=int, default=4,
                   help="real sockets backing the simulated clients")
    p.add_argument("--client-batch", type=int, default=64,
                   help="max records per simulated-client send")
    p.add_argument("--epoch", type=int, default=1,
                   help="producer epoch for exactly-once stamps "
                        "(--connections mode stamps every record)")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="write a JSON run report (throughput, AIMD "
                        "rates, observed backoff_ms decay)")
    p.add_argument("--tsdb-out", default=None, metavar="DIR",
                   help="append a final client-side sample (produced, "
                        "rate, sheds, worst RTT) to the shared on-disk "
                        "time-series store (source 'loadgen')")
    p.add_argument("--trace-sample", type=int, default=10, metavar="N",
                   help="--connections mode: keep the N slowest sends "
                        "by RTT in the report, each with the "
                        "deterministic client trace id it carried on "
                        "the wire (dtrace.client_trace_id; resolve "
                        "server-side with kme-trace)")
    args = p.parse_args(argv)
    if args.connections is not None and args.broker is None:
        p.error("--connections requires --broker")
    from kme_tpu.wire import dumps_order
    from kme_tpu.workload import harness_stream

    msgs = harness_stream(args.events, seed=args.seed,
                          num_accounts=args.accounts,
                          num_symbols=args.symbols,
                          payout_opcode_bug=not args.fix_payout_opcode,
                          validate=args.validate)
    if args.connections is not None:
        return _loadgen_connections(args, msgs)
    if args.broker is not None:
        from kme_tpu.bridge.provision import provision
        from kme_tpu.bridge.service import TOPIC_IN
        from kme_tpu.bridge.tcp import TcpBroker, parse_addr

        import time

        from kme_tpu.bridge.broker import BrokerOverload

        host, port = parse_addr(args.broker)
        client = TcpBroker(host, port)
        shed = 0
        try:
            provision(client)  # idempotent: both topics must exist
            lo = 0
            while lo < len(msgs):
                try:
                    client.produce_batch(
                        TOPIC_IN, [(None, dumps_order(m))
                                   for m in msgs[lo:lo + 4096]])
                except BrokerOverload as e:
                    # bounded ingress (kme-serve --max-lag) or adaptive
                    # shedding (--overload-high-lag): the broker sheds
                    # load instead of growing the backlog — treat as
                    # backpressure, honoring the AIMD backoff hint when
                    # the controller sent one, and re-offer the batch
                    # from the broker's durable high-water mark
                    shed += 1
                    hint = getattr(e, "backoff_ms", None)
                    time.sleep(hint / 1e3 if hint else 0.1)
                    lo = client.end_offset(TOPIC_IN)
                    continue
                lo += 4096
        finally:
            client.close()
        note = f" ({shed} overload backoffs)" if shed else ""
        print(f"kme-loadgen: produced {len(msgs)} records to MatchIn"
              f"{note}", file=sys.stderr)
        _tsdb_append_once(args.tsdb_out, "loadgen",
                          {"loadgen_produced_total": len(msgs),
                           "loadgen_sheds_total": shed},
                          "kme-loadgen")
        return 0
    for m in msgs:
        print(dumps_order(m))
    return 0


def _tsdb_append_once(store, source: str, vals: dict,
                      tool: str) -> None:
    """One-shot client-side history sample (kme-loadgen): open the
    shared store, adopt its cursor, append, close. Best-effort — a
    client must never die because the history disk filled."""
    if store is None:
        return
    from kme_tpu.telemetry import TSDB

    try:
        db = TSDB(store, source=source)
        db.append_values(vals, db.next_seq())
        db.close()
    except (OSError, ValueError) as e:
        print(f"{tool}: TSDB write failed: {e}", file=sys.stderr)


def _loadgen_connections(args, msgs) -> int:
    """--connections N: N simulated clients share --pool sockets, each
    with its own AIMD pacer (additive rate increase on success,
    multiplicative decrease on rej_overload, honoring the broker's
    backoff_ms hint before the next send). Every record carries an
    exactly-once (epoch, out_seq) stamp assigned at send time from one
    global sequence, so transport-fault retries are dup-suppressed by
    the broker and the admitted stream stays duplicate-free; a shed
    batch resumes from the admitted prefix (.admitted on the binary
    path, the per-record send count on the JSON path)."""
    import json as _json
    import time

    import numpy as np

    from kme_tpu.bridge.broker import (BrokerError, BrokerFenced,
                                       BrokerOverload)
    from kme_tpu.bridge.provision import provision
    from kme_tpu.bridge.service import TOPIC_IN
    from kme_tpu.bridge.tcp import TcpBroker, parse_addr
    from kme_tpu.telemetry.dtrace import (client_trace_id,
                                          client_trace_ids)
    from kme_tpu.wire import dumps_order, encode_frames

    host, port = parse_addr(args.broker)
    ncli = max(1, args.connections)
    pool = [TcpBroker(host, port)
            for _ in range(max(1, min(args.pool, ncli)))]
    transport_retries = 0

    def call_rt(fn, *a, **kw):
        # transport faults retry the SAME record/stamps immediately (the
        # broker dedups by out_seq; TcpBroker preserves the ats stamp),
        # broker verdicts (overload/fence) propagate to the pacer
        nonlocal transport_retries
        for _ in range(100):
            try:
                return fn(*a, **kw)
            except (BrokerOverload, BrokerFenced):
                raise
            except BrokerError:
                transport_retries += 1
                time.sleep(0.01)
        raise BrokerError("transport retry budget exhausted")

    try:
        provision(pool[0])
        # client i owns msgs[i::ncli]; heads[] walks each queue
        sizes = (len(msgs) - np.arange(ncli) + ncli - 1) // ncli
        sizes = np.maximum(sizes, 0)
        heads = np.zeros(ncli, dtype=np.int64)
        remaining = sizes.copy()
        rate = np.full(ncli, 1000.0)    # records/s; AI +10, MD x0.5
        next_at = np.zeros(ncli)
        next_seq = 0
        sheds = dup = 0
        backoff_samples = []
        # sampled tracing: every send carries a deterministic client
        # trace id (pure mix of out_seq/aid/oid — replayable, never a
        # clock); the N slowest RTTs keep theirs so a tail spike in
        # this report resolves server-side via kme-trace
        nslow = max(0, getattr(args, "trace_sample", 0))
        slow = []

        def note_slow(rtt_us, seq, m, tid, nrec):
            if nslow == 0:
                return
            if len(slow) >= nslow and rtt_us <= slow[-1]["rtt_us"]:
                return
            slow.append({"rtt_us": int(rtt_us), "out_seq": int(seq),
                         "aid": int(m.aid), "oid": int(m.oid),
                         "records": int(nrec),
                         "trace_id": f"0x{tid:016x}"})
            slow.sort(key=lambda s: -s["rtt_us"])
            del slow[nslow:]

        t0 = time.monotonic()
        while True:
            active = np.flatnonzero(remaining > 0)
            if active.size == 0:
                break
            now = time.monotonic() - t0
            due = active[next_at[active] <= now]
            if due.size == 0:
                time.sleep(max(1e-4,
                               float(next_at[active].min()) - now))
                continue
            for ci in due:
                ci = int(ci)
                k = int(min(args.client_batch, remaining[ci]))
                h = int(heads[ci])
                batch = [msgs[ci + (h + j) * ncli] for j in range(k)]
                cli = pool[ci % len(pool)]
                seq0 = next_seq
                sent = 0
                now = time.monotonic() - t0
                try:
                    if args.binary:
                        tids = client_trace_ids(
                            seq0, [m.aid for m in batch],
                            [m.oid for m in batch])
                        buf = encode_frames(batch, tids=tids)
                        bt = time.monotonic()
                        n, _ = call_rt(cli.produce_frames, TOPIC_IN,
                                       None, buf, epoch=args.epoch,
                                       seq0=seq0)
                        note_slow((time.monotonic() - bt) * 1e6,
                                  seq0, batch[0], tids[0], k)
                        dup += k - n    # transport-retry suppressions
                        ok_n = k
                    else:
                        for m in batch:
                            tid = client_trace_id(seq0 + sent,
                                                  m.aid, m.oid)
                            bt = time.monotonic()
                            r = call_rt(cli.produce, TOPIC_IN, None,
                                        dumps_order(m),
                                        epoch=args.epoch,
                                        out_seq=seq0 + sent,
                                        tid=tid)
                            note_slow((time.monotonic() - bt) * 1e6,
                                      seq0 + sent, m, tid, 1)
                            if r == -1:
                                dup += 1
                            sent += 1
                        ok_n = k
                except BrokerOverload as e:
                    ok_n = ((getattr(e, "admitted", None) or 0)
                            if args.binary else sent)
                    sheds += 1
                    hint = getattr(e, "backoff_ms", None)
                    backoff_samples.append(
                        [round(now, 4),
                         None if hint is None else int(hint)])
                    next_at[ci] = now + ((hint / 1e3) if hint else 0.1)
                    rate[ci] = max(1.0, rate[ci] * 0.5)
                else:
                    rate[ci] = min(10000.0, rate[ci] + 10.0)
                    next_at[ci] = now + k / rate[ci]
                next_seq += ok_n
                heads[ci] += ok_n
                remaining[ci] -= ok_n
        dur = time.monotonic() - t0
    finally:
        for cli in pool:
            cli.close()
    hints = [h for _, h in backoff_samples if h is not None]
    mask = sizes > 0
    report = {
        "connections": ncli,
        "events": len(msgs),
        "binary": bool(args.binary),
        "epoch": args.epoch,
        "produced": int(next_seq),
        "dup_suppressed": int(dup),
        "sheds": int(sheds),
        "transport_retries": int(transport_retries),
        "duration_s": round(dur, 3),
        "rate_rps": round(next_seq / dur, 1) if dur > 0 else None,
        "aimd": {
            "rate_mean": round(float(rate[mask].mean()), 1)
            if mask.any() else None,
            "rate_min": round(float(rate[mask].min()), 1)
            if mask.any() else None,
            "rate_max": round(float(rate[mask].max()), 1)
            if mask.any() else None,
        },
        # the controller's AIMD hint should decay as pressure falls —
        # the raw samples let CI (and humans) see the curve
        "backoff_ms_samples": backoff_samples[:1000],
        "backoff_ms_max": max(hints) if hints else None,
        "backoff_ms_last": hints[-1] if hints else None,
        # slowest sends observed client-side; the binary path samples
        # per batch ("records" > 1), JSON per record — either way the
        # trace id matches what the broker recorded, so
        # `kme-trace --cluster --order AID:OID` shows the server half
        "slow_samples": slow,
    }
    if args.report:
        with open(args.report, "w") as f:
            _json.dump(report, f, indent=1)
    vals = {"loadgen_produced_total": int(next_seq),
            "loadgen_sheds_total": int(sheds),
            "loadgen_dup_suppressed_total": int(dup),
            "loadgen_transport_retries_total": int(transport_retries)}
    if report["rate_rps"] is not None:
        vals["loadgen_rate_rps"] = report["rate_rps"]
    if slow:
        vals["loadgen_slowest_rtt_us"] = slow[0]["rtt_us"]
    if report["backoff_ms_last"] is not None:
        vals["loadgen_backoff_ms_last"] = report["backoff_ms_last"]
    _tsdb_append_once(args.tsdb_out, "loadgen", vals, "kme-loadgen")
    print(f"kme-loadgen: {next_seq} records from {ncli} simulated "
          f"clients ({'binary' if args.binary else 'json'}) in "
          f"{dur:.2f}s, {sheds} sheds, {transport_retries} transport "
          f"retries", file=sys.stderr)
    return 0


def oracle_main(argv=None) -> int:
    """Reference-replica engine over stdin/stdout: read order JSON lines,
    print the 'IN {...}' / 'OUT {...}' stream consumer.js would show."""
    p = argparse.ArgumentParser(prog="kme-oracle", description=oracle_main.__doc__)
    p.add_argument("--compat", choices=("java", "fixed"), default="java")
    args = p.parse_args(argv)
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.wire import parse_order

    eng = OracleEngine(args.compat)
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        for rec in eng.process(parse_order(line)):
            print(rec.wire())
    return 0


def bench_main(argv=None) -> int:
    """Benchmark harness (bench.py at the repo root drives the same code)."""
    try:
        from kme_tpu.benchmarks import main as _main
    except ImportError:
        return _not_yet("the benchmark suite")
    return _main(argv)


def serve_main(argv=None) -> int:
    """Engine service speaking the reference Kafka wire contract."""
    try:
        from kme_tpu.bridge.serve import main as _main
    except ImportError:
        return _not_yet("the transport bridge")
    return _main(argv)


def consume_main(argv=None) -> int:
    """Fill-stream consumer — the consumer.js role."""
    try:
        from kme_tpu.bridge.consume import main as _main
    except ImportError:
        return _not_yet("the transport bridge")
    return _main(argv)


def feed_main(argv=None) -> int:
    """Market-data fan-out server (ISSUE 13): book deltas, depth
    snapshots, subscriber filtering, conflation."""
    try:
        from kme_tpu.feed.server import main as _main
    except ImportError:
        return _not_yet("the feed tier")
    return _main(argv)


def provision_main(argv=None) -> int:
    """Topic provisioner — the topic.js role."""
    try:
        from kme_tpu.bridge.provision import main as _main
    except ImportError:
        return _not_yet("the transport bridge")
    return _main(argv)


def _fmt_event(ev: dict) -> str:
    from kme_tpu.wire import rej_name

    bits = [f"seq={ev.get('seq', '?')}",
            f"b={ev.get('b', '?')}:{ev.get('i', '?')}",
            f"off={ev.get('off', -1)}",
            f"{ev['e']:<13s}"]
    for k in ("oid", "aid", "sid", "px", "qty", "moid", "maid",
              "in_us", "plan_us", "dev_us", "prod_us", "e2e_us"):
        if k in ev:
            bits.append(f"{k}={ev[k]}")
    if ev.get("rej"):
        bits.append(f"rej={rej_name(ev['rej'])}")
    if "ts" in ev:
        import datetime

        t = datetime.datetime.fromtimestamp(ev["ts"] / 1e6,
                                            datetime.timezone.utc)
        bits.append(t.strftime("%H:%M:%S.%f"))
    return "  ".join(bits)


def _trace_self_check() -> int:
    """Synthetic end-to-end smoke: journal a canned stream through both
    framings, reconstruct a lifecycle, and byte-compare against the
    oracle replay. Exit 0 only if every step agrees (used by CI)."""
    import os
    import tempfile

    from kme_tpu.oracle import OracleEngine
    from kme_tpu.telemetry.journal import (
        Journal, canonical_lines, lifecycle_summary, oracle_events,
        order_lifecycle, read_events)
    from kme_tpu.wire import dumps_order, parse_order
    from kme_tpu.workload import harness_stream

    msgs = harness_stream(400, seed=7, num_accounts=6, num_symbols=2,
                          payout_opcode_bug=False, validate=True)
    lines = [dumps_order(m) for m in msgs]
    eng = OracleEngine("fixed")
    out = [[rec.wire() for rec in eng.process(parse_order(ln))]
           for ln in lines]
    ok = True
    with tempfile.TemporaryDirectory() as td:
        for ext in ("jsonl", "bin"):
            path = os.path.join(td, f"sc.{ext}")
            j = Journal(path)
            for lo in range(0, len(out), 100):
                j.record_batch(out[lo:lo + 100],
                               offsets=list(range(lo, lo + 100)))
            j.close()
            evs = read_events(path)
            want = canonical_lines(oracle_events(lines))
            got = canonical_lines(evs)
            if got != want:
                print(f"kme-trace --self-check: {ext} journal does not "
                      f"match oracle replay ({len(got)} vs {len(want)} "
                      "events)", file=sys.stderr)
                ok = False
                continue
            seqs = [e["seq"] for e in evs]
            if seqs != sorted(set(seqs)):
                print(f"kme-trace --self-check: {ext} seq numbers not "
                      "strictly monotonic", file=sys.stderr)
                ok = False
                continue
            oids = [e["oid"] for e in evs
                    if e["e"] == "fill" and "oid" in e]
            if oids:
                life = order_lifecycle(evs, oids[0])
                summ = lifecycle_summary(life, oids[0])
                if not life or summ["filled"] <= 0:
                    print("kme-trace --self-check: lifecycle "
                          "reconstruction came back empty",
                          file=sys.stderr)
                    ok = False
    print("kme-trace --self-check: "
          + ("OK" if ok else "FAILED"), file=sys.stderr)
    return 0 if ok else 1


def _trace_cluster(args) -> int:
    """kme-trace --cluster: stitch per-order waterfalls across front,
    groups, transfer legs and merge from a multi-leader run directory
    (telemetry/dtrace.py). Exit 0 iff every admitted order stitched to
    a complete waterfall."""
    import json

    from kme_tpu.telemetry import dtrace

    doc = dtrace.stitch_state_root(args.state_root,
                                   input_path=args.input,
                                   prefund=args.prefund)
    if args.chrome_out is not None:
        with open(args.chrome_out, "w") as f:
            json.dump(dtrace.chrome_trace_doc(doc), f)
        print(f"kme-trace: Chrome trace written to {args.chrome_out}",
              file=sys.stderr)
    if args.order is not None:
        o = dtrace.find_order(doc, args.order)
        if o is None:
            print(f"kme-trace: no stitched order matches "
                  f"{args.order!r}", file=sys.stderr)
            return 1
        print(dtrace.waterfall_text(o))
        return 0
    orders = doc["orders"]
    if args.json:
        for o in orders[:args.limit] if args.limit else orders:
            print(json.dumps(o, sort_keys=True))
    elif args.limit:
        for o in orders[:args.limit]:
            print(dtrace.waterfall_text(o))
            print()
    frac = (doc["stitched"] / doc["admitted"]) if doc["admitted"] else 0
    legs = sum(len(o["legs"]) for o in orders)
    print(f"kme-trace: {doc['admitted']} orders admitted across "
          f"{doc['groups']} groups, {doc['stitched']} stitched "
          f"({frac:.2%}), {legs} transfer/broadcast legs linked, "
          f"counters={doc['counters']}", file=sys.stderr)
    return 0 if doc["admitted"] and doc["stitched"] == doc["admitted"] \
        else (1 if doc["admitted"] else 2)


def agg_main(argv=None) -> int:
    """Cluster SLO plane: aggregate the front's and every group's
    /metrics.json into cluster-wide end-to-end latency (exact merged
    quantiles from raw histogram buckets), global SLO burn rate, a
    per-group health table, and p99 exemplars that resolve to
    waterfalls via kme-trace --cluster --order AID:OID."""
    p = argparse.ArgumentParser(prog="kme-agg",
                                description=agg_main.__doc__)
    p.add_argument("sources", nargs="*", metavar="URL|PATH",
                   help="metrics sources: http://host:port endpoints "
                        "(scraped via /metrics.json), heartbeat files, "
                        "or saved snapshot JSON files")
    p.add_argument("--state-root", default=None, metavar="DIR",
                   help="discover group health surfaces under a "
                        "multi-leader run dir (top.discover_endpoints) "
                        "and scrape those too")
    p.add_argument("--slo-ms", type=float, default=None, metavar="MS",
                   help="cluster e2e SLO threshold; reports the global "
                        "burn rate against --slo-target")
    p.add_argument("--slo-target", type=float, default=0.999,
                   help="SLO attainment target (default 0.999)")
    p.add_argument("--json", action="store_true",
                   help="emit the full aggregate document as JSON")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="also write the aggregate JSON here")
    p.add_argument("--history", default=None, metavar="DIR",
                   help="on-disk TSDB store (kme-serve --tsdb et al.): "
                        "append per-source history — sparkline "
                        "look-back in the text view, window summaries "
                        "under a 'history' key in --json/--out")
    args = p.parse_args(argv)
    import json

    from kme_tpu.telemetry import dtrace
    from kme_tpu.telemetry.top import discover_endpoints, scrape

    sources = list(args.sources)
    if args.state_root:
        import os

        eps = discover_endpoints(args.state_root)
        sources.extend(g["health"] for g in eps["groups"])
        # feed-tier heartbeats are optional surfaces: only scrape the
        # ones that exist, so absent feeds don't add DEGRADED rows
        for fp in [eps["feed"]] + [g["feed"] for g in eps["groups"]]:
            if os.path.exists(fp):
                sources.append(fp)
    if not sources:
        p.error("no sources: give URLs/paths or --state-root")
    import time as _time

    snaps = []
    stale = {}
    now = _time.time()
    for src in sources:
        node = scrape(src)      # same path as kme-top: never raises
        snaps.append((src, node["metrics"] if node["ok"] else None))
        # staleness: a heartbeat FILE that scraped fine but whose
        # writer stopped advancing (sample_seq/mtime frozen for more
        # than 3 write intervals) describes the past, not the present.
        # Live HTTP scrapes are fresh by construction; a heartbeat
        # that says "closing" froze on purpose.
        hb = node.get("hb")
        if (node["ok"] and hb and not hb.get("closing")
                and not src.startswith(("http://", "https://"))):
            every = float(hb.get("every") or 1.0)
            age = None
            if isinstance(hb.get("time"), (int, float)):
                age = now - float(hb["time"])
            else:
                try:
                    import os as _os

                    age = now - _os.path.getmtime(src)
                except OSError:
                    pass
            if age is not None and age > 3.0 * every:
                stale[src] = {"age_s": round(age, 3),
                              "intervals": round(age / every, 2),
                              "sample_seq": hb.get("sample_seq")}
            elif (isinstance(hb.get("events_lag_bytes"), (int, float))
                    and hb["events_lag_bytes"] > 0):
                # heartbeat is live but the control-plane event
                # recorder has unflushed bytes: the process advances
                # while its timeline froze — a distinct STALE variant
                # (the inverse of a stalled heartbeat)
                stale[src] = {
                    "sample_seq": hb.get("sample_seq"),
                    "events_frozen": True,
                    "events_lag_bytes": int(hb["events_lag_bytes"])}
    doc = dtrace.aggregate(snaps, slo_ms=args.slo_ms,
                           slo_target=args.slo_target,
                           stale=stale or None)
    hist_sources = []
    if args.history:
        import os as _os

        from kme_tpu.telemetry import tsdb as _tsdb

        try:
            hist_sources = sorted(
                {e[:-len(".kmet")] for e in _os.listdir(args.history)
                 if e.endswith(".kmet")})
        except OSError as e:
            print(f"kme-agg: history store unreadable: {e}",
                  file=sys.stderr)
        doc["history"] = {
            src: _tsdb.window_summary(args.history, source=src)
            for src in hist_sources}
    recent = []
    if args.state_root:
        # recent control-plane events ride the aggregate: the tail of
        # the merged cluster timeline in the text view, the full merged
        # timeline (+ its digest) under an "events" key in --json/--out
        from kme_tpu.telemetry import events as cpevents

        try:
            recent = cpevents.merge_logs([args.state_root])
        except OSError:
            recent = []
        if recent:
            doc["events"] = {
                "count": len(recent),
                "digest": cpevents.timeline_digest(recent),
                "timeline": recent}
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    if args.json:
        print(json.dumps(doc, indent=1))
    else:
        print(dtrace.render_agg(doc))
        if hist_sources:
            from kme_tpu.telemetry.top import history_lines

            for src in hist_sources:
                for ln in history_lines(args.history, source=src):
                    print(ln)
        if recent:
            from kme_tpu.telemetry import events as cpevents

            print(f"  recent events (last {min(8, len(recent))} of "
                  f"{len(recent)} — kme-events for the full timeline):")
            for ev in recent[-8:]:
                print(f"    {cpevents.format_event(ev)}")
    return 0 if any(s for _n, s in snaps) else 1


def prof_main(argv=None) -> int:
    """Profiling & telemetry-history query tool over the on-disk TSDB
    (kme-serve --tsdb and friends): list/plot/export metric series,
    verify segment digests, inspect the transfer-vs-compute artifact,
    and attribute a regression to a pipeline stage with --diff between
    two history windows or recorded BENCH artifacts."""
    p = argparse.ArgumentParser(prog="kme-prof",
                                description=prof_main.__doc__)
    p.add_argument("store", nargs="?", default=None, metavar="DIR",
                   help="TSDB store directory (or one .kmet segment)")
    p.add_argument("--source", default=None, metavar="NAME",
                   help="only this writer's series (serve, standby, "
                        "feed, front, consume, loadgen, ...; default "
                        "all)")
    p.add_argument("--names", default=None, metavar="A,B,...",
                   help="only these series (exact names, comma-"
                        "separated)")
    p.add_argument("--last", type=int, default=None, metavar="N",
                   help="keep only the newest N points per series")
    p.add_argument("--csv", action="store_true",
                   help="emit ts_us,source-agnostic CSV rows instead "
                        "of the sparkline table")
    p.add_argument("--json", action="store_true",
                   help="emit machine-readable JSON")
    p.add_argument("--verify", action="store_true",
                   help="audit the sha256 sidecars of every finalized "
                        "segment (exit 1 on any mismatch)")
    p.add_argument("--artifact", default=None, metavar="PATH",
                   help="print the per-backend transfer-vs-compute "
                        "artifact (kme-serve --profile-artifact) "
                        "instead of querying a store")
    p.add_argument("--diff", nargs=2, default=None,
                   metavar=("BASE", "CUR"),
                   help="stage-level regression attribution between "
                        "two TSDB stores (window summaries) or two "
                        "recorded BENCH/driver artifacts — each "
                        "operand may be either")
    p.add_argument("--captures", default=None, metavar="DIR",
                   help="list and pretty-print the capture_NNN.json "
                        "trigger captures in DIR (kme-serve "
                        "--capture-dir: SLO/p99 TriggerCaptures and "
                        "kme-xray watchpoint hits share the format)")
    args = p.parse_args(argv)
    import json
    import os

    from kme_tpu.telemetry import tsdb

    if args.captures is not None:
        from kme_tpu.telemetry.profiler import (format_capture,
                                                list_captures)

        paths = list_captures(args.captures)
        if not paths:
            print(f"kme-prof: no captures under {args.captures}",
                  file=sys.stderr)
            return 1
        if args.json:
            docs = []
            for pth in paths:
                with open(pth) as f:
                    docs.append(dict(json.load(f), path=pth))
            print(json.dumps(docs, indent=1, sort_keys=True))
            return 0
        for pth in paths:
            try:
                print(format_capture(pth))
            except (OSError, ValueError) as e:
                print(f"kme-prof: unreadable capture {pth}: {e}",
                      file=sys.stderr)
        return 0
    if args.artifact is not None:
        from kme_tpu.telemetry import read_transfer_artifact

        try:
            doc = read_transfer_artifact(args.artifact)
        except (OSError, ValueError) as e:
            print(f"kme-prof: {e}", file=sys.stderr)
            return 2
        print(json.dumps(doc, indent=1, sort_keys=True))
        return 0
    if args.diff is not None:
        from kme_tpu import perfgate

        def _metrics(operand: str):
            if os.path.isdir(operand):
                return tsdb.window_summary(operand,
                                           source=args.source)
            return perfgate.load_artifact(operand)["metrics"]

        base, cur = (_metrics(x) for x in args.diff)
        if not base or not cur:
            print("kme-prof: no metrics on one side of --diff",
                  file=sys.stderr)
            return 2
        att = perfgate.attribute_regression(base, cur)
        if args.json:
            print(json.dumps(att, indent=1))
        else:
            print(perfgate.format_attribution(att))
        return 0
    if args.store is None:
        p.error("give a store dir (or --artifact / --diff)")
    if args.verify:
        rep = tsdb.verify_store(args.store)
        print(json.dumps(rep) if args.json else
              f"kme-prof: {rep['verified']}/{rep['segments']} "
              f"segment digests verified"
              + (f"; MISMATCHED: {', '.join(rep['mismatched'])}"
                 if rep["mismatched"] else ""))
        return 1 if rep["mismatched"] else 0
    names = ([n for n in args.names.split(",") if n]
             if args.names else None)
    series = tsdb.query(args.store, names=names, source=args.source)
    if args.last:
        series = {k: v[-args.last:] for k, v in series.items()}
    if not series:
        print("kme-prof: no samples matched", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps({k: [[ts, v] for ts, v in pts]
                          for k, pts in series.items()},
                         sort_keys=True))
        return 0
    if args.csv:
        print("name,ts_us,value")
        for name in sorted(series):
            for ts, v in series[name]:
                print(f"{name},{ts},{v:g}")
        return 0
    from kme_tpu.telemetry.top import sparkline

    w = max(len(n) for n in series)
    for name in sorted(series):
        pts = series[name]
        vals = [v for _ts, v in pts]
        shown = vals
        if tsdb._is_monotonic_name(name) and len(vals) > 1:
            shown = [b - a for a, b in zip(vals, vals[1:])]
        print(f"{name:<{w}s}  n={len(pts):<6d} "
              f"{sparkline(shown):<24s} last={vals[-1]:g}")
    return 0


def trace_main(argv=None) -> int:
    """Flight-recorder query tool: reconstruct one order's or account's
    lifecycle from a journal written by kme-serve --journal-out (or
    kme-bench --journal-out), verify a journal against the reference
    oracle replay, or replay an audit violation repro dump."""
    p = argparse.ArgumentParser(prog="kme-trace",
                                description=trace_main.__doc__)
    p.add_argument("journal", nargs="?", default=None,
                   help="journal path (.jsonl or .bin/.kmej; rotated "
                        "PATH.N siblings are read automatically)")
    p.add_argument("--order", default=None, metavar="OID|AID:OID",
                   help="print every event touching this order id "
                        "(taker or resting maker side) plus a terminal-"
                        "state summary; with --cluster, AID:OID (or a "
                        "trace id) selects the per-order waterfall")
    p.add_argument("--account", type=int, default=None, metavar="AID",
                   help="print every event touching this account id")
    p.add_argument("--limit", type=int, default=None, metavar="N",
                   help="print at most the last N matching events")
    p.add_argument("--json", action="store_true",
                   help="emit raw event JSON lines instead of the "
                        "pretty rendering")
    p.add_argument("--no-rotated", action="store_true",
                   help="read only the live file, ignore PATH.N "
                        "rotation siblings")
    p.add_argument("--verify", default=None, metavar="INPUT",
                   help="replay this order-JSONL input through the "
                        "Python oracle and byte-compare the canonical "
                        "event stream against the journal (exit 1 on "
                        "divergence)")
    p.add_argument("--compat", choices=("java", "fixed"),
                   default="fixed", help="oracle compat for --verify")
    p.add_argument("--book-slots", type=int, default=None,
                   help="capacity envelope for --verify (match the "
                        "serving engine's --slots)")
    p.add_argument("--max-fills", type=int, default=None,
                   help="per-order fill cap for --verify (match the "
                        "serving engine's --max-fills)")
    p.add_argument("--replay-repro", default=None, metavar="DUMP",
                   help="re-run the invariant auditor over an "
                        "audit_repro_*.json violation dump (exit 1 if "
                        "the violation reproduces)")
    p.add_argument("--self-check", action="store_true",
                   help="synthetic round-trip smoke test (no journal "
                        "needed); exit 0 iff journal/oracle/lifecycle "
                        "machinery agrees")
    p.add_argument("--cluster", action="store_true",
                   help="stitch cluster-wide per-order waterfalls from "
                        "a multi-leader run dir (--state-root): merges "
                        "every group's journal spans with the "
                        "deterministic front routing (transfer legs "
                        "linked parent/child, failover replay deduped)")
    p.add_argument("--state-root", default=None, metavar="DIR",
                   help="--cluster: run dir with group{k}/ children "
                        "(the kme-chaos shard-failover layout)")
    p.add_argument("--input", default=None, metavar="PATH",
                   help="--cluster: the front's global input stream "
                        "(default <state-root>/front.in)")
    p.add_argument("--prefund", type=int, default=8,
                   help="--cluster: the front's --prefund (the routing "
                        "re-run must match the original split)")
    p.add_argument("--chrome-out", default=None, metavar="PATH",
                   help="--cluster: write a Chrome trace-event JSON "
                        "(flow arrows across groups) here")
    args = p.parse_args(argv)
    import json

    if args.self_check:
        return _trace_self_check()
    if args.cluster:
        if args.state_root is None:
            p.error("--cluster needs --state-root")
        return _trace_cluster(args)
    if args.replay_repro is not None:
        from kme_tpu.telemetry.audit import replay_repro

        found = replay_repro(args.replay_repro)
        for v in found:
            print(json.dumps(v))
        print(f"kme-trace: repro {'REPRODUCED' if found else 'clean'} "
              f"({len(found)} violation(s))", file=sys.stderr)
        return 1 if found else 0
    if args.journal is None:
        p.error("a journal path is required (or --self-check / "
                "--replay-repro)")
    from kme_tpu.telemetry.journal import (
        account_history, canonical_lines, lifecycle_summary,
        oracle_events, order_lifecycle, read_events)

    events = read_events(args.journal,
                         include_rotated=not args.no_rotated)
    if args.verify is not None:
        with open(args.verify) as fh:
            lines = [ln.strip() for ln in fh if ln.strip()]
        want = canonical_lines(oracle_events(
            lines, compat=args.compat, book_slots=args.book_slots,
            max_fills=args.max_fills))
        got = canonical_lines(events)
        if got == want:
            print(f"kme-trace: journal matches oracle replay "
                  f"({len(got)} events)", file=sys.stderr)
            return 0
        n = min(len(got), len(want))
        div = next((k for k in range(n) if got[k] != want[k]), n)
        print(f"kme-trace: DIVERGENCE at canonical event {div} "
              f"(journal {len(got)} events, oracle {len(want)})",
              file=sys.stderr)
        if div < len(got):
            print(f"  journal: {got[div]}", file=sys.stderr)
        if div < len(want):
            print(f"  oracle:  {want[div]}", file=sys.stderr)
        return 1
    if args.order is not None:
        try:
            oid = int(args.order)
        except ValueError:
            p.error("--order takes AID:OID only with --cluster; "
                    "on a single journal give the integer OID")
        picked = order_lifecycle(events, oid)
        summary = lifecycle_summary(picked, oid)
    elif args.account is not None:
        picked = account_history(events, args.account)
        summary = None
    else:
        picked, summary = events, None
    if args.limit is not None:
        picked = picked[-args.limit:]
    for ev in picked:
        print(json.dumps(ev) if args.json else _fmt_event(ev))
    if summary is not None:
        print(f"kme-trace: order {summary['oid']} state="
              f"{summary['state']} filled={summary['filled']} "
              f"rested={summary['rested']} "
              f"events={summary['events']}", file=sys.stderr)
    elif args.order is None and args.account is None:
        from collections import Counter as _Counter

        kinds = _Counter(e["e"] for e in events)
        print("kme-trace: " + " ".join(
            f"{k}={kinds[k]}" for k in sorted(kinds)), file=sys.stderr)
    return 0


def supervise_main(argv=None) -> int:
    """Failure detection + supervised restart of kme-serve."""
    try:
        from kme_tpu.bridge.supervise import main as _main
    except ImportError:
        return _not_yet("the supervisor")
    return _main(argv)


def standby_main(argv=None) -> int:
    """Hot-standby replica: tail the leader's durable input, stay one
    batch behind, take over (next leader epoch, old one fenced) when
    kme-supervise writes the promote file."""
    try:
        from kme_tpu.bridge.replica import main as _main
    except ImportError:
        return _not_yet("the hot-standby replica")
    return _main(argv)


def top_main(argv=None) -> int:
    """Live operations dashboard over the /metrics.json surfaces of a
    leader, an optional standby, and the supervisor state file."""
    try:
        from kme_tpu.telemetry.top import main as _main
    except ImportError:
        return _not_yet("the kme-top dashboard")
    return _main(argv)


def front_main(argv=None) -> int:
    """Multi-leader front door: split MatchIn into per-group substreams
    (cross-shard balance transfers injected), merge per-group MatchOut
    streams into the canonical global feed, verify vs the oracle."""
    try:
        from kme_tpu.bridge.front import main as _main
    except ImportError:
        return _not_yet("the multi-leader front door")
    return _main(argv)


def reshard_main(argv=None) -> int:
    """Live N->M group re-split over drained leaders: fence the old
    epochs durably, migrate book/position state through the checkpoint
    codec, settle balances with stamped exactly-once transfer legs."""
    try:
        from kme_tpu.bridge.reshard import main as _main
    except ImportError:
        return _not_yet("the reshard coordinator")
    return _main(argv)


def chaos_main(argv=None) -> int:
    """Deterministic fault-injection runs (kme-supervise + KME_FAULTS)
    with byte-exact MatchOut verification against the oracle."""
    try:
        from kme_tpu.bridge.chaos import main as _main
    except ImportError:
        return _not_yet("the chaos harness")
    return _main(argv)


def xray_main(argv=None) -> int:
    """Time-travel state inspection over the durable MatchIn log:
    materialize oracle state at any retained offset (nearest snapshot +
    deterministic replay), bisect the first divergent batch between a
    journal and a fresh replay, evaluate watchpoint predicates offline,
    and take a consistent cross-group cut. Strictly read-only: MatchIn
    and MatchOut bytes are never touched."""
    p = argparse.ArgumentParser(prog="kme-xray",
                                description=xray_main.__doc__)
    p.add_argument("query", nargs="*", metavar="QUERY",
                   help="point query: 'balance AID' | 'order AID:OID' "
                        "| 'book SID' | 'state' | \"eval 'EXPR'\" "
                        "(EXPR uses the watchpoint grammar, e.g. "
                        "balance[3]<0, depth[1]>=8, spread[2]==0)")
    p.add_argument("--log-dir", default=None,
                   help="broker persist dir holding the durable topic "
                        "logs (default: <checkpoint-dir>/broker-log)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="snapshot dir to anchor replays (kme-serve "
                        "--checkpoint-dir); omit to replay cold from "
                        "offset 0 (requires --allow-cold)")
    p.add_argument("--topic", default="MatchIn")
    p.add_argument("--at", type=int, default=None, metavar="OFFSET",
                   help="materialize state AFTER the MatchIn record at "
                        "this offset (default: log end)")
    p.add_argument("--at-trace", default=None, metavar="0xTID",
                   help="resolve a dtrace trace id to its MatchIn "
                        "offset and materialize there")
    p.add_argument("--groups", type=int, default=1,
                   help="group count used when resolving --at-trace "
                        "ids minted by a grouped deployment")
    p.add_argument("--allow-cold", action="store_true",
                   help="permit a full replay from offset 0 when no "
                        "snapshot covers the target")
    p.add_argument("--book-slots", type=int, default=None)
    p.add_argument("--max-fills", type=int, default=None)
    p.add_argument("--bisect", action="store_true",
                   help="binary-search the journal for the first batch "
                        "whose recorded effects diverge from a fresh "
                        "oracle replay; writes a minimized repro")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="journal file for --bisect")
    p.add_argument("--lo", type=int, default=None, metavar="BATCH",
                   help="--bisect window start (journal batch id)")
    p.add_argument("--hi", type=int, default=None, metavar="BATCH",
                   help="--bisect window end (inclusive batch id)")
    p.add_argument("--repro-dir", default=None,
                   help="where --bisect writes its repro dump "
                        "(default: next to the journal)")
    p.add_argument("--replay-repro", default=None, metavar="PATH",
                   help="re-run a bisect repro dump offline and check "
                        "the recorded diff reproduces")
    p.add_argument("--cluster", action="store_true",
                   help="consistent cut across every group under "
                        "--state-root: per-group cash + open margin, "
                        "pending transfer reserve, and global cash "
                        "conservation vs a single-leader replay")
    p.add_argument("--state-root", default=None,
                   help="chaos/cluster layout root (front.in + "
                        "group<k>/state/) for --cluster")
    p.add_argument("--input", default=None, metavar="PATH",
                   help="merged pre-split input for --cluster "
                        "(default: <state-root>/front.in)")
    p.add_argument("--prefund", type=int, default=8,
                   help="per-group transfer prefund the deployment "
                        "ran with (--cluster; must match kme-front)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    import json

    from kme_tpu.telemetry import xray

    try:
        if args.replay_repro is not None:
            res = xray.replay_bisect_repro(args.replay_repro)
            if args.json:
                print(json.dumps(res, indent=1, sort_keys=True))
            else:
                print(f"repro batch {res['batch']}: "
                      f"{'reproduces' if res['match'] else 'DOES NOT reproduce'}")
                for store, line in sorted(res["diff"].items()):
                    print(f"  {store}: {line}")
            return 0 if res["match"] else 1

        if args.cluster:
            if not args.state_root:
                p.error("--cluster requires --state-root")
            rep = xray.cluster_cut(
                args.state_root, at=args.at, input_path=args.input,
                prefund=args.prefund, book_slots=args.book_slots,
                max_fills=args.max_fills)
            if args.json:
                print(json.dumps(rep, indent=1, sort_keys=True))
            else:
                print(f"cut @ {rep['watermark']} input lines "
                      f"({len(rep['groups'])} groups)")
                for k in sorted(rep["groups"]):
                    g = rep["groups"][k]
                    print(f"  group{k}: cut={g['cut']} "
                          f"cash={g['cash']} margin={g['open_margin']} "
                          f"accounts={g['accounts']} "
                          f"resting={g['resting_orders']} "
                          f"(anchor={g['anchor']} "
                          f"replayed={g['replayed']})")
                print(f"  pending transfer reserve: "
                      f"{rep['pending_reserve_total']} "
                      f"(shortfalls={rep['transfer_shortfalls']})")
                print(f"  cluster cash+reserve={rep['cluster']['cash']}"
                      f" margin={rep['cluster']['open_margin']} "
                      f"gross={rep['cluster']['gross']}")
                print(f"  single-leader  cash="
                      f"{rep['single_leader']['cash']} "
                      f"margin={rep['single_leader']['open_margin']} "
                      f"gross={rep['single_leader']['gross']}")
                print("  conserved: "
                      + ("yes" if rep["conserved"]
                         else f"NO — {rep['delta']}"))
            return 0 if rep["conserved"] else 1

        # Point queries and bisection both need the log location.
        log_dir = args.log_dir
        if log_dir is None and args.checkpoint_dir:
            import os as _os
            log_dir = _os.path.join(args.checkpoint_dir, "broker-log")
        if log_dir is None:
            p.error("--log-dir (or --checkpoint-dir) is required")

        if args.bisect:
            if not args.journal:
                p.error("--bisect requires --journal")
            res = xray.bisect(
                args.journal, log_dir, topic=args.topic,
                ckpt_dir=args.checkpoint_dir, lo=args.lo, hi=args.hi,
                book_slots=args.book_slots, max_fills=args.max_fills,
                repro_dir=args.repro_dir)
            if args.json:
                print(json.dumps(res, indent=1, sort_keys=True))
            elif not res["divergent"]:
                print(f"no divergence across {res['window_batches']} "
                      f"journal batches ({res['replays']} replays)")
            else:
                print(f"first divergent batch: {res['batch']} "
                      f"(offset {res['first_divergent_offset']}, "
                      f"{res['replays']} replays)")
                for store, line in sorted(res["diff"].items()):
                    print(f"  {store}: {line}")
                if res.get("repro"):
                    print(f"repro: {res['repro']}")
            return 1 if res["divergent"] else 0

        at = args.at
        if args.at_trace is not None:
            tid = int(args.at_trace, 0)
            off = xray.resolve_trace(tid, log_dir, topic=args.topic,
                                     ngroups=args.groups)
            if off is None:
                raise xray.XrayError(
                    f"trace id {args.at_trace} not found in "
                    f"{args.topic} under {log_dir}")
            at = off + 1
            if not args.json:
                print(f"# trace {args.at_trace} -> offset {off}")

        engine, anchor, replayed = xray.materialize(
            log_dir, at, topic=args.topic,
            ckpt_dir=args.checkpoint_dir,
            allow_cold=args.allow_cold or not args.checkpoint_dir,
            book_slots=args.book_slots, max_fills=args.max_fills)

        q = args.query or ["state"]
        what = q[0]
        out = {"topic": args.topic, "at": at, "anchor": anchor,
               "replayed": replayed}
        if what == "balance":
            if len(q) != 2:
                p.error("usage: balance AID")
            aid = int(q[1])
            bal = engine.balances.get(aid)
            out.update(query=f"balance[{aid}]",
                       value=None if bal is None else int(bal))
        elif what == "order":
            if len(q) != 2 or ":" not in q[1]:
                p.error("usage: order AID:OID")
            aid_s, _, oid_s = q[1].partition(":")
            rec = engine.export_state()["orders"].get(int(oid_s))
            if rec is not None and rec["aid"] != int(aid_s):
                rec = None
            out.update(query=f"order[{q[1]}]", value=rec)
        elif what == "book":
            if len(q) != 2:
                p.error("usage: book SID")
            sid = int(q[1])
            out.update(query=f"book[{sid}]",
                       value=xray.book_summary(engine, sid))
        elif what == "eval":
            if len(q) != 2:
                p.error("usage: eval 'EXPR'")
            pred = xray.parse_watch(q[1])
            fired, val = xray.eval_engine(pred, engine)
            out.update(query=q[1], value=val, fired=fired)
        elif what == "state":
            out.update(query="state",
                       value=xray.engine_canon(engine))
        else:
            p.error(f"unknown query {what!r} (balance | order | "
                    f"book | state | eval)")
        if args.json:
            print(json.dumps(out, indent=1, sort_keys=True))
        else:
            print(f"# {out['query']} @ {args.topic}"
                  f"[{'end' if at is None else at}] "
                  f"(anchor={anchor} replayed={replayed})")
            print(json.dumps(out["value"], indent=1, sort_keys=True))
            if "fired" in out:
                print(f"fired: {out['fired']}")
        return 1 if out.get("fired") else 0
    except xray.XrayError as e:
        print(f"kme-xray: {e}", file=sys.stderr)
        return 2


def lint_main(argv=None) -> int:
    """Repo-native static analysis (hot-path/determinism/tracer/lock
    rules + ruff): see kme_tpu/analysis/."""
    from kme_tpu.analysis.cli import main as _main

    return _main(argv)


def sim_main(argv=None) -> int:
    """Deterministic whole-cluster simulation: seeded virtual-clock
    runs, seed sweeps, shrinking repros (kme_tpu/sim/)."""
    from kme_tpu.sim.cli import sim_main as _main

    return _main(argv)


def events_main(argv=None) -> int:
    """Control-plane flight recorder query tool: merge per-process
    event logs into one causally-ordered cluster timeline, filter or
    follow it, explain one event from the TSDB history (--why), or
    render it as Chrome trace-events."""
    from kme_tpu.telemetry.events_cli import main as _main

    return _main(argv)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m kme_tpu.cli")
    p.add_argument("command", choices=(
        "loadgen", "oracle", "bench", "serve", "consume", "provision",
        "supervise", "standby", "trace", "chaos", "top", "lint",
        "front", "agg", "feed", "reshard", "prof", "xray", "sim",
        "events"))
    args, rest = p.parse_known_args(argv)
    try:
        return {
            "loadgen": loadgen_main, "oracle": oracle_main,
            "bench": bench_main, "serve": serve_main,
            "consume": consume_main, "provision": provision_main,
            "supervise": supervise_main, "standby": standby_main,
            "trace": trace_main, "chaos": chaos_main,
            "top": top_main, "lint": lint_main, "front": front_main,
            "agg": agg_main, "feed": feed_main,
            "reshard": reshard_main, "prof": prof_main,
            "xray": xray_main, "sim": sim_main,
            "events": events_main,
        }[args.command](rest)
    except BrokenPipeError:
        # downstream closed the pipe (e.g. `| head`) — the Unix-polite
        # exit; point both std streams at devnull so interpreter-shutdown
        # flushes can't re-raise on the broken descriptors
        import os

        fd = os.open(os.devnull, os.O_WRONLY)
        os.dup2(fd, sys.stdout.fileno())
        os.dup2(fd, sys.stderr.fileno())
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
